"""Neuroglancer ``neuroglancer_uint64_sharded_v1`` shard codec + hash math.

The reference gets this from cloud-volume (ShardingSpecification,
synthesize_shard_files — consumed at e.g.
/root/reference/igneous/tasks/skeleton.py:26 and
igneous/tasks/image/image.py:596-847) and shard-computer (murmurhash label
assignment, /root/reference/igneous/task_creation/mesh.py:24). This module
is a fresh, numpy-vectorized implementation of both.

Format summary (Neuroglancer sharded spec):
  hashed = hash(chunk_id >> preshift_bits)
  minishard = hashed & (2^minishard_bits - 1)
  shard    = (hashed >> minishard_bits) & (2^shard_bits - 1)
  shard file "<hex shard, ceil(shard_bits/4) digits>.shard":
    [fixed index: 2^minishard_bits pairs of uint64le (start,end) byte
     offsets of each minishard index, relative to the END of this index]
    [chunk data ... minishard indexes ...]
  minishard index (after minishard_index_encoding): uint64le[3][n]:
    row0 chunk ids, delta-encoded;
    row1 start offsets: first relative to end of fixed index, each
         subsequent delta relative to the PREVIOUS CHUNK'S END;
    row2 chunk byte lengths (after data_encoding).
"""

from __future__ import annotations

import gzip as gzip_mod
from typing import Dict, Iterable, List, Optional, Sequence, Tuple, Union

import numpy as np

U32 = np.uint32
U64 = np.uint64


# ---------------------------------------------------------------------------
# murmurhash3_x86_128 (low 64 bits) of a uint64 little-endian key, seed 0.
# Vectorized over numpy arrays.

_C1 = U32(0x239B961B)
_C2 = U32(0xAB0E9789)
_C3 = U32(0x38B34AE5)
_C4 = U32(0xA1E38B93)


def _rotl32(x: np.ndarray, r: int) -> np.ndarray:
  return (x << U32(r)) | (x >> U32(32 - r))


def _fmix32(h: np.ndarray) -> np.ndarray:
  h = h ^ (h >> U32(16))
  h = h * U32(0x85EBCA6B)
  h = h ^ (h >> U32(13))
  h = h * U32(0xC2B2AE35)
  h = h ^ (h >> U32(16))
  return h


def murmurhash3_x86_128_low64(keys) -> np.ndarray:
  """Low 64 bits of MurmurHash3_x86_128(8-byte LE key, seed=0), vectorized."""
  keys = np.asarray(keys, dtype=U64)
  with np.errstate(over="ignore"):
    k1 = (keys & U64(0xFFFFFFFF)).astype(U32)  # bytes 0-3
    k2 = (keys >> U64(32)).astype(U32)  # bytes 4-7
    h1 = np.zeros_like(k1)
    h2 = np.zeros_like(k1)
    h3 = np.zeros_like(k1)
    h4 = np.zeros_like(k1)

    # tail processing for len=8: k2 then k1 (no body blocks)
    k2 = k2 * _C2
    k2 = _rotl32(k2, 16)
    k2 = k2 * _C3
    h2 = h2 ^ k2

    k1 = k1 * _C1
    k1 = _rotl32(k1, 15)
    k1 = k1 * _C2
    h1 = h1 ^ k1

    # finalization
    length = U32(8)
    h1 = h1 ^ length
    h2 = h2 ^ length
    h3 = h3 ^ length
    h4 = h4 ^ length

    h1 = h1 + h2 + h3 + h4
    h2 = h2 + h1
    h3 = h3 + h1
    h4 = h4 + h1

    h1 = _fmix32(h1)
    h2 = _fmix32(h2)
    h3 = _fmix32(h3)
    h4 = _fmix32(h4)

    h1 = h1 + h2 + h3 + h4
    h2 = h2 + h1

    return h1.astype(U64) | (h2.astype(U64) << U64(32))


def _apply_hash(ids: np.ndarray, hashtype: str) -> np.ndarray:
  if hashtype == "identity":
    return np.asarray(ids, dtype=U64)
  if hashtype == "murmurhash3_x86_128":
    return murmurhash3_x86_128_low64(ids)
  raise ValueError(f"Unknown shard hash: {hashtype}")


# ---------------------------------------------------------------------------
# compressed morton code (image chunk ids)


def compressed_morton_code(
  gridpt: Sequence[int], grid_size: Sequence[int]
) -> Union[int, np.ndarray]:
  """Neuroglancer compressed morton code of grid coordinate(s).

  Interleaves bits x,y,z (x lowest) but only for dimensions that still have
  grid range left at that bit position."""
  gridpt = np.atleast_2d(np.asarray(gridpt, dtype=U64))
  grid_size = np.asarray(grid_size, dtype=np.int64)
  nbits = [max(int(np.ceil(np.log2(max(g, 1)))), 0) for g in grid_size]
  code = np.zeros(gridpt.shape[0], dtype=U64)
  out_bit = 0
  for j in range(max(nbits) if nbits else 0):
    for d in range(3):
      if j < nbits[d]:
        bit = (gridpt[:, d] >> U64(j)) & U64(1)
        code |= bit << U64(out_bit)
        out_bit += 1
  return code if code.size > 1 else int(code[0])


# ---------------------------------------------------------------------------
# specification


class ShardingSpecification:
  def __init__(
    self,
    type: str = "neuroglancer_uint64_sharded_v1",
    preshift_bits: int = 0,
    hash: str = "murmurhash3_x86_128",
    minishard_bits: int = 0,
    shard_bits: int = 0,
    minishard_index_encoding: str = "gzip",
    data_encoding: str = "gzip",
  ):
    if type != "neuroglancer_uint64_sharded_v1":
      raise ValueError(f"Unknown sharding type: {type}")
    self.type = type
    self.preshift_bits = int(preshift_bits)
    self.hash = hash
    self.minishard_bits = int(minishard_bits)
    self.shard_bits = int(shard_bits)
    self.minishard_index_encoding = minishard_index_encoding
    self.data_encoding = data_encoding

  @classmethod
  def from_dict(cls, d: dict) -> "ShardingSpecification":
    d = dict(d)
    d["type"] = d.pop("@type", "neuroglancer_uint64_sharded_v1")
    return cls(**d)

  def to_dict(self) -> dict:
    return {
      "@type": self.type,
      "preshift_bits": self.preshift_bits,
      "hash": self.hash,
      "minishard_bits": self.minishard_bits,
      "shard_bits": self.shard_bits,
      "minishard_index_encoding": self.minishard_index_encoding,
      "data_encoding": self.data_encoding,
    }

  # -- placement ------------------------------------------------------------

  def hashed(self, ids) -> np.ndarray:
    ids = np.asarray(ids, dtype=U64) >> U64(self.preshift_bits)
    return _apply_hash(ids, self.hash)

  def minishard_number(self, ids) -> np.ndarray:
    return self.hashed(ids) & U64((1 << self.minishard_bits) - 1)

  def shard_number(self, ids) -> np.ndarray:
    h = self.hashed(ids) >> U64(self.minishard_bits)
    return h & U64((1 << self.shard_bits) - 1)

  def shard_filename(self, shard_number: int) -> str:
    digits = max(1, int(np.ceil(self.shard_bits / 4)))
    return f"{int(shard_number):0{digits}x}.shard"

  def assign_labels_to_shards(self, labels) -> Dict[int, List[int]]:
    """label → shard grouping (shard-computer equivalent, vectorized)."""
    labels = np.asarray(labels, dtype=U64)
    shards = self.shard_number(labels)
    out: Dict[int, List[int]] = {}
    order = np.argsort(shards, kind="stable")
    for s, lbl in zip(shards[order].tolist(), labels[order].tolist()):
      out.setdefault(int(s), []).append(int(lbl))
    return out

  # -- encoding -------------------------------------------------------------

  def _encode(self, data: bytes, encoding: str) -> bytes:
    if encoding == "gzip":
      return gzip_mod.compress(data, compresslevel=6, mtime=0)
    return data

  def _decode(self, data: bytes, encoding: str) -> bytes:
    if encoding == "gzip":
      return gzip_mod.decompress(data)
    return data

  def synthesize_shard(
    self,
    chunks: Dict[int, bytes],
    preambles: Optional[Dict[int, bytes]] = None,
  ) -> bytes:
    """Build one shard file from {chunk_id: raw bytes}. All ids must map to
    the same shard number (not re-verified here).

    ``preambles``: optional per-id bytes written immediately BEFORE the
    indexed chunk content but excluded from its indexed byte range — the
    multires mesh layout, where fragment data precedes each label's
    manifest in the shard (requires data_encoding='raw')."""
    if preambles and self.data_encoding != "raw":
      raise ValueError("preambles require data_encoding='raw'")
    n_minishards = 1 << self.minishard_bits
    buckets: Dict[int, List[Tuple[int, bytes]]] = {}
    for cid, data in chunks.items():
      ms = int(self.minishard_number(cid))
      buckets.setdefault(ms, []).append((int(cid), data))

    data_parts: List[bytes] = []
    data_pos = 0  # relative to end of fixed index
    msindex_blobs: List[Optional[bytes]] = [None] * n_minishards

    for ms in sorted(buckets):
      entries = sorted(buckets[ms])  # by chunk id
      ids = np.array([e[0] for e in entries], dtype=U64)
      raw = [self._encode(e[1], self.data_encoding) for e in entries]
      sizes = np.array([len(r) for r in raw], dtype=U64)
      starts = np.zeros(len(raw), dtype=U64)
      pos = data_pos
      for i, (cid, _) in enumerate(entries):
        pre = preambles.get(cid, b"") if preambles else b""
        if pre:
          data_parts.append(pre)
          pos += len(pre)
        starts[i] = pos
        pos += len(raw[i])
        data_parts.append(raw[i])

      index = np.zeros((3, len(raw)), dtype=U64)
      index[0, 0] = ids[0]
      index[0, 1:] = np.diff(ids)
      # spec: first start is relative to the end of the fixed index;
      # subsequent starts are deltas relative to the previous chunk's END
      index[1, 0] = starts[0]
      if len(raw) > 1:
        prev_ends = starts[:-1] + sizes[:-1]
        index[1, 1:] = starts[1:] - prev_ends
      index[2, :] = sizes
      msindex_blobs[ms] = self._encode(
        index.tobytes(), self.minishard_index_encoding
      )
      data_pos = pos

    # minishard indexes follow the data section
    shard_index = np.zeros((n_minishards, 2), dtype=U64)
    pos = data_pos
    for ms in range(n_minishards):
      blob = msindex_blobs[ms]
      if blob is None:
        shard_index[ms] = (pos, pos)  # empty minishard
      else:
        shard_index[ms] = (pos, pos + len(blob))
        data_parts.append(blob)
        pos += len(blob)

    return shard_index.tobytes() + b"".join(data_parts)

  def synthesize_shard_files(
    self,
    chunks: Dict[int, bytes],
    preambles: Optional[Dict[int, bytes]] = None,
  ) -> Dict[str, bytes]:
    """Group {chunk_id: bytes} by shard and build every shard file."""
    ids = np.array(sorted(chunks.keys()), dtype=U64)
    if len(ids) == 0:
      return {}
    shard_nums = self.shard_number(ids)
    out = {}
    for s in np.unique(shard_nums):
      members = ids[shard_nums == s]
      out[self.shard_filename(int(s))] = self.synthesize_shard(
        {int(i): chunks[int(i)] for i in members},
        preambles=preambles,
      )
    return out


class ShardReader:
  """Random access into shard files via ranged reads."""

  def __init__(self, cf, spec: ShardingSpecification, prefix: str = ""):
    self.cf = cf
    self.spec = spec
    self.prefix = prefix.rstrip("/") + "/" if prefix else ""
    self._msindex_cache: Dict[Tuple[str, int], Optional[np.ndarray]] = {}
    self._fixed_cache: Dict[str, Optional[np.ndarray]] = {}

  def _shard_key(self, shard_number: int) -> str:
    return self.prefix + self.spec.shard_filename(shard_number)

  def _fixed_index(self, key: str) -> Optional[np.ndarray]:
    if key in self._fixed_cache:
      return self._fixed_cache[key]
    n = 1 << self.spec.minishard_bits
    raw = self.cf.get_range(key, 0, n * 16)
    result = None
    if raw is not None and len(raw) >= n * 16:
      result = np.frombuffer(raw, dtype=U64).reshape(n, 2)
    self._fixed_cache[key] = result
    return result

  def minishard_index(self, shard_number: int, minishard: int) -> Optional[np.ndarray]:
    key = self._shard_key(shard_number)
    cache_key = (key, minishard)
    if cache_key in self._msindex_cache:
      return self._msindex_cache[cache_key]
    fixed = self._fixed_index(key)
    result = None
    if fixed is not None:
      start, end = int(fixed[minishard, 0]), int(fixed[minishard, 1])
      if end > start:
        base = (1 << self.spec.minishard_bits) * 16
        raw = self.cf.get_range(key, base + start, end - start)
        if raw is not None:
          raw = self.spec._decode(raw, self.spec.minishard_index_encoding)
          arr = np.frombuffer(raw, dtype=U64).reshape(3, -1).copy()
          arr[0] = np.cumsum(arr[0])  # ids
          # starts: first relative to end of fixed index, then delta from
          # previous chunk end
          starts = arr[1].copy()
          sizes = arr[2]
          for i in range(1, len(starts)):
            starts[i] = starts[i - 1] + sizes[i - 1] + starts[i]
          arr[1] = starts
          result = arr
    self._msindex_cache[cache_key] = result
    return result

  def get_chunk(self, chunk_id: int) -> Optional[bytes]:
    spec = self.spec
    shard = int(spec.shard_number(chunk_id))
    ms = int(spec.minishard_number(chunk_id))
    index = self.minishard_index(shard, ms)
    if index is None:
      return None
    ids = index[0]
    pos = np.searchsorted(ids, U64(chunk_id))
    if pos >= len(ids) or ids[pos] != U64(chunk_id):
      return None
    base = (1 << spec.minishard_bits) * 16
    start = base + int(index[1, pos])
    length = int(index[2, pos])
    raw = self.cf.get_range(self._shard_key(shard), start, length)
    if raw is None:
      return None
    return spec._decode(raw, spec.data_encoding)

  def list_labels(self, shard_number: int) -> np.ndarray:
    """All chunk ids stored in one shard file."""
    out = []
    for ms in range(1 << self.spec.minishard_bits):
      index = self.minishard_index(shard_number, ms)
      if index is not None:
        out.append(index[0])
    if not out:
      return np.zeros(0, dtype=U64)
    return np.concatenate(out)


# ---------------------------------------------------------------------------
# shard parameter solvers


def compute_shard_params_for_hashed(
  num_labels: int,
  shard_index_bytes: int = 8192,
  minishard_index_bytes: int = 40000,
  min_shards: int = 1,
) -> Tuple[int, int, int]:
  """(shard_bits, minishard_bits, preshift_bits) for hash-sharded label data
  (meshes/skeletons). Fresh derivation of the capability at
  /root/reference/igneous/task_creation/common.py:140-213.

  Targets: fixed index ≤ shard_index_bytes (16 bytes/minishard), minishard
  index ≤ minishard_index_bytes (24 bytes/label), ≥ min_shards shards.
  preshift_bits stays 0 because hashed placement gains nothing from it.
  """
  if num_labels <= 0:
    return (0, 0, 0)

  max_minishard_bits = max(int(np.log2(max(shard_index_bytes // 16, 1))), 0)
  labels_per_minishard = max(minishard_index_bytes // 24, 1)

  total_minishards_needed = int(np.ceil(num_labels / labels_per_minishard))
  total_bits = max(int(np.ceil(np.log2(max(total_minishards_needed, 1)))), 0)

  minishard_bits = min(total_bits, max_minishard_bits)
  shard_bits = max(total_bits - minishard_bits, 0)
  min_shard_bits = max(int(np.ceil(np.log2(max(min_shards, 1)))), 0)
  shard_bits = max(shard_bits, min_shard_bits)
  return (shard_bits, minishard_bits, 0)


def create_sharded_image_info(
  dataset_size: Sequence[int],
  chunk_size: Sequence[int],
  encoding: str,
  dtype,
  uncompressed_shard_bytesize: int = int(3.5e9),
  max_shard_index_bytes: int = 8192,
  minishard_index_bytes: int = 40000,
  min_shards: int = 1,
  minishard_index_encoding: str = "gzip",
  data_encoding: "str | None" = None,
) -> dict:
  """Sharding spec dict for an image scale. Fresh derivation of
  /root/reference/igneous/task_creation/image.py:347-505.

  Image chunk ids are compressed morton codes, so PRESHIFT bits group
  spatially-adjacent chunks into the same minishard; identity hash keeps
  that locality. The solver picks bits so one shard holds about
  uncompressed_shard_bytesize of voxel data with bounded index sizes.
  """
  dataset_size = np.asarray(dataset_size, dtype=np.int64)
  chunk_size = np.asarray(chunk_size, dtype=np.int64)
  grid_size = np.ceil(dataset_size / chunk_size).astype(np.int64)
  # morton code space is 2^ceil(log2(g)) per axis
  grid_bits = sum(max(int(np.ceil(np.log2(max(g, 1)))), 0) for g in grid_size)

  voxels_per_chunk = int(np.prod(chunk_size))
  byte_width = np.dtype(dtype).itemsize
  chunk_bytes = voxels_per_chunk * byte_width

  chunks_per_shard = max(int(uncompressed_shard_bytesize // chunk_bytes), 1)
  chunk_bits = max(int(np.floor(np.log2(chunks_per_shard))), 0)
  chunk_bits = min(chunk_bits, grid_bits)

  # split chunk_bits between preshift (spatial grouping inside a minishard)
  # and minishard bits, bounded by the index byte budgets
  max_minishard_bits = max(int(np.log2(max(max_shard_index_bytes // 16, 1))), 0)
  chunks_per_minishard_cap = max(minishard_index_bytes // 24, 1)
  preshift_cap = max(int(np.floor(np.log2(chunks_per_minishard_cap))), 0)

  preshift_bits = min(chunk_bits, preshift_cap)
  minishard_bits = min(chunk_bits - preshift_bits, max_minishard_bits)

  shard_bits = max(grid_bits - preshift_bits - minishard_bits, 0)
  min_shard_bits = max(int(np.ceil(np.log2(max(min_shards, 1)))), 0)
  shard_bits = max(shard_bits, min_shard_bits)

  return {
    "@type": "neuroglancer_uint64_sharded_v1",
    "preshift_bits": preshift_bits,
    "hash": "identity",
    "minishard_bits": minishard_bits,
    "shard_bits": shard_bits,
    "minishard_index_encoding": minishard_index_encoding,
    # gzip everything except codecs that are already entropy-coded
    # (reference rule: task_creation/image.py:494-495); callers may
    # force a data_encoding (e.g. compress=False -> raw)
    "data_encoding": data_encoding or (
      "raw" if encoding in ("jpeg", "png", "jpegxl", "fpzip", "zfpc", "jxl")
      else "gzip"
    ),
  }


def image_shard_shape_from_spec(
  spec: Union[dict, ShardingSpecification],
  dataset_size: Sequence[int],
  chunk_size: Sequence[int],
) -> np.ndarray:
  """Spatial shape one shard file covers: distribute the
  preshift+minishard bits over x,y,z in morton order
  (fresh port of /root/reference/igneous/shards.py:10-55)."""
  if isinstance(spec, ShardingSpecification):
    spec = spec.to_dict()
  chunk_size = np.asarray(chunk_size, dtype=np.int64)
  dataset_size = np.asarray(dataset_size, dtype=np.int64)
  grid_size = np.ceil(dataset_size / chunk_size).astype(np.int64)
  nbits = [max(int(np.ceil(np.log2(max(g, 1)))), 0) for g in grid_size]

  spatial_bits = int(spec["preshift_bits"]) + int(spec["minishard_bits"])
  axis_bits = [0, 0, 0]
  j = 0  # bit level
  consumed = 0
  while consumed < spatial_bits:
    progressed = False
    for d in range(3):
      if j < nbits[d]:
        if consumed < spatial_bits:
          axis_bits[d] += 1
          consumed += 1
        progressed = True
    if not progressed:
      break  # grid exhausted; shard covers everything
    j += 1

  shape = chunk_size * (2 ** np.asarray(axis_bits, dtype=np.int64))
  return shape
