"""Prometheus text exposition of the process metrics.

Three consumption modes, all fed by the same snapshot:

  * ``render()`` — the text format (version 0.0.4) as a string;
  * ``write_textfile(path)`` — atomic write for the node-exporter
    textfile collector (``IGNEOUS_METRICS_TEXTFILE``);
  * ``start_http_server(port)`` — a daemon-thread ``/metrics`` endpoint
    served from the worker poll loop (``IGNEOUS_METRICS_PORT`` or
    ``igneous execute --metrics-port``).

Metric mapping: int counters → ``igneous_<name>_total`` counters, timers
→ ``igneous_<name>_seconds`` histograms (log-scale buckets + _sum/_count),
gauges → ``igneous_<name>`` gauges. Names are sanitized to the Prometheus
charset; the original dotted name survives as a ``name`` label-free
comment.
"""

from __future__ import annotations

import math
import os
import re
import threading
from typing import Optional

from . import metrics

from ..analysis import knobs

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
PORT_ENV = "IGNEOUS_METRICS_PORT"
TEXTFILE_ENV = "IGNEOUS_METRICS_TEXTFILE"

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _sanitize(name: str) -> str:
  out = _NAME_RE.sub("_", name)
  if not out or not (out[0].isalpha() or out[0] == "_"):
    out = "_" + out
  return out


def _fmt(value: float) -> str:
  if value != value or math.isinf(value):  # NaN/Inf never serialized
    return "0"
  if float(value).is_integer():
    return str(int(value))
  return repr(float(value))


def render() -> str:
  """The full exposition: counters, timer histograms, gauges."""
  lines = []

  for name, value in sorted(metrics.counters_snapshot().items()):
    metric = f"igneous_{_sanitize(name)}_total"
    lines.append(f"# TYPE {metric} counter")
    lines.append(f"{metric} {_fmt(value)}")

  histos = metrics.histograms_snapshot()
  for name, totals in sorted(metrics.timer_totals().items()):
    metric = f"igneous_{_sanitize(name)}_seconds"
    lines.append(f"# TYPE {metric} histogram")
    h = histos.get(name)
    if h is not None:
      cum = 0
      for bound, count in zip(h["bounds"], h["buckets"]):
        cum += count
        lines.append(f'{metric}_bucket{{le="{_fmt(bound)}"}} {cum}')
      cum += h["buckets"][-1]
      lines.append(f'{metric}_bucket{{le="+Inf"}} {cum}')
    lines.append(f"{metric}_sum {_fmt(totals['sum'])}")
    lines.append(f"{metric}_count {totals['count']}")

  for name, value in sorted(metrics.gauges_snapshot().items()):
    metric = f"igneous_{_sanitize(name)}"
    lines.append(f"# TYPE {metric} gauge")
    lines.append(f"{metric} {_fmt(value)}")

  for name, value in sorted(_self_health_gauges().items()):
    metric = f"igneous_{_sanitize(name)}"
    lines.append(f"# TYPE {metric} gauge")
    lines.append(f"{metric} {_fmt(value)}")

  return "\n".join(lines) + "\n"


def _self_health_gauges() -> dict:
  """Journal/worker self-health, computed at scrape time: a dead journal
  writer must itself be alertable, so the exposition carries the live
  flush age and span backlog whenever a journal is active (the
  companion counters — igneous_journal_segments_total,
  igneous_journal_flush_failed_total — register at journal creation).
  ``igneous_worker_up`` doubles as the liveness gauge: present while
  the worker process answers scrapes, absent (stale in Prometheus)
  once it stops."""
  from . import journal as journal_mod
  from . import trace

  j = journal_mod.get_active()
  if j is None:
    return {}
  return {
    "journal_last_flush_age_seconds": round(j.last_flush_age(), 3),
    "journal_pending_spans": float(trace.pending_spans()),
    "worker_up": 1.0,
  }


def write_textfile(path: Optional[str] = None) -> Optional[str]:
  """Atomic write for the textfile collector; returns the path written
  (env ``IGNEOUS_METRICS_TEXTFILE`` when not given), or None if unset."""
  path = path or knobs.get_str(TEXTFILE_ENV)
  if not path:
    return None
  tmp = f"{path}.tmp.{os.getpid()}"
  with open(tmp, "w") as f:
    f.write(render())
  os.replace(tmp, path)
  return path


class _MetricsServer:
  def __init__(self, port: int):
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
      def do_GET(self):  # noqa: N802 - stdlib API
        if self.path.rstrip("/") not in ("", "/metrics"):
          self.send_response(404)
          self.end_headers()
          return
        body = render().encode("utf8")
        self.send_response(200)
        self.send_header("Content-Type", CONTENT_TYPE)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

      def log_message(self, *args):  # quiet: one line per scrape is noise
        pass

    self.httpd = ThreadingHTTPServer(("0.0.0.0", port), Handler)
    self.port = self.httpd.server_address[1]
    self._thread = threading.Thread(
      target=self.httpd.serve_forever, daemon=True, name="ig-metrics"
    )
    self._thread.start()

  def stop(self):
    self.httpd.shutdown()
    self.httpd.server_close()


_SERVER: Optional[_MetricsServer] = None
_SERVER_LOCK = threading.Lock()


def start_http_server(port: Optional[int] = None) -> Optional[int]:
  """Serve ``/metrics`` on ``port`` (0 picks a free one; None reads
  ``IGNEOUS_METRICS_PORT``, absent/empty disables). Returns the bound
  port or None. Idempotent per process."""
  global _SERVER
  if port is None:
    raw = knobs.raw(PORT_ENV) or ""
    if not raw:
      return None
    try:
      port = int(raw)
    except ValueError:
      return None
    if port < 0:
      return None
  with _SERVER_LOCK:
    if _SERVER is not None:
      return _SERVER.port
    try:
      _SERVER = _MetricsServer(int(port))
    except OSError:
      metrics.incr("metrics.port_bind_failed")
      return None
    return _SERVER.port


def stop_http_server() -> None:
  global _SERVER
  with _SERVER_LOCK:
    if _SERVER is not None:
      _SERVER.stop()
      _SERVER = None
