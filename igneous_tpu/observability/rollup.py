"""Journal rollup compaction: fold raw segments into windowed records.

PR 5's journal is write-optimized — every worker appends small JSONL
segments and ``igneous fleet`` re-reads ALL of them on every call. At
fleet scale that read is O(segments) and grows without bound. Rollups
make the read side O(windows): raw segments fold into a few compact
records under ``<journal>/rollup/`` and then become GC-able
(``igneous fleet gc``, ``IGNEOUS_JOURNAL_RETAIN``).

Rollup file layout (``rollup/<actor>-<millis>-<seq>.jsonl``), one JSON
object per line:

  {"kind": "rollup_manifest", "actor": ..., "ts": ...,
   "covers": {"<segment>": <last record ts>, ...}}
  {"kind": "rollup", "window": [start, end], "ts_min": ..., "ts_max": ...,
   "stages": {name: {"count": n, "sum": s, "durs": [...capped...]}},
   "workers": {worker_id: last_seen_ts},
   "tasks": [<verbatim task span records>]}
  {"kind": "counters", ...}   # latest cumulative snapshot per worker

Design invariants:

* **No coordination.** Workers self-compact only their OWN segments
  (segment names are worker-unique), so concurrent self-compaction never
  races. An admin ``igneous fleet compact`` may cover anything uncovered;
  the read side resolves double coverage deterministically — rollup files
  are visited in sorted order and a file whose ``covers`` intersect an
  already-accepted file is skipped whole — so a worker/admin race can
  never double-count a segment.
* **Exactness where it matters.** Task spans are kept VERBATIM (they are
  the minority of spans but carry trace ids, workers, errors — everything
  ``fleet top``/health detectors need); stage spans collapse to
  count/sum plus up to ``IGNEOUS_ROLLUP_MAX_SAMPLES`` duration samples
  per stage per window, so count/total stay exact and p50/p95 only
  become approximate past the cap. Counters snapshots are cumulative per
  worker, so re-emitting the latest one per worker loses nothing.
* **Mixable.** ``load_effective`` merges rollup records with raw records
  from segments no rollup covers, so readers see one consistent view
  mid-compaction.
"""

from __future__ import annotations

import json
import math
import os
import socket
import time
from typing import Dict, List, Optional, Tuple

from . import journal as journal_mod
from . import metrics

from ..analysis import knobs

ROLLUP_PREFIX = "rollup/"
WINDOW_SEC_ENV = "IGNEOUS_ROLLUP_WINDOW_SEC"
MAX_SAMPLES_ENV = "IGNEOUS_ROLLUP_MAX_SAMPLES"
EVERY_ENV = "IGNEOUS_ROLLUP_EVERY"
RETAIN_ENV = "IGNEOUS_JOURNAL_RETAIN"

DEFAULT_WINDOW_SEC = 60.0
DEFAULT_MAX_SAMPLES = 512
DEFAULT_EVERY = 16        # worker self-compaction: every N segments
DEFAULT_RETAIN_SEC = 3600.0

_SEQ = [0]  # per-process uniqueness suffix for rollup file names


def window_sec() -> float:
  return knobs.get_float(WINDOW_SEC_ENV)


def max_samples() -> int:
  return knobs.get_int(MAX_SAMPLES_ENV)


def self_compact_every() -> int:
  """Worker self-compaction cadence in segments (0 disables)."""
  return knobs.get_int(EVERY_ENV)


def retain_sec() -> float:
  return knobs.get_float(RETAIN_ENV)


def default_actor() -> str:
  host = socket.gethostname().split(".")[0] or "compactor"
  return f"compactor-{host}-{os.getpid()}"


# -- read side ----------------------------------------------------------------


def load_rollups(cloudpath: str) -> Tuple[List[dict], Dict[str, float]]:
  """(rollup records, covered segments) under a journal path.

  Files are visited in sorted key order; a file whose manifest claims a
  segment an earlier file already covers is skipped entirely, so double
  coverage (admin compact racing worker self-compaction) degrades to
  "one of them wins" instead of double counting."""
  from ..storage import CloudFiles

  cf = CloudFiles(cloudpath)
  try:
    keys = sorted(k for k in cf.list(ROLLUP_PREFIX))
  except Exception:
    return [], {}
  records: List[dict] = []
  covered: Dict[str, float] = {}
  for key in keys:
    data = cf.get(key)
    if data is None:
      continue
    data = journal_mod.decode_segment(data)
    recs = []
    for line in data.decode("utf8", errors="replace").splitlines():
      line = line.strip()
      if not line:
        continue
      try:
        recs.append(json.loads(line))
      except ValueError:
        continue
    manifest = next(
      (r for r in recs if r.get("kind") == "rollup_manifest"), None
    )
    if manifest is None:
      continue
    covers = manifest.get("covers") or {}
    if any(seg in covered for seg in covers):
      metrics.incr("rollup.overlap_skipped")
      continue
    for seg, last_ts in covers.items():
      covered[seg] = float(last_ts or 0.0)
    for rec in recs:
      if rec.get("kind") == "rollup_manifest":
        continue
      rec.setdefault("segment", key)
      records.append(rec)
  return records, covered


def load_effective(cloudpath: str) -> List[dict]:
  """Rollup records plus raw records from segments no rollup covers —
  the O(windows) read path for ``fleet status|top``, ``queue_eta`` and
  the health engine (``fleet trace`` still reads raw segments: per-span
  detail never makes it into a rollup)."""
  records, covered = load_rollups(cloudpath)
  raw_keys = [
    k for k in journal_mod.list_segments(cloudpath) if k not in covered
  ]
  records.extend(journal_mod.read_records(cloudpath, keys=raw_keys))
  return records


# -- compaction ---------------------------------------------------------------


def _fold_span(windows: dict, rec: dict, wsec: float, cap: int) -> None:
  ts, dur = rec.get("ts"), rec.get("dur")
  if ts is None or dur is None:
    return  # fleet.status skips these too: folding them would disagree
  ts, dur = float(ts), float(dur)
  wkey = int(math.floor(ts / wsec))
  w = windows.get(wkey)
  if w is None:
    w = windows[wkey] = {
      "window": [wkey * wsec, (wkey + 1) * wsec],
      "ts_min": ts, "ts_max": ts + dur,
      "stages": {}, "workers": {}, "tasks": [],
    }
  w["ts_min"] = min(w["ts_min"], ts)
  w["ts_max"] = max(w["ts_max"], ts + dur)
  worker = rec.get("worker")
  if worker:
    w["workers"][worker] = max(w["workers"].get(worker, 0.0), ts + dur)
  if rec.get("name") == "task":
    t = dict(rec)
    t.pop("segment", None)
    t.pop("kind", None)
    w["tasks"].append(t)
    return
  name = rec.get("name", "span")
  st = w["stages"].get(name)
  if st is None:
    st = w["stages"][name] = {"count": 0, "sum": 0.0, "durs": []}
  st["count"] += 1
  st["sum"] += dur
  if len(st["durs"]) < cap:
    st["durs"].append(dur)


def compact(
  cloudpath: str,
  actor: Optional[str] = None,
  only_worker: Optional[str] = None,
  window: Optional[float] = None,
  samples_cap: Optional[int] = None,
  min_segments: int = 1,
) -> dict:
  """Fold uncovered raw segments into one new rollup file.

  ``only_worker`` restricts to that worker's own segments (the
  coordination-free self-compaction path); the admin CLI compacts
  everything uncovered. Returns a summary dict; ``segments_compacted``
  is 0 when there was nothing (or too little) to do."""
  from ..storage import CloudFiles

  wsec = float(window) if window else window_sec()
  cap = int(samples_cap) if samples_cap else max_samples()
  actor = actor or default_actor()

  _, covered = load_rollups(cloudpath)
  segs = [k for k in journal_mod.list_segments(cloudpath) if k not in covered]
  if only_worker:
    segs = [k for k in segs if k.startswith(only_worker + "-")]
  if len(segs) < max(int(min_segments), 1):
    return {"segments_compacted": 0, "windows": 0, "rollup_key": None}

  windows: dict = {}
  latest_counters: Dict[str, dict] = {}
  latest_device: Dict[str, dict] = {}
  seg_last_ts: Dict[str, float] = {k: 0.0 for k in segs}
  for rec in journal_mod.read_records(cloudpath, keys=segs):
    seg = rec.get("segment")
    ts = rec.get("ts")
    if seg in seg_last_ts and ts is not None:
      seg_last_ts[seg] = max(seg_last_ts[seg], float(ts))
    kind = rec.get("kind")
    if kind == "counters":
      worker = rec.get("worker", "local")
      prev = latest_counters.get(worker)
      if prev is None or rec.get("ts", 0) >= prev.get("ts", 0):
        c = dict(rec)
        c.pop("segment", None)
        latest_counters[worker] = c
    elif kind == "device":
      # device utilization ledgers are CUMULATIVE per worker (ISSUE 7),
      # so — like counters — re-emitting only the latest loses nothing
      worker = rec.get("worker", "local")
      prev = latest_device.get(worker)
      if prev is None or rec.get("ts", 0) >= prev.get("ts", 0):
        d = dict(rec)
        d.pop("segment", None)
        latest_device[worker] = d
    elif kind == "span":
      _fold_span(windows, rec, wsec, cap)

  lines = [json.dumps({
    "kind": "rollup_manifest", "actor": actor, "ts": time.time(),
    "window_sec": wsec, "covers": seg_last_ts,
  })]
  for wkey in sorted(windows):
    w = windows[wkey]
    w["kind"] = "rollup"
    lines.append(json.dumps(w))
  for worker in sorted(latest_counters):
    lines.append(json.dumps(latest_counters[worker]))
  for worker in sorted(latest_device):
    lines.append(json.dumps(latest_device[worker]))

  _SEQ[0] += 1
  name = f"{ROLLUP_PREFIX}{actor}-{int(time.time() * 1000):013d}-{_SEQ[0]:04d}.jsonl"
  data = journal_mod.encode_segment(("\n".join(lines) + "\n").encode("utf8"))
  CloudFiles(cloudpath).put(name, data, compress=None)
  metrics.incr("rollup.compactions")
  metrics.incr("rollup.segments_folded", len(segs))
  return {
    "segments_compacted": len(segs),
    "windows": len(windows),
    "rollup_key": name,
  }


def maybe_self_compact(journal: "journal_mod.Journal") -> Optional[dict]:
  """Worker-side hook: every ``IGNEOUS_ROLLUP_EVERY`` segments, fold this
  worker's own raw segments. Never raises — compaction is maintenance,
  not correctness."""
  every = self_compact_every()
  if every <= 0 or journal.segments_written == 0:
    return None
  if journal.segments_written % every != 0:
    return None
  try:
    return compact(
      journal.cloudpath, actor=journal.worker_id,
      only_worker=journal.worker_id, min_segments=2,
    )
  except Exception:
    metrics.incr("rollup.self_compact_failed")
    return None


# -- garbage collection -------------------------------------------------------


def gc(cloudpath: str, retain: Optional[float] = None,
       now: Optional[float] = None) -> dict:
  """Delete raw segments that a rollup covers AND whose newest record is
  older than the retention window (``IGNEOUS_JOURNAL_RETAIN``, default
  1h). Uncovered segments are never touched — compaction first, GC
  second. ``fleet trace`` loses per-span detail for GC'd history; the
  retention window is exactly the operator's trace-debuggability horizon."""
  from ..storage import CloudFiles

  retain = retain_sec() if retain is None else float(retain)
  now = time.time() if now is None else now
  _, covered = load_rollups(cloudpath)
  cf = CloudFiles(cloudpath)
  deleted = 0
  kept = 0
  for seg in journal_mod.list_segments(cloudpath):
    last_ts = covered.get(seg)
    if last_ts is None:
      kept += 1
      continue
    if now - last_ts >= retain:
      cf.delete(seg)
      deleted += 1
    else:
      kept += 1
  if deleted:
    metrics.incr("rollup.segments_gced", deleted)
  return {"deleted": deleted, "kept": kept, "retain_sec": retain}
