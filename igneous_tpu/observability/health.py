"""Closed-loop fleet health: detectors, SLO burn, autoscaler signal.

PR 5 made the fleet visible; this module makes the telemetry
*actionable*. A :class:`HealthEngine` evaluates journal rollups + live
segments (``rollup.load_effective``) against a queue depth snapshot and
produces one structured report:

* **stragglers** — workers whose p95 task latency is a configurable
  multiple of the fleet median, and workers whose journal went silent
  (no flush for ``stall_sec``) while the queue still has backlog — the
  stalled workers chaos soaks deliberately inject;
* **anomalies** — DLQ/retry/zombie rates out of band, stall-ratio
  regressions, and a fully stalled journal (every writer silent with
  work remaining: the dead-journal-writer alert);
* **SLO burn** — task success rate (and optionally p95 latency) against
  a target, expressed as error-budget burn rate;
* **autoscale** — a desired-worker recommendation from backlog vs
  journal-derived per-worker throughput, hysteresis-damped so an HPA or
  cron consuming it doesn't flap.

The report fans out to every consumer the loop needs: Prometheus gauges
(``igneous_fleet_stragglers``, ``igneous_fleet_desired_workers``,
``igneous_slo_burn``) via :func:`publish_gauges`, structured ``health.*``
events appended to the journal via :func:`emit_events`, a
``health/flags.json`` straggler report that LeaseBatcher polls to
surrender pre-leases early, an exit-code-bearing ``igneous fleet check``
for CI/cron, and the live ``igneous fleet watch`` dashboard rendered by
:func:`render_dashboard`.
"""

from __future__ import annotations

import json
import os
import socket
import time
from collections import defaultdict
from dataclasses import dataclass, fields
from typing import Iterable, List, Optional

from . import fleet, metrics

from ..analysis import knobs

FLAGS_KEY = "health/flags.json"

# a worker's first compile of each kernel is churn-free startup, not a
# storm: the recompile-storm anomaly needs at least this many recompiles
# in the measured interval before the per-minute rate means anything
DEVICE_RECOMPILE_STORM_MIN = 10


@dataclass
class HealthConfig:
  """Detector thresholds; every field has an ``IGNEOUS_*`` env override
  (see :meth:`from_env`) so deployments tune without code."""

  # analysis window for latency/throughput/SLO (seconds of recent history)
  window_sec: float = 600.0
  # latency straggler: worker p95 >= ratio x fleet median, given at least
  # min_tasks samples on both sides
  straggler_ratio: float = 3.0
  straggler_min_tasks: int = 3
  # liveness straggler: no journal record from the worker for this long
  # while the queue still has backlog (clean drain/exit records exempt)
  stall_sec: float = 120.0
  # workers silent longer than this are forgotten entirely (a pod
  # replaced hours ago is history, not a straggler)
  forget_sec: float = 3600.0
  # anomaly rate ceilings, as fractions of observed task executions
  dlq_rate_max: float = 0.05
  retry_rate_max: float = 1.0
  zombie_rate_max: float = 0.5
  stall_ratio_max: float = 0.9
  # SLO: task success-rate target and optional p95 latency target
  slo_success: float = 0.99
  slo_p95_ms: Optional[float] = None
  # autoscaler: drain the backlog within horizon_sec at the observed
  # per-worker rate; recommendations within the hysteresis band of the
  # current worker count collapse to "no change"
  horizon_sec: float = 600.0
  hysteresis: float = 0.2
  min_workers: int = 1
  max_workers: int = 1000
  # device plane (ISSUE 7): recompile storm = sustained XLA recompiles
  # per minute above this (shape churn eating the compile cache); HBM
  # high-water = peak bytes over this fraction of the device limit;
  # device idle = busy ratio below this while the queue has backlog
  recompiles_per_min_max: float = 10.0
  hbm_highwater_frac: float = 0.9
  device_idle_ratio: float = 0.05
  # serving tier (ISSUE 9): p99 request-latency SLO (ms; None = no
  # latency SLO on serving), cold-miss storm = backend-fetch fraction of
  # requests above this with at least min_requests in window (the cache
  # is being bypassed or thrashed — every client hits origin)
  serve_p99_ms: Optional[float] = None
  serve_miss_ratio_max: float = 0.9
  serve_min_requests: int = 50
  # serve federation (ISSUE 18): peer-fill failure storm = origin
  # fallbacks above this fraction of peer attempts (the ring is
  # half-dead and every miss pays a failed peer round before origin);
  # shed-rate SLO = 503s above this fraction of offered requests
  serve_peer_fail_max: float = 0.5
  serve_peer_min_attempts: int = 8
  serve_shed_ratio_max: float = 0.2
  # data integrity (ISSUE 16): corrupt reads + failed write-verifies +
  # quarantined objects above this count is an anomaly — the default 0
  # means ANY detected corruption alerts (it should: every one names a
  # damaged object that needs an audit/heal pass)
  integrity_corrupt_max: float = 0.0
  # campaign survival (ISSUE 17): speculation storm = the fenced share
  # of issued twins above this ceiling (the fleet keeps double-running
  # work the original holder finishes first — insurance premiums with
  # no payout), once at least min_issued twins give the ratio meaning
  speculate_waste_max: float = 0.5
  speculate_min_issued: int = 8

  _ENV = {
    "window_sec": "IGNEOUS_HEALTH_WINDOW_SEC",
    "straggler_ratio": "IGNEOUS_HEALTH_STRAGGLER_RATIO",
    "straggler_min_tasks": "IGNEOUS_HEALTH_STRAGGLER_MIN_TASKS",
    "stall_sec": "IGNEOUS_HEALTH_STALL_SEC",
    "forget_sec": "IGNEOUS_HEALTH_FORGET_SEC",
    "dlq_rate_max": "IGNEOUS_HEALTH_DLQ_RATE",
    "retry_rate_max": "IGNEOUS_HEALTH_RETRY_RATE",
    "zombie_rate_max": "IGNEOUS_HEALTH_ZOMBIE_RATE",
    "stall_ratio_max": "IGNEOUS_HEALTH_STALL_RATIO",
    "slo_success": "IGNEOUS_SLO_SUCCESS",
    "slo_p95_ms": "IGNEOUS_SLO_P95_MS",
    "horizon_sec": "IGNEOUS_AUTOSCALE_HORIZON_SEC",
    "hysteresis": "IGNEOUS_AUTOSCALE_HYSTERESIS",
    "min_workers": "IGNEOUS_AUTOSCALE_MIN",
    "max_workers": "IGNEOUS_AUTOSCALE_MAX",
    "recompiles_per_min_max": "IGNEOUS_HEALTH_RECOMPILES_PER_MIN",
    "hbm_highwater_frac": "IGNEOUS_HEALTH_HBM_FRAC",
    "device_idle_ratio": "IGNEOUS_HEALTH_DEVICE_IDLE_RATIO",
    "serve_p99_ms": "IGNEOUS_SERVE_SLO_P99_MS",
    "serve_miss_ratio_max": "IGNEOUS_SERVE_MISS_RATIO",
    "serve_min_requests": "IGNEOUS_SERVE_MIN_REQUESTS",
    "serve_peer_fail_max": "IGNEOUS_SERVE_PEER_FAIL_RATIO",
    "serve_peer_min_attempts": "IGNEOUS_SERVE_PEER_MIN",
    "serve_shed_ratio_max": "IGNEOUS_SERVE_SHED_RATIO",
    "integrity_corrupt_max": "IGNEOUS_HEALTH_INTEGRITY_MAX",
    "speculate_waste_max": "IGNEOUS_SPECULATE_WASTE_MAX",
    "speculate_min_issued": "IGNEOUS_SPECULATE_MIN_ISSUED",
  }

  @classmethod
  def from_env(cls, **overrides) -> "HealthConfig":
    """Env-derived config; keyword overrides (CLI flags) win. ``None``
    overrides mean "not given" and fall through to env/default."""
    kw = {}
    for f in fields(cls):
      if f.name.startswith("_"):
        continue
      env_name = cls._ENV.get(f.name)
      val = overrides.get(f.name)
      if val is None and env_name:
        val = knobs.opt_float(env_name)
      if val is not None:
        if f.type in ("int",):
          val = int(val)
        kw[f.name] = val
    cfg = cls(**kw)
    cfg.straggler_min_tasks = int(cfg.straggler_min_tasks)
    cfg.min_workers = int(cfg.min_workers)
    cfg.max_workers = int(cfg.max_workers)
    cfg.serve_min_requests = int(cfg.serve_min_requests)
    cfg.serve_peer_min_attempts = int(cfg.serve_peer_min_attempts)
    cfg.speculate_min_issued = int(cfg.speculate_min_issued)
    return cfg


def _percentile(sorted_vals: List[float], q: float) -> float:
  if not sorted_vals:
    return 0.0
  idx = min(int(q * (len(sorted_vals) - 1) + 0.5), len(sorted_vals) - 1)
  return sorted_vals[idx]


class HealthEngine:
  """Evaluates journal-derived records into one health report dict."""

  def __init__(self, config: Optional[HealthConfig] = None):
    self.config = config or HealthConfig.from_env()

  # -- record scan ----------------------------------------------------------

  def _scan(self, records: Iterable[dict], now: float) -> dict:
    cfg = self.config
    per = {}  # worker -> view

    def view(worker: str) -> dict:
      v = per.get(worker)
      if v is None:
        v = per[worker] = {
          "last_seen": 0.0, "clean_exit": False,
          "task_durs": [], "tasks_failed": 0,
          "task_starts": [], "task_ends": [],
        }
      return v

    counters_by_worker: dict = {}
    device_latest: dict = {}    # worker -> newest cumulative device ledger
    device_earliest: dict = {}  # worker -> oldest in-window ledger (rates)
    stall_total = work_total = 0.0
    serve_durs: list = []       # serve.request spans in window (seconds)
    serve_fetches = 0           # serve.fetch spans in window (origin trips)

    def seen(worker, ts):
      # "health-*" actors are check/cron processes appending health.*
      # events, not fleet workers — never liveness targets; ditto the
      # autoscale controller's own journal records
      if worker and ts and not worker.startswith(("health-", "autoscale-")):
        v = view(worker)
        v["last_seen"] = max(v["last_seen"], float(ts))

    def take_task(rec):
      worker = rec.get("worker", "local")
      ts, dur = rec.get("ts"), rec.get("dur")
      if ts is None or dur is None:
        return
      end = float(ts) + float(dur)
      seen(worker, end)
      if end < now - cfg.window_sec or float(ts) > now + fleet.CLOCK_SKEW_TOLERANCE_SEC:
        return
      v = view(worker)
      if rec.get("error"):
        v["tasks_failed"] += 1
      else:
        v["task_durs"].append(float(dur))
        v["task_starts"].append(float(ts))
        v["task_ends"].append(end)

    def take_stage(name, total):
      # unlike fleet.status's informational ratio, this one feeds an
      # exit-code-bearing anomaly — so "queue.wait" (time tasks sat
      # ENQUEUED: that's backlog, the autoscaler's job) must not count
      # as stall, or every backlogged-but-healthy fleet alerts. Only
      # worker-side pipeline stalls (buffer starvation) are regressions.
      nonlocal stall_total, work_total
      if "queue.wait" in name:
        return
      if any(m in name for m in fleet.STALL_MARKERS):
        stall_total += total
      elif (
        name != "task" and not name.startswith("health.")
        and not name.startswith("serve.")
      ):
        # serve.* spans are request latency, not pipeline work — they
        # get their own detectors below, not the stall-ratio one
        work_total += total

    for rec in records:
      kind = rec.get("kind")
      if kind == "rollup":
        for wid, last in (rec.get("workers") or {}).items():
          seen(wid, last)
        for name, s in (rec.get("stages") or {}).items():
          take_stage(name, float(s.get("sum", 0.0)))
        for t in rec.get("tasks") or ():
          take_task(t)
      elif kind == "counters":
        worker = rec.get("worker", "local")
        seen(worker, rec.get("ts"))
        prev = counters_by_worker.get(worker)
        if prev is None or rec.get("ts", 0) >= prev.get("ts", 0):
          counters_by_worker[worker] = rec
        if rec.get("event") in ("drain", "exit"):
          view(worker)["clean_exit"] = True
      elif kind == "device":
        worker = rec.get("worker", "local")
        ts = rec.get("ts")
        seen(worker, ts)
        prev = device_latest.get(worker)
        if prev is None or (ts or 0) >= prev.get("ts", 0):
          device_latest[worker] = rec
        if ts is not None and ts >= now - cfg.window_sec:
          early = device_earliest.get(worker)
          if early is None or ts < early.get("ts", float("inf")):
            device_earliest[worker] = rec
      elif kind == "span":
        worker = rec.get("worker", "local")
        ts, dur = rec.get("ts"), rec.get("dur")
        if ts is None or dur is None:
          continue
        if rec.get("name") == "task":
          take_task(rec)
        else:
          seen(worker, float(ts) + float(dur))
          name = rec.get("name", "span")
          if float(ts) + float(dur) >= now - cfg.window_sec:
            if name == "serve.request":
              serve_durs.append(float(dur))
            elif name == "serve.fetch":
              serve_fetches += 1
          take_stage(name, float(dur))

    # a worker silent past forget_sec is history, not a detector target
    per = {
      w: v for w, v in per.items()
      if v["last_seen"] >= now - self.config.forget_sec
    }
    counters: dict = defaultdict(int)
    for rec in counters_by_worker.values():
      for k, val in (rec.get("counters") or {}).items():
        counters[k] += val
    return {
      "per_worker": per,
      "counters": dict(counters),
      "stall_total": stall_total,
      "work_total": work_total,
      "device_latest": device_latest,
      "device_earliest": device_earliest,
      "serve_durs": serve_durs,
      "serve_fetches": serve_fetches,
    }

  # -- evaluation -----------------------------------------------------------

  def evaluate(self, records: Iterable[dict],
               queue_stats: Optional[dict] = None,
               now: Optional[float] = None) -> dict:
    cfg = self.config
    now = time.time() if now is None else now
    scan = self._scan(records, now)
    per = scan["per_worker"]
    counters = scan["counters"]
    backlog = int((queue_stats or {}).get("backlog") or 0)

    all_durs = sorted(d for v in per.values() for d in v["task_durs"])
    tasks_ok = len(all_durs)
    tasks_failed = sum(v["tasks_failed"] for v in per.values())
    tasks_total = tasks_ok + tasks_failed
    fleet_median = _percentile(all_durs, 0.50)
    fleet_p95 = _percentile(all_durs, 0.95)

    # throughput over the observed in-window task extent
    starts = [t for v in per.values() for t in v["task_starts"]]
    ends = [t for v in per.values() for t in v["task_ends"]]
    elapsed = max(max(ends) - min(starts), 1.0) if starts else 0.0
    tasks_per_sec = (tasks_ok / elapsed) if elapsed > 0 else 0.0

    stragglers = []
    for worker in sorted(per):
      v = per[worker]
      durs = sorted(v["task_durs"])
      if (
        len(durs) >= cfg.straggler_min_tasks
        and len(all_durs) >= cfg.straggler_min_tasks
        and fleet_median > 0
      ):
        p95 = _percentile(durs, 0.95)
        if p95 >= cfg.straggler_ratio * fleet_median:
          stragglers.append({
            "worker": worker, "kind": "latency",
            "p95_ms": round(p95 * 1e3, 1),
            "fleet_median_ms": round(fleet_median * 1e3, 1),
            "ratio": round(p95 / fleet_median, 2),
            "tasks": len(durs),
          })
          continue
      age = now - v["last_seen"]
      if backlog > 0 and not v["clean_exit"] and age >= cfg.stall_sec:
        stragglers.append({
          "worker": worker, "kind": "stalled",
          "last_seen_age_sec": round(age, 1),
          "stall_sec": cfg.stall_sec,
        })

    anomalies = []
    denom = max(tasks_total, 1)
    dlq = counters.get("dlq.promoted", 0)
    if dlq and dlq / denom > cfg.dlq_rate_max:
      anomalies.append({
        "kind": "dlq_rate", "dlq_promoted": dlq,
        "rate": round(dlq / denom, 3), "max": cfg.dlq_rate_max,
      })
    retries = sum(v for k, v in counters.items() if k.startswith("retries."))
    if retries and retries / denom > cfg.retry_rate_max:
      anomalies.append({
        "kind": "retry_rate", "retries": retries,
        "rate": round(retries / denom, 3), "max": cfg.retry_rate_max,
      })
    zombies = sum(v for k, v in counters.items() if k.startswith("zombie."))
    if zombies and zombies / denom > cfg.zombie_rate_max:
      anomalies.append({
        "kind": "zombie_rate", "zombie_fences": zombies,
        "rate": round(zombies / denom, 3), "max": cfg.zombie_rate_max,
      })
    # campaign survival (ISSUE 17): speculation is insurance against
    # stragglers — a fenced twin means the original holder resolved
    # first and the duplicate issue bought nothing. A high fenced share
    # is a storm: the driver keeps paying premiums with no payout
    # (mis-tuned tail ratio, or flags firing on healthy workers)
    spec_issued = counters.get("speculation.issued", 0)
    spec_won = counters.get("speculation.won", 0)
    spec_fenced = counters.get("speculation.fenced", 0)
    spec_waste = (spec_fenced / spec_issued) if spec_issued else None
    if (
      spec_issued >= cfg.speculate_min_issued
      and spec_waste is not None and spec_waste > cfg.speculate_waste_max
    ):
      anomalies.append({
        "kind": "speculation_storm",
        "issued": spec_issued, "won": spec_won, "fenced": spec_fenced,
        "waste_ratio": round(spec_waste, 3),
        "max": cfg.speculate_waste_max,
        "wasted_ms": counters.get("speculation.wasted_ms", 0),
      })
    # data integrity (ISSUE 16): every corrupt read / failed
    # verify-after-write / quarantined object names at-rest damage that
    # retries cannot fix — only an audit/heal pass can
    corrupt_reads = counters.get("integrity.corrupt_reads", 0)
    verify_failed = counters.get("integrity.verify_failed", 0)
    quarantined = counters.get("integrity.quarantined", 0)
    audit_findings = counters.get("integrity.audit.findings", 0)
    corrupt_total = corrupt_reads + verify_failed + quarantined
    if corrupt_total > cfg.integrity_corrupt_max or audit_findings > 0:
      anomalies.append({
        "kind": "integrity",
        "corrupt_reads": corrupt_reads,
        "verify_failed": verify_failed,
        "quarantined": quarantined,
        "audit_findings": audit_findings,
        "max": cfg.integrity_corrupt_max,
      })
    stall_total, work_total = scan["stall_total"], scan["work_total"]
    stall_ratio = (
      stall_total / (stall_total + work_total)
      if stall_total + work_total > 0 else None
    )
    if stall_ratio is not None and stall_ratio > cfg.stall_ratio_max:
      anomalies.append({
        "kind": "stall_ratio", "stall_ratio": round(stall_ratio, 3),
        "max": cfg.stall_ratio_max,
      })
    if per and backlog > 0 and all(
      now - v["last_seen"] >= cfg.stall_sec and not v["clean_exit"]
      for v in per.values()
    ):
      # every journal writer silent with work remaining: the journal
      # itself (or the whole fleet) is dead — alert even though no
      # single worker stands out
      anomalies.append({
        "kind": "journal_stalled",
        "workers": len(per), "backlog": backlog,
        "stall_sec": cfg.stall_sec,
      })

    # device-plane anomalies (ISSUE 7): recompile storms, HBM pressure,
    # and the "TPU idles while work waits" condition the ROADMAP only
    # asserted — all from the cumulative per-worker device ledgers
    device_ledgers = scan["device_latest"]
    for worker in sorted(device_ledgers):
      rec = device_ledgers[worker]
      early = scan["device_earliest"].get(worker)
      d_rec = rec.get("recompiles", 0)
      dt = float(rec.get("ts", now)) - float(
        rec.get("t_start", rec.get("ts", now))
      )
      if (
        early is not None and early is not rec
        and rec.get("ts", 0) > early.get("ts", 0)
      ):
        # two in-window snapshots: rate over their delta, not since boot
        d_rec = rec.get("recompiles", 0) - early.get("recompiles", 0)
        dt = float(rec["ts"]) - float(early["ts"])
      rate_per_min = d_rec / max(dt, 1.0) * 60.0
      if (
        d_rec >= DEVICE_RECOMPILE_STORM_MIN
        and rate_per_min > cfg.recompiles_per_min_max
      ):
        anomalies.append({
          "kind": "recompile_storm", "worker": worker,
          "recompiles": d_rec, "per_min": round(rate_per_min, 2),
          "max_per_min": cfg.recompiles_per_min_max,
        })
      for dev, dstats in sorted((rec.get("hbm") or {}).items()):
        limit = dstats.get("bytes_limit")
        if not limit:
          continue
        frac = dstats.get("peak_bytes_in_use", 0) / limit
        if frac >= cfg.hbm_highwater_frac:
          anomalies.append({
            "kind": "hbm_high_water", "worker": worker, "device": dev,
            "peak_frac": round(frac, 3),
            "max_frac": cfg.hbm_highwater_frac,
            "peak_bytes": dstats.get("peak_bytes_in_use", 0),
            "limit_bytes": limit,
          })
      busy = rec.get("busy_ratio")
      v = per.get(worker)
      worker_live = (
        v is not None and not v["clean_exit"]
        and now - v["last_seen"] < cfg.stall_sec
      )
      if (
        backlog > 0 and worker_live and busy is not None
        and rec.get("dispatches", 0) > 0
        and busy <= cfg.device_idle_ratio
      ):
        anomalies.append({
          "kind": "device_idle", "worker": worker,
          "busy_ratio": busy, "min_busy_ratio": cfg.device_idle_ratio,
          "backlog": backlog,
        })

    # serving-tier detectors (ISSUE 9): request latency SLO + cold-miss
    # storm, from the per-request spans the serve tier journals
    serve_durs = sorted(scan["serve_durs"])
    serve_req = len(serve_durs)
    serve_fetches = scan["serve_fetches"]
    serve_p50 = _percentile(serve_durs, 0.50)
    serve_p99 = _percentile(serve_durs, 0.99)
    serve_miss_ratio = (serve_fetches / serve_req) if serve_req else None
    if (
      serve_req >= cfg.serve_min_requests
      and serve_miss_ratio is not None
      and serve_miss_ratio > cfg.serve_miss_ratio_max
    ):
      anomalies.append({
        "kind": "cold_miss_storm", "requests": serve_req,
        "backend_fetches": serve_fetches,
        "miss_ratio": round(serve_miss_ratio, 3),
        "max": cfg.serve_miss_ratio_max,
      })
    if cfg.serve_p99_ms and serve_p99 * 1e3 > cfg.serve_p99_ms:
      anomalies.append({
        "kind": "serve_latency_slo", "p99_ms": round(serve_p99 * 1e3, 1),
        "target_ms": cfg.serve_p99_ms, "requests": serve_req,
      })

    # serve federation detectors (ISSUE 18), from the fleet-aggregated
    # counters: a peer-fill failure storm means misses pay a dead peer
    # round before origin on every fill; shed rate over the SLO ceiling
    # means the fleet is turning real viewers away faster than budgeted
    peer_hits = counters.get("serve.peer.hits", 0)
    peer_fallbacks = counters.get("serve.peer.fallback", 0)
    peer_attempts = (
      peer_hits + peer_fallbacks + counters.get("serve.peer.notfound", 0)
    )
    peer_fail_ratio = (
      peer_fallbacks / peer_attempts if peer_attempts else None
    )
    if (
      peer_attempts >= cfg.serve_peer_min_attempts
      and peer_fail_ratio is not None
      and peer_fail_ratio > cfg.serve_peer_fail_max
    ):
      anomalies.append({
        "kind": "peer_fill_storm", "attempts": peer_attempts,
        "fallbacks": peer_fallbacks,
        "fail_ratio": round(peer_fail_ratio, 3),
        "max": cfg.serve_peer_fail_max,
      })
    serve_sheds = counters.get("serve.shed.requests", 0)
    serve_offered = serve_sheds + counters.get("serve.requests", 0)
    shed_ratio = (serve_sheds / serve_offered) if serve_offered else None
    if (
      serve_offered >= cfg.serve_min_requests
      and shed_ratio is not None
      and shed_ratio > cfg.serve_shed_ratio_max
    ):
      anomalies.append({
        "kind": "shed_rate_slo", "offered": serve_offered,
        "sheds": serve_sheds, "shed_ratio": round(shed_ratio, 3),
        "max": cfg.serve_shed_ratio_max,
      })

    # SLO burn: error-budget consumption rate (1.0 = burning exactly at
    # budget; >1 = on track to violate the SLO)
    success_rate = (tasks_ok / tasks_total) if tasks_total else None
    err_budget = max(1.0 - cfg.slo_success, 1e-9)
    burn = 0.0
    if success_rate is not None:
      burn = (1.0 - success_rate) / err_budget
    if cfg.slo_p95_ms and fleet_p95 > 0:
      burn = max(burn, (fleet_p95 * 1e3) / cfg.slo_p95_ms)
    if cfg.serve_p99_ms and serve_p99 > 0:
      burn = max(burn, (serve_p99 * 1e3) / cfg.serve_p99_ms)
    burn = round(burn, 3)

    # autoscale: workers active now vs workers needed to drain the
    # backlog within the horizon at the observed per-worker rate
    active = [
      w for w, v in per.items()
      if not v["clean_exit"] and now - v["last_seen"] < cfg.stall_sec
    ]
    contributing = [w for w, v in per.items() if v["task_durs"]]
    current = len(active)
    per_worker_rate = tasks_per_sec / max(len(contributing), 1)
    # the desired-workers formula lives in observability.autoscale so
    # the HealthEngine report, the fleet simulator, and the live
    # controller share one implementation (ISSUE 13 policy extraction)
    from .autoscale import AutoscalePolicy, compute_desired

    desired, damped = compute_desired(
      backlog, per_worker_rate, current,
      AutoscalePolicy(
        min_workers=cfg.min_workers, max_workers=cfg.max_workers,
        horizon_sec=cfg.horizon_sec, hysteresis=cfg.hysteresis,
      ),
    )

    workers_report = {
      w: {
        "tasks": len(v["task_durs"]),
        "tasks_failed": v["tasks_failed"],
        "p95_ms": round(_percentile(sorted(v["task_durs"]), 0.95) * 1e3, 1),
        "last_seen_age_sec": round(now - v["last_seen"], 1),
        "clean_exit": v["clean_exit"],
      }
      for w, v in sorted(per.items())
    }
    flagged = sorted({s["worker"] for s in stragglers})
    report = {
      "ts": now,
      "window_sec": cfg.window_sec,
      "healthy": not stragglers and not anomalies and burn <= 1.0,
      "stragglers": stragglers,
      "anomalies": anomalies,
      "flagged_workers": flagged,
      "fleet": {
        "workers_seen": len(per),
        "workers_active": current,
        "tasks": tasks_total,
        "tasks_failed": tasks_failed,
        "tasks_per_sec": round(tasks_per_sec, 3),
        "median_task_ms": round(fleet_median * 1e3, 1),
        "p95_task_ms": round(fleet_p95 * 1e3, 1),
        "stall_ratio": (
          round(stall_ratio, 3) if stall_ratio is not None else None
        ),
      },
      "slo": {
        "success_rate": (
          round(success_rate, 4) if success_rate is not None else None
        ),
        "target": cfg.slo_success,
        "p95_target_ms": cfg.slo_p95_ms,
        "burn": burn,
      },
      "autoscale": {
        "backlog": backlog,
        "current_workers": current,
        "desired_workers": desired,
        "per_worker_tasks_per_sec": round(per_worker_rate, 3),
        "horizon_sec": cfg.horizon_sec,
        "hysteresis_damped": damped,
      },
      "workers": workers_report,
    }
    if serve_req > 0 or peer_attempts or serve_sheds:
      report["serve"] = {
        "requests": serve_req,
        "backend_fetches": serve_fetches,
        "p50_ms": round(serve_p50 * 1e3, 1),
        "p99_ms": round(serve_p99 * 1e3, 1),
        "miss_ratio": (
          round(serve_miss_ratio, 3) if serve_miss_ratio is not None else None
        ),
        "p99_target_ms": cfg.serve_p99_ms,
        "peer_hits": peer_hits,
        "peer_attempts": peer_attempts,
        "peer_fail_ratio": (
          round(peer_fail_ratio, 3) if peer_fail_ratio is not None else None
        ),
        "sheds": serve_sheds,
        "shed_ratio": (
          round(shed_ratio, 3) if shed_ratio is not None else None
        ),
      }
    if spec_issued or counters.get("steal.claims", 0):
      report["speculation"] = {
        "issued": spec_issued,
        "won": spec_won,
        "fenced": spec_fenced,
        "waste_ratio": (
          round(spec_waste, 3) if spec_waste is not None else None
        ),
        "wasted_ms": counters.get("speculation.wasted_ms", 0),
        "steal_claims": counters.get("steal.claims", 0),
        "steal_granted": counters.get("steal.granted", 0),
        "steal_tasks": counters.get("steal.tasks", 0),
      }
    if corrupt_total or audit_findings:
      report["integrity"] = {
        "corrupt_reads": corrupt_reads,
        "verify_failed": verify_failed,
        "quarantined": quarantined,
        "audit_findings": audit_findings,
      }
    from . import device as device_mod

    report["devices"] = device_mod.fleet_summary(device_ledgers)
    return report


# -- consumers ----------------------------------------------------------------


def publish_gauges(report: dict) -> None:
  """Report → Prometheus gauges (rendered by observability.prom):
  ``igneous_fleet_stragglers``, ``igneous_fleet_desired_workers``,
  ``igneous_fleet_backlog``, ``igneous_slo_burn``,
  ``igneous_fleet_anomalies``."""
  metrics.gauge_set("fleet.stragglers", len(report["stragglers"]))
  metrics.gauge_set("fleet.anomalies", len(report["anomalies"]))
  metrics.gauge_set("fleet.desired_workers",
                    report["autoscale"]["desired_workers"])
  metrics.gauge_set("fleet.backlog", report["autoscale"]["backlog"])
  metrics.gauge_set("slo.burn", report["slo"]["burn"])
  dev = report.get("devices")
  if dev:
    if dev.get("busy_ratio") is not None:
      metrics.gauge_set("fleet.device_busy_ratio", dev["busy_ratio"])
    metrics.gauge_set("fleet.device_recompiles", dev["recompiles"])
    metrics.gauge_set("fleet.device_dispatches", dev["dispatches"])
    if dev.get("hbm_peak_frac") is not None:
      metrics.gauge_set("fleet.device_hbm_peak_frac", dev["hbm_peak_frac"])
  srv = report.get("serve")
  if srv:
    metrics.gauge_set("fleet.serve_requests", srv["requests"])
    metrics.gauge_set("fleet.serve_p99_ms", srv["p99_ms"])
    if srv.get("miss_ratio") is not None:
      metrics.gauge_set("fleet.serve_miss_ratio", srv["miss_ratio"])
    if srv.get("peer_fail_ratio") is not None:
      metrics.gauge_set("fleet.serve_peer_fail_ratio",
                        srv["peer_fail_ratio"])
    if srv.get("shed_ratio") is not None:
      metrics.gauge_set("fleet.serve_shed_ratio", srv["shed_ratio"])
  spec = report.get("speculation")
  if spec:
    # rendered by observability.prom as igneous_speculation_* — the
    # deployment.yaml igneous-campaign PrometheusRule alerts on these
    metrics.gauge_set("speculation.issued", spec["issued"])
    metrics.gauge_set("speculation.won", spec["won"])
    metrics.gauge_set("speculation.fenced", spec["fenced"])
    if spec.get("waste_ratio") is not None:
      metrics.gauge_set("speculation.waste_ratio", spec["waste_ratio"])
    metrics.gauge_set("steal.claims", spec["steal_claims"])
    metrics.gauge_set("steal.tasks", spec["steal_tasks"])
  integ = report.get("integrity")
  if integ:
    # rendered by observability.prom as igneous_integrity_* — the
    # deployment.yaml igneous-integrity PrometheusRule alerts on these
    metrics.gauge_set("integrity.corrupt_reads", integ["corrupt_reads"])
    metrics.gauge_set("integrity.quarantined", integ["quarantined"])
    metrics.gauge_set("integrity.audit_findings", integ["audit_findings"])


def health_events(report: dict) -> List[dict]:
  """Structured ``health.*`` journal records for one report (zero-dur
  span records, so ``fleet status|trace`` surface them natively)."""
  now = report["ts"]
  events = []

  def ev(name, **attrs):
    events.append({
      "kind": "span", "name": name, "ts": now, "dur": 0.0, **attrs,
    })

  for s in report["stragglers"]:
    ev("health.straggler", flagged=s["worker"], straggler_kind=s["kind"],
       detail={k: v for k, v in s.items() if k not in ("worker", "kind")})
  for a in report["anomalies"]:
    ev("health.anomaly", anomaly_kind=a["kind"],
       detail={k: v for k, v in a.items() if k != "kind"})
  if report["slo"]["burn"] > 1.0:
    ev("health.slo_burn", burn=report["slo"]["burn"],
       success_rate=report["slo"]["success_rate"],
       target=report["slo"]["target"])
  ev("health.autoscale", **report["autoscale"])
  return events


def emit_events(report: dict, journal) -> Optional[str]:
  """Append the report's ``health.*`` events to the journal as one
  segment (``journal`` is an ``observability.journal.Journal``)."""
  return journal.write_records(health_events(report), event="health")


def write_flags(cloudpath: str, report: dict) -> None:
  """Publish the straggler report where workers can see it
  (``<journal>/health/flags.json``): LeaseBatcher polls this and a
  flagged worker stops pre-leasing round i+1 — it surrenders queue
  depth to healthy workers instead of hoarding leases it will be slow
  to serve."""
  from ..storage import CloudFiles

  CloudFiles(cloudpath).put_json(FLAGS_KEY, {
    "ts": report["ts"],
    "stragglers": report["flagged_workers"],
    "desired_workers": report["autoscale"]["desired_workers"],
  })


def flagged_workers(cloudpath: str, max_age_sec: float = 600.0) -> set:
  """Workers the last health evaluation flagged (empty when no flags
  file exists or it is older than ``max_age_sec`` — stale verdicts must
  not dampen a worker forever)."""
  from ..storage import CloudFiles

  try:
    flags = CloudFiles(cloudpath).get_json(FLAGS_KEY)
  except Exception:
    return set()
  if not flags:
    return set()
  if time.time() - float(flags.get("ts") or 0) > max_age_sec:
    return set()
  return set(flags.get("stragglers") or ())


# -- rendering ----------------------------------------------------------------


def check_lines(report: dict) -> List[str]:
  """Human summary for ``igneous fleet check`` (and each ``watch``
  frame): verdict first, then every straggler/anomaly by name."""
  f, a = report["fleet"], report["autoscale"]
  lines = [
    ("HEALTHY" if report["healthy"] else "UNHEALTHY")
    + f" — {f['workers_active']} active / {f['workers_seen']} seen workers, "
      f"{f['tasks']} tasks in window ({f['tasks_failed']} failed)",
    f"throughput: {f['tasks_per_sec']} tasks/s  "
    f"p50 {f['median_task_ms']}ms p95 {f['p95_task_ms']}ms"
    + (f"  stall {f['stall_ratio']}" if f["stall_ratio"] is not None else ""),
    f"slo: success {report['slo']['success_rate']} "
    f"(target {report['slo']['target']}) burn {report['slo']['burn']}",
    f"autoscale: current {a['current_workers']} -> desired "
    f"{a['desired_workers']} (backlog {a['backlog']}, "
    f"{a['per_worker_tasks_per_sec']} tasks/s/worker"
    + (", damped)" if a["hysteresis_damped"] else ")"),
  ]
  srv = report.get("serve")
  if srv:
    lines.insert(3, (
      f"serve: {srv['requests']} requests  p50 {srv['p50_ms']}ms "
      f"p99 {srv['p99_ms']}ms  miss {srv['miss_ratio']}"
      + (f" (p99 target {srv['p99_target_ms']}ms)"
         if srv.get("p99_target_ms") else "")
      + (f"  peer-fill {srv['peer_hits']}/{srv['peer_attempts']}"
         if srv.get("peer_attempts") else "")
      + (f"  shed {srv['sheds']} ({srv['shed_ratio']})"
         if srv.get("sheds") else "")
    ))
  for s in report["stragglers"]:
    if s["kind"] == "stalled":
      lines.append(
        f"STRAGGLER {s['worker']}: stalled — no journal record for "
        f"{s['last_seen_age_sec']}s (threshold {s['stall_sec']}s)"
      )
    else:
      lines.append(
        f"STRAGGLER {s['worker']}: p95 {s['p95_ms']}ms = "
        f"{s['ratio']}x fleet median {s['fleet_median_ms']}ms"
      )
  for an in report["anomalies"]:
    detail = " ".join(
      f"{k}={v}" for k, v in an.items() if k != "kind"
    )
    lines.append(f"ANOMALY {an['kind']}: {detail}")
  return lines


def render_dashboard(report: dict, queue_stats: Optional[dict] = None,
                     title: str = "igneous fleet") -> List[str]:
  """One ``fleet watch`` frame: status header, per-worker table,
  alerts, autoscale line."""
  ts = time.strftime("%H:%M:%S", time.localtime(report["ts"]))
  lines = [f"{title} — {ts}  (window {int(report['window_sec'])}s)"]
  if queue_stats:
    q = queue_stats
    lines.append(
      "queue: "
      + "  ".join(
        f"{k} {q[k]}" for k in
        ("backlog", "leased", "completed", "dlq", "stale_leases")
        if q.get(k) is not None
      )
    )
  lines.extend(check_lines(report)[:4])
  dev = report.get("devices")
  if dev:
    fp = dev.get("fastpath") or {}
    fp_total = fp.get("batched", 0) + fp.get("host", 0)
    lines.append(
      "devices: "
      + (
        f"busy {dev['busy_ratio'] * 100:.1f}%  "
        if dev.get("busy_ratio") is not None else ""
      )
      + f"dispatches {dev['dispatches']}  recompiles {dev['recompiles']}"
      + (
        f"  hbm peak {dev['hbm_peak_frac'] * 100:.0f}%"
        if dev.get("hbm_peak_frac") is not None else ""
      )
      + (
        f"  pad waste {dev['pad_waste_ratio'] * 100:.1f}%"
        if dev.get("pad_waste_ratio") is not None else ""
      )
      + (
        f"  fastpath {fp.get('batched', 0)}/{fp_total} batched"
        if fp_total else ""
      )
    )
  spec = report.get("speculation")
  if spec:
    lines.append(
      f"speculation: issued {spec['issued']}  won {spec['won']}  "
      f"fenced {spec['fenced']}"
      + (
        f"  waste {spec['waste_ratio']}"
        if spec.get("waste_ratio") is not None else ""
      )
      + (
        f"  steal {spec['steal_granted']}/{spec['steal_claims']} grants"
        f" ({spec['steal_tasks']} tasks)"
        if spec["steal_claims"] else ""
      )
    )
  lines.append("")
  lines.append(f"{'worker':<28}{'tasks':>6}{'fail':>6}{'p95_ms':>9}"
               f"{'seen_ago':>10}  state")
  flagged = set(report["flagged_workers"])
  for w, v in report["workers"].items():
    if v["clean_exit"]:
      state = "drained"
    elif w in flagged:
      state = "STRAGGLER"
    else:
      state = "ok"
    lines.append(
      f"{w:<28}{v['tasks']:>6}{v['tasks_failed']:>6}{v['p95_ms']:>9}"
      f"{v['last_seen_age_sec']:>9.1f}s  {state}"
    )
  alerts = check_lines(report)[4:]
  if alerts:
    lines.append("")
    lines.extend(alerts)
  return lines


def default_checker_id() -> str:
  host = socket.gethostname().split(".")[0] or "health"
  return f"health-{host}-{os.getpid()}"


def report_json(report: dict) -> str:
  return json.dumps(report, indent=2, sort_keys=False)
