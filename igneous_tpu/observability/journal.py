"""Durable fleet event journal: JSONL segments on the storage layer.

Workers append batches of span records + a cumulative metrics snapshot
as immutable segment objects under ``<queue>/journal/`` (any CloudFiles
path — a shared filesystem next to an fq:// queue, or a bucket prefix
for SQS fleets via ``IGNEOUS_JOURNAL``). Segments are write-once and
worker-unique, so no coordination is needed; ``igneous fleet`` merges
them after the fact.

Flush triggers: a time interval (``IGNEOUS_JOURNAL_FLUSH_SEC``, default
30), lease-round boundaries, a lifecycle drain request (StopFlag.set
marks the journal dirty; the poll loop's next ``maybe_flush`` writes),
and process exit (the CLI worker arms an atexit last-will so even a
crashing worker leaves its final batch behind).

Record kinds (one JSON object per line):

  {"kind": "span", "worker": ..., "trace": ..., "span": ..., "parent":
   ..., "name": ..., "ts": ..., "dur": ..., ...attrs}
  {"kind": "counters", "worker": ..., "ts": ..., "event": ...,
   "counters": {...}, "timers": {...}, "gauges": {...}}
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from typing import Iterable, Iterator, List, Optional

from . import metrics, trace

from ..analysis import knobs

FLUSH_SEC_ENV = "IGNEOUS_JOURNAL_FLUSH_SEC"
PATH_ENV = "IGNEOUS_JOURNAL"
COMPRESS_ENV = "IGNEOUS_JOURNAL_COMPRESS"
DEFAULT_FLUSH_SEC = 30.0

_GZIP_MAGIC = b"\x1f\x8b"


def compression_enabled() -> bool:
  return knobs.get_bool(COMPRESS_ENV)


def encode_segment(data: bytes) -> bytes:
  """Segment bytes as written: gzip when ``IGNEOUS_JOURNAL_COMPRESS=1``
  (mtime pinned to 0 so identical content is identical bytes — the
  simulator's bit-identical-rerun contract extends through compression),
  plain JSONL otherwise. Segment names stay ``*.jsonl`` either way; the
  read side sniffs the gzip magic, so mixed journals (campaign enabled
  compression midway) merge fine."""
  if not compression_enabled():
    return data
  import gzip
  import io

  buf = io.BytesIO()
  with gzip.GzipFile(fileobj=buf, mode="wb", mtime=0) as gz:
    gz.write(data)
  return buf.getvalue()


def decode_segment(data: bytes) -> bytes:
  """Inverse of :func:`encode_segment`, keyed on magic bytes rather than
  the env — readers never need to know how the writer was configured."""
  if data[:2] == _GZIP_MAGIC:
    import gzip

    try:
      return gzip.decompress(data)
    except OSError:
      return data
  return data

# extra-record providers: callables returning a list of record dicts to
# append to every flushed segment (the device plane's utilization ledger
# rides along this way — journal.py stays ignorant of who contributes)
_RECORD_PROVIDERS: list = []
# poll hooks: cheap callables invoked from maybe_flush_active (the
# between-tasks cadence every worker loop already has) — the profiler
# trigger poll lives here so solo AND batched workers both see it
_POLL_HOOKS: list = []


def register_record_provider(fn) -> None:
  if fn not in _RECORD_PROVIDERS:
    _RECORD_PROVIDERS.append(fn)


def register_poll_hook(fn) -> None:
  if fn not in _POLL_HOOKS:
    _POLL_HOOKS.append(fn)


def default_worker_id() -> str:
  host = socket.gethostname().split(".")[0] or "worker"
  return f"{host}-{os.getpid()}"


def journal_path_for(queue, spec: Optional[str] = None) -> Optional[str]:
  """Resolve where a worker's journal lives: ``IGNEOUS_JOURNAL`` wins;
  fq:// queues get a ``journal/`` sibling of queue/leased/dlq on the same
  filesystem; other backends (SQS has no storage) need the env."""
  env = knobs.get_str(PATH_ENV)
  if env:
    return env
  path = getattr(queue, "path", None)  # FileQueue
  if path:
    return f"file://{path}/journal"
  if spec:
    if spec.startswith("fq://"):
      return f"file://{os.path.abspath(os.path.expanduser(spec[5:]))}/journal"
    if "://" not in spec:
      return f"file://{os.path.abspath(os.path.expanduser(spec))}/journal"
  return None


class Journal:
  """Append-only segment writer for one worker process."""

  def __init__(self, cloudpath: str, worker_id: Optional[str] = None,
               flush_interval: Optional[float] = None):
    self.cloudpath = cloudpath
    self.worker_id = worker_id or default_worker_id()
    if flush_interval is None:
      flush_interval = knobs.get_float(FLUSH_SEC_ENV)
    self.flush_interval = float(flush_interval)
    self._lock = threading.Lock()
    self._seq = 0  # guarded-by: self._lock
    self._last_flush = time.monotonic()  # guarded-by: self._lock
    self._dirty = threading.Event()  # drain requested: flush ASAP
    self.segments_written = 0  # guarded-by: self._lock
    # register the self-health keys so the Prometheus exposition carries
    # igneous_journal_segments_total/..._flush_failed_total from the
    # moment a journal exists — a writer that NEVER lands a segment is
    # exactly the dead-journal case the fleet health plane must see
    metrics.incr("journal.segments", 0)
    metrics.incr("journal.flush_failed", 0)

  def last_flush_age(self) -> float:
    """Seconds since the last flush attempt (Prometheus self-health:
    ``igneous_journal_last_flush_age_seconds``)."""
    return time.monotonic() - self._last_flush

  # -- write side -----------------------------------------------------------

  def mark_dirty(self) -> None:
    """Request an out-of-band flush (lifecycle drain, round boundary);
    safe to call from signal handlers — it only sets an event."""
    self._dirty.set()

  def maybe_flush(self, event: Optional[str] = None) -> bool:
    """Flush if the interval elapsed or a flush was requested. Cheap when
    neither holds (one monotonic read). Called from poll loops between
    tasks."""
    if not self._dirty.is_set():
      if time.monotonic() - self._last_flush < self.flush_interval:
        return False
    return self.flush(event=event)

  def flush(self, event: Optional[str] = None) -> bool:
    """Write one segment with all pending spans + a metrics snapshot.
    Skips the write when there is nothing new and no ``event`` to record.
    Returns True when a segment landed."""
    extra_records = []
    for provider in list(_RECORD_PROVIDERS):
      try:
        extra_records.extend(provider() or ())
      except Exception:
        metrics.incr("journal.provider_failed")
    with self._lock:
      self._dirty.clear()
      spans = trace.drain_spans()
      self._last_flush = time.monotonic()
      if not spans and not extra_records and event is None:
        return False
      lines = []
      snap = {
        "kind": "counters", "worker": self.worker_id, "ts": time.time(),
        "event": event or "interval",
        "counters": metrics.counters_snapshot(),
        "timers": metrics.timer_totals(),
        "gauges": metrics.gauges_snapshot(),
      }
      dropped = trace.dropped_spans()
      if dropped:
        snap["spans_dropped"] = dropped
      lines.append(json.dumps(snap))
      for rec in spans:
        rec = dict(rec)
        rec["kind"] = "span"
        rec["worker"] = self.worker_id
        lines.append(json.dumps(rec))
      for rec in extra_records:
        rec = dict(rec)
        rec.setdefault("kind", "span")
        rec["worker"] = self.worker_id
        lines.append(json.dumps(rec))
      name = f"{self.worker_id}-{self._seq:06d}.jsonl"
      self._seq += 1
      data = encode_segment(("\n".join(lines) + "\n").encode("utf8"))
    try:
      from ..storage import CloudFiles

      CloudFiles(self.cloudpath).put(name, data, compress=None)
    except Exception:
      # observability must never kill a healthy worker; the batch is
      # gone but the next flush carries the cumulative counters anyway
      metrics.incr("journal.flush_failed")
      return False
    with self._lock:
      self.segments_written += 1
    metrics.incr("journal.segments")
    # rollup maintenance rides the flush cadence: every N segments the
    # worker folds its OWN raw segments (worker-unique names, so no
    # coordination) into <journal>/rollup/ — `fleet status` stays
    # O(windows) even on long campaigns
    from . import rollup

    rollup.maybe_self_compact(self)
    return True

  def write_records(self, records: Iterable[dict],
                    event: Optional[str] = None) -> Optional[str]:
    """Write one segment holding ``records`` verbatim (plus worker/kind
    defaults) — the health engine's emission path for ``health.*``
    events. Returns the segment name, or None when the put failed."""
    with self._lock:
      lines = []
      for rec in records:
        rec = dict(rec)
        rec.setdefault("kind", "span")
        rec.setdefault("worker", self.worker_id)
        if event is not None:
          rec.setdefault("event", event)
        lines.append(json.dumps(rec))
      if not lines:
        return None
      name = f"{self.worker_id}-{self._seq:06d}.jsonl"
      self._seq += 1
      data = encode_segment(("\n".join(lines) + "\n").encode("utf8"))
    try:
      from ..storage import CloudFiles

      CloudFiles(self.cloudpath).put(name, data, compress=None)
    except Exception:
      metrics.incr("journal.flush_failed")
      return None
    with self._lock:
      self.segments_written += 1
    metrics.incr("journal.segments")
    return name


# -- process-wide active journal ---------------------------------------------

_ACTIVE: Optional[Journal] = None
_LAST_WILL = {"armed": False, "fired": False}


def set_active(journal: Optional[Journal]) -> None:
  global _ACTIVE
  _ACTIVE = journal


def get_active() -> Optional[Journal]:
  return _ACTIVE


def maybe_flush_active(event: Optional[str] = None) -> None:
  j = _ACTIVE
  if j is not None:
    for hook in list(_POLL_HOOKS):
      try:
        hook(j)
      except Exception:
        metrics.incr("journal.poll_hook_failed")
    j.maybe_flush(event=event)


def flush_active(event: Optional[str] = None) -> None:
  j = _ACTIVE
  if j is not None:
    j.flush(event=event)


def request_flush() -> None:
  """Signal-handler-safe: mark the active journal dirty so the next
  ``maybe_flush`` (poll loop, round boundary) writes the pending batch."""
  j = _ACTIVE
  if j is not None:
    j.mark_dirty()


def install_last_will(extra: Optional[dict] = None) -> None:
  """Arm an atexit hook: whatever kills this worker (unhandled exception,
  sys.exit, normal return), the final counters line + journal batch land.
  Re-arms the fire guard each call — a process hosting several worker
  runs (tests, notebooks) gets one last will per run, not per process —
  while the atexit registration itself stays singular."""
  _LAST_WILL["fired"] = False
  if _LAST_WILL["armed"]:
    return
  _LAST_WILL["armed"] = True
  import atexit

  atexit.register(fire_last_will, "atexit", extra or {})


def fire_last_will(event: str = "exit", extra: Optional[dict] = None) -> None:
  if _LAST_WILL["fired"]:
    return
  _LAST_WILL["fired"] = True
  try:
    metrics.emit_counters(event=event, **(extra or {}))
  finally:
    flush_active(event=event)


def disarm_last_will(flush: bool = True) -> None:
  """Clean-exit path: the journal's final segment still lands, but no
  counters line prints (healthy workers keep their historical stdout)."""
  _LAST_WILL["fired"] = True
  if flush:
    flush_active(event="exit")


# -- read side ----------------------------------------------------------------


def is_raw_segment(key: str) -> bool:
  """Top-level ``*.jsonl`` objects are raw worker segments; everything
  in a subdirectory (``rollup/`` compactions, ``health/`` flag files)
  belongs to other subsystems and must not merge as span records."""
  return "/" not in key and key.endswith(".jsonl")


def list_segments(cloudpath: str) -> List[str]:
  """Sorted raw segment names under a journal path."""
  from ..storage import CloudFiles

  try:
    return sorted(k for k in CloudFiles(cloudpath).list() if is_raw_segment(k))
  except Exception:
    return []


def read_records(cloudpath: str,
                 keys: Optional[Iterable[str]] = None) -> Iterator[dict]:
  """Iterate every record of every raw segment under a journal path
  (order: segment name, then line order — i.e. per-worker
  chronological). ``keys`` restricts to specific segments (the rollup
  merge path reads only uncovered ones)."""
  from ..storage import CloudFiles

  cf = CloudFiles(cloudpath)
  if keys is None:
    keys = list_segments(cloudpath)
  for key in keys:
    data = cf.get(key)
    if data is None:
      continue
    data = decode_segment(data)
    for line in data.decode("utf8", errors="replace").splitlines():
      line = line.strip()
      if not line:
        continue
      try:
        rec = json.loads(line)
      except ValueError:
        continue
      rec.setdefault("segment", key)
      yield rec


def segment_count(cloudpath: str) -> int:
  return len(list_segments(cloudpath))
