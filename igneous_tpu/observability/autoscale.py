"""Closed-loop autoscaling: one policy, pluggable actuators, a controller.

PR 6's HealthEngine computes ``desired_workers`` but nothing *acts* on
it — deployments lean on an external HPA reading the gauge. This module
closes the loop in-process:

* :func:`compute_desired` — the desired-workers formula, extracted from
  ``health.py`` so the HealthEngine, the fleet simulator, and the live
  controller run ONE implementation (a policy validated in simulation is
  literally the code that scales the real fleet);
* :class:`PolicyLoop` — the stateful half (cooldown between actions,
  per-action step cap) shared by simulator and controller;
* actuators — :class:`LocalPoolActuator` spawns/drains real ``igneous
  execute`` worker subprocesses (dev fleets, CI, policy validation),
  :class:`TextfileActuator` publishes the target where an external
  reconciler reads it (k8s sidecar pattern), :class:`CommandActuator`
  shells out to a ``kubectl scale``-style template;
* :class:`AutoscaleController` — the ``igneous fleet autoscale`` loop:
  poll journal + queue depth, evaluate, damp, actuate, journal the
  action as ``autoscale.action`` records + ``autoscale.*`` counters.

Safety posture: the controller never kills a worker — scale-down is
SIGTERM, riding the PR 2 graceful-drain path (finish in-flight work,
release leases, exit 83). Cooldown and hysteresis are enforced HERE, not
in the actuator, so every actuator gets the same damping.
"""

from __future__ import annotations

import math
import os
import signal
import subprocess
import sys
import time
from dataclasses import dataclass, fields
from typing import List, Optional

from . import metrics

from ..analysis import knobs

COOLDOWN_ENV = "IGNEOUS_AUTOSCALE_COOLDOWN_SEC"
INTERVAL_ENV = "IGNEOUS_AUTOSCALE_INTERVAL_SEC"
STEP_MAX_ENV = "IGNEOUS_AUTOSCALE_STEP_MAX"

DEFAULT_COOLDOWN_SEC = 60.0
DEFAULT_INTERVAL_SEC = 15.0


@dataclass
class AutoscalePolicy:
  """Sizing + damping knobs. The first four mirror the PR 6 HealthConfig
  fields (same env vars, same defaults); cooldown/step are controller
  additions — a recommendation can flap per-evaluation, an *action*
  must not."""

  min_workers: int = 1
  max_workers: int = 1000
  horizon_sec: float = 600.0
  hysteresis: float = 0.2
  cooldown_sec: float = DEFAULT_COOLDOWN_SEC
  step_max: int = 0  # max workers added/removed per action; 0 = no cap

  _ENV = {
    "min_workers": "IGNEOUS_AUTOSCALE_MIN",
    "max_workers": "IGNEOUS_AUTOSCALE_MAX",
    "horizon_sec": "IGNEOUS_AUTOSCALE_HORIZON_SEC",
    "hysteresis": "IGNEOUS_AUTOSCALE_HYSTERESIS",
    "cooldown_sec": COOLDOWN_ENV,
    "step_max": STEP_MAX_ENV,
  }

  @classmethod
  def from_env(cls, **overrides) -> "AutoscalePolicy":
    kw = {}
    for f in fields(cls):
      if f.name.startswith("_"):
        continue
      val = overrides.get(f.name)
      if val is None:
        val = knobs.opt_float(cls._ENV[f.name])
      if val is not None:
        kw[f.name] = val
    pol = cls(**kw)
    pol.min_workers = int(pol.min_workers)
    pol.max_workers = int(pol.max_workers)
    pol.step_max = int(pol.step_max)
    return pol


def compute_desired(backlog: int, per_worker_rate: float, current: int,
                    policy: AutoscalePolicy):
  """Workers needed to drain ``backlog`` within ``horizon_sec`` at the
  observed per-worker rate, clamped to [min, max] and hysteresis-damped
  against ``current``. Returns ``(desired, damped)``.

  This IS the PR 6 HealthEngine formula (extracted, not forked):
  ``health.evaluate`` calls it for the report's ``desired_workers``, the
  simulator calls it for virtual controller ticks, and the live
  controller calls it before actuating — tune once, behave identically
  everywhere."""
  if backlog <= 0:
    desired = policy.min_workers
  elif per_worker_rate <= 0:
    # backlog with no observed throughput: never scale DOWN on missing
    # data; hold current (or bootstrap to min when nothing runs yet)
    desired = max(current, policy.min_workers)
  else:
    desired = int(math.ceil(
      backlog / (per_worker_rate * policy.horizon_sec)
    ))
  desired = max(policy.min_workers, min(policy.max_workers, desired))
  if backlog > 0 and desired < 1:
    # scale-to-zero floors (batch campaigns) still need a bootstrap
    # worker whose journal seeds the rate estimate
    desired = 1
  damped = False
  if (
    backlog > 0 and current > 0
    and abs(desired - current) / current <= policy.hysteresis
  ):
    desired, damped = current, True
  return desired, damped


class PolicyLoop:
  """Stateful damping over :func:`compute_desired`: a cooldown window
  after every action and an optional per-action step cap. Deterministic
  given explicit ``now`` values — the simulator drives it with virtual
  time, the controller with wall-clock."""

  def __init__(self, policy: Optional[AutoscalePolicy] = None):
    self.policy = policy or AutoscalePolicy.from_env()
    self.last_change_ts: Optional[float] = None

  def decide(self, backlog: int, per_worker_rate: float, current: int,
             now: float) -> dict:
    pol = self.policy
    desired, damped = compute_desired(
      backlog, per_worker_rate, current, pol
    )
    target = desired
    reason = "steady"
    if target != current:
      reason = "scale_up" if target > current else "scale_down"
      if (
        self.last_change_ts is not None
        and now - self.last_change_ts < pol.cooldown_sec
      ):
        target, reason = current, "cooldown"
      elif pol.step_max > 0 and abs(target - current) > pol.step_max:
        target = current + (
          pol.step_max if target > current else -pol.step_max
        )
    elif damped:
      reason = "hysteresis"
    if target != current:
      self.last_change_ts = now
    return {
      "backlog": int(backlog),
      "per_worker_rate": round(per_worker_rate, 4),
      "current": int(current),
      "desired": int(desired),
      "target": int(target),
      "reason": reason,
    }


# -- actuators ----------------------------------------------------------------


class Actuator:
  """Minimal surface the controller needs: observed worker count and a
  scale-to-N action. ``reap`` lets process-owning actuators collect
  exits between ticks; ``shutdown`` is the controller's exit path."""

  name = "abstract"

  def current(self) -> int:
    raise NotImplementedError

  def scale_to(self, n: int) -> None:
    raise NotImplementedError

  def reap(self) -> None:
    pass

  def shutdown(self) -> None:
    pass


class LocalPoolActuator(Actuator):
  """A real local worker pool: ``scale_to`` spawns/drains ``igneous
  execute`` subprocesses. This is the dev/validation actuator — the
  sim_smoke acceptance drives it against a live fq:// queue — and the
  honest definition of "the controller works": real processes, real
  leases, real graceful drains.

  Scale-down SIGTERMs the newest workers (the PR 2 drain path: finish
  the in-flight task, release pre-leases, flush the journal, exit 83);
  nothing is ever SIGKILLed here."""

  name = "local"

  def __init__(self, queue_spec: str, worker_args: Optional[List[str]] = None,
               env: Optional[dict] = None, grace_sec: float = 60.0):
    self.queue_spec = queue_spec
    self.worker_args = list(worker_args or ())
    self.env = dict(os.environ, **(env or {}))
    self.grace_sec = grace_sec
    self.procs: List[subprocess.Popen] = []
    self.stats = {"spawned": 0, "drained": 0, "exits": {}}

  def _spawn(self) -> subprocess.Popen:
    cmd = [
      sys.executable, "-m", "igneous_tpu", "execute", self.queue_spec,
      *self.worker_args,
    ]
    proc = subprocess.Popen(cmd, env=self.env)
    self.stats["spawned"] += 1
    return proc

  def reap(self) -> None:
    alive = []
    for p in self.procs:
      rc = p.poll()
      if rc is None:
        alive.append(p)
      else:
        key = str(rc)
        self.stats["exits"][key] = self.stats["exits"].get(key, 0) + 1
    self.procs = alive

  def current(self) -> int:
    self.reap()
    return len(self.procs)

  def scale_to(self, n: int) -> None:
    self.reap()
    n = max(int(n), 0)
    while len(self.procs) < n:
      self.procs.append(self._spawn())
    surplus = len(self.procs) - n
    for p in self.procs[len(self.procs) - surplus:]:
      try:
        p.send_signal(signal.SIGTERM)
      except OSError:
        pass
      self.stats["drained"] += 1
    # drained workers stay in self.procs until reap() sees them exit:
    # "current" keeps counting a draining worker (it still holds leases)

  def shutdown(self) -> None:
    """Drain everything and wait out the grace window."""
    self.scale_to(0)
    deadline = time.monotonic() + self.grace_sec
    for p in self.procs:
      timeout = max(deadline - time.monotonic(), 0.1)
      try:
        p.wait(timeout=timeout)
      except subprocess.TimeoutExpired:
        p.kill()
        p.wait()
    self.reap()


class TextfileActuator(Actuator):
  """Publish the target where an external reconciler reads it — a k8s
  sidecar watching a shared volume, a node-exporter textfile collector,
  a cron diffing the file against ``kubectl get deploy``. Atomic
  (tmp+rename) so readers never see a torn write."""

  name = "textfile"

  def __init__(self, path: str, initial: int = 0):
    self.path = path
    self._current = int(initial)

  def current(self) -> int:
    return self._current

  def scale_to(self, n: int) -> None:
    import json

    tmp = f"{self.path}.tmp.{os.getpid()}"
    payload = {"desired_workers": int(n), "ts": time.time()}
    dirname = os.path.dirname(self.path)
    if dirname:
      os.makedirs(dirname, exist_ok=True)
    with open(tmp, "w") as f:
      json.dump(payload, f)
    os.replace(tmp, self.path)
    self._current = int(n)


class CommandActuator(Actuator):
  """Shell out to a scale command template with a ``{n}`` placeholder —
  ``kubectl scale --replicas={n} deployment/igneous-worker`` being the
  canonical production wiring. The observed count is the last target we
  set (external truth lives in the orchestrator)."""

  name = "command"

  def __init__(self, template: str, initial: int = 0):
    if "{n}" not in template:
      raise ValueError("command template needs a {n} placeholder")
    self.template = template
    self._current = int(initial)

  def current(self) -> int:
    return self._current

  def scale_to(self, n: int) -> None:
    import shlex

    cmd = shlex.split(self.template.format(n=int(n)))
    res = subprocess.run(cmd, capture_output=True)
    if res.returncode != 0:
      metrics.incr("autoscale.actuate_failed")
      raise RuntimeError(
        f"scale command failed rc={res.returncode}: "
        f"{res.stderr.decode('utf8', errors='replace')[-500:]}"
      )
    self._current = int(n)


# -- controller ---------------------------------------------------------------


class AutoscaleController:
  """The ``igneous fleet autoscale`` loop.

  Each tick: read the journal (rollups + uncovered raw — the PR 6
  O(windows) path), evaluate the HealthEngine for the per-worker rate,
  snapshot live queue depth for backlog (fresher than the journal),
  run the :class:`PolicyLoop`, actuate, and journal the action — so
  ``igneous fleet status|watch`` and the simulator's live-vs-predicted
  comparison see the controller's own history as first-class records."""

  def __init__(
    self,
    journal_path: str,
    queue,
    actuator: Actuator,
    policy: Optional[AutoscalePolicy] = None,
    health_config=None,
    interval_sec: Optional[float] = None,
    journal=None,
  ):
    from . import health as health_mod
    from . import journal as journal_mod

    self.journal_path = journal_path
    self.queue = queue
    self.actuator = actuator
    self.loop = PolicyLoop(policy)
    self.health_config = health_config
    self.engine = health_mod.HealthEngine(health_config)
    self.interval_sec = (
      float(interval_sec) if interval_sec is not None
      else knobs.get_float(INTERVAL_ENV)
    )
    self.journal = journal or journal_mod.Journal(
      journal_path, worker_id=f"autoscale-{os.getpid()}",
    )
    self.history: List[dict] = []

  def _queue_stats(self) -> dict:
    if hasattr(self.queue, "depth_snapshot"):
      try:
        return self.queue.depth_snapshot()
      except Exception:
        pass
    try:
      return {"backlog": int(getattr(self.queue, "backlog"))}
    except Exception:
      return {"backlog": 0}

  def step(self, now: Optional[float] = None) -> dict:
    from . import fleet

    now = time.time() if now is None else now
    queue_stats = self._queue_stats()
    backlog = int(queue_stats.get("backlog") or 0)
    per_worker_rate = 0.0
    report = None
    try:
      records = fleet.load_effective(self.journal_path)
    except Exception:
      records = []
    if records:
      report = self.engine.evaluate(records, queue_stats, now=now)
      per_worker_rate = report["autoscale"]["per_worker_tasks_per_sec"]
    # the last health report + raw records, for composers (the campaign
    # runner writes flags / drives speculation off the SAME evaluation
    # this tick actuated on, without a second journal load)
    self.last_report = report
    self.last_records = records
    current = self.actuator.current()
    decision = self.loop.decide(backlog, per_worker_rate, current, now)
    decision["ts"] = now
    decision["actuator"] = self.actuator.name
    target = decision["target"]
    if target != current:
      self.actuator.scale_to(target)
      delta = target - current
      if delta > 0:
        metrics.incr("autoscale.scale_up")
        metrics.incr("autoscale.workers_added", delta)
      else:
        metrics.incr("autoscale.scale_down")
        metrics.incr("autoscale.workers_removed", -delta)
      decision["actuated"] = True
    else:
      metrics.incr("autoscale.steady")
      decision["actuated"] = False
    metrics.gauge_set("autoscale.target_workers", target)
    self.history.append(decision)
    # journal the action: one autoscale.action span + this process's
    # cumulative autoscale.* counters, so `fleet status` counts actions
    # and the simulator's validation can diff policy traces
    try:
      self.journal.write_records(
        [
          {
            "kind": "span", "name": "autoscale.action",
            "ts": now, "dur": 0.0, **{
              k: v for k, v in decision.items() if k != "ts"
            },
          },
          {
            "kind": "counters", "ts": now, "event": "autoscale",
            "counters": metrics.counters_snapshot(),
            "timers": {}, "gauges": metrics.gauges_snapshot(),
          },
        ],
        event="autoscale",
      )
    except Exception:
      metrics.incr("autoscale.journal_failed")
    return decision

  def run(
    self,
    iterations: Optional[int] = None,
    stop_when_drained: bool = False,
    sleep_fn=time.sleep,
  ) -> List[dict]:
    """Tick until ``iterations`` runs out (None = forever), or — with
    ``stop_when_drained`` — until the queue has no backlog and the pool
    sits at the policy floor (the batch-campaign exit: scale up, drain,
    scale down, leave)."""
    n = 0
    while True:
      decision = self.step()
      n += 1
      if stop_when_drained:
        self.actuator.reap()
        if (
          decision["backlog"] <= 0
          and self.actuator.current() <= self.loop.policy.min_workers
        ):
          return self.history
      if iterations is not None and n >= iterations:
        return self.history
      sleep_fn(self.interval_sec)
