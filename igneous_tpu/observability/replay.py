"""Workload mining: fold journal history into a replayable model.

The journal (PR 5-7) records everything a capacity model needs — per-task
spans with durations/attempts/errors, per-round lease overhead, device
transfer byte counts, per-worker latency spread — but nothing reads it
*forward* in time. :class:`WorkloadModel` is that forward view: empirical
per-task-type distributions mined from journal records (raw segments or
rollups interchangeably, since rollups keep task spans verbatim), small
enough to serialize next to the journal and deterministic enough to seed
the fleet simulator (:mod:`.sim`).

What gets mined:

* **durations** — per task type, error-free deliveries only, as a capped
  empirical sample list (the simulator bootstraps draws from it, so
  straggler *tails* survive — no parametric fit to hide them);
* **retry probability** — failed deliveries / total deliveries per type
  (the journal's ``error`` spans ARE the empirical failure process);
* **bytes moved** — h2d/d2h transfer spans and storage get/put byte
  attrs, attributed to task types through each span's trace id;
* **round overhead** — ``lease.acquire`` spans (queue interaction time
  per lease round, recorded by the lease batcher) so batched campaigns
  simulate queue costs, not just compute;
* **worker speed spread** — per-worker median vs fleet median, so a
  simulated fleet replays the real fleet's heterogeneity instead of N
  identical clones.

Everything is plain JSON (:meth:`to_dict`/:meth:`from_dict`,
:meth:`save`/:meth:`load` via CloudFiles) — a mined model is an artifact
you can commit, diff, and re-simulate months later.
"""

from __future__ import annotations

import json
from collections import defaultdict
from typing import Dict, Iterable, List, Optional

MODEL_VERSION = 1

# per-type duration sample cap: 4096 doubles keep a model file small
# (~32KB/type) while pinning p99 of any realistic campaign
DEFAULT_SAMPLE_CAP = 4096

# spans whose byte counts attribute data movement to the owning trace
_BYTE_SPAN_NAMES = ("device.h2d", "device.d2h")


def _percentile(sorted_vals: List[float], q: float) -> float:
  if not sorted_vals:
    return 0.0
  idx = min(int(q * (len(sorted_vals) - 1) + 0.5), len(sorted_vals) - 1)
  return sorted_vals[idx]


class WorkloadModel:
  """Empirical fleet workload distributions mined from journal records."""

  def __init__(
    self,
    task_types: Optional[Dict[str, dict]] = None,
    round_overhead: Optional[dict] = None,
    worker_speeds: Optional[List[float]] = None,
    meta: Optional[dict] = None,
    range_sizes: Optional[List[int]] = None,
  ):
    # task_types[name] = {count, failures, sum, durs (sorted, capped),
    #                     bytes_per_task, max_attempt}
    self.task_types: Dict[str, dict] = task_types or {}
    # round_overhead = {count, sum, durs} from lease.acquire spans
    self.round_overhead: dict = round_overhead or {
      "count": 0, "sum": 0.0, "durs": [],
    }
    # range-lease spans per round, mined from the lease batcher's
    # ``range_sizes`` attr on lease.acquire (ISSUE 15); empty for
    # campaigns that ran per-task leases
    self.range_sizes: List[int] = list(range_sizes or [])
    # per-worker median_dur / fleet median_dur ratios (sorted): the
    # straggler-tail replay — a simulated worker's speed is one of these
    self.worker_speeds: List[float] = sorted(worker_speeds or [])
    self.meta: dict = meta or {}

  # -- mining ---------------------------------------------------------------

  @classmethod
  def mine(
    cls,
    records: Iterable[dict],
    sample_cap: int = DEFAULT_SAMPLE_CAP,
    window_sec: Optional[float] = None,
    now: Optional[float] = None,
  ) -> "WorkloadModel":
    """Fold journal records (``fleet.load_effective`` output — rollups
    and raw mix freely) into a model. ``window_sec`` restricts to spans
    ending after ``now - window_sec`` (None = all history)."""
    from . import fleet

    records = list(records)
    if now is None and window_sec is not None:
      now = max(
        (float(r.get("ts") or 0.0) + float(r.get("dur") or 0.0)
         for r in fleet.iter_task_spans(records)),
        default=0.0,
      )
    cutoff = (now - window_sec) if window_sec is not None else None

    types: Dict[str, dict] = {}
    trace_to_type: Dict[str, str] = {}
    per_worker_durs: Dict[str, List[tuple]] = defaultdict(list)
    overhead = {"count": 0, "sum": 0.0, "durs": []}
    range_sizes: List[int] = []

    def type_stats(name: str) -> dict:
      st = types.get(name)
      if st is None:
        st = types[name] = {
          "count": 0, "failures": 0, "sum": 0.0, "durs": [],
          "bytes": 0.0, "bytes_spans": 0, "max_attempt": 1,
        }
      return st

    for rec in fleet.iter_task_spans(records):
      ts, dur = rec.get("ts"), rec.get("dur")
      if ts is None or dur is None:
        continue
      if cutoff is not None and float(ts) + float(dur) < cutoff:
        continue
      name = rec.get("task", "?")
      st = type_stats(name)
      st["count"] += 1
      tid = rec.get("trace")
      if tid:
        trace_to_type[tid] = name
      attempt = rec.get("attempt")
      if attempt:
        st["max_attempt"] = max(st["max_attempt"], int(attempt))
      if rec.get("error"):
        st["failures"] += 1
        continue
      d = float(dur)
      st["sum"] += d
      if len(st["durs"]) < sample_cap:
        st["durs"].append(d)
      per_worker_durs[rec.get("worker", "local")].append((name, d))

    # second pass: byte movement + round overhead (non-task spans live
    # only in raw segments and rollup stage aggregates; bytes need the
    # per-span attrs, so they mine best before rollup GC)
    for rec in records:
      if rec.get("kind", "span") != "span":
        continue
      name = rec.get("name", "")
      if name == "lease.acquire":
        dur = rec.get("dur")
        if dur is None:
          continue
        overhead["count"] += 1
        overhead["sum"] += float(dur)
        if len(overhead["durs"]) < sample_cap:
          overhead["durs"].append(float(dur))
        sizes = rec.get("range_sizes")
        if isinstance(sizes, (list, tuple)):
          for s in sizes:
            if len(range_sizes) >= sample_cap:
              break
            range_sizes.append(int(s))
        continue
      if name in _BYTE_SPAN_NAMES:
        nbytes = rec.get("bytes")
        ttype = trace_to_type.get(rec.get("trace"))
        if nbytes and ttype:
          st = types[ttype]
          st["bytes"] += float(nbytes)
          st["bytes_spans"] += 1

    task_types = {}
    for name, st in types.items():
      st["durs"].sort()
      completed = len(st["durs"])
      task_types[name] = {
        "count": st["count"],
        "failures": st["failures"],
        "sum": round(st["sum"], 6),
        "durs": [round(d, 6) for d in st["durs"]],
        "bytes_per_task": (
          round(st["bytes"] / completed, 1) if completed and st["bytes"]
          else None
        ),
        "max_attempt": st["max_attempt"],
      }
    overhead["sum"] = round(overhead["sum"], 6)
    overhead["durs"] = sorted(round(d, 6) for d in overhead["durs"])

    # worker speed compares SAME-TYPE durations only: on a heterogeneous
    # mix, a worker that happened to draw the quick task types is not a
    # faster machine (one downsample-heavy worker once mined as "84×
    # fleet speed" and poisoned every forecast built on the model). Each
    # worker's per-type median is normalized by the fleet median for
    # that type; its speed is the sample-count-weighted mean of ratios.
    fleet_type_median = {
      name: _percentile(t["durs"], 0.50)
      for name, t in task_types.items() if t["durs"]
    }
    speeds = []
    for samples in per_worker_durs.values():
      by_type: Dict[str, List[float]] = defaultdict(list)
      for name, d in samples:
        by_type[name].append(d)
      num = den = 0.0
      for name, durs in by_type.items():
        fm = fleet_type_median.get(name, 0.0)
        if fm <= 0 or len(durs) < 2:
          continue
        num += (_percentile(sorted(durs), 0.50) / fm) * len(durs)
        den += len(durs)
      if den:
        speeds.append(round(num / den, 4))

    return cls(
      task_types=task_types,
      round_overhead=overhead,
      worker_speeds=speeds,
      range_sizes=sorted(range_sizes),
      meta={
        "version": MODEL_VERSION,
        "tasks_seen": sum(t["count"] for t in task_types.values()),
        "workers_seen": len(per_worker_durs),
        "window_sec": window_sec,
      },
    )

  # -- queries --------------------------------------------------------------

  def total_tasks(self) -> int:
    return sum(t["count"] for t in self.task_types.values())

  def task_mix(self) -> Dict[str, int]:
    """Completed-delivery count per type — the campaign shape a default
    simulation replays (retries excluded: the simulator re-rolls its own
    failures from :meth:`fail_prob`)."""
    return {
      name: max(len(t["durs"]), 1) for name, t in self.task_types.items()
    }

  def clip_outliers(self, factor: float = 4.0) -> int:
    """Drop per-type duration samples beyond ``factor`` × the type
    median. A journal mined from a chaos run carries fault-inflated
    spans — a SIGSTOPped worker's interrupted task records the whole
    freeze inside its ``dur`` — and a forecast that injects the same
    fault through a ChaosSpec would double-count it. Returns the number
    of samples dropped; ``sum`` is re-derived from the survivors."""
    dropped = 0
    for t in self.task_types.values():
      durs = t.get("durs") or []
      if len(durs) < 4:
        continue
      median = durs[len(durs) // 2]   # durs are mined sorted
      if median <= 0:
        continue
      kept = [d for d in durs if d <= factor * median]
      if len(kept) == len(durs):
        continue
      dropped += len(durs) - len(kept)
      t["durs"] = kept
      t["sum"] = round(sum(kept), 6)
    return dropped

  def fail_prob(self, task_type: str) -> float:
    t = self.task_types.get(task_type)
    if not t or not t["count"]:
      return 0.0
    return t["failures"] / t["count"]

  def sample_duration(self, task_type: str, rng) -> float:
    """One bootstrap draw from the type's empirical distribution.
    Deterministic given a seeded ``random.Random`` — the simulator's
    bit-identical-rerun contract rides on this."""
    t = self.task_types.get(task_type)
    durs = t["durs"] if t else ()
    if not durs:
      return 1.0  # unmodeled type: a neutral unit task
    return durs[rng.randrange(len(durs))]

  def sample_round_overhead(self, rng) -> float:
    durs = self.round_overhead.get("durs") or ()
    if not durs:
      return 0.0
    return durs[rng.randrange(len(durs))]

  def sample_worker_speed(self, rng) -> float:
    """One draw from the mined per-worker speed spread (1.0 = fleet
    median; >1 = slower). Falls back to 1.0 for unmined fleets."""
    if not self.worker_speeds:
      return 1.0
    return self.worker_speeds[rng.randrange(len(self.worker_speeds))]

  def summary(self) -> dict:
    """Human-facing digest (`fleet simulate` header, sim-report.json)."""
    per_type = {}
    for name, t in sorted(self.task_types.items()):
      durs = t["durs"]
      per_type[name] = {
        "count": t["count"],
        "fail_prob": round(self.fail_prob(name), 4),
        "p50_ms": round(_percentile(durs, 0.50) * 1e3, 2),
        "p95_ms": round(_percentile(durs, 0.95) * 1e3, 2),
        "p99_ms": round(_percentile(durs, 0.99) * 1e3, 2),
        "mean_ms": (
          round(t["sum"] / len(durs) * 1e3, 2) if durs else None
        ),
        "bytes_per_task": t.get("bytes_per_task"),
      }
    od = self.round_overhead.get("durs") or []
    return {
      "tasks_seen": self.total_tasks(),
      "task_types": per_type,
      "round_overhead_p50_ms": round(_percentile(od, 0.50) * 1e3, 2),
      "worker_speed_spread": self.worker_speeds,
    }

  # -- serialization --------------------------------------------------------

  def to_dict(self) -> dict:
    return {
      "version": MODEL_VERSION,
      "task_types": self.task_types,
      "round_overhead": self.round_overhead,
      "worker_speeds": self.worker_speeds,
      "range_sizes": self.range_sizes,
      "meta": self.meta,
    }

  @classmethod
  def from_dict(cls, d: dict) -> "WorkloadModel":
    ver = d.get("version", 0)
    if ver > MODEL_VERSION:
      raise ValueError(
        f"workload model version {ver} is newer than this reader "
        f"({MODEL_VERSION}); upgrade igneous_tpu"
      )
    return cls(
      task_types=d.get("task_types") or {},
      round_overhead=d.get("round_overhead"),
      worker_speeds=d.get("worker_speeds"),
      # pre-ISSUE-15 models have no range_sizes; default to none mined
      range_sizes=d.get("range_sizes"),
      meta=d.get("meta"),
    )

  def save(self, cloudpath: str, key: str = "workload_model.json") -> str:
    from ..storage import CloudFiles

    CloudFiles(cloudpath).put(
      key, json.dumps(self.to_dict()).encode("utf8"), compress=None,
    )
    return key

  @classmethod
  def load(cls, cloudpath: str,
           key: str = "workload_model.json") -> "WorkloadModel":
    from ..storage import CloudFiles

    data = CloudFiles(cloudpath).get(key)
    if data is None:
      raise FileNotFoundError(f"{cloudpath}/{key}")
    return cls.from_dict(json.loads(data.decode("utf8")))


def mine_journal(journal_path: str, **kw) -> WorkloadModel:
  """Mine a journal path directly (rollups + uncovered raw segments —
  the `igneous fleet simulate --from-journal` entry point)."""
  from . import fleet

  return WorkloadModel.mine(fleet.load_effective(journal_path), **kw)
