"""Deterministic discrete-event fleet simulator over a mined workload.

Takes a :class:`~.replay.WorkloadModel` (empirical per-type duration
samples, failure rates, round overhead, worker-speed spread mined from a
real journal) and replays a campaign through N **virtual** workers on a
virtual clock, modeling the semantics that actually decide campaign
shape:

* lease / redeliver / nack / DLQ-after-max-deliveries, with lease-expiry
  recycling and zombie fencing (a late completion on an expired lease is
  discarded and counted, exactly like the real queue);
* pre-lease rounds (``batch_size`` members per round, ``lease.acquire``
  overhead drawn from the mined distribution, straggler flag dropping a
  flagged worker to single-member rounds);
* chaos fault modes — graceful preemption (finish in-flight member,
  release the rest, clean ``drain`` exit), hard kill (silent death,
  leases recycle at expiry), stragglers (mined speed tail amplified),
  stall (lease a round then go dark: the recycle + fence path);
* an optional **virtual autoscale controller** ticking the same
  :class:`~.autoscale.PolicyLoop` the live controller runs — this is how
  a policy is tuned before it touches a real fleet.

Two contracts matter more than realism:

1. **Determinism** — one seeded ``random.Random``, a (time, seq) heap
   for total event order, counter-derived span/trace ids, and a fixed
   ``base_ts`` anchor (default 0.0, i.e. *no wall-clock anywhere*): the
   same seed + model + config produce bit-identical results AND
   bit-identical journal bytes.
2. **Journal-format output** — :meth:`FleetSimulator.write_journal`
   emits per-worker segments indistinguishable in shape from real ones,
   so ``igneous fleet status|check|watch|top``, the HealthEngine, the
   Perfetto exporter, and even :func:`~.replay.mine_journal` itself run
   unchanged on a simulated campaign.
"""

from __future__ import annotations

import heapq
import random
from collections import deque
from dataclasses import dataclass, field, fields
from typing import Dict, List, Optional

from .autoscale import AutoscalePolicy, PolicyLoop

from ..analysis import knobs


@dataclass
class ChaosSpec:
  """Fault injection: how many workers misbehave, and when (sim-seconds;
  a time of 0 auto-picks a fraction of the naive makespan estimate so
  the fault lands mid-campaign regardless of scale)."""

  preempt: int = 0          # graceful SIGTERM-style drains
  preempt_at: float = 0.0
  kill: int = 0             # silent deaths — leases recycle at expiry
  kill_at: float = 0.0
  stragglers: int = 0       # speed multiplied by straggler_factor
  straggler_factor: float = 4.0
  stall: int = 0            # lease one round, then go dark
  stall_at: float = 0.0

  def any(self) -> bool:
    return bool(self.preempt or self.kill or self.stragglers or self.stall)


@dataclass
class SimConfig:
  workers: int = 4
  seed: int = 0
  tasks: Optional[int] = None      # total tasks; None = replay mined mix
  batch_size: int = 1
  lease_sec: float = 60.0
  max_deliveries: int = 5
  poll_sec: float = 2.0
  worker_start_sec: float = 5.0    # spawn -> first lease (autoscale adds)
  fail_scale: float = 1.0          # multiply mined failure probabilities
  base_ts: float = 0.0             # journal timestamp anchor (0 = virtual)
  replay_worker_speeds: bool = True
  autoscale: bool = False
  policy: Optional[AutoscalePolicy] = None
  autoscale_interval_sec: float = 15.0
  rate_window_sec: float = 60.0    # completion-rate window for the loop
  cost_per_worker_hour: float = 0.0
  chaos: ChaosSpec = field(default_factory=ChaosSpec)
  max_sim_sec: float = 30 * 24 * 3600.0
  segment_spans: int = 512         # spans per emitted journal segment
  range_lease: int = 0             # 1 = one shared lease per round (ISSUE 15)
  # campaign survival (ISSUE 17): duplicate-issue the leased members of
  # slow/stalled holders (first resolution wins, the loser fences) and
  # let idle workers carve the unstarted tails of long-held rounds
  speculate: int = 0
  speculate_interval_sec: float = 10.0
  steal: int = 0
  steal_min_held_sec: float = 5.0
  # replay an OBSERVED fleet trajectory: one worker spawned per entry,
  # at that sim-second offset (0 = campaign start). Overrides `workers`
  # for the initial population — replacements an external autoscaler
  # produced are just later entries, so a forecast can hold the fleet
  # history fixed and test only the execution/lease/survival model.
  worker_arrivals: Optional[List[float]] = None

  _ENV = {
    "workers": "IGNEOUS_SIM_WORKERS",
    "seed": "IGNEOUS_SIM_SEED",
    "batch_size": "IGNEOUS_SIM_BATCH",
    "lease_sec": "IGNEOUS_SIM_LEASE_SEC",
    "max_deliveries": "IGNEOUS_SIM_MAX_DELIVERIES",
    "poll_sec": "IGNEOUS_SIM_POLL_SEC",
    "worker_start_sec": "IGNEOUS_SIM_WORKER_START_SEC",
    "fail_scale": "IGNEOUS_SIM_FAIL_SCALE",
    "max_sim_sec": "IGNEOUS_SIM_MAX_SEC",
    "range_lease": "IGNEOUS_SIM_RANGE_LEASE",
    "speculate": "IGNEOUS_SIM_SPECULATE",
    "steal": "IGNEOUS_SIM_STEAL",
  }
  _INT_FIELDS = ("workers", "seed", "tasks", "batch_size",
                 "max_deliveries", "segment_spans", "range_lease",
                 "speculate", "steal")

  @classmethod
  def from_env(cls, **overrides) -> "SimConfig":
    kw = {}
    for f in fields(cls):
      if f.name.startswith("_"):
        continue
      val = overrides.get(f.name)
      if val is None and f.name in cls._ENV:
        val = knobs.opt_float(cls._ENV[f.name])
      if val is not None:
        kw[f.name] = val
    cfg = cls(**kw)
    for name in cls._INT_FIELDS:
      val = getattr(cfg, name)
      if val is not None:
        setattr(cfg, name, int(val))
    return cfg


class _SimWorker:
  __slots__ = (
    "wid", "speed", "mode", "alive", "draining", "exited", "exit_event",
    "start_t", "end_t", "records", "counters", "round_state", "rounds",
    "busy_sec", "completed", "straggler_flagged", "stalled",
  )

  def __init__(self, wid: str, speed: float):
    self.wid = wid
    self.speed = speed
    self.mode = "normal"       # normal | straggler | stall
    self.alive = False
    self.draining = False
    self.exited = False
    self.exit_event = None     # "exit" | "drain" | None (killed/stalled)
    self.start_t = None
    self.end_t = None
    self.records: List[dict] = []
    self.counters: Dict[str, int] = {}
    self.round_state = None
    self.rounds = 0
    self.busy_sec = 0.0
    self.completed = 0
    self.straggler_flagged = False
    self.stalled = False

  def incr(self, key: str, n: int = 1) -> None:
    self.counters[key] = self.counters.get(key, 0) + n


class FleetSimulator:
  """One simulation run. Construct, :meth:`run`, then optionally
  :meth:`write_journal`. Instances are single-use."""

  DRIVER_ID = "sim-driver"

  def __init__(self, model, config: Optional[SimConfig] = None):
    self.model = model
    self.cfg = config or SimConfig()
    self.rng = random.Random(self.cfg.seed)
    self._heap: list = []
    self._evseq = 0
    self._id_counter = 0
    self._lease_seq = 0
    self._wseq = 0
    self.t = 0.0
    self.done = False
    self.timed_out = False
    self.makespan: Optional[float] = None
    self.tasks: List[dict] = []
    self.pending: deque = deque()
    self.workers: Dict[str, _SimWorker] = {}
    self.driver = _SimWorker(self.DRIVER_ID, 1.0)
    self.completion_log: List[float] = []
    self.scale_events: List[dict] = []
    self.peak_workers = 0
    self.terminal = 0          # done + dlq
    self.dlq = 0
    self.failed_deliveries = 0
    self.lease_recycles = 0
    self.zombie_fenced = 0
    self.released = 0
    self.range_rounds = 0
    self.spec_issued = 0       # campaign survival (ISSUE 17)
    self.spec_won = 0
    self.spec_fenced = 0
    self.spec_dup = 0
    self.steals = 0
    self.steal_tasks = 0
    self.policy_loop = PolicyLoop(
      self.cfg.policy or AutoscalePolicy()
    ) if self.cfg.autoscale else None
    self._ran = False

  # -- plumbing -------------------------------------------------------------

  def _push(self, t: float, fn) -> None:
    self._evseq += 1
    heapq.heappush(self._heap, (t, self._evseq, fn))

  def _sid(self) -> str:
    self._id_counter += 1
    return f"{self._id_counter:016x}"

  def _trace_id(self) -> str:
    self._id_counter += 1
    return f"sim{self.cfg.seed & 0xFFFF:04x}{self._id_counter:012x}"

  def _span(self, w: _SimWorker, name: str, ts: float, dur: float,
            trace: Optional[str] = None, parent: Optional[str] = None,
            span: Optional[str] = None, **attrs) -> dict:
    rec = {
      "kind": "span",
      "trace": trace or self._trace_id(),
      "span": span or self._sid(),
      "parent": parent,
      "name": name,
      "ts": round(ts, 6),
      "dur": round(dur, 6),
    }
    rec.update(attrs)
    w.records.append(rec)
    return rec

  # -- setup ----------------------------------------------------------------

  def _build_tasks(self) -> None:
    mix = self.model.task_mix()
    if not mix:
      mix = {"Task": max(self.cfg.tasks or 1, 1)}
    if self.cfg.tasks:
      total = sum(mix.values())
      scaled, rema = {}, []
      for name in sorted(mix):
        exact = mix[name] * self.cfg.tasks / total
        scaled[name] = int(exact)
        rema.append((-(exact - int(exact)), name))
      short = self.cfg.tasks - sum(scaled.values())
      for _, name in sorted(rema)[:short]:
        scaled[name] += 1
      mix = {k: v for k, v in scaled.items() if v > 0}
    names = [name for name in sorted(mix) for _ in range(mix[name])]
    self.rng.shuffle(names)   # deterministic interleave of the type mix
    for i, name in enumerate(names):
      self.tasks.append({
        "i": i, "type": name, "state": "pending", "deliveries": 0,
        "enqueue_t": 0.0, "lease_token": 0, "lease_worker": None,
        "done_t": None,
        # speculation (ISSUE 17): a leased task can carry a second live
        # lease — the twin. spec: None -> "wait" (twin queued) ->
        # "open" (twin leased) -> "resolved" (first terminal ack won)
        "twin_token": 0, "twin_worker": None, "spec": None,
      })
      self.pending.append(i)

  def _naive_makespan(self) -> float:
    """Serial work / worker count: the chaos auto-time anchor."""
    total = 0.0
    for name, t in self.model.task_types.items():
      durs = t.get("durs") or ()
      mean = (sum(durs) / len(durs)) if durs else 1.0
      count = sum(1 for task in self.tasks if task["type"] == name)
      total += mean * count
    unmodeled = sum(
      1 for task in self.tasks if task["type"] not in self.model.task_types
    )
    total += float(unmodeled)
    return max(total / max(self.cfg.workers, 1), 1.0)

  def _add_worker(self, t: float, delay: float = 0.0) -> _SimWorker:
    wid = f"sim-w{self._wseq:03d}"
    self._wseq += 1
    speed = (
      self.model.sample_worker_speed(self.rng)
      if self.cfg.replay_worker_speeds else 1.0
    )
    w = _SimWorker(wid, max(speed, 0.05))
    self.workers[wid] = w
    self._push(t + delay, lambda: self._worker_start(w))
    return w

  def _pool(self) -> List[_SimWorker]:
    """The autoscaler's view of "current": everything spawned and not
    yet exited or draining (a scheduled-but-unstarted worker counts — it
    was paid for)."""
    return [
      w for w in self.workers.values()
      if not w.exited and not w.draining and not w.stalled
    ]

  def _assign_chaos(self) -> None:
    chaos = self.cfg.chaos
    if not chaos.any():
      return
    est = self._naive_makespan()
    order = [self.workers[k] for k in sorted(self.workers)]
    cursor = 0
    for _ in range(min(chaos.stragglers, len(order))):
      w = order[cursor % len(order)]
      w.mode = "straggler"
      w.speed *= max(chaos.straggler_factor, 1.0)
      cursor += 1
    for _ in range(min(chaos.stall, len(order) - 1)):
      w = order[cursor % len(order)]
      if w.mode == "normal":
        w.mode = "stall"
      cursor += 1
    kill_at = chaos.kill_at or est * 0.4
    for _ in range(min(chaos.kill, max(len(order) - 1, 0))):
      w = order[cursor % len(order)]
      cursor += 1
      self._push(kill_at, lambda w=w: self._kill(w))
    preempt_at = chaos.preempt_at or est * 0.25
    for _ in range(min(chaos.preempt, max(len(order) - 1, 0))):
      w = order[cursor % len(order)]
      cursor += 1
      self._push(preempt_at, lambda w=w: self._preempt(w))

  # -- worker lifecycle -----------------------------------------------------

  def _worker_start(self, w: _SimWorker) -> None:
    if w.exited:
      return
    w.alive = True
    w.start_t = self.t
    self.peak_workers = max(self.peak_workers, len(self._pool()))
    self._poll(w)

  def _clean_exit(self, w: _SimWorker) -> None:
    w.alive = False
    w.exited = True
    w.exit_event = "exit"
    w.end_t = self.t

  def _drain_exit(self, w: _SimWorker, released: List[int]) -> None:
    for i in released:
      task = self.tasks[i]
      if task["state"] != "leased":
        continue
      if task["lease_worker"] == w.wid:
        task["lease_token"] = 0
        task["lease_worker"] = None
      elif task["twin_worker"] == w.wid:
        task["twin_token"] = 0
        task["twin_worker"] = None
      else:
        continue
      # requeue only when no speculative twin survives us — a live
      # twin keeps the index; requeueing would fence its completion
      if not (task["lease_token"] or task["twin_token"]):
        task["state"] = "pending"
        self.pending.append(i)
      w.incr("drain.released")
      self.released += 1
    rs = w.round_state
    if rs is not None:
      self._span(
        w, "lease.round", rs["t0"], self.t - rs["t0"],
        members=len(rs["members"]), executed=rs["executed"],
        failed=rs["failed"], drained=len(released),
      )
      w.round_state = None
    w.alive = False
    w.exited = True
    w.exit_event = "drain"
    w.end_t = self.t

  def _preempt(self, w: _SimWorker) -> None:
    if w.exited or not w.alive:
      return
    w.draining = True
    self._span(w, "sim.preempt", self.t, 0.0)
    if w.round_state is None:
      # idle: drain immediately rather than waiting for the next poll
      self._drain_exit(w, [])

  def _kill(self, w: _SimWorker) -> None:
    if w.exited:
      return
    w.alive = False
    w.exited = True
    w.exit_event = None   # silent death: no clean-exit record
    w.end_t = self.t
    # leased members recycle at their already-scheduled expiry events

  def _poll(self, w: _SimWorker) -> None:
    if not w.alive or w.exited:
      return
    if w.draining:
      return self._drain_exit(w, [])
    members: List[int] = []
    twins: List[int] = []
    cap = 1 if w.straggler_flagged else max(self.cfg.batch_size, 1)
    use_range = bool(self.cfg.range_lease)
    while self.pending and len(members) < cap:
      i = self.pending.popleft()
      task = self.tasks[i]
      if task["state"] == "leased":
        # speculative duplicate-issue (ISSUE 17): the original holder
        # keeps its lease — this worker runs a twin copy with its own
        # token; first resolution wins, the loser's ack fences
        if task["spec"] != "wait" or task["lease_worker"] == w.wid:
          continue   # resolved / recycled / own lease: stale entry
        task["spec"] = "open"
        task["deliveries"] += 1
        task["twin_worker"] = w.wid
        if not use_range:
          self._lease_seq += 1
          task["twin_token"] = self._lease_seq
          tok = self._lease_seq
          self._push(
            self.t + self.cfg.lease_sec,
            lambda i=i, tok=tok: self._lease_expire(i, tok),
          )
        else:
          twins.append(i)
        members.append(i)
        continue
      if task["state"] != "pending":
        continue   # reached terminal state while a stale entry sat queued
      task["state"] = "leased"
      task["deliveries"] += 1
      task["lease_worker"] = w.wid
      if not use_range:
        self._lease_seq += 1
        task["lease_token"] = self._lease_seq
        tok = self._lease_seq
        self._push(
          self.t + self.cfg.lease_sec,
          lambda i=i, tok=tok: self._lease_expire(i, tok),
        )
      members.append(i)
    if use_range and members:
      # range lease (ISSUE 15): the round holds ONE shared token and ONE
      # expiry event, mirroring an fq:// segment lease — completed /
      # nacked members change state individually (sub-task accounting),
      # so the shared expiry recycles only still-leased survivors
      self._lease_seq += 1
      tok = self._lease_seq
      twin_set = set(twins)
      for i in members:
        if i in twin_set:
          self.tasks[i]["twin_token"] = tok
        else:
          self.tasks[i]["lease_token"] = tok
      self._push(
        self.t + self.cfg.lease_sec,
        lambda m=tuple(members), tok=tok: self._range_expire(m, tok),
      )
      self.range_rounds += 1
      w.incr("sim.range_rounds")
    if not members:
      if self.done:
        return self._clean_exit(w)
      if self.cfg.steal and self._steal(w):
        # a claim was serviced: the carved tail is back in pending —
        # re-poll now instead of sleeping through the backoff
        self._push(self.t, lambda: self._poll(w))
        return
      self._push(self.t + self.cfg.poll_sec, lambda: self._poll(w))
      return
    w.rounds += 1
    overhead = self.model.sample_round_overhead(self.rng)
    w.round_state = {
      "members": members, "i": 0, "t0": self.t,
      "executed": 0, "failed": 0,
    }
    if overhead > 0:
      attrs = {"members": len(members)}
      if use_range:
        attrs["range_sizes"] = [len(members)]
      self._span(w, "lease.acquire", self.t, overhead, **attrs)
    if w.mode == "stall" and not w.stalled:
      # the zombie scenario: a round is leased, then the worker goes
      # dark holding it — expiry recycles the members, and any fence
      # accounting lands when (never, here) it wakes
      w.stalled = True
      w.incr("sim.stalled_rounds")
      self._span(w, "sim.stall", self.t, 0.0, members=len(members))
      return
    self._push(self.t + overhead, lambda: self._exec_next(w))

  def _exec_next(self, w: _SimWorker) -> None:
    if not w.alive or w.exited:
      return
    rs = w.round_state
    if rs is None:
      return
    if w.draining:
      return self._drain_exit(w, rs["members"][rs["i"]:])
    if rs["i"] >= len(rs["members"]):
      self._span(
        w, "lease.round", rs["t0"], self.t - rs["t0"],
        members=len(rs["members"]), executed=rs["executed"],
        failed=rs["failed"],
      )
      w.round_state = None
      # mined speed tail >2x fleet median mirrors the lease batcher's
      # straggler flag: subsequent rounds lease a single member
      if w.speed > 2.0 and not w.straggler_flagged:
        w.straggler_flagged = True
        w.incr("sim.straggler_flagged")
      self._push(self.t, lambda: self._poll(w))
      return
    i = rs["members"][rs["i"]]
    task = self.tasks[i]
    if task["state"] == "leased" and task["lease_worker"] == w.wid:
      tok = task["lease_token"]
    elif task["state"] == "leased" and task["twin_worker"] == w.wid:
      tok = task["twin_token"]   # we hold the speculative twin side
    else:
      # lease recycled or stolen from under us before the member started
      rs["i"] += 1
      self._push(self.t, lambda: self._exec_next(w))
      return
    dur = self.model.sample_duration(task["type"], self.rng) * w.speed
    dur = max(dur, 1e-6)
    fail_p = min(
      self.model.fail_prob(task["type"]) * self.cfg.fail_scale, 0.95,
    )
    fail = self.rng.random() < fail_p
    start_t = self.t
    self._push(
      self.t + dur,
      lambda: self._member_done(w, i, tok, start_t, dur, fail),
    )

  def _member_done(self, w: _SimWorker, i: int, tok: int,
                   start_t: float, dur: float, fail: bool) -> None:
    if w.exited or not w.alive:
      return   # killed mid-member: work lost, lease recycles at expiry
    rs = w.round_state
    task = self.tasks[i]
    w.busy_sec += dur
    side = (
      "twin" if (task["twin_token"] and tok == task["twin_token"])
      else "orig"
    )
    live = task["state"] == "leased" and (
      tok == task["lease_token"] or
      (task["twin_token"] and tok == task["twin_token"])
    )
    if not live:
      # lease expired / recycled mid-execution, or the speculative twin
      # already resolved this index: the completion is fenced exactly
      # like the real queue's zombie + done-marker paths
      w.incr("zombie.delete")
      self.zombie_fenced += 1
      if task["spec"] == "resolved":
        w.incr("speculation.duplicate_ack")
        self.spec_dup += 1
      self._span(
        w, "task", start_t, dur, task=task["type"],
        attempt=task["deliveries"], fenced=True,
      )
    else:
      attempt = task["deliveries"]
      tid = self._trace_id()
      task_sid = self._sid()
      wait = max(start_t - task["enqueue_t"], 0.0)
      self._span(
        w, "queue.wait", task["enqueue_t"], wait,
        trace=tid, parent=task_sid, attempt=attempt,
      )
      if fail:
        w.incr("tasks.failed")
        self.failed_deliveries += 1
        self._span(
          w, "task", start_t, dur, trace=tid, span=task_sid,
          task=task["type"], attempt=attempt, error="SimFault",
        )
        # retire the acking side; a surviving twin/orig keeps running
        # and owns the remaining retry budget
        if side == "twin":
          task["twin_token"] = 0
          task["twin_worker"] = None
        else:
          task["lease_token"] = 0
          task["lease_worker"] = None
        if task["lease_token"] or task["twin_token"]:
          pass   # the other side is still live: no requeue, no dlq
        elif (
          self.cfg.max_deliveries
          and attempt >= self.cfg.max_deliveries
        ):
          task["state"] = "dlq"
          w.incr("dlq.promoted")
          self.dlq += 1
          if task["spec"] in ("wait", "open"):
            # the pair resolved by exhaustion, not by a win: account it
            # as fenced so won + fenced == issued still reconciles
            task["spec"] = "resolved"
            w.incr("speculation.fenced")
            self.spec_fenced += 1
          self._terminal()
        else:
          w.incr("retries.nack")
          task["state"] = "pending"
          self.pending.append(i)
      else:
        self._span(
          w, "task", start_t, dur, trace=tid, span=task_sid,
          task=task["type"], attempt=attempt,
        )
        task["state"] = "done"
        task["done_t"] = self.t
        if task["spec"] in ("wait", "open"):
          # first terminal ack wins the pair — the done-marker seam
          task["spec"] = "resolved"
          if side == "twin":
            w.incr("speculation.won")
            self.spec_won += 1
          else:
            w.incr("speculation.fenced")
            self.spec_fenced += 1
        w.completed += 1
        self.completion_log.append(self.t)
        self._terminal()
    if rs is not None:
      rs["i"] += 1
      if fail:
        rs["failed"] += 1
      else:
        rs["executed"] += 1
      self._push(self.t, lambda: self._exec_next(w))

  def _expire_side(self, i: int, tok: int) -> bool:
    """Retire whichever side (original lease or speculative twin) of
    task ``i`` holds ``tok``. The task recycles back to pending only
    when no other live side remains — a surviving twin keeps running
    and owns the index. Returns True when the task was recycled."""
    task = self.tasks[i]
    if task["state"] != "leased":
      return False
    if task["lease_token"] == tok:
      task["lease_token"] = 0
      task["lease_worker"] = None
    elif task["twin_token"] and task["twin_token"] == tok:
      task["twin_token"] = 0
      task["twin_worker"] = None
    else:
      return False
    if task["lease_token"] or task["twin_token"]:
      return False
    task["state"] = "pending"
    self.pending.append(i)
    return True

  def _lease_expire(self, i: int, tok: int) -> None:
    if self._expire_side(i, tok):
      self.driver.incr("retries.lease_recycle")
      self.lease_recycles += 1

  def _range_expire(self, members, tok: int) -> None:
    """Shared-token expiry for a range-leased round: recycle every member
    still holding the round's token. Members already done / dlq'd / nacked
    back to pending (sub-task accounting) are untouched."""
    recycled = sum(1 for i in members if self._expire_side(i, tok))
    if recycled:
      self.driver.incr("retries.lease_recycle", recycled)
      self.lease_recycles += recycled

  # -- campaign survival (ISSUE 17) ------------------------------------------

  def _speculate_tick(self) -> None:
    """The campaign driver's speculation sweep: duplicate-issue every
    leased task whose holder is stalled, straggler-slow, or dead with
    an unexpired lease (the live runner's silent-holder trigger). First
    terminal ack wins the pair; the loser fences — exactly the live
    ``speculate_flagged`` + done-marker protocol."""
    if self.done:
      return
    issued = 0
    for task in self.tasks:
      if task["state"] != "leased" or task["spec"] is not None:
        continue
      holder = self.workers.get(task["lease_worker"])
      if holder is None:
        continue
      # a dead holder's unexpired lease is journal-silent: the live
      # driver's silent-holder trigger twins it instead of waiting out
      # lease expiry, so the sim must too (exited-with-leases = killed;
      # drains release on the way out and never reach here)
      if not holder.exited and not (
        holder.stalled or holder.mode == "straggler"
        or holder.straggler_flagged
      ):
        continue
      task["spec"] = "wait"
      self.pending.append(task["i"])
      issued += 1
    if issued:
      self.spec_issued += issued
      self.driver.incr("speculation.issued", issued)
      self._span(self.driver, "sim.speculate", self.t, 0.0, twinned=issued)
    self._push(self.t + self.cfg.speculate_interval_sec,
               self._speculate_tick)

  def _steal(self, w: _SimWorker) -> bool:
    """Idle worker carves the unstarted tail off the longest round held
    past ``steal_min_held_sec`` — the claim-file handshake collapsed to
    its effect (the holder's heartbeat releases; here it is immediate
    and deterministic). Returns True when tasks were released."""
    best = None
    for wid in sorted(self.workers):
      v = self.workers[wid]
      rs = v.round_state
      if v is w or rs is None:
        continue
      if self.t - rs["t0"] < self.cfg.steal_min_held_sec:
        continue
      tail = [
        i for i in rs["members"][rs["i"] + 1:]
        if self.tasks[i]["state"] == "leased"
        and self.tasks[i]["lease_worker"] == v.wid
        and self.tasks[i]["spec"] is None
      ]
      if len(tail) >= 2 and (best is None or len(tail) > len(best[1])):
        best = (v, tail)
    if best is None:
      return False
    v, tail = best
    grant = tail[-(len(tail) // 2):]   # holder keeps at least half + current
    for i in grant:
      task = self.tasks[i]
      task["state"] = "pending"
      task["lease_token"] = 0
      task["lease_worker"] = None
      self.pending.append(i)
    grant_set = set(grant)
    rs = v.round_state
    rs["members"] = [i for i in rs["members"] if i not in grant_set]
    w.incr("steal.claims")
    v.incr("steal.granted")
    v.incr("steal.tasks", len(grant))
    self.steals += 1
    self.steal_tasks += len(grant)
    self._span(
      self.driver, "sim.steal", self.t, 0.0,
      thief=w.wid, victim=v.wid, tasks=len(grant),
    )
    return True

  def _terminal(self) -> None:
    self.terminal += 1
    if self.terminal >= len(self.tasks) and not self.done:
      self.done = True
      self.makespan = self.t

  # -- virtual autoscale controller -----------------------------------------

  def _autoscale_tick(self) -> None:
    if self.done:
      return
    window = max(self.cfg.rate_window_sec, 1e-9)
    floor = self.t - window
    while self.completion_log and self.completion_log[0] <= floor:
      self.completion_log.pop(0)
    rate = len(self.completion_log) / window
    backlog = len(self.pending)
    pool = self._pool()
    current = len(pool)
    pwr = rate / max(current, 1)
    decision = self.policy_loop.decide(backlog, pwr, current, self.t)
    target = decision["target"]
    if target > current:
      for _ in range(target - current):
        self._add_worker(self.t, delay=self.cfg.worker_start_sec)
      self.driver.incr("autoscale.scale_up")
      self.driver.incr("autoscale.workers_added", target - current)
    elif target < current:
      # drain the newest workers first, idle ones preferentially
      victims = sorted(
        pool, key=lambda w: (w.round_state is not None, w.wid),
        reverse=True,
      )[:current - target]
      for w in victims:
        self._preempt(w)
      self.driver.incr("autoscale.scale_down")
      self.driver.incr("autoscale.workers_removed", current - target)
    else:
      self.driver.incr("autoscale.steady")
    if target != current:
      self.scale_events.append({
        "t": round(self.t, 3), "current": current, "target": target,
        "reason": decision["reason"],
      })
      self._span(
        self.driver, "autoscale.action", self.t, 0.0,
        **{k: v for k, v in decision.items()},
      )
    self._push(self.t + self.cfg.autoscale_interval_sec,
               self._autoscale_tick)

  # -- run ------------------------------------------------------------------

  def run(self) -> dict:
    if self._ran:
      raise RuntimeError("FleetSimulator instances are single-use")
    self._ran = True
    cfg = self.cfg
    self._build_tasks()
    if cfg.worker_arrivals:
      # observed-trajectory replay: spawn order follows arrival order so
      # chaos assignment (sorted wids) lands on the campaign's earliest
      # workers — the ones a real storm actually hit
      for off in sorted(float(o) for o in cfg.worker_arrivals):
        self._add_worker(max(off, 0.0))
    else:
      initial = cfg.workers
      if cfg.autoscale:
        pol = self.policy_loop.policy
        initial = max(pol.min_workers, min(pol.max_workers, cfg.workers))
      for _ in range(max(initial, 0)):
        self._add_worker(0.0)
    self._assign_chaos()
    if cfg.autoscale:
      self._push(cfg.autoscale_interval_sec, self._autoscale_tick)
    if cfg.speculate:
      self._push(cfg.speculate_interval_sec, self._speculate_tick)
    while self._heap:
      t, _, fn = heapq.heappop(self._heap)
      if t > cfg.max_sim_sec:
        self.timed_out = True
        break
      self.t = t
      fn()
    if self.makespan is None:
      self.makespan = self.t
    # close out survivors (stalled / never-exited workers ran to the end)
    for w in self.workers.values():
      if w.end_t is None:
        w.end_t = self.makespan
    return self._results()

  def _results(self) -> dict:
    cfg = self.cfg
    completed = sum(1 for t in self.tasks if t["state"] == "done")
    worker_seconds = sum(
      max((w.end_t or 0.0) - w.start_t, 0.0)
      for w in self.workers.values() if w.start_t is not None
    )
    busy = sum(w.busy_sec for w in self.workers.values())
    per_type: Dict[str, dict] = {}
    for t in self.tasks:
      st = per_type.setdefault(
        t["type"], {"tasks": 0, "completed": 0, "dlq": 0},
      )
      st["tasks"] += 1
      if t["state"] == "done":
        st["completed"] += 1
      elif t["state"] == "dlq":
        st["dlq"] += 1
    makespan = self.makespan or 0.0
    cost = (
      round(worker_seconds / 3600.0 * cfg.cost_per_worker_hour, 4)
      if cfg.cost_per_worker_hour else None
    )
    return {
      "seed": cfg.seed,
      "workers": cfg.workers,
      "peak_workers": self.peak_workers,
      "tasks": len(self.tasks),
      "completed": completed,
      "completed_all": completed + self.dlq >= len(self.tasks) and (
        completed == len(self.tasks) - self.dlq
      ),
      "dlq": self.dlq,
      "failed_deliveries": self.failed_deliveries,
      "lease_recycles": self.lease_recycles,
      "zombie_fenced": self.zombie_fenced,
      "released": self.released,
      "rounds": sum(w.rounds for w in self.workers.values()),
      "range_rounds": self.range_rounds,
      "speculation": {
        "issued": self.spec_issued,
        "won": self.spec_won,
        "fenced": self.spec_fenced,
        "duplicate_acks": self.spec_dup,
      },
      "steals": {"claims": self.steals, "tasks": self.steal_tasks},
      "makespan_sec": round(makespan, 3),
      "tasks_per_sec": (
        round(completed / makespan, 4) if makespan > 0 else 0.0
      ),
      "worker_seconds": round(worker_seconds, 3),
      "busy_seconds": round(busy, 3),
      "utilization": (
        round(busy / worker_seconds, 4) if worker_seconds > 0 else 0.0
      ),
      "cost_usd": cost,
      "scale_events": self.scale_events,
      "autoscale": {
        "ups": self.driver.counters.get("autoscale.scale_up", 0),
        "downs": self.driver.counters.get("autoscale.scale_down", 0),
      },
      "timed_out": self.timed_out,
    }

  # -- journal emission ------------------------------------------------------

  def write_journal(self, cloudpath: str) -> int:
    """Emit the run as journal segments (one or more per worker plus a
    driver segment) under ``cloudpath``. Timestamps are ``base_ts +
    sim_t``; with the default anchor of 0.0 and a fixed seed the bytes
    are identical across reruns. Returns segments written."""
    if not self._ran:
      raise RuntimeError("run() before write_journal()")
    import json

    from ..storage import CloudFiles
    from . import journal as journal_mod

    base = self.cfg.base_ts
    cf = CloudFiles(cloudpath)
    nseg = 0

    # the driver carries the campaign-level span + queue-side counters
    self._span(
      self.driver, "sim.run", 0.0, self.makespan or 0.0,
      seed=self.cfg.seed, workers=self.cfg.workers,
      tasks=len(self.tasks), autoscale=bool(self.cfg.autoscale),
    )

    def counters_record(w: _SimWorker) -> dict:
      return {
        "kind": "counters",
        "worker": w.wid,
        "ts": round(base + (w.end_t or 0.0), 6),
        "event": w.exit_event or "interval",
        "counters": {k: w.counters[k] for k in sorted(w.counters)},
        "timers": {},
        "gauges": {},
      }

    order = sorted(self.workers) + [self.DRIVER_ID]
    for wid in order:
      w = self.driver if wid == self.DRIVER_ID else self.workers[wid]
      if w.start_t is None and w is not self.driver and not w.records:
        continue   # scheduled after completion; never ran
      spans = []
      for rec in w.records:
        rec = dict(rec)
        rec["worker"] = w.wid
        rec["ts"] = round(base + rec["ts"], 6)
        spans.append(rec)
      if w is self.driver:
        w.end_t = self.makespan
        w.exit_event = "exit"
      chunk = max(self.cfg.segment_spans, 1)
      seq = 0
      pieces = [
        spans[i:i + chunk] for i in range(0, len(spans), chunk)
      ] or [[]]
      for pi, piece in enumerate(pieces):
        lines = [json.dumps(r) for r in piece]
        if pi == len(pieces) - 1:
          lines.append(json.dumps(counters_record(w)))
        data = ("\n".join(lines) + "\n").encode("utf8")
        data = journal_mod.encode_segment(data)
        cf.put(f"{w.wid}-{seq:06d}.jsonl", data, compress=None)
        seq += 1
        nseg += 1
    return nseg


def simulate(model, config: Optional[SimConfig] = None,
             journal_path: Optional[str] = None) -> dict:
  """One-shot convenience: run, optionally emit the journal, return the
  results dict."""
  sim = FleetSimulator(model, config)
  results = sim.run()
  if journal_path:
    results["journal_segments"] = sim.write_journal(journal_path)
    results["journal_path"] = journal_path
  return results


def what_if(model, base: SimConfig, worker_counts: List[int]) -> List[dict]:
  """Same campaign, same seed, different fleet sizes — the forecast
  table `igneous fleet simulate` prints. Each entry is the results dict
  plus the varied worker count."""
  out = []
  for n in worker_counts:
    cfg = SimConfig(**{
      f.name: getattr(base, f.name) for f in fields(base)
      if not f.name.startswith("_")
    })
    cfg.workers = int(n)
    out.append(FleetSimulator(model, cfg).run())
  return out
