"""Process-local metrics: counters, float timers, gauges, histograms.

This is the former ``igneous_tpu.telemetry`` (that module is now a compat
shim over this package). Additions for the observability subsystem:

  * ``observe()`` feeds a log-scale histogram per timer (Prometheus
    histogram export) and records a trace span when a sampled trace
    context is active on the calling thread — the pipeline's existing
    ``observe()`` sites become span emitters for free.
  * ``reset_counters()`` is now counter-only; ``reset_all()`` clears
    timers/gauges/histograms too (the old conflated behavior).
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from collections import defaultdict
from typing import Dict, Iterator, Optional

from . import trace

from ..analysis import knobs

_local = threading.local()

# -- failure-containment counters (ISSUE 1) ----------------------------------
# process-wide monotonic counters for retry/fault/DLQ events: cheap enough
# to always collect, surfaced by `igneous queue status` and the chaos soak.

_COUNTERS: Dict[str, int] = defaultdict(int)
_COUNTERS_LOCK = threading.Lock()


def incr(name: str, n: int = 1) -> None:
  """Bump a named counter (e.g. "retries.storage_http", "dlq.promoted")."""
  with _COUNTERS_LOCK:
    _COUNTERS[name] += n


def counters_snapshot() -> Dict[str, int]:
  with _COUNTERS_LOCK:
    return dict(_COUNTERS)


def reset_counters() -> None:
  """Clear the int counters ONLY (timers/gauges/histograms survive)."""
  with _COUNTERS_LOCK:
    _COUNTERS.clear()


def reset_all() -> None:
  """Clear every metric family: counters, timers, gauges, histograms —
  what ``reset_counters()`` used to do implicitly."""
  with _COUNTERS_LOCK:
    _COUNTERS.clear()
    _TIMERS.clear()
    _TIMER_COUNTS.clear()
    _GAUGES.clear()
    _HISTOGRAMS.clear()


# -- staged-pipeline spans (ISSUE 3) -----------------------------------------
# float-valued accumulators alongside the int counters: per-stage stall
# time, bytes in flight, queue depth. Same lock — a pipeline flush reads
# both families as one consistent snapshot.

_TIMERS: Dict[str, float] = defaultdict(float)
_TIMER_COUNTS: Dict[str, int] = defaultdict(int)
_GAUGES: Dict[str, float] = defaultdict(float)  # high-water marks

# log-scale histogram per timer name (Prometheus export). Upper bounds in
# seconds; the final implicit bucket is +Inf.
HISTOGRAM_BUCKETS = (
  0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 60.0,
)
_HISTOGRAMS: Dict[str, list] = {}


def observe(name: str, seconds: float) -> None:
  """Accumulate a float span (e.g. "pipeline.download.stall_s")."""
  observe_quiet(name, seconds)
  # observe sites double as span emitters when the calling thread runs
  # inside a sampled trace (pipeline stages, buffer stalls)
  trace.record_span(name, seconds)


def observe_quiet(name: str, seconds: float) -> None:
  """``observe`` without the trace-span side channel — for callers that
  emit their own richer span for the same interval (the device plane
  records ``device.execute`` spans with kernel/device/byte attrs; a
  second bare span from observe() would double every interval in the
  Perfetto view)."""
  seconds = float(seconds)
  with _COUNTERS_LOCK:
    _TIMERS[name] += seconds
    _TIMER_COUNTS[name] += 1
    buckets = _HISTOGRAMS.get(name)
    if buckets is None:
      buckets = _HISTOGRAMS[name] = [0] * (len(HISTOGRAM_BUCKETS) + 1)
    for i, bound in enumerate(HISTOGRAM_BUCKETS):
      if seconds <= bound:
        buckets[i] += 1
        break
    else:
      buckets[-1] += 1


def gauge_max(name: str, value: float) -> None:
  """Record a high-water mark (e.g. "pipeline.buffer.bytes" in flight)."""
  with _COUNTERS_LOCK:
    if value > _GAUGES[name]:
      _GAUGES[name] = float(value)


def gauge_set(name: str, value: float) -> None:
  """Overwrite a gauge (health/autoscale signals: the CURRENT value is
  the point, unlike gauge_max's high-water marks)."""
  with _COUNTERS_LOCK:
    _GAUGES[name] = float(value)


def gauge_set_async_safe(name: str, value: float) -> None:
  """Signal-handler-safe gauge write: skips the metrics lock (a handler
  interrupting this thread while it holds the lock would deadlock). A
  dict setitem is atomic under the GIL — a concurrent snapshot may miss
  the newest value, but state can never corrupt."""
  _GAUGES[name] = float(value)


def timers_snapshot() -> Dict[str, dict]:
  with _COUNTERS_LOCK:
    out = {
      name: {"seconds": round(total, 4), "count": _TIMER_COUNTS[name]}
      for name, total in _TIMERS.items()
    }
    out.update({
      name: {"max": round(v, 1)} for name, v in _GAUGES.items()
    })
    return out


def gauges_snapshot() -> Dict[str, float]:
  with _COUNTERS_LOCK:
    return dict(_GAUGES)


def histograms_snapshot() -> Dict[str, dict]:
  """Per-timer bucket counts: {name: {"buckets": [...], "bounds": [...]}}
  where buckets[i] counts observations <= bounds[i] (last = +Inf)."""
  with _COUNTERS_LOCK:
    return {
      name: {"bounds": list(HISTOGRAM_BUCKETS), "buckets": list(b)}
      for name, b in _HISTOGRAMS.items()
    }


def histogram_quantile(name: str, q: float) -> Optional[float]:
  """Approximate quantile (seconds) of a timer from its log-scale
  histogram — the upper bound of the bucket holding the q-th
  observation, Prometheus ``histogram_quantile`` style. The serve tier's
  p50/p99 gauges and the bench read latency through this; None when the
  timer has no observations. The overflow bucket reports the top bound
  (the histogram cannot resolve beyond it)."""
  with _COUNTERS_LOCK:
    buckets = _HISTOGRAMS.get(name)
    if buckets is None:
      return None
    buckets = list(buckets)
  total = sum(buckets)
  if total == 0:
    return None
  rank = max(1, int(q * total + 0.5))
  cum = 0
  for i, count in enumerate(buckets):
    cum += count
    if cum >= rank:
      return HISTOGRAM_BUCKETS[min(i, len(HISTOGRAM_BUCKETS) - 1)]
  return HISTOGRAM_BUCKETS[-1]


def timer_totals() -> Dict[str, dict]:
  """Raw (sum, count) per timer, no gauges mixed in (Prometheus export)."""
  with _COUNTERS_LOCK:
    return {
      name: {"sum": total, "count": _TIMER_COUNTS[name]}
      for name, total in _TIMERS.items()
    }


def emit_counters(event: str = "counters", **extra) -> dict:
  """Flush the counters as one JSON line (stdout). Workers call this on
  graceful drain so retry/zombie/DLQ tallies survive the pod — the line
  is the worker's last will, greppable from `kubectl logs --previous`."""
  record = {"event": event, **extra, "counters": counters_snapshot()}
  timers = timers_snapshot()
  if timers:
    record["spans"] = timers
  print(json.dumps(record), flush=True)
  return record


def _stack():
  if not hasattr(_local, "stack"):
    _local.stack = []
  return _local.stack


class StageTimes:
  """Accumulates wall-clock per named stage (download/compute/upload/…)."""

  def __init__(self):
    self.totals: Dict[str, float] = defaultdict(float)
    self.counts: Dict[str, int] = defaultdict(int)

  def add(self, stage: str, seconds: float):
    self.totals[stage] += seconds
    self.counts[stage] += 1

  def summary(self) -> dict:
    return {
      stage: {"seconds": round(self.totals[stage], 4), "count": self.counts[stage]}
      for stage in sorted(self.totals)
    }

  def __str__(self):
    return json.dumps(self.summary())


@contextlib.contextmanager
def task_timing() -> Iterator[StageTimes]:
  """Collect stage timings for one task execution."""
  st = StageTimes()
  _stack().append(st)
  try:
    yield st
  finally:
    _stack().pop()


@contextlib.contextmanager
def stage(name: str):
  """Time a stage; attributes to every active task_timing() scope."""
  t0 = time.perf_counter()
  try:
    yield
  finally:
    dt = time.perf_counter() - t0
    for st in _stack():
      st.add(name, dt)


@contextlib.contextmanager
def device_trace(logdir: Optional[str] = None):
  """jax.profiler trace around a device-heavy region.

  Gated on ``IGNEOUS_PROFILE_DIR`` (legacy ``IGNEOUS_TPU_PROFILE_DIR``
  still honored) so it is INERT by default — workers without profiling
  infrastructure pay one env read. Logdirs are namespaced per worker
  process (hostname-pid): concurrent workers sharing one profile dir
  must not interleave their TensorBoard event files. ``stop_trace`` is
  exception-safe twice over: it runs from a ``finally`` so the region's
  exception still stops the profiler, and a stop failure (profiler
  already torn down, backend gone mid-drain) never masks — or adds to —
  the region's own outcome."""
  logdir = (
    logdir
    or knobs.get_str("IGNEOUS_PROFILE_DIR")
    or knobs.get_str("IGNEOUS_TPU_PROFILE_DIR")
  )
  if not logdir:
    yield
    return
  import socket

  import jax

  host = socket.gethostname().split(".")[0] or "worker"
  logdir = os.path.join(logdir, f"{host}-{os.getpid()}")
  try:
    jax.profiler.start_trace(logdir)
  except Exception:
    # a second start (nested regions, a concurrent triggered capture)
    # raises inside jax; profiling is diagnostics, not correctness
    incr("device.profile.start_failed")
    yield
    return
  try:
    yield
  finally:
    try:
      jax.profiler.stop_trace()
    except Exception:
      incr("device.profile.stop_failed")


def timed_poll_hooks(verbose: bool = True):
  """(before_fn, after_fn) for FileQueue.poll: logs per-task wall time and
  stage breakdown as one JSON line per completed task."""
  state = {}

  def _close():
    scope = state.pop("scope", None)
    if scope is not None:
      scope.__exit__(None, None, None)

  def before(task):
    # poll() calls after_fn only on success: if the previous task raised,
    # its scope is still open — close it here so the stack never grows
    _close()
    state["t0"] = time.perf_counter()
    scope = task_timing()
    state["st"] = scope.__enter__()
    state["scope"] = scope

  def after(task):
    st: StageTimes = state["st"]
    _close()
    record = {
      "task": type(task).__name__,
      "wall_s": round(time.perf_counter() - state["t0"], 4),
      "stages": st.summary(),
    }
    if verbose:
      print(json.dumps(record), flush=True)

  return before, after


def queue_eta(queue, sample_seconds: float = 10.0,
              journal_path: Optional[str] = None) -> dict:
  """Tasks/sec + ETA. When ``journal_path`` holds journal segments, the
  throughput derives from the fleet's task spans (no sampling sleep);
  otherwise two enqueued-count samples ``sample_seconds`` apart
  (reference `igneous queue status --eta`, cli.py:1998-2048)."""
  if journal_path is not None:
    from . import fleet

    derived = fleet.journal_throughput(journal_path)
    if derived is not None:
      rate = derived["tasks_per_sec"]
      enq = queue.enqueued
      return {
        "enqueued": enq,
        "tasks_per_sec": round(rate, 3),
        "eta_sec": round(enq / rate, 1) if rate > 0 else None,
        "source": "journal",
        "window_sec": derived["window_sec"],
        "tasks_observed": derived["tasks"],
      }
  first = queue.enqueued
  t0 = time.time()
  time.sleep(sample_seconds)
  second = queue.enqueued
  dt = time.time() - t0
  rate = max((first - second) / dt, 0.0)
  return {
    "enqueued": second,
    "tasks_per_sec": round(rate, 3),
    "eta_sec": round(second / rate, 1) if rate > 0 else None,
    "source": "sampled",
  }
