"""Perfetto / Chrome-trace export of journal span records.

Produces the Trace Event Format JSON that both ``chrome://tracing`` and
https://ui.perfetto.dev open directly: one complete ("ph": "X") event per
span, grouped into one Perfetto "process" row per worker. Used by
``igneous fleet trace <trace_id> -o trace.json`` for single-task deep
dives and by the CI soak to leave a browsable artifact behind.
"""

from __future__ import annotations

import json
from typing import Iterable, Optional

_META_KEYS = {"kind", "segment", "worker", "trace", "span", "parent",
              "name", "ts", "dur"}


def chrome_trace(records: Iterable[dict],
                 trace_id: Optional[str] = None) -> dict:
  """Span records (journal dicts or trace.drain_spans output) → Trace
  Event Format. ``trace_id`` filters to one trace; None exports all."""
  events = []
  pids = {}  # worker -> pid
  device_tids = {}  # (pid, device label) -> tid
  serve_tids = {}   # (pid, layer) -> tid
  t0 = None

  spans = [
    r for r in records
    if r.get("kind", "span") == "span" and "ts" in r and "dur" in r
    and (trace_id is None or r.get("trace") == trace_id)
  ]
  for rec in spans:
    if t0 is None or rec["ts"] < t0:
      t0 = rec["ts"]
  t0 = t0 or 0.0

  for rec in spans:
    worker = rec.get("worker", "local")
    pid = pids.setdefault(worker, len(pids) + 1)
    args = {k: v for k, v in rec.items() if k not in _META_KEYS}
    args["trace_id"] = rec.get("trace")
    args["span_id"] = rec.get("span")
    if rec.get("parent"):
      args["parent_span_id"] = rec["parent"]
    name = rec.get("name", "span")
    if name.startswith("device.") and rec.get("device"):
      # device telemetry (ISSUE 7): kernel/transfer spans render on one
      # dedicated track per physical device inside the worker row, so
      # compile/execute/h2d intervals read as a device timeline instead
      # of vanishing into whichever task trace triggered them. tids
      # 10000+ keep clear of the per-trace task rows below.
      tid = device_tids.setdefault(
        (pid, rec["device"]), 10_000 + len(device_tids)
      )
    elif name.startswith("serve.") and rec.get("layer"):
      # serving tier (ISSUE 9): request/fetch/decode spans render on one
      # track per served layer — a layer's request timeline reads
      # contiguously instead of scattering across per-trace rows (every
      # request is its own trace). tids 20000+ stay clear of both the
      # device tracks and the hashed task rows.
      tid = serve_tids.setdefault(
        (pid, rec["layer"]), 20_000 + len(serve_tids)
      )
    else:
      # one row per trace inside the worker keeps concurrent tasks from
      # visually stacking into one another
      tid = abs(hash(rec.get("trace", ""))) % 10_000
    events.append({
      "name": name,
      "cat": "igneous",
      "ph": "X",
      "ts": (rec["ts"] - t0) * 1e6,          # microseconds
      "dur": max(rec["dur"], 0.0) * 1e6,
      "pid": pid,
      "tid": tid,
      "args": args,
    })

  for worker, pid in pids.items():
    events.append({
      "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
      "args": {"name": f"worker {worker}"},
    })
  for (pid, dev), tid in device_tids.items():
    events.append({
      "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
      "args": {"name": f"device {dev}"},
    })
  for (pid, layer), tid in serve_tids.items():
    events.append({
      "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
      "args": {"name": f"serve {layer}"},
    })

  return {
    "traceEvents": events,
    "displayTimeUnit": "ms",
    "otherData": {"exporter": "igneous fleet", "epoch_s": t0},
  }


def dump(records: Iterable[dict], path: str,
         trace_id: Optional[str] = None) -> int:
  """Write the chrome trace JSON to ``path``; returns the event count."""
  doc = chrome_trace(records, trace_id=trace_id)
  with open(path, "w") as f:
    json.dump(doc, f)
  return len(doc["traceEvents"])
