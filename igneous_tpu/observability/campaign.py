"""Closed-loop campaign driver: autoscale + speculation + stealing (ISSUE 17).

``igneous campaign run`` is the one process a hostile-fleet campaign
needs running besides the workers. Each tick it composes the survival
mechanisms the repo already has into one loop:

1. **autoscale** — an :class:`~.autoscale.AutoscaleController` step:
   load the journal, evaluate the HealthEngine, size the fleet to drain
   the backlog within the horizon, actuate (spawn/SIGTERM-drain local
   workers, or publish the target for an external reconciler);
2. **flags** — publish ``health/flags.json`` so flagged stragglers
   surrender their pre-leases (the PR 6 LeaseBatcher poll);
3. **speculation** — twin the unfinished tails of range leases held by
   flagged workers, by holders whose journal-mined per-task time is
   projected past ``IGNEOUS_SPECULATE_TAIL_RATIO`` × the fleet p95, and
   by holders gone journal-silent past the stall window (the worker
   frozen before its first flush, invisible to the health engine)
   (queues.FileQueue.speculate_flagged: first ack wins, the loser is
   fenced, completions never double-count);
4. **stealing** — nothing to drive here: idle workers pull claims
   themselves (``IGNEOUS_STEAL``); the driver only ships the knob into
   worker environments and surfaces ``steal.*`` counters.

The loop exits when the campaign drains (no backlog, no outstanding
leases, pool at the policy floor) or ``max_wall_sec`` elapses. Its
summary carries the final fleet status so the chaos soak and the
acceptance test can assert that the sim forecast, the live run, and
``fleet status`` agree.
"""

from __future__ import annotations

import time
from typing import List, Optional

from . import fleet, health, metrics
from .autoscale import AutoscaleController, AutoscalePolicy

from ..analysis import knobs


class CampaignRunner:
  """One driver tick = autoscale step + flags + speculation sweep."""

  def __init__(
    self,
    journal_path: str,
    queue,
    actuator,
    policy: Optional[AutoscalePolicy] = None,
    health_config=None,
    tick_sec: Optional[float] = None,
    speculate: Optional[bool] = None,
    max_wall_sec: Optional[float] = None,
  ):
    self.journal_path = journal_path
    self.queue = queue
    self.controller = AutoscaleController(
      journal_path, queue, actuator,
      policy=policy, health_config=health_config, interval_sec=tick_sec,
    )
    self.tick_sec = (
      float(tick_sec) if tick_sec is not None
      else knobs.get_float("IGNEOUS_CAMPAIGN_TICK_SEC")
    )
    self.speculate = (
      bool(speculate) if speculate is not None
      else knobs.get_bool("IGNEOUS_CAMPAIGN_SPECULATE")
    )
    wall = (
      float(max_wall_sec) if max_wall_sec is not None
      else knobs.get_float("IGNEOUS_CAMPAIGN_MAX_WALL_SEC")
    )
    self.max_wall_sec = wall if wall and wall > 0 else None
    self.history: List[dict] = []

  # -- speculation targeting --------------------------------------------------

  def _slow_holders(self, report: dict, records) -> set:
    """Holders whose journal-mined per-task time projects a range tail
    past ``tail_ratio`` × the fleet p95 — the stragglers that haven't
    tripped a health flag (yet) but will hold the campaign tail hostage
    if left alone. Rates are busy-time (fleet.worker_rates), so an
    idle-but-fast holder never qualifies."""
    ratio = knobs.get_float("IGNEOUS_SPECULATE_TAIL_RATIO")
    p95_ms = (report.get("fleet") or {}).get("p95_task_ms") or 0.0
    if not records or p95_ms <= 0 or ratio <= 0:
      return set()
    rates = fleet.worker_rates(records)
    if not rates:
      return set()
    range_leases = getattr(self.queue, "range_leases", None)
    if range_leases is None:
      return set()
    slow = set()
    for r in range_leases():
      holder = r.get("holder")
      rate = rates.get(holder)
      if not holder or not rate or r.get("expired") or r.get("spec"):
        continue
      # projected per-member time on this holder vs the fleet p95:
      # the member count cancels out of the comparison
      if (1000.0 / rate) > ratio * p95_ms:
        slow.add(holder)
    return slow

  def _silent_holders(self, records, now: Optional[float] = None) -> set:
    """Holders of live, unpaired range leases that have gone journal-
    silent past the health stall window. This catches the worker frozen
    BEFORE its first flush — it has no rate and never trips a health
    flag (the engine only judges workers it has seen), so it is
    invisible to the other two triggers — as well as one whose journal
    simply stopped mid-campaign. The lease's own ``leased_at`` is the
    silence floor: a holder is never condemned for quiet time predating
    its lease."""
    range_leases = getattr(self.queue, "range_leases", None)
    if range_leases is None:
      return set()
    cfg = self.controller.health_config or health.HealthConfig()
    stall = float(getattr(cfg, "stall_sec", 0.0) or 0.0)
    if stall <= 0:
      return set()
    now = time.time() if now is None else now
    last_seen: dict = {}
    for r in records or ():
      w = r.get("worker")
      ts = r.get("ts")
      if w and isinstance(ts, (int, float)):
        end = float(ts) + float(r.get("dur") or 0.0)
        if end > last_seen.get(w, 0.0):
          last_seen[w] = end
    silent = set()
    for r in range_leases():
      holder = r.get("holder")
      if not holder or r.get("expired") or r.get("spec"):
        continue
      anchor = max(
        float(r.get("leased_at") or 0.0), last_seen.get(holder, 0.0)
      )
      if anchor and now - anchor >= stall:
        silent.add(holder)
    return silent

  def _speculate(self, report: dict, records) -> int:
    speculate_flagged = getattr(self.queue, "speculate_flagged", None)
    if speculate_flagged is None:
      return 0
    targets = set(report.get("flagged_workers") or ())
    targets |= self._slow_holders(report, records)
    targets |= self._silent_holders(records)
    if not targets:
      return 0
    try:
      return int(speculate_flagged(targets))
    except Exception:
      metrics.incr("campaign.speculate_failed")
      return 0

  # -- the loop ----------------------------------------------------------------

  def tick(self, now: Optional[float] = None) -> dict:
    now = time.time() if now is None else now
    decision = self.controller.step(now=now)
    report = self.controller.last_report
    speculated = 0
    if report is not None:
      health.publish_gauges(report)
      try:
        health.write_flags(self.journal_path, report)
      except Exception:
        metrics.incr("campaign.flags_failed")
      if self.speculate:
        speculated = self._speculate(report, self.controller.last_records)
    metrics.incr("campaign.ticks")
    if speculated:
      metrics.incr("campaign.speculated", speculated)
    summary = dict(
      decision,
      speculated=speculated,
      flagged=sorted(report["flagged_workers"]) if report else [],
      anomalies=(
        [a["kind"] for a in report["anomalies"]] if report else []
      ),
    )
    self.history.append(summary)
    return summary

  def _reconcile_ledger(self) -> dict:
    """Worker journals are lossy under SIGKILL: a won/fenced increment
    whose marker (and completion) committed to disk dies with the
    worker if it never flushed. The queue's speculation tallies are
    crash-safe (1-byte appends written in the same breath as the done
    marker), so once the pool is down the driver journals the missing
    difference — ``won + fenced == issued`` then reconciles from the
    journal alone, no matter how the workers died."""
    won = getattr(self.queue, "speculation_won", None)
    fenced = getattr(self.queue, "speculation_fenced", None)
    if not won and not fenced:
      return {}
    try:
      counters = fleet.status(
        fleet.load_effective(self.journal_path)
      ).get("counters", {})
    except Exception:
      return {}
    topped = {}
    missing = int(won or 0) - int(counters.get("speculation.won", 0))
    if missing > 0:
      metrics.incr("speculation.won", missing)
      topped["speculation.won"] = missing
    missing = int(fenced or 0) - int(counters.get("speculation.fenced", 0))
    if missing > 0:
      metrics.incr("speculation.fenced", missing)
      topped["speculation.fenced"] = missing
    if topped:
      metrics.incr("campaign.ledger_topped_up", sum(topped.values()))
      try:
        self.controller.journal.write_records(
          [{
            "kind": "counters", "ts": time.time(), "event": "campaign",
            "counters": metrics.counters_snapshot(), "timers": {},
            "gauges": metrics.gauges_snapshot(),
          }],
          event="campaign",
        )
      except Exception:
        metrics.incr("campaign.reconcile_failed")
    return topped

  def _drained(self, decision: dict) -> bool:
    if decision["backlog"] > 0:
      return False
    # backlog counts PENDING work; outstanding leases must resolve too,
    # or the driver walks away while stragglers still hold the tail
    enqueued = getattr(self.queue, "enqueued", 0)
    if enqueued and enqueued > 0:
      return False
    actuator = self.controller.actuator
    actuator.reap()
    return actuator.current() <= self.controller.loop.policy.min_workers

  def run(self, iterations: Optional[int] = None,
          sleep_fn=time.sleep) -> dict:
    """Tick until the campaign drains, ``max_wall_sec`` elapses, or
    ``iterations`` runs out. The actuator is always shut down (graceful
    SIGTERM drain) on the way out."""
    t0 = time.time()
    n = 0
    timed_out = False
    try:
      while True:
        decision = self.tick()
        n += 1
        if n > 1 and self._drained(decision):
          break
        if iterations is not None and n >= iterations:
          break
        if self.max_wall_sec and time.time() - t0 > self.max_wall_sec:
          timed_out = True
          metrics.incr("campaign.timed_out")
          break
        sleep_fn(self.tick_sec)
    finally:
      self.controller.actuator.shutdown()
      # after shutdown every surviving worker has flushed; what the
      # SIGKILLed ones lost is recovered from the queue's tallies
      self._reconcile_ledger()
    return self.summary(timed_out=timed_out, wall_sec=time.time() - t0)

  def summary(self, timed_out: bool = False,
              wall_sec: Optional[float] = None) -> dict:
    """Final reconciliation: driver history + the queue's own tallies +
    a fresh ``fleet status`` over the journal, in one dict — the three
    views the acceptance criteria require to agree."""
    try:
      status = fleet.status(fleet.load_effective(self.journal_path))
    except Exception:
      status = None
    out = {
      "ticks": len(self.history),
      "actions": sum(1 for d in self.history if d.get("actuated")),
      "speculated": sum(d.get("speculated", 0) for d in self.history),
      "timed_out": timed_out,
      "queue": {},
      "fleet_status": status,
    }
    if wall_sec is not None:
      out["wall_sec"] = round(wall_sec, 2)
    for attr in ("enqueued", "completed", "inserted", "dlq_count", "leased"):
      try:
        out["queue"][attr] = int(getattr(self.queue, attr))
      except Exception:
        continue
    actuator = self.controller.actuator
    if hasattr(actuator, "stats"):
      out["actuator"] = dict(
        actuator.stats, exits=dict(actuator.stats.get("exits", {}))
      )
    return out
