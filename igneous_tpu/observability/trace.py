"""Trace context + span recording — the identity layer of observability.

Every task minted by a factory carries a ``trace_id`` (and optionally a
``parent_span_id``) in its queue payload, so enqueue → lease → execute →
retry → DLQ is ONE trace no matter how many workers touch it. Spans are
wall-clock intervals attributed to that trace: the task execution itself,
each pipeline stage (download/compute/encode/upload — recorded through
the existing ``telemetry.observe`` sites), storage ops, and lease-batcher
rounds.

Cost model: span records are plain dicts appended to per-thread buffers
(one tiny uncontended lock per thread — no global lock on the hot path),
drained in batches by the journal. ``IGNEOUS_TRACE_SAMPLE`` (default 1.0)
gates allocation: at 0 no trace objects exist at all (task payloads carry
no trace, every span call is a thread-local None check), between 0 and 1
trace identity is always minted (lineage stays intact) but only the
sampled fraction records spans.
"""

from __future__ import annotations

import contextlib
import os
import random
import threading
import time
import uuid
from typing import Iterator, Optional

from ..analysis import knobs

SAMPLE_ENV = "IGNEOUS_TRACE_SAMPLE"

# per-thread span buffers are bounded: a worker that never flushes (no
# journal configured) must not grow without limit. Drops are counted.
MAX_SPANS_PER_THREAD = 50_000

_TLS = threading.local()
_BUFFERS: list = []  # _ThreadBuffer registry (drained by the journal)
_BUFFERS_LOCK = threading.Lock()
_DROPPED = [0]

# one trace id per process for worker-scoped spans (lease rounds, poll
# idle) that belong to no single task
_WORKER_TRACE = uuid.uuid4().hex[:16]


def sample_rate() -> float:
  return knobs.get_float(SAMPLE_ENV)


def tracing_enabled() -> bool:
  return sample_rate() > 0.0


def new_id() -> str:
  return uuid.uuid4().hex[:16]


def worker_trace_id() -> str:
  return _WORKER_TRACE


def mint(parent_span_id: Optional[str] = None) -> Optional[dict]:
  """Trace payload for a freshly created task (embedded in the queue
  payload under ``"trace"``). None when tracing is off entirely — that is
  the sampling=0 'no span allocation' contract."""
  rate = sample_rate()
  if rate <= 0.0:
    return None
  t = {"trace_id": new_id(), "ts": time.time()}
  if parent_span_id:
    t["parent_span_id"] = parent_span_id
  if rate < 1.0 and random.random() >= rate:
    t["sampled"] = False
  return t


class SpanContext:
  """The thread-local active node of a trace: new spans parent to
  ``span_id``. Activation installs a per-thread COPY (contexts are
  mutated for nesting, and one task's stages run on many threads)."""

  __slots__ = ("trace_id", "span_id", "sampled")

  def __init__(self, trace_id: str, span_id: Optional[str], sampled: bool):
    self.trace_id = trace_id
    self.span_id = span_id
    self.sampled = sampled

  def copy(self) -> "SpanContext":
    return SpanContext(self.trace_id, self.span_id, self.sampled)


def current() -> Optional[SpanContext]:
  return getattr(_TLS, "ctx", None)


def active() -> bool:
  ctx = getattr(_TLS, "ctx", None)
  return ctx is not None and ctx.sampled


class _ThreadBuffer:
  __slots__ = ("lock", "items")

  def __init__(self):
    self.lock = threading.Lock()
    self.items: list = []


def _buffer() -> _ThreadBuffer:
  buf = getattr(_TLS, "buf", None)
  if buf is None:
    buf = _ThreadBuffer()
    _TLS.buf = buf
    with _BUFFERS_LOCK:
      _BUFFERS.append(buf)
  return buf


def _record(rec: dict) -> None:
  buf = _buffer()
  with buf.lock:  # per-thread, uncontended except during a drain
    if len(buf.items) >= MAX_SPANS_PER_THREAD:
      _DROPPED[0] += 1
      return
    buf.items.append(rec)


def drain_spans() -> list:
  """Collect every thread's pending span records (journal flush path)."""
  out = []
  with _BUFFERS_LOCK:
    bufs = list(_BUFFERS)
  for buf in bufs:
    with buf.lock:
      if buf.items:
        out.extend(buf.items)
        buf.items = []
  return out


def dropped_spans() -> int:
  return _DROPPED[0]


def pending_spans() -> int:
  """Spans buffered but not yet journaled (Prometheus self-health:
  a growing backlog means the flush path is stuck)."""
  with _BUFFERS_LOCK:
    bufs = list(_BUFFERS)
  return sum(len(b.items) for b in bufs)


def reset() -> None:
  """Testing hook: drop all pending spans and the drop tally."""
  drain_spans()
  _DROPPED[0] = 0


@contextlib.contextmanager
def activate(ctx: Optional[SpanContext]) -> Iterator[Optional[SpanContext]]:
  """Install ``ctx`` (a copy) as this thread's active trace context."""
  prev = getattr(_TLS, "ctx", None)
  _TLS.ctx = ctx.copy() if ctx is not None else None
  try:
    yield _TLS.ctx
  finally:
    _TLS.ctx = prev


@contextlib.contextmanager
def span(name: str, **attrs) -> Iterator[Optional[str]]:
  """Record a wall-clock span under the active context (no-op when no
  sampled context is active). Nested spans parent to this one."""
  ctx = getattr(_TLS, "ctx", None)
  if ctx is None or not ctx.sampled:
    yield None
    return
  span_id = new_id()
  parent = ctx.span_id
  ctx.span_id = span_id
  ts = time.time()
  t0 = time.perf_counter()
  error = None
  try:
    yield span_id
  except BaseException as e:
    error = type(e).__name__
    raise
  finally:
    ctx.span_id = parent
    rec = {
      "trace": ctx.trace_id, "span": span_id, "parent": parent,
      "name": name, "ts": ts,
      "dur": time.perf_counter() - t0,
    }
    if error:
      rec["error"] = error
    if attrs:
      rec.update(attrs)
    _record(rec)


def maybe_span(name: str, **attrs):
  """``span`` with a fast inactive path (storage hot loops)."""
  ctx = getattr(_TLS, "ctx", None)
  if ctx is None or not ctx.sampled:
    return contextlib.nullcontext()
  return span(name, **attrs)


def record_span(name: str, seconds: float, **attrs) -> None:
  """Record a pre-measured span ending NOW (the telemetry.observe hook:
  observe sites measure duration themselves)."""
  ctx = getattr(_TLS, "ctx", None)
  if ctx is None or not ctx.sampled:
    return
  rec = {
    "trace": ctx.trace_id, "span": new_id(), "parent": ctx.span_id,
    "name": name, "ts": time.time() - seconds, "dur": float(seconds),
  }
  if attrs:
    rec.update(attrs)
  _record(rec)


def event(name: str, **attrs) -> None:
  """Zero-duration marker under the active context (chaos faults,
  lifecycle edges)."""
  record_span(name, 0.0, **attrs)


def record_at(name: str, ts: float, dur: float, trace_id: str,
              span_id: Optional[str] = None, parent: Optional[str] = None,
              **attrs) -> Optional[str]:
  """Record a span with fully explicit identity (trace, span, parent).

  The serve tier needs this: its request handlers interleave on ONE
  event-loop thread, so the thread-local context of :func:`span` cannot
  carry per-request identity. Returns the span id recorded (minted when
  ``span_id`` is None), or None when tracing is off."""
  if not tracing_enabled():
    return None
  sid = span_id or new_id()
  rec = {
    "trace": trace_id, "span": sid, "parent": parent,
    "name": name, "ts": float(ts), "dur": float(dur),
  }
  if attrs:
    rec.update(attrs)
  _record(rec)
  return sid


def record_root(name: str, ts: float, dur: float,
                trace_id: Optional[str] = None, **attrs) -> None:
  """Record a span with explicit timing under an explicit trace
  (worker-scoped spans like lease rounds; no thread context needed)."""
  if not tracing_enabled():
    return
  rec = {
    "trace": trace_id or _WORKER_TRACE, "span": new_id(), "parent": None,
    "name": name, "ts": float(ts), "dur": float(dur),
  }
  if attrs:
    rec.update(attrs)
  _record(rec)


# -- task-level plumbing ------------------------------------------------------


def trace_of(task) -> Optional[dict]:
  return getattr(task, "_trace", None)


def _exec_root(tinfo: dict) -> str:
  """The root span id of this delivery's execution; stage spans recorded
  through task_context() parent to it. Minted lazily per deserialized
  task instance — a redelivery is a fresh instance, hence a fresh root."""
  sid = tinfo.get("exec_span_id")
  if not sid:
    sid = new_id()
    tinfo["exec_span_id"] = sid
  return sid


def task_context(task) -> Optional[SpanContext]:
  """A SpanContext rooted at the task's execution span, or None when the
  task carries no trace (or tracing is off). Activate it on whatever
  thread runs one of the task's stages."""
  tinfo = trace_of(task)
  if tinfo is None or not tracing_enabled():
    return None
  return SpanContext(
    tinfo["trace_id"], _exec_root(tinfo), bool(tinfo.get("sampled", True))
  )


def record_for_task(task, name: str, ts: float, dur: float, **attrs) -> None:
  """Record a span attributed to ``task``'s trace without needing an
  active thread context (e.g. the pipelined runner's admit→join span)."""
  tinfo = trace_of(task)
  if tinfo is None or not tinfo.get("sampled", True) or not tracing_enabled():
    return
  rec = {
    "trace": tinfo["trace_id"], "span": _exec_root(tinfo),
    "parent": tinfo.get("parent_span_id"),
    "name": name, "ts": float(ts), "dur": float(dur),
    "task": type(task).__name__,
  }
  if attrs:
    rec.update(attrs)
  _record(rec)


@contextlib.contextmanager
def task_span(task, attempt=None, **attrs) -> Iterator[Optional[SpanContext]]:
  """Wrap one delivery's execution: records the enqueue-wait span (mint →
  now; on attempt N this measures the retry latency too) and the task
  span itself, with nested stage spans parenting to it."""
  tinfo = trace_of(task)
  if tinfo is None or not tracing_enabled():
    yield None
    return
  ctx = task_context(task)
  if ctx is not None and ctx.sampled and tinfo.get("ts"):
    wait = max(time.time() - float(tinfo["ts"]), 0.0)
    rec = {
      "trace": ctx.trace_id, "span": new_id(), "parent": ctx.span_id,
      "name": "queue.wait", "ts": float(tinfo["ts"]), "dur": wait,
    }
    if attempt is not None:
      rec["attempt"] = attempt
    _record(rec)
  ts = time.time()
  t0 = time.perf_counter()
  error = None
  try:
    with activate(ctx) as live:
      yield live
  except BaseException as e:
    error = type(e).__name__
    raise
  finally:
    if ctx is not None and ctx.sampled:
      rec = {
        "trace": ctx.trace_id, "span": ctx.span_id,
        "parent": tinfo.get("parent_span_id"),
        "name": "task", "ts": ts, "dur": time.perf_counter() - t0,
        "task": type(task).__name__,
      }
      if attempt is not None:
        rec["attempt"] = attempt
      if error:
        rec["error"] = error
      extra = getattr(task, "trace_attrs", None)
      if extra is not None:
        try:
          rec.update(extra())
        except Exception:
          pass
      if attrs:
        rec.update(attrs)
      _record(rec)
