"""Device telemetry plane: kernel spans, recompile/HBM accounting,
utilization ledger, on-demand profiler capture (ISSUE 7).

PRs 5-6 made the *fleet* observable; the device itself stayed a black
box — "the pipeline is I/O-dominated and the TPU mostly idles" was
folklore, not a number. This module is the instrument:

* **Kernel spans** — the executors (``parallel/executor.py``), the
  single-task device pyramid (``ops/pooling.downsample``), and thereby
  every pipeline compute stage emit ``device.compile`` vs
  ``device.execute`` spans (timed through ``block_until_ready``) plus
  ``device.h2d``/``device.d2h`` transfer spans with byte counts. Spans
  nest under whatever task/stage trace context is active on the calling
  thread (PR 5), so ``fleet trace`` and the Perfetto export show the
  device work inside the task that caused it — on its own per-device
  track.
* **Compile-cache / shape-churn ledger** — distinct compiled signatures
  per kernel are counted; ``device.recompiles`` increments exactly once
  per NEW signature (the ragged-batching baseline number), and the
  fast-path eligibility gauge tracks batched vs fell-to-host deliveries.
* **HBM + utilization accounting** — per-kernel peak-memory watermarks
  and live-buffer gauges from ``Device.memory_stats()`` (graceful no-op
  on backends without them — XLA CPU returns None), and a per-worker
  utilization ledger: device-busy seconds / wall seconds, per-kernel
  vox/s and bytes/s. The ledger is CUMULATIVE and flushes into the
  journal as ``{"kind": "device"}`` records (latest-per-worker is
  lossless, so rollups keep only that), surfaces as ``igneous_device_*``
  Prometheus gauges, the ``igneous fleet devices`` CLI, the ``fleet
  watch`` dashboard, and three new HealthEngine anomalies (recompile
  storm, HBM high-water, device idle-while-backlogged).
* **On-demand profiler capture** — ``igneous profile capture`` publishes
  ``<journal>/profile/request.json``; workers poll it (same pattern as
  the PR 6 straggler flags) and run a bounded ``jax.profiler`` trace,
  uploading the artifacts next to the journal under ``profiles/``.
  ``IGNEOUS_PROFILE_EVERY`` additionally samples every Nth device
  dispatch into ``IGNEOUS_PROFILE_DIR`` with zero flag-file traffic.

Everything here must be safe on accelerator-less hosts and cost nothing
when idle: ledger updates are a dict update under one lock, span records
only allocate when a sampled trace context is active, and the profiler
is inert unless explicitly triggered.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Dict, Iterator, List, Optional

from . import metrics, trace

from ..analysis import knobs

PROFILE_DIR_ENV = "IGNEOUS_PROFILE_DIR"
PROFILE_EVERY_ENV = "IGNEOUS_PROFILE_EVERY"
PROFILE_REQUEST_KEY = "profile/request.json"
PROFILE_ARTIFACT_PREFIX = "profiles/"
# how often a worker re-reads <journal>/profile/request.json (one small
# object GET, piggybacked on the journal maybe_flush cadence — the same
# deal as LeaseBatcher's straggler-flag poll)
PROFILE_POLL_SEC = 15.0
# a capture request older than this is history, not a trigger: a worker
# booting days later must not burn minutes profiling for nobody
PROFILE_REQUEST_TTL_SEC = 600.0


# ---------------------------------------------------------------------------
# utilization ledger


class DeviceLedger:
  """Process-wide cumulative accounting of device work.

  One instance per worker process (module singleton). All totals are
  monotonic since ``t_start`` so the journal's latest-per-worker record
  is a complete summary — rollup compaction keeps exactly that.
  """

  def __init__(self):
    self.lock = threading.Lock()
    self.reset()

  def reset(self) -> None:
    with self.lock:
      self.t_start = time.time()  # guarded-by: self.lock
      self._t0 = time.monotonic()  # guarded-by: self.lock
      # kernel -> cumulative stats
      self.kernels: Dict[str, dict] = {}  # guarded-by: self.lock
      # (kernel, signature-repr) seen-set: the recompile ledger
      self._signatures: set = set()  # guarded-by: self.lock
      # device label -> cumulative busy seconds
      self.device_busy: Dict[str, float] = {}  # guarded-by: self.lock
      self.h2d_bytes = 0  # guarded-by: self.lock
      self.d2h_bytes = 0  # guarded-by: self.lock
      self.h2d_seconds = 0.0  # guarded-by: self.lock
      self.d2h_seconds = 0.0  # guarded-by: self.lock
      self.recompiles = 0  # guarded-by: self.lock
      self.dispatches = 0  # guarded-by: self.lock
      self.fastpath = {"batched": 0, "host": 0}  # guarded-by: self.lock
      # persistent compile-cache accounting (ISSUE 19): saved_s sums the
      # producer-measured compile seconds each hit avoided — the number
      # `igneous fleet devices` rolls up into compile-seconds-saved
      self.compile_cache = dict(  # guarded-by: self.lock
        hits=0, misses=0, puts=0, corrupt=0, saved_s=0.0, fetch_s=0.0,
      )
      # padding-byte accounting across every batched dispatch (pow2
      # batch rounding, page-pool filler slots, infer group fill)
      self.pad_bytes = 0  # guarded-by: self.lock
      self.real_bytes = 0  # guarded-by: self.lock
      # device label -> last sampled memory stats (+ peak high-water)
      self.hbm: Dict[str, dict] = {}  # guarded-by: self.lock
      # anything recorded since the last journal flush? An idle worker
      # must not grow a segment per flush interval forever
      self._dirty = False  # guarded-by: self.lock

  def _kernel_locked(self, name: str) -> dict:
    k = self.kernels.get(name)
    if k is None:
      k = self.kernels[name] = {
        "compiles": 0, "compile_s": 0.0,
        "executes": 0, "execute_s": 0.0,
        "elements": 0, "bytes": 0, "cache_hits": 0,
      }
    return k

  # -- write side -----------------------------------------------------------

  def note_signature(self, kernel: str, signature,
                     cached: bool = False) -> bool:
    """True exactly once per (kernel, signature): the recompile tick.
    Counter contract (ISSUE 7 acceptance): ``device.recompiles``
    increments ONLY when a shape/dtype signature is first compiled.

    ``cached=True`` marks a persistent compile-cache hit (ISSUE 19): the
    signature still enters the seen-set, but ``device.recompiles`` does
    NOT tick — a warm-started fleet fetched the executable instead of
    compiling, and must not trip the recompile-storm anomaly or skew
    ``igneous_device_fastpath_ratio`` baselines."""
    key = (kernel, repr(signature))
    with self.lock:
      if key in self._signatures:
        return False
      self._signatures.add(key)
      if not cached:
        self.recompiles += 1
    if not cached:
      metrics.incr("device.recompiles")
    return True

  _CACHE_COUNTER = {"hits": "hit", "misses": "miss",
                    "puts": "put", "corrupt": "corrupt"}

  def record_cache_event(self, event: str, kernel: str = "",
                         saved_s: float = 0.0,
                         fetch_s: float = 0.0) -> None:
    """Persistent compile-cache accounting (ISSUE 19): ``event`` is one
    of hits|misses|puts|corrupt. ``saved_s`` is the producer-measured
    compile time a hit avoided; ``fetch_s`` the deserialize+download
    cost actually paid instead."""
    with self.lock:
      cc = self.compile_cache
      cc[event] += 1
      cc["saved_s"] += float(saved_s)
      cc["fetch_s"] += float(fetch_s)
      if kernel and event == "hits":
        k = self._kernel_locked(kernel)
        k["cache_hits"] = k.get("cache_hits", 0) + 1
      self._dirty = True
    metrics.incr(f"device.compile_cache.{self._CACHE_COUNTER[event]}")

  def record_compile(self, kernel: str, seconds: float) -> None:
    with self.lock:
      k = self._kernel_locked(kernel)
      k["compiles"] += 1
      k["compile_s"] += float(seconds)
      self._dirty = True

  def record_execute(self, kernel: str, seconds: float,
                     elements: int = 0, nbytes: int = 0,
                     devices: Optional[List[str]] = None) -> None:
    """One device dispatch: ``seconds`` of wall time in which the listed
    devices were busy (the program is sharded across all of them, so
    each is attributed the full interval)."""
    seconds = float(seconds)
    with self.lock:
      k = self._kernel_locked(kernel)
      k["executes"] += 1
      k["execute_s"] += seconds
      k["elements"] += int(elements)
      k["bytes"] += int(nbytes)
      self.dispatches += 1
      self._dirty = True
      for dev in devices or ("device",):
        self.device_busy[dev] = self.device_busy.get(dev, 0.0) + seconds

  def record_transfer(self, direction: str, nbytes: int,
                      seconds: float) -> None:
    with self.lock:
      self._dirty = True
      if direction == "h2d":
        self.h2d_bytes += int(nbytes)
        self.h2d_seconds += float(seconds)
      else:
        self.d2h_bytes += int(nbytes)
        self.d2h_seconds += float(seconds)

  def record_fastpath(self, batched: int = 0, host: int = 0) -> None:
    """Fast-path eligibility accounting: ``batched`` deliveries rode a
    batched device dispatch, ``host`` fell to the per-task host path
    (ragged shape, single-member group, accelerator-less worker)."""
    with self.lock:
      self.fastpath["batched"] += int(batched)
      self.fastpath["host"] += int(host)
      self._dirty = True
      b, h = self.fastpath["batched"], self.fastpath["host"]
    if batched:
      metrics.incr("device.fastpath.batched", int(batched))
    if host:
      metrics.incr("device.fastpath.host", int(host))
    if b + h:
      metrics.gauge_set("device.fastpath_ratio", b / (b + h))

  def record_pad_waste(self, padded_bytes: int = 0,
                       real_bytes: int = 0) -> None:
    """Padding-byte accounting for batched dispatches: ``padded_bytes``
    were filler (pow2 batch rounding, page-pool slack, dispatch-group
    fill), ``real_bytes`` carried cutout data. The exported gauge is the
    cumulative padded/real ratio — the waste the ragged paged packer
    exists to eliminate. Padding layers can nest (a paged round's filler
    pages also ride the executor's own pow2 rounding), so the totals are
    additive bookkeeping of every layer's slack, not disjoint memory."""
    with self.lock:
      self.pad_bytes += int(padded_bytes)
      self.real_bytes += int(real_bytes)
      self._dirty = True
      p, r = self.pad_bytes, self.real_bytes
    if r:
      metrics.gauge_set("device.pad_waste_ratio", p / r)

  def sample_hbm(self) -> Dict[str, dict]:
    """Poll ``Device.memory_stats()`` on every local device; a backend
    without them (XLA CPU) simply contributes nothing — the gauges
    no-op instead of erroring (ISSUE 7 acceptance)."""
    try:
      import jax

      devices = jax.local_devices()
    except Exception:
      return {}
    out = {}
    for dev in devices:
      try:
        stats = dev.memory_stats()
      except Exception:
        stats = None
      if not stats:
        continue
      label = f"{dev.platform}:{dev.id}"
      rec = {
        "bytes_in_use": int(stats.get("bytes_in_use", 0)),
        "peak_bytes_in_use": int(
          stats.get("peak_bytes_in_use", stats.get("bytes_in_use", 0))
        ),
      }
      limit = stats.get("bytes_limit") or stats.get("bytes_reservable_limit")
      if limit:
        rec["bytes_limit"] = int(limit)
      out[label] = rec
    if out:
      with self.lock:
        for label, rec in out.items():
          prev = self.hbm.get(label) or {}
          rec["peak_bytes_in_use"] = max(
            rec["peak_bytes_in_use"], prev.get("peak_bytes_in_use", 0)
          )
          self.hbm[label] = rec
      worst = max(out.values(), key=lambda r: r["peak_bytes_in_use"])
      metrics.gauge_set("device.hbm.bytes_in_use", worst["bytes_in_use"])
      metrics.gauge_max("device.hbm.peak_bytes", worst["peak_bytes_in_use"])
      if worst.get("bytes_limit"):
        # the PrometheusRule divides peak by this for the high-water alert
        metrics.gauge_set("device.hbm.bytes_limit", worst["bytes_limit"])
    return out

  # -- read side ------------------------------------------------------------

  def busy_seconds(self) -> float:
    with self.lock:
      return max(self.device_busy.values(), default=0.0)

  def utilization(self) -> Optional[float]:
    """device-busy seconds / wall seconds since ledger start, using the
    busiest device (the program shards across all of them, so the
    busiest one bounds what overlap could still hide). None before any
    dispatch — "no device work" and "device idle" are different facts."""
    wall = time.monotonic() - self._t0
    if wall <= 0 or not self.device_busy:
      return None
    return min(self.busy_seconds() / wall, 1.0)

  def snapshot(self) -> Optional[dict]:
    """The journal/Prometheus view; None when no device work happened
    (accelerator-less workers write no device records at all)."""
    with self.lock:
      if not self.dispatches and not self.fastpath["host"] \
         and not self.h2d_bytes:
        return None
      wall = max(time.monotonic() - self._t0, 1e-9)
      kernels = {}
      for name, k in self.kernels.items():
        kernels[name] = {
          **{key: (round(v, 4) if isinstance(v, float) else v)
             for key, v in k.items()},
          "vox_per_sec": (
            round(k["elements"] / k["execute_s"], 1)
            if k["execute_s"] > 0 else None
          ),
          "bytes_per_sec": (
            round(k["bytes"] / k["execute_s"], 1)
            if k["execute_s"] > 0 and k["bytes"] else None
          ),
        }
      busy = max(self.device_busy.values(), default=0.0)
      snap = {
        "ts": time.time(),
        "t_start": self.t_start,
        "wall_s": round(wall, 3),
        "busy_s": round(busy, 4),
        "busy_ratio": round(min(busy / wall, 1.0), 4),
        "dispatches": self.dispatches,
        "recompiles": self.recompiles,
        "distinct_signatures": len(self._signatures),
        "kernels": kernels,
        "devices": {
          dev: round(s, 4) for dev, s in sorted(self.device_busy.items())
        },
        "fastpath": dict(self.fastpath),
        "compile_cache": {
          k: (round(v, 4) if isinstance(v, float) else v)
          for k, v in self.compile_cache.items()
        },
        "pad_bytes": self.pad_bytes,
        "real_bytes": self.real_bytes,
        "pad_waste_ratio": (
          round(self.pad_bytes / self.real_bytes, 4)
          if self.real_bytes else None
        ),
        "h2d_bytes": self.h2d_bytes,
        "d2h_bytes": self.d2h_bytes,
        "h2d_MBps": (
          round(self.h2d_bytes / self.h2d_seconds / 1e6, 1)
          if self.h2d_seconds > 0 else None
        ),
        "d2h_MBps": (
          round(self.d2h_bytes / self.d2h_seconds / 1e6, 1)
          if self.d2h_seconds > 0 else None
        ),
      }
      if self.hbm:
        snap["hbm"] = {dev: dict(rec) for dev, rec in self.hbm.items()}
      return snap


LEDGER = DeviceLedger()


def reset() -> None:
  """Testing hook: fresh ledger + profiler trigger state."""
  LEDGER.reset()
  _PROFILE_STATE.update(cache=(0.0, None), served=set(), active=False)


def publish_gauges() -> None:
  """Ledger → ``igneous_device_*`` gauges (rendered by prom.render):
  busy ratio, dispatch/recompile tallies (the counters register at
  record time), and the HBM watermarks sampled fresh."""
  util = LEDGER.utilization()
  if util is not None:
    metrics.gauge_set("device.busy_ratio", util)
  LEDGER.sample_hbm()


# ---------------------------------------------------------------------------
# span emission — called by the executors around each device phase


def _devices_of(mesh=None) -> List[str]:
  if mesh is not None:
    try:
      return [f"{d.platform}:{d.id}" for d in mesh.devices.flat]
    except Exception:
      pass
  try:  # un-meshed dispatch runs on the default device
    import jax

    d = jax.devices()[0]
    return [f"{d.platform}:{d.id}"]
  except Exception:
    return ["device"]


def record_span(name: str, seconds: float, **attrs) -> None:
  """A pre-measured device span carrying kernel/device/byte attrs for
  the Perfetto device tracks. Under a sampled task/stage context it
  nests there (``fleet trace`` shows the device work inside the task
  that caused it); otherwise it lands on the worker trace — lease-round
  dispatches and driver-run batched workloads happen outside any task
  span, and their device timeline must not vanish for it."""
  ctx = trace.current()
  if ctx is not None:
    if ctx.sampled:
      trace.record_span(name, seconds, **attrs)
    return  # unsampled task: honor its sampling verdict
  if trace.tracing_enabled():
    trace.record_root(name, time.time() - seconds, seconds, **attrs)


@contextlib.contextmanager
def compile_span(kernel: str, devices: List[str]) -> Iterator[None]:
  """Time one XLA compilation (lower+compile, or the first traced call
  of a fresh signature) and account it to the ledger."""
  t0 = time.perf_counter()
  try:
    yield
  finally:
    dt = time.perf_counter() - t0
    LEDGER.record_compile(kernel, dt)
    metrics.observe_quiet("device.compile.s", dt)
    record_span("device.compile", dt, kernel=kernel,
                device=devices[0] if devices else None)


@contextlib.contextmanager
def execute_span(kernel: str, elements: int = 0, nbytes: int = 0,
                 mesh=None, **attrs) -> Iterator[None]:
  """Time one device dispatch. The caller must block on the result
  INSIDE the context (``jax.block_until_ready``) — dispatch is async and
  an unblocked timing would measure enqueue, not execution.

  Extra keyword ``attrs`` ride onto the emitted ``device.execute`` span
  verbatim (e.g. the fused pyramid kernel's ``mip_from``/``mip_to``)."""
  devices = _devices_of(mesh)
  t0 = time.perf_counter()
  try:
    yield
  finally:
    dt = time.perf_counter() - t0
    LEDGER.record_execute(kernel, dt, elements=elements, nbytes=nbytes,
                          devices=devices)
    metrics.observe_quiet("device.execute.s", dt)
    record_span("device.execute", dt, kernel=kernel, elements=elements,
                device=devices[0] if devices else None,
                devices=len(devices), **attrs)
    maybe_sample_profile()


@contextlib.contextmanager
def transfer_span(direction: str, nbytes: int, kernel: str = "",
                  mesh=None) -> Iterator[None]:
  """Time one host<->device transfer (``direction`` is "h2d" or "d2h")
  with its byte count."""
  devices = _devices_of(mesh)
  t0 = time.perf_counter()
  try:
    yield
  finally:
    dt = time.perf_counter() - t0
    LEDGER.record_transfer(direction, nbytes, dt)
    metrics.observe_quiet(f"device.{direction}.s", dt)
    record_span(f"device.{direction}", dt, kernel=kernel or None,
                bytes=int(nbytes), device=devices[0] if devices else None)


def nbytes_of(tree) -> int:
  """Total bytes across a pytree of arrays (transfer span byte counts)."""
  try:
    import jax

    return sum(int(getattr(l, "nbytes", 0)) for l in jax.tree.leaves(tree))
  except Exception:
    return 0


def elements_of(tree) -> int:
  try:
    import jax

    return sum(int(getattr(l, "size", 0)) for l in jax.tree.leaves(tree))
  except Exception:
    return 0


# ---------------------------------------------------------------------------
# journal integration


def journal_records() -> List[dict]:
  """The journal flush hook (registered via
  ``journal.register_record_provider``): one cumulative ``device``
  record per flush — only when the ledger changed since the last one
  (an idle worker must not mint a fresh segment every interval)."""
  with LEDGER.lock:
    dirty = LEDGER._dirty
    LEDGER._dirty = False
  if not dirty:
    return []
  publish_gauges()  # refreshes HBM watermarks before the snapshot
  snap = LEDGER.snapshot()
  if snap is None:
    return []
  snap["kind"] = "device"
  return [snap]


def install() -> None:
  """Wire the device plane into an active journal-bearing worker:
  ledger records ride every journal flush, and the profiler trigger is
  polled on the same cadence. Idempotent."""
  from . import journal as journal_mod

  journal_mod.register_record_provider(journal_records)
  journal_mod.register_poll_hook(poll_profile_trigger)


# ---------------------------------------------------------------------------
# on-demand profiler capture


_PROFILE_STATE = {
  "cache": (0.0, None),  # (checked_at_monotonic, request-or-None)
  "served": set(),       # request ids this process already captured
  "active": False,       # a capture thread is running
}
_PROFILE_LOCK = threading.Lock()


def write_profile_request(journal_path: str, duration_sec: float = 5.0,
                          workers: Optional[List[str]] = None,
                          request_id: Optional[str] = None) -> dict:
  """Publish a capture request where workers can see it (the ``igneous
  profile capture`` CLI). ``workers`` restricts the trigger; None means
  every worker that polls the flag captures once."""
  from ..storage import CloudFiles

  req = {
    "id": request_id or trace.new_id(),
    "ts": time.time(),
    "duration_sec": float(duration_sec),
    "workers": list(workers) if workers else None,
  }
  CloudFiles(journal_path).put_json(PROFILE_REQUEST_KEY, req)
  return req


def read_profile_request(journal_path: str) -> Optional[dict]:
  from ..storage import CloudFiles

  try:
    req = CloudFiles(journal_path).get_json(PROFILE_REQUEST_KEY)
  except Exception:
    return None
  if not req or not req.get("id"):
    return None
  if time.time() - float(req.get("ts") or 0) > PROFILE_REQUEST_TTL_SEC:
    return None
  return req


def poll_profile_trigger(journal=None) -> bool:
  """Worker-side poll (TTL-cached, piggybacked on the journal flush
  cadence): when a fresh capture request names this worker (or no one
  in particular), run one bounded profiler capture in the background.
  Returns True when a capture was started."""
  j = journal
  if j is None:
    from . import journal as journal_mod

    j = journal_mod.get_active()
  if j is None:
    return False
  now = time.monotonic()
  checked_at, req = _PROFILE_STATE["cache"]
  if now - checked_at > PROFILE_POLL_SEC:
    req = read_profile_request(j.cloudpath)
    _PROFILE_STATE["cache"] = (now, req)
  if req is None:
    return False
  if req["id"] in _PROFILE_STATE["served"]:
    return False
  targets = req.get("workers")
  if targets and j.worker_id not in targets:
    return False
  _PROFILE_STATE["served"].add(req["id"])
  return start_capture(
    duration_sec=float(req.get("duration_sec") or 5.0),
    journal=j, request_id=req["id"],
  )


def start_capture(duration_sec: float, journal=None,
                  request_id: str = "manual",
                  logdir: Optional[str] = None) -> bool:
  """Run one bounded ``jax.profiler`` capture on a background thread
  (the worker keeps executing — profiling the device plane must not
  idle it) and upload the artifacts next to the journal. Returns False
  when a capture is already running or the profiler is unavailable.

  The thread is deliberately NON-daemon: the XLA profiler leaves
  thread-local state behind, and an unjoined profiler thread at
  interpreter exit segfaults in TSL teardown (reproduced on jaxlib
  0.4.36 CPU: daemon capture thread + normal exit → SIGSEGV with no
  Python frame). Non-daemon means threading's shutdown joins it before
  the interpreter tears down — which also guarantees a draining
  worker's capture artifacts land instead of dying with the pod."""
  with _PROFILE_LOCK:
    if _PROFILE_STATE["active"]:
      return False
    _PROFILE_STATE["active"] = True

  def run():
    try:
      _capture_blocking(duration_sec, journal, request_id, logdir)
    finally:
      _PROFILE_STATE["active"] = False

  threading.Thread(target=run, daemon=False, name="ig-profile").start()
  return True


def _capture_blocking(duration_sec, journal, request_id, logdir):
  import tempfile

  from . import metrics as metrics_mod

  try:
    import jax
  except Exception:
    return
  base = logdir or knobs.get_str(PROFILE_DIR_ENV)
  tmp = None
  if not base:
    tmp = tempfile.mkdtemp(prefix="igneous-profile-")
    base = tmp
  worker = journal.worker_id if journal is not None else "local"
  capture_dir = os.path.join(base, f"{worker}-{request_id}")
  try:
    jax.profiler.start_trace(capture_dir)
  except Exception:
    metrics_mod.incr("device.profile.start_failed")
    return
  try:
    time.sleep(max(float(duration_sec), 0.0))
  finally:
    try:
      jax.profiler.stop_trace()
    except Exception:
      metrics_mod.incr("device.profile.stop_failed")
      return
  metrics_mod.incr("device.profile.captures")
  trace.event("device.profile", request_id=request_id, dir=capture_dir,
              duration_sec=float(duration_sec))
  if journal is not None:
    uploaded = _upload_artifacts(journal.cloudpath, capture_dir,
                                 f"{PROFILE_ARTIFACT_PREFIX}{worker}-{request_id}/")
    journal.write_records([{
      "kind": "span", "name": "device.profile", "ts": time.time(),
      "dur": float(duration_sec), "trace": trace.worker_trace_id(),
      "span": trace.new_id(), "parent": None,
      "request_id": request_id, "artifacts": uploaded,
    }], event="profile")


def _upload_artifacts(journal_path: str, local_dir: str,
                      prefix: str) -> int:
  """Copy the profiler's local artifact tree under
  ``<journal>/profiles/`` via CloudFiles; returns files uploaded."""
  from ..storage import CloudFiles

  cf = CloudFiles(journal_path)
  n = 0
  for root, _dirs, files in os.walk(local_dir):
    for fname in files:
      full = os.path.join(root, fname)
      rel = os.path.relpath(full, local_dir)
      try:
        with open(full, "rb") as f:
          cf.put(prefix + rel.replace(os.sep, "/"), f.read(), compress=None)
        n += 1
      except Exception:
        metrics.incr("device.profile.upload_failed")
  return n


def list_profiles(journal_path: str) -> List[str]:
  from ..storage import CloudFiles

  try:
    return sorted(CloudFiles(journal_path).list(PROFILE_ARTIFACT_PREFIX))
  except Exception:
    return []


_SAMPLE_COUNT = [0]


def maybe_sample_profile() -> None:
  """Sampled capture: with ``IGNEOUS_PROFILE_DIR`` set and
  ``IGNEOUS_PROFILE_EVERY=N`` (N>0), every Nth device dispatch starts a
  short capture. Inert by default — two env reads per dispatch, nothing
  else."""
  if not knobs.get_str(PROFILE_DIR_ENV):
    return
  every = knobs.get_int(PROFILE_EVERY_ENV)
  if every <= 0:
    return
  _SAMPLE_COUNT[0] += 1
  if _SAMPLE_COUNT[0] % every:
    return
  start_capture(
    duration_sec=knobs.get_float("IGNEOUS_PROFILE_SEC"),
    request_id=f"sample-{_SAMPLE_COUNT[0]}",
  )


# ---------------------------------------------------------------------------
# fleet read side — merged per-device table


def device_ledgers(records) -> Dict[str, dict]:
  """Latest cumulative device record per worker from merged journal
  records (raw segments or rollups — both carry them verbatim)."""
  out: Dict[str, dict] = {}
  for rec in records:
    if rec.get("kind") != "device":
      continue
    worker = rec.get("worker", "local")
    prev = out.get(worker)
    if prev is None or rec.get("ts", 0) >= prev.get("ts", 0):
      out[worker] = rec
  return out


def _fmt_bytes(n) -> str:
  if n is None:
    return "-"
  n = float(n)
  for unit in ("B", "KB", "MB", "GB", "TB"):
    if abs(n) < 1024 or unit == "TB":
      return f"{n:.0f}{unit}" if unit == "B" else f"{n:.1f}{unit}"
    n /= 1024
  return f"{n:.1f}TB"


def render_devices(ledgers: Dict[str, dict]) -> List[str]:
  """The ``igneous fleet devices`` table: one row per worker x device
  with busy ratio + HBM, then per-kernel vox/s rows."""
  if not ledgers:
    return ["no device records in the journal (no worker dispatched "
            "device work, or the device plane is disabled)"]
  lines = [
    f"{'worker':<28}{'device':<14}{'busy_s':>9}{'busy%':>7}"
    f"{'disp':>6}{'recomp':>7}{'hbm_peak':>10}"
  ]
  for worker in sorted(ledgers):
    rec = ledgers[worker]
    devices = rec.get("devices") or {}
    hbm = rec.get("hbm") or {}
    ratio = rec.get("busy_ratio")
    for i, (dev, busy) in enumerate(sorted(devices.items())):
      peak = (hbm.get(dev) or {}).get("peak_bytes_in_use")
      pct = (
        f"{ratio * 100:.1f}%" if ratio is not None and i == 0 else ""
      )
      lines.append(
        f"{worker if i == 0 else '':<28}{dev:<14}{busy:>9.2f}"
        f"{pct:>7}"
        f"{rec.get('dispatches', 0) if i == 0 else '':>6}"
        f"{rec.get('recompiles', 0) if i == 0 else '':>7}"
        f"{_fmt_bytes(peak):>10}"
      )
    if not devices:
      lines.append(f"{worker:<28}{'-':<14}{0.0:>9.2f}{'':>7}"
                   f"{rec.get('dispatches', 0):>6}"
                   f"{rec.get('recompiles', 0):>7}{'-':>10}")
  lines.append("")
  lines.append(f"{'worker':<28}{'kernel':<22}{'execs':>6}{'exec_s':>9}"
               f"{'vox/s':>14}{'compiles':>9}")
  for worker in sorted(ledgers):
    for i, (kname, k) in enumerate(
      sorted((ledgers[worker].get("kernels") or {}).items())
    ):
      vox = k.get("vox_per_sec")
      lines.append(
        f"{worker if i == 0 else '':<28}{kname:<22}{k.get('executes', 0):>6}"
        f"{k.get('execute_s', 0.0):>9.3f}"
        f"{(f'{vox:,.0f}' if vox else '-'):>14}{k.get('compiles', 0):>9}"
      )
  fp = {"batched": 0, "host": 0}
  for rec in ledgers.values():
    for key in fp:
      fp[key] += int((rec.get("fastpath") or {}).get(key, 0))
  total = fp["batched"] + fp["host"]
  if total:
    lines.append("")
    lines.append(
      f"fast path: {fp['batched']}/{total} deliveries batched "
      f"({fp['batched'] / total:.1%}), {fp['host']} fell to host"
    )
  pad = sum(int(rec.get("pad_bytes") or 0) for rec in ledgers.values())
  real = sum(int(rec.get("real_bytes") or 0) for rec in ledgers.values())
  if real:
    lines.append(
      f"pad waste: {_fmt_bytes(pad)} padding over {_fmt_bytes(real)} real "
      f"bytes ({pad / real:.1%})"
    )
  cc = _cache_rollup(ledgers)
  if cc["hits"] or cc["misses"] or cc["puts"] or cc["corrupt"]:
    lines.append(
      f"compile cache: {cc['hits']} hits / {cc['misses']} misses, "
      f"{cc['puts']} puts, {cc['corrupt']} corrupt — "
      f"{cc['saved_s']:.1f}s compile time saved fleet-wide "
      f"({cc['fetch_s']:.1f}s spent fetching)"
    )
  return lines


def _cache_rollup(ledgers: Dict[str, dict]) -> dict:
  """Summed persistent compile-cache stats across every worker's latest
  ledger record — the fleet-wide compile-seconds-saved number."""
  cc = {"hits": 0, "misses": 0, "puts": 0, "corrupt": 0,
        "saved_s": 0.0, "fetch_s": 0.0}
  for rec in ledgers.values():
    src = rec.get("compile_cache") or {}
    for key in cc:
      cc[key] += type(cc[key])(src.get(key, 0) or 0)
  cc["saved_s"] = round(cc["saved_s"], 4)
  cc["fetch_s"] = round(cc["fetch_s"], 4)
  return cc


def fleet_summary(ledgers: Dict[str, dict]) -> Optional[dict]:
  """Compact cross-worker rollup for the health report / watch
  dashboard: fleet busy ratio (busiest device per worker, averaged),
  total recompiles/dispatches, worst HBM fraction."""
  if not ledgers:
    return None
  ratios = [
    r["busy_ratio"] for r in ledgers.values()
    if r.get("busy_ratio") is not None
  ]
  hbm_frac = None
  for rec in ledgers.values():
    for dev_stats in (rec.get("hbm") or {}).values():
      limit = dev_stats.get("bytes_limit")
      if limit:
        frac = dev_stats.get("peak_bytes_in_use", 0) / limit
        hbm_frac = frac if hbm_frac is None else max(hbm_frac, frac)
  fp = {"batched": 0, "host": 0}
  for rec in ledgers.values():
    for key in fp:
      fp[key] += int((rec.get("fastpath") or {}).get(key, 0))
  pad = sum(int(rec.get("pad_bytes") or 0) for rec in ledgers.values())
  real = sum(int(rec.get("real_bytes") or 0) for rec in ledgers.values())
  return {
    "workers": len(ledgers),
    "pad_waste_ratio": round(pad / real, 4) if real else None,
    "busy_ratio": (
      round(sum(ratios) / len(ratios), 4) if ratios else None
    ),
    "dispatches": sum(r.get("dispatches", 0) for r in ledgers.values()),
    "recompiles": sum(r.get("recompiles", 0) for r in ledgers.values()),
    "hbm_peak_frac": round(hbm_frac, 4) if hbm_frac is not None else None,
    "fastpath": fp,
    "compile_cache": _cache_rollup(ledgers),
  }


def report_json(ledgers: Dict[str, dict]) -> str:
  return json.dumps(
    {"summary": fleet_summary(ledgers), "workers": ledgers},
    indent=2,
  )
