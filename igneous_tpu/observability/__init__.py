"""Distributed observability: trace context, spans, journal, exporters.

The subsystem in one picture::

    task factory ──mint──> trace_id in the queue payload
         │                        │
      enqueue              lease / redeliver / DLQ (identity survives)
         │                        │
         └──> worker: task_span + stage spans (pipeline observe() sites,
              storage ops, lease rounds) → per-thread span buffers
                                  │
              Journal.flush ──> <queue>/journal/*.jsonl segments
                                  │
         igneous fleet status|trace|top   Prometheus /metrics   Perfetto

``igneous_tpu.telemetry`` remains as a compat shim over
:mod:`.metrics`; new code should import from here.
"""

from . import fleet, journal, perfetto, prom, trace
from .metrics import (
  StageTimes,
  counters_snapshot,
  device_trace,
  emit_counters,
  gauge_max,
  gauges_snapshot,
  histograms_snapshot,
  incr,
  observe,
  queue_eta,
  reset_all,
  reset_counters,
  stage,
  task_timing,
  timed_poll_hooks,
  timers_snapshot,
)

__all__ = [
  "fleet", "journal", "perfetto", "prom", "trace",
  "StageTimes", "counters_snapshot", "device_trace", "emit_counters",
  "gauge_max", "gauges_snapshot", "histograms_snapshot", "incr", "observe",
  "queue_eta", "reset_all", "reset_counters", "stage", "task_timing",
  "timed_poll_hooks", "timers_snapshot",
]
