"""Distributed observability: trace context, spans, journal, exporters.

The subsystem in one picture::

    task factory ──mint──> trace_id in the queue payload
         │                        │
      enqueue              lease / redeliver / DLQ (identity survives)
         │                        │
         └──> worker: task_span + stage spans (pipeline observe() sites,
              storage ops, lease rounds) → per-thread span buffers
                                  │
              Journal.flush ──> <queue>/journal/*.jsonl segments
                                  │            │
         igneous fleet status|trace|top        │ rollup.compact (ISSUE 6)
         Prometheus /metrics        Perfetto   ▼
                                    <journal>/rollup/ windowed records
                                               │
              HealthEngine (stragglers, anomalies, SLO burn, autoscale)
                 │               │                    │
         fleet check|watch   health.* events   health/flags.json
         (exit codes, CI)    + Prom gauges     (LeaseBatcher backs off)

    device plane (ISSUE 7, device.py): executors/pooling emit
    device.compile|execute|h2d|d2h spans + a cumulative utilization
    ledger (recompiles, HBM, busy ratio, per-kernel vox/s) → journal
    "device" records → igneous_device_* gauges, `fleet devices`, watch
    dashboard, recompile-storm/HBM/idle anomalies; profile/request.json
    triggers on-demand jax.profiler captures → <journal>/profiles/

    forward plane (ISSUE 13): replay.py mines the journal into a
    WorkloadModel; sim.py replays it through a deterministic
    discrete-event fleet simulation that EMITS journal format (every
    fleet command works on simulated runs); autoscale.py closes the
    loop — the same policy formula drives the health report, the
    simulator's virtual controller, and `igneous fleet autoscale`

``igneous_tpu.telemetry`` remains as a compat shim over
:mod:`.metrics`; new code should import from here.
"""

from . import (
  autoscale,
  device,
  fleet,
  health,
  journal,
  perfetto,
  prom,
  replay,
  rollup,
  sim,
  trace,
)
from .metrics import (
  StageTimes,
  counters_snapshot,
  device_trace,
  emit_counters,
  gauge_max,
  gauge_set,
  gauges_snapshot,
  histograms_snapshot,
  incr,
  observe,
  observe_quiet,
  queue_eta,
  reset_all,
  reset_counters,
  stage,
  task_timing,
  timed_poll_hooks,
  timers_snapshot,
)

__all__ = [
  "autoscale", "device", "fleet", "health", "journal", "perfetto",
  "prom", "replay", "rollup", "sim", "trace",
  "StageTimes", "counters_snapshot", "device_trace", "emit_counters",
  "gauge_max", "gauge_set", "gauges_snapshot", "histograms_snapshot",
  "incr", "observe", "observe_quiet", "queue_eta", "reset_all",
  "reset_counters", "stage",
  "task_timing", "timed_poll_hooks", "timers_snapshot",
]
