"""Fleet-level aggregation over merged journal segments.

The read half of the journal: ``igneous fleet status|trace|top`` load
every worker's segments from the bucket and answer the questions tqdm
bars cannot — where does fleet wall-clock go per stage (p50/p95), how
much of it is stall vs work, which tasks are slowest, how many zombie
fences / DLQ promotions fired, and what is one task's full lineage.
"""

from __future__ import annotations

import time
from collections import defaultdict
from typing import Iterable, Iterator, List, Optional

from . import journal as journal_mod

# stage timer names whose spans measure waiting, not work: the stall
# ratio `igneous fleet status` reports is stall_time / (stall + work)
STALL_MARKERS = ("stall_s", "queue.wait")


def load(journal_path: str) -> List[dict]:
  """Every RAW record (all segments, rollup coverage ignored) — the
  per-span detail path (`fleet trace`, Perfetto export)."""
  return list(journal_mod.read_records(journal_path))


def load_effective(journal_path: str) -> List[dict]:
  """Rollup records + raw records from uncovered segments — the
  O(windows) aggregate path (`fleet status|top|check|watch`,
  ``queue_eta``). Identical to :func:`load` when no rollups exist."""
  from . import rollup

  return rollup.load_effective(journal_path)


def _percentile(sorted_vals: List[float], q: float) -> float:
  if not sorted_vals:
    return 0.0
  idx = min(int(q * (len(sorted_vals) - 1) + 0.5), len(sorted_vals) - 1)
  return sorted_vals[idx]


def _is_stall(name: str) -> bool:
  return any(m in name for m in STALL_MARKERS)


def status(records: Iterable[dict]) -> dict:
  """Merged fleet aggregates: per-stage p50/p95/total, stall ratio,
  counter totals (zombie/DLQ/retries), workers seen, task throughput.

  Accepts raw span/counters records AND ``rollup`` records (windowed
  compactions) interchangeably: rollups carry exact per-stage count/sum
  plus capped duration samples, so totals/counts match the raw view
  exactly and percentiles match whenever the sample cap wasn't hit."""
  # per stage: [count, sum, samples] — raw spans contribute 1/dur/dur,
  # rollup stages contribute their exact count/sum + capped samples
  stage_stats: dict = defaultdict(lambda: [0, 0.0, []])
  task_spans = []
  workers = set()
  counters_by_worker: dict = {}
  ts_min, ts_max = None, None

  def _take_span_times(ts, dur):
    nonlocal ts_min, ts_max
    ts_min = ts if ts_min is None else min(ts_min, ts)
    ts_max = max(ts_max or 0.0, ts + dur)

  def _take_task(rec):
    ts, dur = rec.get("ts"), rec.get("dur")
    if ts is None or dur is None:
      return
    if rec.get("worker"):
      workers.add(rec["worker"])
    _take_span_times(ts, dur)
    st = stage_stats["task"]
    st[0] += 1
    st[1] += float(dur)
    st[2].append(float(dur))
    task_spans.append(rec)

  for rec in records:
    kind = rec.get("kind")
    if kind == "rollup":
      if rec.get("ts_min") is not None:
        _take_span_times(rec["ts_min"], 0.0)
      if rec.get("ts_max") is not None:
        _take_span_times(rec["ts_max"], 0.0)
      for wid in (rec.get("workers") or {}):
        workers.add(wid)
      for name, s in (rec.get("stages") or {}).items():
        st = stage_stats[name]
        st[0] += int(s.get("count", 0))
        st[1] += float(s.get("sum", 0.0))
        st[2].extend(float(d) for d in s.get("durs", ()))
      for t in rec.get("tasks") or ():
        _take_task(t)
      continue
    worker = rec.get("worker", "local")
    workers.add(worker)
    if kind == "counters":
      # cumulative per process: the LAST snapshot per worker is the truth
      prev = counters_by_worker.get(worker)
      if prev is None or rec.get("ts", 0) >= prev.get("ts", 0):
        counters_by_worker[worker] = rec
      continue
    if kind != "span":
      continue
    ts, dur = rec.get("ts"), rec.get("dur")
    if ts is None or dur is None:
      continue
    name = rec.get("name", "span")
    if name == "task":
      _take_task(rec)
      continue
    _take_span_times(ts, dur)
    st = stage_stats[name]
    st[0] += 1
    st[1] += float(dur)
    st[2].append(float(dur))

  stages = {}
  stall_total = work_total = 0.0
  for name, (count, total, samples) in stage_stats.items():
    samples.sort()
    if count == len(samples):
      # no sample cap bit: recompute from the sorted list so the output
      # is bit-identical whether the spans arrived raw or via rollups
      total = sum(samples)
    stages[name] = {
      "count": count,
      "total_s": round(total, 3),
      "p50_ms": round(_percentile(samples, 0.50) * 1e3, 2),
      "p95_ms": round(_percentile(samples, 0.95) * 1e3, 2),
    }
    if _is_stall(name):
      stall_total += total
    elif name != "task":  # task spans contain the stage spans; don't double
      work_total += total

  counters: dict = defaultdict(int)
  for rec in counters_by_worker.values():
    for k, v in (rec.get("counters") or {}).items():
      counters[k] += v

  window = (ts_max - ts_min) if ts_min is not None else 0.0
  tasks_ok = [r for r in task_spans if not r.get("error")]
  return {
    "workers": sorted(workers),
    "window_sec": round(window, 2),
    "tasks": len(task_spans),
    "tasks_failed": len(task_spans) - len(tasks_ok),
    "tasks_per_sec": round(len(tasks_ok) / window, 3) if window > 0 else None,
    "stall_ratio": (
      round(stall_total / (stall_total + work_total), 3)
      if stall_total + work_total > 0 else None
    ),
    "stages": dict(sorted(stages.items())),
    "zombie_fences": sum(
      v for k, v in counters.items() if k.startswith("zombie.")
    ),
    "dlq_promoted": counters.get("dlq.promoted", 0),
    "tasks_failed_counter": counters.get("tasks.failed", 0),
    "counters": dict(sorted(counters.items())),
  }


def iter_task_spans(records: Iterable[dict]) -> Iterator[dict]:
  """Task span records from raw segments AND rollup windows (rollups
  keep task spans verbatim, so both views yield identical records)."""
  for r in records:
    kind = r.get("kind")
    if kind == "rollup":
      for t in r.get("tasks") or ():
        yield t
    elif kind == "span" and r.get("name") == "task":
      yield r


def slowest_tasks(records: Iterable[dict], n: int = 10) -> List[dict]:
  """``igneous fleet top``: the n slowest task executions, by trace."""
  tasks = [r for r in iter_task_spans(records) if r.get("dur") is not None]
  tasks.sort(key=lambda r: -r["dur"])
  out = []
  for rec in tasks[:n]:
    out.append({
      "trace_id": rec.get("trace"),
      "task": rec.get("task", "?"),
      "dur_s": round(rec["dur"], 3),
      "worker": rec.get("worker", "local"),
      "attempt": rec.get("attempt"),
      "error": rec.get("error"),
    })
  return out


def trace_records(records: Iterable[dict], trace_id: str) -> List[dict]:
  """Every span of one trace, time-ordered (the merged lineage)."""
  spans = [
    r for r in records
    if r.get("kind", "span") == "span" and r.get("trace") == trace_id
  ]
  spans.sort(key=lambda r: (r.get("ts") or 0.0))
  return spans


def render_trace(spans: List[dict]) -> List[str]:
  """One text line per span, children indented under their parent —
  the terminal view of `igneous fleet trace` (the Perfetto export is the
  graphical one)."""
  by_id = {r.get("span"): r for r in spans if r.get("span")}

  def depth(rec, seen=()):
    parent = rec.get("parent")
    if not parent or parent not in by_id or parent in seen:
      return 0
    return 1 + depth(by_id[parent], seen + (rec.get("span"),))

  t0 = min((r.get("ts") or 0.0) for r in spans) if spans else 0.0
  lines = []
  for rec in spans:
    pad = "  " * depth(rec)
    extras = []
    if rec.get("attempt") is not None:
      extras.append(f"attempt={rec['attempt']}")
    if rec.get("task"):
      extras.append(rec["task"])
    if rec.get("error"):
      extras.append(f"ERROR={rec['error']}")
    if rec.get("worker"):
      extras.append(f"@{rec['worker']}")
    lines.append(
      f"{(rec.get('ts', 0.0) - t0) * 1e3:9.1f}ms "
      f"{pad}{rec.get('name', 'span')} "
      f"[{(rec.get('dur') or 0.0) * 1e3:.1f}ms]"
      + (" " + " ".join(extras) if extras else "")
    )
  return lines


def worker_rates(records: Iterable[dict], window_sec: Optional[float] = None,
                 now: Optional[float] = None) -> dict:
  """Per-worker throughput (successful task spans per BUSY second, i.e.
  1/mean task duration) mined from the journal — the relative-speed
  signal behind throughput-weighted partitioning (ISSUE 17):
  ``page_partition(weights=...)`` hands a slow host proportionally less
  of the page table up front, and the campaign runner projects a range
  lease's tail against the fleet p95 of these rates to decide when to
  speculate. Busy-time (not wall-clock) rates, so an idle-but-fast
  worker isn't mistaken for a straggler. ``window_sec`` restricts to
  recent spans (skew-guarded like :func:`journal_throughput`)."""
  now = time.time() if now is None else now
  per: dict = defaultdict(lambda: [0, 0.0])  # worker -> [n_ok, busy_s]
  for rec in iter_task_spans(records):
    if rec.get("error"):
      continue
    ts, dur = rec.get("ts"), rec.get("dur")
    if ts is None or dur is None or dur <= 0:
      continue
    if window_sec is not None:
      if ts < now - window_sec or ts > now + CLOCK_SKEW_TOLERANCE_SEC:
        continue
    acc = per[rec.get("worker") or "local"]
    acc[0] += 1
    acc[1] += float(dur)
  return {
    w: n / busy for w, (n, busy) in per.items() if n > 0 and busy > 0
  }


# a segment timestamped further than this into the future is a skewed
# worker clock, not data: counting it would stretch the throughput
# window to a time that hasn't happened yet
CLOCK_SKEW_TOLERANCE_SEC = 300.0


def journal_throughput(journal_path: str, window_sec: float = 600.0,
                       now: Optional[float] = None) -> Optional[dict]:
  """Fleet tasks/sec derived from recent journal task spans (the
  ``queue status --eta`` journal path), reading rollups + uncovered raw
  segments (O(windows), not O(all segments)). None when no segments
  exist, when no task span falls inside the window (empty or expired —
  the fleet stopped more than ``window_sec`` ago), or when every
  in-window span is clock-skewed into the future — callers fall back to
  live sampling in each case."""
  now = time.time() if now is None else now
  durs = []
  ts_min = ts_max = None
  records = load_effective(journal_path)
  if not records:
    return None
  for rec in iter_task_spans(records):
    if rec.get("error"):
      continue
    ts = rec.get("ts")
    if ts is None or ts < now - window_sec:
      continue  # expired: finished before the window opened
    if ts > now + CLOCK_SKEW_TOLERANCE_SEC:
      continue  # skewed worker clock: a "future" task proves nothing
    durs.append(rec)
    end = ts + (rec.get("dur") or 0.0)
    ts_min = ts if ts_min is None else min(ts_min, ts)
    ts_max = end if ts_max is None else max(ts_max, end)
  if not durs or ts_max is None or ts_max <= ts_min:
    return None
  window = ts_max - ts_min
  return {
    "tasks": len(durs),
    "window_sec": round(window, 2),
    "tasks_per_sec": len(durs) / window,
  }
