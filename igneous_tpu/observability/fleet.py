"""Fleet-level aggregation over merged journal segments.

The read half of the journal: ``igneous fleet status|trace|top`` load
every worker's segments from the bucket and answer the questions tqdm
bars cannot — where does fleet wall-clock go per stage (p50/p95), how
much of it is stall vs work, which tasks are slowest, how many zombie
fences / DLQ promotions fired, and what is one task's full lineage.
"""

from __future__ import annotations

import time
from collections import defaultdict
from typing import Iterable, List, Optional

from . import journal as journal_mod

# stage timer names whose spans measure waiting, not work: the stall
# ratio `igneous fleet status` reports is stall_time / (stall + work)
STALL_MARKERS = ("stall_s", "queue.wait")


def load(journal_path: str) -> List[dict]:
  return list(journal_mod.read_records(journal_path))


def _percentile(sorted_vals: List[float], q: float) -> float:
  if not sorted_vals:
    return 0.0
  idx = min(int(q * (len(sorted_vals) - 1) + 0.5), len(sorted_vals) - 1)
  return sorted_vals[idx]


def _is_stall(name: str) -> bool:
  return any(m in name for m in STALL_MARKERS)


def status(records: Iterable[dict]) -> dict:
  """Merged fleet aggregates: per-stage p50/p95/total, stall ratio,
  counter totals (zombie/DLQ/retries), workers seen, task throughput."""
  stage_durs: dict = defaultdict(list)
  task_spans = []
  workers = set()
  counters_by_worker: dict = {}
  ts_min, ts_max = None, None

  for rec in records:
    kind = rec.get("kind")
    worker = rec.get("worker", "local")
    workers.add(worker)
    if kind == "counters":
      # cumulative per process: the LAST snapshot per worker is the truth
      prev = counters_by_worker.get(worker)
      if prev is None or rec.get("ts", 0) >= prev.get("ts", 0):
        counters_by_worker[worker] = rec
      continue
    if kind != "span":
      continue
    ts, dur = rec.get("ts"), rec.get("dur")
    if ts is None or dur is None:
      continue
    ts_min = ts if ts_min is None else min(ts_min, ts)
    ts_max = max(ts_max or 0.0, ts + dur)
    name = rec.get("name", "span")
    stage_durs[name].append(float(dur))
    if name == "task":
      task_spans.append(rec)

  stages = {}
  stall_total = work_total = 0.0
  for name, durs in stage_durs.items():
    durs.sort()
    total = sum(durs)
    stages[name] = {
      "count": len(durs),
      "total_s": round(total, 3),
      "p50_ms": round(_percentile(durs, 0.50) * 1e3, 2),
      "p95_ms": round(_percentile(durs, 0.95) * 1e3, 2),
    }
    if _is_stall(name):
      stall_total += total
    elif name != "task":  # task spans contain the stage spans; don't double
      work_total += total

  counters: dict = defaultdict(int)
  for rec in counters_by_worker.values():
    for k, v in (rec.get("counters") or {}).items():
      counters[k] += v

  window = (ts_max - ts_min) if ts_min is not None else 0.0
  tasks_ok = [r for r in task_spans if not r.get("error")]
  return {
    "workers": sorted(workers),
    "window_sec": round(window, 2),
    "tasks": len(task_spans),
    "tasks_failed": len(task_spans) - len(tasks_ok),
    "tasks_per_sec": round(len(tasks_ok) / window, 3) if window > 0 else None,
    "stall_ratio": (
      round(stall_total / (stall_total + work_total), 3)
      if stall_total + work_total > 0 else None
    ),
    "stages": dict(sorted(stages.items())),
    "zombie_fences": sum(
      v for k, v in counters.items() if k.startswith("zombie.")
    ),
    "dlq_promoted": counters.get("dlq.promoted", 0),
    "tasks_failed_counter": counters.get("tasks.failed", 0),
    "counters": dict(sorted(counters.items())),
  }


def slowest_tasks(records: Iterable[dict], n: int = 10) -> List[dict]:
  """``igneous fleet top``: the n slowest task executions, by trace."""
  tasks = [
    r for r in records
    if r.get("kind") == "span" and r.get("name") == "task"
    and r.get("dur") is not None
  ]
  tasks.sort(key=lambda r: -r["dur"])
  out = []
  for rec in tasks[:n]:
    out.append({
      "trace_id": rec.get("trace"),
      "task": rec.get("task", "?"),
      "dur_s": round(rec["dur"], 3),
      "worker": rec.get("worker", "local"),
      "attempt": rec.get("attempt"),
      "error": rec.get("error"),
    })
  return out


def trace_records(records: Iterable[dict], trace_id: str) -> List[dict]:
  """Every span of one trace, time-ordered (the merged lineage)."""
  spans = [
    r for r in records
    if r.get("kind", "span") == "span" and r.get("trace") == trace_id
  ]
  spans.sort(key=lambda r: (r.get("ts") or 0.0))
  return spans


def render_trace(spans: List[dict]) -> List[str]:
  """One text line per span, children indented under their parent —
  the terminal view of `igneous fleet trace` (the Perfetto export is the
  graphical one)."""
  by_id = {r.get("span"): r for r in spans if r.get("span")}

  def depth(rec, seen=()):
    parent = rec.get("parent")
    if not parent or parent not in by_id or parent in seen:
      return 0
    return 1 + depth(by_id[parent], seen + (rec.get("span"),))

  t0 = min((r.get("ts") or 0.0) for r in spans) if spans else 0.0
  lines = []
  for rec in spans:
    pad = "  " * depth(rec)
    extras = []
    if rec.get("attempt") is not None:
      extras.append(f"attempt={rec['attempt']}")
    if rec.get("task"):
      extras.append(rec["task"])
    if rec.get("error"):
      extras.append(f"ERROR={rec['error']}")
    if rec.get("worker"):
      extras.append(f"@{rec['worker']}")
    lines.append(
      f"{(rec.get('ts', 0.0) - t0) * 1e3:9.1f}ms "
      f"{pad}{rec.get('name', 'span')} "
      f"[{(rec.get('dur') or 0.0) * 1e3:.1f}ms]"
      + (" " + " ".join(extras) if extras else "")
    )
  return lines


def journal_throughput(journal_path: str,
                       window_sec: float = 600.0) -> Optional[dict]:
  """Fleet tasks/sec derived from recent journal task spans (the
  ``queue status --eta`` journal path). None when no segments or no task
  spans exist — callers fall back to live sampling."""
  now = time.time()
  durs = []
  ts_min = ts_max = None
  found = False
  for rec in journal_mod.read_records(journal_path):
    found = True
    if rec.get("kind") != "span" or rec.get("name") != "task":
      continue
    if rec.get("error"):
      continue
    ts = rec.get("ts")
    if ts is None or ts < now - window_sec:
      continue
    durs.append(rec)
    end = ts + (rec.get("dur") or 0.0)
    ts_min = ts if ts_min is None else min(ts_min, ts)
    ts_max = end if ts_max is None else max(ts_max, end)
  if not found or not durs or ts_max is None or ts_max <= ts_min:
    return None
  window = ts_max - ts_min
  return {
    "tasks": len(durs),
    "window_sec": round(window, 2),
    "tasks_per_sec": len(durs) / window,
  }
