"""igneous_tpu: a TPU-native framework for Neuroglancer Precomputed pipelines.

Capabilities mirror seung-lab/igneous (downsampling, transfer, meshing,
skeletonization, CCL, contrast, voxel stats, queue/CLI tooling) with the
per-chunk compute implemented as JAX/XLA/Pallas device programs batched over
a TPU mesh, and the queue/object-store fabric as first-party host code.
"""

from .lib import Bbox, Vec
from .volume import Volume, CloudVolume
from .storage import CloudFiles

__version__ = "0.1.0"
