"""Environment configuration (reference igneous/secrets.py:13-16 parity).

Workers read these so container CMDs stay declarative (Dockerfile /
deployment.yaml set them): QUEUE_URL (the reference's SQS_URL analog),
LEASE_SECONDS, and optional cloud credentials directory.
"""

from __future__ import annotations

import os

from .analysis import knobs


def queue_url() -> "str | None":
  return os.environ.get("QUEUE_URL") or os.environ.get("SQS_URL")


def sqs_region_name() -> "str | None":
  return os.environ.get("SQS_REGION_NAME")


def sqs_endpoint_url() -> "str | None":
  return os.environ.get("SQS_ENDPOINT_URL")


def lease_seconds() -> int:
  return int(os.environ.get("LEASE_SECONDS", 600))


def heartbeat_seconds() -> "float | None":
  """Lease-renewal interval for workers. None (unset) lets the heartbeat
  default to lease/3; 0 disables renewal entirely."""
  return knobs.opt_float("IGNEOUS_HEARTBEAT_SEC")


def secrets_dir() -> str:
  return knobs.get_str("IGNEOUS_TPU_SECRETS") or os.path.expanduser(
    "~/.cloudfiles/secrets"
  )
