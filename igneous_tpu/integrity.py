"""Data-integrity plane (ISSUE 16): the checksummed write envelope.

Campaign outputs live in object storage for months between the write
and the read that discovers a torn upload or a bit-flipped block — by
which point the producing task, its queue, and its worker are long
gone. This module closes that loop:

* **Write envelope** — every task-output put records a blake2b-128
  digest of the *stored wire bytes* (post-compression, the exact bytes
  at rest) into per-prefix manifest sidecars under
  ``<layer>/integrity/manifests/<top-level-dir>/``. Records are
  buffered per layer and flushed as write-once JSONL segments, the same
  append-only discipline as journal segments: a segment is never
  rewritten, merges are last-writer-wins on the record timestamp.
  ``IGNEOUS_INTEGRITY=off`` restores the bytes-only write path.

* **Quarantine ledger** — read-path corruption (decode failures,
  digest mismatches) files the bad object reference under
  ``integrity/quarantine/`` immediately (no batching: a corrupt read
  is rare and must survive a crash) and ticks ``integrity.*``
  counters. Quarantine never raises: it rides exception paths.

* **Verify-after-write** — ``IGNEOUS_INTEGRITY_VERIFY_AFTER_WRITE=1``
  reads every put back and compares digests before the put returns,
  converting a torn write into an immediate task failure that the
  retry/DLQ machinery already knows how to handle.

``igneous audit`` (tasks/audit.py) replays the campaign's chunk grid
against these manifests; audit findings feed repair-task creation
(task_creation/audit.py) so a damaged campaign heals itself.

Exemptions: the envelope covers payload objects, not metadata.
``integrity/`` sidecars themselves (recursion), ``info``/``provenance``
singletons (rewritten in place — a "latest digest" is meaningless for
a write-once envelope), and ``.json``/``.jsonl`` keys (journal
segments, reports — append-structured, self-describing) are skipped.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from hashlib import blake2b
from typing import Dict, List, Optional

from . import telemetry
from .analysis import knobs

# every envelope artifact lives under this top-level prefix inside the
# layer; byte-compare tooling (chaos soak, transfers) excludes it
INTEGRITY_PREFIX = "integrity"


def digest_hex(data) -> str:
  """blake2b-128 hex of the stored wire bytes — same digest family as
  the chunk decode cache key and serve's strong ETag, so one digest
  value is comparable across all three planes."""
  return blake2b(bytes(data), digest_size=16).hexdigest()


class CorruptChunkError(Exception):
  """A stored object failed decode or digest verification.

  Deliberately NOT an ``EmptyVolumeError``/``IOError`` subclass: callers
  that tolerate missing chunks (fill_missing) must not accidentally
  tolerate corrupt ones."""

  def __init__(self, cloudpath: str, key: str, reason: str,
               expected: Optional[str] = None, actual: Optional[str] = None):
    self.cloudpath = cloudpath
    self.key = key
    self.reason = reason
    self.expected = expected
    self.actual = actual
    msg = f"corrupt object {key} in {cloudpath}: {reason}"
    if expected is not None:
      msg += f" (expected digest {expected}, got {actual})"
    super().__init__(msg)


def enabled() -> bool:
  return knobs.get_bool("IGNEOUS_INTEGRITY")


def exempt(key: str) -> bool:
  """True for keys the envelope does not cover (see module docstring)."""
  if key.startswith(INTEGRITY_PREFIX + "/"):
    return True
  base = os.path.basename(key)
  if base in ("info", "provenance") or base.startswith("provenance"):
    return True
  return base.endswith(".json") or base.endswith(".jsonl")


class ManifestRecorder:
  """Buffers (stored key → digest) records per layer, flushing them as
  write-once JSONL segments grouped by the key's top-level directory
  (the mip dir for image layers) so an audit of one mip loads only that
  prefix. One process-global instance; thread-safe."""

  def __init__(self):
    self._lock = threading.Lock()
    self._buf: Dict[str, List[dict]] = {}
    self._seq = 0

  def record(self, cloudpath: str, stored_key: str, payload: bytes) -> Optional[str]:
    """Buffer a manifest record for a completed put. Returns the digest
    hex (for verify-after-write) or None if the key is exempt."""
    if not enabled() or exempt(stored_key):
      return None
    dig = digest_hex(payload)
    rec = {
      "key": stored_key,
      "digest": dig,
      "n": len(payload),
      "ts": round(time.time(), 6),
    }
    telemetry.incr("integrity.records")
    flush_now = None
    cloudpath = cloudpath.rstrip("/")
    with self._lock:
      buf = self._buf.setdefault(cloudpath, [])
      buf.append(rec)
      if len(buf) >= max(1, knobs.get_int("IGNEOUS_INTEGRITY_BATCH")):
        flush_now, self._buf[cloudpath] = buf, []
    if flush_now:
      self._write_segments(cloudpath, flush_now, swallow=False)
    return dig

  def flush(self, cloudpath: Optional[str] = None, swallow: bool = False):
    """Flush buffered records (one layer, or all). ``swallow=True`` is
    the atexit/backstop mode: a layer whose file:// root is gone (tests
    tearing down tempdirs) is dropped, and write errors are ignored —
    the backstop must never turn a clean exit into a traceback."""
    with self._lock:
      if cloudpath is not None:
        items = [(cloudpath.rstrip("/"), self._buf.pop(cloudpath.rstrip("/"), []))]
      else:
        items = list(self._buf.items())
        self._buf = {}
    for path, records in items:
      if not records:
        continue
      if swallow and _file_root_gone(path):
        continue
      self._write_segments(path, records, swallow=swallow)

  def _write_segments(self, cloudpath: str, records: List[dict], swallow: bool):
    from .storage import CloudFiles

    groups: Dict[str, List[dict]] = {}
    for rec in records:
      top = rec["key"].split("/", 1)[0] if "/" in rec["key"] else "_root"
      groups.setdefault(top, []).append(rec)
    try:
      cf = CloudFiles(cloudpath)
      for top, recs in groups.items():
        with self._lock:
          self._seq += 1
          seq = self._seq
        name = (
          f"{INTEGRITY_PREFIX}/manifests/{top}/"
          f"seg_w{os.getpid()}_{seq:06d}.jsonl"
        )
        body = "".join(json.dumps(r, sort_keys=True) + "\n" for r in recs)
        cf.put(name, body.encode("utf8"), compress=None)
        telemetry.incr("integrity.manifest_segments")
    except Exception:
      if not swallow:
        raise


def _file_root_gone(cloudpath: str) -> bool:
  from .storage import extract_path

  pth = extract_path(cloudpath)
  return pth.protocol == "file" and not os.path.isdir(pth.path)


_RECORDER = ManifestRecorder()


def record_put(cloudpath: str, stored_key: str, payload: bytes, backend=None):
  """Storage-layer hook: called by ``CloudFiles.put``/``put_stored``
  after a successful backend write. Records the manifest entry and,
  under ``IGNEOUS_INTEGRITY_VERIFY_AFTER_WRITE``, reads the object back
  to prove the stored bytes match before the put returns."""
  dig = _RECORDER.record(cloudpath, stored_key, payload)
  if dig is None or backend is None:
    return
  if not knobs.get_bool("IGNEOUS_INTEGRITY_VERIFY_AFTER_WRITE"):
    return
  back = backend.get(stored_key)
  actual = digest_hex(back) if back is not None else None
  if actual != dig:
    telemetry.incr("integrity.verify_failed")
    quarantine(cloudpath, stored_key, "verify-after-write mismatch")
    raise CorruptChunkError(
      cloudpath, stored_key, "verify-after-write mismatch",
      expected=dig, actual=actual,
    )


def flush_all(swallow: bool = False):
  """Flush every buffered manifest record. Workers call this on drain
  (alongside the journal last-will); audits call it before reading."""
  _RECORDER.flush(swallow=swallow)


def flush(cloudpath: str):
  _RECORDER.flush(cloudpath)


atexit.register(flush_all, True)


def load_manifest(cloudpath: str, prefix: Optional[str] = None) -> Dict[str, dict]:
  """Merge manifest segments into {stored key → record}, last-writer-wins
  on the record timestamp (a healed chunk's re-put supersedes the
  original digest). ``prefix`` restricts the load to one top-level key
  directory (e.g. a mip dir)."""
  from .storage import CloudFiles

  cf = CloudFiles(cloudpath)
  base = f"{INTEGRITY_PREFIX}/manifests/"
  if prefix:
    base += prefix.strip("/") + "/"
  out: Dict[str, dict] = {}
  for seg in sorted(cf.list(base)):
    if not seg.endswith(".jsonl"):
      continue
    raw = cf.get(seg)
    if raw is None:
      continue
    for line in raw.splitlines():
      if not line.strip():
        continue
      rec = json.loads(line)
      prev = out.get(rec["key"])
      if prev is None or rec["ts"] >= prev["ts"]:
        out[rec["key"]] = rec
  return out


_QUARANTINE_LOCK = threading.Lock()
_QUARANTINE_SEQ = 0


def quarantine(cloudpath: str, key: str, reason: str):
  """File a corrupt-object reference under ``integrity/quarantine/``.
  Written immediately (one record per file — corruption is rare, and the
  ledger must survive the crash the corrupt read may be about to cause)
  and never raises: this rides exception paths."""
  global _QUARANTINE_SEQ
  if not enabled():
    return
  from .storage import CloudFiles

  telemetry.incr("integrity.quarantined")
  with _QUARANTINE_LOCK:
    _QUARANTINE_SEQ += 1
    seq = _QUARANTINE_SEQ
  rec = {
    "key": key,
    "reason": reason,
    "ts": round(time.time(), 6),
  }
  try:
    CloudFiles(cloudpath).put(
      f"{INTEGRITY_PREFIX}/quarantine/q_w{os.getpid()}_{seq:06d}.jsonl",
      (json.dumps(rec, sort_keys=True) + "\n").encode("utf8"),
      compress=None,
    )
  except Exception:
    pass


def load_quarantine(cloudpath: str) -> List[dict]:
  from .storage import CloudFiles

  cf = CloudFiles(cloudpath)
  out = []
  for seg in sorted(cf.list(f"{INTEGRITY_PREFIX}/quarantine/")):
    raw = cf.get(seg)
    if raw is None:
      continue
    for line in raw.splitlines():
      if line.strip():
        out.append(json.loads(line))
  return out
