"""Fleet-wide persistent compile cache for AOT executables (ISSUE 19).

Every worker in a fleet pays the identical XLA compile tax for the same
(kernel, signature) — the ISSUE 7 recompile ledger measures exactly this
waste, and ISSUE 12's one-signature-per-kernel paging means a big
campaign compiles the *same* handful of programs once per worker. This
module serializes each AOT executable (``jax.experimental
.serialize_executable``) to any CloudFiles backend the moment worker 1
compiles it, so worker N>1 fetches instead of compiling.

Key anatomy — an entry is only valid for the exact compile context:

    kernel name + input signature (shapes/dtypes/treedef repr)
    + kernel variant (the closure config a name alone can't capture:
      pyramid factors, CCL tile/algo/engine, EDT anisotropy/line block,
      infer model spec)
    + platform / device kind / device count / process count / mesh axes
    + jax AND jaxlib versions

All of it is digested (blake2b) into the storage key, so version skew or
a different topology is a *natural miss* — never a wrong executable.

Entry wire format::

    b"IGXC0001" | u32 header_len | header JSON | body

where the header carries the full key meta, the body's blake2b digest
and length, and the *producer's measured compile seconds* (the number a
hit credits to the fleet's compile-seconds-saved rollup), and the body
is a pickle of ``serialize_executable.serialize``'s (blob, in_tree,
out_tree) triple.

Degradation matrix — the cache can only ever fall back to compiling:

========================  =============================================
condition                 behavior
========================  =============================================
knob unset                executors compile exactly as before
entry absent              miss counter, compile, write-once put
version/topology skew     different digest → natural miss (as above)
truncated / bit-flipped   quarantined under ``quarantine/``, corrupt
entry                     counter, fallback compile re-puts a good copy
concurrent writers        write-once put (exists-check + the backend's
                          tmp+rename atomic rename) converges on one
storage backend error     error counter, fallback compile
========================  =============================================

Telemetry: ``device.compile_cache.hit|miss|put|corrupt`` counters and a
``device.compile_cache.hit`` span per fetch; a hit ticks the signature
into the ledger seen-set WITHOUT ``device.recompiles`` (warm fleets must
not trip the recompile-storm anomaly). ``igneous fleet devices`` rolls
the per-worker stats up into fleet-wide compile-seconds-saved.
"""

from __future__ import annotations

import hashlib
import json
import pickle
import re
import time
from typing import Any, Callable, Optional, Tuple

from .analysis import knobs
from .observability import device as device_telemetry
from .observability import metrics

CACHE_ENV = "IGNEOUS_COMPILE_CACHE"
MAGIC = b"IGXC0001"
ENTRY_PREFIX = "executables/"
QUARANTINE_PREFIX = "quarantine/"
_DIGEST_SIZE = 20


class CompileCacheError(Exception):
  """An entry failed verification (magic/header/digest/meta/deserialize).
  Always recoverable: the reader quarantines and falls back to compile."""


def _sanitize(name: str) -> str:
  return re.sub(r"[^A-Za-z0-9._\[\]-]+", "_", str(name)) or "kernel"


def versions() -> Tuple[str, str]:
  import jax

  try:
    import jaxlib

    jaxlib_v = getattr(jaxlib, "__version__", "")
  except Exception:
    jaxlib_v = ""
  return str(jax.__version__), str(jaxlib_v)


def topology(mesh=None) -> dict:
  """Device-topology component of the cache key: an executable is only
  valid on the platform/device-kind/count (and mesh layout + process
  count) it was compiled for."""
  import jax

  try:
    devs = list(mesh.devices.flat) if mesh is not None else jax.devices()
  except Exception:
    devs = []
  try:
    procs = int(jax.process_count())
  except Exception:
    procs = 1
  topo = {
    "platform": str(devs[0].platform) if devs else "none",
    "device_kind": str(devs[0].device_kind) if devs else "none",
    "device_count": len(devs),
    "processes": procs,
  }
  if mesh is not None:
    topo["mesh_axes"] = list(mesh.axis_names)
    topo["mesh_shape"] = [int(s) for s in mesh.devices.shape]
  return topo


def entry_meta(kernel: str, signature, mesh=None, variant=None) -> dict:
  """The full cache key as a JSON-able dict (digested by entry_key)."""
  jax_v, jaxlib_v = versions()
  return {
    "kernel": str(kernel),
    "signature": repr(signature),
    "variant": repr(variant) if variant is not None else None,
    "jax": jax_v,
    "jaxlib": jaxlib_v,
    **topology(mesh),
  }


def entry_key(meta: dict) -> str:
  digest = hashlib.blake2b(
    json.dumps(meta, sort_keys=True).encode("utf8"),
    digest_size=_DIGEST_SIZE,
  ).hexdigest()
  return f"{ENTRY_PREFIX}{_sanitize(meta['kernel'])}/{digest}.bin"


def encode_entry(meta: dict, compiled, compile_s: float) -> bytes:
  """Serialize one AOT executable into the self-verifying wire format."""
  from jax.experimental import serialize_executable

  blob, in_tree, out_tree = serialize_executable.serialize(compiled)
  body = pickle.dumps(
    (blob, in_tree, out_tree), protocol=pickle.HIGHEST_PROTOCOL
  )
  header = json.dumps(
    {
      "meta": meta,
      "body_digest": hashlib.blake2b(
        body, digest_size=_DIGEST_SIZE
      ).hexdigest(),
      "body_len": len(body),
      "compile_s": round(float(compile_s), 6),
      "created": time.time(),
    },
    sort_keys=True,
  ).encode("utf8")
  return MAGIC + len(header).to_bytes(4, "big") + header + body


def decode_entry(data: bytes, meta: dict):
  """(compiled, header) after full verification; raises CompileCacheError
  on any corruption, truncation, or key mismatch — never returns a
  partially-verified executable."""
  hstart = len(MAGIC) + 4
  if len(data) < hstart or data[: len(MAGIC)] != MAGIC:
    raise CompileCacheError("bad magic")
  hlen = int.from_bytes(data[len(MAGIC): hstart], "big")
  hend = hstart + hlen
  if hend > len(data):
    raise CompileCacheError("truncated header")
  try:
    header = json.loads(data[hstart:hend].decode("utf8"))
  except Exception as exc:
    raise CompileCacheError(f"unparseable header: {exc}")
  body = data[hend:]
  if len(body) != int(header.get("body_len", -1)):
    raise CompileCacheError("truncated body")
  digest = hashlib.blake2b(body, digest_size=_DIGEST_SIZE).hexdigest()
  if digest != header.get("body_digest"):
    raise CompileCacheError("body digest mismatch")
  if header.get("meta") != meta:
    # the digest key matched but the embedded meta did not: tampering or
    # a truncated-then-refilled write — never trust it
    raise CompileCacheError("key meta mismatch")
  try:
    from jax.experimental import serialize_executable

    blob, in_tree, out_tree = pickle.loads(body)
    compiled = serialize_executable.deserialize_and_load(
      blob, in_tree, out_tree
    )
  except Exception as exc:
    raise CompileCacheError(f"deserialize failed: {exc}")
  return compiled, header


class CompileCache:
  """Persistent executable store rooted at a CloudFiles path.

  Entries live under ``executables/<kernel>/<digest>.bin``; failed
  verifications are moved to ``quarantine/`` (self-healing: the next
  compile re-puts a good copy); tuned autotuner configs live alongside
  under ``tuned/`` (see :mod:`igneous_tpu.tune`)."""

  def __init__(self, cloudpath: str):
    from .storage import CloudFiles

    self.cloudpath = cloudpath
    self.cf = CloudFiles(cloudpath)

  def get(self, meta: dict):
    """(compiled, header) on a fully-verified hit; None on miss. A
    corrupt or mismatched entry is quarantined and reads as a miss."""
    key = entry_key(meta)
    try:
      data = self.cf.get(key)
    except Exception:
      metrics.incr("device.compile_cache.error")
      return None
    if data is None:
      return None
    try:
      return decode_entry(bytes(data), meta)
    except CompileCacheError:
      self.quarantine(key, bytes(data))
      return None

  def put(self, meta: dict, compiled, compile_s: float) -> bool:
    """Write-once publish. False when the entry already exists (another
    worker won the race — the backend's tmp+rename makes simultaneous
    writers converge on exactly one complete object) or this executable
    cannot be serialized on this backend."""
    key = entry_key(meta)
    try:
      if self.cf.exists(key):
        return False
      self.cf.put(key, encode_entry(meta, compiled, compile_s),
                  compress=None)
    except Exception:
      metrics.incr("device.compile_cache.error")
      return False
    device_telemetry.LEDGER.record_cache_event("puts")
    return True

  def quarantine(self, key: str, data: bytes) -> None:
    """Move a failed entry aside (keeps the evidence, unblocks the slot
    so the fallback compile's re-put lands a good copy)."""
    dest = QUARANTINE_PREFIX + (
      key[len(ENTRY_PREFIX):] if key.startswith(ENTRY_PREFIX) else key
    )
    try:
      self.cf.put(dest, data, compress=None)
      self.cf.delete(key)
    except Exception:
      metrics.incr("device.compile_cache.error")
    device_telemetry.LEDGER.record_cache_event("corrupt")


# [resolved knob value, CompileCache-or-None]: one instance per process
# per cache root; re-resolved when the knob changes (tests).
_ACTIVE: list = [None, None]


def get_active() -> Optional[CompileCache]:
  spec = knobs.get_str(CACHE_ENV)
  if not spec:
    _ACTIVE[0] = _ACTIVE[1] = None
    return None
  if _ACTIVE[0] != spec:
    try:
      cache = CompileCache(spec)
    except Exception:
      metrics.incr("device.compile_cache.error")
      cache = None
    _ACTIVE[0], _ACTIVE[1] = spec, cache
  return _ACTIVE[1]


def reset_active() -> None:
  """Testing hook: drop the process's resolved cache instance."""
  _ACTIVE[0] = _ACTIVE[1] = None


def load_or_compile(
  kernel: str,
  signature,
  mesh,
  compile_fn: Callable[[], Any],
  variant=None,
):
  """The executors' single AOT compile entry point.

  With no cache configured — or no declared ``variant`` (the closure
  config that disambiguates same-name-same-signature kernels; a site
  that can't state its variant must not share executables) — this is
  exactly the pre-cache behavior: recompile tick + ``device.compile``
  span around ``compile_fn()``.

  With a cache: a verified hit deserializes the stored executable,
  enters the signature into the ledger seen-set *without* ticking
  ``device.recompiles`` (satellite: warm fleets must not trip the
  recompile-storm anomaly), ticks ``device.compile_cache.hit``, credits
  the producer's measured compile seconds as saved, and emits a
  ``device.compile_cache.hit`` span instead of ``device.compile``. Any
  miss/corruption/skew compiles as before, then publishes write-once.
  """
  cache = get_active() if variant is not None else None
  meta = None
  if cache is not None:
    try:
      meta = entry_meta(kernel, signature, mesh=mesh, variant=variant)
      t0 = time.perf_counter()
      hit = cache.get(meta)
      if hit is not None:
        compiled, header = hit
        fetch_s = time.perf_counter() - t0
        saved_s = float(header.get("compile_s") or 0.0)
        device_telemetry.LEDGER.note_signature(
          kernel, signature, cached=True
        )
        device_telemetry.LEDGER.record_cache_event(
          "hits", kernel=kernel, saved_s=saved_s, fetch_s=fetch_s
        )
        device_telemetry.record_span(
          "device.compile_cache.hit", fetch_s, kernel=kernel,
          saved_s=saved_s,
        )
        return compiled
      device_telemetry.LEDGER.record_cache_event("misses", kernel=kernel)
    except Exception:
      metrics.incr("device.compile_cache.error")
      meta = None  # half-built key state: skip the put too
  device_telemetry.LEDGER.note_signature(kernel, signature)
  t0 = time.perf_counter()
  with device_telemetry.compile_span(
    kernel, device_telemetry._devices_of(mesh)
  ):
    compiled = compile_fn()
  if cache is not None and meta is not None:
    cache.put(meta, compiled, time.perf_counter() - t0)
  return compiled
