"""Multilabel morphology — fastmorph parity (SURVEY.md §2.3).

Reference consumers: MeshTask hole filling
(/root/reference/igneous/tasks/mesh/mesh.py:211-246 fastmorph.fill_holes),
SkeletonTask hole filling (tasks/skeleton.py:268-301), dilation for
repairs. The TPU split mirrors the survey note: dilation is a max-pool
style stencil (device); flood-fill hole filling stays host (scipy).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from scipy import ndimage


@jax.jit
def _dilate_kernel(labels: jnp.ndarray) -> jnp.ndarray:
  """One 6-connected multilabel dilation step on device.

  Background voxels take the most frequent nonzero neighbor (ties to the
  axis order -z,+z,-y,+y,-x,+x); foreground voxels are unchanged —
  fastmorph.dilate semantics for labeled volumes."""
  shifts = []
  for axis in (0, 1, 2):
    for direction in (1, -1):
      rolled = jnp.roll(labels, direction, axis=axis)
      size = labels.shape[axis]
      coord = jax.lax.broadcasted_iota(jnp.int32, labels.shape, axis)
      valid = coord != (0 if direction == 1 else size - 1)
      shifts.append(jnp.where(valid, rolled, 0))

  n = len(shifts)
  best_v = jnp.zeros_like(labels)
  best_s = jnp.full(labels.shape, -1, dtype=jnp.int32)
  for i in range(n):
    counts = jnp.zeros(labels.shape, dtype=jnp.int32)
    for j in range(n):
      counts = counts + ((shifts[j] == shifts[i]) & (shifts[i] != 0)).astype(
        jnp.int32
      )
    score = jnp.where(shifts[i] != 0, counts * n - i, -1)
    take = score > best_s
    best_s = jnp.where(take, score, best_s)
    best_v = jnp.where(take, shifts[i], best_v)
  return jnp.where(labels != 0, labels, best_v)


def dilate(labels: np.ndarray, iterations: int = 1) -> np.ndarray:
  """Multilabel 6-connected dilation (device kernel per step)."""
  if labels.ndim != 3:
    raise ValueError("labels must be (x, y, z)")
  uniq, inv = np.unique(labels, return_inverse=True)
  dense = inv.astype(np.int32).reshape(labels.shape)
  if uniq[0] != 0:
    dense += 1
    # keep uniq's dtype: a bare [0] would promote uint64 to float64 and
    # collapse labels >= 2^53
    uniq = np.concatenate([np.zeros(1, dtype=uniq.dtype), uniq])
  dev = jnp.asarray(np.ascontiguousarray(dense.transpose(2, 1, 0)))
  for _ in range(int(iterations)):
    dev = _dilate_kernel(dev)
  out = np.asarray(dev).transpose(2, 1, 0)
  return uniq[out].astype(labels.dtype)


def erode(labels: np.ndarray, iterations: int = 1) -> np.ndarray:
  """Multilabel erosion: a voxel keeps its label only if all 6 neighbors
  share it (array borders count as background)."""
  out = labels.copy()
  for _ in range(int(iterations)):
    keep = np.ones(out.shape, dtype=bool)
    for axis in range(3):
      for sign in (1, -1):
        nb = np.roll(out, sign, axis=axis)
        sl = [slice(None)] * 3
        sl[axis] = 0 if sign == 1 else -1
        nb[tuple(sl)] = 0
        keep &= nb == out
    out = np.where(keep, out, 0).astype(labels.dtype)
  return out


def fill_holes(
  labels: np.ndarray,
  return_fill_count: bool = False,
  level: int = 1,
):
  """Fill cavities fully enclosed by a single label (fastmorph
  fill_holes semantics, host flood fill per label).

  Levels follow the reference's MeshTask ladder (mesh.py:211-246):
    1  fill enclosed cavities;
    2  same as 1 here (the reference's v2 cross-border repair needs
       neighbor-task context this local op does not have);
    3+ morphological closing first (dilate, fill, erode) so thin cracks
       into a cavity do not keep it open.
  """
  if level >= 3:
    grown = dilate(labels)
    filled = fill_holes(grown, level=1)
    closed = erode(filled)
    # closing may erase 1-voxel-thin structures: restore the originals
    closed = np.where(labels != 0, labels, closed).astype(labels.dtype)
    if return_fill_count:
      add = (closed != 0) & (labels == 0)
      vals, counts = np.unique(closed[add], return_counts=True)
      return closed, {int(v): int(c) for v, c in zip(vals, counts)}
    return closed
  out = labels.copy()
  fill_counts = {}
  # crop each label to its bbox: O(sum of label extents), not O(L x V)
  from .remap import renumber as _renumber

  dense, mapping = _renumber(labels)
  for new_id, sl in enumerate(
    ndimage.find_objects(dense.astype(np.int32)), start=1
  ):
    if sl is None:
      continue
    v = mapping[new_id]
    sub_mask = dense[sl] == new_id
    filled = ndimage.binary_fill_holes(sub_mask)
    add = filled & ~sub_mask & (out[sl] == 0)  # true background cavities only
    if add.any():
      out[sl][add] = v
      fill_counts[int(v)] = int(add.sum())
  if return_fill_count:
    return out, fill_counts
  return out
