"""TEASAR skeletonization: device EDT + host path tracing.

kimimaro-parity core (SURVEY.md §2.3; reference invocation at
/root/reference/igneous/tasks/skeleton.py:303-335). The split follows the
reference's own: the Euclidean distance transform (the per-voxel flops)
runs on device (ops.edt); the inherently-sequential Dijkstra/TEASAR path
extraction stays on host, built on scipy.sparse.csgraph's C dijkstra.

Algorithm per label (TEASAR with kimimaro's "rolling invalidation ball"):
  1. device EDT of the mask (anisotropic, black border).
  2. root = voxel farthest (graph distance) from an arbitrary start.
  3. penalty field PDRF = const * (1 - edt/max_edt)^16 — paths prefer the
     center of the object.
  4. repeat until every voxel is captured: take the farthest uncaptured
     voxel, trace its penalized-shortest path to the existing tree, and
     invalidate voxels within scale*edt + const of the new path vertices.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import numpy as np
from scipy import ndimage
from scipy.sparse import coo_matrix
from scipy.sparse.csgraph import dijkstra

from ..skeleton_io import Skeleton
from .edt import edt as device_edt

PDRF_EXPONENT = 16


class TeasarParams:
  """TEASAR tuning knobs, mirroring the kimimaro teasar_params dict the
  reference forwards verbatim (reference igneous_cli/cli.py:1325-1337):
  path-invalidation scale/const, PDRF shaping, soma handling thresholds
  (all physical units), and a path-count cap."""

  def __init__(
    self,
    scale: float = 4.0,
    const: float = 500.0,  # physical units (nm)
    pdrf_scale: float = 100000.0,
    pdrf_exponent: int = PDRF_EXPONENT,
    soma_detection_threshold: float = 1100.0,
    soma_acceptance_threshold: float = 3500.0,
    soma_invalidation_scale: float = 2.0,
    soma_invalidation_const: float = 300.0,
    max_paths: Optional[int] = None,
  ):
    self.scale = scale
    self.const = const
    self.pdrf_scale = pdrf_scale
    self.pdrf_exponent = pdrf_exponent
    self.soma_detection_threshold = soma_detection_threshold
    self.soma_acceptance_threshold = soma_acceptance_threshold
    self.soma_invalidation_scale = soma_invalidation_scale
    self.soma_invalidation_const = soma_invalidation_const
    self.max_paths = max_paths

  KNOWN = (
    "scale", "const", "pdrf_scale", "pdrf_exponent",
    "soma_detection_threshold", "soma_acceptance_threshold",
    "soma_invalidation_scale", "soma_invalidation_const", "max_paths",
  )

  @classmethod
  def from_dict(cls, d: Optional[dict]) -> "TeasarParams":
    """Unknown keys are ignored with a warning instead of failing every
    queued task."""
    d = dict(d or {})
    unknown = set(d) - set(cls.KNOWN)
    if unknown:
      import warnings

      warnings.warn(
        f"TeasarParams: ignoring unsupported keys {sorted(unknown)}",
        stacklevel=2,
      )
    return cls(**{k: v for k, v in d.items() if k in cls.KNOWN})


def _positive_deltas():
  """The 13 positive-lex neighbor deltas with their voxel_graph bits:
  [((dx, dy, dz), bit), ...]."""
  from .ccl import graph_bit  # local import: ccl pulls in jax

  out = []
  for dx in (-1, 0, 1):
    for dy in (-1, 0, 1):
      for dz in (-1, 0, 1):
        if (dx, dy, dz) <= (0, 0, 0):
          continue
        out.append(((dx, dy, dz), graph_bit((dx, dy, dz))))
  return out


def _foreground_graph_native(mask, pdrf, anisotropy, voxel_graph):
  """Direct symmetric-CSR build in C++ (native/csrc/fggraph.cpp); None
  when the toolchain is unavailable (caller falls back to numpy)."""
  import ctypes

  from ..native import fggraph_lib

  lib = fggraph_lib()
  if lib is None:
    return None
  idx = np.full(mask.size, -1, dtype=np.int64)
  fg = np.flatnonzero(mask.reshape(-1))
  idx[fg] = np.arange(len(fg))
  n = len(fg)
  w = np.asarray(anisotropy, dtype=np.float64)
  pairs = _positive_deltas()
  deltas = np.ascontiguousarray(
    [d for d, _b in pairs], dtype=np.int8
  ).reshape(-1)
  lens = np.ascontiguousarray(
    [float(np.linalg.norm(w * np.asarray(d))) for d, _b in pairs],
    dtype=np.float64,
  )
  bits = np.ascontiguousarray([b for _d, b in pairs], dtype=np.int32)
  pdrf_c = np.ascontiguousarray(pdrf, dtype=np.float32)
  vg = (
    None if voxel_graph is None
    else np.ascontiguousarray(voxel_graph, dtype=np.uint32)
  )
  indptr = np.zeros(n + 1, dtype=np.int64)

  def call(indices, weights, fill):
    return lib.ig_fggraph(
      mask.shape[0], mask.shape[1], mask.shape[2],
      idx.ctypes.data_as(ctypes.c_void_p),
      pdrf_c.ctypes.data_as(ctypes.c_void_p),
      None if vg is None else vg.ctypes.data_as(ctypes.c_void_p),
      deltas.ctypes.data_as(ctypes.c_void_p),
      lens.ctypes.data_as(ctypes.c_void_p),
      bits.ctypes.data_as(ctypes.c_void_p),
      n,
      indptr.ctypes.data_as(ctypes.c_void_p),
      None if indices is None else indices.ctypes.data_as(ctypes.c_void_p),
      None if weights is None else weights.ctypes.data_as(ctypes.c_void_p),
      fill,
    )

  nnz = call(None, None, 0)
  if nnz == 0:
    return None, fg
  indices = np.empty(nnz, dtype=np.int32)
  weights = np.empty(nnz, dtype=np.float64)
  call(indices, weights, 1)
  from scipy.sparse import csr_matrix

  g = csr_matrix((weights, indices, indptr), shape=(n, n))
  # canonical sorted rows: the numpy builder's `csr + csr.T` emits
  # sorted columns, and dijkstra's equal-distance tie-breaking follows
  # storage order — unsorted rows would change which (equally valid)
  # predecessor tree wins and break batched-vs-solo byte identity
  g.sort_indices()
  return g, fg


def _foreground_graph(
  mask: np.ndarray, pdrf: np.ndarray, anisotropy, voxel_graph=None
):
  """26-connected sparse graph over foreground voxels; edge weight =
  mean endpoint penalty * physical step length. ``voxel_graph`` (uint32
  bitfields from ops.ccl.voxel_connectivity_graph) removes edges whose
  direction bit is unset at the source voxel — the movement constraint
  kimimaro applies for the graphene autapse fix (reference
  tasks/skeleton.py:368-377). Built natively when the toolchain exists
  (identical output; ~20% of forge wall in the numpy form)."""
  native = _foreground_graph_native(mask, pdrf, anisotropy, voxel_graph)
  if native is not None:
    return native
  idx = np.full(mask.shape, -1, dtype=np.int64)
  fg = np.flatnonzero(mask.reshape(-1))
  idx.reshape(-1)[fg] = np.arange(len(fg))
  if voxel_graph is not None:
    from .ccl import graph_bit  # local import: ccl pulls in jax

  rows, cols, vals = [], [], []
  for dx in (-1, 0, 1):
    for dy in (-1, 0, 1):
      for dz in (-1, 0, 1):
        if (dx, dy, dz) <= (0, 0, 0):
          continue  # each unordered pair once
        src = tuple(
          slice(max(0, -d), mask.shape[a] - max(0, d))
          for a, d in enumerate((dx, dy, dz))
        )
        dst = tuple(
          slice(max(0, d), mask.shape[a] - max(0, -d))
          for a, d in enumerate((dx, dy, dz))
        )
        both = mask[src] & mask[dst]
        if voxel_graph is not None:
          bit = np.uint32(graph_bit((dx, dy, dz)))
          both &= (voxel_graph[src] >> bit) & np.uint32(1) != 0
        if not both.any():
          continue
        a_idx = idx[src][both]
        b_idx = idx[dst][both]
        step = float(np.linalg.norm(
          np.asarray(anisotropy, np.float64) * np.asarray((dx, dy, dz))
        ))
        # float64 like the native builder: both paths must agree bitwise
        cost = (
          (pdrf[src][both] + pdrf[dst][both]).astype(np.float64)
          * 0.5 * step
        )
        rows.append(a_idx)
        cols.append(b_idx)
        vals.append(cost)
  if not rows:
    return None, fg
  rows = np.concatenate(rows)
  cols = np.concatenate(cols)
  vals = np.concatenate(vals).astype(np.float64)
  n = len(fg)
  g = coo_matrix((vals, (rows, cols)), shape=(n, n)).tocsr()
  return g + g.T, fg


def skeletonize_mask(
  mask: np.ndarray,
  anisotropy: Sequence[float] = (1.0, 1.0, 1.0),
  params: Optional[TeasarParams] = None,
  offset: Sequence[float] = (0.0, 0.0, 0.0),
  edt_field: Optional[np.ndarray] = None,
  extra_targets: Optional[np.ndarray] = None,
  voxel_graph: Optional[np.ndarray] = None,
  fix_branching: bool = True,
) -> Skeleton:
  """Skeletonize one binary object. Vertices come out in physical units:
  (voxel + offset) * anisotropy. ``edt_field`` lets callers supply a
  precomputed whole-cutout device EDT (the batched task path).

  ``extra_targets``: (k, 3) voxel coords that MUST become skeleton
  vertices with a traced path to the tree — the border-pinning mechanism
  that makes adjacent tasks' skeletons weld at shared overlap planes
  (the reference's kimimaro fix_borders / extra_targets_after,
  tasks/skeleton.py:68-69,177).

  ``fix_branching``: recompute the penalized shortest-path field from the
  ENTIRE current tree before each new path (multi-source Dijkstra), so
  branches attach at the correct centerline junction instead of wherever
  the single root-rooted predecessor tree happens to pass (the
  reference's kimimaro fix_branching flag, tasks/skeleton.py:68;
  default True there and here). False = one predecessor tree per
  component, ~paths× faster, slightly off-center branch points."""
  params = params or TeasarParams()
  mask = np.ascontiguousarray(mask.astype(bool))
  if not mask.any():
    return Skeleton()

  dt = edt_field if edt_field is not None else device_edt(
    mask.astype(np.uint8), anisotropy, black_border=True
  )

  # a label can have several disconnected pieces inside one cutout (e.g. a
  # process leaving and re-entering); every 26-connected component gets its
  # own trace — kimimaro behaves the same way. Each component is CROPPED
  # to its bounding box first: the per-component field work (pdrf power,
  # masking, graph indexing) is full-array, and on multi-blob cutouts the
  # full-cutout form was the single largest profile line (VERDICT r4 #4).
  comps, ncomp = ndimage.label(mask, structure=np.ones((3, 3, 3), bool))
  if ncomp > 1:
    pieces = []
    for ci, sl in enumerate(ndimage.find_objects(comps), start=1):
      if sl is None:
        continue
      lo = np.array([s.start for s in sl])
      sub_targets = None
      if extra_targets is not None and len(extra_targets):
        et = np.asarray(extra_targets, dtype=np.int64)
        hi = np.array([s.stop for s in sl])
        keep = ((et >= lo) & (et < hi)).all(axis=1)
        sub_targets = et[keep] - lo
      piece = _skeletonize_component(
        comps[sl] == ci, dt[sl], anisotropy, params,
        np.asarray(offset, np.float32) + lo.astype(np.float32),
        sub_targets,
        None if voxel_graph is None else voxel_graph[sl],
        fix_branching,
      )
      if not piece.empty:
        pieces.append(piece)
    if not pieces:
      return Skeleton()
    return Skeleton.simple_merge(pieces).consolidate()
  return _skeletonize_component(
    mask, dt, anisotropy, params, offset, extra_targets, voxel_graph,
    fix_branching,
  )


class _IncrementalDijkstra:
  """Warm-field multi-source shortest-path forest over a CSR graph.

  Adding sources S to an existing multi-source field only improves
  distances in the region closer to S, so re-seeding the heap against
  the warm field relaxes exactly that region — the result equals a cold
  recompute from (all sources so far), which is what fix_branching's
  per-path forest regrow needs. Measured: the full scipy recompute per
  path was ~60 ms on a 70k-node component (8.1 s of a 12.9 s forge);
  the incremental update touches only the new branch's neighborhood.
  ``None`` when the native toolchain is unavailable (caller falls back
  to scipy full recomputes — identical semantics).
  """

  def __init__(self, graph):
    from ..native import dijkstra_lib

    self.lib = dijkstra_lib()
    if self.lib is None:
      return
    g = graph.tocsr()
    self.n = g.shape[0]
    self.indptr = np.ascontiguousarray(g.indptr, dtype=np.int64)
    self.indices = np.ascontiguousarray(g.indices, dtype=np.int32)
    self.weights = np.ascontiguousarray(g.data, dtype=np.float64)
    self.dist = np.full(self.n, np.inf, dtype=np.float64)
    self.pred = np.full(self.n, -1, dtype=np.int32)

  def update(self, sources) -> None:
    import ctypes

    src = np.ascontiguousarray(sources, dtype=np.int64)
    rc = self.lib.igdij_update(
      self.n,
      self.indptr.ctypes.data_as(ctypes.c_void_p),
      self.indices.ctypes.data_as(ctypes.c_void_p),
      self.weights.ctypes.data_as(ctypes.c_void_p),
      self.dist.ctypes.data_as(ctypes.c_void_p),
      self.pred.ctypes.data_as(ctypes.c_void_p),
      src.ctypes.data_as(ctypes.c_void_p),
      len(src),
    )
    if rc != 0:
      raise ValueError("igdij_update: source index out of range")


def _skeletonize_component(
  mask: np.ndarray,
  dt: np.ndarray,
  anisotropy,
  params: TeasarParams,
  offset,
  extra_targets,
  voxel_graph=None,
  fix_branching: bool = True,
) -> Skeleton:
  dt = np.where(mask, dt, 0.0)
  dmax = float(dt.max())
  if dmax <= 0:
    return Skeleton()

  pdrf = (
    params.pdrf_scale * (1.0 - dt / (1.05 * dmax)) ** params.pdrf_exponent
  ).astype(np.float32) + 1e-5
  pdrf[~mask] = np.float32(np.inf)

  graph, fg = _foreground_graph(mask, pdrf, anisotropy, voxel_graph)
  n = len(fg)
  if graph is None or n == 1:
    # a single voxel: degenerate one-vertex skeleton
    coords = np.array(np.unravel_index(fg, mask.shape)).T.astype(np.float32)
    verts = (coords + np.asarray(offset, np.float32)) * np.asarray(
      anisotropy, np.float32
    )
    return Skeleton(verts, np.zeros((0, 2), np.uint32),
                    radii=dt.reshape(-1)[fg])

  coords = np.array(np.unravel_index(fg, mask.shape)).T  # (n, 3) voxel
  phys = coords.astype(np.float32) * np.asarray(anisotropy, np.float32)

  edt_flat = dt.reshape(-1)[fg]
  inval_radius = params.scale * edt_flat + params.const

  flat_targets = None
  if extra_targets is not None and len(extra_targets):
    flat_targets = np.ravel_multi_index(
      np.asarray(extra_targets, dtype=np.int64).T, mask.shape
    )

  # a voxel_graph can sever a geometrically-connected mask into several
  # graph components (the autapse-fix mechanism); every component must be
  # traced, not just the one containing the first root — kimimaro
  # skeletonizes each graph-connected piece
  from scipy.sparse.csgraph import connected_components as graph_components

  ncomp_g, comp_ids = graph_components(graph, directed=False)

  # soma mode (kimimaro soma_acceptance_threshold): a very thick object
  # is a cell body — root at the EDT maximum, one big invalidation ball,
  # radial paths to whatever pokes out, instead of a surface-crawling
  # zigzag over the soma membrane
  soma_node = None
  if (
    params.soma_acceptance_threshold
    and dmax > params.soma_acceptance_threshold
  ):
    soma_node = int(np.argmax(edt_flat))

  paths = []
  roots = []
  on_tree = np.zeros(n, dtype=bool)
  max_paths = params.max_paths or n
  # one warm field shared across graph components: they are edge-disjoint,
  # so a later component's updates can never leak into (or read) another's
  inc = _IncrementalDijkstra(graph) if fix_branching else None
  use_inc = inc is not None and inc.lib is not None
  for c in range(ncomp_g):
    in_comp = comp_ids == c
    nodes = np.flatnonzero(in_comp)
    if soma_node is not None and in_comp[soma_node]:
      root = soma_node
    else:
      # root: farthest voxel (unweighted hops) from an arbitrary start
      d0 = dijkstra(graph, indices=int(nodes[0]), unweighted=True)
      root = int(np.argmax(np.where(np.isfinite(d0), d0, -1)))
    roots.append(root)

    captured = ~in_comp  # other components are off-limits for this trace
    captured = captured.copy()
    captured[root] = True
    tree_c = np.zeros(n, dtype=bool)  # this component's current tree
    tree_c[root] = True

    if root == soma_node:
      r = (
        params.soma_invalidation_scale * edt_flat[root]
        + params.soma_invalidation_const
      )
      d2 = ((phys - phys[root]) ** 2).sum(-1)
      captured |= d2 <= r * r

    # penalized distances + shortest-path forest. With fix_branching the
    # forest is regrown from the WHOLE current tree before every path
    # (multi-source), so each branch attaches at the true junction; without
    # it one root-rooted tree serves every path (faster, branches attach
    # wherever the root tree passes).
    if fix_branching:
      if use_inc:
        inc.update([root])
        dist, pred = inc.dist, inc.pred
      else:
        dist, pred, _ = dijkstra(
          graph, indices=[root], min_only=True, return_predecessors=True
        )
    else:
      dist, pred = dijkstra(graph, indices=root, return_predecessors=True)

    # ``remaining`` is maintained incrementally: a full
    # flatnonzero(~captured) costs O(component) PER PATH, which dominated
    # the trace loop; the invalidation pass below already computes exactly
    # which members it captured, so only a cheap shrinking-array prune is
    # needed per path (for captured[path] updates).
    remaining = np.flatnonzero(~captured)
    # phys rows for `remaining`, maintained in lockstep: re-gathering
    # phys[rem] per path chunk was the largest single line of the blob
    # forge profile (~18 ms per gather at 380k survivors)
    rem_phys = phys[remaining]
    tree_nodes = [np.asarray([root], dtype=np.int64)]  # mirrors tree_c
    for _ in range(max_paths):
      alive = ~captured[remaining]
      if not alive.all():
        remaining = remaining[alive]
        rem_phys = rem_phys[alive]
      if len(remaining) == 0:
        break
      target = int(remaining[np.argmax(dist[remaining])])
      # walk the predecessor forest from target back onto the tree: with
      # fix_branching every source is a tree vertex (pred < 0 there); the
      # single-tree variant stops at the first captured vertex
      path = [target]
      cur = target
      while pred[cur] >= 0 and not (tree_c[cur] if fix_branching
                                    else captured[cur]):
        cur = int(pred[cur])
        path.append(cur)
      path = np.asarray(path, dtype=np.int64)
      paths.append(path)
      tree_c[path] = True
      tree_nodes.append(path)
      # rolling invalidation ball: capture voxels near the new centerline
      ball = inval_radius[path]  # (p,)
      # chunk to bound memory: |remaining| x |path| distances
      rem = remaining
      rp = rem_phys
      for start in range(0, len(path), 512):
        seg = path[start : start + 512]
        rchunk = ball[start : start + 512]
        # exact bbox prefilter: no voxel outside the chunk's bounding box
        # padded by its largest ball radius can be captured — for tube-like
        # objects this shrinks the pairwise set by orders of magnitude
        rmax = float(rchunk.max())
        sp = phys[seg]
        lo = sp.min(axis=0) - rmax
        hi = sp.max(axis=0) + rmax
        near = np.flatnonzero(
          ((rp >= lo) & (rp <= hi)).all(axis=1)
        )
        if len(near) == 0:
          continue
        cand = rem[near]
        # ||c - s||^2 via GEMM: the broadcast form materializes a
        # (c, p, 3) temporary and reduces it in numpy — measured ~50% of
        # the whole forge on blob fixtures; BLAS does (c,p) directly.
        # float64 keeps the x^2+s^2-2xs cancellation below 1e-7 vox.
        cp = rp[near].astype(np.float64)
        ps = sp.astype(np.float64)
        d2 = (
          (cp * cp).sum(1)[:, None]
          + (ps * ps).sum(1)[None, :]
          - 2.0 * (cp @ ps.T)
        )  # (c, p)
        hit = (d2 <= (rchunk[None, :].astype(np.float64) ** 2)).any(axis=1)
        captured[cand[hit]] = True
        if hit.any():
          keep = np.ones(len(rem), dtype=bool)
          keep[near[hit]] = False
          rem = rem[keep]
          rp = rp[keep]
        if len(rem) == 0:
          break
      remaining = rem  # survivors; path members prune at the loop top
      rem_phys = rp
      captured[path] = True
      if fix_branching and not captured.all():
        if use_inc:
          # warm-field update from just the new branch — equals a cold
          # recompute from the whole tree, touching only the region the
          # branch improves
          inc.update(path)
          dist, pred = inc.dist, inc.pred
        else:
          # scipy fallback: full recompute from the incrementally-
          # maintained tree vertex list (duplicates at path attach points
          # are fine — dijkstra takes the min over sources)
          dist, pred, _ = dijkstra(
            graph,
            indices=np.concatenate(tree_nodes),
            min_only=True,
            return_predecessors=True,
          )

    # forced targets: path each one into this component's tree regardless
    # of invalidation
    if flat_targets is not None:
      for p in paths:
        on_tree[p] = True
      on_tree[root] = True
      pos = np.searchsorted(fg, flat_targets)
      for p, t in zip(pos, flat_targets):
        if p >= n or fg[p] != t or not in_comp[p]:
          continue
        path = [int(p)]
        cur = int(p)
        while pred[cur] >= 0 and not on_tree[cur]:
          cur = int(pred[cur])
          path.append(cur)
        if len(path) > 1:
          arr = np.asarray(path, dtype=np.int64)
          paths.append(arr)
          on_tree[arr] = True

  # assemble skeleton from paths
  verts = (coords.astype(np.float32) + np.asarray(offset, np.float32)) * \
    np.asarray(anisotropy, np.float32)
  edges = []
  for path in paths:
    edges.append(np.stack([path[:-1], path[1:]], axis=1))
  edges = np.concatenate(edges) if edges else np.zeros((0, 2), np.int64)

  used = np.unique(np.concatenate([edges.reshape(-1), roots]))
  remap = np.full(n, -1, dtype=np.int64)
  remap[used] = np.arange(len(used))
  skel = Skeleton(
    verts[used],
    remap[edges].astype(np.uint32),
    radii=edt_flat[used],
    vertex_types=np.zeros(len(used), np.uint8),
  )
  return skel.consolidate()


def skeletonize(
  labels: np.ndarray,
  anisotropy: Sequence[float] = (1.0, 1.0, 1.0),
  params: Optional[TeasarParams] = None,
  offset: Sequence[float] = (0.0, 0.0, 0.0),
  object_ids: Optional[Sequence[int]] = None,
  dust_threshold: int = 0,
  extra_targets_per_label: Optional[Dict[int, np.ndarray]] = None,
  parallel: int = 1,
  progress: bool = False,
  voxel_graph: Optional[np.ndarray] = None,
  edt_field: Optional[np.ndarray] = None,
  fix_branching: bool = True,
  fix_avocados: bool = False,
) -> Dict[int, Skeleton]:
  """Skeletonize every label in a volume → {label: Skeleton}.

  The whole-cutout EDT runs as ONE device program; per-label tracing crops
  to each label's bounding box (the reference's per-label split,
  tasks/skeleton.py:303-335). ``parallel`` threads the label loop (the
  scipy/numpy hot paths release the GIL) — the reference forwards the
  same knob to kimimaro (task_creation/skeleton.py:159-163).

  ``fix_avocados`` (reference tasks/skeleton.py:70): a soma whose nucleus
  was segmented as a separate label skeletonizes like an avocado — the
  EDT sees a hollow shell and traces around the pit. For every
  soma-candidate label (max EDT ≥ soma_detection_threshold), labels
  wholly engulfed by its filled hull are absorbed into it (and dropped
  from the output — the fused body is reported under the soma's label),
  background holes are filled, and the label's EDT is recomputed on the
  solid mask. With ``object_ids``, only requested labels are soma
  candidates, so a requested label can never be silently absorbed by an
  unrequested one."""
  del progress
  params = params or TeasarParams()
  labels = np.asarray(labels)
  if labels.ndim == 4:
    labels = labels[..., 0]

  # the batched forge precomputes K cutouts' EDTs in one device dispatch
  # and injects them here (edt_batch); solo tasks compute their own
  whole_edt = (
    edt_field if edt_field is not None
    else device_edt(labels, anisotropy, black_border=True)
  )

  from .remap import renumber as _renumber

  dense, mapping = _renumber(labels)
  slices = ndimage.find_objects(dense.astype(np.int32))

  wanted = set(int(v) for v in object_ids) if object_ids else None

  absorbed: set = set()
  solid_masks: Dict[int, np.ndarray] = {}
  solid_edts: Dict[int, np.ndarray] = {}
  if fix_avocados:
    counts = np.bincount(dense.reshape(-1))
    detect = float(params.soma_detection_threshold or 0.0)
    for new_id, sl in enumerate(slices, start=1):
      if sl is None:
        continue
      # only requested labels can be somas: absorption then never steals
      # an explicitly requested label (it could only vanish into another
      # requested label), and the scan cost scales with the request, not
      # with the cutout's label count
      if wanted is not None and int(mapping[new_id]) not in wanted:
        continue
      mask = dense[sl] == new_id
      filled = ndimage.binary_fill_holes(mask)
      added = filled & ~mask
      if not added.any():
        continue
      crop = dense[sl]
      pit_labels = [
        int(lab)
        for lab in np.unique(crop[added])
        if lab not in (0, new_id)
        and int(np.count_nonzero((crop == lab) & added)) == int(counts[lab])
      ]
      bg_holes = added & (crop == 0)
      if not pit_labels and not bg_holes.any():
        continue
      solid = mask | bg_holes
      if pit_labels:
        solid |= np.isin(crop, pit_labels) & added
      # soma candidacy is judged on the SOLID body: a hollow shell's raw
      # EDT never reaches soma thickness, which is exactly the avocado
      # symptom being repaired
      edt_solid = device_edt(
        solid.astype(np.uint8), anisotropy, black_border=True
      )
      if float(edt_solid.max()) < detect:
        continue
      absorbed.update(pit_labels)
      solid_masks[new_id] = solid
      solid_edts[new_id] = edt_solid

  def trace(new_id: int, sl) -> Optional[tuple]:
    if new_id in absorbed:  # a nucleus swallowed by its soma
      return None
    orig = mapping[new_id]
    if wanted is not None and orig not in wanted:
      return None
    if new_id in solid_masks:
      # the pit is solid now; the cavity-distorted whole-cutout EDT no
      # longer applies — use the EDT of the solid body
      mask = solid_masks[new_id]
      crop_edt = solid_edts[new_id]
    else:
      mask = dense[sl] == new_id
      crop_edt = np.where(mask, whole_edt[sl], 0.0)
    if dust_threshold and mask.sum() < dust_threshold:
      return None
    crop_offset = np.asarray(offset, np.float32) + np.asarray(
      [s.start for s in sl], np.float32
    )
    targets = None
    if extra_targets_per_label and orig in extra_targets_per_label:
      t = np.asarray(extra_targets_per_label[orig], dtype=np.int64)
      t = t - np.asarray([s.start for s in sl], dtype=np.int64)
      inside = np.all(
        (t >= 0) & (t < np.asarray(mask.shape, dtype=np.int64)), axis=1
      )
      targets = t[inside]
    skel = skeletonize_mask(
      mask, anisotropy, params, offset=crop_offset, edt_field=crop_edt,
      extra_targets=targets,
      voxel_graph=None if voxel_graph is None else voxel_graph[sl],
      fix_branching=fix_branching,
    )
    return None if skel.empty else (int(orig), skel)

  jobs = [
    (new_id, sl)
    for new_id, sl in enumerate(slices, start=1)
    if sl is not None
  ]
  out: Dict[int, Skeleton] = {}
  if parallel > 1 and len(jobs) > 1:
    import concurrent.futures as cf

    with cf.ThreadPoolExecutor(max_workers=int(parallel)) as pool:
      for result in pool.map(lambda j: trace(*j), jobs):
        if result is not None:
          out[result[0]] = result[1]
  else:
    for job in jobs:
      result = trace(*job)
      if result is not None:
        out[result[0]] = result[1]
  return out
