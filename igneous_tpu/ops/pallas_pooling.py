"""Pallas TPU kernel for 2x2x1 pooling — the hand-tiled fast path.

The default pooling pyramid (ops/pooling.py) is XLA-fused jnp code; this
module provides an explicitly tiled Pallas version of the hottest single
op (one 2x2x1 average/mode pooling step) for TPU:

  - layout (z-last): pooling runs over the sublane/second-minor dims while
    the lane dimension (z) streams untouched, so every load is contiguous
    in lanes;
  - the grid walks (y-tiles, x-tiles); each program reads a
    (2*TY, 2*TX, Z) VMEM block and writes (TY, TX, Z);
  - the mode variant implements the same earliest-position majority vote
    as ops/pooling._pool_mode via 4 static window slices.

Use ``available()`` / ``pool2x2x1`` with ``interpret=True`` for CPU tests.
The task pipeline keeps the XLA path; this kernel is the promotion
CANDIDATE — bench.py records the device-resident Pallas-vs-XLA A/B on
every TPU run (detail.pool_ab), and the pyramid switches only when that
evidence says so (ROADMAP item 1).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

try:  # pallas is part of jax, but guard exotic builds
  from jax.experimental import pallas as pl

  _PALLAS = True
except Exception:  # pragma: no cover
  _PALLAS = False


def available() -> bool:
  return _PALLAS


def _avg_step(x):
  a = x[0::2, 0::2, :].astype(jnp.int32)
  b = x[0::2, 1::2, :].astype(jnp.int32)
  c = x[1::2, 0::2, :].astype(jnp.int32)
  d = x[1::2, 1::2, :].astype(jnp.int32)
  return ((a + b + c + d + 2) // 4).astype(x.dtype)


def _mode_step(x):
  # earliest-position majority of the 4 window values (y-major window
  # order matches ops/pooling's z-major/y/x ordering for a 2x2x1 factor)
  vs = [
    x[0::2, 0::2, :],
    x[0::2, 1::2, :],
    x[1::2, 0::2, :],
    x[1::2, 1::2, :],
  ]
  best_s = None
  best_v = None
  for i in range(4):
    counts = None
    for j in range(4):
      e = (vs[i] == vs[j]).astype(jnp.int32)
      counts = e if counts is None else counts + e
    score = counts * 4 - i
    if best_s is None:
      best_s, best_v = score, vs[i]
    else:
      take = score > best_s
      best_s = jnp.where(take, score, best_s)
      best_v = jnp.where(take, vs[i], best_v)
  return best_v


def _avg_kernel(x_ref, o_ref):
  o_ref[...] = _avg_step(x_ref[...])


def _mode_kernel(x_ref, o_ref):
  o_ref[...] = _mode_step(x_ref[...])


def _pyramid_kernel(x_ref, *o_refs, method: str):
  # the whole mip walk on one VMEM-resident block: level l+1 pools
  # level l's block without ever leaving VMEM
  cur = x_ref[...]
  step = _avg_step if method == "average" else _mode_step
  for o in o_refs:
    cur = step(cur)
    o[...] = cur


@partial(jax.jit, static_argnames=("method", "ty", "tx", "interpret"))
def _pool_zlast(x, method: str, ty: int, tx: int, interpret: bool):
  """x: (Y, X, Z) with Y, X even, Y % 2ty == 0, X % 2tx == 0, Z % 128 == 0."""
  Y, X, Z = x.shape
  kernel = _avg_kernel if method == "average" else _mode_kernel
  return pl.pallas_call(
    kernel,
    out_shape=jax.ShapeDtypeStruct((Y // 2, X // 2, Z), x.dtype),
    grid=(Y // (2 * ty), X // (2 * tx)),
    in_specs=[
      pl.BlockSpec((2 * ty, 2 * tx, Z), lambda i, j: (i, j, 0)),
    ],
    out_specs=pl.BlockSpec((ty, tx, Z), lambda i, j: (i, j, 0)),
    interpret=interpret,
  )(x)


@partial(
  jax.jit, static_argnames=("method", "levels", "ty", "tx", "interpret")
)
def _pyramid_zlast(x, method: str, levels: int, ty: int, tx: int,
                   interpret: bool):
  """x: (Y, X, Z) with Y % (ty << levels) == 0, X % (tx << levels) == 0,
  Z % 128 == 0. Returns one (Y>>l, X>>l, Z) array per level l=1..levels,
  all produced by a SINGLE pallas_call: each grid program loads one
  (ty<<levels, tx<<levels, Z) block and walks the whole pyramid in VMEM.
  """
  Y, X, Z = x.shape
  by, bx = ty << levels, tx << levels
  out_shape = [
    jax.ShapeDtypeStruct((Y >> (l + 1), X >> (l + 1), Z), x.dtype)
    for l in range(levels)
  ]
  out_specs = [
    pl.BlockSpec((by >> (l + 1), bx >> (l + 1), Z), lambda i, j: (i, j, 0))
    for l in range(levels)
  ]
  return pl.pallas_call(
    partial(_pyramid_kernel, method=method),
    out_shape=out_shape,
    grid=(Y // by, X // bx),
    in_specs=[pl.BlockSpec((by, bx, Z), lambda i, j: (i, j, 0))],
    out_specs=out_specs,
    interpret=interpret,
  )(x)


def pyramid2x2x1(
  img: np.ndarray, num_mips: int = 2, method: str = "average",
  interpret: bool = False,
):
  """Fused multi-mip 2x2x1 pyramid: ONE pallas_call computes every mip.

  img: (x, y, z) numpy; returns a list of num_mips arrays, bitwise what
  L separate pool2x2x1 calls produce. The one-dispatch in-VMEM walk runs
  when x and y are multiples of 2**num_mips — then no mip's extent ever
  goes odd, so every window the cropped outputs read is fully real and
  pad-once (tile alignment only) is exact. Other extents fall back to
  iterated pool2x2x1 calls: an odd INTERMEDIATE extent makes the walks
  genuinely differ (the iterated walk duplicates that mip's own pooled
  edge line; a pad-once walk would fill the same slot by pooling mip-0
  edge replicas), and production chunk shapes are 2**k-aligned anyway.

  VMEM budget: each program holds a (8<<L, 8<<L, Z~128) input block plus
  its mip stack — ~2.8MB at L=3 for int32, comfortably inside the ~16MB
  per-core budget; L>4 callers should drop to ops.pooling's XLA walk.
  Same dtype gates as pool2x2x1.
  """
  if not _PALLAS:
    raise RuntimeError("pallas unavailable in this jax build")
  if num_mips < 1:
    raise ValueError("num_mips must be >= 1")
  if img.shape[0] % (1 << num_mips) or img.shape[1] % (1 << num_mips):
    outs = []
    cur = img
    for _ in range(num_mips):
      cur = pool2x2x1(cur, method=method, interpret=interpret)
      outs.append(cur)
    return outs
  if method == "mode" and img.dtype.itemsize > 4:
    raise ValueError("use ops.pooling for 64-bit labels (hi/lo planes)")
  if method == "average" and (
    np.issubdtype(img.dtype, np.floating) or img.dtype.itemsize > 2
  ):
    raise ValueError(
      "pallas averaging covers <=16-bit integers; use ops.pooling otherwise"
    )
  orig = img.shape
  work = img
  if work.dtype.itemsize <= 2 and method == "mode":
    work = work.astype(np.uint32)

  arr = np.ascontiguousarray(np.transpose(work, (1, 0, 2)))  # (y, x, z)
  ty, tx = 8, 8
  pad_y = (-arr.shape[0]) % (ty << num_mips)
  pad_x = (-arr.shape[1]) % (tx << num_mips)
  pad_z = (-arr.shape[2]) % 128
  if pad_y or pad_x or pad_z:
    arr = np.pad(arr, ((0, pad_y), (0, pad_x), (0, pad_z)), mode="edge")

  outs = _pyramid_zlast(
    jnp.asarray(arr), method, num_mips, ty, tx, interpret
  )
  results = []
  sx, sy, sz = orig
  for o in outs:
    sx, sy = (sx + 1) // 2, (sy + 1) // 2
    r = np.transpose(np.asarray(o), (1, 0, 2))[:sx, :sy, :sz]
    results.append(r.astype(img.dtype, copy=False))
  return results


def pool2x2x1(
  img: np.ndarray, method: str = "average", interpret: bool = False
) -> np.ndarray:
  """One 2x2x1 pooling step via the Pallas kernel.

  img: (x, y, z) numpy. Shapes are padded (edge-replicate, exact for
  factor 2 — see ops/pooling) to even x/y, lane-multiple z, and tile
  multiples.
  """
  if not _PALLAS:
    raise RuntimeError("pallas unavailable in this jax build")
  if method == "mode" and img.dtype.itemsize > 4:
    raise ValueError("use ops.pooling for 64-bit labels (hi/lo planes)")
  if method == "average" and (
    np.issubdtype(img.dtype, np.floating) or img.dtype.itemsize > 2
  ):
    # the kernel accumulates in int32: exact only for <=16-bit integers.
    # Wider dtypes use ops.pooling's hi/lo-split XLA path.
    raise ValueError(
      "pallas averaging covers <=16-bit integers; use ops.pooling otherwise"
    )
  orig = img.shape
  work = img
  if work.dtype.itemsize <= 2 and method == "mode":
    work = work.astype(np.uint32)

  # z-last layout: (y, x, z)
  arr = np.ascontiguousarray(np.transpose(work, (1, 0, 2)))
  ty, tx = 8, 8
  pad_y = (-arr.shape[0]) % (2 * ty)
  pad_x = (-arr.shape[1]) % (2 * tx)
  pad_z = (-arr.shape[2]) % 128
  if pad_y or pad_x or pad_z:
    arr = np.pad(arr, ((0, pad_y), (0, pad_x), (0, pad_z)), mode="edge")

  out = np.asarray(_pool_zlast(jnp.asarray(arr), method, ty, tx, interpret))
  out = np.transpose(out, (1, 0, 2))  # back to (x, y, z)
  out = out[: (orig[0] + 1) // 2, : (orig[1] + 1) // 2, : orig[2]]
  return out.astype(img.dtype, copy=False)
