"""Block-local connected components labeling on device — cc3d parity.

Replaces the reference's cc3d C++ kernel for the block-local pass of
whole-image CCL (/root/reference/igneous/tasks/image/ccl.py:126-194 uses
cc3d.connected_components per task; the global merge stays host-side union
find, SURVEY.md §2.3).

Algorithm (TPU-first): segmented-scan label propagation with pointer
doubling. Each foreground voxel starts as its own flat index; every
round runs a segmented cummin along each axis (a log-depth
lax.associative_scan that collapses every contiguous same-label run to
its minimum at once — no gathers), one neighbor-min over the requested
connectivity to couple runs across bends and diagonals, then
path-compresses by gathering L[L] (pointer jumping). Multilabel
semantics match cc3d: two voxels connect iff their input labels are
equal and nonzero.

The neighbor-min looks redundant for 6-connectivity (axis adjacency IS
run adjacency) but is not: it moves post-sweep values across orthogonal
run boundaries within the same round — measured on representative
volumes it saves a full round (and a round costs two whole-volume
compression gathers, more than six rolled mins) on dense multilabel and
sparse-speckle inputs, and never adds one.

Output labels are the component's minimum flat index + 1 — deterministic,
so the 4-pass CCL protocol can recompute identical labels in later passes
(ccl.py relies on this, reference ccl.py:296-356). Host-side ``relabel``
renumbers to 1..N in first-scan order.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis import knobs


def neighbor_offsets(connectivity: int):
  """cc3d-style neighborhoods: 6 = faces, 18 = +edges, 26 = +corners."""
  if connectivity not in (6, 18, 26):
    raise ValueError(f"connectivity must be 6, 18 or 26: {connectivity}")
  offs = []
  for dz in (-1, 0, 1):
    for dy in (-1, 0, 1):
      for dx in (-1, 0, 1):
        if (dx, dy, dz) == (0, 0, 0):
          continue
        degree = abs(dx) + abs(dy) + abs(dz)
        if connectivity == 6 and degree > 1:
          continue
        if connectivity == 18 and degree > 2:
          continue
        offs.append((dz, dy, dx))
  return offs


def _neighbor_min(
  L: jnp.ndarray, labels: jnp.ndarray, connectivity: int = 6,
  axes: Tuple[int, int, int] = (0, 1, 2),
) -> jnp.ndarray:
  """One min-propagation step over the connectivity neighborhood.
  L, labels: (z, y, x) on ``axes`` — leading axes (e.g. a tile batch)
  are untouched."""
  big = jnp.iinfo(jnp.int32).max

  def shifted_min(L, off):
    # neighbor at -off (roll by +off moves neighbor data onto the voxel);
    # wrapped planes are invalidated per axis
    nb_L = L
    nb_lab = labels
    valid = None
    for axis, d in zip(axes, off):
      if d == 0:
        continue
      nb_L = jnp.roll(nb_L, d, axis=axis)
      nb_lab = jnp.roll(nb_lab, d, axis=axis)
      size = labels.shape[axis]
      coord = jax.lax.broadcasted_iota(jnp.int32, labels.shape, axis)
      v = coord != (0 if d == 1 else size - 1)
      valid = v if valid is None else (valid & v)
    same = valid & (nb_lab == labels)
    return jnp.where(same, nb_L, big)

  m = L
  for off in neighbor_offsets(connectivity):
    m = jnp.minimum(m, shifted_min(L, off))
  return m


def _compress(L: jnp.ndarray, iters: int = 2) -> jnp.ndarray:
  flat = L.reshape(-1)
  for _ in range(iters):
    flat = flat[flat]
  return flat.reshape(L.shape)


def _seg_cummin(
  L: jnp.ndarray, labels: jnp.ndarray, axis: int, reverse: bool
) -> jnp.ndarray:
  """Segmented running-min of L along ``axis`` within contiguous
  same-label runs — a log-depth associative scan, no gathers. One
  forward+backward pair collapses every straight run to its minimum in a
  single round (vs one voxel per round for stencil relaxation)."""

  def op(a, b):
    av, af = a
    bv, bf = b
    return (jnp.where(bf, bv, jnp.minimum(av, bv)), af | bf)

  lab = labels
  if reverse:
    L = jnp.flip(L, axis)
    lab = jnp.flip(lab, axis)
  prev = jnp.roll(lab, 1, axis)
  coord = jax.lax.broadcasted_iota(jnp.int32, lab.shape, axis)
  reset = (coord == 0) | (lab != prev)
  v, _ = jax.lax.associative_scan(op, (L, reset), axis=axis)
  if reverse:
    v = jnp.flip(v, axis)
  return v


@partial(jax.jit, static_argnames=("connectivity", "algo"))
def _ccl_kernel(
  labels: jnp.ndarray, connectivity: int = 6, algo: str = "scan"
) -> jnp.ndarray:
  """labels: (z, y, x) int32 (0 = background) → component roots (flat
  min-index per component; background stays huge sentinel).

  Each round: segmented-cummin sweeps along all three axes (whole
  same-label runs collapse at once), one neighbor-min coupling runs
  across the requested connectivity, then — in the default ``scan``
  algorithm — pointer-jump compression. Measured round counts vs plain
  stencil relaxation: 69→4 on a snaking tube, 33→10 on dense random
  multilabel, 5→2 on blobby segmentation — and rounds are what cost:
  every round carries the two full-volume compression gathers (VERDICT
  round-1 weak item 4).

  ``algo="relax"`` drops the pointer jumps entirely: min VALUES (not
  pointers) flow through the sweeps until fixpoint. More rounds, but
  zero gathers per round — on TPU a whole-volume gather lowers to slow
  dynamic-slice loops while scans/rolls stay vectorized, so which
  variant wins is a hardware question (ROADMAP item 1; select with
  IGNEOUS_CCL_DEVICE_ALGO). Both converge to the identical fixpoint:
  every voxel holds its component's minimum flat index."""
  n = labels.size
  idx = jnp.arange(n, dtype=jnp.int32).reshape(labels.shape)
  fg = labels != 0
  big = jnp.iinfo(jnp.int32).max
  L0 = jnp.where(fg, idx, idx)  # background points at itself (inert)

  def cond(state):
    _, changed = state
    return changed

  def body(state):
    L, _ = state
    Lp = L
    for axis in range(3):
      Lp = jnp.minimum(
        _seg_cummin(Lp, labels, axis, False),
        _seg_cummin(Lp, labels, axis, True),
      )
    Lp = jnp.minimum(Lp, _neighbor_min(Lp, labels, connectivity))
    Lp = jnp.where(fg, jnp.minimum(L, Lp), L)
    if algo == "scan":
      Lp = _compress(Lp, iters=2)
    changed = jnp.any(Lp != L)
    return (Lp, changed)

  L, _ = jax.lax.while_loop(cond, body, (L0, jnp.bool_(True)))
  return jnp.where(fg, L, big)


# ---------------------------------------------------------------------------
# tiled label propagation — the production device path (ISSUE 11)
#
# The whole-volume kernel above converges in rounds bounded by the largest
# component's tortuosity across the FULL volume — on dense near-percolation
# inputs that is dozens-to-hundreds of rounds, each a whole-volume sweep
# (the ~138k vox/s BENCH_r05 measurement). The tiled kernel bounds rounds
# by TILE tortuosity instead: VMEM-sized blocks resolve locally (converged
# tiles freeze — per-tile early exit), and one exact host union-find over
# tile-face root pairs stitches the global components. Any consistent
# unique per-component representative gives byte-identical output after
# _roots_to_components (the 1..N renumber depends only on the partition),
# so the tiled path stays bit-for-bit equal to the whole-volume kernel and
# the native C++ two-pass — _ccl_kernel is kept as the parity oracle.

_DEFAULT_TILE = (2, 4, 8)
_DEFAULT_TILE_TPU = (8, 16, 128)


def _tile_shape() -> Tuple[int, int, int]:
  """(tz, ty, tx) block-local resolve tile, override with
  IGNEOUS_CCL_TILE=tz,ty,tx.

  Rounds scale with tile tortuosity, so smaller tiles converge in fewer
  sweeps but push more boundary edges to the host merge. Measured sweep
  on the 1-core CPU bench fixture (64^3 dense multilabel, relax):
  (8,16,16) 0.9 Mvox/s → (4,8,8) 1.5 → (2,4,8) 2.1, vs 0.138 for the
  whole-volume kernel — (2,4,8) is the CPU default. On TPU the tile must
  fill the (8, 128) sublane/lane register shape instead: (8,16,128) is
  64KB per int32 working array, ~5 arrays ≈ 320KB of the ~16MB VMEM, so
  a tile's whole round loop runs on-chip with room to double-buffer."""
  from .. import tune

  # explicit env > tuned/<device_kind>.json > backend default (ISSUE 19)
  spec = tune.resolve("IGNEOUS_CCL_TILE")
  if not spec:
    return (
      _DEFAULT_TILE_TPU if jax.default_backend() == "tpu"
      else _DEFAULT_TILE
    )
  try:
    t = tuple(int(v) for v in spec.split(","))
  except ValueError:
    t = ()
  if len(t) != 3 or any(v < 1 for v in t):
    raise ValueError(
      f"IGNEOUS_CCL_TILE must be 'tz,ty,tx' positive ints: {spec!r}"
    )
  return t


def _ccl_engine() -> str:
  """'lax' | 'pallas' for the tile-resolve stage. Pallas engages on real
  TPU backends when the lowering is available; the lax path is the
  portable default (and what the CPU bench host measures). Force with
  IGNEOUS_CCL_ENGINE=lax|pallas (pallas on CPU runs in interpret mode —
  correct but slow; for parity tests)."""
  import os

  override = knobs.get_str("IGNEOUS_CCL_ENGINE")
  if override:
    if override not in ("lax", "pallas"):
      raise ValueError(
        f"IGNEOUS_CCL_ENGINE must be 'lax' or 'pallas': {override!r}"
      )
    return override
  from . import pallas_ccl

  return (
    "pallas"
    if pallas_ccl.available() and jax.default_backend() == "tpu"
    else "lax"
  )


@partial(
  jax.jit, static_argnames=("connectivity", "algo", "tile", "engine")
)
def _ccl_tiled_kernel(
  labels: jnp.ndarray,
  connectivity: int = 6,
  algo: str = "scan",
  tile: Tuple[int, int, int] = _DEFAULT_TILE,
  engine: str = "lax",
):
  """labels (z, y, x) int32 → per-voxel TILE-LOCAL root as a global flat
  index over the tile-padded volume (background: int32 max sentinel).

  The volume is cut into (tz, ty, tx) tiles (clipped to the volume,
  padded with background); every tile runs the same seg-cummin /
  neighbor-min / pointer-jump round structure as _ccl_kernel but over
  LOCAL indices, with a per-tile active mask: a converged tile freezes
  while stragglers keep iterating, and the loop exits when the last tile
  converges — rounds are bounded by tile tortuosity, not volume
  tortuosity. Cross-tile merging happens host-side (_merge_tile_roots)."""
  Z, Y, X = labels.shape
  tz, ty, tx = (min(t, s) for t, s in zip(tile, labels.shape))
  pz, py, px = (-Z) % tz, (-Y) % ty, (-X) % tx
  lab = jnp.pad(labels, ((0, pz), (0, py), (0, px)))
  Zp, Yp, Xp = Z + pz, Y + py, X + px
  nz, ny, nx = Zp // tz, Yp // ty, Xp // tx
  tsize = tz * ty * tx

  def to_tiles(a):
    return (
      a.reshape(nz, tz, ny, ty, nx, tx)
      .transpose(0, 2, 4, 1, 3, 5)
      .reshape(nz * ny * nx, tz, ty, tx)
    )

  labt = to_tiles(lab)
  gidx = to_tiles(
    jnp.arange(Zp * Yp * Xp, dtype=jnp.int32).reshape(Zp, Yp, Xp)
  )
  fg = labt != 0
  big = jnp.iinfo(jnp.int32).max

  if engine == "pallas":
    from . import pallas_ccl

    L = pallas_ccl.tile_resolve(
      labt, connectivity, interpret=jax.default_backend() != "tpu"
    )
  else:
    L0 = jnp.broadcast_to(
      jnp.arange(tsize, dtype=jnp.int32).reshape(1, tz, ty, tx), labt.shape
    )

    def cond(state):
      _, active = state
      return jnp.any(active)

    def body(state):
      L, active = state
      Lp = L
      for axis in (1, 2, 3):
        Lp = jnp.minimum(
          _seg_cummin(Lp, labt, axis, False),
          _seg_cummin(Lp, labt, axis, True),
        )
      Lp = jnp.minimum(
        Lp, _neighbor_min(Lp, labt, connectivity, axes=(1, 2, 3))
      )
      Lp = jnp.where(fg, jnp.minimum(L, Lp), L)
      if algo == "scan":
        flat = Lp.reshape(-1, tsize)
        for _ in range(2):
          flat = jnp.take_along_axis(flat, flat, axis=1)
        Lp = flat.reshape(Lp.shape)
      # per-tile early exit: converged tiles freeze (no further updates)
      Lp = jnp.where(active[:, None, None, None], Lp, L)
      return (Lp, jnp.any(Lp != L, axis=(1, 2, 3)))

    L, _ = jax.lax.while_loop(
      cond, body, (L0, jnp.ones((labt.shape[0],), dtype=bool))
    )

  # local root -> global flat index of that root voxel (in padded space)
  g = jnp.take_along_axis(
    gidx.reshape(-1, tsize), L.reshape(-1, tsize), axis=1
  )
  g = jnp.where(fg.reshape(-1, tsize), g, big).reshape(labt.shape)
  return (
    g.reshape(nz, ny, nx, tz, ty, tx)
    .transpose(0, 3, 1, 4, 2, 5)
    .reshape(Zp, Yp, Xp)[:Z, :Y, :X]
  )


def _merge_tile_roots(
  roots: np.ndarray, labels: np.ndarray, connectivity: int,
  tile: Tuple[int, int, int],
) -> np.ndarray:
  """Exact cross-tile merge (host side) for _ccl_tiled_kernel output.

  roots, labels: (z, y, x) — tile-local roots (int32 global flat indices,
  int32-max sentinel = background) and the dense input labels. Every
  neighbor offset of the connectivity contributes (root_a, root_b) edges
  for equal-nonzero-label voxel pairs that straddle a tile boundary;
  connected components over those edges (scipy csgraph) pick each merged
  group's minimum root as its representative. Only boundary-straddling
  pairs matter — within-tile pairs are already resolved — so edge volume
  scales with tile surface, not volume."""
  Z, Y, X = labels.shape
  tzyx = tuple(min(t, s) for t, s in zip(tile, labels.shape))
  coords = [np.arange(s) // t for s, t in zip((Z, Y, X), tzyx)]
  pa, pb = [], []
  for off in neighbor_offsets(connectivity):
    if off < (0, 0, 0):  # each unordered pair once (lexicographic half)
      continue
    src = tuple(
      slice(max(0, -d), s - max(0, d)) for d, s in zip(off, (Z, Y, X))
    )
    dst = tuple(
      slice(max(0, d), s - max(0, -d)) for d, s in zip(off, (Z, Y, X))
    )
    cross = None
    for a, d in enumerate(off):
      if d == 0:
        continue
      line = coords[a][src[a]] != coords[a][dst[a]]
      shape1 = [1, 1, 1]
      shape1[a] = line.size
      line = line.reshape(shape1)
      cross = line if cross is None else (cross | line)
    m = cross & (labels[src] != 0) & (labels[src] == labels[dst])
    if m.any():
      pa.append(roots[src][m])
      pb.append(roots[dst][m])
  if not pa:
    return roots
  ra = np.concatenate(pa)
  rb = np.concatenate(pb)
  nodes = np.unique(np.concatenate([ra, rb]))
  from scipy import sparse
  from scipy.sparse import csgraph

  g = sparse.coo_matrix(
    (
      np.ones(len(ra), dtype=np.int8),
      (np.searchsorted(nodes, ra), np.searchsorted(nodes, rb)),
    ),
    shape=(len(nodes), len(nodes)),
  )
  _, grp = csgraph.connected_components(g, directed=False)
  rep = np.full(int(grp.max()) + 1, np.iinfo(np.int64).max, dtype=np.int64)
  np.minimum.at(rep, grp, nodes.astype(np.int64))
  mapped = rep[grp].astype(roots.dtype)
  # remap: only roots that appear in a boundary edge can change
  flat = roots.reshape(-1)
  pos = np.searchsorted(nodes, flat)
  pos_c = np.minimum(pos, len(nodes) - 1)
  hit = nodes[pos_c] == flat
  out = flat.copy()
  out[hit] = mapped[pos_c[hit]]
  return out.reshape(roots.shape)


def _ccl_tiled(
  labels_zyx: np.ndarray, connectivity: int, algo: str
) -> np.ndarray:
  """Device tiled resolve + host boundary merge → merged roots (z, y, x)."""
  tile = _tile_shape()
  roots = np.asarray(
    _ccl_tiled_kernel(
      jnp.asarray(labels_zyx), connectivity, algo=algo, tile=tile,
      engine=_ccl_engine(),
    )
  )
  return _merge_tile_roots(roots, labels_zyx, connectivity, tile)


def _ccl_native(labels: np.ndarray, connectivity: int):
  """Two-pass union-find in C++ (native/csrc/ccl.cpp); None if the
  toolchain is unavailable. Output numbering matches the device path."""
  import ctypes

  from ..native import ccl_lib

  lib = ccl_lib()
  if lib is None:
    return None
  # (z, y, x) C-contiguous = Fortran scan order for the (x, y, z) array
  t = np.ascontiguousarray(labels.transpose(2, 1, 0))
  if t.dtype.itemsize <= 4:
    if t.dtype.itemsize < 4:
      t = t.astype(np.int32)
    t = t.view(np.int32)
    fn = lib.ccl_ml32
  else:
    t = t.view(np.int64)
    fn = lib.ccl_ml64
  out = np.empty(t.shape, dtype=np.int32)
  n = fn(
    t.ctypes.data_as(ctypes.c_void_p), out.ctypes.data_as(ctypes.c_void_p),
    t.shape[0], t.shape[1], t.shape[2], int(connectivity),
  )
  return out.transpose(2, 1, 0).astype(np.uint32), int(n)


def _device_algo() -> str:
  import os

  algo = knobs.get_str("IGNEOUS_CCL_DEVICE_ALGO")
  if algo not in ("scan", "relax"):
    raise ValueError(
      f"IGNEOUS_CCL_DEVICE_ALGO must be 'scan' or 'relax': {algo!r}"
    )
  return algo


def _ccl_backend() -> str:
  import os

  override = knobs.get_str("IGNEOUS_CCL_BACKEND")
  if override:
    if override not in ("native", "device"):
      raise ValueError(
        f"IGNEOUS_CCL_BACKEND must be 'native' or 'device': {override!r}"
      )
    return override
  platforms = os.environ.get("JAX_PLATFORMS", "")
  if platforms:
    return "native" if platforms.split(",")[0] == "cpu" else "device"
  return "device" if jax.default_backend() != "cpu" else "native"


def connected_components(
  labels: np.ndarray, connectivity: int = 6, return_N: bool = False
):
  """cc3d-equivalent block CCL. labels: (x, y, z) any integer dtype.

  Returns components renumbered 1..N in order of each component's first
  voxel in Fortran (x-fastest) scan order; 0 stays background.
  Deterministic across recomputation. Dispatches to the device kernel on
  accelerator backends and the native C++ two-pass union-find on CPU
  hosts (override with IGNEOUS_CCL_BACKEND=native|device) — both
  orderings are identical, so the 4-pass CCL protocol's recompute
  determinism holds across backends.
  """
  if labels.ndim != 3:
    raise ValueError("labels must be (x, y, z)")
  neighbor_offsets(connectivity)  # validate on EVERY backend, same error
  if labels.size == 0:
    out = np.zeros(labels.shape, dtype=np.uint32)
    return (out, 0) if return_N else out

  if _ccl_backend() == "native":
    got = _ccl_native(labels, connectivity)
    if got is not None:
      out, N = got
      return (out, N) if return_N else out
    # no toolchain: fall through to the device kernel

  lab32 = _dense_relabel(labels)

  # device layout (z, y, x): x innermost on lanes
  zyx = np.ascontiguousarray(lab32.transpose(2, 1, 0))
  roots = _ccl_tiled(zyx, connectivity, _device_algo()).transpose(2, 1, 0)

  out = _roots_to_components(roots)
  N = int(out.max())
  if return_N:
    return out, N
  return out


def dust(
  labels: np.ndarray, threshold: int, connectivity: int = 6,
  in_place: bool = False,
) -> np.ndarray:
  """cc3d.dust parity: zero out connected components smaller than
  ``threshold`` voxels (reference call site
  /root/reference/igneous/tasks/image/ccl.py:168-171). Components are
  evaluated per-label (a multilabel image's touching distinct labels stay
  distinct components)."""
  if threshold <= 0:
    return labels
  cc = connected_components(labels, connectivity=connectivity)
  counts = np.bincount(cc.ravel())
  small = counts < int(threshold)
  small[0] = False  # background is never dusted
  if not in_place:
    labels = labels.copy()
  labels[small[cc]] = 0
  return labels


def _dense_relabel(labels: np.ndarray) -> np.ndarray:
  """Compress any integer dtype to int32 dense ids for the device kernel
  (multilabel equality only needs label-identity). Background zero keeps
  dense id 0; every real label gets a positive id — including when signed
  inputs sort negatives before zero, or when zero is absent entirely."""
  uniq, inv = np.unique(labels, return_inverse=True)
  lab32 = inv.astype(np.int32).reshape(labels.shape)
  if not np.any(uniq == 0):
    # no zero present: keep everything foreground (checking membership,
    # not uniq[0] — signed inputs can sort negatives before zero)
    lab32 = lab32 + 1
  elif uniq[0] != 0:
    # zero present but not first (negative labels): make zero's dense id 0
    zero_pos = int(np.searchsorted(uniq, 0))
    lab32 = np.where(
      lab32 == zero_pos, 0, np.where(lab32 < zero_pos, lab32 + 1, lab32)
    ).astype(np.int32)
  return lab32


def _roots_to_components(roots: np.ndarray) -> np.ndarray:
  """Root flat-indices (x, y, z) → components renumbered 1..N in Fortran
  (x-fastest) first-appearance order; background (sentinel) stays 0."""
  big = np.iinfo(np.int32).max
  fg = roots != big
  if not fg.any():
    return np.zeros(roots.shape, dtype=np.uint32)
  flat_f = roots.reshape(-1, order="F")
  fg_f = fg.reshape(-1, order="F")
  seen, first_pos = np.unique(flat_f[fg_f], return_index=True)
  order = np.argsort(first_pos, kind="stable")
  rank = np.empty(len(seen), dtype=np.uint32)
  rank[order] = np.arange(1, len(seen) + 1, dtype=np.uint32)
  comp = rank[np.searchsorted(seen, flat_f[fg_f])]
  out_f = np.zeros(flat_f.shape, dtype=np.uint32)
  out_f[fg_f] = comp
  return out_f.reshape(roots.shape, order="F")


# executors (and their jit caches) are reused per connectivity so repeat
# batches of the same shape never recompile
_BATCH_EXECUTORS = {}


def _batch_executor(connectivity: int, mesh=None):
  algo = _device_algo()
  tile = _tile_shape()
  engine = _ccl_engine()
  mesh_key = (
    None if mesh is None
    else (tuple(d.id for d in mesh.devices.flat), mesh.axis_names)
  )
  key = (connectivity, algo, tile, engine, mesh_key)
  if key not in _BATCH_EXECUTORS:
    from ..parallel.executor import BatchKernelExecutor

    _BATCH_EXECUTORS[key] = BatchKernelExecutor(
      partial(
        _ccl_tiled_kernel, connectivity=connectivity, algo=algo,
        tile=tile, engine=engine,
      ),
      mesh=mesh,
      name=f"ccl.tiled[{algo}]",
      cache_variant=("ccl_tiled", connectivity, algo, tile, engine),
    )
  return _BATCH_EXECUTORS[key]


def connected_components_batch(
  labels_batch: np.ndarray, connectivity: int = 6, executor=None
):
  """Batched block CCL: (K, x, y, z) → list of K component volumes, each
  numbered exactly as connected_components would number it alone.

  One shard_map'd device dispatch labels all K cutouts with the chunk
  axis partitioned across the mesh (SURVEY.md §5.8 / VERDICT item 3);
  the per-chunk renumber stays host-side and is unchanged, so outputs are
  byte-identical to the per-task path.
  """
  labels_batch = np.asarray(labels_batch)
  if labels_batch.ndim != 4:
    raise ValueError("labels_batch must be (K, x, y, z)")
  if executor is None and _ccl_backend() == "native":
    # CPU-only host: per-cutout native union-find IS the fast path (the
    # device kernel on XLA CPU is orders of magnitude slower); an
    # explicit executor means the caller already chose the device route
    return [connected_components(b, connectivity) for b in labels_batch]
  lab32 = _dense_relabel(labels_batch)
  dev = np.ascontiguousarray(lab32.transpose(0, 3, 2, 1))  # (K, z, y, x)
  if executor is None:
    executor = _batch_executor(connectivity)
  roots = executor(dev)  # (K, z, y, x) tile-local roots
  tile = _tile_shape()
  return [
    _roots_to_components(
      _merge_tile_roots(np.asarray(r), dev[k], connectivity, tile)
      .transpose(2, 1, 0)
    )
    for k, r in enumerate(roots)
  ]


def threshold_image(
  img: np.ndarray,
  threshold_gte: Optional[float] = None,
  threshold_lte: Optional[float] = None,
) -> np.ndarray:
  """Grayscale → binary foreground (reference ccl.py:89-101)."""
  if threshold_gte is None and threshold_lte is None:
    return img
  fg = np.ones(img.shape, dtype=bool)
  if threshold_gte is not None:
    fg &= img >= threshold_gte
  if threshold_lte is not None:
    fg &= img <= threshold_lte
  return fg.astype(np.uint8)


class DisjointSet:
  """Path-compressed union-find over arbitrary int labels
  (reference ccl.py:48-73; the single-machine global merge structure)."""

  def __init__(self):
    self.parent = {}

  def makeset(self, x: int):
    if x not in self.parent:
      self.parent[x] = x

  def find(self, x: int) -> int:
    self.makeset(x)
    root = x
    while self.parent[root] != root:
      root = self.parent[root]
    while self.parent[x] != root:  # path compression
      self.parent[x], x = root, self.parent[x]
    return root

  def union(self, x: int, y: int):
    rx, ry = self.find(x), self.find(y)
    if rx != ry:
      if rx > ry:
        rx, ry = ry, rx
      self.parent[ry] = rx

  def renumber(self, start: int = 1):
    """{label: dense component id} over every seen label."""
    out = {}
    next_id = {}
    counter = start
    for x in sorted(self.parent):
      r = self.find(x)
      if r not in next_id:
        next_id[r] = counter
        counter += 1
      out[x] = next_id[r]
    return out, counter - 1


# ---------------------------------------------------------------------------
# cc3d feature parity: voxel connectivity graph + statistics


def graph_bit(off) -> int:
  """Bit index for neighbor offset (dx, dy, dz) in the voxel connectivity
  graph: linear index over (dz, dy, dx) in {-1,0,1}^3 with the center
  skipped. Documented layout — consumers (skeletonize voxel_graph) use
  these helpers rather than assuming cc3d's internal ordering."""
  dx, dy, dz = off
  lin = (dz + 1) * 9 + (dy + 1) * 3 + (dx + 1)
  if lin == 13:
    raise ValueError("no bit for the center offset")
  return lin if lin < 13 else lin - 1


def voxel_connectivity_graph(
  labels: np.ndarray, connectivity: int = 26, pair_allowed=None
) -> np.ndarray:
  """Per-voxel uint32 bitfield: bit set when the neighbor in that
  direction is in-bounds and connected — by default, holds the same
  nonzero label; ``pair_allowed(src_vals, dst_vals) -> bool array``
  substitutes a custom predicate (the graphene chunk-graph uses edge-set
  membership).

  Capability parity with cc3d.voxel_connectivity_graph (used by the
  reference's graphene autapse fix, /root/reference/igneous/tasks/
  skeleton.py:368-377, to confine skeleton traces within proofread
  boundaries); kimimaro consumes it as a movement constraint, which
  ops.skeletonize mirrors via its voxel_graph parameter.
  labels: (x, y, z). Pure numpy — consumers are host-side graph builders.
  """
  if labels.ndim != 3:
    raise ValueError("labels must be (x, y, z)")
  out = np.zeros(labels.shape, dtype=np.uint32)
  fg = labels != 0
  for dz, dy, dx in neighbor_offsets(connectivity):
    off = (dx, dy, dz)
    src = tuple(
      slice(max(0, -d), labels.shape[a] - max(0, d))
      for a, d in enumerate(off)
    )
    dst = tuple(
      slice(max(0, d), labels.shape[a] - max(0, -d))
      for a, d in enumerate(off)
    )
    if pair_allowed is None:
      conn = fg[src] & (labels[src] == labels[dst])
    else:
      conn = fg[src] & pair_allowed(labels[src], labels[dst])
    out[src] |= conn.astype(np.uint32) << np.uint32(graph_bit(off))
  return out


def statistics(labels: np.ndarray) -> dict:
  """cc3d.statistics parity: per-component voxel counts, bounding boxes,
  and centroids for a 1..N-labeled volume (0 = background).

  Returns {"voxel_counts": (N+1,), "bounding_boxes": [(slice,)*3]*(N+1),
  "centroids": (N+1, 3)} indexed by label; entry 0 (background) and labels
  absent from the volume have NaN centroids, matching cc3d.
  Reference call sites: cc3d.statistics at
  /root/reference/igneous/task_creation/image.py:2074-2076 (ROI detection).
  """
  from scipy import ndimage

  labels = np.asarray(labels)
  N = int(labels.max()) if labels.size else 0
  counts = np.bincount(labels.reshape(-1), minlength=N + 1).astype(np.uint64)
  objs = ndimage.find_objects(labels.astype(np.int64, copy=False))
  boxes = [
    tuple(slice(0, s) for s in labels.shape)
  ] + [o for o in objs]
  centroids = np.full((N + 1, 3), np.nan, dtype=np.float64)
  if N:
    # center_of_mass needs only a bool weight volume — no float64
    # coordinate volumes; absent labels come back NaN
    with np.errstate(invalid="ignore"):
      cent = ndimage.center_of_mass(
        labels != 0, labels, np.arange(1, N + 1)
      )
    centroids[1:] = np.asarray(cent, dtype=np.float64).reshape(N, 3)
  return {
    "voxel_counts": counts,
    "bounding_boxes": boxes,
    "centroids": centroids,
  }
