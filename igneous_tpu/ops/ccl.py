"""Block-local connected components labeling on device — cc3d parity.

Replaces the reference's cc3d C++ kernel for the block-local pass of
whole-image CCL (/root/reference/igneous/tasks/image/ccl.py:126-194 uses
cc3d.connected_components per task; the global merge stays host-side union
find, SURVEY.md §2.3).

Algorithm (TPU-first): label-propagation with pointer doubling.
Each foreground voxel starts as its own flat index; every round takes the
min over same-label 6-neighbors, then path-compresses by gathering
L[L] (pointer jumping) — convergence in O(log diameter) rounds instead of
O(diameter) for plain relaxation. Multilabel semantics match cc3d: two
voxels connect iff their input labels are equal and nonzero.

Output labels are the component's minimum flat index + 1 — deterministic,
so the 4-pass CCL protocol can recompute identical labels in later passes
(ccl.py relies on this, reference ccl.py:296-356). Host-side ``relabel``
renumbers to 1..N in first-scan order.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _neighbor_min(L: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
  """One 6-connected min-propagation step. L, labels: (z, y, x)."""
  big = jnp.iinfo(jnp.int32).max

  def shifted_min(L, axis, direction):
    # neighbor along +axis or -axis; out-of-range neighbors are background
    nb_L = jnp.roll(L, direction, axis=axis)
    nb_lab = jnp.roll(labels, direction, axis=axis)
    # kill the wrapped plane
    size = labels.shape[axis]
    coord = jax.lax.broadcasted_iota(jnp.int32, labels.shape, axis)
    valid = coord != (0 if direction == 1 else size - 1)
    same = valid & (nb_lab == labels)
    return jnp.where(same, nb_L, big)

  m = L
  for axis in (0, 1, 2):
    for direction in (1, -1):
      m = jnp.minimum(m, shifted_min(L, axis, direction))
  return m


def _compress(L: jnp.ndarray, iters: int = 2) -> jnp.ndarray:
  flat = L.reshape(-1)
  for _ in range(iters):
    flat = flat[flat]
  return flat.reshape(L.shape)


@jax.jit
def _ccl_kernel(labels: jnp.ndarray) -> jnp.ndarray:
  """labels: (z, y, x) int32 (0 = background) → component roots (flat
  min-index per component; background stays huge sentinel)."""
  n = labels.size
  idx = jnp.arange(n, dtype=jnp.int32).reshape(labels.shape)
  fg = labels != 0
  big = jnp.iinfo(jnp.int32).max
  L0 = jnp.where(fg, idx, idx)  # background points at itself (inert)

  def cond(state):
    _, changed = state
    return changed

  def body(state):
    L, _ = state
    Lp = _neighbor_min(L, labels)
    Lp = jnp.where(fg, jnp.minimum(L, Lp), L)
    Lp = _compress(Lp, iters=2)
    changed = jnp.any(Lp != L)
    return (Lp, changed)

  L, _ = jax.lax.while_loop(cond, body, (L0, jnp.bool_(True)))
  return jnp.where(fg, L, big)


def connected_components(
  labels: np.ndarray, connectivity: int = 6, return_N: bool = False
):
  """cc3d-equivalent block CCL. labels: (x, y, z) any integer dtype.

  Returns components renumbered 1..N in order of each component's first
  voxel in Fortran (x-fastest) scan order; 0 stays background. Deterministic
  across recomputation.
  """
  if connectivity != 6:
    raise NotImplementedError("only 6-connectivity is implemented")
  if labels.ndim != 3:
    raise ValueError("labels must be (x, y, z)")

  # multilabel equality only needs label-identity: compress any dtype to
  # int32 via dense renumbering (cheap: sort-based)
  uniq, inv = np.unique(labels, return_inverse=True)
  lab32 = inv.astype(np.int32).reshape(labels.shape)
  if uniq[0] != 0:
    lab32 = lab32 + 1  # no zero present: keep everything foreground

  # device layout (z, y, x): x innermost on lanes
  dev = jnp.asarray(np.ascontiguousarray(lab32.transpose(2, 1, 0)))
  roots = np.asarray(_ccl_kernel(dev)).transpose(2, 1, 0)  # (x, y, z)

  big = np.iinfo(np.int32).max
  fg = roots != big
  out = np.zeros(labels.shape, dtype=np.uint32)
  if fg.any():
    # root values are flat indices in (z,y,x) C-order; renumber components
    # in Fortran-scan first-appearance order for cc3d-like numbering
    flat_f = roots.reshape(-1, order="F")
    fg_f = fg.reshape(-1, order="F")
    seen, first_pos = np.unique(flat_f[fg_f], return_index=True)
    order = np.argsort(first_pos, kind="stable")
    rank = np.empty(len(seen), dtype=np.uint32)
    rank[order] = np.arange(1, len(seen) + 1, dtype=np.uint32)
    comp = rank[np.searchsorted(seen, flat_f[fg_f])]
    out_f = np.zeros(flat_f.shape, dtype=np.uint32)
    out_f[fg_f] = comp
    out = out_f.reshape(labels.shape, order="F")
  N = int(out.max())
  if return_N:
    return out, N
  return out


def threshold_image(
  img: np.ndarray,
  threshold_gte: Optional[float] = None,
  threshold_lte: Optional[float] = None,
) -> np.ndarray:
  """Grayscale → binary foreground (reference ccl.py:89-101)."""
  if threshold_gte is None and threshold_lte is None:
    return img
  fg = np.ones(img.shape, dtype=bool)
  if threshold_gte is not None:
    fg &= img >= threshold_gte
  if threshold_lte is not None:
    fg &= img <= threshold_lte
  return fg.astype(np.uint8)


class DisjointSet:
  """Path-compressed union-find over arbitrary int labels
  (reference ccl.py:48-73; the single-machine global merge structure)."""

  def __init__(self):
    self.parent = {}

  def makeset(self, x: int):
    if x not in self.parent:
      self.parent[x] = x

  def find(self, x: int) -> int:
    self.makeset(x)
    root = x
    while self.parent[root] != root:
      root = self.parent[root]
    while self.parent[x] != root:  # path compression
      self.parent[x], x = root, self.parent[x]
    return root

  def union(self, x: int, y: int):
    rx, ry = self.find(x), self.find(y)
    if rx != ry:
      if rx > ry:
        rx, ry = ry, rx
      self.parent[ry] = rx

  def renumber(self, start: int = 1):
    """{label: dense component id} over every seen label."""
    out = {}
    next_id = {}
    counter = start
    for x in sorted(self.parent):
      r = self.find(x)
      if r not in next_id:
        next_id[r] = counter
        counter += 1
      out[x] = next_id[r]
    return out, counter - 1
