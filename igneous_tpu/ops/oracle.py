"""Numpy reference implementations (oracles) for device kernels.

Every Pallas/XLA kernel in ops/ has a numpy twin here defining its exact
semantics; tests assert device == oracle (the pattern the reference uses
with its kernel libraries, e.g. /root/reference/test/test_tasks.py:57-71
asserting task output == tinybrain recomputation).
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np


def _np_windows(img: np.ndarray, f) -> np.ndarray:
  """(x,y,z,c) → (X,Y,Z,c,n) with window order z-major, then y, then x —
  matching ops.downsample's device flattening order."""
  fx, fy, fz = int(f[0]), int(f[1]), int(f[2])
  sx, sy, sz, c = img.shape
  px, py, pz = (-sx) % fx, (-sy) % fy, (-sz) % fz
  if px or py or pz:
    img = np.pad(img, ((0, px), (0, py), (0, pz), (0, 0)), mode="edge")
  sx, sy, sz, c = img.shape
  v = img.reshape(sx // fx, fx, sy // fy, fy, sz // fz, fz, c)
  # window axis order (fz, fy, fx): z-major
  v = v.transpose(0, 2, 4, 6, 5, 3, 1)
  return v.reshape(sx // fx, sy // fy, sz // fz, c, fz * fy * fx)


def np_downsample_with_averaging(
  img: np.ndarray, factor, num_mips: int = 1
) -> List[np.ndarray]:
  squeeze = img.ndim == 3
  if squeeze:
    img = img[..., np.newaxis]
  outs = []
  cur = img
  for _ in range(num_mips):
    w = _np_windows(cur, factor)
    n = w.shape[-1]
    if np.issubdtype(img.dtype, np.floating):
      cur = np.mean(w.astype(np.float32), axis=-1).astype(img.dtype)
    else:
      # exact int64 accumulation; the device matches this exactly for
      # <=16-bit dtypes and for 32-bit dtypes with power-of-two windows
      # (its documented float32 fallback covers the remaining cases)
      acc = np.sum(w.astype(np.int64), axis=-1)
      cur = ((acc + n // 2) // n).astype(img.dtype)
    outs.append(cur[..., 0] if squeeze else cur)
  return outs


def np_downsample_segmentation(
  img: np.ndarray, factor, num_mips: int = 1, sparse: bool = False
) -> List[np.ndarray]:
  squeeze = img.ndim == 3
  if squeeze:
    img = img[..., np.newaxis]
  outs = []
  cur = img
  for _ in range(num_mips):
    w = _np_windows(cur, factor)  # (..., n)
    n = w.shape[-1]
    counts = np.zeros(w.shape, dtype=np.int32)
    for j in range(n):
      counts += (w == w[..., j : j + 1]).astype(np.int32)
    pos = np.arange(n, dtype=np.int32)
    score = counts * n - pos
    if sparse:
      score = np.where(w == 0, -1, score)
    winner = np.argmax(score, axis=-1)
    cur = np.take_along_axis(w, winner[..., None], axis=-1)[..., 0]
    outs.append(cur[..., 0] if squeeze else cur)
  return outs


def np_downsample_minmax(img, factor, op: str, num_mips: int = 1):
  squeeze = img.ndim == 3
  if squeeze:
    img = img[..., np.newaxis]
  outs = []
  cur = img
  for _ in range(num_mips):
    w = _np_windows(cur, factor)
    cur = np.min(w, axis=-1) if op == "min" else np.max(w, axis=-1)
    outs.append(cur[..., 0] if squeeze else cur)
  return outs


def np_downsample_striding(img, factor, num_mips: int = 1):
  squeeze = img.ndim == 3
  if squeeze:
    img = img[..., np.newaxis]
  fx, fy, fz = [int(v) for v in factor]
  outs = []
  cur = img
  for _ in range(num_mips):
    cur = cur[::fx, ::fy, ::fz]
    outs.append(cur[..., 0] if squeeze else cur)
  return outs


# ---------------------------------------------------------------------------
# native CPU comparator (bench baseline) — semantics twins of the numpy
# oracles above at C speed; the closest in-image stand-in for tinybrain


def _native_pyramid(img, factor, num_mips, dtype, run_mip):
  """Shared mip-pyramid scaffold for the native pooling comparators."""
  from ..native import pooling_lib

  lib = pooling_lib()
  if lib is None or img.dtype != dtype or img.ndim != 3:
    return None
  outs = []
  cur = np.ascontiguousarray(img)
  fx, fy, fz = (int(f) for f in factor)
  for _ in range(num_mips):
    nx, ny, nz = cur.shape
    out = np.empty(
      ((nx + fx - 1) // fx, (ny + fy - 1) // fy, (nz + fz - 1) // fz),
      dtype=dtype,
    )
    run_mip(lib, cur, out, (nx, ny, nz), (fx, fy, fz))
    outs.append(out)
    cur = out
  return outs


def native_downsample_with_averaging(img, factor, num_mips=1, parallel=0):
  """uint8 average pyramid via native/csrc/pooling.cpp; None if the
  toolchain is unavailable."""
  import ctypes

  def run(lib, cur, out, dims, f):
    lib.pool_avg_u8(
      cur.ctypes.data_as(ctypes.c_void_p), out.ctypes.data_as(ctypes.c_void_p),
      *dims, *f, int(parallel),
    )

  return _native_pyramid(img, factor, num_mips, np.uint8, run)


def native_downsample_segmentation(img, factor, num_mips=1, sparse=False,
                                   parallel=0):
  """uint64 mode pyramid via native/csrc/pooling.cpp; None if unavailable."""
  import ctypes

  def run(lib, cur, out, dims, f):
    lib.pool_mode_u64(
      cur.ctypes.data_as(ctypes.c_void_p), out.ctypes.data_as(ctypes.c_void_p),
      *dims, *f, int(bool(sparse)), int(parallel),
    )

  return _native_pyramid(img, factor, num_mips, np.uint64, run)
