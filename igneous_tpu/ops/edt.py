"""Multilabel anisotropic Euclidean distance transform on device.

The flop-heavy core of skeletonization (kimimaro's bundled ``edt`` C++
library — SURVEY.md §2.3, /root/reference/igneous/tasks/skeleton.py:303-335
runs it inside kimimaro.skeletonize). Semantics (oracle: scipy per label):
for every nonzero voxel, the anisotropic distance to the nearest voxel
center holding a DIFFERENT label (background voxels read 0).

TPU-first formulation: three axis passes, each a label-aware *tropical
(min-plus) matrix product* over lines:

    out[b, i] = min_j ( keep(b, j, i) + (i - j)^2 w^2 )
    keep(b, j, i) = val[b, j]  if label[b, j] == label[b, i]  else 0

Exactness: the per-axis decomposition of min_u ||v-u||² is valid for any
target set; when the line voxel j already has a different label than i,
its in-line/in-plane contribution is 0 (the voxel itself is a target),
which the mask term implements — so label handling stays exact through
all three passes. Each pass is a dense (B, n, n) broadcast-min: exactly
the regular, batched arithmetic the VPU eats, instead of the reference's
sequential parabola-envelope scans.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

INF = np.float32(1e20)


# peak bytes allowed for one tile's (BT, n, n) contrib tensor
_TILE_BUDGET = 1 << 28  # 256 MB


def _axis_pass(val: jnp.ndarray, lab: jnp.ndarray, w: float) -> jnp.ndarray:
  """One min-plus pass along the LAST axis. val, lab: (..., n).

  Lines are processed in scan tiles so the (tile, n, n) contribution
  tensor stays within a fixed memory budget — the full (lines, n, n)
  broadcast would need N·n·4 bytes (hundreds of GB at 512³)."""
  n = val.shape[-1]
  lead = val.shape[:-1]
  B = int(np.prod(lead)) if lead else 1
  bt = max(1, min(B, _TILE_BUDGET // max(n * n * 4, 1)))
  nb = -(-B // bt)

  v = val.reshape(B, n)
  l = lab.reshape(B, n)
  if nb * bt != B:
    pad = nb * bt - B
    v = jnp.concatenate([v, jnp.full((pad, n), INF, jnp.float32)])
    l = jnp.concatenate([l, jnp.zeros((pad, n), l.dtype)])
  v = v.reshape(nb, bt, n)
  l = l.reshape(nb, bt, n)

  i = jnp.arange(n, dtype=jnp.float32)
  cost = ((i[None, :] - i[:, None]) * w) ** 2  # (j, i)

  def tile(_, args):
    tv, tl = args  # (bt, n)
    same = tl[:, :, None] == tl[:, None, :]  # (bt, j, i)
    contrib = jnp.where(same, tv[:, :, None], 0.0) + cost[None]
    return None, jnp.min(contrib, axis=1)

  _, out = jax.lax.scan(tile, None, (v, l))
  return out.reshape(nb * bt, n)[:B].reshape(*lead, n)


@partial(jax.jit, static_argnames=("anisotropy",))
def _edt_sq_kernel(labels: jnp.ndarray, anisotropy: Tuple[float, float, float]):
  """labels (z, y, x) int32 → squared EDT float32; three tiled passes."""
  wx, wy, wz = anisotropy
  val = jnp.full(labels.shape, INF, dtype=jnp.float32)

  # pass along x (last axis)
  val = _axis_pass(val, labels, wx)
  # pass along y
  val = jnp.swapaxes(_axis_pass(
    jnp.swapaxes(val, 1, 2), jnp.swapaxes(labels, 1, 2), wy
  ), 1, 2)
  # pass along z
  val = jnp.moveaxis(_axis_pass(
    jnp.moveaxis(val, 0, 2), jnp.moveaxis(labels, 0, 2), wz
  ), 2, 0)

  return jnp.where(labels == 0, 0.0, val)


def edt(
  labels: np.ndarray,
  anisotropy: Sequence[float] = (1.0, 1.0, 1.0),
  black_border: bool = False,
) -> np.ndarray:
  """labels: (x, y, z) integers → float32 distances, same layout.

  black_border treats the array boundary as background (kimimaro uses this
  so skeletons stay inside the cutout).
  """
  if labels.ndim != 3:
    raise ValueError("labels must be 3d")
  orig_shape = labels.shape
  work = labels
  if black_border:
    work = np.pad(labels, 1, mode="constant", constant_values=0)

  # compress labels to int32 identity space (values only matter by equality)
  uniq, inv = np.unique(work, return_inverse=True)
  lab32 = inv.astype(np.int32).reshape(work.shape)
  if uniq[0] != 0:
    lab32 += 1

  dev = jnp.asarray(np.ascontiguousarray(lab32.transpose(2, 1, 0)))
  wx, wy, wz = (float(a) for a in anisotropy)
  sq = np.asarray(_edt_sq_kernel(dev, (wx, wy, wz))).transpose(2, 1, 0)
  if black_border:
    sq = sq[1:-1, 1:-1, 1:-1]
  out = np.sqrt(sq, dtype=np.float32)
  out[labels == 0] = 0.0
  return out.reshape(orig_shape)
