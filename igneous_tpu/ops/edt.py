"""Multilabel anisotropic Euclidean distance transform on device.

The flop-heavy core of skeletonization (kimimaro's bundled ``edt`` C++
library — SURVEY.md §2.3, /root/reference/igneous/tasks/skeleton.py:303-335
runs it inside kimimaro.skeletonize). Semantics (oracle: scipy per label):
for every nonzero voxel, the anisotropic distance to the nearest voxel
center holding a DIFFERENT label (background voxels read 0).

TPU-first formulation: three axis passes of a label-aware min-plus
product over lines,

    out[b, i] = min_j ( keep(b, j, i) + (i - j)^2 w^2 )
    keep(b, j, i) = val[b, j]  if label[b, j] == label[b, i]  else 0

decomposed exactly into two data-parallel pieces per pass (round-2
replacement for the dense (B, n, n) broadcast, which was O(n^4) per axis
and lost to CPU at production sizes):

  1. *Run-edge term* — the best different-label j. Labels form runs along
     the line; the nearest different-label voxel is the one just past i's
     own run boundary, and cost is monotone in |i-j|, so this term is
     (distance to own-run edge)^2 w^2 — two O(n) cumulative scans.
  2. *Same-run lower envelope* — the best same-label j. A same-label j
     beyond an interposed different-label run is always dominated by that
     interposed voxel (|i-k| < |i-j| and val >= 0), so only j inside i's
     OWN run matter. Within a run this is the classic 1D squared-distance
     min-plus, solved by the Felzenszwalb-Huttenlocher parabola envelope:
     O(n) work per line, run here as a lax.scan over line positions
     vectorized across ALL lines at once (B lanes per step). Run
     boundaries reset the envelope via segmented stacks: each run's
     envelope occupies its own monotonically-allocated region of a
     (B, 2n) stack, with a one-slot gap so the +inf top sentinel of a
     finished run survives the next run's first push.

Exactness of the per-axis decomposition for any target set: when line
voxel j already has a different label than i, its in-line contribution is
0 (the voxel itself is a target), which the edge term implements; heights
are normalized by w^2 inside the envelope so float32 intersection
arithmetic stays in a safe magnitude range at any anisotropy.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis import knobs

INF = np.float32(1e20)


def _edge_term(lab: jnp.ndarray, w: float) -> jnp.ndarray:
  """(distance to nearest different-label voxel along the line)^2 w^2."""
  B, n = lab.shape
  idx = jnp.arange(n, dtype=jnp.int32)
  chg = lab[:, 1:] != lab[:, :-1]  # change at k means lab[k] != lab[k-1]
  big = np.int32(2 * n)
  # left: start s of i's run = last change position <= i; different voxel
  # at s-1, distance i-s+1. No change to the left -> run starts at 0 -> inf.
  starts = jnp.concatenate(
    [jnp.full((B, 1), -big, jnp.int32), jnp.where(chg, idx[1:], -big)],
    axis=1,
  )
  left = jax.lax.cummax(starts, axis=1)
  dl = jnp.where(left >= 1, (idx[None] - left + 1).astype(jnp.float32), INF)
  # right: first change position k > i; different voxel at k, distance k-i.
  nxt = jnp.concatenate(
    [jnp.where(chg, idx[1:], big), jnp.full((B, 1), big, jnp.int32)],
    axis=1,
  )
  right = jax.lax.cummin(nxt, axis=1, reverse=True)
  dr = jnp.where(right <= n - 1, (right - idx[None]).astype(jnp.float32), INF)
  d = jnp.minimum(dl, dr)
  return jnp.where(d >= INF, INF, (d * w) ** 2)


def _take(arr: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
  """Per-lane gather arr[b, idx[b]] for (B, S) arr, (B,) idx."""
  return jnp.take_along_axis(arr, idx[:, None], axis=1)[:, 0]


def _envelope_pass(val: jnp.ndarray, lab: jnp.ndarray, w: float) -> jnp.ndarray:
  """Same-run parabola-envelope min-plus along the last axis.

  val, lab: (B, n). Returns min_j in i's run of val[j] + (i-j)^2 w^2.
  Heights are carried as val/w^2 so envelope intersections stay ~n^2 in
  magnitude regardless of anisotropy (float32-safe); the result is
  rescaled by w^2 on the way out.
  """
  B, n = val.shape
  S = 2 * n + 2  # stack slots: <=1 push per column + 1 gap slot per run
  w2 = np.float32(w * w)
  f = jnp.where(val >= INF, INF, val / w2)  # normalized heights
  chg = jnp.concatenate(
    [
      jnp.ones((B, 1), bool),
      lab[:, 1:] != lab[:, :-1],
    ],
    axis=1,
  )
  finite = f < INF / 2

  qs = jnp.arange(n, dtype=jnp.float32)
  rows = jnp.arange(B)

  def intersect(fq, q, hk, vk):
    # rightmost crossing of parabola (q, fq) with (vk, hk), unit spacing
    return ((fq + q * q) - (hk + vk * vk)) / (2.0 * (q - vk))

  def build(carry, xs):
    v, h, z, k, base = carry
    fq, cq, finq, q = xs
    # run change: open a fresh (empty) envelope region above the old top,
    # leaving one gap slot so the finished run's +inf sentinel survives
    base = jnp.where(cq, k + 2, base)
    k = jnp.where(cq, base - 1, k)

    # pop dominated parabolas: while k >= base and s(q, top) <= z[top]
    def pop_cond(state):
      k_, active = state
      return active.any()

    def pop_body(state):
      k_, active = state
      vk = _take(v, jnp.maximum(k_, 0))
      hk = _take(h, jnp.maximum(k_, 0))
      zk = _take(z, jnp.maximum(k_, 0))
      s = intersect(fq, q, hk, vk)
      pop = active & (s <= zk)
      k_ = jnp.where(pop, k_ - 1, k_)
      active = pop & (k_ >= base)
      return k_, active

    active0 = finq & (k >= base)
    k, _ = jax.lax.while_loop(pop_cond, pop_body, (k, active0))

    # push the new parabola (only finite heights)
    vk = _take(v, jnp.maximum(k, 0))
    hk = _take(h, jnp.maximum(k, 0))
    s = jnp.where(k >= base, intersect(fq, q, hk, vk), -INF)
    pos = jnp.clip(k + 1, 0, S - 2)
    v = v.at[rows, pos].set(jnp.where(finq, q, _take(v, pos)))
    h = h.at[rows, pos].set(jnp.where(finq, fq, _take(h, pos)))
    z = z.at[rows, pos].set(jnp.where(finq, s, _take(z, pos)))
    z = z.at[rows, pos + 1].set(
      jnp.where(finq, INF, _take(z, pos + 1))
    )
    k = jnp.where(finq, k + 1, k)
    return (v, h, z, k, base), base

  v0 = jnp.zeros((B, S), jnp.float32)
  h0 = jnp.full((B, S), INF, jnp.float32)
  z0 = jnp.full((B, S), INF, jnp.float32)
  k0 = jnp.full(B, -1, jnp.int32)
  b0 = jnp.zeros(B, jnp.int32)
  xs = (
    f.T, chg.T, finite.T,
    jnp.broadcast_to(qs[:, None], (n, B)),
  )
  (v, h, z, _, _), bases = jax.lax.scan(build, (v0, h0, z0, k0, b0), xs)
  # bases: (n, B) — the envelope region start for each position's run

  def query(kq, xs):
    baseq, cq, q = xs
    kq = jnp.where(cq, baseq, kq)

    # advance while the next parabola's region starts left of q
    def adv_cond(state):
      kq_, active = state
      return active.any()

    def adv_body(state):
      kq_, active = state
      znext = _take(z, jnp.minimum(kq_ + 1, S - 1))
      step = active & (znext < q)
      kq_ = jnp.where(step, kq_ + 1, kq_)
      return kq_, step

    kq, _ = jax.lax.while_loop(adv_cond, adv_body, (kq, jnp.full(B, True)))
    vk = _take(v, kq)
    hk = _take(h, kq)
    out_q = hk + (q - vk) ** 2
    return kq, out_q

  xs_q = (bases, chg.T, jnp.broadcast_to(qs[:, None], (n, B)))
  _, outs = jax.lax.scan(query, jnp.zeros(B, jnp.int32), xs_q)
  # threshold the INF sentinel BEFORE the w2 rescale (matching the numpy
  # twin): for w < 1 a scaled sentinel would drop below INF/2 and leak
  out = jnp.where(outs.T >= INF / 2, INF, outs.T * w2)
  return jnp.where(out >= INF / 2, INF, out)


# lines per envelope block (device path). XLA's CPU backend cannot alias
# the (B, S) v/h/z stack carries of the envelope scan, so every position
# step COPIES them; running the whole volume's B lines at once makes that
# copy ~50MB/step at 128^3 (DRAM-bound). Blocking the lines keeps each
# block's stacks cache-resident — the same total copy volume moves at
# L2/L3 speed instead. Per-line independence makes any blocking bitwise
# identical (the numpy twin blocks the same way via _NP_LINE_BATCH).
# The block size is a tunable (ISSUE 19): IGNEOUS_EDT_LINE_BLOCK >
# tuned/<device_kind>.json > this default.
_DEFAULT_LINE_BLOCK = 256


def _line_block() -> int:
  """Lines per envelope block, via the tuned-knob resolution order."""
  from .. import tune

  spec = tune.resolve("IGNEOUS_EDT_LINE_BLOCK")
  if not spec:
    return _DEFAULT_LINE_BLOCK
  try:
    lb = int(spec)
  except ValueError:
    lb = 0
  if lb < 1:
    raise ValueError(
      f"IGNEOUS_EDT_LINE_BLOCK must be a positive int: {spec!r}"
    )
  return lb


def _axis_pass(
  val: jnp.ndarray, lab: jnp.ndarray, w: float, first: bool,
  line_block: int = _DEFAULT_LINE_BLOCK,
) -> jnp.ndarray:
  """One pass along the LAST axis. val, lab: (..., n)."""
  n = val.shape[-1]
  lead = val.shape[:-1]
  B = int(np.prod(lead)) if lead else 1
  v = val.reshape(B, n)
  l = lab.reshape(B, n)
  out = _edge_term(l, w)
  if not first:
    # the first pass starts from val=INF everywhere, so the same-run
    # envelope could only produce INF — the edge term alone is the answer
    lb = min(int(line_block), B)
    pad = (-B) % lb
    if pad:
      # padded lines are all-background (label 0, val INF): the envelope
      # returns INF for them and they are sliced off below
      v = jnp.pad(v, ((0, pad), (0, 0)), constant_values=INF)
      l = jnp.pad(l, ((0, pad), (0, 0)))
    env = jax.lax.map(
      lambda args: _envelope_pass(args[0], args[1], w),
      (v.reshape(-1, lb, n), l.reshape(-1, lb, n)),
    ).reshape(-1, n)[:B]
    out = jnp.minimum(out, env)
  return out.reshape(*lead, n)


@partial(jax.jit, static_argnames=("anisotropy", "line_block"))
def _edt_sq_kernel(
  labels: jnp.ndarray, anisotropy: Tuple[float, float, float],
  line_block: int = _DEFAULT_LINE_BLOCK,
):
  """labels (z, y, x) int32 → squared EDT float32; three passes.

  Each pass runs along the LAST axis of a layout chosen so consecutive
  transposes fuse into one permutation between passes (in+out transpose
  pairs per pass collapsed: x in (z,y,x), y in (z,x,y), z in (y,x,z) —
  two label transposes and three value transposes total instead of six).
  Values are identical under any layout walk; the envelope itself runs
  blocked over ``line_block``-line chunks (see above — static arg so the
  autotuner can sweep the geometry; any value is bitwise identical)."""
  wx, wy, wz = anisotropy

  # pass along x, native (z, y, x) layout
  val = _axis_pass(
    jnp.full(labels.shape, INF, dtype=jnp.float32), labels, wx, first=True
  )
  # (z, y, x) -> (z, x, y): pass along y
  lab_y = jnp.swapaxes(labels, 1, 2)
  val = _axis_pass(jnp.swapaxes(val, 1, 2), lab_y, wy, first=False,
                   line_block=line_block)
  # (z, x, y) -> (y, x, z): pass along z
  lab_z = jnp.transpose(lab_y, (2, 1, 0))
  val = _axis_pass(jnp.transpose(val, (2, 1, 0)), lab_z, wz, first=False,
                   line_block=line_block)
  # (y, x, z) -> (z, y, x)
  val = jnp.transpose(val, (2, 0, 1))

  return jnp.where(labels == 0, 0.0, val)


# ---------------------------------------------------------------------------
# numpy twin of the envelope passes — the CPU-backend production path.
#
# XLA's scan cannot alias the (B, 2n) stack carries on the CPU backend, so
# every per-position scatter copies the whole stack (measured ~0.2 Mvox/s
# at 256^3). numpy fancy indexing IS in-place, so the identical algorithm
# runs at memory-bound speed; the device kernel above remains the TPU path
# and the semantics twin for tests.


def _edge_term_np(lab: np.ndarray, w: float) -> np.ndarray:
  B, n = lab.shape
  idx = np.arange(n, dtype=np.int64)
  chg = lab[:, 1:] != lab[:, :-1]
  big = 2 * n
  starts = np.full((B, n), -big, dtype=np.int64)
  starts[:, 1:][chg] = np.broadcast_to(idx[1:], chg.shape)[chg]
  left = np.maximum.accumulate(starts, axis=1)
  dl = np.where(left >= 1, (idx[None] - left + 1).astype(np.float32), INF)
  nxt = np.full((B, n), big, dtype=np.int64)
  nxt[:, :-1][chg] = np.broadcast_to(idx[1:], chg.shape)[chg]
  right = np.minimum.accumulate(nxt[:, ::-1], axis=1)[:, ::-1]
  dr = np.where(right <= n - 1, (right - idx[None]).astype(np.float32), INF)
  d = np.minimum(dl, dr)
  dc = np.where(d >= INF, np.float32(0), d)  # avoid f32 overflow of INF*w
  return np.where(d >= INF, INF, (dc * w) ** 2).astype(np.float32)


def _envelope_pass_np(val: np.ndarray, lab: np.ndarray, w: float) -> np.ndarray:
  # Layouts are position-major — lines (n, B), stacks (S, B) — so every
  # per-step slice is contiguous; the lane-major layout made each column
  # access touch B cache lines and ran ~50x slower.
  B, n = val.shape
  S = 2 * n + 2
  w2 = np.float32(w * w)
  f = np.ascontiguousarray(
    np.where(val >= INF, INF, val / w2).astype(np.float32).T
  )  # (n, B)
  chg = np.empty((n, B), bool)
  chg[0] = True
  chg[1:] = (lab[:, 1:] != lab[:, :-1]).T
  finite = f < INF / 2

  v = np.zeros((S, B), np.float32)
  h = np.full((S, B), INF, np.float32)
  z = np.full((S, B), INF, np.float32)
  k = np.full(B, -1, np.int64)
  base = np.zeros(B, np.int64)
  rows = np.arange(B)
  bases = np.empty((n, B), np.int32)  # S < 2^31 always

  def intersect(fq, q, hk, vk):
    den = 2.0 * (q - vk)
    den = np.where(den == 0, 1.0, den)
    return ((fq + q * q) - (hk + vk * vk)) / den

  for q in range(n):
    cq = chg[q]
    fq = f[q]
    finq = finite[q]
    base[cq] = k[cq] + 2
    k[cq] = base[cq] - 1
    active = finq & (k >= base)
    while active.any():
      ar = rows[active]
      ka = k[active]
      s = intersect(fq[active], q, h[ka, ar], v[ka, ar])
      pop = s <= z[ka, ar]
      k[ar[pop]] -= 1
      active = np.zeros(B, bool)
      active[ar[pop]] = True
      active &= k >= base
    pr = rows[finq]
    kp = k[finq]
    kc = np.maximum(kp, 0)
    s = np.where(
      kp >= base[finq],
      intersect(fq[finq], q, h[kc, pr], v[kc, pr]),
      -INF,
    )
    pos = kp + 1
    v[pos, pr] = q
    h[pos, pr] = fq[finq]
    z[pos, pr] = s
    z[pos + 1, pr] = INF
    k[finq] += 1
    bases[q] = base

  out = np.empty((n, B), np.float32)
  kq = np.zeros(B, np.int64)
  for q in range(n):
    cq = chg[q]
    kq[cq] = bases[q][cq]
    adv = z[np.minimum(kq + 1, S - 1), rows] < q
    while adv.any():
      kq[adv] += 1
      nxt = np.zeros(B, bool)
      nxt[adv] = z[np.minimum(kq[adv] + 1, S - 1), rows[adv]] < q
      adv = nxt
    out[q] = h[kq, rows] + (q - v[kq, rows]) ** 2
  res = np.where(out >= INF / 2, INF, out * w2).astype(np.float32)
  return np.ascontiguousarray(res.T)


# line-batch size for the numpy fallback: bounds transient stack memory
# (the (S, B) stacks would be ~GBs at 512^3 if all lines ran at once)
_NP_LINE_BATCH = 1 << 14


def _axis_pass_np(
  val: np.ndarray, lab: np.ndarray, w: float, first: bool
) -> np.ndarray:
  n = val.shape[-1]
  lead = val.shape[:-1]
  B = int(np.prod(lead)) if lead else 1
  v = np.ascontiguousarray(val).reshape(B, n)
  l = np.ascontiguousarray(lab).reshape(B, n)
  out = _edge_term_np(l, w)
  if not first:
    for lo in range(0, B, _NP_LINE_BATCH):
      hi = min(B, lo + _NP_LINE_BATCH)
      out[lo:hi] = np.minimum(
        out[lo:hi], _envelope_pass_np(v[lo:hi], l[lo:hi], w)
      )
  return out.reshape(*lead, n)


def _edt_sq_numpy(lab32: np.ndarray, anisotropy) -> np.ndarray:
  """(x, y, z) host layout; same three passes as the device kernel."""
  wx, wy, wz = anisotropy
  val = np.full(lab32.shape, INF, dtype=np.float32)
  val = np.moveaxis(
    _axis_pass_np(np.moveaxis(val, 0, 2), np.moveaxis(lab32, 0, 2), wx, True),
    2, 0,
  )
  val = np.swapaxes(
    _axis_pass_np(
      np.swapaxes(val, 1, 2), np.swapaxes(lab32, 1, 2), wy, False
    ), 1, 2,
  )
  val = _axis_pass_np(val, lab32, wz, False)
  return np.where(lab32 == 0, np.float32(0), val)


def _edt_sq_native(labels: np.ndarray, anisotropy, parallel: int = 0):
  """Threaded C++ envelope passes (native/csrc/edt.cpp); None if the
  native toolchain is unavailable. Labels are compared by raw equality so
  no renumber/unique pass is needed at any width."""
  from ..native import edt_lib

  lib = edt_lib()
  if lib is None:
    return None
  import ctypes

  if labels.dtype.itemsize <= 4:
    lab = np.ascontiguousarray(labels)
    if lab.dtype.itemsize < 4:
      lab = lab.astype(np.int32)
    lab = lab.view(np.int32)
    fn = lib.edt_ml_sq32
  else:
    lab = np.ascontiguousarray(labels).view(np.int64)
    fn = lib.edt_ml_sq64
  out = np.empty(lab.shape, dtype=np.float32)
  nx, ny, nz = lab.shape
  fn(
    lab.ctypes.data_as(ctypes.c_void_p), out.ctypes.data_as(ctypes.c_void_p),
    nx, ny, nz, float(anisotropy[0]), float(anisotropy[1]),
    float(anisotropy[2]), int(parallel),
  )
  return out


def _host_backend() -> str:
  """'native' | 'numpy' | 'device' for the current environment."""
  import os

  override = knobs.get_str("IGNEOUS_EDT_BACKEND")
  if override:
    if override not in ("native", "numpy", "device"):
      raise ValueError(
        "IGNEOUS_EDT_BACKEND must be 'native', 'numpy' or 'device': "
        f"{override!r}"
      )
    return override
  platforms = os.environ.get("JAX_PLATFORMS", "")
  if platforms:
    return "native" if platforms.split(",")[0] == "cpu" else "device"
  # env var unset: resolve the actual backend (lazy — only reached when
  # nothing pinned the platform, so no tunnel-style hang risk from a
  # pre-registered remote plugin)
  return "device" if jax.default_backend() != "cpu" else "native"


# executors (and their jit caches) reused per anisotropy: repeat batches
# of the same shape never recompile
_BATCH_EXECUTORS = {}


def batch_edt_executor(anisotropy, mesh=None):
  """Cached BatchKernelExecutor for the squared-EDT kernel, keyed by
  anisotropy + mesh so callers (the lease batcher) can pin dispatches to
  an injected device mesh instead of the full device set."""
  wx, wy, wz = (float(a) for a in anisotropy)
  mesh_key = (
    None if mesh is None
    else (tuple(d.id for d in mesh.devices.flat), mesh.axis_names)
  )
  lb = _line_block()
  key = (wx, wy, wz, lb, mesh_key)
  if key not in _BATCH_EXECUTORS:
    from functools import partial as _partial

    from ..parallel.executor import BatchKernelExecutor

    _BATCH_EXECUTORS[key] = BatchKernelExecutor(
      _partial(_edt_sq_kernel, anisotropy=(wx, wy, wz), line_block=lb),
      mesh=mesh,
      name="edt.sq_blocked",
      cache_variant=("edt", wx, wy, wz, lb),
    )
  return _BATCH_EXECUTORS[key]


def edt_batch(
  labels_batch: np.ndarray,
  anisotropy: Sequence[float] = (1.0, 1.0, 1.0),
  black_border: bool = False,
  executor=None,
):
  """Batched device EDT: (K, x, y, z) → list of K float32 distance fields.

  One shard_map'd dispatch computes all K cutouts' transforms with the
  chunk axis partitioned across the mesh (VERDICT round-1 item 3: the
  skeleton forge's flop-heavy stage in the batched path). Honors the same
  backend dispatch as edt() — on host backends each chunk runs the
  native/numpy path so batched and solo outputs stay bit-identical.
  """
  labels_batch = np.asarray(labels_batch)
  if labels_batch.ndim != 4:
    raise ValueError("labels_batch must be (K, x, y, z)")
  if executor is None and _host_backend() != "device":
    return [
      edt(l, anisotropy, black_border=black_border) for l in labels_batch
    ]
  work = labels_batch
  if black_border:
    work = np.pad(
      labels_batch, ((0, 0), (1, 1), (1, 1), (1, 1)), constant_values=0
    )
  from .ccl import _dense_relabel

  lab32 = _dense_relabel(work)  # shared: handles signed/no-zero inputs
  dev = np.ascontiguousarray(lab32.transpose(0, 3, 2, 1))  # (K, z, y, x)
  wx, wy, wz = (float(a) for a in anisotropy)
  if executor is None:
    executor = batch_edt_executor((wx, wy, wz))
  sq = executor(dev)
  outs = []
  for k in range(len(labels_batch)):
    s = np.asarray(sq[k]).transpose(2, 1, 0)
    if black_border:
      s = s[1:-1, 1:-1, 1:-1]
    o = np.sqrt(s, dtype=np.float32)
    o[labels_batch[k] == 0] = 0.0
    outs.append(o)
  return outs


def edt(
  labels: np.ndarray,
  anisotropy: Sequence[float] = (1.0, 1.0, 1.0),
  black_border: bool = False,
) -> np.ndarray:
  """labels: (x, y, z) integers → float32 distances, same layout.

  black_border treats the array boundary as background (kimimaro uses this
  so skeletons stay inside the cutout). Dispatches to the device kernel on
  accelerator backends and the in-place numpy envelope on the CPU backend
  (override with IGNEOUS_EDT_BACKEND=numpy|device).
  """
  if labels.ndim != 3:
    raise ValueError("labels must be 3d")
  orig_shape = labels.shape
  work = labels
  if black_border:
    work = np.pad(labels, 1, mode="constant", constant_values=0)

  wx, wy, wz = (float(a) for a in anisotropy)
  backend = _host_backend()
  sq = None
  if backend == "native":
    # host paths compare labels by raw equality — no renumber pass needed
    sq = _edt_sq_native(work, (wx, wy, wz))
    if sq is None:
      backend = "numpy"  # no toolchain — numpy twin
  if backend == "numpy":
    sq = _edt_sq_numpy(work, (wx, wy, wz))
  elif backend == "device":
    # compress labels to int32 identity space (values only matter by
    # equality; the device kernel works on 32-bit planes). Shared helper:
    # keeps zero as background even for signed inputs with negatives.
    from .ccl import _dense_relabel

    lab32 = _dense_relabel(work)
    dev = jnp.asarray(np.ascontiguousarray(lab32.transpose(2, 1, 0)))
    sq = np.asarray(
      _edt_sq_kernel(dev, (wx, wy, wz), line_block=_line_block())
    ).transpose(2, 1, 0)
  if black_border:
    sq = sq[1:-1, 1:-1, 1:-1]
  out = np.sqrt(sq, dtype=np.float32)
  out[labels == 0] = 0.0
  return out.reshape(orig_shape)
