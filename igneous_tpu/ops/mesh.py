"""Device isosurface extraction — the zmesh (marching cubes) equivalent.

Replaces the reference's zmesh C++ mesher for MeshTask
(/root/reference/igneous/tasks/mesh/mesh.py:245 ``Mesher.mesh(data)``).

TPU-first design: marching TETRAHEDRA instead of marching cubes. Each cell
splits into 6 tetrahedra sharing the main diagonal; a tet has only 16
sign cases, so the full case tables are generated programmatically at
import (no hand-copied 256-entry MC tables), and per-cell work is a pure
table-gather + arithmetic — exactly what vectorizes on the VPU. The
surface is watertight and sits at the 0.5 iso-level of the binary mask
(vertices at edge midpoints, like zmesh on binary masks).

Variable-size output uses the two-pass count/emit pattern (SURVEY.md §7
"hard parts"): kernel 1 computes the per-slot validity mask and total
count; host sizes a static capacity; kernel 2 gathers only the valid
slots and emits vertex coordinates.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# cube corner i sits at offset (i&1, i>>1&1, i>>2&1)
CORNER_OFFSETS = np.array(
  [[(i >> d) & 1 for d in range(3)] for i in range(8)], dtype=np.float32
)
# 6-tet decomposition sharing the 0-7 diagonal
TETS = np.array(
  [
    (0, 1, 3, 7),
    (0, 3, 2, 7),
    (0, 2, 6, 7),
    (0, 6, 4, 7),
    (0, 4, 5, 7),
    (0, 5, 1, 7),
  ],
  dtype=np.int32,
)


def _build_tables():
  """NTRIS[tet, case] and EDGES[tet, case, tri, vtx, 2] (cube corner pairs).

  Triangles are oriented so normals point from inside (mask=1) to outside.
  """
  ntris = np.zeros((6, 16), dtype=np.int32)
  edges = np.zeros((6, 16, 2, 3, 2), dtype=np.int32)

  for t, tet in enumerate(TETS):
    pts = CORNER_OFFSETS[tet]  # (4, 3) canonical coords
    for case in range(16):
      inside = [j for j in range(4) if (case >> j) & 1]
      outside = [j for j in range(4) if not (case >> j) & 1]
      tris = []  # list of [(a_local, b_local) x3]
      if len(inside) == 1:
        v = inside[0]
        tris.append([(v, outside[0]), (v, outside[1]), (v, outside[2])])
      elif len(inside) == 3:
        v = outside[0]
        tris.append([(inside[0], v), (inside[1], v), (inside[2], v)])
      elif len(inside) == 2:
        i0, i1 = inside
        o0, o1 = outside
        # cut quad in cyclic order
        quad = [(i0, o0), (i1, o0), (i1, o1), (i0, o1)]
        tris.append([quad[0], quad[1], quad[2]])
        tris.append([quad[0], quad[2], quad[3]])

      if not tris:
        continue
      in_centroid = pts[inside].mean(axis=0) if inside else pts.mean(axis=0)
      for k, tri in enumerate(tris):
        mids = np.array([(pts[a] + pts[b]) / 2.0 for a, b in tri])
        n = np.cross(mids[1] - mids[0], mids[2] - mids[0])
        outward = mids.mean(axis=0) - in_centroid
        if np.dot(n, outward) < 0:
          tri = [tri[0], tri[2], tri[1]]
        for v, (a, b) in enumerate(tri):
          edges[t, case, k, v, 0] = tet[a]
          edges[t, case, k, v, 1] = tet[b]
      ntris[t, case] = len(tris)
  return ntris, edges


NTRIS_TABLE, EDGES_TABLE = _build_tables()
MAX_SLOTS_PER_CELL = 12  # 6 tets x 2 triangles


def _case_list(mask: jnp.ndarray):
  """mask: (z, y, x) uint8 → list of 6 per-cell case-id arrays (cz, cy, cx).

  Kept as separate per-tet arrays: stacking shifted slices into one big
  array and reshaping it compiles pathologically slowly on XLA CPU, and
  per-tet arrays fuse fine on TPU anyway.
  """
  sz, sy, sx = mask.shape
  cz, cy, cx = sz - 1, sy - 1, sx - 1
  corners = []
  for i in range(8):
    ox, oy, oz = i & 1, (i >> 1) & 1, (i >> 2) & 1
    corners.append(mask[oz : oz + cz, oy : oy + cy, ox : ox + cx].astype(jnp.int32))
  cases = []
  for tet in TETS:
    c = (
      corners[tet[0]]
      + corners[tet[1]] * 2
      + corners[tet[2]] * 4
      + corners[tet[3]] * 8
    )
    cases.append(c)
  return cases


@jax.jit
def _count_kernel(mask: jnp.ndarray):
  """→ (6 per-tet case arrays, 6 per-tet triangle counts, total).

  Triangle count per tet case derives arithmetically from the popcount:
  min(bits, 4 - bits) — no table gather needed on device."""
  cases = _case_list(mask)
  per_tet = []
  total = jnp.int32(0)
  for c in cases:
    b = (c & 1) + ((c >> 1) & 1) + ((c >> 2) & 1) + ((c >> 3) & 1)
    n = jnp.minimum(b, 4 - b)
    per_tet.append(n)
    total = total + jnp.sum(n, dtype=jnp.int32)
  return tuple(cases), tuple(per_tet), total


def _emit_host(cases_np, per_np, shape, real_cells=None) -> np.ndarray:
  """Host-side triangle emission: O(triangles) table lookups in numpy.

  The device pass is O(voxels) (case + count); everything below touches
  only the ~surface-sized slot set, where numpy fancy indexing is faster
  than compiling a device gather program per capacity.

  ``real_cells``: (cx, cy, cz) cell counts of the un-padded mask — cells in
  the shape-bucketing pad ring are dropped (their triangles are artifacts
  of the replicate padding).
  Returns (n, 3, 3) vertex coords in (x, y, z) voxel units.
  """
  sz, sy, sx = shape
  cz, cy, cx = sz - 1, sy - 1, sx - 1
  per = np.stack([p.reshape(-1) for p in per_np], axis=-1)  # (ncells, 6)
  ncells = per.shape[0]

  sel1 = per >= 1
  sel2 = per >= 2
  if real_cells is not None:
    rx, ry, rz = real_cells
    flat = np.arange(ncells, dtype=np.int64)
    in_real = (
      (flat % cx < rx) & ((flat // cx) % cy < ry) & (flat // (cy * cx) < rz)
    )
    sel1 &= in_real[:, None]
    sel2 &= in_real[:, None]
  # nonzero keeps allocation proportional to the surface, not the volume
  cell1, tet1 = np.nonzero(sel1)
  cell2, tet2 = np.nonzero(sel2)
  cell = np.concatenate([cell1, cell2])
  tet = np.concatenate([tet1, tet2])
  tri = np.concatenate([
    np.zeros(len(cell1), dtype=np.int64),
    np.ones(len(cell2), dtype=np.int64),
  ])

  cases_flat = np.stack([c.reshape(-1) for c in cases_np], axis=-1)  # (ncells, 6)
  case = cases_flat[cell, tet]
  pair = EDGES_TABLE[tet, case, tri]  # (n, 3, 2)
  mid = (CORNER_OFFSETS[pair[..., 0]] + CORNER_OFFSETS[pair[..., 1]]) / 2.0

  base = np.stack(
    [cell % cx, (cell // cx) % cy, cell // (cy * cx)], axis=-1
  ).astype(np.float32)  # xyz
  return base[:, None, :] + mid


def _bucket_shape(orig) -> Tuple[int, int, int]:
  """Power-of-two shape bucket so the count kernel compiles a bounded set
  of variants (and batch members can share one compiled program)."""
  return tuple(max(8, 1 << int(np.ceil(np.log2(s)))) for s in orig)


def _pad_to_bucket(mask: np.ndarray, bucket) -> np.ndarray:
  if tuple(mask.shape) == tuple(bucket):
    return mask
  # replicate padding adds no surface inside the real region; artifact
  # triangles in the pad ring are filtered by cell coordinate
  return np.pad(
    mask, tuple((0, b - s) for b, s in zip(bucket, mask.shape)), mode="edge"
  )


def _weld(tris, anisotropy, offset):
  """(n, 3, 3) half-lattice triangles → welded (verts, faces), physical."""
  from ..mesh_io import drop_degenerate_faces

  lattice = np.round(tris.reshape(-1, 3) * 2.0).astype(np.int64)
  uniq, inverse = np.unique(lattice, axis=0, return_inverse=True)
  vertices = uniq.astype(np.float32) / 2.0
  faces = inverse.reshape(-1, 3).astype(np.uint32)
  faces = drop_degenerate_faces(faces)
  vertices = (vertices + np.asarray(offset, dtype=np.float32)) * np.asarray(
    anisotropy, dtype=np.float32
  )
  return vertices, faces


_EMPTY_MESH = (
  np.zeros((0, 3), dtype=np.float32), np.zeros((0, 3), dtype=np.uint32)
)

_COUNT_EXECUTOR = None


def marching_tetrahedra_batch(
  masks, anisotropy=(1.0, 1.0, 1.0), offsets=None, executor=None,
  batch_size: int = 16,
):
  """Batched isosurface extraction: list of binary (x, y, z) masks →
  list of (vertices, faces), identical to per-mask marching_tetrahedra.

  Masks are padded into power-of-two shape buckets and each bucket's
  members run the count pass as ONE shard_map'd device dispatch with the
  mask axis partitioned over the mesh (VERDICT round-1 item 3: the mesh
  forge's per-voxel stage in the batched path). Emission stays host-side
  per mask (O(surface)).
  """
  if offsets is None:
    offsets = [(0.0, 0.0, 0.0)] * len(masks)
  out = [None] * len(masks)
  groups = {}
  for i, m in enumerate(masks):
    if m.ndim != 3:
      raise ValueError("masks must be 3d")
    groups.setdefault(_bucket_shape(m.shape), []).append(i)

  if executor is None:
    # one module-level executor: its jit cache covers every shape bucket
    global _COUNT_EXECUTOR
    if _COUNT_EXECUTOR is None:
      from ..parallel.executor import BatchKernelExecutor

      _COUNT_EXECUTOR = BatchKernelExecutor(_count_kernel)
    executor = _COUNT_EXECUTOR

  for bucket, idxs in groups.items():
    # cap group size: an uncapped bucket (e.g. hundreds of labels sharing
    # one shape bucket) would materialize a (K, *bucket) stack at once
    for g0 in range(0, len(idxs), batch_size):
      gidx = idxs[g0 : g0 + batch_size]
      batch = np.stack([
        np.ascontiguousarray(
          _pad_to_bucket(masks[i].astype(np.uint8), bucket).transpose(2, 1, 0)
        )
        for i in gidx
      ])  # (K, z, y, x)
      cases_b, per_b, totals = executor(batch)
      for k, i in enumerate(gidx):
        if int(totals[k]) == 0:
          out[i] = _EMPTY_MESH
          continue
        orig = masks[i].shape
        tris = _emit_host(
          [c[k] for c in cases_b], [p[k] for p in per_b], batch.shape[1:],
          real_cells=(orig[0] - 1, orig[1] - 1, orig[2] - 1),
        )
        if len(tris) == 0:
          out[i] = _EMPTY_MESH
          continue
        out[i] = _weld(tris, anisotropy, offsets[i])
  return out


def marching_tetrahedra(
  mask: np.ndarray, anisotropy=(1.0, 1.0, 1.0), offset=(0.0, 0.0, 0.0)
) -> Tuple[np.ndarray, np.ndarray]:
  """Binary mask (x, y, z) → (vertices (V,3) float32, faces (F,3) uint32).

  Vertices are in physical units: (voxel_coord + offset) * anisotropy.
  The surface is watertight over the mask's interior; to close a surface
  at the array boundary, pad the mask with a zero shell first (MeshTask
  handles dataset-edge policy).
  """
  if mask.ndim != 3:
    raise ValueError("mask must be 3d")
  orig = mask.shape
  bucket = _bucket_shape(orig)
  mask = _pad_to_bucket(mask, bucket)
  dev = jnp.asarray(
    np.ascontiguousarray(mask.astype(np.uint8).transpose(2, 1, 0))
  )  # (z, y, x)
  cases, per_tet, total = _count_kernel(dev)
  if int(total) == 0:
    return (
      np.zeros((0, 3), dtype=np.float32),
      np.zeros((0, 3), dtype=np.uint32),
    )
  cases_np = [np.asarray(c) for c in cases]
  per_np = [np.asarray(p) for p in per_tet]
  tris = _emit_host(
    cases_np, per_np, dev.shape,
    real_cells=(orig[0] - 1, orig[1] - 1, orig[2] - 1),
  )  # (n, 3, 3) xyz
  if len(tris) == 0:
    return _EMPTY_MESH
  return _weld(tris, anisotropy, offset)
