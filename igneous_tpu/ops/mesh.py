"""Device isosurface extraction — the zmesh (marching cubes) equivalent.

Replaces the reference's zmesh C++ mesher for MeshTask
(/root/reference/igneous/tasks/mesh/mesh.py:245 ``Mesher.mesh(data)``).

Two meshers share one TPU-first skeleton (two-pass count/emit, SURVEY.md
§7 "hard parts": kernel 1 computes per-cell cases + triangle counts on
device, O(voxels); the host then touches only the O(surface) slot set):

* ``marching_cubes`` — true 256-case MC, zmesh's algorithm and the
  production default. The case tables are GENERATED at import by walking
  each case's surface loops over the cube's faces (segments per face,
  chained through the shared crossing edges, fan-triangulated), with the
  "separate inside corners" rule on ambiguous faces — a per-face rule, so
  adjacent cells always agree and the surface is watertight by
  construction. No hand-copied 256-entry tables.
* ``marching_tetrahedra`` — 6-tet decomposition with 16-case tables; kept
  as an independent second implementation (its output doubles as a
  cross-check oracle: same voxel volume, same topology, ~2x triangles).

Both emit vertices at cube-edge midpoints (the 0.5 iso-level of the
binary mask, like zmesh on binary masks).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis import knobs

# cube corner i sits at offset (i&1, i>>1&1, i>>2&1)
CORNER_OFFSETS = np.array(
  [[(i >> d) & 1 for d in range(3)] for i in range(8)], dtype=np.float32
)
# 6-tet decomposition sharing the 0-7 diagonal
TETS = np.array(
  [
    (0, 1, 3, 7),
    (0, 3, 2, 7),
    (0, 2, 6, 7),
    (0, 6, 4, 7),
    (0, 4, 5, 7),
    (0, 5, 1, 7),
  ],
  dtype=np.int32,
)


def _build_tables():
  """NTRIS[tet, case] and EDGES[tet, case, tri, vtx, 2] (cube corner pairs).

  Triangles are oriented so normals point from inside (mask=1) to outside.
  """
  ntris = np.zeros((6, 16), dtype=np.int32)
  edges = np.zeros((6, 16, 2, 3, 2), dtype=np.int32)

  for t, tet in enumerate(TETS):
    pts = CORNER_OFFSETS[tet]  # (4, 3) canonical coords
    for case in range(16):
      inside = [j for j in range(4) if (case >> j) & 1]
      outside = [j for j in range(4) if not (case >> j) & 1]
      tris = []  # list of [(a_local, b_local) x3]
      if len(inside) == 1:
        v = inside[0]
        tris.append([(v, outside[0]), (v, outside[1]), (v, outside[2])])
      elif len(inside) == 3:
        v = outside[0]
        tris.append([(inside[0], v), (inside[1], v), (inside[2], v)])
      elif len(inside) == 2:
        i0, i1 = inside
        o0, o1 = outside
        # cut quad in cyclic order
        quad = [(i0, o0), (i1, o0), (i1, o1), (i0, o1)]
        tris.append([quad[0], quad[1], quad[2]])
        tris.append([quad[0], quad[2], quad[3]])

      if not tris:
        continue
      in_centroid = pts[inside].mean(axis=0) if inside else pts.mean(axis=0)
      for k, tri in enumerate(tris):
        mids = np.array([(pts[a] + pts[b]) / 2.0 for a, b in tri])
        n = np.cross(mids[1] - mids[0], mids[2] - mids[0])
        outward = mids.mean(axis=0) - in_centroid
        if np.dot(n, outward) < 0:
          tri = [tri[0], tri[2], tri[1]]
        for v, (a, b) in enumerate(tri):
          edges[t, case, k, v, 0] = tet[a]
          edges[t, case, k, v, 1] = tet[b]
      ntris[t, case] = len(tris)
  return ntris, edges


NTRIS_TABLE, EDGES_TABLE = _build_tables()


def _case_list(mask: jnp.ndarray):
  """mask: (z, y, x) uint8 → list of 6 per-cell case-id arrays (cz, cy, cx).

  Kept as separate per-tet arrays: stacking shifted slices into one big
  array and reshaping it compiles pathologically slowly on XLA CPU, and
  per-tet arrays fuse fine on TPU anyway.
  """
  sz, sy, sx = mask.shape
  cz, cy, cx = sz - 1, sy - 1, sx - 1
  corners = []
  for i in range(8):
    ox, oy, oz = i & 1, (i >> 1) & 1, (i >> 2) & 1
    corners.append(mask[oz : oz + cz, oy : oy + cy, ox : ox + cx].astype(jnp.int32))
  cases = []
  for tet in TETS:
    c = (
      corners[tet[0]]
      + corners[tet[1]] * 2
      + corners[tet[2]] * 4
      + corners[tet[3]] * 8
    )
    cases.append(c)
  return cases


@jax.jit
def _count_kernel(mask: jnp.ndarray):
  """→ (6 per-tet case arrays, 6 per-tet triangle counts, total).

  Triangle count per tet case derives arithmetically from the popcount:
  min(bits, 4 - bits) — no table gather needed on device."""
  cases = _case_list(mask)
  per_tet = []
  total = jnp.int32(0)
  for c in cases:
    b = (c & 1) + ((c >> 1) & 1) + ((c >> 2) & 1) + ((c >> 3) & 1)
    n = jnp.minimum(b, 4 - b)
    per_tet.append(n)
    total = total + jnp.sum(n, dtype=jnp.int32)
  return tuple(cases), tuple(per_tet), total


def _emit_host(cases_np, per_np, shape, real_cells=None) -> np.ndarray:
  """Host-side triangle emission: O(triangles) table lookups in numpy.

  The device pass is O(voxels) (case + count); everything below touches
  only the ~surface-sized slot set, where numpy fancy indexing is faster
  than compiling a device gather program per capacity.

  ``real_cells``: (cx, cy, cz) cell counts of the un-padded mask — cells in
  the shape-bucketing pad ring are dropped (their triangles are artifacts
  of the replicate padding).
  Returns (n, 3, 3) vertex coords in (x, y, z) voxel units.
  """
  sz, sy, sx = shape
  cz, cy, cx = sz - 1, sy - 1, sx - 1
  per = np.stack([p.reshape(-1) for p in per_np], axis=-1)  # (ncells, 6)

  # nonzero keeps allocation proportional to the surface, not the volume
  cell1, tet1 = np.nonzero(per >= 1)
  cell2, tet2 = np.nonzero(per >= 2)
  if real_cells is not None:
    # pad-ring filter on the O(surface) nonzero set only
    rx, ry, rz = real_cells

    def in_real(cell):
      return (
        (cell % cx < rx) & ((cell // cx) % cy < ry)
        & (cell // (cy * cx) < rz)
      )

    k1, k2 = in_real(cell1), in_real(cell2)
    cell1, tet1 = cell1[k1], tet1[k1]
    cell2, tet2 = cell2[k2], tet2[k2]
  cell = np.concatenate([cell1, cell2])
  tet = np.concatenate([tet1, tet2])
  tri = np.concatenate([
    np.zeros(len(cell1), dtype=np.int64),
    np.ones(len(cell2), dtype=np.int64),
  ])

  cases_flat = np.stack([c.reshape(-1) for c in cases_np], axis=-1)  # (ncells, 6)
  case = cases_flat[cell, tet]
  pair = EDGES_TABLE[tet, case, tri]  # (n, 3, 2)
  mid = (CORNER_OFFSETS[pair[..., 0]] + CORNER_OFFSETS[pair[..., 1]]) / 2.0

  base = np.stack(
    [cell % cx, (cell // cx) % cy, cell // (cy * cx)], axis=-1
  ).astype(np.float32)  # xyz
  return base[:, None, :] + mid


def _bucket_shape(orig) -> Tuple[int, int, int]:
  """Power-of-two shape bucket so the count kernel compiles a bounded set
  of variants (and batch members can share one compiled program)."""
  return tuple(max(8, 1 << int(np.ceil(np.log2(s)))) for s in orig)


def _pad_to_bucket(mask: np.ndarray, bucket) -> np.ndarray:
  if tuple(mask.shape) == tuple(bucket):
    return mask
  # replicate padding adds no surface inside the real region; artifact
  # triangles in the pad ring are filtered by cell coordinate
  return np.pad(
    mask, tuple((0, b - s) for b, s in zip(bucket, mask.shape)), mode="edge"
  )


def _weld(tris, anisotropy, offset):
  """(n, 3, 3) half-lattice triangles → welded (verts, faces), physical."""
  from ..mesh_io import drop_degenerate_faces

  lattice = np.round(tris.reshape(-1, 3) * 2.0).astype(np.int64)
  # scalar-key unique: ~5x faster than unique(axis=0)'s void-view row
  # sort. x occupies the top bits so the sort order (and therefore the
  # vertex numbering) is identical to lexicographic row order. 21 bits
  # per axis covers half-lattice coords to 2^21 (volumes to ~1M voxels
  # per side — far beyond any task cutout).
  key = (lattice[:, 0] << 42) | (lattice[:, 1] << 21) | lattice[:, 2]
  ukey, inverse = np.unique(key, return_inverse=True)
  uniq = np.empty((len(ukey), 3), dtype=np.int64)
  uniq[:, 0] = ukey >> 42
  uniq[:, 1] = (ukey >> 21) & 0x1FFFFF
  uniq[:, 2] = ukey & 0x1FFFFF
  vertices = uniq.astype(np.float32) / 2.0
  faces = inverse.reshape(-1, 3).astype(np.uint32)
  faces = drop_degenerate_faces(faces)
  faces = _cancel_coincident_pairs(faces)
  # prune vertices orphaned by the cancellation
  used = np.zeros(len(vertices), dtype=bool)
  used[faces.reshape(-1)] = True
  if not used.all():
    remap = np.cumsum(used) - 1
    vertices = vertices[used]
    faces = remap[faces.astype(np.int64)].astype(np.uint32)
  vertices = (vertices + np.asarray(offset, dtype=np.float32)) * np.asarray(
    anisotropy, dtype=np.float32
  )
  return vertices, faces


def _cancel_coincident_pairs(faces: np.ndarray) -> np.ndarray:
  """Drop pairs of coincident triangles (same vertex triple).

  Marching cubes' fan triangulation can place a diagonal in a cell face's
  plane; when the loop has further vertices on that same face, a whole fan
  triangle can lie IN the shared face and the neighboring cell emits the
  mirrored copy — a zero-volume fin. The pair cancels exactly: removing
  both lowers each boundary edge's face count by 2, so closedness (even
  counts) is preserved. An odd-multiplicity group (fin pair + a real
  surface triangle) keeps one member of the MAJORITY winding — the real
  triangle's orientation appears twice (its own copy plus the matching
  fin half), so the survivor faces outward.
  """
  if len(faces) == 0:
    return faces
  tri = np.sort(faces, axis=1).astype(np.int64)
  if int(tri[:, 2].max()) < (1 << 21):
    # scalar-key grouping (fast path): collision-free while every vertex
    # index fits 21 bits...
    key = (tri[:, 0] << 42) | (tri[:, 1] << 21) | tri[:, 2]
    _, inv, cnt = np.unique(key, return_inverse=True, return_counts=True)
  else:
    # ...multi-million-vertex meshes fall back to exact row grouping
    _, inv, cnt = np.unique(tri, axis=0, return_inverse=True,
                            return_counts=True)
  if (cnt <= 1).all():
    return faces
  keep = cnt[inv] == 1
  # group duplicate rows by one argsort instead of rescanning per group
  dup_ids = np.flatnonzero(~keep)
  order = dup_ids[np.argsort(inv[dup_ids], kind="stable")]
  ginv = inv[order]
  starts = np.flatnonzero(np.concatenate([[True], ginv[1:] != ginv[:-1]]))
  ends = np.concatenate([starts[1:], [len(order)]])
  # winding parity: (a,b,c) is an even permutation of its sorted triple
  perm = np.argsort(faces[order], axis=1)
  even = (
    (perm == (0, 1, 2)).all(axis=1)
    | (perm == (1, 2, 0)).all(axis=1)
    | (perm == (2, 0, 1)).all(axis=1)
  )
  for s, e in zip(starts, ends):
    if (e - s) % 2 == 0:
      continue
    grp_even = even[s:e]
    maj = grp_even if grp_even.sum() * 2 > (e - s) else ~grp_even
    keep[order[s + int(np.flatnonzero(maj)[0])]] = True
  return faces[keep]


_EMPTY_MESH = (
  np.zeros((0, 3), dtype=np.float32), np.zeros((0, 3), dtype=np.uint32)
)

_COUNT_EXECUTOR = None


def _isosurface_batch(
  masks, anisotropy, offsets, executor, batch_size, get_executor, emit_k
):
  """Shared batched count/emit orchestration for both meshers.

  Masks are padded into power-of-two shape buckets and each bucket's
  members run the count pass as ONE shard_map'd device dispatch with the
  mask axis partitioned over the mesh (VERDICT round-1 item 3: the mesh
  forge's per-voxel stage in the batched path). Emission stays host-side
  per mask (O(surface)); ``emit_k(results, k, shape, real_cells)``
  unpacks member k of the kernel outputs into a triangle array.
  """
  if offsets is None:
    offsets = [(0.0, 0.0, 0.0)] * len(masks)
  out = [None] * len(masks)
  groups = {}
  for i, m in enumerate(masks):
    if m.ndim != 3:
      raise ValueError("masks must be 3d")
    groups.setdefault(_bucket_shape(m.shape), []).append(i)

  if executor is None:
    # one module-level executor per kernel: its jit cache covers every
    # shape bucket
    executor = get_executor()

  for bucket, idxs in groups.items():
    # cap group size: an uncapped bucket (e.g. hundreds of labels sharing
    # one shape bucket) would materialize a (K, *bucket) stack at once
    for g0 in range(0, len(idxs), batch_size):
      gidx = idxs[g0 : g0 + batch_size]
      batch = np.stack([
        np.ascontiguousarray(
          _pad_to_bucket(masks[i].astype(np.uint8), bucket).transpose(2, 1, 0)
        )
        for i in gidx
      ])  # (K, z, y, x)
      results = executor(batch)
      totals = results[-1]
      for k, i in enumerate(gidx):
        if int(totals[k]) == 0:
          out[i] = _EMPTY_MESH
          continue
        orig = masks[i].shape
        tris = emit_k(
          results, k, batch.shape[1:],
          (orig[0] - 1, orig[1] - 1, orig[2] - 1),
        )
        if len(tris) == 0:
          out[i] = _EMPTY_MESH
          continue
        out[i] = _weld(tris, anisotropy, offsets[i])
  return out


def _mt_executor():
  global _COUNT_EXECUTOR
  if _COUNT_EXECUTOR is None:
    from ..parallel.executor import BatchKernelExecutor

    _COUNT_EXECUTOR = BatchKernelExecutor(_count_kernel)
  return _COUNT_EXECUTOR


def _mt_emit_k(results, k, shape, real_cells):
  cases_b, per_b, _ = results
  return _emit_host(
    [c[k] for c in cases_b], [p[k] for p in per_b], shape,
    real_cells=real_cells,
  )


def marching_tetrahedra_batch(
  masks, anisotropy=(1.0, 1.0, 1.0), offsets=None, executor=None,
  batch_size: int = 16,
):
  """Batched isosurface extraction: list of binary (x, y, z) masks →
  list of (vertices, faces), identical to per-mask marching_tetrahedra."""
  return _isosurface_batch(
    masks, anisotropy, offsets, executor, batch_size,
    _mt_executor, _mt_emit_k,
  )


def marching_tetrahedra(
  mask: np.ndarray, anisotropy=(1.0, 1.0, 1.0), offset=(0.0, 0.0, 0.0)
) -> Tuple[np.ndarray, np.ndarray]:
  """Binary mask (x, y, z) → (vertices (V,3) float32, faces (F,3) uint32).

  Vertices are in physical units: (voxel_coord + offset) * anisotropy.
  The surface is watertight over the mask's interior; to close a surface
  at the array boundary, pad the mask with a zero shell first (MeshTask
  handles dataset-edge policy).
  """
  if mask.ndim != 3:
    raise ValueError("mask must be 3d")
  orig = mask.shape
  bucket = _bucket_shape(orig)
  mask = _pad_to_bucket(mask, bucket)
  dev = jnp.asarray(
    np.ascontiguousarray(mask.astype(np.uint8).transpose(2, 1, 0))
  )  # (z, y, x)
  cases, per_tet, total = _count_kernel(dev)
  if int(total) == 0:
    return (
      np.zeros((0, 3), dtype=np.float32),
      np.zeros((0, 3), dtype=np.uint32),
    )
  cases_np = [np.asarray(c) for c in cases]
  per_np = [np.asarray(p) for p in per_tet]
  tris = _emit_host(
    cases_np, per_np, dev.shape,
    real_cells=(orig[0] - 1, orig[1] - 1, orig[2] - 1),
  )  # (n, 3, 3) xyz
  if len(tris) == 0:
    return _EMPTY_MESH
  return _weld(tris, anisotropy, offset)


# ---------------------------------------------------------------------------
# marching cubes (256-case), tables generated by surface-loop walking


def _build_mc_tables():
  """Generate the 256-case MC tables programmatically.

  For each corner-insideness case, surface segments are produced per cube
  face (0, 1, or 2 segments from the face's 4 crossing pattern; ambiguous
  faces — diagonal inside corners — always SEPARATE the inside corners, a
  rule that depends only on the shared face so adjacent cells agree and
  the global surface is watertight), chained into closed loops through
  the crossing cube edges (each crossing edge borders exactly two faces),
  and fan-triangulated. Orientation: each loop's Newell normal is made to
  point away from the mean of the loop's inside corner endpoints.

  Returns (ntri[256], tris[256, MAXT, 3] edge ids padded with 0,
  edge_mid[12, 3] midpoint offsets).
  """
  # 12 cube edges as corner pairs (corner i at (i&1, i>>1&1, i>>2&1))
  edge_pairs = []
  for a in range(8):
    for d in range(3):
      if not (a >> d) & 1:
        edge_pairs.append((a, a | (1 << d)))
  edge_id = {p: i for i, p in enumerate(edge_pairs)}  # 12 edges
  edge_mid = np.array(
    [(CORNER_OFFSETS[a] + CORNER_OFFSETS[b]) / 2.0 for a, b in edge_pairs],
    dtype=np.float32,
  )

  # 6 faces: (axis, side) -> 4 corners in cyclic order around the face
  faces = []
  for d in range(3):
    u, v = (d + 1) % 3, (d + 2) % 3
    for s in (0, 1):
      cyc = []
      for bu, bv in ((0, 0), (1, 0), (1, 1), (0, 1)):
        cyc.append((s << d) | (bu << u) | (bv << v))
      faces.append(cyc)

  all_tris = []
  for case in range(256):
    inside = [(case >> i) & 1 for i in range(8)]
    segments = []  # pairs of edge ids
    for cyc in faces:
      cross = [
        k for k in range(4)
        if inside[cyc[k]] != inside[cyc[(k + 1) % 4]]
      ]  # indices into the face cycle: edge (cyc[k], cyc[k+1]) crosses
      def eid(k):
        a, b = cyc[k], cyc[(k + 1) % 4]
        return edge_id[(min(a, b), max(a, b))]
      if len(cross) == 2:
        segments.append((eid(cross[0]), eid(cross[1])))
      elif len(cross) == 4:
        # ambiguous: exactly two diagonal inside corners; cut each inside
        # corner off individually. corner cyc[k] sits between face edges
        # k-1 and k.
        for k in range(4):
          if inside[cyc[k]] and not inside[cyc[(k + 1) % 4]] \
             and not inside[cyc[(k - 1) % 4]]:
            segments.append((eid((k - 1) % 4), eid(k)))

    # chain segments into loops (each crossing edge appears in exactly 2
    # segments -> every vertex has degree 2)
    tris_case = []
    if segments:
      adj = {}
      for a, b in segments:
        adj.setdefault(a, []).append(b)
        adj.setdefault(b, []).append(a)
      unvisited = set(adj)
      loops = []
      while unvisited:
        start = min(unvisited)
        loop = [start]
        unvisited.discard(start)
        prev, cur = None, start
        while True:
          nxt = [x for x in adj[cur] if x != prev]
          # a double edge (two segments between the same pair) closes a
          # 2-loop; guard by preferring unvisited continuation
          nxt = nxt[0] if nxt else adj[cur][0]
          if nxt == start:
            break
          loop.append(nxt)
          unvisited.discard(nxt)
          prev, cur = cur, nxt
        loops.append(loop)

      for loop in loops:
        pts = edge_mid[loop]
        # Newell normal of the (possibly non-planar) loop
        n = np.zeros(3)
        for i in range(len(loop)):
          p0, p1 = pts[i], pts[(i + 1) % len(loop)]
          n += np.cross(p0, p1)
        # inside reference: mean of the loop's inside corner endpoints
        ref = np.zeros(3)
        cnt = 0
        for e in loop:
          a, b = edge_pairs[e]
          c = a if inside[a] else b
          ref += CORNER_OFFSETS[c]
          cnt += 1
        ref /= cnt
        flip = np.dot(n, pts.mean(axis=0) - ref) < 0
        for i in range(1, len(loop) - 1):
          t = (loop[0], loop[i], loop[i + 1])
          tris_case.append((t[0], t[2], t[1]) if flip else t)
    all_tris.append(tris_case)

  maxt = max(len(t) for t in all_tris)
  ntri = np.array([len(t) for t in all_tris], dtype=np.int32)
  tris = np.zeros((256, maxt, 3), dtype=np.int32)
  for case, tc in enumerate(all_tris):
    for k, t in enumerate(tc):
      tris[case, k] = t
  return ntri, tris, edge_mid


MC_NTRI, MC_TRIS, MC_EDGE_MID = _build_mc_tables()


@jax.jit
def _mc_count_kernel(mask: jnp.ndarray):
  """mask (z, y, x) uint8 → (case (cz,cy,cx) int32, ntri, total).

  One 256-entry table gather per cell — constant-table ``take`` lowers to
  a vectorized gather on the VPU."""
  sz, sy, sx = mask.shape
  cz, cy, cx = sz - 1, sy - 1, sx - 1
  case = jnp.zeros((cz, cy, cx), dtype=jnp.int32)
  for i in range(8):
    ox, oy, oz = i & 1, (i >> 1) & 1, (i >> 2) & 1
    case = case + (
      mask[oz : oz + cz, oy : oy + cy, ox : ox + cx].astype(jnp.int32) << i
    )
  ntri = jnp.take(jnp.asarray(MC_NTRI), case)
  return case, ntri, jnp.sum(ntri, dtype=jnp.int32)


def _mc_emit_host(case_np, ntri_np, shape, real_cells=None) -> np.ndarray:
  """Host-side MC triangle emission, O(triangles) numpy fancy indexing.
  Returns (n, 3, 3) vertex coords in (x, y, z) voxel units."""
  sz, sy, sx = shape
  cz, cy, cx = sz - 1, sy - 1, sx - 1
  ntri = np.asarray(ntri_np).reshape(-1)
  case = np.asarray(case_np).reshape(-1)
  cells = np.flatnonzero(ntri)
  if real_cells is not None and len(cells):
    # pad-ring filter on the O(surface) nonzero set only — full-grid
    # coordinate arithmetic per label costs more than the device pass
    rx, ry, rz = real_cells
    in_real = (
      (cells % cx < rx) & ((cells // cx) % cy < ry)
      & (cells // (cy * cx) < rz)
    )
    cells = cells[in_real]
  if len(cells) == 0:
    return np.zeros((0, 3, 3), dtype=np.float32)
  reps = ntri[cells]
  cell = np.repeat(cells, reps)
  # per-triangle index within its cell: arange minus each cell's start
  starts = np.concatenate([[0], np.cumsum(reps)[:-1]])
  k = np.arange(len(cell), dtype=np.int64) - np.repeat(starts, reps)
  edges = MC_TRIS[case[cell], k]  # (n, 3) edge ids
  mid = MC_EDGE_MID[edges]  # (n, 3, 3)
  base = np.stack(
    [cell % cx, (cell // cx) % cy, cell // (cy * cx)], axis=-1
  ).astype(np.float32)
  return base[:, None, :] + mid


def _mesh_emit_backend() -> str:
  """'host' | 'device' triangle emission. The host path is numpy fancy
  indexing (fast on CPU hosts); the device path keeps count+emit on the
  accelerator so MeshTask's forge stage stops round-tripping cases/counts
  through the host. Override with IGNEOUS_MESH_EMIT=host|device."""
  import os

  override = knobs.get_str("IGNEOUS_MESH_EMIT")
  if override:
    if override not in ("host", "device"):
      raise ValueError(
        f"IGNEOUS_MESH_EMIT must be 'host' or 'device': {override!r}"
      )
    return override
  platforms = os.environ.get("JAX_PLATFORMS", "")
  if platforms:
    return "host" if platforms.split(",")[0] == "cpu" else "device"
  return "device" if jax.default_backend() != "cpu" else "host"


@partial(jax.jit, static_argnames=("capacity",))
def _mc_emit_kernel(case: jnp.ndarray, ntri: jnp.ndarray, capacity: int):
  """Device MC triangle emission as a masked gather over ``capacity``
  static slots: exclusive-cumsum triangle offsets per cell, slot→cell via
  searchsorted, then the same MC_TRIS/MC_EDGE_MID table gathers as the
  host path. Slot order IS the host emission order (cells ascending in
  flat (z, y, x) scan order, k ascending within a cell), so after the
  host-side [:total] slice + pad-ring filter the triangle stream — and
  therefore _weld's vertex/face numbering — is byte-identical. Slots
  >= total hold garbage from clamped gathers and are sliced off."""
  cz, cy, cx = ntri.shape
  nt = ntri.reshape(-1)
  ex = jnp.cumsum(nt, dtype=jnp.int32) - nt  # exclusive starts
  slots = jnp.arange(capacity, dtype=jnp.int32)
  # last cell whose start <= slot: ties (zero-tri cells share a start)
  # resolve to the one cell whose [start, start+ntri) interval holds slot
  cell = (
    jnp.searchsorted(ex, slots, side="right").astype(jnp.int32) - 1
  )
  k = slots - jnp.take(ex, cell)
  k = jnp.minimum(k, jnp.int32(MC_TRIS.shape[1] - 1))  # dead-slot clamp
  cs = jnp.take(case.reshape(-1), cell)
  edges = jnp.asarray(MC_TRIS)[cs, k]  # (capacity, 3)
  mid = jnp.asarray(MC_EDGE_MID)[edges]  # (capacity, 3, 3)
  base = jnp.stack(
    [
      (cell % cx).astype(jnp.float32),
      ((cell // cx) % cy).astype(jnp.float32),
      (cell // (cy * cx)).astype(jnp.float32),
    ],
    axis=-1,
  )
  return base[:, None, :] + mid, cell


def _mc_emit_device(
  case, ntri, total: int, shape, real_cells=None
) -> np.ndarray:
  """Run _mc_emit_kernel under the solo-dispatch telemetry pattern
  (compile span on a fresh (shape, capacity-bucket) signature, execute
  span + recompile ledger otherwise) and apply the pad-ring filter on
  the returned per-triangle cell ids."""
  from ..observability import device as device_telemetry

  sz, sy, sx = shape
  cz, cy, cx = sz - 1, sy - 1, sx - 1
  capacity = 1 << max(10, int(total - 1).bit_length())
  kernel = "mesh.mc_emit"
  sig = ((cz, cy, cx), capacity)
  fresh = device_telemetry.LEDGER.note_signature(kernel, sig)
  span = (
    device_telemetry.compile_span(kernel, device_telemetry._devices_of())
    if fresh else
    device_telemetry.execute_span(
      kernel, elements=int(total),
      nbytes=int(np.asarray(case).nbytes) + int(np.asarray(ntri).nbytes),
    )
  )
  with span:
    tris, cell = _mc_emit_kernel(
      jnp.asarray(case), jnp.asarray(ntri), capacity
    )
    jax.block_until_ready((tris, cell))
  tris = np.asarray(tris)[:total]
  cell = np.asarray(cell)[:total]
  if real_cells is not None and len(cell):
    rx, ry, rz = real_cells
    in_real = (
      (cell % cx < rx) & ((cell // cx) % cy < ry)
      & (cell // (cy * cx) < rz)
    )
    tris = tris[in_real]
  return tris


def _mc_emit(case, ntri, total: int, shape, real_cells=None) -> np.ndarray:
  """Backend-dispatched MC emission; both paths produce the identical
  triangle stream (order and bits)."""
  if total and _mesh_emit_backend() == "device":
    return _mc_emit_device(case, ntri, total, shape, real_cells)
  return _mc_emit_host(
    np.asarray(case), np.asarray(ntri), shape, real_cells
  )


_MC_COUNT_EXECUTOR = None


def marching_cubes(
  mask: np.ndarray, anisotropy=(1.0, 1.0, 1.0), offset=(0.0, 0.0, 0.0)
) -> Tuple[np.ndarray, np.ndarray]:
  """Binary mask (x, y, z) → (vertices (V,3) float32, faces (F,3) uint32).

  True 256-case marching cubes (zmesh's algorithm; ~half the triangles of
  marching_tetrahedra for the same surface). Vertices in physical units:
  (voxel + offset) * anisotropy. Watertight over the mask interior; pad
  with a zero shell to close surfaces at the array boundary."""
  if mask.ndim != 3:
    raise ValueError("mask must be 3d")
  orig = mask.shape
  bucket = _bucket_shape(orig)
  mask = _pad_to_bucket(mask, bucket)
  dev = jnp.asarray(
    np.ascontiguousarray(mask.astype(np.uint8).transpose(2, 1, 0))
  )
  case, ntri, total = _mc_count_kernel(dev)
  if int(total) == 0:
    return _EMPTY_MESH
  tris = _mc_emit(
    case, ntri, int(total), dev.shape,
    real_cells=(orig[0] - 1, orig[1] - 1, orig[2] - 1),
  )
  if len(tris) == 0:
    return _EMPTY_MESH
  return _weld(tris, anisotropy, offset)


def _mc_executor():
  global _MC_COUNT_EXECUTOR
  if _MC_COUNT_EXECUTOR is None:
    from ..parallel.executor import BatchKernelExecutor

    _MC_COUNT_EXECUTOR = BatchKernelExecutor(_mc_count_kernel)
  return _MC_COUNT_EXECUTOR


def _mc_emit_k(results, k, shape, real_cells):
  case_b, ntri_b, totals = results
  return _mc_emit(
    case_b[k], ntri_b[k], int(np.asarray(totals[k])), shape,
    real_cells=real_cells,
  )


def marching_cubes_batch(
  masks, anisotropy=(1.0, 1.0, 1.0), offsets=None, executor=None,
  batch_size: int = 16,
):
  """Batched marching cubes: list of binary (x, y, z) masks → list of
  (vertices, faces), identical to per-mask marching_cubes. Same
  shard_map'd one-dispatch-per-bucket count pass as
  marching_tetrahedra_batch."""
  return _isosurface_batch(
    masks, anisotropy, offsets, executor, batch_size,
    _mc_executor, _mc_emit_k,
  )
