"""Pallas TPU kernel for the CCL block-local tile resolve.

`ops/ccl.py`'s tiled label-propagation path cuts the volume into
VMEM-sized tiles and resolves each tile locally before one host
boundary-merge pass. This module is the Pallas engine for that local
resolve (``IGNEOUS_CCL_ENGINE=pallas``; the lax fallback in ccl.py is
the portable default — same dispatch pattern as ops/pallas_pooling.py).

Per grid program: one (tz, ty, tx) tile lives in VMEM and iterates a
gather-free round — log-doubling segmented cummin along each axis
(Hillis–Steele with run-break flags: rolls + wheres only, no
associative_scan, no pointer gathers) plus one neighbor-min over the
requested connectivity — inside an in-kernel ``while_loop``. That loop
is the real per-tile early exit: each tile stops at ITS OWN fixpoint
instead of the batched-lax path's max-over-tiles round count.

Output contract matches ccl._ccl_tiled_kernel's lax engine exactly:
every voxel holds the LOCAL flat index of its tile-component's minimum
voxel (background voxels keep their own index; the caller masks them),
so the two engines are interchangeable bit-for-bit.

Use ``tile_resolve(..., interpret=True)`` for CPU parity tests.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

try:  # pallas is part of jax, but guard exotic builds
  from jax.experimental import pallas as pl

  _PALLAS = True
except Exception:  # pragma: no cover
  _PALLAS = False


def available() -> bool:
  return _PALLAS


def _seg_cummin_doubling(L, lab, axis, reverse):
  """Segmented cummin along ``axis`` via log-step doubling.

  ok_s[i] tracks "the s-long chain upstream of i stays in one run";
  both the value window and the flag double each step, so ceil(log2(n))
  rolls collapse every contiguous same-label run to its min — the same
  result as ccl._seg_cummin without lax.associative_scan (which Mosaic
  does not lower)."""
  n = L.shape[axis]
  d = -1 if reverse else 1
  coord = jax.lax.broadcasted_iota(jnp.int32, L.shape, axis)
  edge = coord >= 1 if not reverse else coord <= n - 2
  ok = edge & (jnp.roll(lab, d, axis) == lab)
  v = L
  s = 1
  while s < n:
    vs = jnp.roll(v, d * s, axis)
    oks = jnp.roll(ok, d * s, axis)
    v = jnp.where(ok, jnp.minimum(v, vs), v)
    ok = ok & oks  # false flags never wrap into true ones (i >= s holds)
    s *= 2
  return v


def _resolve_kernel(lab_ref, out_ref, *, connectivity: int):
  from .ccl import neighbor_offsets

  lab = lab_ref[0]
  tz, ty, tx = lab.shape
  fg = lab != 0
  big = jnp.iinfo(jnp.int32).max
  L0 = (
    jax.lax.broadcasted_iota(jnp.int32, lab.shape, 0) * (ty * tx)
    + jax.lax.broadcasted_iota(jnp.int32, lab.shape, 1) * tx
    + jax.lax.broadcasted_iota(jnp.int32, lab.shape, 2)
  )

  def nb_min(L):
    m = L
    for off in neighbor_offsets(connectivity):
      nb_L, nb_lab, valid = L, lab, None
      for axis, dd in enumerate(off):
        if dd == 0:
          continue
        nb_L = jnp.roll(nb_L, dd, axis)
        nb_lab = jnp.roll(nb_lab, dd, axis)
        size = lab.shape[axis]
        coord = jax.lax.broadcasted_iota(jnp.int32, lab.shape, axis)
        ok = coord != (0 if dd == 1 else size - 1)
        valid = ok if valid is None else (valid & ok)
      same = valid & (nb_lab == lab)
      m = jnp.minimum(m, jnp.where(same, nb_L, big))
    return m

  def cond(state):
    return state[1]

  def body(state):
    L, _ = state
    Lp = L
    for axis in range(3):
      Lp = jnp.minimum(
        _seg_cummin_doubling(Lp, lab, axis, False),
        _seg_cummin_doubling(Lp, lab, axis, True),
      )
    Lp = jnp.minimum(Lp, nb_min(Lp))
    Lp = jnp.where(fg, jnp.minimum(L, Lp), L)
    return (Lp, jnp.any(Lp != L))

  L, _ = jax.lax.while_loop(cond, body, (L0, jnp.bool_(True)))
  out_ref[0] = L


@partial(jax.jit, static_argnames=("connectivity", "interpret"))
def tile_resolve(
  labt: jnp.ndarray, connectivity: int = 6, interpret: bool = False
) -> jnp.ndarray:
  """labt: (T, tz, ty, tx) int32 tiles → per-voxel local component roots
  (local flat index of the tile-component minimum; background voxels
  keep their own index — the caller masks them)."""
  if not _PALLAS:
    raise RuntimeError("pallas unavailable in this jax build")
  T, tz, ty, tx = labt.shape
  return pl.pallas_call(
    partial(_resolve_kernel, connectivity=connectivity),
    out_shape=jax.ShapeDtypeStruct((T, tz, ty, tx), jnp.int32),
    grid=(T,),
    in_specs=[pl.BlockSpec((1, tz, ty, tx), lambda i: (i, 0, 0, 0))],
    out_specs=pl.BlockSpec((1, tz, ty, tx), lambda i: (i, 0, 0, 0)),
    interpret=interpret,
  )(labt)
