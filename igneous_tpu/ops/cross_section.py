"""Cross-sectional area at skeleton vertices — xs3d capability parity.

Reference: kimimaro.cross_sectional_area (backed by the xs3d C++ library,
/root/reference/igneous/tasks/skeleton.py:400-572) computes, per skeleton
vertex, the area of the label's planar slice perpendicular to the local
skeleton direction.

Implementation (round 2 — exact): for vertex v with unit physical tangent
t, the slice is the plane through v with normal t. Every voxel cube the
plane crosses and that is flood-connected to v within the crossed set
contributes the EXACT area of (plane ∩ cube) — a convex polygon obtained
by clipping an in-plane patch against the cube's six half-spaces with the
same vectorized Sutherland-Hodgman used for multires wall
retriangulation. Cube slices partition the label's slice, so the sum is
the exact planar section area of the voxelized solid (xs3d semantics):
axis-aligned and oblique slices of cuboids are exact to float precision,
curved solids exact for their voxelization. Connectivity within the
crossed set keeps parallel branches of the same label from inflating the
area (xs3d's contiguous-section rule).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
from scipy import ndimage

from ..skeleton_io import Skeleton


def vertex_tangents(skel: Skeleton, smoothing_window: int = 1) -> np.ndarray:
  """Unit tangent per vertex: mean direction of incident edges.

  ``smoothing_window`` > 1 averages each vertex's tangent with the
  sign-aligned tangents of vertices within ceil((w-1)/2) graph hops —
  the reference's kimimaro ``cross_sectional_area(smoothing_window=...)``
  knob, which steadies slice normals on jagged centerlines
  (reference tasks/skeleton.py:449-457)."""
  n = len(skel.vertices)
  tangents = np.zeros((n, 3), np.float32)
  edges = skel.edges.astype(np.int64)
  for a, b in edges:
    d = skel.vertices[b] - skel.vertices[a]
    norm = np.linalg.norm(d)
    if norm == 0:
      continue
    d = d / norm
    # orient consistently (sign-insensitive accumulation)
    for idx in (a, b):
      ref = tangents[idx]
      if np.dot(ref, d) < 0:
        tangents[idx] -= d
      else:
        tangents[idx] += d
  norms = np.linalg.norm(tangents, axis=1, keepdims=True)
  norms[norms == 0] = 1.0
  tangents = tangents / norms

  w = int(smoothing_window)
  if w > 1 and len(edges):
    hops = (w - 1 + 1) // 2  # ceil((w-1)/2)
    adj = [[] for _ in range(n)]
    for a, b in edges:
      adj[a].append(int(b))
      adj[b].append(int(a))
    smoothed = np.empty_like(tangents)
    for i in range(n):
      seen = {i}
      frontier = [i]
      for _ in range(hops):
        nxt = []
        for u in frontier:
          for v in adj[u]:
            if v not in seen:
              seen.add(v)
              nxt.append(v)
        frontier = nxt
      acc = np.zeros(3, np.float32)
      ref = tangents[i]
      for u in seen:
        t = tangents[u]
        acc += -t if np.dot(ref, t) < 0 else t
      norm = np.linalg.norm(acc)
      smoothed[i] = acc / norm if norm > 0 else ref
    tangents = smoothed
  return tangents


def _plane_basis(t: np.ndarray):
  """Two unit vectors spanning the plane with unit normal t."""
  e = np.zeros(3)
  e[int(np.argmin(np.abs(t)))] = 1.0
  u = np.cross(t, e)
  u /= np.linalg.norm(u)
  return u, np.cross(t, u)


def _plane_cube_areas(
  vox_idx: np.ndarray, v_phys: np.ndarray, t: np.ndarray, anis: np.ndarray
) -> float:
  """Exact Σ area(plane ∩ cube) over voxel cubes at integer indices
  vox_idx (K, 3); plane through v_phys with unit normal t. Convention:
  index i is the CUBE CENTER, i.e. cube k spans
  [(vox_idx-1/2)*anis, (vox_idx+1/2)*anis). Dispatches to the native
  xs3d-equivalent kernel (native/csrc/xsection.cpp — the same algorithm
  with the same tolerances, scalar C++); this numpy twin doubles as the
  fallback and the equivalence oracle."""
  if len(vox_idx) == 0:
    return 0.0
  from ..native import xsection_lib

  lib = xsection_lib()
  if lib is not None:
    import ctypes

    vi = np.ascontiguousarray(vox_idx, dtype=np.int64)
    v = np.ascontiguousarray(v_phys, dtype=np.float64)
    tn = np.ascontiguousarray(t, dtype=np.float64)
    an = np.ascontiguousarray(anis, dtype=np.float64)
    return float(lib.xs_plane_cubes_area(
      vi.ctypes.data_as(ctypes.c_void_p), len(vi),
      v.ctypes.data_as(ctypes.c_void_p),
      tn.ctypes.data_as(ctypes.c_void_p),
      an.ctypes.data_as(ctypes.c_void_p),
    ))
  return _plane_cube_areas_py(vox_idx, v_phys, t, anis)


def _plane_cube_areas_py(
  vox_idx: np.ndarray, v_phys: np.ndarray, t: np.ndarray, anis: np.ndarray
) -> float:
  """Numpy twin of the native kernel (kept as oracle + fallback)."""
  from ..mesh_multires import clip_polygons

  if len(vox_idx) == 0:
    return 0.0
  centers = vox_idx.astype(np.float64) * anis
  lo_phys = centers - anis / 2.0
  d_c = (centers - v_phys) @ t
  # patch center: cube center projected onto the plane, cube-local coords
  p_rel = (centers - d_c[:, None] * t) - lo_phys
  s = float(np.linalg.norm(anis))  # covers any cube cross-section
  u, w = _plane_basis(t)
  quad = np.stack([
    p_rel + s * (u + w), p_rel + s * (u - w),
    p_rel + s * (-u - w), p_rel + s * (-u + w),
  ], axis=1)  # (K, 4, 3), ordered around the patch
  counts = np.full(len(quad), 4, dtype=np.int64)
  verts = quad
  for axis in range(3):
    for sign, bound in ((-1.0, 0.0), (1.0, float(anis[axis]))):
      verts, counts = clip_polygons(verts, counts, axis, sign, bound)
      keep = counts >= 3
      verts, counts = verts[keep], counts[keep]
      if len(verts) == 0:
        return 0.0
  # 3D shoelace per polygon: 0.5 * |sum_i (v_i - v_0) x (v_{i+1} - v_0)|
  total = 0.0
  rel = verts - verts[:, :1]
  acc = np.zeros((len(verts), 3))
  for i in range(1, verts.shape[1] - 1):
    valid = counts > i + 1
    if not valid.any():
      break
    acc[valid] += np.cross(rel[valid, i], rel[valid, i + 1])
  total = 0.5 * np.linalg.norm(acc, axis=1).sum()
  return float(total)


def cross_sectional_area(
  mask: np.ndarray,
  skel: Skeleton,
  anisotropy: Sequence[float] = (1.0, 1.0, 1.0),
  offset: Sequence[float] = (0.0, 0.0, 0.0),
  window: int = 48,
  vertex_mask: Optional[np.ndarray] = None,
  smoothing_window: int = 1,
) -> np.ndarray:
  """Per-vertex slice areas (physical units²) of one label's mask.

  ``vertex_mask``: optional bool array — compute only these vertices
  (others stay -1); the contact-repair pass uses it to revisit just the
  flagged vertices against a context re-download.

  ``skel`` vertices are physical; ``mask`` is the (x,y,z) label mask whose
  voxel grid starts at ``offset`` (voxels). Returns float32 values:
    area > 0   clean slice;
    area < 0   |area| is a LOWER BOUND — the slice was clipped by the
               window or the cutout boundary (the reference's
               boundary-contact case, which its repair pass re-visits,
               tasks/skeleton.py:574-720);
    -1         vertex outside the mask.
  """
  anis = np.asarray(anisotropy, np.float32)
  tangents = vertex_tangents(skel, smoothing_window=smoothing_window)
  out = np.full(len(skel.vertices), -1.0, np.float32)
  shape = np.asarray(mask.shape, dtype=np.int64)
  w = int(window)

  for i, (v, t) in enumerate(zip(skel.vertices, tangents)):
    if vertex_mask is not None and not vertex_mask[i]:
      continue
    vv = v / anis - np.asarray(offset, np.float32)  # voxel coords
    vi = np.round(vv).astype(np.int64)
    if np.any(vi < 0) or np.any(vi >= shape):
      continue
    if not mask[tuple(vi)]:
      continue
    if t[0] == 0 and t[1] == 0 and t[2] == 0:
      continue
    lo = np.maximum(vi - w, 0)
    hi = np.minimum(vi + w + 1, shape)
    sub = mask[lo[0]:hi[0], lo[1]:hi[1], lo[2]:hi[2]]

    # signed distance of each subwindow voxel center from the plane,
    # built from per-axis aranges (never a materialized (2w+1)^3 grid —
    # at the repair window of 150 that would be ~GB-scale)
    frac = (vi.astype(np.float32) - vv) * anis  # sub-voxel shift, physical
    axes = [
      (np.arange(lo[a], hi[a], dtype=np.float32) - vi[a])
      * (anis[a] * t[a])
      for a in range(3)
    ]
    dist = (
      axes[0][:, None, None] + axes[1][None, :, None]
      + axes[2][None, None, :]
    ) + float(frac @ t)
    # a cube is crossed by the plane iff the center's distance is within
    # the cube's support radius along the normal. Half-open: a plane
    # lying EXACTLY on a shared face belongs to one neighbor only —
    # inclusive-both would double-count the full face polygon
    support = 0.5 * float(np.abs(anis * t).sum())
    crossed = sub & (dist > -support) & (dist <= support)
    seed = tuple(vi - lo)
    if not crossed[seed]:
      # the rounded vertex voxel can land on the open side of the
      # half-open test (vertex exactly on a face); step to the crossed
      # neighbor along the dominant tangent axis
      ax = int(np.argmax(np.abs(t)))
      for step_dir in (1, -1):
        alt = np.asarray(seed)
        alt[ax] += step_dir
        if np.all(alt >= 0) and np.all(alt < np.asarray(sub.shape)) and \
            crossed[tuple(alt)]:
          seed = tuple(alt)
          break
      else:
        continue
    # connectivity within the crossed set: other branches crossing the
    # plane must not count (xs3d's contiguous-section semantics)
    labeled, _ = ndimage.label(crossed, structure=np.ones((3, 3, 3), bool))
    comp_mask = labeled == labeled[seed]

    # exact area: clip the plane against every crossed cube, sum polygons
    local_idx = np.argwhere(comp_mask)  # crop-window voxel indices
    vox_idx = local_idx + lo  # crop-frame voxel indices
    area = _plane_cube_areas(
      vox_idx, vv.astype(np.float64) * anis, t.astype(np.float64), anis
    )

    # truncation: the section touches the window or cutout boundary, so
    # the true slice may continue beyond what we counted (window-clipped
    # and cutout-contact cases both surface as a border touch)
    clipped = any(
      comp_mask.take(0, axis=a).any() or comp_mask.take(-1, axis=a).any()
      for a in range(3)
    )
    out[i] = -area if clipped else area
  return out
