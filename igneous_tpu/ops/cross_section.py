"""Cross-sectional area at skeleton vertices — xs3d capability parity.

Reference: kimimaro.cross_sectional_area (backed by the xs3d C++ library,
/root/reference/igneous/tasks/skeleton.py:400-572) computes, per skeleton
vertex, the area of the label's planar slice perpendicular to the local
skeleton direction.

Implementation: voxel-slab counting. For vertex v with unit tangent t,
every foreground voxel center p in a local window contributes when
|(p - v)·t| < 1/2 voxel step (a one-voxel-thick slab) and p is
flood-connected to v within the slab (so parallel branches of the same
label do not inflate the area). Area = count x (voxel volume / step),
which converges to the geometric slice area for slabs through voxelized
solids. Accuracy is the voxelization's (compare the tube test: pi*r^2
within ~10%); exact polygonal slicing a la xs3d can swap in behind the
same signature.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
from scipy import ndimage

from ..skeleton_io import Skeleton


def vertex_tangents(skel: Skeleton) -> np.ndarray:
  """Unit tangent per vertex: mean direction of incident edges."""
  n = len(skel.vertices)
  tangents = np.zeros((n, 3), np.float32)
  edges = skel.edges.astype(np.int64)
  for a, b in edges:
    d = skel.vertices[b] - skel.vertices[a]
    norm = np.linalg.norm(d)
    if norm == 0:
      continue
    d = d / norm
    # orient consistently (sign-insensitive accumulation)
    for idx in (a, b):
      ref = tangents[idx]
      if np.dot(ref, d) < 0:
        tangents[idx] -= d
      else:
        tangents[idx] += d
  norms = np.linalg.norm(tangents, axis=1, keepdims=True)
  norms[norms == 0] = 1.0
  return tangents / norms


def cross_sectional_area(
  mask: np.ndarray,
  skel: Skeleton,
  anisotropy: Sequence[float] = (1.0, 1.0, 1.0),
  offset: Sequence[float] = (0.0, 0.0, 0.0),
  window: int = 48,
) -> np.ndarray:
  """Per-vertex slice areas (physical units²) of one label's mask.

  ``skel`` vertices are physical; ``mask`` is the (x,y,z) label mask whose
  voxel grid starts at ``offset`` (voxels). Returns float32 values:
    area > 0   clean slice;
    area < 0   |area| is a LOWER BOUND — the slice was clipped by the
               window or the cutout boundary (the reference's
               boundary-contact case, which its repair pass re-visits,
               tasks/skeleton.py:574-720);
    -1         vertex outside the mask.
  """
  anis = np.asarray(anisotropy, np.float32)
  voxel_volume = float(np.prod(anis))
  tangents = vertex_tangents(skel)
  out = np.full(len(skel.vertices), -1.0, np.float32)
  shape = np.asarray(mask.shape, dtype=np.int64)

  # one shared window coordinate grid; per vertex only a slice + the
  # sub-voxel shift changes
  w = int(window)
  base_grid = (
    np.indices((2 * w + 1,) * 3).astype(np.float32) - w
  )  # (3, 2w+1, 2w+1, 2w+1), centered

  for i, (v, t) in enumerate(zip(skel.vertices, tangents)):
    vv = v / anis - np.asarray(offset, np.float32)  # voxel coords
    vi = np.round(vv).astype(np.int64)
    if np.any(vi < 0) or np.any(vi >= shape):
      continue
    if not mask[tuple(vi)]:
      continue
    if t[0] == 0 and t[1] == 0 and t[2] == 0:
      continue
    lo = np.maximum(vi - w, 0)
    hi = np.minimum(vi + w + 1, shape)
    sub = mask[lo[0]:hi[0], lo[1]:hi[1], lo[2]:hi[2]]

    gsl = tuple(
      slice(int(a - (c - w)), int(b - (c - w)))
      for a, b, c in zip(lo, hi, vi)
    )
    frac = (vi.astype(np.float32) - vv) * anis  # sub-voxel shift, physical
    dist = (
      base_grid[0][gsl] * (anis[0] * t[0])
      + base_grid[1][gsl] * (anis[1] * t[1])
      + base_grid[2][gsl] * (anis[2] * t[2])
    ) + float(frac @ t)
    # slab thickness: one step of the (anisotropic) voxel grid along t
    step = float(np.linalg.norm(anis * t))
    slab = sub & (np.abs(dist) < step / 2.0)
    seed = tuple(vi - lo)
    if not slab[seed]:
      continue
    # connectivity within the slab: other branches crossing the plane
    # must not count (xs3d's contiguous-section semantics)
    labeled, _ = ndimage.label(slab, structure=np.ones((3, 3, 3), bool))
    comp_mask = labeled == labeled[seed]
    count = int(comp_mask.sum())
    area = count * voxel_volume / step

    # truncation: the section touches the window or cutout boundary, so
    # the true slice may continue beyond what we counted (window-clipped
    # and cutout-contact cases both surface as a border touch)
    clipped = any(
      comp_mask.take(0, axis=a).any() or comp_mask.take(-1, axis=a).any()
      for a in range(3)
    )
    out[i] = -area if clipped else area
  return out
