"""DBSCAN clustering — capability parity with the `dbscan` C++ package.

Reference use: clustering boundary-contact skeleton vertices so each
cluster gets one context re-download in the cross-section repair pass
(/root/reference/igneous/tasks/skeleton.py:574-720 via `import dbscan`).

Standard DBSCAN semantics on a cKDTree eps-graph: core points have at
least ``min_samples`` neighbors within ``eps`` (self included); clusters
are connected components of core points, with border points attached to
an adjacent core's cluster; everything else is noise (-1).
"""

from __future__ import annotations

import numpy as np
from scipy.spatial import cKDTree


def dbscan(
  points: np.ndarray, eps: float, min_samples: int = 1
) -> np.ndarray:
  """points: (n, d) → int labels (n,), clusters 0..k-1, noise -1."""
  points = np.asarray(points, dtype=np.float64)
  n = len(points)
  if n == 0:
    return np.zeros(0, dtype=np.int64)
  tree = cKDTree(points)
  pairs = tree.query_pairs(float(eps), output_type="ndarray")

  degree = np.ones(n, dtype=np.int64)  # self counts
  if len(pairs):
    np.add.at(degree, pairs[:, 0], 1)
    np.add.at(degree, pairs[:, 1], 1)
  core = degree >= int(min_samples)

  parent = np.arange(n, dtype=np.int64)

  def find(x):
    root = x
    while parent[root] != root:
      root = parent[root]
    while parent[x] != root:
      parent[x], x = root, parent[x]
    return root

  for a, b in pairs:
    if core[a] and core[b]:
      ra, rb = find(int(a)), find(int(b))
      if ra != rb:
        parent[max(ra, rb)] = min(ra, rb)

  labels = np.full(n, -1, dtype=np.int64)
  roots = {}
  for i in range(n):
    if core[i]:
      r = find(i)
      if r not in roots:
        roots[r] = len(roots)
      labels[i] = roots[r]
  # border points: attach to any adjacent core cluster
  for a, b in pairs:
    a, b = int(a), int(b)
    if core[a] and not core[b] and labels[b] == -1:
      labels[b] = labels[find(a)]
    elif core[b] and not core[a] and labels[a] == -1:
      labels[a] = labels[find(b)]
  return labels
