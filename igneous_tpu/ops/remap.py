"""Label relabeling utilities — fastremap parity (SURVEY.md §2.3).

remap/renumber/unique/mask/mask_except/inverse_component_map as vectorized
numpy (sort + searchsorted), the same capability surface the reference pulls
from the fastremap C++ library (e.g.
/root/reference/igneous/tasks/image/ccl.py:276-286, image.py:804,876).
These run on host next to IO; the device-side equivalent of ``remap`` is a
gather, used inside kernels where the table is dense.
"""

from __future__ import annotations

from typing import Dict, Iterable, Tuple

import numpy as np


def remap(
  arr: np.ndarray,
  table: Dict[int, int],
  preserve_missing_labels: bool = False,
) -> np.ndarray:
  """Apply {old: new} to arr. Missing labels raise unless preserved."""
  if len(table) == 0:
    if preserve_missing_labels:
      return arr.copy()
    if arr.size and arr.any():
      raise KeyError("empty remap table for nonempty array")
    return arr.copy()
  keys = np.fromiter(table.keys(), dtype=arr.dtype, count=len(table))
  vals = np.fromiter(table.values(), dtype=arr.dtype, count=len(table))
  order = np.argsort(keys)
  keys, vals = keys[order], vals[order]
  idx = np.searchsorted(keys, arr)
  idx_c = np.clip(idx, 0, len(keys) - 1)
  found = keys[idx_c] == arr
  if preserve_missing_labels:
    return np.where(found, vals[idx_c], arr)
  if not bool(found.all()):
    missing = np.unique(arr[~found])
    raise KeyError(f"labels not in remap table: {missing[:10].tolist()}…")
  return vals[idx_c]


def renumber(
  arr: np.ndarray, start: int = 1, preserve_zero: bool = True
) -> Tuple[np.ndarray, Dict[int, int]]:
  """Relabel to a dense range; returns (renumbered, {new: old})."""
  uniq = np.unique(arr)
  if preserve_zero:
    uniq = uniq[uniq != 0]
  n = len(uniq) + start
  if n < 2**16:
    dtype = np.uint16
  elif n < 2**32:
    dtype = np.uint32
  else:
    dtype = np.uint64
  out = (np.searchsorted(uniq, arr) + start).astype(dtype)
  if preserve_zero:
    out[arr == 0] = 0
  mapping = {start + i: int(v) for i, v in enumerate(uniq.tolist())}
  if preserve_zero:
    mapping[0] = 0
  return out, mapping


def unique(arr: np.ndarray, return_counts: bool = False):
  return np.unique(arr, return_counts=return_counts)


def mask(arr: np.ndarray, labels: Iterable[int]) -> np.ndarray:
  """Zero out the given labels."""
  labels = np.asarray(sorted(set(int(l) for l in labels)), dtype=arr.dtype)
  if len(labels) == 0:
    return arr.copy()
  idx = np.clip(np.searchsorted(labels, arr), 0, len(labels) - 1)
  hit = labels[idx] == arr
  return np.where(hit, arr.dtype.type(0), arr)


def mask_except(arr: np.ndarray, labels: Iterable[int]) -> np.ndarray:
  """Zero out everything EXCEPT the given labels."""
  labels = np.asarray(sorted(set(int(l) for l in labels)), dtype=arr.dtype)
  if len(labels) == 0:
    return np.zeros_like(arr)
  idx = np.clip(np.searchsorted(labels, arr), 0, len(labels) - 1)
  hit = labels[idx] == arr
  return np.where(hit, arr, arr.dtype.type(0))


def inverse_component_map(a: np.ndarray, b: np.ndarray) -> Dict[int, np.ndarray]:
  """For each nonzero label in ``a``: the set of nonzero ``b`` labels that
  co-occur at the same positions (the CCL face-linking primitive,
  reference ccl.py:276-286)."""
  a = a.reshape(-1)
  b = b.reshape(-1)
  sel = (a != 0) & (b != 0)
  if not sel.any():
    return {}
  pairs = np.stack([a[sel].astype(np.uint64), b[sel].astype(np.uint64)], axis=1)
  pairs = np.unique(pairs, axis=0)
  out: Dict[int, np.ndarray] = {}
  split_at = np.flatnonzero(np.diff(pairs[:, 0])) + 1
  groups = np.split(pairs, split_at)
  for g in groups:
    out[int(g[0, 0])] = g[:, 1]
  return out


def fit_dtype(dtype, value: int):
  """Smallest same-kind dtype that can hold ``value``."""
  kind = np.dtype(dtype).kind
  for width in (1, 2, 4, 8):
    candidate = np.dtype(f"{kind}{width}")
    if value <= np.iinfo(candidate).max:
      return candidate
  raise ValueError(f"{value} does not fit any {kind} dtype")


def label_bboxes(labels: np.ndarray):
  """{original label: (slice, slice, slice)} bounding boxes, one pass.

  Shared by the skeleton CSA branch and CompressedLabels so the
  renumber+find_objects recipe lives in one place; transient memory is
  one dense volume at the minimal renumbered dtype (a uint32 view feeds
  find_objects without an extra int32 copy)."""
  from scipy import ndimage

  dense, mapping = renumber(labels)
  if dense.dtype == np.uint32:
    dense_i = dense.view(np.int32)  # renumbered ids are far below 2^31
  elif dense.dtype.kind != "i":
    dense_i = dense.astype(np.int32)
  else:
    dense_i = dense
  slices = ndimage.find_objects(dense_i)
  return {
    int(mapping[new_id]): sl
    for new_id, sl in enumerate(slices, start=1)
    if sl is not None
  }
