"""Multi-mip pooling kernels: the tinybrain equivalents, TPU-first.

Reference capabilities replaced here (SURVEY.md §2.3: tinybrain):
2x2x1 / 2x2x2 average pooling, mode (COUNTLESS-style majority) pooling for
segmentation with a sparse variant, min/max pooling, striding, and
multi-mip output in one call (/root/reference/igneous/tasks/image/image.py:37-55).

Design notes (TPU):
  - Layout on device is (c, z, y, x): x is innermost so the 128-lane VPU
    vectorizes along the largest axis.
  - One jitted program produces the whole mip pyramid: each mip is a
    reshape-into-windows + reduce, which XLA fuses into tight VPU loops —
    no HBM round-trips between mips.
  - Mode pooling counts pairwise equality over the (≤8-voxel) window and
    argmaxes a score that encodes "highest count, ties to the earliest
    window position (z-major, then y, then x)". Equality-only compares mean
    uint32 label bit patterns can be treated as int32 safely.
  - Odd extents are edge-replicated to the next multiple of the factor:
    for factor-2 windows duplicating the partial contents preserves both
    exact averages and majority votes, so border voxels are exact.
  - uint64 labels should be renumbered to ≤32 bits before pooling (the
    tasks do this via renumbered downloads, as the reference does for
    memory reasons at tasks/image/image.py:749-760) and remapped after.

Exact semantics (mirrored by ops.oracle for tests):
  - average on integer dtypes: per-mip sum then round-half-up division.
  - mode: majority value; ties broken by earliest window position of the
    winning value; sparse=True ignores zeros unless the window is all zero.
"""

from __future__ import annotations

import os
from functools import lru_cache, partial
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis import knobs

Factor3 = Tuple[int, int, int]


def method_for_layer(layer_type: str, method="auto") -> str:
  """``method`` accepts the string names, a DownsampleMethods enum member,
  or its integer value."""
  from ..types import DownsampleMethods

  method = DownsampleMethods.to_name(method)
  if method != "auto":
    return method
  return "mode" if layer_type == "segmentation" else "average"


# ---------------------------------------------------------------------------
# device kernels (operate on (c, z, y, x) arrays)


def _pad_to_multiple(x: jnp.ndarray, f: Factor3) -> jnp.ndarray:
  fx, fy, fz = f
  c, sz, sy, sx = x.shape
  pads = (
    (0, 0),
    (0, (-sz) % fz),
    (0, (-sy) % fy),
    (0, (-sx) % fx),
  )
  if any(p[1] for p in pads):
    x = jnp.pad(x, pads, mode="edge")
  return x


def _window_slices(x: jnp.ndarray, f: Factor3) -> list:
  """The n = fz*fy*fx strided slices of each pooling window, ordered
  z-major then y then x (position index = dx + fx*(dy + fy*dz)).

  Strided slicing keeps the lane (x) dimension's layout intact — no 7-D
  transpose — which is what makes these kernels run at HBM speed on TPU.
  """
  fx, fy, fz = f
  x = _pad_to_multiple(x, f)
  return [
    x[:, dz::fz, dy::fy, dx::fx]
    for dz in range(fz)
    for dy in range(fy)
    for dx in range(fx)
  ]


def _pool_average(x: jnp.ndarray, f: Factor3) -> jnp.ndarray:
  """Mean over each window. Integer semantics: round-half-up, exact.

  ≤16-bit integers accumulate in int32 (≤2^20 window sum, exact). 32-bit
  integers split into 16-bit hi/lo planes whose partial sums stay in int32;
  for power-of-two window sizes the rounded division distributes exactly
  across the split (the TPU has no native 64-bit integers). Non-power-of-two
  windows on 32-bit data fall back to float32 (documented approximation).
  """
  vs = _window_slices(x, f)
  n = len(vs)
  if jnp.issubdtype(x.dtype, jnp.floating):
    acc = sum(v.astype(jnp.float32) for v in vs)
    return (acc / n).astype(x.dtype)
  if x.dtype.itemsize <= 2:
    acc = sum(v.astype(jnp.int32) for v in vs)
    return ((acc + n // 2) // n).astype(x.dtype)
  if n & (n - 1) == 0:  # power-of-two window on 32-bit integers: exact
    k = n.bit_length() - 1
    lo = sum((v & jnp.uint32(0xFFFF)).astype(jnp.int32) for v in (
      vv.astype(jnp.uint32) for vv in vs))
    hi = sum((v >> jnp.uint32(16)).astype(jnp.int32) for v in (
      vv.astype(jnp.uint32) for vv in vs))
    lo = lo + n // 2
    hi = hi + (lo >> 16)
    lo = lo & jnp.int32(0xFFFF)
    # floor((hi*2^16 + lo) / 2^k) = hi*2^(16-k) + lo>>k exactly for k<=16
    out = (hi << (16 - k)) + (lo >> k)
    return out.astype(jnp.uint32).astype(x.dtype)
  acc = sum(v.astype(jnp.float32) for v in vs)
  return jnp.floor(acc / n + 0.5).astype(x.dtype)


def _pool_mode(x, f: Factor3, sparse: bool):
  """Majority pooling. ``x`` is one array or a tuple of same-shaped planes
  jointly representing each voxel's value (uint64 labels ride as two uint32
  planes — the TPU never touches 64-bit integers).

  Winner = highest occurrence count, ties to the earliest window position;
  sparse ignores zeros unless the whole window is zero."""
  is_tuple = isinstance(x, tuple)
  planes = x if is_tuple else (x,)
  per_plane_slices = [_window_slices(p, f) for p in planes]
  n = len(per_plane_slices[0])
  # vs[i] = tuple of plane values at window position i
  vs = [tuple(ps[i] for ps in per_plane_slices) for i in range(n)]

  def eq(a, b):
    e = None
    for pa, pb in zip(a, b):
      ee = pa == pb
      e = ee if e is None else (e & ee)
    return e

  # pairwise equalities are symmetric: n*(n-1)/2 compares instead of n^2
  pair = {}
  for i in range(n):
    for j in range(i + 1, n):
      pair[(i, j)] = eq(vs[i], vs[j]).astype(jnp.int32)

  best_score = None
  best_val = None
  for i in range(n):
    counts = None
    for j in range(n):
      if i == j:
        continue
      e = pair[(min(i, j), max(i, j))]
      counts = e if counts is None else counts + e
    counts = counts + 1  # self-match
    score = counts * n - i
    if sparse:
      zero = None
      for p in vs[i]:
        z = p == 0
        zero = z if zero is None else (zero & z)
      # all-zero windows keep 0: position 0's value is 0 and survives
      score = jnp.where(zero, jnp.int32(-1), score)
    if best_score is None:
      best_score, best_val = score, vs[i]
    else:
      take = score > best_score
      best_score = jnp.where(take, score, best_score)
      best_val = tuple(
        jnp.where(take, a, b) for a, b in zip(vs[i], best_val)
      )
  return best_val if is_tuple else best_val[0]


def _pool_minmax(x: jnp.ndarray, f: Factor3, op: str) -> jnp.ndarray:
  vs = _window_slices(x, f)
  acc = vs[0]
  for v in vs[1:]:
    acc = jnp.minimum(acc, v) if op == "min" else jnp.maximum(acc, v)
  return acc


def _pool_striding(x: jnp.ndarray, f: Factor3) -> jnp.ndarray:
  fx, fy, fz = f
  return x[:, ::fz, ::fy, ::fx]


def _pool_once(x, f: Factor3, method: str, sparse: bool):
  if method == "mode":
    return _pool_mode(x, f, sparse)
  if isinstance(x, tuple):
    raise ValueError("plane-tuple inputs are only valid for mode pooling")
  if method == "average":
    return _pool_average(x, f)
  if method in ("min", "max"):
    return _pool_minmax(x, f, method)
  if method == "striding":
    return _pool_striding(x, f)
  raise ValueError(f"Unknown downsample method: {method}")


def _pyramid_impl(x, factors: Tuple[Factor3, ...], method: str, sparse: bool):
  outs = []
  for f in factors:
    x = _pool_once(x, f, method, sparse)
    outs.append(x)
  return tuple(outs)


_jit_pyramid = partial(
  jax.jit, static_argnames=("factors", "method", "sparse")
)(_pyramid_impl)


def _pyramid(x, factors, method, sparse):
  """The jitted pyramid behind device telemetry (ISSUE 7): the solo-task
  device path (``downsample()``) ticks the same recompile ledger and
  emits the same device.compile/device.execute spans as the batched
  executors — first call on a new input signature is the compile."""
  from ..observability import device as device_telemetry

  kernel = f"pooling.pyramid[{method}]"
  leaves = x if isinstance(x, tuple) else (x,)
  sig = (tuple((np.shape(a), str(np.asarray(a).dtype)) for a in leaves),
         factors, sparse)
  fresh = device_telemetry.LEDGER.note_signature(kernel, sig)
  elements = sum(int(np.size(a)) for a in leaves)
  span = (
    device_telemetry.compile_span(kernel, device_telemetry._devices_of())
    if fresh else
    device_telemetry.execute_span(
      kernel, elements=elements,
      nbytes=sum(int(np.asarray(a).nbytes) for a in leaves),
    )
  )
  with span:
    outs = _jit_pyramid(x, factors, method, sparse)
    jax.block_until_ready(outs)
  return outs


def _fused_pyramid(x, factors, method, sparse, mip_from: int = 0):
  """The fused multi-mip walk: the SAME single compiled program as
  ``_pyramid`` (the whole mip0→mipN walk is one XLA dispatch with no HBM
  round-trips between mips — it shares ``_jit_pyramid``'s executable
  cache), accounted under its own ``pooling.fused_pyramid[method]``
  kernel with ``mip_from``/``mip_to`` attributes on the device.execute
  span. Callers that walk a varying mip range per invocation (the serve
  tier's ancestor synth) use this so the journal records which levels
  each fused dispatch produced."""
  from ..observability import device as device_telemetry

  kernel = f"pooling.fused_pyramid[{method}]"
  leaves = x if isinstance(x, tuple) else (x,)
  sig = (tuple((np.shape(a), str(np.asarray(a).dtype)) for a in leaves),
         factors, sparse)
  fresh = device_telemetry.LEDGER.note_signature(kernel, sig)
  elements = sum(int(np.size(a)) for a in leaves)
  span = (
    device_telemetry.compile_span(kernel, device_telemetry._devices_of())
    if fresh else
    device_telemetry.execute_span(
      kernel, elements=elements,
      nbytes=sum(int(np.asarray(a).nbytes) for a in leaves),
      mip_from=int(mip_from), mip_to=int(mip_from) + len(factors),
    )
  )
  with span:
    outs = _jit_pyramid(x, factors, method, sparse)
    jax.block_until_ready(outs)
  return outs


@lru_cache(maxsize=None)
def pyramid_batched(factors: Tuple[Factor3, ...], method: str, sparse: bool):
  """Compiled batched pyramid: (B, c, z, y, x) → tuple of (B, …) mips.

  The batch axis is how one host feeds many chunks to the device in a
  single program (and how shard_map distributes chunks over a TPU mesh)."""
  return jax.jit(
    jax.vmap(lambda x: _pyramid_impl(x, factors, method, sparse))
  )


# ---------------------------------------------------------------------------
# host-facing API: (x, y, z, c) numpy in/out


def _split_u64_planes(u: np.ndarray):
  """uint64 → (lo, hi) uint32 zero-copy STRIDED VIEWS when the layout
  allows (the one unavoidable copy then happens inside _to_device_layout's
  contiguity fixup). Arithmetic fallback for non-contiguous inputs and
  big-endian hosts (where the word halves are swapped in memory)."""
  import sys

  if sys.byteorder == "little":
    if u.flags["C_CONTIGUOUS"]:
      pairs = u.view(np.uint32).reshape(u.shape + (2,))
      return pairs[..., 0], pairs[..., 1]
    if u.flags["F_CONTIGUOUS"]:
      t = u.T
      pairs = t.view(np.uint32).reshape(t.shape + (2,))
      return pairs[..., 0].T, pairs[..., 1].T
  lo = (u & np.uint64(0xFFFFFFFF)).astype(np.uint32)
  hi = (u >> np.uint64(32)).astype(np.uint32)
  return lo, hi


def _pack_u64_planes(lo: np.ndarray, hi: np.ndarray) -> np.ndarray:
  """(lo, hi) uint32 → uint64 via two interleaving plane writes into an
  F-order buffer, then a zero-copy uint64 view.

  The inputs are (x,y,z,c) transpose views of (c,z,y,x) device outputs, so
  an F-order destination makes both sides of each copy sequential —
  measured 60x faster at 512^3 than astype+shift+or into C order (21s →
  0.35s), and the F-order result is exactly what raw encode (tobytes("F"))
  wants. Arithmetic fallback on big-endian hosts."""
  import sys

  if sys.byteorder == "little":
    if lo.flags["C_CONTIGUOUS"] and hi.flags["C_CONTIGUOUS"]:
      # C-contiguous planes (e.g. batched device outputs): sequential
      # reads, stride-2 writes, C-order result
      out = np.empty(lo.shape + (2,), dtype=np.uint32)
      out[..., 0] = lo
      out[..., 1] = hi
      return out.view(np.uint64)[..., 0]
    out = np.empty((2,) + lo.shape, dtype=np.uint32, order="F")
    out[0] = lo
    out[1] = hi
    return out.T.view(np.uint64)[..., 0].T
  return lo.astype(np.uint64) | (hi.astype(np.uint64) << np.uint64(32))


def _to_device_layout(img: np.ndarray) -> np.ndarray:
  if img.ndim == 3:
    img = img[..., np.newaxis]
  return np.ascontiguousarray(img.transpose(3, 2, 1, 0))  # (c,z,y,x)


def _from_device_layout(x) -> np.ndarray:
  return np.asarray(x).transpose(3, 2, 1, 0)  # back to (x,y,z,c)


def _normalize_factors(factor, num_mips: int) -> Tuple[Factor3, ...]:
  """One (fx,fy,fz) triple applied every mip, or a per-mip sequence."""
  arr = np.asarray(factor, dtype=np.int64)
  if arr.ndim == 2:
    if len(arr) < num_mips:
      raise ValueError(f"need {num_mips} per-mip factors, got {len(arr)}")
    return tuple(tuple(int(v) for v in f) for f in arr[:num_mips])
  return tuple(tuple(int(v) for v in arr) for _ in range(num_mips))


def downsample(
  img: np.ndarray,
  factor,
  num_mips: int = 1,
  method: str = "average",
  sparse: bool = False,
  mip_from: Optional[int] = None,
) -> List[np.ndarray]:
  """Pool ``img`` (x,y,z[,c]) iteratively; returns one array per mip.

  ``factor`` is one (fx,fy,fz) triple applied every mip, or a per-mip
  sequence of triples (near-isotropic pyramids).

  ``mip_from``: when given, the device walk runs as the
  ``pooling.fused_pyramid`` kernel and its device.execute spans carry
  ``mip_from``/``mip_to`` attributes (``img`` is a cutout of mip
  ``mip_from``; the results are mips ``mip_from+1 .. mip_from+num_mips``).
  The compiled program — and the numeric output — is identical either way.
  """
  squeeze = img.ndim == 3
  orig_dtype = img.dtype
  if img.dtype == bool:
    img = img.view(np.uint8)
  factors = _normalize_factors(factor, num_mips)
  run_pyramid = (
    _pyramid if mip_from is None
    else partial(_fused_pyramid, mip_from=mip_from)
  )

  if method == "mode" and img.dtype.itemsize == 8:
    # 64-bit labels ride as (lo, hi) uint32 planes: equality distributes
    # over the split, so majority votes are exact and the device stays
    # in its native 32-bit integer width with no renumber pass.
    # int64/float64 go through their uint64 bit pattern (equality-preserving
    # for integers; float mode pooling is not supported).
    if img.dtype.kind == "f":
      raise ValueError("mode pooling of floating-point data is not supported")
    u = img.view(np.uint64) if img.dtype.kind == "i" else img
    lo, hi = _split_u64_planes(u)
    outs = run_pyramid((_to_device_layout(lo), _to_device_layout(hi)),
                       factors, method, sparse)
    results = []
    for ol, oh in outs:
      r = _pack_u64_planes(_from_device_layout(ol), _from_device_layout(oh))
      r = r.view(orig_dtype) if orig_dtype.kind == "i" else r.astype(orig_dtype)
      results.append(r[..., 0] if squeeze else r)
    return results

  work = img
  if img.dtype.itemsize == 8 and method == "average":
    work = img.astype(np.float32)
  x = _to_device_layout(work)
  outs = run_pyramid(x, factors, method, sparse)
  results = []
  for o in outs:
    r = _from_device_layout(o).astype(orig_dtype, copy=False)
    results.append(r[..., 0] if squeeze else r)
  return results


def downsample_with_averaging(img: np.ndarray, factor, num_mips: int = 1):
  return downsample(img, factor, num_mips, method="average")


def downsample_segmentation(
  img: np.ndarray, factor, num_mips: int = 1, sparse: bool = False
):
  return downsample(img, factor, num_mips, method="mode", sparse=sparse)


# ---------------------------------------------------------------------------
# host production path (accelerator-less workers)
#
# The reference's workers are CPU machines running tinybrain's C kernels
# (SURVEY.md §2.3); an igneous_tpu worker on a host with no TPU gets the
# same deal: the oracle-exact native C++ pooling kernels
# (native/csrc/pooling.cpp) threaded across cores, instead of paying the
# XLA CPU backend's overhead on what is a memory-bound stencil. Tasks call
# downsample_auto(); kernel tests keep calling downsample() so device
# coverage is unchanged. Control: IGNEOUS_POOL_HOST=auto(default)|1|0,
# IGNEOUS_POOL_THREADS=0(hardware)|N.


def _backend_is_cpu() -> bool:
  """True when jax would execute on host CPU. Checks JAX_PLATFORMS first so
  a CPU-pinned worker never initializes a backend just to ask."""
  plats = os.environ.get("JAX_PLATFORMS", "")
  if plats:
    return plats.split(",")[0].strip().lower() == "cpu"
  try:
    return jax.default_backend() == "cpu"
  except Exception:
    return True  # no usable backend at all: host path is the only path


def _host_pool_threads() -> int:
  return knobs.get_int("IGNEOUS_POOL_THREADS")


def _mode_as_u64(img: np.ndarray):
  """Lossless integer→uint64 value mapping for mode pooling (mode only uses
  equality, which any injective mapping preserves; zero maps to zero so
  sparse semantics survive). Returns (u64 array, back-converter)."""
  dt = img.dtype
  if dt == np.uint64:
    return img, lambda r: r
  if dt.kind == "i" and dt.itemsize == 8:
    return img.view(np.uint64), lambda r: r.view(dt)
  if dt.kind == "u" or dt == np.uint8:
    return img.astype(np.uint64), lambda r: r.astype(dt)
  if dt.kind == "i":
    u = np.dtype(f"u{dt.itemsize}")
    return img.view(u).astype(np.uint64), lambda r: r.astype(u).view(dt)
  return None, None


def host_downsample(
  img: np.ndarray,
  factor,
  num_mips: int = 1,
  method: str = "average",
  sparse: bool = False,
  parallel: Optional[int] = None,
) -> Optional[List[np.ndarray]]:
  """`downsample` semantics on the native host kernels; None when this
  (method, dtype) combination has no native path (caller falls back to the
  device kernels). Channels pool independently, matching the device path."""
  from ..native import pooling_lib

  if method not in ("average", "mode", "striding"):
    return None
  if parallel is None:
    parallel = _host_pool_threads()

  squeeze = img.ndim == 3
  if img.ndim == 3:
    img = img[..., np.newaxis]
  if img.ndim != 4:
    return None
  orig_dtype = img.dtype
  if img.dtype == bool:
    img = img.view(np.uint8)
  factors = _normalize_factors(factor, num_mips)

  if method == "striding":
    outs = []
    cur = img
    for fx, fy, fz in factors:
      cur = cur[::fx, ::fy, ::fz]
      outs.append(cur.astype(orig_dtype, copy=False))
    return [o[..., 0] if squeeze else o for o in outs]

  lib = pooling_lib()
  if lib is None:
    return None

  import ctypes

  if method == "average":
    if img.dtype != np.uint8:
      return None

    def run_mip(cur, out, dims, f):
      lib.pool_avg_u8(
        cur.ctypes.data_as(ctypes.c_void_p),
        out.ctypes.data_as(ctypes.c_void_p),
        *dims, *f, int(parallel),
      )

    work, back = img, lambda r: r
    dtype = np.uint8
  else:  # mode
    work, back = _mode_as_u64(img)
    if work is None:
      return None
    dtype = np.uint64

    def run_mip(cur, out, dims, f):
      lib.pool_mode_u64(
        cur.ctypes.data_as(ctypes.c_void_p),
        out.ctypes.data_as(ctypes.c_void_p),
        *dims, *f, int(bool(sparse)), int(parallel),
      )

    def run_mip_f(cur, out, dims, f):
      # Fortran-layout variant: exact for any factor (gathers windows in
      # the required dx-fastest tie order with explicit strides)
      lib.pool_mode_u64_f(
        cur.ctypes.data_as(ctypes.c_void_p),
        out.ctypes.data_as(ctypes.c_void_p),
        *dims, *f, int(bool(sparse)), int(parallel),
      )

  # Transposed-call layout trick: a Fortran-ordered (x, y, z) cutout IS a
  # C-ordered (z, y, x) array, so the kernel can run on it directly with
  # reversed dims/factors — no ascontiguousarray transpose-copy (which
  # otherwise dominates the whole pyramid's wall clock). Exact for
  # average at any factor (order-free sum); for mode only at 2x2 windows,
  # where the earliest-position tie-break provably coincides across both
  # traversal orders (see pooling.cpp f122 note + layout tests).
  def mode_transpose_ok(f):
    # average: order-free sum, any factor. mode: only the NON-sparse
    # 2x2x1 case, where the f122 waterfall's winner is provably order-
    # independent; sparse votes and other factors go through the exact
    # Fortran-strided kernel instead.
    if method == "average":
      return True
    return (not sparse) and f == (2, 2, 1)

  nchan = work.shape[3]
  chan_outs: List[List[np.ndarray]] = []
  for c in range(nchan):
    cur = work[..., c]
    outs = []
    for f in factors:
      fx, fy, fz = f
      nx, ny, nz = cur.shape
      oshape = ((nx + fx - 1) // fx, (ny + fy - 1) // fy,
                (nz + fz - 1) // fz)
      f_contig = (
        not cur.flags["C_CONTIGUOUS"] and cur.T.flags["C_CONTIGUOUS"]
      )
      if f_contig and mode_transpose_ok(f):
        out_t = np.empty(oshape[::-1], dtype=dtype)
        run_mip(cur.T, out_t, (nz, ny, nx), (fz, fy, fx))
        out = out_t.T  # logical (x, y, z), Fortran-ordered like the input
      elif f_contig and method == "mode":
        # factors the transpose-equivalence proof does not cover (e.g.
        # volumetric 2x2x2): the dedicated Fortran-strided mode kernel
        out = np.empty(oshape[::-1], dtype=dtype).T
        run_mip_f(cur, out, (nx, ny, nz), (fx, fy, fz))
      else:
        cur = np.ascontiguousarray(cur)
        out = np.empty(oshape, dtype=dtype)
        run_mip(cur, out, (nx, ny, nz), (fx, fy, fz))
      outs.append(out)
      cur = out
    chan_outs.append(outs)

  results = []
  for i in range(len(factors)):
    if nchan == 1:
      r = chan_outs[0][i][..., np.newaxis]  # view, no copy
    else:
      r = np.stack([chan_outs[c][i] for c in range(nchan)], axis=-1)
    r = back(r)
    if r.dtype != orig_dtype:
      r = r.astype(orig_dtype)
    results.append(r[..., 0] if squeeze else r)
  return results


def _host_pool_active() -> bool:
  """True when downsample_auto would try the native host kernels first.
  Exposed so batching policy (parallel/lease_batcher._group_key) can keep
  downsamples solo on accelerator-less workers, where per-cutout native
  pooling IS the fast path and an XLA-CPU batch dispatch is a ~9x
  pessimization."""
  mode = knobs.get_str("IGNEOUS_POOL_HOST").lower()
  return mode != "0" and (mode == "1" or _backend_is_cpu())


def downsample_auto(
  img: np.ndarray,
  factor,
  num_mips: int = 1,
  method: str = "average",
  sparse: bool = False,
  mip_from: Optional[int] = None,
) -> List[np.ndarray]:
  """Production dispatch: native host kernels when jax would run on CPU
  anyway (or when forced), device kernels otherwise. ``mip_from`` labels
  the device walk's spans (see :func:`downsample`); the native host path
  computes the same walk without device telemetry."""
  if _host_pool_active():
    out = host_downsample(img, factor, num_mips, method=method, sparse=sparse)
    if out is not None:
      return out
  return downsample(
    img, factor, num_mips, method=method, sparse=sparse, mip_from=mip_from
  )
