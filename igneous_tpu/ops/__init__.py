"""Device compute kernels (JAX/XLA/Pallas).

Every per-voxel hot loop the reference delegates to native C++ libraries
(tinybrain, cc3d, zmesh, kimimaro EDT — see SURVEY.md §2.3) lives here as a
jittable device program. Host-side numpy oracles for each kernel live in
``igneous_tpu.ops.oracle`` and define the exact semantics tests assert.
"""

from .pooling import (
  downsample,
  downsample_with_averaging,
  downsample_segmentation,
  method_for_layer,
  pyramid_batched,
)
