"""Queue-leased batched execution — SURVEY.md §5.8's north star made real.

The reference's worker loop (`igneous execute`, reference
igneous_cli/cli.py:888-964) runs one task per lease. On a TPU host that
wastes the chip: each task's device stage (a pooling pyramid, an EDT, a
block CCL) occupies a sliver of the mesh while download/upload dominate
wall clock. This module teaches the worker loop to lease up to K tasks,
group the compatible ones (same type + same device-stage signature), and
run each group's device stage as ONE shard_map'd dispatch across the
mesh — while every lease still completes independently:

  * a member whose host stage fails keeps its lease and recycles alone
    after the visibility timeout (at-least-once, exactly like the solo
    poll loop in queues/filequeue.py:36-80);
  * a failed group dispatch falls back to running the incomplete
    members solo within the same round, so one poisoned member can't
    repeatedly drag K-1 healthy leases into recycling;
  * outputs are byte-identical to solo execution — the group handlers
    feed the batched device results back through the SAME completion
    code paths the solo tasks use (downsample_and_upload(_mips_out=...),
    SkeletonTask.execute(_prepared=..., _edt_field=...), the CCL
    store_ccl_faces helpers).

Batchable today: DownsampleTask (pooling pyramid), SkeletonTask (EDT),
CCLFacesTask (block CCL), MeshTask (marching-cubes count pass). Anything
else — or any member whose cutout clamps to a different shape — executes
solo within the same lease round.
"""

from __future__ import annotations

import random
import time
from collections import defaultdict
from typing import Optional

import numpy as np

from ..lib import Bbox
from ..observability import device as device_telemetry
from ..observability import journal as journal_mod
from ..observability import trace
from ..queues.filequeue import failure_reason, run_with_deadline


def _cutout_key(task):
  """Cache key for a prefetched downsample cutout download."""
  return (
    task.src_path, int(task.mip),
    tuple(int(v) for v in task.offset),
    tuple(int(v) for v in task.shape),
  )


def _range_sizes(tokens):
  """Contiguous-range composition of a lease round: member counts per
  shared RangeLease, largest first (classic tokens excluded). None when
  the round had no range members — the journal attr only appears for
  range-leased rounds, which is what replay.py mines."""
  from ..queues.ranges import RangeSub

  sizes = {}
  for tok in tokens:
    if isinstance(tok, RangeSub):
      sizes[id(tok.parent)] = sizes.get(id(tok.parent), 0) + 1
  return sorted(sizes.values(), reverse=True) if sizes else None


def _group_key(task, volmeta_cache):
  """Hashable device-stage signature, or None when the task must run solo.

  Tasks whose device stage depends only on (cutout shape, dtype, kernel
  params) batch together; the offset is the batch dimension. Keys embed
  the PREDICTED cutout shape so boundary tasks clamped along the same
  dataset faces still group, while ragged members fall out to solo."""
  from ..tasks.ccl import CCLFacesTask
  from ..tasks.image import DownsampleTask
  from ..tasks.mesh import MeshTask
  from ..tasks.skeleton import SkeletonTask

  def bounds_of(path, mip, fill_missing=False):
    key = (path, mip)
    if key not in volmeta_cache:
      from ..volume import Volume

      volmeta_cache[key] = Volume(
        path, mip=mip, fill_missing=fill_missing, bounded=False
      ).meta.bounds(mip)
    return volmeta_cache[key]

  if type(task) is DownsampleTask:
    from ..ops.pooling import _host_pool_active

    if _host_pool_active():
      # accelerator-less host: per-cutout native pooling IS the fast
      # path (same policy as the CCL native check below); an XLA-CPU
      # batch dispatch would be a ~9x pessimization
      return None
    bounds = bounds_of(task.src_path, task.mip, task.fill_missing)
    box = Bbox.intersection(
      Bbox(task.offset, task.offset + task.shape), bounds
    )
    if box.empty():
      return None
    if box != Bbox(task.offset, task.offset + task.shape):
      # clamped edge cutout (ISSUE 12): the paged pyramid batches it
      # with its full-shape siblings when the factor chain pages; chains
      # that must resolve against destination metadata (factor/num_mips
      # unset) stay solo — the handler can't predict their geometry here
      from ..ops.pooling import _normalize_factors
      from .paged import pages_compatible

      if task.factor is None or task.num_mips is None:
        return None
      if not pages_compatible(
        _normalize_factors(task.factor, int(task.num_mips))
      ):
        return None
    return (
      "downsample", task.src_path, int(task.mip),
      tuple(int(v) for v in task.shape),
      None if task.factor is None else tuple(int(v) for v in task.factor),
      task.num_mips, bool(task.sparse), bool(task.fill_missing),
      task.downsample_method, task.compress,
      bool(task.delete_black_uploads), int(task.background_color),
    )

  if type(task) is SkeletonTask:
    bounds = bounds_of(task.cloudpath, task.mip, task.fill_missing)
    core = Bbox.intersection(
      Bbox(task.offset, task.offset + task.shape), bounds
    )
    if core.empty():
      return None  # solo path no-ops it cheaply
    cutout = Bbox.intersection(Bbox(core.minpt, core.maxpt + 1), bounds)
    from ..ops.edt import _host_backend

    # paged EDT (ISSUE 12) runs every shape through one canonical-shape
    # signature, so shape need not partition the group on device hosts
    shape_part = (
      ("paged",) if _host_backend() == "device"
      else tuple(int(v) for v in cutout.size3())
    )
    return (
      "skeleton", task.cloudpath, int(task.mip),
      shape_part, bool(task.fill_missing),
    )

  if type(task) is CCLFacesTask:
    from ..ops.ccl import _ccl_backend

    if _ccl_backend() == "native":
      # CPU-only host: per-cutout native union-find IS the fast path
      # (same policy as ops.ccl.connected_components_batch)
      return None
    bounds = bounds_of(task.src_path, task.mip, task.fill_missing)
    cutout = Bbox.intersection(
      Bbox(task.offset, task.offset + task.shape + 1), bounds
    )
    if cutout.empty():
      return None
    from .paged import ccl_page_compatible

    # paged CCL (ISSUE 12): one page-batch signature covers ragged
    # cutouts, so shape only partitions when pages can't tile the tile
    shape_part = (
      ("paged",) if ccl_page_compatible()
      else tuple(int(v) for v in cutout.size3())
    )
    return (
      "ccl_faces", task.src_path, int(task.mip),
      shape_part,
      task.threshold_gte, task.threshold_lte,
      int(task.dust_threshold), bool(task.fill_missing),
    )

  if type(task) is MeshTask:
    # mesh cutouts need not share shapes: the count pass batches per
    # per-label mask bucket, which already spans tasks (see
    # _run_mesh_group); the kernel and resolution must agree though
    return ("mesh", task.layer_path, int(task.mip), task.mesher)

  return None


class LeaseBatcher:
  """Worker loop that leases up to ``batch_size`` tasks per round and
  runs compatible device stages as single mesh dispatches."""

  def __init__(
    self,
    queue,
    batch_size: int = 8,
    lease_seconds: float = 600,
    mesh=None,
    verbose: bool = False,
    timing: bool = False,
    task_deadline_seconds: Optional[float] = None,
    heartbeat_seconds: Optional[float] = None,
    drain_flag=None,
  ):
    self.queue = queue
    self.batch_size = int(batch_size)
    self.lease_seconds = lease_seconds
    self.mesh = mesh
    self.verbose = verbose
    # per-member wall-clock deadline for the solo/completion stages —
    # shares queues.filequeue.run_with_deadline with the solo poll loop
    self.task_deadline_seconds = task_deadline_seconds
    # lease renewal while a round executes (a K-member round holds K
    # leases across ONE long device dispatch — without renewal, short
    # --lease-sec would re-issue the whole round mid-flight)
    self.heartbeat_seconds = heartbeat_seconds
    # graceful preemption: finish the member in flight, release the rest
    self.drain_flag = drain_flag
    # --time equivalent for batched rounds: per-task stage timing makes
    # no sense when K tasks share one dispatch, so emit one JSON line
    # per lease ROUND instead (wall, members, dispatches delta)
    self.timing = timing
    self.stats = {
      "executed": 0, "batched": 0, "solo": 0, "failed": 0,
      "group_fallbacks": 0, "released": 0, "prefetched_rounds": 0,
      "prefetched_cutouts": 0,
      # ISSUE 6: rounds where the health plane's straggler flag made
      # this worker surrender/skip round-(i+1) pre-leasing
      "straggler_surrenders": 0, "straggler_prefetch_skips": 0,
      # ISSUE 12: members whose unstarted page ranges a flagged worker
      # shed back to the queue mid-campaign (healthy hosts re-lease them)
      "paged_splits": 0,
      # ISSUE 17: steal claims this worker filed while the queue looked
      # empty (the claimed holder's next heartbeat releases the tail)
      "steal_claims": 0,
      "dispatches": defaultdict(int),
    }
    # straggler-flag poll cache: (checked_at_monotonic, flagged)
    self._flag_cache = (0.0, False)
    self._completed_in_group = set()
    self._hb = None
    # next-round pipelining (ISSUE 3): while round i's device dispatch
    # and completions run, a background thread leases round i+1's
    # members and downloads their groupable cutouts, so the chip never
    # waits on the queue or the object store between rounds
    self._next_round = None   # cf.Future -> list[(task, lease_id)]
    self._img_cache = {}      # download-prefetch results, keyed by
                              # (src_path, mip, offset, shape)

  def _draining(self) -> bool:
    return self.drain_flag is not None and self.drain_flag.is_set()

  # how often a worker re-reads <journal>/health/flags.json (one small
  # object GET; anything the health checker wrote since last poll takes
  # effect within this many seconds)
  FLAG_POLL_SEC = 15.0

  def _straggler_flagged(self) -> bool:
    """True when the fleet health plane flagged THIS worker (ISSUE 6):
    `igneous fleet check` publishes a straggler report next to the
    journal, and a flagged worker stops pre-leasing round i+1 — queue
    depth goes to healthy workers instead of a lease this worker will
    be slow (or too dead) to serve."""
    j = journal_mod.get_active()
    if j is None:
      return False
    now = time.monotonic()
    checked_at, flagged = self._flag_cache
    if now - checked_at < self.FLAG_POLL_SEC:
      return flagged
    try:
      from ..observability import health

      flagged = j.worker_id in health.flagged_workers(j.cloudpath)
    except Exception:
      flagged = False
    self._flag_cache = (now, flagged)
    return flagged

  def _current_id(self, lease_id):
    """The member's CURRENT lease token (heartbeat renewals re-timestamp
    fq:// tokens) — and stop renewing it: every caller is about to
    delete, nack, or release the lease."""
    return self._hb.untrack(lease_id) if self._hb is not None else lease_id

  def _release_members(self, members):
    """Drain path: hand still-leased members straight back to the queue
    instead of letting their leases age out on a dead pod."""
    from .. import telemetry

    for _task, lease_id in members:
      try:
        self.queue.release(self._current_id(lease_id))
      except Exception:
        continue  # worst case the lease ages out, as before
      self.stats["released"] += 1
      telemetry.incr("drain.released")

  # -- poll loop ------------------------------------------------------------

  def poll(
    self,
    stop_fn=None,
    max_backoff_window: float = 30.0,
    task_budget: Optional[int] = None,
  ) -> int:
    """Lease K → group → dispatch → complete each lease independently.
    Same stop_fn/backoff contract as queues.filequeue.poll_loop.
    ``task_budget`` caps TOTAL executed tasks: the lease loop never takes
    more leases than the remaining budget, so ``--num-tasks N`` means N
    even when N < batch_size (stop_fn alone is only consulted between
    rounds and would overshoot by up to batch_size-1)."""
    from ..queues.heartbeat import LeaseHeartbeat

    # ONE heartbeat spans the whole poll loop, not one per round: round
    # i+1's pre-leased members must keep renewing WHILE round i executes,
    # or a round longer than lease_seconds would expire them and re-issue
    # the tasks to other workers — the duplicate-execution window the
    # heartbeats exist to close
    self._hb = LeaseHeartbeat(
      self.queue, self.lease_seconds, interval=self.heartbeat_seconds
    )
    self._hb.start()
    try:
      return self._poll_inner(stop_fn, max_backoff_window, task_budget)
    finally:
      self._hb.stop()
      self._hb = None

  def _poll_inner(self, stop_fn, max_backoff_window, task_budget) -> int:
    backoff = 1.0
    while True:
      if self._draining():
        self._surrender_prefetch()
        return self.stats["executed"]
      if stop_fn is not None and stop_fn(
        executed=self.stats["executed"], empty=False
      ):
        self._surrender_prefetch()
        return self.stats["executed"]
      cap = self.batch_size
      if task_budget is not None:
        cap = min(cap, task_budget - self.stats["executed"])
        if cap <= 0:
          self._surrender_prefetch()
          return self.stats["executed"]
      if self._next_round is not None and self._straggler_flagged():
        # flagged mid-flight: round i+1's pre-leased members go straight
        # back to the queue instead of waiting on this slow worker
        self._surrender_prefetch()
        self.stats["straggler_surrenders"] += 1
      members = self._take_prefetched()
      if len(members) > cap:
        # the budget shrank between prefetch and now: surplus goes back
        self._release_members(members[cap:])
        members = members[:cap]
      lease_t0 = time.time()
      synced = []
      while len(members) < cap and not self._draining():
        got = self._lease_many(cap - len(members))
        if not got:
          break
        for leased in got:
          members.append(leased)
          self._hb.track(leased[1])
          synced.append(leased[1])
      if synced:
        # per-round queue-interaction cost: the workload miner folds
        # these into the round-overhead distribution the fleet
        # simulator replays, so batched campaigns simulate queue time,
        # not just compute. range_sizes (when present) records the
        # round's contiguous-range composition for range-lease replay.
        attrs = {"members": len(synced)}
        sizes = _range_sizes(synced)
        if sizes:
          attrs["range_sizes"] = sizes
        trace.record_root(
          "lease.acquire", lease_t0, time.time() - lease_t0, **attrs,
        )
      if self._draining():
        # preempted between lease and dispatch: nothing ran, so every
        # member goes straight back (_release_members untracks each
        # lease from the heartbeat as it releases)
        self._release_members(members)
        return self.stats["executed"]
      if not members:
        if stop_fn is not None and stop_fn(
          executed=self.stats["executed"], empty=True
        ):
          return self.stats["executed"]
        if self._try_steal():
          # a claim is filed: the holder's next heartbeat releases the
          # unstarted tail back to the queue — re-poll soon, don't back
          # off, or the released tasks sit idle for the backoff window
          time.sleep(1.0 + random.random())
          continue
        time.sleep(backoff + random.random())
        backoff = min(backoff * 2, max_backoff_window)
        continue
      backoff = 1.0
      # pipeline the NEXT round while this one dispatches/completes; the
      # prefetch is fenced off every (path, mip) this round writes
      if len(members) == cap and (
        task_budget is None
        or task_budget - self.stats["executed"] - len(members) > 0
      ):
        if self._straggler_flagged():
          # health plane flagged this worker: run what we hold, but
          # don't pre-lease more — healthy workers take round i+1
          self.stats["straggler_prefetch_skips"] += 1
        else:
          next_cap = self.batch_size
          if task_budget is not None:
            next_cap = min(
              next_cap, task_budget - self.stats["executed"] - len(members)
            )
          from ..pipeline import shared_prefetch_pool

          self._next_round = shared_prefetch_pool().submit(
            self._prelease_and_prefetch, next_cap,
            self._round_write_set(members),
          )
      if self.timing:
        import json

        before = dict(self.stats, dispatches=dict(self.stats["dispatches"]))
        t0 = time.perf_counter()
        self.run_round(members)
        print(json.dumps({
          "round_members": len(members),
          "wall_s": round(time.perf_counter() - t0, 3),
          "executed": self.stats["executed"] - before["executed"],
          "failed": self.stats["failed"] - before["failed"],
          "dispatches": {
            k: v - before["dispatches"].get(k, 0)
            for k, v in self.stats["dispatches"].items()
            if v - before["dispatches"].get(k, 0)
          },
        }))
      else:
        self.run_round(members)
      # round boundary: the round's spans (one lease.round + K member
      # task spans) flush as one journal segment
      journal_mod.maybe_flush_active(event="round")

  def _try_steal(self) -> bool:
    """Idle-worker pull half of work stealing (ISSUE 17): the queue
    looks empty, but long-held range leases may still pin unstarted
    work — claim the biggest one so its holder's next heartbeat renewal
    releases the unstarted tail back to the pool. Opt-in
    (IGNEOUS_STEAL); queues without the protocol are skipped."""
    from ..analysis import knobs

    steal_claim = getattr(self.queue, "steal_claim", None)
    if steal_claim is None or not knobs.get_bool("IGNEOUS_STEAL"):
      return False
    try:
      seg = steal_claim()
    except Exception:
      return False
    if seg is None:
      return False
    self.stats["steal_claims"] += 1
    return True

  @staticmethod
  def _mark_started(lease_id):
    """Fence this member off work stealing: only UNSTARTED members are
    carved off a claimed range (queues/ranges.py). Classic string
    tokens have no mark and need none — stealing is range-only."""
    mark = getattr(lease_id, "mark_started", None)
    if mark is not None:
      mark()

  def _lease_many(self, n: int):
    """One queue interaction for up to ``n`` leases: the batched wire
    protocol (ISSUE 15) when the backend has it — fq:// segments arrive
    as RangeSub members sharing ONE underlying lease, which the round's
    delete/nack/release/renew calls consume natively — else the classic
    scalar lease loop."""
    lease_batch = getattr(self.queue, "lease_batch", None)
    if lease_batch is not None:
      return lease_batch(self.lease_seconds, max_tasks=n)
    out = []
    while len(out) < n and not self._draining():
      leased = self.queue.lease(self.lease_seconds)
      if leased is None:
        break
      out.append(leased)
    return out

  # -- next-round pipelining ------------------------------------------------

  def _take_prefetched(self):
    fut, self._next_round = self._next_round, None
    if fut is None:
      return []
    return fut.result()

  def _surrender_prefetch(self):
    """Drain/stop path: pre-leased members of a round that will never
    run go straight back to the queue."""
    try:
      self._release_members(self._take_prefetched())
    finally:
      self._img_cache.clear()

  def _round_write_set(self, members):
    """Conservative (path, mip) image-chunk write set for a round's
    members, or None when a member's writes are unknowable (an arbitrary
    task type may write any layer). Fences the next round's cutout
    prefetch off chunks this round is still producing."""
    from ..tasks.ccl import CCLFacesTask
    from ..tasks.image import TransferTask
    from ..tasks.mesh import MeshTask
    from ..tasks.skeleton import SkeletonTask

    writes = set()
    for task, _lease_id in members:
      if isinstance(task, TransferTask):  # DownsampleTask included
        if not task.skip_first:
          writes.add((task.dest_path, int(task.mip)))
        if task.skip_downsamples:
          continue
        if task.num_mips is None:
          return None  # pyramid depth resolves from dest metadata
        writes.update(
          (task.dest_path, int(task.mip) + m)
          for m in range(1, int(task.num_mips) + 1)
        )
      elif type(task) in (SkeletonTask, CCLFacesTask, MeshTask):
        # these write frag/scratch artifacts, never the image chunks a
        # downsample cutout prefetch reads
        continue
      else:
        return None
    return writes

  def _invalidate_cache(self, writes):
    """Drop prefetched cutouts whose (path, mip) a round wrote — a stale
    image must never feed a later round's dispatch. ``writes=None``
    (unknowable write set) drops everything. The shared chunk decode
    cache follows the same fence (its digest keys keep late readers
    correct regardless; this frees doomed entries at the round edge)."""
    from .. import chunk_cache

    if writes is None:
      self._img_cache.clear()
      chunk_cache.clear()
      return
    if not writes:
      return
    for ckey in [k for k in self._img_cache if (k[0], k[1]) in writes]:
      self._img_cache.pop(ckey, None)
    chunk_cache.invalidate_writes(writes)

  def _prelease_and_prefetch(self, cap: int, busy_writes=frozenset()):
    """Background half of the round pipeline: lease round i+1's members
    and download the cutouts its downsample groups will need, while
    round i owns the device. ``busy_writes`` is the running round's
    (path, mip) write set: cutouts intersecting it are NOT downloaded
    (their chunks are still changing under round i's uploads — the
    round's own fetch reads them fresh after the writes land), and stale
    cache leftovers matching it are dropped. Download failures are
    dropped silently — the round's own download retries and surfaces the
    real error."""
    members = []
    while len(members) < cap and not self._draining():
      got = self._lease_many(cap - len(members))
      if not got:
        break
      if self._draining():
        # the drain raced our leases: members the dying round just
        # released (or fresh tasks) must go straight back UNCOUNTED —
        # keeping them would double-account the same task as both a
        # round release and a surrendered prefetch
        for leased in got:
          try:
            self.queue.release(leased[1])
          except Exception:
            pass
        break
      for leased in got:
        members.append(leased)
        if self._hb is not None:
          # renew from the moment of pre-lease: round i may run longer
          # than lease_seconds, and an expired pre-lease re-delivers the
          # task to another worker while we still hold it
          self._hb.track(leased[1])
    if not members:
      return members
    self.stats["prefetched_rounds"] += 1
    self._invalidate_cache(busy_writes)
    # bound the cache: entries a round never consumed (handler fell back
    # solo, say) must not accumulate; insertion order evicts oldest
    while len(self._img_cache) > 2 * max(cap, 1):
      self._img_cache.pop(next(iter(self._img_cache)), None)
    volmeta_cache = {}
    vols = {}
    from .. import telemetry
    from ..volume import Volume

    for task, _lease_id in members:
      if self._draining():
        break
      try:
        key = _group_key(task, volmeta_cache)
      except Exception:
        continue
      if key is None or key[0] != "downsample":
        continue
      ckey = _cutout_key(task)
      if ckey in self._img_cache:
        continue
      if busy_writes is None or (ckey[0], ckey[1]) in busy_writes:
        continue  # round i is still writing this (path, mip)
      vkey = (task.src_path, int(task.mip), bool(task.fill_missing))
      try:
        if vkey not in vols:
          vols[vkey] = Volume(
            task.src_path, mip=task.mip, fill_missing=task.fill_missing
          )
        self._img_cache[ckey] = vols[vkey].download(
          Bbox(task.offset, task.offset + task.shape)
        )
        self.stats["prefetched_cutouts"] += 1
        telemetry.incr("pipeline.lease.prefetched_cutouts")
      except Exception:
        continue
    return members

  def run_round(self, members):
    """Execute one lease round: group, dispatch groups, solo the rest.

    All K leases are heartbeat-renewed for the duration of the round; a
    drain request releases every member not yet started (groups not yet
    dispatched, solo members not yet executing) back to the queue."""
    from ..queues.heartbeat import LeaseHeartbeat

    owns_hb = self._hb is None  # direct callers outside poll()
    if owns_hb:
      self._hb = LeaseHeartbeat(
        self.queue, self.lease_seconds, interval=self.heartbeat_seconds
      )
      self._hb.start()
    for _task, lease_id in members:
      self._hb.track(lease_id)  # idempotent for pre-leased members
    t0 = time.time()
    before_exec = self.stats["executed"]
    before_fail = self.stats["failed"]
    try:
      self._run_round_inner(members)
    finally:
      # worker-scoped span: one lease round (group dispatch + member
      # completions) under the process's own trace id
      trace.record_root(
        "lease.round", t0, time.time() - t0, members=len(members),
        executed=self.stats["executed"] - before_exec,
        failed=self.stats["failed"] - before_fail,
      )
      # cutouts this round's writes made stale must never feed a later
      # round from the prefetch cache (a member re-leased after failure,
      # say, whose cutout lingered unconsumed)
      self._invalidate_cache(self._round_write_set(members))
      if owns_hb:
        self._hb.stop()
        self._hb = None

  def _run_round_inner(self, members):
    volmeta_cache = {}
    groups = defaultdict(list)
    solo = []
    for task, lease_id in members:
      try:
        key = _group_key(task, volmeta_cache)
      except Exception:
        key = None  # unreadable metadata: the solo path surfaces it
      if key is None:
        solo.append((task, lease_id))
      else:
        groups[key].append((task, lease_id))

    for key, group in groups.items():
      if self._draining():
        self._release_members(group)
        continue
      if len(group) == 1:
        solo.extend(group)
        continue
      handler = {
        "downsample": self._run_downsample_group,
        "skeleton": self._run_skeleton_group,
        "ccl_faces": self._run_ccl_group,
        "mesh": self._run_mesh_group,
      }[key[0]]
      self._completed_in_group = set()
      for _task, lease_id in group:
        self._mark_started(lease_id)  # group dispatch begins now
      try:
        handler(key, group)
      except Exception:
        # group-stage failure (one member's corrupt chunk poisoning the
        # shared download/dispatch, say): don't let it drag K-1 healthy
        # leases into recycling — rerun the incomplete members solo
        # within the same round, so only genuinely bad leases recycle.
        # Tasks are idempotent (at-least-once), so a member whose work
        # finished but whose completion raised is safe to rerun.
        if self.verbose:
          import traceback

          traceback.print_exc()
        self.stats["group_fallbacks"] += 1
        solo.extend(
          m for m in group if m[1] not in self._completed_in_group
        )

    for i, (task, lease_id) in enumerate(solo):
      if self._draining():
        self._release_members(solo[i:])
        return
      if self.verbose:
        print(f"Executing (solo) {task!r}")
      self._mark_started(lease_id)
      try:
        with trace.task_span(
          task, attempt=self._attempt_of(lease_id), mode="batch-solo"
        ):
          run_with_deadline(task.execute, self.task_deadline_seconds)
      except Exception as e:
        self._record_failure(lease_id, e)
        continue
      self.queue.delete(self._current_id(lease_id))
      self.stats["executed"] += 1
      self.stats["solo"] += 1
      # per-delivery fast-path eligibility (ISSUE 7): this delivery fell
      # off the batched device path (ragged shape, singleton group,
      # host-pool policy) — the ledger's ratio is the ragged-batching
      # roadmap item's baseline
      device_telemetry.LEDGER.record_fastpath(host=1)

  # -- completion plumbing --------------------------------------------------

  def _record_failure(self, lease_id, exc):
    """One bookkeeping path for every failed member — solo execution,
    group completion, deadline overrun: the reason is recorded with the
    task (queue.nack), so the batcher's group→solo degradation and the
    DLQ promotion share the same persisted evidence."""
    if self.verbose:
      import traceback

      traceback.print_exc()
    from .. import telemetry

    telemetry.incr("tasks.failed")
    self.stats["failed"] += 1
    if hasattr(self.queue, "nack"):
      self.queue.nack(self._current_id(lease_id), failure_reason(exc))

  def _complete(self, lease_id):
    self.queue.delete(self._current_id(lease_id))
    self.stats["executed"] += 1
    self.stats["batched"] += 1
    device_telemetry.LEDGER.record_fastpath(batched=1)
    # group membership tracks the ORIGINAL token (what handlers hold)
    self._completed_in_group.add(lease_id)

  def _attempt_of(self, lease_id):
    try:
      if hasattr(self.queue, "delivery_count"):
        return int(self.queue.delivery_count(lease_id))
    except Exception:
      pass
    return None

  def _finish_members(self, group, finish_one):
    """Run each member's host completion; a failure keeps that member's
    lease only."""
    for idx, (task, lease_id) in enumerate(group):
      try:
        # the member's completion span: its share of the batched round
        # (the shared device dispatch is the round's own lease.round span)
        with trace.task_span(
          task, attempt=self._attempt_of(lease_id), mode="batched"
        ):
          run_with_deadline(
            lambda: finish_one(idx, task), self.task_deadline_seconds
          )
      except Exception as e:
        self._record_failure(lease_id, e)
        continue
      self._complete(lease_id)

  # -- group handlers -------------------------------------------------------

  def _run_downsample_group(self, key, group):
    """K downsample cutouts → one ChunkExecutor pyramid dispatch for the
    full-shape members plus one paged-pyramid campaign for the clamped
    edge members (ISSUE 12: one compiled signature regardless of edge
    geometry); uploads go back through downsample_and_upload so chunk
    bytes match solo. Between paged rounds a straggler-flagged worker
    sheds members whose page ranges haven't started back to the queue,
    so idle hosts pick up the remainder of the campaign."""
    from ..ops import pooling
    from ..tasks.image import _resolve_factors, downsample_and_upload
    from ..volume import Volume
    from .batch_runner import _from_batch_layout, device_pyramid_batch
    from .executor import cached_chunk_executor, make_mesh

    t0 = group[0][0]
    src = Volume(t0.src_path, mip=t0.mip, fill_missing=t0.fill_missing)
    dest = Volume(
      t0.dest_path, mip=t0.mip, fill_missing=t0.fill_missing,
      delete_black_uploads=t0.delete_black_uploads,
      background_color=t0.background_color,
    )
    factors = _resolve_factors(dest, t0.mip, t0.shape, t0.num_mips, t0.factor)
    if not factors:
      # nothing to produce; solo semantics are a clean no-op per task
      for _task, lease_id in group:
        self._complete(lease_id)
      return
    method = pooling.method_for_layer(dest.layer_type, t0.downsample_method)
    bounds = src.meta.bounds(t0.mip)
    boxes = [
      Bbox.intersection(Bbox(t.offset, t.offset + t.shape), bounds)
      for t, _ in group
    ]
    nominal = tuple(int(v) for v in t0.shape)  # key-shared across members
    full_idx = [
      k for k, b in enumerate(boxes)
      if tuple(int(v) for v in b.size3()) == nominal
    ]
    ragged_idx = [k for k in range(len(boxes)) if k not in full_idx]

    def fetch(pair):
      k, task = pair
      img = self._img_cache.pop(_cutout_key(task), None)
      if img is not None and (
        tuple(int(v) for v in img.shape[:3])
        == tuple(int(v) for v in boxes[k].size3())
      ):
        return img
      return src.download(boxes[k])

    from ..pipeline import shared_prefetch_pool

    imgs = list(shared_prefetch_pool().map(
      fetch, list(enumerate(t for t, _ in group))
    ))
    mesh = self.mesh if self.mesh is not None else make_mesh()

    mips_out = None
    full_pos = {k: j for j, k in enumerate(full_idx)}
    if full_idx:
      is_u64 = method == "mode" and dest.dtype.itemsize == 8
      executor = cached_chunk_executor(
        mesh, factors=tuple(factors), method=method, sparse=t0.sparse,
        planes=2 if is_u64 else 1,
      )
      mips_out = device_pyramid_batch(
        executor, [imgs[k] for k in full_idx], is_u64
      )
      self.stats["dispatches"]["downsample"] += 1

    pyramid = None
    ragged_pos = {k: j for j, k in enumerate(ragged_idx)}
    released = set()
    if ragged_idx:
      from .paged import PagedPyramid

      pyramid = PagedPyramid(
        [imgs[k] for k in ragged_idx], tuple(factors), len(factors),
        method=method, sparse=t0.sparse, mesh=mesh,
      )
      while pyramid.pending:
        if self._straggler_flagged():
          shed = pyramid.split_unstarted()
          if shed:
            self._release_members([group[ragged_idx[j]] for j in shed])
            self.stats["paged_splits"] += len(shed)
            for j in shed:
              k = ragged_idx[j]
              released.add(k)
              # the lease is back in the queue for a healthy worker: the
              # group-fallback path must not ALSO rerun it solo here
              self._completed_in_group.add(group[k][1])
        if not pyramid.pending:
          break
        pyramid.run_round()
        self.stats["dispatches"]["downsample_paged"] += 1

    to_finish = [m for k, m in enumerate(group) if k not in released]
    idx_map = [k for k in range(len(group)) if k not in released]

    def finish(j, task):
      k = idx_map[j]
      # the member's chunk encodes+puts thread on the shared pool; the
      # join keeps the completion contract (delete only after every
      # byte landed) inside the member's own deadline window
      from ..pipeline import SerialSink, config as pcfg, shared_encode_pool

      sink = (
        shared_encode_pool().ticket() if pcfg.use_threads() else SerialSink()
      )
      mips = (
        pyramid.result(ragged_pos[k]) if k in ragged_pos
        else [_from_batch_layout(np.asarray(m[full_pos[k]])) for m in mips_out]
      )
      downsample_and_upload(
        None, boxes[k], dest,
        task_shape=task.shape, mip=task.mip, num_mips=task.num_mips,
        factor=task.factor, sparse=task.sparse,
        method=task.downsample_method, compress=task.compress,
        _mips_out=mips,
        sink=sink,
      )
      sink.join()

    self._finish_members(to_finish, finish)

  def _run_skeleton_group(self, key, group):
    """K skeleton cutouts → one batched EDT dispatch; TEASAR and uploads
    run through SkeletonTask.execute(_prepared, _edt_field)."""
    from ..ops.edt import _host_backend, edt_batch
    from ..volume import Volume

    t0 = group[0][0]
    vol = Volume(
      t0.cloudpath, mip=t0.mip, fill_missing=t0.fill_missing, bounded=False
    )
    anis = tuple(float(v) for v in vol.resolution)

    def prep(task):
      return task.prepare_labels(Volume(
        t0.cloudpath, mip=t0.mip, fill_missing=task.fill_missing,
        bounded=False,
      ))

    from ..pipeline import shared_prefetch_pool

    preps = list(shared_prefetch_pool().map(prep, [t for t, _ in group]))

    live = [i for i, p in enumerate(preps) if p is not None]
    fields = {}
    if live and _host_backend() == "device":
      # device hosts group ragged shapes under one key (ISSUE 12): the
      # paged EDT relabels every member into one canonical-shape page
      # batch, so the whole group rides a single compiled signature
      from .paged import paged_edt

      edts = paged_edt([preps[i][0] for i in live], anis, mesh=self.mesh)
      self.stats["dispatches"]["skeleton"] += 1
      fields = {i: f for i, f in zip(live, edts)}
    elif live:
      labels_batch = np.stack([preps[i][0] for i in live])
      # host backend: shapes partition the group key, so the stack is
      # rectangular; no executor pin — edt_batch's host fallback keeps
      # batched EDTs bit-identical to solo on accelerator-less hosts
      edts = edt_batch(labels_batch, anis, black_border=True, executor=None)
      self.stats["dispatches"]["skeleton"] += 1
      fields = {i: f for i, f in zip(live, edts)}

    def finish(k, task):
      if preps[k] is None:
        return  # empty core: solo execute() is the same clean no-op
      task.execute(_prepared=preps[k], _edt_field=fields[k])

    self._finish_members(group, finish)

  def _run_ccl_group(self, key, group):
    """K CCL cutouts → one batched block-CCL dispatch; face planes are
    stored by the same helpers CCLFacesTask.execute uses."""
    from ..ops.ccl import _batch_executor, connected_components_batch
    from ..storage import CloudFiles
    from ..tasks.ccl import (
      _offset_components,
      _prep_ccl_image,
      ccl_scratch_path,
      store_ccl_faces,
    )

    t0 = group[0][0]
    files = CloudFiles(t0.src_path)
    scratch = ccl_scratch_path(t0.src_path, t0.mip)

    def prep(task):
      return _prep_ccl_image(
        task.src_path, task.mip, task.shape, task.offset,
        task.fill_missing, task.threshold_gte, task.threshold_lte,
        task.dust_threshold,
      )

    from ..pipeline import shared_prefetch_pool

    preps = list(shared_prefetch_pool().map(prep, [t for t, _ in group]))

    from .paged import ccl_page_compatible

    if ccl_page_compatible():
      # page-compatible tile: ragged cutouts share the group key
      # (ISSUE 12), and the paged CCL runs them all through one
      # fixed-page-batch signature
      from .paged import paged_ccl

      comps = paged_ccl([p[0] for p in preps], 6, mesh=self.mesh)
    else:
      imgs = np.stack([p[0] for p in preps])
      comps = connected_components_batch(
        imgs, executor=_batch_executor(6, mesh=self.mesh)
      )
    self.stats["dispatches"]["ccl_faces"] += 1

    def finish(k, task):
      _img, cutout, core = preps[k]
      cc = _offset_components(comps[k], task.task_num, task.shape)
      store_ccl_faces(cc, cutout, core, task.task_num, files, scratch)

    self._finish_members(group, finish)

  def _run_mesh_group(self, key, group):
    """K mesh cutouts → the marching-cubes count pass batches across ALL
    tasks' labels per mask-shape bucket (one dispatch per bucket instead
    of per task); emit/weld/simplify/upload stay per task."""
    from ..tasks.mesh import execute_mesh_tasks_batched

    dispatches = execute_mesh_tasks_batched(
      [t for t, _ in group], mesh=self.mesh,
    )
    self.stats["dispatches"]["mesh"] += dispatches

    def finish(k, task):
      if getattr(task, "_batch_error", None) is not None:
        err = task._batch_error
        task._batch_error = None
        raise err

    self._finish_members(group, finish)


def poll_batched(
  queue,
  batch_size: int = 8,
  lease_seconds: float = 600,
  verbose: bool = False,
  stop_fn=None,
  max_backoff_window: float = 30.0,
  mesh=None,
  task_budget: Optional[int] = None,
  timing: bool = False,
  task_deadline_seconds: Optional[float] = None,
  heartbeat_seconds: Optional[float] = None,
  drain_flag=None,
):
  """Functional entry point mirroring queues.filequeue.poll_loop."""
  batcher = LeaseBatcher(
    queue, batch_size=batch_size, lease_seconds=lease_seconds,
    mesh=mesh, verbose=verbose, timing=timing,
    task_deadline_seconds=task_deadline_seconds,
    heartbeat_seconds=heartbeat_seconds, drain_flag=drain_flag,
  )
  executed = batcher.poll(
    stop_fn=stop_fn, max_backoff_window=max_backoff_window,
    task_budget=task_budget,
  )
  return executed, batcher.stats
