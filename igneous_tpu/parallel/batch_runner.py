"""Batched downsample driver: many grid cells per device dispatch.

SURVEY.md §5.8's TPU mapping made concrete: instead of one process per
task (the reference's LocalTaskQueue(parallel=N)), one host walks the task
grid, downloads K equal-shaped cutouts with an IO thread pool, runs ONE
shard_map'd pooling program for all K across the chip mesh, and uploads
every mip — IO overlaps device compute via double buffering.

Edge cells (clamped to odd shapes) ride the paged pyramid (parallel.paged,
ISSUE 12): fixed (pz, py, px) pages with per-page extent sidecars keep one
compiled signature for every shape; the per-task solo path remains only
for factor chains the page can't tile.
"""

from __future__ import annotations

import concurrent.futures as cf
from functools import partial
from typing import Optional, Sequence

import numpy as np

from ..lib import Bbox, Vec
from ..volume import Volume
from ..downsample_scales import compute_factors, DEFAULT_FACTOR
from ..task_creation.common import get_bounds
from ..tasks.image import DownsampleTask
from ..ops.pooling import (
  _from_device_layout,
  _pack_u64_planes,
  _split_u64_planes,
  _to_device_layout,
)
from .executor import cached_chunk_executor, make_mesh

# single source of truth for the (x,y,z,c) <-> (c,z,y,x) convention
_to_batch_layout = _to_device_layout
_from_batch_layout = _from_device_layout


def device_pyramid_batch(executor, imgs, is_u64_mode: bool):
  """K same-shape (x,y,z[,c]) cutouts → per-mip batch arrays via ONE
  ChunkExecutor dispatch. uint64 mode rides as (lo, hi) uint32 planes and
  comes back packed. Shared by batched_downsample and the lease batcher."""
  if is_u64_mode:
    # zero-copy strided views; the one copy per plane happens in
    # _to_batch_layout's contiguity fixup (shared helpers with
    # ops.pooling.downsample — keep the two paths in sync)
    planes = [_split_u64_planes(i) for i in imgs]
    lo = np.stack([_to_batch_layout(l) for l, _ in planes])
    hi = np.stack([_to_batch_layout(h) for _, h in planes])
    outs, _ = executor((lo, hi))
    return [
      _pack_u64_planes(np.asarray(ol), np.asarray(oh)) for ol, oh in outs
    ]
  batch = np.stack([_to_batch_layout(i) for i in imgs])
  outs, _ = executor(batch)
  return outs


def batched_downsample(
  layer_path: str,
  mip: int = 0,
  num_mips: int = 4,
  shape: Sequence[int] = (256, 256, 64),
  batch_size: int = 8,
  factor: Sequence[int] = DEFAULT_FACTOR,
  sparse: bool = False,
  fill_missing: bool = False,
  compress="gzip",
  mesh=None,
  method: str = "auto",
  bounds: Optional[Bbox] = None,
  drain_flag=None,
) -> dict:
  """Downsample a whole layer with batched device dispatches.

  Creates destination scales (like create_downsampling_tasks), then
  processes the grid in K-cutout batches. Returns run statistics.
  ``bounds`` (at ``mip``) restricts the processed region.
  ``drain_flag`` (anything with ``is_set()``, e.g. lifecycle.StopFlag):
  graceful preemption — the in-flight batch's uploads finish, remaining
  grid cells are skipped and reported via ``stats["drained"]`` so the
  caller can resume with a bounds restriction or a task-queue pass.
  """
  from ..downsample_scales import create_downsample_scales
  from ..ops import pooling

  vol = Volume(layer_path, mip=mip, fill_missing=fill_missing)
  # chunk_size guard: every produced mip must stay chunk-writable
  factors = compute_factors(
    shape, factor, num_mips, chunk_size=vol.meta.chunk_size(mip)
  )
  if not factors:
    raise ValueError(
      f"shape {list(shape)} admits no chunk-aligned downsamples by "
      f"{list(factor)} (chunk {vol.meta.chunk_size(mip).tolist()})"
    )
  create_downsample_scales(vol.meta, mip, shape, factor, num_mips=len(factors))
  vol.commit_info()

  method = pooling.method_for_layer(vol.layer_type, method)
  bounds = get_bounds(vol, bounds, mip, mip)
  shape = Vec(*shape)

  if pooling._host_pool_active():
    # CPU-only host: per-cutout native pooling is the production path
    # (same policy as batched_ccl_faces) — an XLA-CPU batch dispatch is
    # a ~9x pessimization on the most common task type. The cutout
    # stream still pipelines: downloads prefetch and chunk encodes
    # thread while the native kernels pool (ISSUE 3).
    stats = {"batched_cutouts": 0, "edge_cutouts": 0, "dispatches": 0,
             "native_cutouts": 0, "drained": False}
    from ..lib import chunk_bboxes
    from ..pipeline import run_tasks_pipelined

    def native_tasks():
      for gbox in chunk_bboxes(bounds, shape, offset=bounds.minpt, clamp=False):
        if Bbox.intersection(gbox, bounds).empty():
          continue
        yield DownsampleTask(
          layer_path=layer_path, mip=mip, shape=shape.tolist(),
          offset=[int(v) for v in gbox.minpt], fill_missing=fill_missing,
          sparse=sparse, num_mips=len(factors), factor=tuple(factor),
          compress=compress, downsample_method=method,
        )

    run_stats = run_tasks_pipelined(native_tasks(), drain_flag=drain_flag)
    stats["native_cutouts"] = run_stats["executed"]
    stats["drained"] = run_stats["drained"]
    from ..observability import device as device_telemetry

    device_telemetry.LEDGER.record_fastpath(host=run_stats["executed"])
    return stats

  full_boxes = []
  edge_offsets = []  # nominal grid offsets; the per-task path clamps itself
  from ..lib import chunk_bboxes

  for gbox in chunk_bboxes(bounds, shape, offset=bounds.minpt, clamp=False):
    clipped = Bbox.intersection(gbox, bounds)
    if clipped == gbox:
      full_boxes.append(gbox)
    elif not clipped.empty():
      edge_offsets.append(gbox.minpt)

  mesh = mesh if mesh is not None else make_mesh()
  is_u64_mode = method == "mode" and vol.dtype.itemsize == 8
  # shared instance: a fresh ChunkExecutor per call would recompile the
  # pyramid on every lease batch
  executor = cached_chunk_executor(
    mesh, factors=tuple(factors), method=method, sparse=sparse,
    planes=2 if is_u64_mode else 1,
  )
  # the fused walk's span attributes: every device.execute this run emits
  # records which mip range the one-dispatch pyramid produced
  executor.span_attrs = {"mip_from": int(mip), "mip_to": int(mip) + len(factors)}

  stats = {"batched_cutouts": 0, "edge_cutouts": 0, "paged_cutouts": 0,
           "dispatches": 0, "drained": False}

  def draining() -> bool:
    if drain_flag is not None and drain_flag.is_set():
      stats["drained"] = True
    return stats["drained"]

  from ..pipeline import shared_encode_pool, shared_prefetch_pool

  def upload_batch(boxes, mips_out):
    """Route every chunk encode+put through the shared encode pool under
    one ticket — callers overlap it with the next batch's compute and
    only join one batch behind (ISSUE 3: the encode stage was the serial
    tail of every device round)."""
    ticket = shared_encode_pool().ticket()
    for mip_idx, batch_arr in enumerate(mips_out):
      f = Vec(*np.prod(np.asarray(factors[: mip_idx + 1]), axis=0))
      dest_mip = mip + mip_idx + 1
      for k, box in enumerate(boxes):
        mn = box.minpt // f
        arr = _from_batch_layout(batch_arr[k])
        dest_box = Bbox(mn, mn + Vec(*arr.shape[:3]))
        dest_box = Bbox.intersection(dest_box, vol.meta.bounds(dest_mip))
        sl = tuple(slice(0, int(s)) for s in dest_box.size3())
        vol.upload(
          dest_box, arr[sl].astype(vol.dtype, copy=False),
          dest_mip, compress, sink=ticket,
        )
    return ticket

  def run_batch(boxes, imgs):
    mips_out = device_pyramid_batch(executor, imgs, is_u64_mode)
    stats["batched_cutouts"] += len(boxes)
    stats["dispatches"] += 1
    return upload_batch(boxes, mips_out)

  # double buffering: batch i+1's downloads run while batch i computes
  # and uploads (prefetch pool is distinct from the chunk-get pool the
  # downloads fan out to — same-pool nesting would deadlock)
  batches = [
    full_boxes[i : i + batch_size]
    for i in range(0, len(full_boxes), batch_size)
  ]
  io_pool = shared_prefetch_pool()
  pending = (
    [io_pool.submit(vol.download, b) for b in batches[0]]
    if batches else []
  )
  prev_ticket = None
  for i, batch in enumerate(batches):
    if draining():
      break
    imgs = [f.result() for f in pending]
    pending = (
      [io_pool.submit(vol.download, b) for b in batches[i + 1]]
      if i + 1 < len(batches) else []
    )
    # join batch i-1's uploads only now: they overlapped batch i's
    # downloads and this batch's device dispatch
    if prev_ticket is not None:
      prev_ticket.join()
    prev_ticket = run_batch(batch, imgs)
  if prev_ticket is not None:
    prev_ticket.join()
  for f in pending:  # drained mid-stream: settle abandoned downloads
    try:
      f.result()
    except Exception:  # noqa: BLE001 - nothing consumed them
      pass

  # ragged edge cells (ISSUE 12): the paged pyramid packs every clamped
  # cutout into fixed pages, so edges ride the batched device path under
  # the same compiled signature as every other round; the per-task solo
  # path remains only for factor chains no page tiles (pages_compatible)
  from .paged import PagedPyramid, pages_compatible

  if edge_offsets and pages_compatible(tuple(factors)) and not draining():
    from ..tasks.image import downsample_and_upload

    edge_boxes = [
      Bbox.intersection(Bbox(offset, offset + shape), bounds)
      for offset in edge_offsets
    ]
    futs = [io_pool.submit(vol.download, b) for b in edge_boxes]
    imgs = [f.result() for f in futs]
    pyramid = PagedPyramid(
      imgs, tuple(factors), len(factors), method=method, sparse=sparse,
      mesh=mesh,
    )
    ticket = shared_encode_pool().ticket()
    while pyramid.pending and not draining():
      for idx in pyramid.run_round():
        # the solo task's own upload routine, fed the paged mips: chunk
        # bytes stay identical to per-task execution
        downsample_and_upload(
          None, edge_boxes[idx], vol, task_shape=shape.tolist(), mip=mip,
          num_mips=len(factors), factor=tuple(factor), sparse=sparse,
          method=method, compress=compress,
          _mips_out=pyramid.result(idx), sink=ticket,
        )
        stats["paged_cutouts"] += 1
      stats["dispatches"] += 1
    ticket.join()
  else:
    for offset in edge_offsets:
      if draining():
        break
      DownsampleTask(
        layer_path=layer_path,
        mip=mip,
        shape=shape.tolist(),
        offset=[int(v) for v in offset],
        fill_missing=fill_missing,
        sparse=sparse,
        num_mips=len(factors),
        factor=tuple(factor),
        compress=compress,
        downsample_method=method,
      ).execute()
      stats["edge_cutouts"] += 1

  # fast-path eligibility (ISSUE 7): paged edge cutouts ride the batched
  # device program, so only the solo fallback counts as host deliveries
  from ..observability import device as device_telemetry

  device_telemetry.LEDGER.record_fastpath(
    batched=stats["batched_cutouts"] + stats["paged_cutouts"],
    host=stats["edge_cutouts"],
  )
  return stats


# ---------------------------------------------------------------------------
# batched CCL + skeleton forges (VERDICT round-1 item 3: the lease-K →
# one-dispatch pattern generalized beyond downsampling)


def _chunked(items, size):
  return [items[i : i + size] for i in range(0, len(items), size)]


def batched_ccl_faces(
  src_path: str,
  mip: int = 0,
  shape: Sequence[int] = (448, 448, 448),
  batch_size: int = 8,
  threshold_gte=None,
  threshold_lte=None,
  fill_missing: bool = False,
  mesh=None,
) -> dict:
  """CCL pass 1 over a whole layer with batched device dispatches.

  Consumes the same task grid create_ccl_face_tasks builds (identical
  task_nums, offsets, and face outputs — later passes cannot tell the
  difference). Cutouts stream through the PAGED CCL kernel (ISSUE 12) in
  prefetched mixed-shape groups — one compiled signature regardless of
  boundary clamping. When the tile config can't page
  (ccl_page_compatible), the pre-paged per-shape partition remains, with
  single-member shapes on the per-task path.
  """
  from ..ops.ccl import (
    _batch_executor,
    _ccl_backend,
    connected_components_batch,
  )
  from ..storage import CloudFiles
  from ..task_creation.ccl import create_ccl_face_tasks
  from ..tasks.ccl import (
    _offset_components,
    _prep_ccl_image,
    ccl_scratch_path,
    store_ccl_faces,
  )
  tasks = list(create_ccl_face_tasks(
    src_path, mip=mip, shape=shape, threshold_gte=threshold_gte,
    threshold_lte=threshold_lte, fill_missing=fill_missing,
  ))
  stats = {"batched_cutouts": 0, "edge_cutouts": 0, "dispatches": 0}
  if _ccl_backend() == "native":
    # CPU-only host: the native two-pass union-find (per cutout) is the
    # production path — the device kernel on XLA CPU is orders of
    # magnitude slower, so batching it would be a pessimization
    for t in tasks:
      t.execute()
      stats["edge_cutouts"] += 1
    return stats
  files = CloudFiles(src_path)
  scratch = ccl_scratch_path(src_path, mip)

  def prep(task):
    img, cutout, core = _prep_ccl_image(
      src_path, mip, task.shape, task.offset, fill_missing,
      threshold_gte, threshold_lte,
    )
    return task, img, cutout, core

  from .paged import ccl_page_compatible, paged_ccl

  if ccl_page_compatible():
    # ragged paged CCL (ISSUE 12): every cutout — boundary or interior —
    # rides the page kernel under ONE compiled signature, so there is no
    # per-shape partition and no single-member solo fallback
    groups = _chunked(tasks, batch_size)
    with cf.ThreadPoolExecutor(max_workers=8) as io_pool:
      pending = [io_pool.submit(prep, t) for t in groups[0]] if groups else []
      for i, group in enumerate(groups):
        preps = [f.result() for f in pending]
        pending = (
          [io_pool.submit(prep, t) for t in groups[i + 1]]
          if i + 1 < len(groups) else []
        )
        comps = paged_ccl([p[1] for p in preps], 6, mesh=mesh)
        stats["dispatches"] += 1
        for (task, _img, cutout, core), cc in zip(preps, comps):
          cc = _offset_components(cc, task.task_num, task.shape)
          store_ccl_faces(cc, cutout, core, task.task_num, files, scratch)
          stats["batched_cutouts"] += 1
    from ..observability import device as device_telemetry

    device_telemetry.LEDGER.record_fastpath(
      batched=stats["batched_cutouts"], host=stats["edge_cutouts"]
    )
    return stats

  # page-incompatible tile config: the pre-ISSUE-12 per-shape partition —
  # boundary tasks clamped along the same dataset faces batch together;
  # shapes with a single member run the plain task path
  executor = _batch_executor(6, mesh=mesh)
  vol = Volume(src_path, mip=mip)
  bounds = vol.meta.bounds(mip)
  by_shape = {}
  for t in tasks:
    cutout = Bbox.intersection(Bbox(t.offset, t.offset + t.shape + 1), bounds)
    by_shape.setdefault(tuple(cutout.size3()), []).append(t)

  with cf.ThreadPoolExecutor(max_workers=8) as io_pool:
    for shp, members in by_shape.items():
      if len(members) == 1:
        members[0].execute()
        stats["edge_cutouts"] += 1
        continue
      groups = _chunked(members, batch_size)
      # prefetch one group ahead: group i+1 downloads while i computes
      pending = [io_pool.submit(prep, t) for t in groups[0]]
      for i, group in enumerate(groups):
        preps = [f.result() for f in pending]
        pending = (
          [io_pool.submit(prep, t) for t in groups[i + 1]]
          if i + 1 < len(groups) else []
        )
        imgs = np.stack([p[1] for p in preps])
        comps = connected_components_batch(imgs, executor=executor)
        stats["dispatches"] += 1
        for (task, _img, cutout, core), cc in zip(preps, comps):
          cc = _offset_components(cc, task.task_num, task.shape)
          store_ccl_faces(cc, cutout, core, task.task_num, files, scratch)
          stats["batched_cutouts"] += 1
  from ..observability import device as device_telemetry

  device_telemetry.LEDGER.record_fastpath(
    batched=stats["batched_cutouts"], host=stats["edge_cutouts"]
  )
  return stats


def batched_skeleton_forge(
  cloudpath: str,
  mip: int = 0,
  shape: Sequence[int] = (512, 512, 512),
  batch_size: int = 4,
  mesh=None,
  **skeleton_kwargs,
) -> dict:
  """Skeleton forge with the flop-heavy EDT batched across K tasks.

  On the device EDT backend, tasks stream in prefetched MIXED-shape
  groups through the paged canonical-shape EDT (ISSUE 12) — one compiled
  signature, no per-shape partition, no solo fallback. Host backends keep
  the per-shape grouping: label prep on IO threads, all K EDTs as one
  edt_batch call (which runs the native/numpy kernel per cutout), then
  per-task host TEASAR + uploads via SkeletonTask.execute(_prepared,
  _edt_field). Outputs are identical to solo task execution either way.
  """
  from ..ops.edt import edt_batch
  from ..task_creation.skeleton import create_skeletonizing_tasks

  tasks = list(create_skeletonizing_tasks(
    cloudpath, mip=mip, shape=shape, **skeleton_kwargs
  ))
  vol = Volume(cloudpath, mip=mip)
  anis = tuple(float(v) for v in vol.resolution)
  bounds = vol.meta.bounds(mip)
  stats = {"batched_cutouts": 0, "solo_cutouts": 0, "dispatches": 0}

  eligible = []
  by_shape = {}
  for t in tasks:
    core = Bbox.intersection(Bbox(t.offset, t.offset + t.shape), bounds)
    if core.empty():
      continue
    eligible.append(t)
    cutout = Bbox.intersection(Bbox(core.minpt, core.maxpt + 1), bounds)
    by_shape.setdefault(tuple(cutout.size3()), []).append(t)

  def prep(task):
    return task, task.prepare_labels(Volume(
      cloudpath, mip=mip, fill_missing=task.fill_missing, bounded=False
    ))

  from ..ops.edt import _host_backend
  from .paged import paged_edt

  if _host_backend() == "device":
    # ragged paged EDT (ISSUE 12): canonical-shape pages batch every
    # cutout — boundary or interior — through one compiled signature, so
    # mixed shapes need neither a per-shape partition nor solo fallbacks
    groups = _chunked(eligible, batch_size)
    with cf.ThreadPoolExecutor(max_workers=8) as io_pool:
      pending = [io_pool.submit(prep, t) for t in groups[0]] if groups else []
      for i, group in enumerate(groups):
        preps = [f.result() for f in pending]
        pending = (
          [io_pool.submit(prep, t) for t in groups[i + 1]]
          if i + 1 < len(groups) else []
        )
        preps = [(t, p) for t, p in preps if p is not None]
        if not preps:
          continue
        fields = paged_edt([p[0] for _, p in preps], anis, mesh=mesh)
        stats["dispatches"] += 1
        for (task, prepared), field in zip(preps, fields):
          task.execute(_prepared=prepared, _edt_field=field)
          stats["batched_cutouts"] += 1
    from ..observability import device as device_telemetry

    device_telemetry.LEDGER.record_fastpath(
      batched=stats["batched_cutouts"], host=stats["solo_cutouts"]
    )
    return stats

  with cf.ThreadPoolExecutor(max_workers=8) as io_pool:
    for shp, members in by_shape.items():
      if len(members) == 1:
        members[0].execute()
        stats["solo_cutouts"] += 1
        continue
      groups = _chunked(members, batch_size)
      pending = [io_pool.submit(prep, t) for t in groups[0]]
      for i, group in enumerate(groups):
        preps = [f.result() for f in pending]
        pending = (
          [io_pool.submit(prep, t) for t in groups[i + 1]]
          if i + 1 < len(groups) else []
        )
        preps = [(t, p) for t, p in preps if p is not None]
        if not preps:
          continue
        labels_batch = np.stack([p[0] for _, p in preps])
        fields = edt_batch(labels_batch, anis, black_border=True)
        stats["dispatches"] += 1
        for (task, prepared), field in zip(preps, fields):
          task.execute(_prepared=prepared, _edt_field=field)
          stats["batched_cutouts"] += 1
  from ..observability import device as device_telemetry

  device_telemetry.LEDGER.record_fastpath(
    batched=stats["batched_cutouts"], host=stats["solo_cutouts"]
  )
  return stats
