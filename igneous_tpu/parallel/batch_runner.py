"""Batched downsample driver: many grid cells per device dispatch.

SURVEY.md §5.8's TPU mapping made concrete: instead of one process per
task (the reference's LocalTaskQueue(parallel=N)), one host walks the task
grid, downloads K equal-shaped cutouts with an IO thread pool, runs ONE
shard_map'd pooling program for all K across the chip mesh, and uploads
every mip — IO overlaps device compute via double buffering.

Edge cells (clamped to odd shapes) fall back to the per-task path so the
batched program keeps a single compiled shape.
"""

from __future__ import annotations

import concurrent.futures as cf
from typing import Sequence

import numpy as np

from ..lib import Bbox, Vec
from ..volume import Volume
from ..downsample_scales import compute_factors, DEFAULT_FACTOR
from ..task_creation.common import get_bounds
from ..tasks.image import DownsampleTask
from ..ops.pooling import _from_device_layout, _to_device_layout
from .executor import ChunkExecutor, make_mesh

# single source of truth for the (x,y,z,c) <-> (c,z,y,x) convention
_to_batch_layout = _to_device_layout
_from_batch_layout = _from_device_layout


def batched_downsample(
  layer_path: str,
  mip: int = 0,
  num_mips: int = 4,
  shape: Sequence[int] = (256, 256, 64),
  batch_size: int = 8,
  factor: Sequence[int] = DEFAULT_FACTOR,
  sparse: bool = False,
  fill_missing: bool = False,
  compress="gzip",
  mesh=None,
) -> dict:
  """Downsample a whole layer with batched device dispatches.

  Creates destination scales (like create_downsampling_tasks), then
  processes the grid in K-cutout batches. Returns run statistics.
  """
  from ..downsample_scales import create_downsample_scales
  from ..ops import pooling

  vol = Volume(layer_path, mip=mip, fill_missing=fill_missing)
  # chunk_size guard: every produced mip must stay chunk-writable
  factors = compute_factors(
    shape, factor, num_mips, chunk_size=vol.meta.chunk_size(mip)
  )
  if not factors:
    raise ValueError(
      f"shape {list(shape)} admits no chunk-aligned downsamples by "
      f"{list(factor)} (chunk {vol.meta.chunk_size(mip).tolist()})"
    )
  create_downsample_scales(vol.meta, mip, shape, factor, num_mips=len(factors))
  vol.commit_info()

  method = pooling.method_for_layer(vol.layer_type, "auto")
  bounds = get_bounds(vol, None, mip, mip)
  shape = Vec(*shape)

  full_boxes = []
  edge_offsets = []  # nominal grid offsets; the per-task path clamps itself
  from ..lib import chunk_bboxes

  for gbox in chunk_bboxes(bounds, shape, offset=bounds.minpt, clamp=False):
    clipped = Bbox.intersection(gbox, bounds)
    if clipped == gbox:
      full_boxes.append(gbox)
    elif not clipped.empty():
      edge_offsets.append(gbox.minpt)

  mesh = mesh if mesh is not None else make_mesh()
  is_u64_mode = method == "mode" and vol.dtype.itemsize == 8
  executor = ChunkExecutor(
    mesh, factors=tuple(factors), method=method, sparse=sparse,
    planes=2 if is_u64_mode else 1,
  )

  stats = {"batched_cutouts": 0, "edge_cutouts": 0, "dispatches": 0}

  def upload_batch(io_pool, boxes, mips_out):
    """Submit the uploads and return their futures — callers overlap them
    with the next batch's compute and only join one batch behind."""
    futures = []
    for mip_idx, batch_arr in enumerate(mips_out):
      f = Vec(*np.prod(np.asarray(factors[: mip_idx + 1]), axis=0))
      dest_mip = mip + mip_idx + 1
      for k, box in enumerate(boxes):
        mn = box.minpt // f
        arr = _from_batch_layout(batch_arr[k])
        dest_box = Bbox(mn, mn + Vec(*arr.shape[:3]))
        dest_box = Bbox.intersection(dest_box, vol.meta.bounds(dest_mip))
        sl = tuple(slice(0, int(s)) for s in dest_box.size3())
        futures.append(io_pool.submit(
          vol.upload, dest_box, arr[sl].astype(vol.dtype), dest_mip, compress
        ))
    return futures

  def run_batch(io_pool, boxes, imgs):
    if is_u64_mode:
      lo = np.stack([
        _to_batch_layout((i & np.uint64(0xFFFFFFFF)).astype(np.uint32))
        for i in imgs
      ])
      hi = np.stack([
        _to_batch_layout((i >> np.uint64(32)).astype(np.uint32)) for i in imgs
      ])
      outs, _ = executor((lo, hi))
      mips_out = [
        (ol.astype(np.uint64) | (oh.astype(np.uint64) << np.uint64(32)))
        for ol, oh in outs
      ]
    else:
      batch = np.stack([_to_batch_layout(i) for i in imgs])
      mips_out, _ = executor(batch)
    stats["batched_cutouts"] += len(boxes)
    stats["dispatches"] += 1
    return upload_batch(io_pool, boxes, mips_out)

  # double buffering: batch i+1's downloads run while batch i computes
  # and uploads
  batches = [
    full_boxes[i : i + batch_size]
    for i in range(0, len(full_boxes), batch_size)
  ]
  with cf.ThreadPoolExecutor(max_workers=8) as io_pool:
    pending = (
      [io_pool.submit(vol.download, b) for b in batches[0]]
      if batches else []
    )
    prev_uploads = []
    for i, batch in enumerate(batches):
      imgs = [f.result() for f in pending]
      pending = (
        [io_pool.submit(vol.download, b) for b in batches[i + 1]]
        if i + 1 < len(batches) else []
      )
      # join batch i-1's uploads only now: they overlapped batch i's
      # downloads and this batch's device dispatch
      for fut in prev_uploads:
        fut.result()
      prev_uploads = run_batch(io_pool, batch, imgs)
    for fut in prev_uploads:
      fut.result()

    # ragged edge cells: the standard per-task path (nominal grid shape —
    # the task clamps to bounds itself, keeping even pooling extents)
    for offset in edge_offsets:
      DownsampleTask(
        layer_path=layer_path,
        mip=mip,
        shape=shape.tolist(),
        offset=[int(v) for v in offset],
        fill_missing=fill_missing,
        sparse=sparse,
        num_mips=len(factors),
        factor=tuple(factor),
        compress=compress,
      ).execute()
      stats["edge_cutouts"] += 1

  return stats
