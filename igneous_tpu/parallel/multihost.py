"""Multi-host (TPU pod) execution: one lease per pod, one program per batch.

SURVEY.md §5.8's scaling story, extended across hosts: cross-POD
coordination stays queue control plane + object-store data plane (exactly
where the reference puts NCCL-free coordination), and WITHIN one pod
lease, ``jax.distributed`` forms a single global device mesh over every
host's chips so the batched chunk programs (ChunkExecutor /
BatchKernelExecutor) shard_map across the whole pod — collectives ride
ICI between chips and the inter-host fabric between hosts, never DCN to
the object store.

The reference's analog is k8s horizontal scaling of single-host workers
(/root/reference/deployment.yaml, README.md:178); a TPU pod is the unit
here because its hosts share ICI and must run one program.

Usage on each host of a pod (the driver's `dryrun` and the test rig use
the same calls):

    from igneous_tpu.parallel import multihost
    multihost.initialize()          # env-driven: COORDINATOR/NPROC/PID
    mesh = multihost.pod_mesh()     # global mesh over every host's chips
    mine, per = multihost.lease_partition(n_chunks)
    batch = multihost.from_process_local(mesh, download(mine), per)
    ex = ChunkExecutor(mesh, ...)   # same executors as single-host
    outs, stats = ex.run_global(batch)   # read via .addressable_shards
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from ..analysis import knobs


def initialize(
  coordinator_address: Optional[str] = None,
  num_processes: Optional[int] = None,
  process_id: Optional[int] = None,
) -> None:
  """jax.distributed.initialize with env fallbacks — idempotent.

  Env: IGNEOUS_COORDINATOR (host:port), IGNEOUS_NUM_PROCESSES,
  IGNEOUS_PROCESS_ID. On real TPU pods jax auto-detects all three, so
  calling with no arguments and no env is also valid there.
  """
  import jax

  kw = {}
  addr = (
    coordinator_address if coordinator_address is not None
    else knobs.get_str("IGNEOUS_COORDINATOR")
  )
  if addr:
    kw["coordinator_address"] = addr
  nproc = (
    num_processes if num_processes is not None
    else knobs.get_int("IGNEOUS_NUM_PROCESSES")
  )
  if nproc is not None:
    kw["num_processes"] = int(nproc)
  pid = (
    process_id if process_id is not None
    else knobs.get_int("IGNEOUS_PROCESS_ID")
  )
  if pid is not None:
    kw["process_id"] = int(pid)
  prior = getattr(initialize, "_args", None)
  if prior is not None:
    if prior != kw:
      raise RuntimeError(
        f"multihost.initialize already ran with {prior}; re-initializing "
        f"with {kw} is not supported (jax.distributed is process-global)"
      )
    return
  _enable_cpu_collectives(kw)
  jax.distributed.initialize(**kw)
  initialize._args = kw


def cpu_collectives_available() -> bool:
  """Does this jaxlib build ship gloo TCP collectives for the CPU
  backend? Without them a multi-process CPU "pod" can form a mesh but
  every cross-process program fails with "Multiprocess computations
  aren't implemented on the CPU backend"."""
  try:
    from jax._src.lib import xla_client

    return hasattr(xla_client._xla, "make_gloo_tcp_collectives")
  except Exception:
    return False


def _enable_cpu_collectives(kw: dict) -> None:
  """Multi-process rig on the CPU backend: switch the CPU client's
  collectives implementation to gloo BEFORE the backend initializes.

  jax defaults ``jax_cpu_collectives_implementation`` to "none", under
  which any cross-process computation dies with "Multiprocess
  computations aren't implemented on the CPU backend" (the seed failure
  of tests/test_multihost.py). The env var spelling of the flag is not
  read by this jax version, so the config update must be programmatic.
  Real TPU pods never enter here (their collectives ride ICI, not gloo).
  """
  import jax

  if int(kw.get("num_processes") or 1) <= 1:
    return
  plats = os.environ.get("JAX_PLATFORMS", "")
  if plats.split(",")[0].strip().lower() != "cpu":
    return
  if not cpu_collectives_available():
    return  # jaxlib without gloo: leave the default; callers may skip
  try:
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
  except Exception:
    pass  # config option renamed/removed: the capability probe above
          # keeps callers honest about what this build can do


def pod_mesh(axis: str = "chunks"):
  """Global 1-axis mesh over EVERY process's devices (jax.devices() is
  the global list after jax.distributed.initialize). Same construction
  as the single-host executor's make_mesh."""
  from .executor import make_mesh

  return make_mesh(axis=axis)


def lease_partition(n_chunks: int):
  """(this process's chunk indices, per-process slot count).

  The global batch is padded to the canonical size every sharding rule
  needs: a multiple of the global device count (which is itself a
  multiple of the process count on a homogeneous pod). Every process
  owns exactly ``per`` slots; indices past ``n_chunks`` are the zero-pad
  slots ``from_process_local`` fills, so every process always passes the
  SAME local shape regardless of lease divisibility.
  """
  import jax

  ndev = jax.device_count()
  nproc = jax.process_count()
  canon = -(-max(n_chunks, 1) // ndev) * ndev
  per = canon // nproc
  pid = jax.process_index()
  start = pid * per
  return [i for i in range(start, start + per) if i < n_chunks], per


def page_partition(n_pages: int, weights=None):
  """Contiguous per-process PAGE ranges for a paged pod dispatch — the
  page-granular sibling of :func:`lease_partition` (ISSUE 12).

  Returns ``(start, stop, per)``: this process owns global page indices
  ``[start, stop)`` of the campaign's page table, and every process pads
  its local pages to ``per`` slots (a local-device multiple computed
  identically everywhere from the shared inputs) before
  :func:`from_process_local` assembles the global page batch. Pages, not
  chunks, are the unit so ragged members split across hosts mid-cutout.

  ``weights``: optional per-process throughput weights (from journal
  telemetry): a flagged straggler gets a proportionally shorter page
  range, which is how the lease batcher splits a slow host's unstarted
  page ranges to idle hosts without abandoning in-flight rounds.
  """
  import jax

  ndev = jax.device_count()
  nproc = jax.process_count()
  ldev = max(ndev // nproc, 1)
  if weights is None:
    w = np.ones(nproc, dtype=np.float64)
  else:
    if len(weights) != nproc:
      raise ValueError(f"need {nproc} weights, got {len(weights)}")
    w = np.maximum(np.asarray(weights, dtype=np.float64), 1e-9)
  w = w / w.sum()
  bounds = np.floor(np.cumsum(w) * n_pages + 0.5).astype(np.int64)
  bounds[-1] = n_pages
  starts = np.concatenate([[0], bounds[:-1]])
  lens = np.maximum(bounds - starts, 0)
  per = int(-(-max(int(lens.max()), 1) // ldev) * ldev)
  pid = jax.process_index()
  return int(starts[pid]), int(bounds[pid]), per


def throughput_weights(
  journal_path: str,
  workers,
  window_sec: float = 600.0,
  floor: float = 0.25,
):
  """Per-worker weights for :func:`page_partition`, mined from journal
  task spans (ISSUE 17): each worker's busy-time rate (tasks per second
  while executing), so a host running at half the fleet's speed gets
  roughly half the pages up front instead of holding the campaign tail
  hostage. ``workers`` is the process-ordered worker-id list (process i
  must pass the same list so every host computes identical bounds).

  Returns a list aligned to ``workers``, or None when the journal has
  no usable rates — callers fall back to the uniform split. Workers the
  journal hasn't seen yet get the fleet median; measured rates are
  clamped to ``floor``× the median so one noisy sample can't starve a
  host to zero pages.
  """
  from ..observability import fleet

  try:
    rates = fleet.worker_rates(
      fleet.load_effective(journal_path), window_sec=window_sec
    )
  except Exception:
    return None
  known = sorted(rates[w] for w in workers if w in rates)
  if not known:
    return None
  median = float(known[len(known) // 2])
  if median <= 0:
    return None
  return [
    max(float(rates.get(w, median)), floor * median) for w in workers
  ]


def from_process_local(mesh, local_batch: np.ndarray, per: int):
  """Assemble the global sharded batch from each host's local chunks.

  Each process passes the chunks of its ``lease_partition`` slice (any
  count up to ``per``); short batches are zero-padded to ``per`` rows so
  all processes contribute identical local shapes and the inferred
  global shape is consistent. No cross-host data movement — downloads
  stay host-local, the way the reference keeps each worker's IO private.
  """
  import jax
  from jax.sharding import NamedSharding, PartitionSpec as P

  local_batch = np.asarray(local_batch)
  if local_batch.shape[0] > per:
    raise ValueError(
      f"local batch has {local_batch.shape[0]} chunks but this process "
      f"owns only {per} slots (see lease_partition)"
    )
  if local_batch.shape[0] < per:
    pad = np.zeros(
      (per - local_batch.shape[0],) + local_batch.shape[1:],
      local_batch.dtype,
    )
    local_batch = np.concatenate([local_batch, pad])
  sharding = NamedSharding(mesh, P(mesh.axis_names[0]))
  return jax.make_array_from_process_local_data(sharding, local_batch)
