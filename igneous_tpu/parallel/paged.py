"""Ragged paged device batching (ISSUE 12).

The batched executors require same-shape cutouts, so boundary chunks and
mixed-shape fleets fall back to the solo host path or pay per-shape
recompiles — exactly the waste `igneous_device_fastpath_ratio` measures.
This module borrows the Ragged Paged Attention idea (PAPERS.md): decompose
every cutout into fixed ``(pz, py, px)`` pages of a dense device batch,
carry each page's valid extent in an int32 sidecar, and run the kernels
over the page batch so ONE compiled signature serves every shape. Page
rounds always dispatch the same page count (filler pages are zero, extent
0), so the jit signature depends on page geometry alone — a whole campaign
of ragged boundary chunks compiles once per kernel (assert via the ISSUE 7
recompile ledger).

Reassembly is bitwise-identical to the solo paths:

- **Pooling pyramid** — pages are picked so every per-mip cumulative
  factor divides the page dims (``pages_compatible``): pooling windows
  never straddle pages and page origins stay window-aligned at every mip.
  Inside the kernel a clamp-gather replicates each axis's last valid row
  into the slack before pooling — the same value `_pad_to_multiple`'s
  edge padding feeds partial windows in the solo path — and the unpacker
  crops each page's output to the ceil-chained local extent, so partial
  windows match the solo bytes and slack lanes never surface.
- **CCL** — pages tile the zero-padded volume and the tile grid divides
  the page (``ccl_page_compatible``), so the per-page tile-local resolve
  equals the solo kernel's tiling; page-local roots are remapped host-side
  to volume-global flat indices and ONE `_merge_tile_roots` stitches both
  in-page tile seams and page seams. Renumbering depends only on the
  partition, which exact CCL makes identical either way.
- **EDT** — line passes are global along each axis, so EDT pages by
  CANONICAL SHAPE instead of spatial pages: every item is zero-padded to
  the fleet's per-axis max rounded up to a pow2 page count. With
  ``black_border=True`` the appended zeros extend the border background
  run without adding label changes, so foreground distances keep their
  exact envelopes (the envelope passes are run-scoped).

When the solo path still wins: single same-shape deliveries (the dense
stacked pyramid is already one signature and has no page slack), CPU
host-pool policy (`IGNEOUS_POOL_HOST` / native CCL / numpy EDT — the host
kernels beat XLA-on-CPU regardless of packing), and cutouts much smaller
than a page (slack > payload; see ``igneous_device_pad_waste_ratio``).

Env knobs: ``IGNEOUS_PAGE_SHAPE=pz,py,px`` (default 32,32,32) and
``IGNEOUS_PAGE_BATCH`` (pages per dispatch round, default 32; rounded up
to a pow2 multiple of the device count).
"""

from __future__ import annotations

import os
from functools import partial
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..observability import device as device_telemetry
from ..ops.pooling import (
  _from_device_layout,
  _normalize_factors,
  _pack_u64_planes,
  _pool_once,
  _split_u64_planes,
  _to_device_layout,
)
from .executor import BatchKernelExecutor, LRUCache, _shard_map, make_mesh

from .. import tune
from ..analysis import knobs

_DEFAULT_PAGE = (32, 32, 32)


def _next_pow2(n: int) -> int:
  p = 1
  while p < n:
    p <<= 1
  return p


def page_shape() -> Tuple[int, int, int]:
  """The fixed page shape (pz, py, px) in device (z, y, x) axis order.

  The default 32^3 divides evenly by every standard mip factor chain up
  to 5 halvings and by both CCL tile defaults, so all three paged kernels
  share one page geometry."""
  # explicit env > tuned/<device_kind>.json > registry default (ISSUE 19)
  raw = tune.resolve("IGNEOUS_PAGE_SHAPE") or ""
  if not raw:
    return _DEFAULT_PAGE
  parts = tuple(int(v) for v in raw.replace(" ", "").split(","))
  if len(parts) != 3 or any(p <= 0 for p in parts):
    raise ValueError(
      f"IGNEOUS_PAGE_SHAPE must be three positive ints 'pz,py,px': {raw!r}"
    )
  return parts


def page_round_cap(n_devices: int) -> int:
  """Pages per dispatch round: every round sends exactly this many pages
  (zero filler pages, extent 0), so the compiled signature is
  round-count-independent. Pow2 multiple of the device count so the
  executor's own canonical-K rounding is a no-op."""
  want = int(tune.resolve("IGNEOUS_PAGE_BATCH")
             or knobs.KNOBS["IGNEOUS_PAGE_BATCH"].default)
  if want <= 0:
    raise ValueError("IGNEOUS_PAGE_BATCH must be positive")
  cap = max(n_devices, 1)
  while cap < want:
    cap <<= 1
  return cap


def pages_compatible(factors, page: Optional[Tuple[int, int, int]] = None
                     ) -> bool:
  """Can this factor chain pool page-locally? True iff every per-mip
  cumulative factor divides the page dim on its axis — then no pooling
  window ever straddles a page boundary and page origins remain
  window-aligned at every mip."""
  page = page or page_shape()
  cum = [1, 1, 1]
  for (fx, fy, fz) in factors:
    for i, f in enumerate((fz, fy, fx)):
      cum[i] *= int(f)
      if cum[i] <= 0 or page[i] % cum[i]:
        return False
  return True


def ccl_page_compatible(page: Optional[Tuple[int, int, int]] = None) -> bool:
  """True iff the CCL tile grid divides the page, so page boundaries are
  tile-grid boundaries and one host merge stitches both seam kinds."""
  from ..ops.ccl import _tile_shape

  page = page or page_shape()
  return all(p % min(t, p) == 0 for t, p in zip(_tile_shape(), page))


def _ceil_chain(extent, factors):
  """Per-mip extents of one region under the factor chain (z, y, x)."""
  e = tuple(int(v) for v in extent)
  out = []
  for (fx, fy, fz) in factors:
    e = tuple(-(-a // f) for a, f in zip(e, (fz, fy, fx)))
    out.append(e)
  return out


def _mesh_key(mesh):
  return (
    None if mesh is None
    else (tuple(d.id for d in mesh.devices.flat), mesh.axis_names)
  )


# ---------------------------------------------------------------------------
# paged pooling pyramid


def _make_page_kernel(factors, method: str, sparse: bool, planes: int):
  """Per-page pyramid kernel: (pages, ext) → per-mip page outputs.

  ``pages``: (c, pz, py, px) — or a (lo, hi) tuple for uint64 plane pairs;
  ``ext``: (3,) int32 valid extent (ez, ey, ex). Before each pooling step a
  clamp-gather overwrites every row past the extent with the last valid
  row (``min(arange, ext-1)`` — always in-bounds, filler pages clamp to
  row 0), reproducing `_pad_to_multiple`'s edge semantics for the partial
  window while keeping the shape fixed. The extent ceil-divides alongside
  the data, so each mip re-clamps against its own valid region; anything
  past it is slack the unpacker crops."""
  factors = tuple(tuple(int(v) for v in f) for f in factors)

  def kernel(tree):
    pages, ext = tree
    cur = pages if planes == 2 else (pages,)
    e = ext.astype(jnp.int32)
    outs = []
    for (fx, fy, fz) in factors:
      clamped = []
      for p in cur:
        for a in range(3):
          idx = jnp.minimum(
            jnp.arange(p.shape[a + 1], dtype=jnp.int32),
            jnp.maximum(e[a] - 1, 0),
          )
          p = jnp.take(p, idx, axis=a + 1)
        clamped.append(p)
      x = tuple(clamped) if planes == 2 else clamped[0]
      x = _pool_once(x, (fx, fy, fz), method, sparse)
      cur = x if planes == 2 else (x,)
      f_zyx = jnp.asarray((fz, fy, fx), jnp.int32)
      e = (e + f_zyx - 1) // f_zyx
      outs.append(x)
    return tuple(outs)

  return kernel


_PAGED_EXECUTORS = {}


def paged_pyramid_executor(
  factors, method: str, sparse: bool, planes: int = 1, mesh=None
) -> BatchKernelExecutor:
  """Cached executor for the paged pyramid kernel. The page geometry and
  work dtype live in the batch signature, so one executor serves every
  campaign; the cache only keys the kernel configuration."""
  factors = tuple(tuple(int(v) for v in f) for f in factors)
  key = (factors, method, bool(sparse), int(planes), _mesh_key(mesh))
  if key not in _PAGED_EXECUTORS:
    _PAGED_EXECUTORS[key] = BatchKernelExecutor(
      _make_page_kernel(factors, method, sparse, planes),
      mesh=mesh,
      name=f"pooling.paged_pyramid[{method}]",
      cache_variant=(
        "paged_pyramid", factors, method, bool(sparse), int(planes)
      ),
    )
  return _PAGED_EXECUTORS[key]


class PagedPyramid:
  """Incremental paged pyramid over a ragged fleet of cutouts.

  Packs every item (x, y, z[, c]) into fixed pages, dispatches them in
  rounds of exactly ``page_round_cap`` pages, and reassembles per-item
  per-mip outputs bitwise-identical to ``pooling.downsample``. The round
  structure is the lease batcher's straggler-split seam: between rounds a
  flagged host calls :meth:`split_unstarted` to shed every member whose
  page range has not begun, and idle hosts re-lease those members.
  """

  def __init__(
    self,
    imgs: Sequence[np.ndarray],
    factor,
    num_mips: int = 1,
    method: str = "average",
    sparse: bool = False,
    mesh=None,
    page: Optional[Tuple[int, int, int]] = None,
  ):
    if not imgs:
      raise ValueError("need at least one image")
    self.factors = _normalize_factors(factor, num_mips)
    self.page = tuple(page or page_shape())
    if not pages_compatible(self.factors, self.page):
      raise ValueError(
        f"factor chain {self.factors} does not divide page {self.page}; "
        "use the solo path (see pages_compatible)"
      )
    dts = {img.dtype for img in imgs}
    cs = {1 if img.ndim == 3 else img.shape[3] for img in imgs}
    if len(dts) != 1 or len(cs) != 1:
      raise ValueError("paged fleets must share dtype and channel count")
    self._orig_dtype = next(iter(dts))
    self._c = next(iter(cs))
    self.method = method
    self._squeeze = [img.ndim == 3 for img in imgs]

    u64 = method == "mode" and self._orig_dtype.itemsize == 8
    if u64 and self._orig_dtype.kind == "f":
      raise ValueError("mode pooling of floating-point data is not supported")
    self.planes = 2 if u64 else 1

    # mirror pooling.downsample's device dtype rules exactly
    self._planes_in: List[Tuple[np.ndarray, ...]] = []
    self._shapes: List[Tuple[int, int, int]] = []
    for img in imgs:
      work = img.view(np.uint8) if img.dtype == bool else img
      if u64:
        u = work.view(np.uint64) if work.dtype.kind == "i" else work
        lo, hi = _split_u64_planes(u)
        planes = (_to_device_layout(lo), _to_device_layout(hi))
      else:
        if work.dtype.itemsize == 8 and method == "average":
          work = work.astype(np.float32)
        planes = (_to_device_layout(work),)
      self._planes_in.append(planes)
      self._shapes.append(planes[0].shape[1:])  # (Z, Y, X)
    self._work_dtype = self._planes_in[0][0].dtype

    self._executor = paged_pyramid_executor(
      self.factors, method, sparse, self.planes, mesh
    )
    self.cap = page_round_cap(self._executor.n_devices)

    # page table: items packed sequentially so each item's pages are
    # contiguous — a round boundary splits at most one item
    pz, py, px = self.page
    self._entries = []  # (item, (oz, oy, ox), (ez, ey, ex))
    self._left = []
    for i, (Z, Y, X) in enumerate(self._shapes):
      n0 = len(self._entries)
      for oz in range(0, Z, pz):
        for oy in range(0, Y, py):
          for ox in range(0, X, px):
            ext = (min(pz, Z - oz), min(py, Y - oy), min(px, X - ox))
            self._entries.append((i, (oz, oy, ox), ext))
      self._left.append(len(self._entries) - n0)

    self._staged = [
      [
        tuple(
          np.zeros((self._c,) + e, self._work_dtype)
          for _ in range(self.planes)
        )
        for e in _ceil_chain(shape, self.factors)
      ]
      for shape in self._shapes
    ]
    self._next = 0
    self._completed: set = set()
    self._released: set = set()

  @property
  def n_items(self) -> int:
    return len(self._shapes)

  @property
  def pending(self) -> bool:
    return self._next < len(self._entries)

  @property
  def rounds_remaining(self) -> int:
    return -(-(len(self._entries) - self._next) // self.cap)

  def split_unstarted(self) -> List[int]:
    """Straggler split: drop every item NONE of whose pages has been
    dispatched and return their indices. The caller (lease batcher)
    releases those members back to the queue so idle hosts pick up the
    shed page ranges; in-flight items stay here to finish."""
    started = {e[0] for e in self._entries[: self._next]}
    rest = self._entries[self._next:]
    dropped = sorted({e[0] for e in rest} - started)
    if dropped:
      ds = set(dropped)
      self._entries = self._entries[: self._next] + [
        e for e in rest if e[0] not in ds
      ]
      self._released.update(ds)
    return dropped

  def run_round(self) -> List[int]:
    """Dispatch the next round of pages; returns newly-completed item
    indices (whose :meth:`result` is now available)."""
    todo = self._entries[self._next: self._next + self.cap]
    if not todo:
      return []
    self._next += len(todo)
    pz, py, px = self.page
    batch_planes = [
      np.zeros((self.cap, self._c, pz, py, px), self._work_dtype)
      for _ in range(self.planes)
    ]
    exts = np.zeros((self.cap, 3), np.int32)
    itemsize = self._work_dtype.itemsize
    real = 0
    for j, (i, (oz, oy, ox), (ez, ey, ex)) in enumerate(todo):
      for src, dst in zip(self._planes_in[i], batch_planes):
        dst[j][:, :ez, :ey, :ex] = (
          src[:, oz: oz + ez, oy: oy + ey, ox: ox + ex]
        )
      exts[j] = (ez, ey, ex)
      real += ez * ey * ex * self._c * itemsize * self.planes
    # page-pool slack + filler pages: the layer of padding the page
    # packer itself introduces (the pow2 batch layer records separately)
    total = self.cap * pz * py * px * self._c * itemsize * self.planes
    device_telemetry.LEDGER.record_pad_waste(
      padded_bytes=total - real, real_bytes=real
    )
    tree = (
      tuple(batch_planes) if self.planes == 2 else batch_planes[0],
      exts,
    )
    outs = self._executor(
      tree,
      span_attrs={
        "pages": len(todo), "filler_pages": self.cap - len(todo),
      },
    )
    done = []
    for j, (i, (oz, oy, ox), ext) in enumerate(todo):
      F = (1, 1, 1)
      e = ext
      for m, (fx, fy, fz) in enumerate(self.factors):
        f = (fz, fy, fx)
        F = tuple(a * b for a, b in zip(F, f))
        e = tuple(-(-a // b) for a, b in zip(e, f))
        o = (oz // F[0], oy // F[1], ox // F[2])
        mip_out = outs[m] if self.planes == 2 else (outs[m],)
        for pi in range(self.planes):
          self._staged[i][m][pi][
            :,
            o[0]: o[0] + e[0],
            o[1]: o[1] + e[1],
            o[2]: o[2] + e[2],
          ] = np.asarray(mip_out[pi][j])[:, : e[0], : e[1], : e[2]]
      self._left[i] -= 1
      if self._left[i] == 0:
        self._completed.add(i)
        done.append(i)
    return done

  def result(self, i: int) -> List[np.ndarray]:
    """Per-mip outputs for a completed item, formatted exactly as
    ``pooling.downsample`` returns them."""
    if i not in self._completed:
      raise ValueError(f"item {i} is not complete")
    od = self._orig_dtype
    results = []
    for planes in self._staged[i]:
      if self.planes == 2:
        r = _pack_u64_planes(
          _from_device_layout(planes[0]), _from_device_layout(planes[1])
        )
        r = r.view(od) if od.kind == "i" else r.astype(od)
      else:
        r = _from_device_layout(planes[0]).astype(od, copy=False)
      results.append(r[..., 0] if self._squeeze[i] else r)
    return results

  def run(self) -> List[List[np.ndarray]]:
    """Drive every round; returns results for all (unreleased) items."""
    while self.pending:
      self.run_round()
    return [
      self.result(i) for i in range(self.n_items)
      if i not in self._released
    ]


def paged_pyramid(
  imgs: Sequence[np.ndarray],
  factor,
  num_mips: int = 1,
  method: str = "average",
  sparse: bool = False,
  mesh=None,
  page: Optional[Tuple[int, int, int]] = None,
) -> List[List[np.ndarray]]:
  """One-shot paged pyramid: ragged (x, y, z[, c]) cutouts → per-item
  per-mip outputs, bitwise-identical to solo ``pooling.downsample``."""
  return PagedPyramid(
    imgs, factor, num_mips, method=method, sparse=sparse, mesh=mesh,
    page=page,
  ).run()


# ---------------------------------------------------------------------------
# paged CCL


_PAGED_CCL_EXECUTORS = {}


def _paged_ccl_executor(connectivity: int, mesh=None):
  from ..ops.ccl import (
    _ccl_engine, _ccl_tiled_kernel, _device_algo, _tile_shape,
  )

  algo = _device_algo()
  tile = _tile_shape()
  engine = _ccl_engine()
  key = (connectivity, algo, tile, engine, _mesh_key(mesh))
  if key not in _PAGED_CCL_EXECUTORS:
    _PAGED_CCL_EXECUTORS[key] = BatchKernelExecutor(
      partial(
        _ccl_tiled_kernel, connectivity=connectivity, algo=algo,
        tile=tile, engine=engine,
      ),
      mesh=mesh,
      name=f"ccl.paged[{algo}]",
      cache_variant=("ccl_paged", connectivity, algo, tile, engine),
    )
  return _PAGED_CCL_EXECUTORS[key]


def paged_ccl(
  imgs: Sequence[np.ndarray],
  connectivity: int = 6,
  mesh=None,
  page: Optional[Tuple[int, int, int]] = None,
) -> List[np.ndarray]:
  """Ragged device CCL: list of (x, y, z) label volumes → list of
  component volumes numbered exactly as ``connected_components`` numbers
  each alone.

  Every volume is zero-padded (background) to page multiples and cut into
  full-extent pages; the tile-local kernel runs per page, page-local roots
  are remapped to volume-global flat indices, and one `_merge_tile_roots`
  per item stitches in-page tile seams and page seams alike (the tile
  grid divides the page — ``ccl_page_compatible``). Exact CCL both ways
  plus a partition-only renumber ⇒ bitwise-identical outputs."""
  from ..ops.ccl import (
    _dense_relabel, _merge_tile_roots, _roots_to_components, _tile_shape,
    neighbor_offsets,
  )

  neighbor_offsets(connectivity)  # validate before any device work
  page = tuple(page or page_shape())
  if not ccl_page_compatible(page):
    raise ValueError(
      f"CCL tile {_tile_shape()} does not divide page {page}; use the "
      "solo path (see ccl_page_compatible)"
    )
  tile_eff = tuple(min(t, p) for t, p in zip(_tile_shape(), page))
  executor = _paged_ccl_executor(connectivity, mesh)
  cap = page_round_cap(executor.n_devices)
  pz, py, px = page

  vols = []  # (padded labels (Zp,Yp,Xp), (Z,Y,X))
  entries = []  # (item, (oz, oy, ox))
  for img in imgs:
    if img.ndim != 3:
      raise ValueError("labels must be (x, y, z)")
    lab32 = _dense_relabel(np.asarray(img))
    zyx = np.ascontiguousarray(lab32.transpose(2, 1, 0))
    Z, Y, X = zyx.shape
    Zp, Yp, Xp = (-(-s // p) * p for s, p in zip((Z, Y, X), page))
    padded = np.zeros((Zp, Yp, Xp), np.int32)
    padded[:Z, :Y, :X] = zyx
    i = len(vols)
    vols.append((padded, (Z, Y, X)))
    for oz in range(0, Zp, pz):
      for oy in range(0, Yp, py):
        for ox in range(0, Xp, px):
          entries.append((i, (oz, oy, ox)))

  big = np.iinfo(np.int32).max
  roots_vols = [np.full(v[0].shape, big, np.int32) for v in vols]
  page_nbytes = pz * py * px * 4
  real_nbytes = {
    i: int(np.prod(shape)) * 4 for i, (_, shape) in enumerate(vols)
  }
  for r0 in range(0, len(entries), cap):
    todo = entries[r0: r0 + cap]
    batch = np.zeros((cap, pz, py, px), np.int32)
    for j, (i, (oz, oy, ox)) in enumerate(todo):
      batch[j] = vols[i][0][oz: oz + pz, oy: oy + py, ox: ox + px]
    roots = executor(
      batch,
      span_attrs={
        "pages": len(todo), "filler_pages": cap - len(todo),
      },
    )
    for j, (i, (oz, oy, ox)) in enumerate(todo):
      r = np.asarray(roots[j])
      fg = r != big
      if not fg.any():
        continue
      # page-local flat root → volume-global flat root: without this,
      # roots from different pages of one volume collide in the merge
      lz, ly, lx = np.unravel_index(r[fg].astype(np.int64), page)
      dst = roots_vols[i][oz: oz + pz, oy: oy + py, ox: ox + px]
      dst[fg] = np.ravel_multi_index(
        (lz + oz, ly + oy, lx + ox), vols[i][0].shape
      ).astype(np.int32)
  # page padding accounting: pages minus real voxels, plus filler pages
  total = (-(-len(entries) // cap)) * cap * page_nbytes
  real = sum(real_nbytes.values())
  device_telemetry.LEDGER.record_pad_waste(
    padded_bytes=total - real, real_bytes=real
  )

  results = []
  for i, (padded, (Z, Y, X)) in enumerate(vols):
    merged = _merge_tile_roots(
      roots_vols[i], padded, connectivity, tile_eff
    )
    results.append(
      _roots_to_components(merged[:Z, :Y, :X].transpose(2, 1, 0))
    )
  return results


# ---------------------------------------------------------------------------
# paged EDT (canonical-shape pages)


_PAGED_EDT_EXECUTORS = {}


def _paged_edt_executor(anisotropy, mesh=None):
  from ..ops.edt import _edt_sq_kernel, _line_block

  wx, wy, wz = (float(a) for a in anisotropy)
  lb = _line_block()
  key = (wx, wy, wz, lb, _mesh_key(mesh))
  if key not in _PAGED_EDT_EXECUTORS:
    _PAGED_EDT_EXECUTORS[key] = BatchKernelExecutor(
      partial(_edt_sq_kernel, anisotropy=(wx, wy, wz), line_block=lb),
      mesh=mesh,
      name="edt.sq_paged",
      cache_variant=("edt_paged", wx, wy, wz, lb),
    )
  return _PAGED_EDT_EXECUTORS[key]


def paged_edt(
  labels_list: Sequence[np.ndarray],
  anisotropy: Sequence[float] = (1.0, 1.0, 1.0),
  mesh=None,
  page: Optional[Tuple[int, int, int]] = None,
) -> List[np.ndarray]:
  """Ragged device EDT with ``black_border=True`` semantics: list of
  (x, y, z) label volumes → list of float32 distance fields, each
  bitwise-identical to the solo device ``edt(..., black_border=True)``.

  EDT's line passes are global along each axis, so spatial paging is
  impossible; instead items page by CANONICAL SHAPE — zero-padded to the
  fleet's per-axis max (plus the black border) rounded up to a pow2 page
  count, so signatures grow logarithmically with fleet diversity. The
  appended zeros extend the border background run without introducing
  label changes, leaving every foreground voxel's run-scoped envelope —
  and therefore its distance — bit-exact. Only ``black_border=True`` has
  this invariance (an open border would treat the pad as a new boundary),
  which is the skeleton forge's mode; other callers use ``edt_batch``."""
  from ..ops.ccl import _dense_relabel

  if not labels_list:
    return []
  page = tuple(page or page_shape())
  pxyz = (page[2], page[1], page[0])  # page is (pz,py,px); items are xyz
  items = [np.asarray(l) for l in labels_list]
  for it in items:
    if it.ndim != 3:
      raise ValueError("labels must be (x, y, z)")
  canon = tuple(
    _next_pow2(-(-(max(it.shape[a] for it in items) + 2) // p)) * p
    for a, p in zip(range(3), pxyz)
  )
  work = np.zeros((len(items),) + canon, np.int32)
  for k, it in enumerate(items):
    sx, sy, sz = it.shape
    work[k, 1: sx + 1, 1: sy + 1, 1: sz + 1] = _dense_relabel(it)
  real = sum(int(np.prod(it.shape)) * 4 for it in items)
  device_telemetry.LEDGER.record_pad_waste(
    padded_bytes=int(work.nbytes) - real, real_bytes=real
  )
  dev = np.ascontiguousarray(work.transpose(0, 3, 2, 1))  # (K, z, y, x)
  executor = _paged_edt_executor(anisotropy, mesh)
  sq = executor(
    dev, span_attrs={"canonical_shape": "x".join(str(c) for c in canon)}
  )
  outs = []
  for k, it in enumerate(items):
    sx, sy, sz = it.shape
    s = np.asarray(sq[k]).transpose(2, 1, 0)[1: sx + 1, 1: sy + 1, 1: sz + 1]
    o = np.sqrt(s, dtype=np.float32)
    o[it == 0] = 0.0
    outs.append(o)
  return outs


# ---------------------------------------------------------------------------
# pod-mesh entry: paged pyramid over a global page batch


class PagedGlobalRunner:
  """Multi-host paged pyramid (mirrors ChunkExecutor.run_global): runs the
  shard_map'd page kernel over ALREADY-sharded global arrays assembled by
  ``multihost.from_process_local`` from each host's ``page_partition``
  range. Callers read outputs through ``.addressable_shards`` — a host
  only addresses its own chips, so no global gather happens here."""

  def __init__(self, factors, method: str = "average", sparse: bool = False,
               planes: int = 1, mesh=None):
    self.mesh = mesh if mesh is not None else make_mesh()
    self.axis = self.mesh.axis_names[0]
    self.factors = tuple(tuple(int(v) for v in f) for f in factors)
    self.planes = int(planes)
    self.name = f"pooling.paged_pyramid[{method}]"
    self._kernel = _make_page_kernel(
      self.factors, method, sparse, self.planes
    )
    self.cache_variant = (
      "paged_global", self.factors, method, bool(sparse), self.planes
    )
    self._fns = LRUCache()
    self._aot = LRUCache()

  def _make(self, tree):
    """The shard_map'd jit closure for one input structure."""
    batched = jax.vmap(self._kernel)
    out_shape = jax.eval_shape(
      batched,
      jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), tree
      ),
    )
    out_specs = jax.tree.map(lambda _: P(self.axis), out_shape)
    try:
      fn = _shard_map(
        batched, mesh=self.mesh, in_specs=(P(self.axis),),
        out_specs=out_specs, check_vma=False,
      )
    except TypeError:  # older jax: the parameter was named check_rep
      fn = _shard_map(
        batched, mesh=self.mesh, in_specs=(P(self.axis),),
        out_specs=out_specs, check_rep=False,
      )
    # lint: allow=IGN201 AOT lower+compile cached by signature at call site
    return jax.jit(fn)

  def __call__(self, pages, exts):
    """pages: global (K, c, pz, py, px) jax.Array (or a (lo, hi) tuple,
    planes=2); exts: global (K, 3) int32. Returns per-mip global arrays."""
    from .. import compile_cache

    tree = (pages, exts)
    leaves = jax.tree.leaves(tree)
    sig = tuple((tuple(a.shape), str(a.dtype)) for a in leaves)
    # persistent cache path (ISSUE 19): a warm worker fetches the AOT
    # executable instead of compiling; any failure falls through to the
    # plain-jit path below (the default when no cache is configured)
    if compile_cache.get_active() is not None:
      compiled = self._aot.get(sig)
      try:
        if compiled is None:
          compiled = compile_cache.load_or_compile(
            self.name, sig, self.mesh,
            lambda: self._make(tree).lower(tree).compile(),
            variant=self.cache_variant,
          )
          self._aot[sig] = compiled
        with device_telemetry.execute_span(
          self.name,
          elements=sum(int(np.prod(a.shape)) for a in leaves),
          mesh=self.mesh,
        ):
          out = compiled(tree)
          jax.block_until_ready(out)
        return out
      except Exception:
        from ..observability import metrics

        metrics.incr("device.compile_cache.error")
    if sig not in self._fns:
      self._fns[sig] = self._make(tree)
    fresh = device_telemetry.LEDGER.note_signature(self.name, sig)
    span = (
      device_telemetry.compile_span(
        self.name, device_telemetry._devices_of(self.mesh)
      ) if fresh else
      device_telemetry.execute_span(
        self.name,
        elements=sum(int(np.prod(a.shape)) for a in leaves),
        mesh=self.mesh,
      )
    )
    with span:
      out = self._fns[sig](tree)
      jax.block_until_ready(out)
    return out
