"""Batched, mesh-sharded execution of per-chunk kernels.

Chunks are this domain's batch dimension. A host leases K grid tasks,
stacks their equally-shaped cutouts into a (K, c, z, y, x) array, and runs
the pooling pyramid once, shard_map-ed over the mesh's "chunks" axis so
each TPU core processes K/n chunks. Collectives (psum over ICI) aggregate
scalar statistics (voxel counts, histograms) without host round-trips.
"""

from __future__ import annotations

from collections import OrderedDict
from functools import partial
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import compile_cache
from ..analysis import knobs
from ..observability import device as device_telemetry
from ..ops.pooling import _pyramid_impl

# jax.shard_map went public in newer jax; this image ships 0.4.x where it
# still lives under jax.experimental (same semantics, check_rep kwarg) —
# resolve once so every executor builds on whichever the runtime has
if hasattr(jax, "shard_map"):
  _shard_map = jax.shard_map
else:  # pragma: no cover - exercised on jax<0.6 images
  from jax.experimental.shard_map import shard_map as _shard_map


def make_mesh(n_devices: Optional[int] = None, axis: str = "chunks") -> Mesh:
  devices = jax.devices()
  if n_devices is not None:
    devices = devices[:n_devices]
  return Mesh(np.asarray(devices), (axis,))


class LRUCache:
  """Bounded mapping for per-process compiled-executable caches
  (ISSUE 19 satellite): a long-lived worker that drifts through many
  signatures must not hold every executable it ever compiled. Cap from
  ``IGNEOUS_EXECUTOR_CACHE_CAP``; least-recently-USED eviction (both
  lookup and insert refresh recency). Eviction is safe — a re-needed
  signature recompiles (or refetches from the persistent cache) without
  a fresh ``device.recompiles`` tick, since the ledger seen-set is
  independent of this cache."""

  def __init__(self, cap: Optional[int] = None):
    if cap is None:
      cap = knobs.get_int("IGNEOUS_EXECUTOR_CACHE_CAP")
    self.cap = max(int(cap or 64), 1)
    self._d: OrderedDict = OrderedDict()

  def __contains__(self, key) -> bool:
    return key in self._d

  def __len__(self) -> int:
    return len(self._d)

  def __getitem__(self, key):
    val = self._d[key]
    self._d.move_to_end(key)
    return val

  def get(self, key, default=None):
    if key not in self._d:
      return default
    return self[key]

  def __setitem__(self, key, val) -> None:
    self._d[key] = val
    self._d.move_to_end(key)
    while len(self._d) > self.cap:
      self._d.popitem(last=False)

  def keys(self):
    return self._d.keys()


_CHUNK_EXECUTOR_CACHE = {}


def cached_chunk_executor(
  mesh: Optional[Mesh] = None,
  factors: Sequence[Tuple[int, int, int]] = ((2, 2, 1),),
  method: str = "average",
  sparse: bool = False,
  planes: int = 1,
) -> "ChunkExecutor":
  """ChunkExecutor instances keyed by (devices, axis, pyramid config).

  Each instance owns a fresh shard_map'd jit closure, so constructing one
  per call recompiles the pyramid every time — repeat callers
  (batched_downsample per lease batch) must share instances to hit the
  jit cache."""
  mesh = mesh if mesh is not None else make_mesh()
  key = (
    tuple(d.id for d in mesh.devices.flat), mesh.axis_names,
    tuple(tuple(int(v) for v in f) for f in factors), method, sparse,
    int(planes),
  )
  if key not in _CHUNK_EXECUTOR_CACHE:
    _CHUNK_EXECUTOR_CACHE[key] = ChunkExecutor(
      mesh, factors=factors, method=method, sparse=sparse, planes=planes
    )
  return _CHUNK_EXECUTOR_CACHE[key]


class BatchKernelExecutor:
  """shard_map + vmap wrapper for ANY per-chunk device kernel.

  Generalizes ChunkExecutor's lease-K → one-dispatch pattern beyond
  pooling (VERDICT round-1 item 3): the kernel is an arbitrary jax
  function on one chunk (pytree in, pytree out, batch-uniform shapes);
  this runs it for K chunks in a single compiled program with the chunk
  axis partitioned across the mesh over ICI. Compiled variants are cached
  per input signature.

  ``consts`` (ISSUE 10): a non-batched pytree — model parameters — passed
  as ``kernel(consts, chunk)`` and replicated across the mesh instead of
  partitioned. Passing params as a runtime argument (``in_axes=(None, 0)``)
  rather than closing over them keeps the compiled program
  params-independent: one model reload or A/B swap does not recompile,
  and XLA never bakes megabytes of weights into the executable as
  literals. Pre-stage them once with :meth:`put_consts` so the h2d cost
  is paid per model, not per dispatch.
  """

  def __init__(self, kernel, mesh: Optional[Mesh] = None,
               name: Optional[str] = None, cache_variant=None):
    """``cache_variant`` (ISSUE 19): a stable tuple of the kernel's
    closure configuration (factors, tile, anisotropy, model spec…) that
    the name+signature alone cannot capture. Declaring it opts this
    executor into the persistent compile cache; None keeps the site
    compile-only — two differently-configured kernels sharing a name
    must never exchange executables."""
    self.kernel = kernel
    self.name = name or getattr(kernel, "__name__", "kernel").lstrip("_")
    self.mesh = mesh if mesh is not None else make_mesh()
    self.axis = self.mesh.axis_names[0]
    self.cache_variant = cache_variant
    self._cache = LRUCache()
    self._consts_cache = LRUCache()

  @property
  def n_devices(self) -> int:
    return int(np.prod(self.mesh.devices.shape))

  def _signature(self, batch):
    leaves, treedef = jax.tree.flatten(batch)
    return (treedef, tuple((l.shape, str(l.dtype)) for l in leaves))

  def put_consts(self, key, consts):
    """Stage a consts pytree on device, replicated over the mesh, once
    per ``key`` (callers use a stable identity such as the model
    cloudpath). Returns the device pytree to pass back as ``consts=``."""
    cache_key = (key, tuple(d.id for d in self.mesh.devices.flat))
    if cache_key not in self._consts_cache:
      consts = jax.tree.map(np.asarray, consts)
      replicated = NamedSharding(self.mesh, P())
      with device_telemetry.transfer_span(
        "h2d", device_telemetry.nbytes_of(consts), kernel=self.name,
        mesh=self.mesh,
      ):
        self._consts_cache[cache_key] = jax.tree.map(
          lambda a: jax.device_put(a, replicated), consts
        )
    return self._consts_cache[cache_key]

  def _build(self, example, consts=None):
    if consts is None:
      batched = jax.vmap(self.kernel)
      out_shape = jax.eval_shape(batched, example)
      in_specs = P(self.axis)
    else:
      batched = jax.vmap(self.kernel, in_axes=(None, 0))
      out_shape = jax.eval_shape(batched, consts, example)
      # P() prefix: the whole consts pytree is replicated, only the
      # chunk batch is partitioned over the mesh axis
      in_specs = (P(), P(self.axis))
    out_specs = jax.tree.map(lambda _: P(self.axis), out_shape)
    # check_vma off: kernels here are pure per-chunk programs with no
    # collectives, but their internal scan/while carries start from
    # literals, which the varying-manual-axes checker rejects under
    # shard_map (carry input unvarying vs output varying)
    try:
      fn = _shard_map(
        batched, mesh=self.mesh,
        in_specs=in_specs, out_specs=out_specs, check_vma=False,
      )
    except TypeError:  # older jax: the parameter was named check_rep
      fn = _shard_map(
        batched, mesh=self.mesh,
        in_specs=in_specs, out_specs=out_specs, check_rep=False,
      )
    # lint: allow=IGN201 AOT lower+compile cached by signature at call site
    return jax.jit(fn)

  def __call__(self, batch, consts=None, span_attrs=None):
    """batch: pytree of (K, ...) arrays → pytree of (K, ...) numpy.
    ``consts``: optional non-batched pytree (see class docstring);
    device arrays from :meth:`put_consts` skip the per-call h2d.
    ``span_attrs``: extra attributes for this call's device.execute
    span (e.g. the infer engine's ``padded_slots``) — never part of
    the compile signature."""
    batch = jax.tree.map(np.asarray, batch)
    leaves = jax.tree.leaves(batch)
    k = leaves[0].shape[0]
    # canonical K: next power of two that is a mesh multiple. K is part
    # of the jit-cache signature, so uncapped ragged group sizes (e.g.
    # per-task label counts) would compile a program per K
    canon = self.n_devices
    while canon < k:
      canon <<= 1
    rem = canon - k
    if rem:
      batch = jax.tree.map(
        lambda a: np.concatenate(
          [a, np.zeros((rem,) + a.shape[1:], a.dtype)]
        ),
        batch,
      )
    # per-dispatch padding bytes (ISSUE 12): the pow2 batch rounding is
    # one of the padding layers igneous_device_pad_waste_ratio tracks
    row_bytes = sum(int(l.nbytes) // max(k, 1) for l in leaves)
    device_telemetry.LEDGER.record_pad_waste(
      padded_bytes=rem * row_bytes, real_bytes=k * row_bytes,
    )
    if consts is not None:
      # numpy consts are staged ad hoc (keyed by leaf identity); callers
      # with a stable model identity use put_consts() for real reuse
      leaves = jax.tree.leaves(consts)
      if any(isinstance(l, np.ndarray) for l in leaves):
        consts = self.put_consts(tuple(id(l) for l in leaves), consts)
    sig = self._signature(batch)
    if consts is not None:
      sig = (sig, self._signature(consts))
    sharding = NamedSharding(self.mesh, P(self.axis))
    with device_telemetry.transfer_span(
      "h2d", device_telemetry.nbytes_of(batch), kernel=self.name,
      mesh=self.mesh,
    ):
      dev = jax.tree.map(lambda a: jax.device_put(a, sharding), batch)
    argv = (dev,) if consts is None else (consts, dev)
    if sig not in self._cache:
      # device.compile vs device.execute split (ISSUE 7): AOT
      # lower+compile so the compile span measures XLA work alone —
      # jit's lazy first-call compile would fold it into the first
      # execute and poison the utilization ledger. load_or_compile
      # (ISSUE 19) consults the persistent cache first when one is
      # configured and this executor declared its cache_variant.
      self._cache[sig] = compile_cache.load_or_compile(
        self.name, sig, self.mesh,
        lambda: self._build(batch, consts).lower(*argv).compile(),
        variant=self.cache_variant,
      )
    with device_telemetry.execute_span(
      self.name, elements=device_telemetry.elements_of(batch),
      nbytes=device_telemetry.nbytes_of(batch), mesh=self.mesh,
      **(span_attrs or {}),
    ):
      out = self._cache[sig](*argv)
      jax.block_until_ready(out)
    with device_telemetry.transfer_span(
      "d2h", device_telemetry.nbytes_of(out), kernel=self.name,
      mesh=self.mesh,
    ):
      return jax.tree.map(lambda a: np.asarray(a)[:k], out)


class ChunkExecutor:
  """Compiles and runs batched chunk pyramids over a device mesh.

  One instance per (factors, method, sparse, chunk shape, dtype) — the
  compiled program is cached by XLA across calls.
  """

  def __init__(
    self,
    mesh: Optional[Mesh] = None,
    factors: Sequence[Tuple[int, int, int]] = ((2, 2, 1),),
    method: str = "average",
    sparse: bool = False,
    planes: int = 1,
  ):
    """``planes=2`` takes (lo, hi) uint32 plane pairs — the uint64 label
    representation (see ops.pooling) — and returns per-mip plane pairs."""
    self.mesh = mesh if mesh is not None else make_mesh()
    self.factors = tuple(tuple(int(v) for v in f) for f in factors)
    self.method = method
    self.sparse = sparse
    self.planes = int(planes)
    if self.planes not in (1, 2):
      raise ValueError("planes must be 1 or 2")
    if self.planes == 2 and method != "mode":
      raise ValueError("plane pairs are only meaningful for mode pooling")
    self.axis = self.mesh.axis_names[0]
    self.name = f"pooling.pyramid[{method}]"
    # extra device.execute span attributes (mutable, not part of any
    # cache key): batched_downsample stamps the fused walk's mip range
    # ({"mip_from": m, "mip_to": m + len(factors)}) here before each run
    self.span_attrs: dict = {}
    self._fn = self._build()
    # input signature -> AOT executable (ISSUE 7); LRU-bounded (ISSUE 19)
    self._compiled = LRUCache()
    # persistent-cache key component: the pyramid configuration this
    # closure bakes in (name+signature alone cannot distinguish two
    # factor chains of equal shapes)
    self.cache_variant = (
      "pyramid", self.factors, method, bool(sparse), self.planes
    )

  def _build(self):
    factors, method, sparse = self.factors, self.method, self.sparse
    axis = self.axis
    planes = self.planes

    def per_shard(xs):  # xs: tuple of (k, c, z, y, x) local shards
      def one(arrs):
        val = arrs if planes == 2 else arrs[0]
        return _pyramid_impl(val, factors, method, sparse)

      outs = jax.vmap(lambda *arrs: one(arrs))(*xs)
      # voxel count psum: a cross-chip collective over ICI so callers get
      # a global nonzero tally with no host gather
      fg = xs[0] != 0
      for extra in xs[1:]:
        fg = fg | (extra != 0)
      nonzero = jax.lax.psum(jnp.sum(fg, dtype=jnp.int32), axis_name=axis)
      return outs, nonzero

    in_spec = tuple(P(self.axis) for _ in range(planes))
    if planes == 2:
      mip_spec = tuple((P(self.axis), P(self.axis)) for _ in factors)
    else:
      mip_spec = tuple(P(self.axis) for _ in factors)
    out_spec = (mip_spec, P())
    fn = _shard_map(
      per_shard, mesh=self.mesh, in_specs=(in_spec,), out_specs=out_spec
    )
    # lint: allow=IGN201 AOT lower+compile cached by signature at call site
    return jax.jit(fn)

  @property
  def n_devices(self) -> int:
    return int(np.prod(self.mesh.devices.shape))

  def pad_batch(self, batch: np.ndarray) -> Tuple[np.ndarray, int]:
    """Pad the chunk axis to a multiple of the mesh size."""
    k = batch.shape[0]
    rem = (-k) % self.n_devices
    if rem:
      batch = np.concatenate([batch, np.zeros((rem,) + batch.shape[1:], batch.dtype)])
    return batch, k

  def run_global(self, global_batch):
    """Multi-host entry point: run the compiled sharded program on an
    ALREADY-sharded global jax.Array (multihost.from_process_local).
    The caller owns padding (multihost.lease_partition) and reads
    outputs through .addressable_shards — a host can only address its
    own chips, so no global gather/un-pad happens here."""
    arrs = (
      global_batch if isinstance(global_batch, tuple) else (global_batch,)
    )
    if len(arrs) != self.planes:
      raise ValueError(f"expected {self.planes} plane(s), got {len(arrs)}")
    sig = ("global",) + tuple((a.shape, str(a.dtype)) for a in arrs)
    # the persistent cache (ISSUE 19) prefers the AOT route so a warm
    # worker skips the compile entirely; any failure (AOT executables
    # and global arrays interact badly across some versions) falls
    # through to the plain-jit path below, which stays the default when
    # no cache is configured
    if compile_cache.get_active() is not None:
      compiled = self._compiled.get(sig)
      try:
        if compiled is None:
          compiled = compile_cache.load_or_compile(
            self.name, sig, self.mesh,
            lambda: self._fn.lower(tuple(arrs)).compile(),
            variant=self.cache_variant + ("global",),
          )
          self._compiled[sig] = compiled
        with device_telemetry.execute_span(
          self.name, elements=device_telemetry.elements_of(arrs),
          mesh=self.mesh, **self.span_attrs,
        ):
          out = compiled(tuple(arrs))
          jax.block_until_ready(out)
        return out
      except Exception:
        from ..observability import metrics

        metrics.incr("device.compile_cache.error")
    # multihost default keeps the plain jit; first-call-per-signature
    # still ticks the recompile ledger and labels as compile
    fresh = device_telemetry.LEDGER.note_signature(self.name, sig)
    span = (
      device_telemetry.compile_span(
        self.name, device_telemetry._devices_of(self.mesh)
      ) if fresh else
      device_telemetry.execute_span(
        self.name, elements=device_telemetry.elements_of(arrs),
        mesh=self.mesh, **self.span_attrs,
      )
    )
    with span:
      out = self._fn(tuple(arrs))
      jax.block_until_ready(out)
    return out

  def __call__(self, batch):
    """batch: (K, c, z, y, x) array (planes=1) or a (lo, hi) tuple of such
    arrays (planes=2) → (per-mip outputs, global_nonzero). Per-mip outputs
    mirror the input arity: arrays, or (lo, hi) tuples."""
    arrs = batch if isinstance(batch, tuple) else (batch,)
    if len(arrs) != self.planes:
      raise ValueError(f"expected {self.planes} plane(s), got {len(arrs)}")
    padded = []
    k = arrs[0].shape[0]
    for a in arrs:
      p, _ = self.pad_batch(np.asarray(a))
      padded.append(p)
    real = sum(int(np.asarray(a).nbytes) for a in arrs)
    device_telemetry.LEDGER.record_pad_waste(
      padded_bytes=sum(int(p.nbytes) for p in padded) - real,
      real_bytes=real,
    )
    sharding = NamedSharding(self.mesh, P(self.axis))
    with device_telemetry.transfer_span(
      "h2d", sum(int(p.nbytes) for p in padded), kernel=self.name,
      mesh=self.mesh,
    ):
      xs = tuple(jax.device_put(p, sharding) for p in padded)
    sig = tuple((a.shape, str(a.dtype)) for a in xs)
    if sig not in self._compiled:
      self._compiled[sig] = compile_cache.load_or_compile(
        self.name, sig, self.mesh,
        lambda: self._fn.lower(xs).compile(),
        variant=self.cache_variant,
      )
    with device_telemetry.execute_span(
      self.name, elements=sum(int(p.size) for p in padded),
      nbytes=sum(int(p.nbytes) for p in padded), mesh=self.mesh,
      **self.span_attrs,
    ):
      outs, nonzero = self._compiled[sig](xs)
      jax.block_until_ready((outs, nonzero))
    with device_telemetry.transfer_span(
      "d2h", device_telemetry.nbytes_of(outs), kernel=self.name,
      mesh=self.mesh,
    ):
      return self._finish_call(outs, nonzero, k)

  def _finish_call(self, outs, nonzero, k):
    if self.planes == 2:
      result = [
        (np.asarray(ol)[:k], np.asarray(oh)[:k]) for ol, oh in outs
      ]
    else:
      result = [np.asarray(o)[:k] for o in outs]
    return result, int(nonzero)
