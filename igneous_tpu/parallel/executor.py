"""Batched, mesh-sharded execution of per-chunk kernels.

Chunks are this domain's batch dimension. A host leases K grid tasks,
stacks their equally-shaped cutouts into a (K, c, z, y, x) array, and runs
the pooling pyramid once, shard_map-ed over the mesh's "chunks" axis so
each TPU core processes K/n chunks. Collectives (psum over ICI) aggregate
scalar statistics (voxel counts, histograms) without host round-trips.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.pooling import _pyramid_impl


def make_mesh(n_devices: Optional[int] = None, axis: str = "chunks") -> Mesh:
  devices = jax.devices()
  if n_devices is not None:
    devices = devices[:n_devices]
  return Mesh(np.asarray(devices), (axis,))


class ChunkExecutor:
  """Compiles and runs batched chunk pyramids over a device mesh.

  One instance per (factors, method, sparse, chunk shape, dtype) — the
  compiled program is cached by XLA across calls.
  """

  def __init__(
    self,
    mesh: Optional[Mesh] = None,
    factors: Sequence[Tuple[int, int, int]] = ((2, 2, 1),),
    method: str = "average",
    sparse: bool = False,
  ):
    self.mesh = mesh if mesh is not None else make_mesh()
    self.factors = tuple(tuple(int(v) for v in f) for f in factors)
    self.method = method
    self.sparse = sparse
    self.axis = self.mesh.axis_names[0]
    self._fn = self._build()

  def _build(self):
    factors, method, sparse = self.factors, self.method, self.sparse
    axis = self.axis

    def per_shard(x):  # x: (k, c, z, y, x) local shard
      outs = jax.vmap(lambda a: _pyramid_impl(a, factors, method, sparse))(x)
      # voxel count psum: a cross-chip collective over ICI so callers get
      # a global nonzero tally with no host gather
      nonzero = jax.lax.psum(
        jnp.sum(x != 0, dtype=jnp.int32), axis_name=axis
      )
      return outs, nonzero

    in_spec = P(self.axis)
    out_spec = (tuple(P(self.axis) for _ in factors), P())
    fn = jax.shard_map(
      per_shard, mesh=self.mesh, in_specs=(in_spec,), out_specs=out_spec
    )
    return jax.jit(fn)

  @property
  def n_devices(self) -> int:
    return int(np.prod(self.mesh.devices.shape))

  def pad_batch(self, batch: np.ndarray) -> Tuple[np.ndarray, int]:
    """Pad the chunk axis to a multiple of the mesh size."""
    k = batch.shape[0]
    rem = (-k) % self.n_devices
    if rem:
      batch = np.concatenate([batch, np.zeros((rem,) + batch.shape[1:], batch.dtype)])
    return batch, k

  def __call__(self, batch: np.ndarray):
    """batch: (K, c, z, y, x) → (list of (K, …) mip arrays, global_nonzero)."""
    padded, k = self.pad_batch(np.asarray(batch))
    sharding = NamedSharding(self.mesh, P(self.axis))
    x = jax.device_put(padded, sharding)
    outs, nonzero = self._fn(x)
    return [np.asarray(o)[:k] for o in outs], int(nonzero)
