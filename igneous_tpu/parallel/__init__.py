"""Device-mesh parallel execution of chunk batches.

The reference scales one host by running N worker *processes*
(LocalTaskQueue(parallel=N), /root/reference/igneous_cli/cli.py:915-933).
The TPU-native equivalent (SURVEY.md §5.8): one host leases many tasks,
batches their cutouts, and runs ONE device program shard_map-ed across the
chip mesh over ICI — spatial-grid data parallelism mapped onto the "data"
axis of a jax.sharding.Mesh.
"""

from .executor import ChunkExecutor, make_mesh
from .batch_runner import batched_downsample
from . import multihost
