"""s3:// storage backend speaking the real S3 REST API (VERDICT r3 #7).

Implements the _FileBackend interface (storage.py) over HTTP with
stdlib-only transport and from-scratch AWS Signature Version 4 signing
(hmac/hashlib): GET (with Range), PUT, MULTIPART upload
(CreateMultipartUpload / UploadPart / CompleteMultipartUpload), HEAD
stat, DELETE, and ListObjectsV2 with continuation-token pagination — the
operation set the reference's data plane uses via cloud-files
(SURVEY.md §2.2).

Credentials, in order of precedence: ``AWS_ACCESS_KEY_ID`` /
``AWS_SECRET_ACCESS_KEY`` env vars, then the CloudVolume-style secret
file ``aws-secret.json`` in ``secrets.secrets_dir()``. Without
credentials the client runs unsigned (public buckets / emulators).
Endpoint: ``S3_ENDPOINT_URL`` / ``AWS_ENDPOINT_URL`` (path-style, the
emulator convention) or the regional AWS URL.

Zero-egress note: the real endpoint is unreachable in this image; the
client is exercised end-to-end against the in-process fake server in
tests/fake_cloud_servers.py (which verifies the SigV4 envelope).
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import json
import os
import re
import urllib.parse
from typing import Iterator, List, Optional, Tuple

from . import secrets
from .retry import default_policy
from .storage_http import HttpError, request

from .analysis import knobs

# env-tunable, read per call so tests exercise multipart with small payloads
def _multipart_threshold() -> int:
  return knobs.get_int("IGNEOUS_S3_MULTIPART_THRESHOLD")


def _multipart_chunk() -> int:
  return knobs.get_int("IGNEOUS_S3_MULTIPART_CHUNK")


def _load_creds() -> Tuple[Optional[str], Optional[str]]:
  akey = os.environ.get("AWS_ACCESS_KEY_ID")
  skey = os.environ.get("AWS_SECRET_ACCESS_KEY")
  if akey and skey:
    return akey, skey
  path = os.path.join(secrets.secrets_dir(), "aws-secret.json")
  if os.path.exists(path):
    with open(path) as f:
      blob = json.load(f)
    return (
      blob.get("AWS_ACCESS_KEY_ID") or blob.get("access_key_id"),
      blob.get("AWS_SECRET_ACCESS_KEY") or blob.get("secret_access_key"),
    )
  return None, None


class SigV4:
  """AWS Signature Version 4 over stdlib hmac/hashlib."""

  def __init__(self, access_key: str, secret_key: str, region: str,
               service: str = "s3"):
    self.access_key = access_key
    self.secret_key = secret_key
    self.region = region
    self.service = service

  def _signature(
    self,
    method: str,
    path: str,
    query: str,
    signed: dict,
    payload_hash: str,
    amz_date: str,
    datestamp: str,
  ) -> Tuple[str, str]:
    """Core SigV4 math over an EXACT header set; shared by sign() and
    verify() so server-side verification recomputes the same canonical
    request from wire-observed values."""
    canonical_query = "&".join(
      sorted(
        f"{urllib.parse.quote(k, safe='')}={urllib.parse.quote(v, safe='')}"
        for k, v in urllib.parse.parse_qsl(query, keep_blank_values=True)
      )
    )
    signed_names = sorted(h.lower() for h in signed)
    canonical_headers = "".join(
      f"{name}:{str(signed[next(h for h in signed if h.lower() == name)]).strip()}\n"
      for name in signed_names
    )
    signed_headers = ";".join(signed_names)
    # S3 canonical URI = the path exactly as sent on the wire (already
    # percent-encoded once by _url); re-quoting here would double-encode
    # and yield SignatureDoesNotMatch against real AWS
    canonical_request = "\n".join([
      method, path or "/", canonical_query,
      canonical_headers, signed_headers, payload_hash,
    ])
    scope = f"{datestamp}/{self.region}/{self.service}/aws4_request"
    string_to_sign = "\n".join([
      "AWS4-HMAC-SHA256", amz_date, scope,
      hashlib.sha256(canonical_request.encode()).hexdigest(),
    ])

    def _hmac(key: bytes, msg: str) -> bytes:
      return hmac.new(key, msg.encode(), hashlib.sha256).digest()

    k = _hmac(f"AWS4{self.secret_key}".encode(), datestamp)
    k = _hmac(k, self.region)
    k = _hmac(k, self.service)
    k = _hmac(k, "aws4_request")
    signature = hmac.new(
      k, string_to_sign.encode(), hashlib.sha256
    ).hexdigest()
    return signature, signed_headers

  def sign(self, method: str, url: str, headers: dict, payload: bytes) -> dict:
    parsed = urllib.parse.urlsplit(url)
    now = datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    datestamp = now.strftime("%Y%m%d")
    payload_hash = hashlib.sha256(payload or b"").hexdigest()

    headers = dict(headers)
    headers["Host"] = parsed.netloc
    headers["x-amz-date"] = amz_date
    headers["x-amz-content-sha256"] = payload_hash

    signature, signed_headers = self._signature(
      method, parsed.path, parsed.query, headers, payload_hash,
      amz_date, datestamp,
    )
    scope = f"{datestamp}/{self.region}/{self.service}/aws4_request"
    headers["Authorization"] = (
      f"AWS4-HMAC-SHA256 Credential={self.access_key}/{scope}, "
      f"SignedHeaders={signed_headers}, Signature={signature}"
    )
    del headers["Host"]  # urllib sets it; keeping both would desync
    return headers

  def verify(
    self, method: str, path: str, query: str, wire_headers, payload: bytes
  ) -> bool:
    """Server-side check: recompute the signature from the wire-observed
    request (used by the fake S3 server so canonicalization drift between
    signing and sending fails tests, not production)."""
    auth = wire_headers.get("Authorization", "")
    m = re.match(
      r"AWS4-HMAC-SHA256 Credential=([^/]+)/(\d{8})/([^/]+)/([^/]+)/"
      r"aws4_request, SignedHeaders=([a-z0-9;-]+), Signature=([0-9a-f]{64})",
      auth,
    )
    if not m:
      return False
    _access, datestamp, _region, _svc, signed_names, signature = m.groups()
    signed = {}
    for name in signed_names.split(";"):
      val = wire_headers.get(name)
      if val is None:
        return False
      signed[name] = val
    payload_hash = hashlib.sha256(payload or b"").hexdigest()
    if signed.get("x-amz-content-sha256") not in (payload_hash, None):
      return False
    expect, _ = self._signature(
      method, path, query, signed, payload_hash,
      signed.get("x-amz-date", ""), datestamp,
    )
    return hmac.compare_digest(expect, signature)


class S3Backend:
  """Real s3://bucket/prefix client (storage.py _FileBackend interface).
  Path-style addressing so emulator endpoints work unchanged."""

  def __init__(self, path: str):
    bucket, _, prefix = path.partition("/")
    self.bucket = bucket
    self.prefix = prefix.strip("/")
    self.region = os.environ.get("AWS_DEFAULT_REGION", "us-east-1")
    self.endpoint = (
      os.environ.get("S3_ENDPOINT_URL")
      or os.environ.get("AWS_ENDPOINT_URL")
      or f"https://s3.{self.region}.amazonaws.com"
    ).rstrip("/")
    if "://" not in self.endpoint:
      self.endpoint = "http://" + self.endpoint
    akey, skey = _load_creds()
    self.signer = (
      SigV4(akey, skey, self.region) if akey and skey else None
    )
    # unified retry schedule (retry.RetryPolicy): shared with every other
    # network seam so backoff behavior can't drift per backend
    self.retry = default_policy()

  # -- helpers --------------------------------------------------------------

  def _name(self, key: str) -> str:
    return f"{self.prefix}/{key}" if self.prefix else key

  def _url(self, key: str, query: str = "") -> str:
    path = urllib.parse.quote(f"/{self.bucket}/{self._name(key)}")
    return f"{self.endpoint}{path}" + (f"?{query}" if query else "")

  def _request(self, method, url, headers=None, data=None):
    headers = dict(headers or {})
    if self.signer is not None:
      headers = self.signer.sign(method, url, headers, data or b"")
    return request(method, url, headers=headers, data=data, policy=self.retry)

  # -- interface ------------------------------------------------------------

  def put(self, key: str, data: bytes):
    if len(data) >= _multipart_threshold():
      return self._put_multipart(key, data)
    status, _h, body = self._request("PUT", self._url(key), data=data)
    if status != 200:
      raise HttpError(status, self._url(key), body)

  def _put_multipart(self, key: str, data: bytes):
    url = self._url(key, "uploads")
    status, _h, body = self._request("POST", url, data=b"")
    if status != 200:
      raise HttpError(status, url, body)
    m = re.search(rb"<UploadId>([^<]+)</UploadId>", body)
    if not m:
      raise HttpError(status, url, b"no UploadId in response")
    upload_id = m.group(1).decode()
    etags: List[Tuple[int, str]] = []
    part = 1
    step = _multipart_chunk()
    for start in range(0, len(data), step):
      chunk = data[start : start + step]
      purl = self._url(
        key, f"partNumber={part}&uploadId={urllib.parse.quote(upload_id)}"
      )
      status, hdrs, body = self._request("PUT", purl, data=chunk)
      if status != 200:
        self._request(  # abort so the store reclaims parts
          "DELETE", self._url(key, f"uploadId={urllib.parse.quote(upload_id)}")
        )
        raise HttpError(status, purl, body)
      etags.append((part, hdrs.get("ETag") or hdrs.get("etag") or ""))
      part += 1
    complete = "".join(
      f"<Part><PartNumber>{n}</PartNumber><ETag>{etag}</ETag></Part>"
      for n, etag in etags
    )
    xml = (
      "<CompleteMultipartUpload>" + complete + "</CompleteMultipartUpload>"
    ).encode()
    curl = self._url(key, f"uploadId={urllib.parse.quote(upload_id)}")
    status, _h, body = self._request("POST", curl, data=xml)
    # real S3 can answer CompleteMultipartUpload with 200 OK + an <Error>
    # XML body when assembly fails server-side; treating that as success
    # would silently drop the object
    if status != 200 or b"<Error>" in body:
      raise HttpError(status, curl, body)

  def get(self, key: str) -> Optional[bytes]:
    status, _h, body = self._request("GET", self._url(key))
    return None if status == 404 else body

  def get_range(self, key: str, start: int, length: int) -> Optional[bytes]:
    status, _h, body = self._request(
      "GET", self._url(key),
      headers={"Range": f"bytes={start}-{start + length - 1}"},
    )
    if status == 404:
      return None
    if status == 416:
      return b""
    return body

  def exists(self, key: str) -> bool:
    status, _h, _b = self._request("HEAD", self._url(key))
    return status == 200

  def delete(self, key: str):
    self._request("DELETE", self._url(key))

  def size(self, key: str) -> Optional[int]:
    status, hdrs, _b = self._request("HEAD", self._url(key))
    if status != 200:
      return None
    cl = hdrs.get("Content-Length") or hdrs.get("content-length")
    return int(cl) if cl is not None else None

  def list(self, prefix: str = "") -> Iterator[str]:
    from xml.sax.saxutils import unescape as xml_unescape

    token = None
    full_prefix = self._name(prefix)
    strip = len(self.prefix) + 1 if self.prefix else 0
    while True:
      # encoding-type=url: keys arrive percent-encoded, so the XML layer
      # never has to escape them and unquote() is the exact inverse —
      # without it, a literal '%' in a key would be corrupted on decode
      query = (
        "encoding-type=url&list-type=2&prefix="
        + urllib.parse.quote(full_prefix, safe="")
      )
      if token:
        query += "&continuation-token=" + urllib.parse.quote(token, safe="")
      url = f"{self.endpoint}{urllib.parse.quote(f'/{self.bucket}')}?{query}"
      status, _h, body = self._request("GET", url)
      if status != 200:
        raise HttpError(status, url, body)
      for m in re.finditer(rb"<Key>([^<]*)</Key>", body):
        name = urllib.parse.unquote(xml_unescape(m.group(1).decode()))
        yield name[strip:]
      trunc = re.search(rb"<IsTruncated>true</IsTruncated>", body)
      nxt = re.search(
        rb"<NextContinuationToken>([^<]+)</NextContinuationToken>", body
      )
      if not trunc or not nxt:
        return
      token = xml_unescape(nxt.group(1).decode())
