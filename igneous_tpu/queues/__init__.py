"""Task-queue orchestration layer (control plane).

Capability parity with the reference's external ``python-task-queue``
dependency (/root/reference/igneous_cli/cli.py:69-78,935-964 and
igneous/__init__.py:2): JSON-serializable tasks, ``LocalTaskQueue`` for
in-process/multi-process execution, a lease-based filesystem queue
(``fq://``) for cluster horizontal scaling, and an ``sqs://`` binding over
a pluggable transport (boto3 in deployments; an in-process fake with
faithful visibility semantics for tests).
"""

from .registry import (
  FN_REGISTRY,
  TASK_REGISTRY,
  FunctionTask,
  PrintTask,
  RegisteredTask,
  deserialize,
  queueable,
  serialize,
  totask,
)
from .local import LocalTaskQueue, MockTaskQueue
from .filequeue import FileQueue, StaleLeaseError, TaskDeadlineError
from .heartbeat import LeaseHeartbeat
from .ranges import RangeLease, RangeSub
from .queue import TaskQueue, copy_queue, move_queue, register_queue_protocol
from .sqs import FakeSQSTransport, SQSQueue

register_queue_protocol("sqs", SQSQueue)
