"""``sqs://`` queue binding — the reference's cluster control plane.

Behavioral parity target: python-task-queue's SQS mode as igneous uses it
(/root/reference/igneous_cli/cli.py:935-964, env config
/root/reference/igneous/secrets.py:13-16): at-least-once delivery with a
visibility timeout, lease release via visibility reset, approximate
counts, and the 120-second empty double-confirmation before trusting an
empty queue (/root/reference/igneous_cli/cli.py:854-886 — SQS counts are
eventually consistent, so a single zero sample is not evidence).

The AWS wire protocol is behind a pluggable *transport*: the default is
boto3 (absent in this zero-egress image, so constructing it raises with
instructions), and ``FakeSQSTransport`` is an in-process transport with
faithful visibility semantics — receipt handles invalidated on
redelivery, approximate visible/in-flight counts — so every seam of this
binding is exercised by tests rather than trusted on faith.
"""

from __future__ import annotations

import functools
import time
import uuid
from typing import Iterable, Optional, Tuple

from .filequeue import StaleLeaseError, iter_tasks, poll_loop
from .registry import RegisteredTask, deserialize, serialize

EMPTY_CONFIRMATION_SEC = 120.0  # reference cli.py:858-861
EMPTY_SAMPLES = 3
SQS_BATCH = 10  # hard AWS cap on entries per *Batch API call


class FakeSQSTransport:
  """In-process transport with SQS visibility-timeout semantics.

  ``time_fn`` is injectable so tests can step time instead of sleeping.
  """

  def __init__(self, time_fn=time.monotonic):
    self._now = time_fn
    self._messages = {}     # id -> body
    self._visible_at = {}   # id -> timestamp
    self._receipt = {}      # id -> current receipt handle
    self._by_receipt = {}   # receipt -> id
    self._receive_count = {}  # id -> deliveries (ApproximateReceiveCount)

  def send_message(self, body: str) -> str:
    mid = uuid.uuid4().hex
    self._messages[mid] = body
    self._visible_at[mid] = self._now()
    self._receive_count[mid] = 0
    return mid

  def receive_message(
    self, visibility_timeout: float
  ) -> Optional[Tuple[str, str, dict]]:
    now = self._now()
    for mid, vis in self._visible_at.items():
      if vis <= now:
        # redelivery invalidates any prior receipt (SQS behavior)
        old = self._receipt.pop(mid, None)
        if old is not None:
          self._by_receipt.pop(old, None)
        receipt = uuid.uuid4().hex
        self._receipt[mid] = receipt
        self._by_receipt[receipt] = mid
        self._visible_at[mid] = now + visibility_timeout
        self._receive_count[mid] = self._receive_count.get(mid, 0) + 1
        attrs = {
          "ApproximateReceiveCount": str(self._receive_count[mid])
        }
        return self._messages[mid], receipt, attrs
    return None

  def delete_message(self, receipt: str) -> bool:
    mid = self._by_receipt.pop(receipt, None)
    if mid is None:
      return False  # stale receipt: message was redelivered elsewhere
    self._messages.pop(mid, None)
    self._visible_at.pop(mid, None)
    self._receipt.pop(mid, None)
    self._receive_count.pop(mid, None)
    return True

  def change_visibility(self, receipt: str, timeout: float) -> bool:
    mid = self._by_receipt.get(receipt)
    if mid is None or mid not in self._messages:
      return False
    self._visible_at[mid] = self._now() + timeout
    return True

  # -- batch entry points (same shapes the boto3 transport exposes) ---------

  def send_message_batch(self, bodies) -> list:
    return [self.send_message(b) for b in bodies]

  def receive_messages(self, max_messages: int, visibility_timeout: float):
    out = []
    for _ in range(int(max_messages)):
      got = self.receive_message(visibility_timeout)
      if got is None:
        break
      out.append(got)
    return out

  def delete_message_batch(self, receipts) -> list:
    return [self.delete_message(r) for r in receipts]

  def change_visibility_batch(self, receipts, timeout: float) -> list:
    return [self.change_visibility(r, timeout) for r in receipts]

  def approximate_counts(self) -> Tuple[int, int]:
    now = self._now()
    visible = sum(1 for v in self._visible_at.values() if v <= now)
    return visible, len(self._messages) - visible

  def purge(self):
    self._messages.clear()
    self._visible_at.clear()
    self._receipt.clear()
    self._by_receipt.clear()
    self._receive_count.clear()


def _boto3_transport(spec: str):
  try:
    import boto3  # noqa: F401
  except ImportError as e:
    raise RuntimeError(
      "sqs:// needs the boto3 transport, which this environment does not "
      "ship. Install boto3 (and AWS credentials via SQS_REGION_NAME / "
      "SQS_ENDPOINT_URL, igneous_tpu.secrets), or pass "
      "SQSQueue(spec, transport=...) — e.g. FakeSQSTransport for tests."
    ) from e
  from .. import secrets

  sqs = boto3.client(
    "sqs", region_name=secrets.sqs_region_name(),
    endpoint_url=secrets.sqs_endpoint_url() or None,
  )
  url = spec[len("sqs://"):]

  class Boto3Transport:
    def send_message(self, body):
      return sqs.send_message(QueueUrl=url, MessageBody=body)["MessageId"]

    def receive_message(self, visibility_timeout):
      resp = sqs.receive_message(
        QueueUrl=url, MaxNumberOfMessages=1,
        VisibilityTimeout=int(visibility_timeout), WaitTimeSeconds=1,
        AttributeNames=["ApproximateReceiveCount"],
      )
      msgs = resp.get("Messages", [])
      if not msgs:
        return None
      return (
        msgs[0]["Body"], msgs[0]["ReceiptHandle"],
        msgs[0].get("Attributes", {}),
      )

    def delete_message(self, receipt):
      # stale receipt (task outlived its visibility timeout and was
      # redelivered): report False like the fake, don't crash the worker
      try:
        sqs.delete_message(QueueUrl=url, ReceiptHandle=receipt)
      except Exception as e:
        code = getattr(e, "response", {}).get("Error", {}).get("Code", "")
        if code in ("ReceiptHandleIsInvalid", "InvalidParameterValue"):
          return False
        raise
      return True

    def change_visibility(self, receipt, timeout):
      sqs.change_message_visibility(
        QueueUrl=url, ReceiptHandle=receipt, VisibilityTimeout=int(timeout)
      )
      return True

    # -- batched wire protocol (ISSUE 15): one API call per <= 10 entries.
    # Each *Batch response splits into Successful/Failed; Failed entries
    # get ONE retry (SQS batch failures are routinely partial/transient)
    # before erroring (sends) or reporting False (deletes/visibility).

    def send_message_batch(self, bodies):
      bodies = list(bodies)
      out = []
      for i in range(0, len(bodies), SQS_BATCH):
        chunk = bodies[i:i + SQS_BATCH]
        entries = [
          {"Id": str(j), "MessageBody": b} for j, b in enumerate(chunk)
        ]
        resp = sqs.send_message_batch(QueueUrl=url, Entries=entries)
        got = {e["Id"]: e["MessageId"] for e in resp.get("Successful", [])}
        failed = [e["Id"] for e in resp.get("Failed", [])]
        if failed:
          resp = sqs.send_message_batch(QueueUrl=url, Entries=[
            {"Id": fid, "MessageBody": chunk[int(fid)]} for fid in failed
          ])
          got.update(
            {e["Id"]: e["MessageId"] for e in resp.get("Successful", [])}
          )
          still = [e["Id"] for e in resp.get("Failed", [])]
          if still:
            raise RuntimeError(
              f"SendMessageBatch: {len(still)} entries failed after retry"
            )
        out.extend(got[str(j)] for j in range(len(chunk)))
      return out

    def receive_messages(self, max_messages, visibility_timeout):
      resp = sqs.receive_message(
        QueueUrl=url,
        MaxNumberOfMessages=max(1, min(int(max_messages), SQS_BATCH)),
        VisibilityTimeout=int(visibility_timeout), WaitTimeSeconds=1,
        AttributeNames=["ApproximateReceiveCount"],
      )
      return [
        (m["Body"], m["ReceiptHandle"], m.get("Attributes", {}))
        for m in resp.get("Messages", [])
      ]

    def _receipt_batch(self, api, receipts, extra):
      receipts = list(receipts)
      ok = [False] * len(receipts)
      for i in range(0, len(receipts), SQS_BATCH):
        chunk = receipts[i:i + SQS_BATCH]
        entries = [
          {"Id": str(j), "ReceiptHandle": r, **extra}
          for j, r in enumerate(chunk)
        ]
        resp = api(QueueUrl=url, Entries=entries)
        failed = [e["Id"] for e in resp.get("Failed", [])]
        if failed:
          resp = api(QueueUrl=url, Entries=[
            {"Id": fid, "ReceiptHandle": chunk[int(fid)], **extra}
            for fid in failed
          ])
          failed = [e["Id"] for e in resp.get("Failed", [])]
        bad = {int(fid) for fid in failed}
        for j in range(len(chunk)):
          ok[i + j] = j not in bad
      return ok

    def delete_message_batch(self, receipts):
      return self._receipt_batch(sqs.delete_message_batch, receipts, {})

    def change_visibility_batch(self, receipts, timeout):
      return self._receipt_batch(
        sqs.change_message_visibility_batch, receipts,
        {"VisibilityTimeout": int(timeout)},
      )

    def approximate_counts(self):
      attrs = sqs.get_queue_attributes(
        QueueUrl=url,
        AttributeNames=[
          "ApproximateNumberOfMessages",
          "ApproximateNumberOfMessagesNotVisible",
        ],
      )["Attributes"]
      return (
        int(attrs["ApproximateNumberOfMessages"]),
        int(attrs["ApproximateNumberOfMessagesNotVisible"]),
      )

    def purge(self):
      sqs.purge_queue(QueueUrl=url)

  return Boto3Transport()


class SQSQueue:
  """Queue facade over an SQS(-shaped) transport.

  Same surface as FileQueue where the backend permits: insert / lease /
  delete / release / poll / purge / is_empty / enqueued / leased.
  Tallies (inserted/completed) are per-process — SQS keeps no global
  counters, so cross-worker totals need CloudWatch, not this client.
  """

  def __init__(
    self, spec: str, transport=None,
    empty_confirmation_sec: float = EMPTY_CONFIRMATION_SEC,
    sleep_fn=time.sleep,
    max_deliveries: Optional[int] = None,
    dlq=None,
  ):
    """``max_deliveries``/``dlq``: client-side mirror of SQS redrive —
    a message received more than ``max_deliveries`` times routes to
    ``dlq`` (any queue-like with .insert(), e.g. another SQSQueue or a
    FileQueue) instead of being delivered. With ``dlq=None`` quarantined
    bodies accumulate in ``self.dead_letters`` (per-process). Production
    deployments should prefer a server-side RedrivePolicy; this mirror
    gives the shared poll loop identical semantics on the fake."""
    self.spec = spec
    self.transport = transport or _boto3_transport(spec)
    self.empty_confirmation_sec = float(empty_confirmation_sec)
    self._sleep = sleep_fn
    self._inserted = 0
    self._completed = 0
    self.max_deliveries = (
      None if not max_deliveries or int(max_deliveries) <= 0
      else int(max_deliveries)
    )
    self.dlq = dlq
    self.dead_letters: list = []
    self.last_receive_count: int = 0
    # reasons key on the message BODY (stable across redeliveries —
    # receipts rotate every receive, so they cannot carry attribution
    # from the failing delivery to the promoting one)
    self._failure_reasons: dict = {}  # body -> last recorded reason
    self._receipt_body: dict = {}     # live receipt -> body

  # -- counters -------------------------------------------------------------

  @property
  def inserted(self) -> int:
    return self._inserted

  @property
  def completed(self) -> int:
    return self._completed

  @property
  def enqueued(self) -> int:
    visible, in_flight = self.transport.approximate_counts()
    return visible + in_flight

  @property
  def leased(self) -> int:
    return self.transport.approximate_counts()[1]

  @property
  def backlog(self) -> int:
    """Work remaining (visible + in flight) — the autoscaler's demand
    signal (ISSUE 6). Approximate, like every SQS count."""
    return self.enqueued

  def depth_snapshot(self) -> dict:
    visible, in_flight = self.transport.approximate_counts()
    return {
      "inserted": self.inserted,
      "enqueued": visible + in_flight,
      "leased": in_flight,
      "completed": self.completed,
      "backlog": visible + in_flight,
    }

  def __len__(self) -> int:
    return self.enqueued

  # -- queue ops ------------------------------------------------------------

  def insert(self, tasks: Iterable, total=None):
    del total
    n = 0
    for task in iter_tasks(tasks):
      body = task if isinstance(task, str) else serialize(task)
      self.transport.send_message(body)
      n += 1
    self._inserted += n
    return n

  def insert_batch(self, tasks: Iterable, total=None):
    """Batched enqueue: SendMessageBatch at the 10-entry API cap — one
    wire round-trip per 10 tasks instead of per task. Transports without
    a batch entry point fall back to per-task sends."""
    del total
    send_batch = getattr(self.transport, "send_message_batch", None)
    if send_batch is None:
      return self.insert(tasks)
    n = 0
    chunk = []
    for task in iter_tasks(tasks):
      chunk.append(task if isinstance(task, str) else serialize(task))
      if len(chunk) >= SQS_BATCH:
        send_batch(chunk)
        n += len(chunk)
        chunk = []
    if chunk:
      send_batch(chunk)
      n += len(chunk)
    self._inserted += n
    return n

  def _admit(self, got):
    """Shared receive gate: route exhausted redeliveries to the DLQ,
    register the receipt->body mapping, deserialize. None = promoted."""
    body, receipt = got[0], got[1]
    attrs = got[2] if len(got) > 2 else {}
    count = int(attrs.get("ApproximateReceiveCount", 0) or 0)
    self.last_receive_count = count
    if self.max_deliveries is not None and count > self.max_deliveries:
      # redelivery budget exhausted BEFORE this delivery: quarantine
      # instead of handing a poison task to yet another worker
      self._promote_to_dlq(body, receipt, count)
      return None
    self._receipt_body[receipt] = body
    return deserialize(body), receipt

  def lease(self, seconds: float = 600):
    while True:
      got = self.transport.receive_message(seconds)
      if got is None:
        return None
      admitted = self._admit(got)
      if admitted is not None:
        return admitted

  def lease_batch(self, seconds: float = 600, max_tasks: int = 1):
    """Lease up to ``max_tasks`` in ReceiveMessage batches of 10.
    Returns a list of (task, receipt) pairs — [] when drained."""
    recv = getattr(self.transport, "receive_messages", None)
    out = []
    while len(out) < max_tasks:
      want = max_tasks - len(out)
      if recv is not None:
        batch = recv(min(want, SQS_BATCH), seconds)
      else:
        got = self.transport.receive_message(seconds)
        batch = [] if got is None else [got]
      if not batch:
        break
      for got in batch:
        admitted = self._admit(got)
        if admitted is not None:
          out.append(admitted)
    return out

  def ack_batch(self, tokens):
    """Complete many tasks via DeleteMessageBatch. Results align with
    ``tokens``; False = stale receipt (zombie-fenced, not a completion)."""
    from .. import telemetry

    tokens = list(tokens)
    del_batch = getattr(self.transport, "delete_message_batch", None)
    if del_batch is None:
      return [self.delete(t) for t in tokens]
    for t in tokens:
      body = self._receipt_body.pop(t, None)
      if body is not None:
        self._failure_reasons.pop(body, None)
    results = [bool(r) for r in del_batch(tokens)]
    ok = sum(results)
    self._completed += ok
    if ok < len(results):
      telemetry.incr("zombie.delete", len(results) - ok)
    return results

  def nack_batch(self, tokens, reason: str = "", requeue: bool = False):
    """Record many failed deliveries; with ``requeue=True`` the messages
    return to visibility via ChangeMessageVisibilityBatch(0)."""
    tokens = list(tokens)
    for t in tokens:
      body = self._receipt_body.pop(t, None)
      if body is not None:
        self._failure_reasons[body] = str(reason)[:2000]
    if requeue:
      cvb = getattr(self.transport, "change_visibility_batch", None)
      if cvb is None:
        for t in tokens:
          self.release(t)
      else:
        cvb(tokens, 0)

  def _promote_to_dlq(self, body: str, receipt: str, count: int):
    from .. import telemetry

    if self.dlq is not None:
      self.dlq.insert(body)
    else:
      self.dead_letters.append({
        "payload": body,
        "deliveries": count,
        "error": self._failure_reasons.pop(body, ""),
      })
    self.transport.delete_message(receipt)
    telemetry.incr("dlq.promoted")

  def renew(self, lease_id: str, seconds: float = 600) -> str:
    """Extend the visibility timeout (ChangeMessageVisibility). The
    receipt handle stays valid across renewals, so the token is returned
    unchanged. A stale receipt — the message was redelivered elsewhere
    while this worker stalled — raises StaleLeaseError (``zombie.renew``),
    matching the fq:// fencing contract."""
    from .. import telemetry

    if not self.transport.change_visibility(lease_id, seconds):
      telemetry.incr("zombie.renew")
      raise StaleLeaseError(
        "receipt no longer owns its message (redelivered after the "
        "visibility timeout)"
      )
    return lease_id

  def delete(self, lease_id: str) -> bool:
    body = self._receipt_body.pop(lease_id, None)
    if body is not None:
      self._failure_reasons.pop(body, None)
    if self.transport.delete_message(lease_id):
      self._completed += 1
      return True
    # stale receipt: the task outlived its visibility and was re-issued;
    # this worker's late ack must not count as a completion
    from .. import telemetry

    telemetry.incr("zombie.delete")
    return False

  def nack(self, lease_id: str, reason: str = "", requeue: bool = False):
    """Record a failed delivery. SQS keeps no per-message metadata, so
    the reason lives client-side (telemetry + last-reason map, keyed by
    message body); the visibility timeout (or ``requeue=True``) drives
    redelivery, and the receive-count check in lease() drives DLQ
    promotion."""
    body = self._receipt_body.pop(lease_id, None)
    if body is not None:
      self._failure_reasons[body] = str(reason)[:2000]
    if requeue:
      self.release(lease_id)

  def release(self, lease_id: str):
    self.transport.change_visibility(lease_id, 0)

  def release_all(self):
    raise NotImplementedError(
      "SQS cannot enumerate in-flight receipts; leases recycle on their "
      "visibility timeout (or drop them per-worker with release())."
    )

  def purge(self):
    self.transport.purge()

  def rezero(self):
    self._inserted = 0
    self._completed = 0

  def is_empty(self) -> bool:
    """Empty only after sustained zero counts across the confirmation
    window — SQS counts are approximate/eventually consistent
    (reference cli.py:854-886)."""
    # N samples span (N-1) intervals: dividing by N would shrink the
    # sustained-zero span below the documented window
    interval = self.empty_confirmation_sec / max(EMPTY_SAMPLES - 1, 1)
    for i in range(EMPTY_SAMPLES):
      visible, in_flight = self.transport.approximate_counts()
      if visible + in_flight > 0:
        return False
      if i < EMPTY_SAMPLES - 1:
        self._sleep(interval)
    return True

  def poll(
    self,
    lease_seconds: float = 600,
    verbose: bool = False,
    tally: bool = True,
    stop_fn=None,
    max_backoff_window: float = 30.0,
    before_fn=None,
    after_fn=None,
    task_deadline_seconds: Optional[float] = None,
    heartbeat_seconds: Optional[float] = None,
    drain_flag=None,
  ):
    del tally
    return poll_loop(
      self, lease_seconds, verbose, stop_fn, max_backoff_window,
      before_fn, after_fn, task_deadline_seconds,
      heartbeat_seconds, drain_flag,
    )
