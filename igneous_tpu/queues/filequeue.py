"""Lease-based filesystem task queue (``fq://``).

Behavioral parity with the reference's FileQueue (python-task-queue,
described at /root/reference/README.md:69-81): at-least-once delivery with a
visibility timeout — a leased task that is not deleted within its lease
returns to the pool; workers pick a random task among the first 100 to
avoid lease contention; completions are tallied 1 byte per task.

All state is plain files, so any shared POSIX filesystem (NFS, /mnt
volumes) works as the control plane across machines.

Failure containment (ISSUE 1): each task carries persisted attempt
metadata (``meta/<name>``: delivery count + recent failure reasons).
With ``max_deliveries`` configured, a task that keeps failing — by
raising, overrunning its deadline, or losing its worker — moves to the
``dlq/`` sidecar instead of re-entering rotation, where ``igneous queue
dlq ls|retry|purge`` can inspect, requeue, or drop it. The default
(``max_deliveries=None``) preserves the historical infinite-retry
at-least-once semantics.
"""

from __future__ import annotations

import json
import os
import random
import time
import uuid
from typing import Iterable, List, Optional, Tuple

from .registry import RegisteredTask, deserialize, serialize

LEASE_SEP = "--"
CONTENTION_WINDOW = 100
MAX_RECORDED_FAILURES = 5  # per-task failure-reason ring (meta file bound)


class TaskDeadlineError(Exception):
  """A task overran its per-delivery wall-clock deadline (poll_loop)."""


class StaleLeaseError(Exception):
  """The lease behind a renew/delete no longer belongs to this worker —
  it expired, or the queue re-issued the task to someone else. A worker
  seeing this is a *zombie* for that task: it must stop acting on it
  (the work itself is safe to discard — tasks are idempotent and the
  current owner will complete it)."""


def iter_tasks(tasks):
  """Normalize an insert() argument to an iterator of single tasks.
  Strings/bytes/dicts are single payloads, not collections — shared by
  every queue backend so a payload-dict never gets iterated as keys."""
  if hasattr(tasks, "__iter__") and not isinstance(tasks, (str, bytes, dict)):
    return iter(tasks)
  return iter([tasks])


def failure_reason(exc: BaseException) -> str:
  """One-line failure record shared by every containment path (poll_loop,
  the lease batcher, LocalTaskQueue) so DLQ entries read uniformly."""
  msg = str(exc)
  return f"{type(exc).__name__}: {msg}" if msg else type(exc).__name__


def run_with_deadline(fn, deadline_seconds: Optional[float]):
  """Run ``fn()`` with a wall-clock deadline. On overrun, raises
  TaskDeadlineError so the caller's failure bookkeeping (nack → DLQ)
  takes over. The overrunning call keeps executing on an abandoned
  daemon thread — it cannot be killed safely — which is sound here
  because tasks are idempotent and the lease it held stays failed."""
  if not deadline_seconds or deadline_seconds <= 0:
    return fn()
  import threading

  result = {}

  def body():
    try:
      result["value"] = fn()
    except BaseException as e:  # noqa: BLE001 - relayed to the caller
      result["error"] = e

  t = threading.Thread(target=body, daemon=True)
  t.start()
  t.join(deadline_seconds)
  if t.is_alive():
    raise TaskDeadlineError(
      f"task exceeded its {deadline_seconds:.1f}s deadline"
    )
  if "error" in result:
    raise result["error"]
  return result.get("value")


def poll_loop(
  queue,
  lease_seconds: float = 600,
  verbose: bool = False,
  stop_fn=None,
  max_backoff_window: float = 30.0,
  before_fn=None,
  after_fn=None,
  task_deadline_seconds: Optional[float] = None,
  heartbeat_seconds: Optional[float] = None,
  drain_flag=None,
):
  """Shared worker loop: lease→execute→delete until stop_fn says stop or
  the queue drains (stop_fn=None polls forever, sleeping with bounded
  backoff when empty). Used by every queue backend (fq://, sqs://) so
  execution semantics — at-least-once, recycle-on-failure — are uniform.

  Failure containment: an exception (or ``task_deadline_seconds``
  overrun) records its reason with the task via ``queue.nack`` when the
  backend supports it — feeding the same bookkeeping that promotes
  repeat offenders to the DLQ — and otherwise leaves the lease to
  recycle on its visibility timeout, exactly as before.

  Lifecycle (ISSUE 2): a heartbeat thread renews the held lease every
  ``heartbeat_seconds`` (default lease/3, env IGNEOUS_HEARTBEAT_SEC;
  <= 0 disables) so long tasks outlive a short ``--lease-sec`` without
  being double-executed. ``drain_flag`` (anything with ``is_set()``,
  e.g. lifecycle.StopFlag) requests graceful shutdown: the in-flight
  task finishes, no new lease is taken."""
  from .. import telemetry
  from ..observability import journal as journal_mod
  from ..observability import trace
  from .heartbeat import LeaseHeartbeat

  def draining() -> bool:
    return drain_flag is not None and drain_flag.is_set()

  def attempt_of(lease_id) -> Optional[int]:
    # fq:// persists delivery counts; SQS reports ApproximateReceiveCount
    try:
      if hasattr(queue, "delivery_count"):
        return int(queue.delivery_count(lease_id))
      if getattr(queue, "last_receive_count", 0):
        return int(queue.last_receive_count)
    except Exception:
      pass
    return None

  def idle(seconds: float):
    # wake early when a drain request lands mid-backoff
    if drain_flag is not None and hasattr(drain_flag, "wait"):
      drain_flag.wait(seconds)
    else:
      time.sleep(seconds)

  backoff = 1.0
  executed = 0
  hb = LeaseHeartbeat(queue, lease_seconds, interval=heartbeat_seconds)
  try:
   with hb:
    while True:
      # interval/drain-requested journal flush between tasks: the poll
      # loop IS the worker's main thread, so batches land without a
      # dedicated flusher thread
      journal_mod.maybe_flush_active()
      if draining():
        return executed
      if stop_fn is not None and stop_fn(executed=executed, empty=False):
        return executed
      leased = queue.lease(lease_seconds)
      if leased is None:
        if stop_fn is not None and stop_fn(executed=executed, empty=True):
          return executed
        if draining():
          return executed
        idle(backoff + random.random())
        backoff = min(backoff * 2, max_backoff_window)
        continue
      backoff = 1.0
      task, lease_id = leased
      key = hb.track(lease_id)
      if verbose:
        print(f"Executing {task!r}")
      try:
        if before_fn:
          before_fn(task)
        # IGNEOUS_PIPELINE=1 opts the solo worker loop into tier-A
        # pipelining: the task's chunk encodes+puts thread on the shared
        # pool, joined before the lease delete below — completion
        # semantics are unchanged (execute_with_sink falls back to plain
        # execute() when the task has no stage plan or pipelining is off)
        from ..pipeline import execute_with_sink

        # the task span wraps this delivery: stage/storage spans on this
        # thread (and pool threads the upload ticket propagates to)
        # parent under it, attributed to the payload's trace
        with trace.task_span(
          task, attempt=attempt_of(lease_id), queue=type(queue).__name__
        ):
          run_with_deadline(
            lambda: execute_with_sink(task), task_deadline_seconds
          )
        if after_fn:
          after_fn(task)
      except Exception as e:
        # leave the lease in place: the task recycles after the timeout
        # (at-least-once semantics; matches reference behavior on failure).
        # nack records the reason and quarantines exhausted tasks.
        if verbose:
          import traceback

          traceback.print_exc()
        telemetry.incr("tasks.failed")
        current = hb.untrack(key)
        if hasattr(queue, "nack"):
          queue.nack(current, failure_reason(e))
        continue
      # untrack returns the CURRENT lease token (heartbeat renewals
      # re-timestamp fq:// lease names); delete is fenced against stale
      # tokens, so a zombie's late ack can never complete a re-issued task
      queue.delete(hb.untrack(key))
      executed += 1
  finally:
    # whatever ends the loop — drain, stop_fn, an unhandled error — the
    # pending span batch must not die with the worker
    journal_mod.flush_active(
      event="drain" if draining() else "poll_exit"
    )


class FileQueue:
  def __init__(self, path: str, max_deliveries: Optional[int] = None):
    """``max_deliveries``: after this many deliveries (leases), a task
    that fails again is quarantined in ``dlq/`` instead of recycling.
    None (default) keeps the historical infinite-retry behavior."""
    if path.startswith("fq://"):
      path = path[len("fq://"):]
    self.path = os.path.abspath(os.path.expanduser(path))
    self.queue_dir = os.path.join(self.path, "queue")
    self.lease_dir = os.path.join(self.path, "leased")
    self.dlq_dir = os.path.join(self.path, "dlq")
    self.meta_dir = os.path.join(self.path, "meta")
    self.max_deliveries = (
      None if not max_deliveries or int(max_deliveries) <= 0
      else int(max_deliveries)
    )
    os.makedirs(self.queue_dir, exist_ok=True)
    os.makedirs(self.lease_dir, exist_ok=True)
    os.makedirs(self.dlq_dir, exist_ok=True)
    os.makedirs(self.meta_dir, exist_ok=True)

  # -- per-task attempt metadata --------------------------------------------

  def _meta_path(self, name: str) -> str:
    return os.path.join(self.meta_dir, name)

  def _read_meta(self, name: str) -> dict:
    try:
      with open(self._meta_path(name)) as f:
        return json.load(f)
    except (FileNotFoundError, ValueError):
      return {"deliveries": 0, "failures": []}

  def _write_meta(self, name: str, meta: dict):
    tmp = os.path.join(self.path, f".tmp-meta-{uuid.uuid4().hex}")
    try:
      with open(tmp, "w") as f:
        json.dump(meta, f)
      os.replace(tmp, self._meta_path(name))
    except BaseException:
      # same turd-free contract as storage put(): a failed write must not
      # leave .tmp-* files accumulating next to the counters
      try:
        os.remove(tmp)
      except FileNotFoundError:
        pass
      raise

  def _drop_meta(self, name: str):
    try:
      os.remove(self._meta_path(name))
    except FileNotFoundError:
      pass

  def _record_failure(self, name: str, reason: str) -> dict:
    meta = self._read_meta(name)
    meta.setdefault("failures", []).append({
      "time": time.time(), "error": str(reason)[:2000],
    })
    meta["failures"] = meta["failures"][-MAX_RECORDED_FAILURES:]
    self._write_meta(name, meta)
    return meta

  def delivery_count(self, name_or_lease: str) -> int:
    """Deliveries so far for a task (by queue filename or lease id) —
    the fq:// analogue of SQS's ApproximateReceiveCount."""
    name = name_or_lease.split(LEASE_SEP, 1)[-1]
    return int(self._read_meta(name).get("deliveries", 0))

  def _exhausted(self, name: str) -> bool:
    return (
      self.max_deliveries is not None
      and self.delivery_count(name) >= self.max_deliveries
    )

  # -- dead-letter queue ----------------------------------------------------

  def _quarantine_to_dlq(self, src_path: str, name: str, reason: str):
    """Move a task file into dlq/ (terminal until an operator intervenes).
    The meta file stays: it holds the delivery count + failure reasons
    that `dlq ls` reports."""
    self._record_failure(name, reason)
    try:
      os.rename(src_path, os.path.join(self.dlq_dir, name))
    except FileNotFoundError:
      return  # another worker moved it first
    from .. import telemetry

    telemetry.incr("dlq.promoted")

  @property
  def dlq_count(self) -> int:
    return len(os.listdir(self.dlq_dir))

  def dlq_ls(self) -> List[dict]:
    """One record per quarantined task: name, payload (JSON string),
    delivery count, and the recorded failure reasons (newest last)."""
    out = []
    for name in sorted(os.listdir(self.dlq_dir)):
      try:
        with open(os.path.join(self.dlq_dir, name)) as f:
          payload = f.read()
      except FileNotFoundError:
        continue
      meta = self._read_meta(name)
      out.append({
        "name": name,
        "payload": payload,
        "deliveries": int(meta.get("deliveries", 0)),
        "failures": meta.get("failures", []),
      })
    return out

  def dlq_retry(self, names: Optional[Iterable[str]] = None) -> int:
    """Return quarantined tasks to rotation (all, or just ``names``),
    resetting their delivery counts so they get a fresh budget."""
    if names is None:
      names = sorted(os.listdir(self.dlq_dir))
    n = 0
    for name in names:
      src = os.path.join(self.dlq_dir, name)
      try:
        os.rename(src, os.path.join(self.queue_dir, name))
      except FileNotFoundError:
        continue
      meta = self._read_meta(name)
      meta["deliveries"] = 0
      self._write_meta(name, meta)
      n += 1
    return n

  def dlq_purge(self) -> int:
    """Drop all quarantined tasks (and their metadata). Irreversible."""
    n = 0
    for name in list(os.listdir(self.dlq_dir)):
      try:
        os.remove(os.path.join(self.dlq_dir, name))
        n += 1
      except FileNotFoundError:
        continue
      finally:
        self._drop_meta(name)
    return n

  # -- counters -------------------------------------------------------------

  def _tally(self, counter: str, n: int = 1):
    with open(os.path.join(self.path, counter), "ab") as f:
      f.write(b"\x01" * n)

  def _count(self, counter: str) -> int:
    try:
      return os.path.getsize(os.path.join(self.path, counter))
    except FileNotFoundError:
      return 0

  @property
  def inserted(self) -> int:
    return self._count("insertions")

  @property
  def completed(self) -> int:
    return self._count("completions")

  @property
  def enqueued(self) -> int:
    return len(os.listdir(self.queue_dir)) + len(os.listdir(self.lease_dir))

  @property
  def leased(self) -> int:
    return len(os.listdir(self.lease_dir))

  def lease_ages(self) -> List[float]:
    """Seconds until each outstanding lease expires (negative = overdue,
    will recycle on the next poll)."""
    now = time.time()
    out = []
    for name in os.listdir(self.lease_dir):
      try:
        out.append(float(name.split(LEASE_SEP, 1)[0]) - now)
      except ValueError:
        continue
    return sorted(out)

  @property
  def stale_leases(self) -> int:
    """Leases past expiry that no poll has recycled yet — the queue's
    zombie pressure: each one is a worker that died, hung, or stopped
    heartbeating (`igneous queue status` surfaces this)."""
    return sum(1 for age in self.lease_ages() if age < 0)

  @property
  def backlog(self) -> int:
    """Work remaining (queued + leased, DLQ excluded) — the autoscaler's
    demand signal (ISSUE 6)."""
    return self.enqueued

  def depth_snapshot(self) -> dict:
    """One consistent-ish read of every depth the health plane consumes
    (listing races are possible; each field is individually truthful)."""
    leased = self.leased
    return {
      "inserted": self.inserted,
      "enqueued": self.enqueued,
      "leased": leased,
      "completed": self.completed,
      "backlog": self.backlog,
      "dlq": self.dlq_count,
      "stale_leases": self.stale_leases,
    }

  def reset_deliveries(self) -> int:
    """Zero the delivery count of every task still in rotation (queued or
    leased) so a ``max_deliveries`` budget starts fresh — the operator
    re-arm after a bad deploy burned deliveries on healthy tasks. DLQ'd
    tasks keep their counts (``dlq retry`` already grants fresh budgets)."""
    n = 0
    quarantined = set(os.listdir(self.dlq_dir))
    for name in list(os.listdir(self.meta_dir)):
      if name in quarantined:
        continue
      meta = self._read_meta(name)
      if not meta.get("deliveries"):
        continue
      meta["deliveries"] = 0
      self._write_meta(name, meta)
      n += 1
    return n

  def fsck(self, repair: bool = False) -> dict:
    """Consistency audit: undeserializable task files (the same check
    lease() applies), unparseable lease names, counter drift. With
    repair=True, malformed files move to ``<queue>/quarantine/`` and
    bad-name leases with VALID payloads recycle into the queue (corrupt
    ones are quarantined too)."""
    problems = {"malformed_tasks": [], "bad_lease_names": [],
                "counter_drift": (self.inserted - self.completed
                                  - self.enqueued - self.dlq_count)}
    quarantine_dir = os.path.join(self.path, "quarantine")

    def payload_ok(path: str):
      """None if a worker raced us; else (valid, contents)."""
      try:
        with open(path) as f:
          contents = f.read()
      except FileNotFoundError:
        return None  # leased/recycled mid-scan: healthy, skip
      try:
        deserialize(contents)  # exactly what lease() will do
        return (True, contents)
      except Exception:
        return (False, contents)

    def quarantine(path: str, name: str):
      os.makedirs(quarantine_dir, exist_ok=True)
      try:
        os.rename(path, os.path.join(quarantine_dir, name))
      except FileNotFoundError:
        pass

    for name in list(os.listdir(self.queue_dir)):
      path = os.path.join(self.queue_dir, name)
      result = payload_ok(path)
      if result is None or result[0]:
        continue
      problems["malformed_tasks"].append(name)
      if repair:
        quarantine(path, name)

    for name in list(os.listdir(self.lease_dir)):
      try:
        float(name.split(LEASE_SEP, 1)[0])
        continue  # well-formed lease
      except ValueError:
        pass
      problems["bad_lease_names"].append(name)
      if repair:
        path = os.path.join(self.lease_dir, name)
        result = payload_ok(path)
        if result is not None and result[0]:
          try:
            os.rename(path, os.path.join(self.queue_dir, name))
          except FileNotFoundError:
            pass
        elif result is not None:
          quarantine(path, name)
    return problems

  def is_empty(self) -> bool:
    return self.enqueued == 0

  def rezero(self):
    for counter in ("insertions", "completions"):
      try:
        os.remove(os.path.join(self.path, counter))
      except FileNotFoundError:
        pass

  # -- producer -------------------------------------------------------------

  def insert(self, tasks: Iterable, total: Optional[int] = None):
    del total
    n = 0
    for task in self._iter(tasks):
      payload = serialize(task)
      name = f"{uuid.uuid4().hex}.json"
      tmp = os.path.join(self.path, f".tmp-{name}")
      try:
        with open(tmp, "w") as f:
          f.write(payload)
        os.replace(tmp, os.path.join(self.queue_dir, name))
      except BaseException:
        try:
          os.remove(tmp)
        except FileNotFoundError:
          pass
        raise
      n += 1
    self._tally("insertions", n)
    return n

  insert_all = insert

  _iter = staticmethod(lambda tasks: iter_tasks(tasks))

  # -- consumer -------------------------------------------------------------

  def _recycle_expired(self):
    now = time.time()
    for name in os.listdir(self.lease_dir):
      try:
        deadline = float(name.split(LEASE_SEP, 1)[0])
      except ValueError:
        continue
      if deadline < now:
        orig = name.split(LEASE_SEP, 1)[1]
        src = os.path.join(self.lease_dir, name)
        if self._exhausted(orig):
          # the worker that held this lease died (or never acked): the
          # lease expiring IS the failure signal for its final delivery
          self._quarantine_to_dlq(
            src, orig,
            f"lease expired after delivery {self.delivery_count(orig)} "
            f"(worker lost or task hung)",
          )
          continue
        try:
          os.rename(src, os.path.join(self.queue_dir, orig))
        except FileNotFoundError:
          pass  # another worker recycled it first

  def lease(self, seconds: float = 600) -> Optional[Tuple[RegisteredTask, str]]:
    """Returns (task, lease_id) or None if the queue is drained."""
    self._recycle_expired()
    for _ in range(10):  # bounded retries under contention
      names = sorted(os.listdir(self.queue_dir))
      if not names:
        return None
      name = random.choice(names[:CONTENTION_WINDOW])
      deadline = time.time() + seconds
      lease_name = f"{deadline:.3f}{LEASE_SEP}{name}"
      src = os.path.join(self.queue_dir, name)
      dst = os.path.join(self.lease_dir, lease_name)
      try:
        os.rename(src, dst)
      except FileNotFoundError:
        continue  # lost the race; try another
      meta = self._read_meta(name)
      meta["deliveries"] = int(meta.get("deliveries", 0)) + 1
      self._write_meta(name, meta)
      with open(dst) as f:
        return deserialize(f.read()), lease_name
    return None

  def _lease_deadline(self, lease_id: str) -> Optional[float]:
    try:
      return float(str(lease_id).split(LEASE_SEP, 1)[0])
    except ValueError:
      return None

  def renew(self, lease_id: str, seconds: float = 600) -> str:
    """Extend a held lease's visibility timeout (the fq:// analogue of
    SQS ChangeMessageVisibility) by re-timestamping the lease name.
    Returns the NEW lease token — the old one is dead; callers (normally
    a LeaseHeartbeat) must use the returned token from here on.

    Zombie fencing: renewal is refused (StaleLeaseError + ``zombie.renew``
    counter) once the lease has expired or the task was re-issued — a
    stalled worker that wakes up cannot re-acquire what it lost."""
    from .. import telemetry

    deadline = self._lease_deadline(lease_id)
    orig = str(lease_id).split(LEASE_SEP, 1)[-1]
    if deadline is None or deadline < time.time():
      telemetry.incr("zombie.renew")
      raise StaleLeaseError(
        f"lease for {orig!r} already expired; the task is due for re-issue"
      )
    new_id = f"{time.time() + seconds:.3f}{LEASE_SEP}{orig}"
    try:
      os.rename(
        os.path.join(self.lease_dir, lease_id),
        os.path.join(self.lease_dir, new_id),
      )
    except FileNotFoundError:
      telemetry.incr("zombie.renew")
      raise StaleLeaseError(
        f"lease for {orig!r} was re-issued (or completed) by another worker"
      ) from None
    return new_id

  def delete(self, lease_id: str) -> bool:
    """Complete a task. Zombie-fenced: the delete (and its completion
    tally) only lands while the lease token is current — a worker that
    stalled past its lease and woke after the task was re-issued gets
    False + a ``zombie.delete`` counter instead of double-completing
    (the acceptance invariant: completions tally == task count)."""
    from .. import telemetry

    deadline = self._lease_deadline(lease_id)
    if deadline is not None and deadline < time.time():
      telemetry.incr("zombie.delete")
      return False
    try:
      os.remove(os.path.join(self.lease_dir, lease_id))
    except FileNotFoundError:
      telemetry.incr("zombie.delete")
      return False
    self._drop_meta(str(lease_id).split(LEASE_SEP, 1)[-1])
    self._tally("completions")
    return True

  def nack(self, lease_id: str, reason: str = "", requeue: bool = False):
    """Record a failed delivery. The failure reason persists with the
    task's metadata; once ``max_deliveries`` is exhausted the task moves
    to ``dlq/``. Otherwise the lease is left to recycle on its visibility
    timeout (at-least-once semantics unchanged) unless ``requeue=True``
    returns it to rotation immediately.

    A nack whose lease was already re-issued (or completed) is dropped
    with a ``zombie.nack`` counter — recording it would resurrect meta
    for a task this worker no longer owns."""
    orig = lease_id.split(LEASE_SEP, 1)[-1]
    src = os.path.join(self.lease_dir, lease_id)
    if not os.path.exists(src):
      from .. import telemetry

      telemetry.incr("zombie.nack")
      return
    if self._exhausted(orig):
      self._quarantine_to_dlq(src, orig, reason)  # records the reason
    else:
      self._record_failure(orig, reason)
      if requeue:
        self.release(lease_id)

  def release(self, lease_id: str):
    orig = lease_id.split(LEASE_SEP, 1)[1]
    try:
      os.rename(
        os.path.join(self.lease_dir, lease_id),
        os.path.join(self.queue_dir, orig),
      )
    except FileNotFoundError:
      pass

  def release_all(self):
    for name in list(os.listdir(self.lease_dir)):
      if LEASE_SEP in name:
        self.release(name)

  def purge(self):
    for d in (self.queue_dir, self.lease_dir, self.dlq_dir, self.meta_dir):
      for name in list(os.listdir(d)):
        try:
          os.remove(os.path.join(d, name))
        except FileNotFoundError:
          pass
    self.rezero()

  # -- worker loop ----------------------------------------------------------

  def poll(
    self,
    lease_seconds: float = 600,
    verbose: bool = False,
    tally: bool = True,
    stop_fn=None,
    max_backoff_window: float = 30.0,
    before_fn=None,
    after_fn=None,
    task_deadline_seconds: Optional[float] = None,
    heartbeat_seconds: Optional[float] = None,
    drain_flag=None,
  ):
    """Lease→execute→delete until stop_fn says stop or the queue drains
    (stop_fn=None polls forever, sleeping with bounded backoff when empty)."""
    del tally  # completions are always tallied; kept for API familiarity
    return poll_loop(
      self, lease_seconds, verbose, stop_fn, max_backoff_window,
      before_fn, after_fn, task_deadline_seconds,
      heartbeat_seconds, drain_flag,
    )

  def __len__(self):
    return self.enqueued
