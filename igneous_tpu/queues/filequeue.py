"""Lease-based filesystem task queue (``fq://``).

Behavioral parity with the reference's FileQueue (python-task-queue,
described at /root/reference/README.md:69-81): at-least-once delivery with a
visibility timeout — a leased task that is not deleted within its lease
returns to the pool; workers pick a random task among the first 100 to
avoid lease contention; completions are tallied 1 byte per task.

All state is plain files, so any shared POSIX filesystem (NFS, /mnt
volumes) works as the control plane across machines.

Failure containment (ISSUE 1): each task carries persisted attempt
metadata (``meta/<name>``: delivery count + recent failure reasons).
With ``max_deliveries`` configured, a task that keeps failing — by
raising, overrunning its deadline, or losing its worker — moves to the
``dlq/`` sidecar instead of re-entering rotation, where ``igneous queue
dlq ls|retry|purge`` can inspect, requeue, or drop it. The default
(``max_deliveries=None``) preserves the historical infinite-retry
at-least-once semantics.

Queue scale-out (ISSUE 15): the classic layout is one file + meta per
task, which goes quadratic-ish on listings at the tens-of-millions-of-
tasks campaigns the paper's grid sizes imply. ``insert_batch`` instead
writes **sharded metadata segments** — ``seg_<segid>_<count>.jsonl``
files holding up to ``IGNEOUS_QUEUE_SEG_TASKS`` tasks each (one line
``<index>\\t<payload>`` per task), sized so a batch lands in about
``IGNEOUS_QUEUE_SHARDS`` appends — and ``lease_batch`` leases a whole
segment as ONE :class:`~.ranges.RangeLease`. Depth reads stay
O(segments): task counts ride in the file names, completion tallies stay
1-byte-per-task counter files, and delivery counts key on the segment id
(stable across ack rewrites and splits). Per-task semantics survive
through sub-task accounting — see :mod:`.ranges`. Classic per-task files
and segments coexist freely in one queue directory, so pre-ISSUE-15
layouts keep reading.
"""

from __future__ import annotations

import json
import os
import random
import time
import uuid
from typing import Dict, Iterable, List, Optional, Tuple

from .ranges import RangeLease, RangeSub
from .registry import RegisteredTask, deserialize, serialize

LEASE_SEP = "--"
CONTENTION_WINDOW = 100
MAX_RECORDED_FAILURES = 5  # per-task failure-reason ring (meta file bound)

SEG_PREFIX = "seg_"
SEG_SUFFIX = ".jsonl"
# defaults mirrored by the knobs registry (analysis/knobs.py)
DEFAULT_QUEUE_SHARDS = 16
DEFAULT_SEG_TASKS = 1024
DEFAULT_RECYCLE_SEC = 5.0


def seg_parse(name: str) -> Optional[Tuple[str, int]]:
  """``seg_<segid>_<count>.jsonl`` → (segid, count); None for classic
  per-task file names. The count in the NAME is the task count in the
  file (maintained across ack rewrites), so depth reads never open
  segment files."""
  if not name.startswith(SEG_PREFIX) or not name.endswith(SEG_SUFFIX):
    return None
  parts = name[len(SEG_PREFIX):-len(SEG_SUFFIX)].rsplit("_", 1)
  if len(parts) != 2:
    return None
  try:
    return parts[0], int(parts[1])
  except ValueError:
    return None


def seg_name(segid: str, count: int) -> str:
  return f"{SEG_PREFIX}{segid}_{int(count)}{SEG_SUFFIX}"


def _name_tasks(name: str) -> int:
  """Tasks a queue/lease file name represents (lease prefixes allowed)."""
  parsed = seg_parse(name.split(LEASE_SEP, 1)[-1])
  return parsed[1] if parsed else 1


def _seg_content(entries) -> str:
  return "".join(f"{int(i)}\t{p}\n" for i, p in entries)


class TaskDeadlineError(Exception):
  """A task overran its per-delivery wall-clock deadline (poll_loop)."""


class StaleLeaseError(Exception):
  """The lease behind a renew/delete no longer belongs to this worker —
  it expired, or the queue re-issued the task to someone else. A worker
  seeing this is a *zombie* for that task: it must stop acting on it
  (the work itself is safe to discard — tasks are idempotent and the
  current owner will complete it)."""


def iter_tasks(tasks):
  """Normalize an insert() argument to an iterator of single tasks.
  Strings/bytes/dicts are single payloads, not collections — shared by
  every queue backend so a payload-dict never gets iterated as keys."""
  if hasattr(tasks, "__iter__") and not isinstance(tasks, (str, bytes, dict)):
    return iter(tasks)
  return iter([tasks])


def failure_reason(exc: BaseException) -> str:
  """One-line failure record shared by every containment path (poll_loop,
  the lease batcher, LocalTaskQueue) so DLQ entries read uniformly."""
  msg = str(exc)
  return f"{type(exc).__name__}: {msg}" if msg else type(exc).__name__


def run_with_deadline(fn, deadline_seconds: Optional[float]):
  """Run ``fn()`` with a wall-clock deadline. On overrun, raises
  TaskDeadlineError so the caller's failure bookkeeping (nack → DLQ)
  takes over. The overrunning call keeps executing on an abandoned
  daemon thread — it cannot be killed safely — which is sound here
  because tasks are idempotent and the lease it held stays failed."""
  if not deadline_seconds or deadline_seconds <= 0:
    return fn()
  import threading

  result = {}

  def body():
    try:
      result["value"] = fn()
    except BaseException as e:  # noqa: BLE001 - relayed to the caller
      result["error"] = e

  t = threading.Thread(target=body, daemon=True)
  t.start()
  t.join(deadline_seconds)
  if t.is_alive():
    raise TaskDeadlineError(
      f"task exceeded its {deadline_seconds:.1f}s deadline"
    )
  if "error" in result:
    raise result["error"]
  return result.get("value")


def poll_loop(
  queue,
  lease_seconds: float = 600,
  verbose: bool = False,
  stop_fn=None,
  max_backoff_window: float = 30.0,
  before_fn=None,
  after_fn=None,
  task_deadline_seconds: Optional[float] = None,
  heartbeat_seconds: Optional[float] = None,
  drain_flag=None,
):
  """Shared worker loop: lease→execute→delete until stop_fn says stop or
  the queue drains (stop_fn=None polls forever, sleeping with bounded
  backoff when empty). Used by every queue backend (fq://, sqs://) so
  execution semantics — at-least-once, recycle-on-failure — are uniform.

  Failure containment: an exception (or ``task_deadline_seconds``
  overrun) records its reason with the task via ``queue.nack`` when the
  backend supports it — feeding the same bookkeeping that promotes
  repeat offenders to the DLQ — and otherwise leaves the lease to
  recycle on its visibility timeout, exactly as before.

  Lifecycle (ISSUE 2): a heartbeat thread renews the held lease every
  ``heartbeat_seconds`` (default lease/3, env IGNEOUS_HEARTBEAT_SEC;
  <= 0 disables) so long tasks outlive a short ``--lease-sec`` without
  being double-executed. ``drain_flag`` (anything with ``is_set()``,
  e.g. lifecycle.StopFlag) requests graceful shutdown: the in-flight
  task finishes, no new lease is taken."""
  from .. import telemetry
  from ..observability import journal as journal_mod
  from ..observability import trace
  from .heartbeat import LeaseHeartbeat

  def draining() -> bool:
    return drain_flag is not None and drain_flag.is_set()

  def attempt_of(lease_id) -> Optional[int]:
    # fq:// persists delivery counts; SQS reports ApproximateReceiveCount
    try:
      if hasattr(queue, "delivery_count"):
        return int(queue.delivery_count(lease_id))
      if getattr(queue, "last_receive_count", 0):
        return int(queue.last_receive_count)
    except Exception:
      pass
    return None

  def idle(seconds: float):
    # wake early when a drain request lands mid-backoff
    if drain_flag is not None and hasattr(drain_flag, "wait"):
      drain_flag.wait(seconds)
    else:
      time.sleep(seconds)

  backoff = 1.0
  executed = 0
  hb = LeaseHeartbeat(queue, lease_seconds, interval=heartbeat_seconds)
  try:
   with hb:
    while True:
      # interval/drain-requested journal flush between tasks: the poll
      # loop IS the worker's main thread, so batches land without a
      # dedicated flusher thread
      journal_mod.maybe_flush_active()
      if draining():
        return executed
      if stop_fn is not None and stop_fn(executed=executed, empty=False):
        return executed
      leased = queue.lease(lease_seconds)
      if leased is None:
        if stop_fn is not None and stop_fn(executed=executed, empty=True):
          return executed
        if draining():
          return executed
        idle(backoff + random.random())
        backoff = min(backoff * 2, max_backoff_window)
        continue
      backoff = 1.0
      task, lease_id = leased
      key = hb.track(lease_id)
      if verbose:
        print(f"Executing {task!r}")
      try:
        if before_fn:
          before_fn(task)
        # IGNEOUS_PIPELINE=1 opts the solo worker loop into tier-A
        # pipelining: the task's chunk encodes+puts thread on the shared
        # pool, joined before the lease delete below — completion
        # semantics are unchanged (execute_with_sink falls back to plain
        # execute() when the task has no stage plan or pipelining is off)
        from ..pipeline import execute_with_sink

        # the task span wraps this delivery: stage/storage spans on this
        # thread (and pool threads the upload ticket propagates to)
        # parent under it, attributed to the payload's trace
        with trace.task_span(
          task, attempt=attempt_of(lease_id), queue=type(queue).__name__
        ):
          run_with_deadline(
            lambda: execute_with_sink(task), task_deadline_seconds
          )
        if after_fn:
          after_fn(task)
      except Exception as e:
        # leave the lease in place: the task recycles after the timeout
        # (at-least-once semantics; matches reference behavior on failure).
        # nack records the reason and quarantines exhausted tasks.
        if verbose:
          import traceback

          traceback.print_exc()
        telemetry.incr("tasks.failed")
        current = hb.untrack(key)
        if hasattr(queue, "nack"):
          queue.nack(current, failure_reason(e))
        continue
      # untrack returns the CURRENT lease token (heartbeat renewals
      # re-timestamp fq:// lease names); delete is fenced against stale
      # tokens, so a zombie's late ack can never complete a re-issued task
      queue.delete(hb.untrack(key))
      executed += 1
  finally:
    # whatever ends the loop — drain, stop_fn, an unhandled error — the
    # pending span batch must not die with the worker
    journal_mod.flush_active(
      event="drain" if draining() else "poll_exit"
    )


class FileQueue:
  def __init__(self, path: str, max_deliveries: Optional[int] = None):
    """``max_deliveries``: after this many deliveries (leases), a task
    that fails again is quarantined in ``dlq/`` instead of recycling.
    None (default) keeps the historical infinite-retry behavior."""
    if path.startswith("fq://"):
      path = path[len("fq://"):]
    self.path = os.path.abspath(os.path.expanduser(path))
    self.queue_dir = os.path.join(self.path, "queue")
    self.lease_dir = os.path.join(self.path, "leased")
    self.dlq_dir = os.path.join(self.path, "dlq")
    self.meta_dir = os.path.join(self.path, "meta")
    self.max_deliveries = (
      None if not max_deliveries or int(max_deliveries) <= 0
      else int(max_deliveries)
    )
    os.makedirs(self.queue_dir, exist_ok=True)
    os.makedirs(self.lease_dir, exist_ok=True)
    os.makedirs(self.dlq_dir, exist_ok=True)
    os.makedirs(self.meta_dir, exist_ok=True)
    # cached per-shard pending index (lease picks from here instead of a
    # full listdir+sort per acquisition) and the recycle-scan throttle
    self._pending_cache: Optional[List[str]] = None
    self._last_recycle = 0.0

  # -- per-task attempt metadata --------------------------------------------

  def _meta_path(self, name: str) -> str:
    return os.path.join(self.meta_dir, name)

  @staticmethod
  def _meta_key(name_or_lease: str) -> str:
    """Meta file key for a queue/lease/dlq name. Segments key on the
    SEGID (``seg_<segid>``) so ack rewrites — which change the count in
    the file name — never orphan the delivery-count record."""
    name = str(name_or_lease).split(LEASE_SEP, 1)[-1]
    parsed = seg_parse(name)
    return f"{SEG_PREFIX}{parsed[0]}" if parsed else name

  def _read_meta(self, name: str) -> dict:
    try:
      with open(self._meta_path(name)) as f:
        return json.load(f)
    except (FileNotFoundError, ValueError):
      return {"deliveries": 0, "failures": []}

  def _write_meta(self, name: str, meta: dict):
    tmp = os.path.join(self.path, f".tmp-meta-{uuid.uuid4().hex}")
    try:
      with open(tmp, "w") as f:
        json.dump(meta, f)
      os.replace(tmp, self._meta_path(name))
    except BaseException:
      # same turd-free contract as storage put(): a failed write must not
      # leave .tmp-* files accumulating next to the counters
      try:
        os.remove(tmp)
      except FileNotFoundError:
        pass
      raise

  def _drop_meta(self, name: str):
    try:
      os.remove(self._meta_path(name))
    except FileNotFoundError:
      pass

  def _record_failure(self, name: str, reason: str) -> dict:
    meta = self._read_meta(name)
    meta.setdefault("failures", []).append({
      "time": time.time(), "error": str(reason)[:2000],
    })
    meta["failures"] = meta["failures"][-MAX_RECORDED_FAILURES:]
    self._write_meta(name, meta)
    return meta

  def delivery_count(self, name_or_lease) -> int:
    """Deliveries so far for a task (by queue filename, lease id, or
    range-member handle) — the fq:// analogue of SQS's
    ApproximateReceiveCount. Range members report the shared segment's
    delivery count until a failure splits them out solo."""
    if isinstance(name_or_lease, RangeSub):
      key = f"{SEG_PREFIX}{name_or_lease.parent.segid}"
    else:
      key = self._meta_key(name_or_lease)
    return int(self._read_meta(key).get("deliveries", 0))

  def _exhausted(self, name: str) -> bool:
    return (
      self.max_deliveries is not None
      and self.delivery_count(name) >= self.max_deliveries
    )

  # -- dead-letter queue ----------------------------------------------------

  def _quarantine_to_dlq(self, src_path: str, name: str, reason: str):
    """Move a task file into dlq/ (terminal until an operator intervenes).
    The meta file stays: it holds the delivery count + failure reasons
    that `dlq ls` reports."""
    self._record_failure(name, reason)
    try:
      os.rename(src_path, os.path.join(self.dlq_dir, name))
    except FileNotFoundError:
      return  # another worker moved it first
    from .. import telemetry

    telemetry.incr("dlq.promoted")

  @property
  def dlq_count(self) -> int:
    return len(os.listdir(self.dlq_dir))

  def dlq_ls(self) -> List[dict]:
    """One record per quarantined task: name, payload (JSON string),
    delivery count, and the recorded failure reasons (newest last)."""
    out = []
    for name in sorted(os.listdir(self.dlq_dir)):
      try:
        with open(os.path.join(self.dlq_dir, name)) as f:
          payload = f.read()
      except FileNotFoundError:
        continue
      meta = self._read_meta(name)
      out.append({
        "name": name,
        "payload": payload,
        "deliveries": int(meta.get("deliveries", 0)),
        "failures": meta.get("failures", []),
      })
    return out

  def dlq_retry(self, names: Optional[Iterable[str]] = None) -> int:
    """Return quarantined tasks to rotation (all, or just ``names``),
    resetting their delivery counts so they get a fresh budget."""
    if names is None:
      names = sorted(os.listdir(self.dlq_dir))
    n = 0
    for name in names:
      src = os.path.join(self.dlq_dir, name)
      try:
        os.rename(src, os.path.join(self.queue_dir, name))
      except FileNotFoundError:
        continue
      meta = self._read_meta(name)
      meta["deliveries"] = 0
      self._write_meta(name, meta)
      n += 1
    self._pending_cache = None
    return n

  def dlq_purge(self) -> int:
    """Drop all quarantined tasks (and their metadata). Irreversible."""
    n = 0
    for name in list(os.listdir(self.dlq_dir)):
      try:
        os.remove(os.path.join(self.dlq_dir, name))
        n += 1
      except FileNotFoundError:
        continue
      finally:
        self._drop_meta(name)
    return n

  # -- counters -------------------------------------------------------------

  def _tally(self, counter: str, n: int = 1):
    with open(os.path.join(self.path, counter), "ab") as f:
      f.write(b"\x01" * n)

  def _count(self, counter: str) -> int:
    try:
      return os.path.getsize(os.path.join(self.path, counter))
    except FileNotFoundError:
      return 0

  @property
  def inserted(self) -> int:
    return self._count("insertions")

  @property
  def completed(self) -> int:
    return self._count("completions")

  @property
  def enqueued(self) -> int:
    """Tasks in rotation (queued + leased). O(segments) — segment task
    counts ride in the file names, so no segment file is ever opened."""
    return (
      sum(_name_tasks(n) for n in os.listdir(self.queue_dir))
      + sum(_name_tasks(n) for n in os.listdir(self.lease_dir))
    )

  @property
  def leased(self) -> int:
    return sum(_name_tasks(n) for n in os.listdir(self.lease_dir))

  @property
  def queue_files(self) -> int:
    """Control-plane objects backing the pending pool — O(shards) per
    batch-inserted campaign, vs O(tasks) for the classic layout (the
    `queue status`/smoke-gate scalability signal)."""
    return len(os.listdir(self.queue_dir))

  def lease_ages(self) -> List[float]:
    """Seconds until each outstanding lease expires (negative = overdue,
    will recycle on the next poll)."""
    now = time.time()
    out = []
    for name in os.listdir(self.lease_dir):
      try:
        out.append(float(name.split(LEASE_SEP, 1)[0]) - now)
      except ValueError:
        continue
    return sorted(out)

  @property
  def stale_leases(self) -> int:
    """Leases past expiry that no poll has recycled yet — the queue's
    zombie pressure: each one is a worker that died, hung, or stopped
    heartbeating (`igneous queue status` surfaces this)."""
    return sum(1 for age in self.lease_ages() if age < 0)

  @property
  def backlog(self) -> int:
    """Work remaining (queued + leased, DLQ excluded) — the autoscaler's
    demand signal (ISSUE 6)."""
    return self.enqueued

  def depth_snapshot(self) -> dict:
    """One consistent-ish read of every depth the health plane consumes
    (listing races are possible; each field is individually truthful)."""
    leased = self.leased
    return {
      "inserted": self.inserted,
      "enqueued": self.enqueued,
      "leased": leased,
      "completed": self.completed,
      "backlog": self.backlog,
      "dlq": self.dlq_count,
      "stale_leases": self.stale_leases,
    }

  def reset_deliveries(self) -> int:
    """Zero the delivery count of every task still in rotation (queued or
    leased) so a ``max_deliveries`` budget starts fresh — the operator
    re-arm after a bad deploy burned deliveries on healthy tasks. DLQ'd
    tasks keep their counts (``dlq retry`` already grants fresh budgets)."""
    n = 0
    quarantined = set(os.listdir(self.dlq_dir))
    for name in list(os.listdir(self.meta_dir)):
      if name in quarantined:
        continue
      meta = self._read_meta(name)
      if not meta.get("deliveries"):
        continue
      meta["deliveries"] = 0
      self._write_meta(name, meta)
      n += 1
    return n

  def fsck(self, repair: bool = False) -> dict:
    """Consistency audit: undeserializable task files (the same check
    lease() applies), unparseable lease names, counter drift. With
    repair=True, malformed files move to ``<queue>/quarantine/`` and
    bad-name leases with VALID payloads recycle into the queue (corrupt
    ones are quarantined too)."""
    problems = {"malformed_tasks": [], "bad_lease_names": [],
                "counter_drift": (self.inserted - self.completed
                                  - self.enqueued - self.dlq_count)}
    quarantine_dir = os.path.join(self.path, "quarantine")

    def payload_ok(path: str):
      """None if a worker raced us; else (valid, contents)."""
      try:
        with open(path) as f:
          contents = f.read()
      except FileNotFoundError:
        return None  # leased/recycled mid-scan: healthy, skip
      try:
        deserialize(contents)  # exactly what lease() will do
        return (True, contents)
      except Exception:
        return (False, contents)

    def quarantine(path: str, name: str):
      os.makedirs(quarantine_dir, exist_ok=True)
      try:
        os.rename(path, os.path.join(quarantine_dir, name))
      except FileNotFoundError:
        pass

    def segment_ok(path: str, count: int):
      """None if raced; else whether every line deserializes AND the
      task count in the name matches the file (depth reads trust it)."""
      try:
        entries = self._read_segment(path)
      except FileNotFoundError:
        return None
      except Exception:
        return False
      if len(entries) != count:
        return False
      try:
        for _i, p in entries:
          deserialize(p)
      except Exception:
        return False
      return True

    for name in list(os.listdir(self.queue_dir)):
      path = os.path.join(self.queue_dir, name)
      parsed = seg_parse(name)
      if parsed is not None:
        ok = segment_ok(path, parsed[1])
        if ok is None or ok:
          continue
      else:
        result = payload_ok(path)
        if result is None or result[0]:
          continue
      problems["malformed_tasks"].append(name)
      if repair:
        quarantine(path, name)

    for name in list(os.listdir(self.lease_dir)):
      try:
        float(name.split(LEASE_SEP, 1)[0])
        continue  # well-formed lease
      except ValueError:
        pass
      problems["bad_lease_names"].append(name)
      if repair:
        path = os.path.join(self.lease_dir, name)
        result = payload_ok(path)
        if result is not None and result[0]:
          try:
            os.rename(path, os.path.join(self.queue_dir, name))
          except FileNotFoundError:
            pass
        elif result is not None:
          quarantine(path, name)
    return problems

  def is_empty(self) -> bool:
    return self.enqueued == 0

  def rezero(self):
    for counter in ("insertions", "completions"):
      try:
        os.remove(os.path.join(self.path, counter))
      except FileNotFoundError:
        pass

  # -- segment I/O ----------------------------------------------------------

  def _write_file(self, dirpath: str, name: str, content: str):
    """tmp-write + atomic rename with the same turd-free contract as
    insert()/_write_meta."""
    tmp = os.path.join(self.path, f".tmp-{uuid.uuid4().hex}")
    try:
      with open(tmp, "w") as f:
        f.write(content)
      os.replace(tmp, os.path.join(dirpath, name))
    except BaseException:
      try:
        os.remove(tmp)
      except FileNotFoundError:
        pass
      raise

  @staticmethod
  def _read_segment(path: str) -> List[Tuple[int, str]]:
    """Segment file → [(task_index, payload)] (payloads are single-line
    JSON, so one line per task). Raises FileNotFoundError on lease races
    like every other read here; malformed lines raise ValueError for
    fsck to catch."""
    entries = []
    with open(path) as f:
      for line in f:
        line = line.rstrip("\n")
        if not line:
          continue
        idx, payload = line.split("\t", 1)
        entries.append((int(idx), payload))
    return entries

  def _copy_meta(self, src_segid: str, dst_segid: str):
    """Splits inherit the parent segment's attempt record, so per-task
    DLQ attribution survives any number of lease splits."""
    meta = self._read_meta(f"{SEG_PREFIX}{src_segid}")
    if meta.get("deliveries") or meta.get("failures"):
      self._write_meta(f"{SEG_PREFIX}{dst_segid}", meta)

  # -- producer -------------------------------------------------------------

  def insert(self, tasks: Iterable, total: Optional[int] = None):
    """Classic one-file-per-task insert (kept verbatim for layout
    compatibility; batched producers should call :meth:`insert_batch`)."""
    del total
    n = 0
    for task in self._iter(tasks):
      payload = serialize(task)
      name = f"{uuid.uuid4().hex}.json"
      tmp = os.path.join(self.path, f".tmp-{name}")
      try:
        with open(tmp, "w") as f:
          f.write(payload)
        os.replace(tmp, os.path.join(self.queue_dir, name))
      except BaseException:
        try:
          os.remove(tmp)
        except FileNotFoundError:
          pass
        raise
      n += 1
    self._tally("insertions", n)
    self._pending_cache = None
    return n

  def insert_batch(self, tasks: Iterable, total: Optional[int] = None):
    """Batched wire protocol (ISSUE 15): tasks land in segment files of
    up to ``IGNEOUS_QUEUE_SEG_TASKS`` tasks each — one append per
    segment instead of one file + meta per task. ``total`` (when the
    producer knows it, e.g. a regular grid's task count) sizes segments
    so the batch spreads across ~``IGNEOUS_QUEUE_SHARDS`` files for
    lease-contention spread; unknown totals stream at the per-segment
    cap. ``IGNEOUS_QUEUE_SEG_TASKS=0`` falls back to the classic
    per-task layout."""
    from ..analysis import knobs

    seg_cap = knobs.get_int("IGNEOUS_QUEUE_SEG_TASKS")
    seg_cap = DEFAULT_SEG_TASKS if seg_cap is None else int(seg_cap)
    if seg_cap <= 0:
      return self.insert(tasks, total=total)
    shards = knobs.get_int("IGNEOUS_QUEUE_SHARDS")
    shards = max(int(shards or DEFAULT_QUEUE_SHARDS), 1)
    if total:
      seg_size = min(max(-(-int(total) // shards), 1), seg_cap)
    else:
      seg_size = seg_cap
    base = self.inserted   # global task indices continue across batches
    n = 0
    chunk: List[Tuple[int, str]] = []

    def flush():
      nonlocal chunk
      if chunk:
        self._write_file(
          self.queue_dir, seg_name(uuid.uuid4().hex, len(chunk)),
          _seg_content(chunk),
        )
        chunk = []

    for task in self._iter(tasks):
      payload = task if isinstance(task, str) else serialize(task)
      chunk.append((base + n, payload))
      n += 1
      if len(chunk) >= seg_size:
        flush()
    flush()
    self._tally("insertions", n)
    self._pending_cache = None
    return n

  insert_all = insert

  _iter = staticmethod(lambda tasks: iter_tasks(tasks))

  # -- consumer -------------------------------------------------------------

  def _recycle_expired(self, force: bool = False) -> int:
    """Return expired leases to rotation. Throttled to one lease-dir scan
    per ``IGNEOUS_QUEUE_RECYCLE_SEC`` (0 = scan on every call) — the full
    scan dominated small-task lease latency. ``force=True`` bypasses the
    throttle (used when the pending pool looks drained, so an
    emptied-but-expired queue never reads as done). Returns the number of
    files returned to the pool."""
    from ..analysis import knobs

    now = time.time()
    if not force:
      interval = knobs.get_float("IGNEOUS_QUEUE_RECYCLE_SEC")
      interval = DEFAULT_RECYCLE_SEC if interval is None else float(interval)
      if interval > 0 and now - self._last_recycle < interval:
        return 0
    self._last_recycle = now
    n = 0
    for name in os.listdir(self.lease_dir):
      try:
        deadline = float(name.split(LEASE_SEP, 1)[0])
      except ValueError:
        continue
      if deadline >= now:
        continue
      orig = name.split(LEASE_SEP, 1)[1]
      src = os.path.join(self.lease_dir, name)
      if self._exhausted(orig):
        # the worker that held this lease died (or never acked): the
        # lease expiring IS the failure signal for its final delivery
        reason = (
          f"lease expired after delivery {self.delivery_count(orig)} "
          f"(worker lost or task hung)"
        )
        parsed = seg_parse(orig)
        if parsed:
          self._expire_segment_to_dlq(src, parsed[0], reason)
        else:
          self._quarantine_to_dlq(src, orig, reason)
        continue
      try:
        os.rename(src, os.path.join(self.queue_dir, orig))
      except FileNotFoundError:
        continue  # another worker recycled it first
      n += 1
      if self._pending_cache is not None:
        self._pending_cache.append(orig)
    return n

  def _expire_segment_to_dlq(self, src: str, segid: str, reason: str):
    """A segment that exhausted its delivery budget quarantines
    per-index: every surviving member becomes its own ``dlq/`` entry
    (``task_<segid>_<idx>.json``) carrying the shared attempt record, so
    `dlq ls|retry` keep their per-task granularity. Deterministic names
    make a racing double-expansion idempotent; dlq files land before the
    lease file is removed, so a crash mid-expansion re-runs cleanly."""
    from .. import telemetry

    try:
      entries = self._read_segment(src)
    except FileNotFoundError:
      return  # another worker expanded it first
    seg_meta = self._read_meta(f"{SEG_PREFIX}{segid}")
    for idx, payload in entries:
      name = f"task_{segid}_{idx}.json"
      meta = self._read_meta(name)
      meta["deliveries"] = max(
        int(meta.get("deliveries", 0)), int(seg_meta.get("deliveries", 0))
      )
      meta["failures"] = (
        seg_meta.get("failures", []) + meta.get("failures", [])
      )[-MAX_RECORDED_FAILURES:]
      self._write_meta(name, meta)
      self._record_failure(name, reason)
      self._write_file(self.dlq_dir, name, payload)
      telemetry.incr("dlq.promoted")
    try:
      os.remove(src)
    except FileNotFoundError:
      pass
    self._drop_meta(f"{SEG_PREFIX}{segid}")

  def _pop_pending(self) -> Optional[str]:
    """Pick a pending name from the cached per-shard index — the random-
    within-window contention dodge of the classic lease(), without the
    listdir+sort per acquisition. The cache is reverse-sorted so the
    window sits at the tail for O(1) pops."""
    cache = self._pending_cache
    if not cache:
      return None
    window = min(len(cache), CONTENTION_WINDOW)
    return cache.pop(len(cache) - 1 - random.randrange(window))

  def _lease_one(self, name: str, seconds: float, cap: int):
    """Acquire one pending file (rename = the mutex). A classic per-task
    file leases whole; a segment leases as a :class:`RangeLease`, split
    at ``cap`` members — the remainder returns to the pool under a new
    segid (attempt meta copied) BEFORE the lease shrinks, so a crash
    between duplicates deliveries but never loses tasks. Returns a list
    of (task, token) pairs, or None when the rename race was lost."""
    deadline = time.time() + seconds
    lease_name = f"{deadline:.3f}{LEASE_SEP}{name}"
    src = os.path.join(self.queue_dir, name)
    dst = os.path.join(self.lease_dir, lease_name)
    try:
      os.rename(src, dst)
    except FileNotFoundError:
      return None  # lost the race; caller tries another
    parsed = seg_parse(name)
    if parsed is None:
      meta = self._read_meta(name)
      meta["deliveries"] = int(meta.get("deliveries", 0)) + 1
      self._write_meta(name, meta)
      with open(dst) as f:
        return [(deserialize(f.read()), lease_name)]
    segid = parsed[0]
    entries = self._read_segment(dst)
    cap = max(int(cap), 1)
    if len(entries) > cap:
      keep, rest = entries[:cap], entries[cap:]
      rest_segid = uuid.uuid4().hex
      self._copy_meta(segid, rest_segid)
      rest_name = seg_name(rest_segid, len(rest))
      self._write_file(self.queue_dir, rest_name, _seg_content(rest))
      if self._pending_cache is not None:
        # next pop likely continues the contiguous run on this worker
        self._pending_cache.append(rest_name)
      lease_name_new = f"{deadline:.3f}{LEASE_SEP}{seg_name(segid, len(keep))}"
      self._write_file(self.lease_dir, lease_name_new, _seg_content(keep))
      try:
        os.remove(dst)
      except FileNotFoundError:
        pass
      lease_name, entries = lease_name_new, keep
    meta = self._read_meta(f"{SEG_PREFIX}{segid}")
    meta["deliveries"] = int(meta.get("deliveries", 0)) + 1
    self._write_meta(f"{SEG_PREFIX}{segid}", meta)
    rl = RangeLease(self, lease_name, segid, dict(entries), deadline)
    return [(deserialize(p), RangeSub(rl, i)) for i, p in entries]

  def lease_batch(self, seconds: float = 600, max_tasks: int = 1):
    """Lease up to ``max_tasks`` tasks in one call. Segments come back as
    range members — (task, :class:`RangeSub`) pairs sharing one
    underlying lease — classic files as (task, lease_id) pairs; the two
    mix freely in one result. Returns [] when the queue is drained."""
    self._recycle_expired()
    out: List[Tuple[RegisteredTask, object]] = []
    refreshed = False
    races = 0
    while len(out) < max_tasks and races < 10:
      name = self._pop_pending()
      if name is None:
        if refreshed:
          break
        # cache drained: force a recycle pass (the throttle must not make
        # an emptied-but-expired queue look drained), then re-list once
        self._recycle_expired(force=True)
        self._pending_cache = sorted(os.listdir(self.queue_dir), reverse=True)
        refreshed = True
        continue
      got = self._lease_one(name, seconds, max_tasks - len(out))
      if got is None:
        races += 1
        continue
      out.extend(got)
    return out

  def lease(self, seconds: float = 600) -> Optional[Tuple[RegisteredTask, str]]:
    """Returns (task, lease_id) or None if the queue is drained. On a
    segmented queue the single task splits off its segment, so solo
    pollers interoperate with batch producers."""
    got = self.lease_batch(seconds, max_tasks=1)
    return got[0] if got else None

  def _lease_deadline(self, lease_id: str) -> Optional[float]:
    try:
      return float(str(lease_id).split(LEASE_SEP, 1)[0])
    except ValueError:
      return None

  def renew(self, lease_id, seconds: float = 600):
    """Extend a held lease's visibility timeout (the fq:// analogue of
    SQS ChangeMessageVisibility) by re-timestamping the lease name.
    Returns the NEW lease token — the old one is dead; callers (normally
    a LeaseHeartbeat) must use the returned token from here on. A range
    member renews its parent's ONE lease and returns the same handle:
    RangeSub tokens are stable across renewals (rotation is internal).

    Zombie fencing: renewal is refused (StaleLeaseError + ``zombie.renew``
    counter) once the lease has expired or the task was re-issued — a
    stalled worker that wakes up cannot re-acquire what it lost."""
    from .. import telemetry

    if isinstance(lease_id, RangeSub):
      self._range_renew(lease_id.parent, seconds)
      return lease_id
    deadline = self._lease_deadline(lease_id)
    orig = str(lease_id).split(LEASE_SEP, 1)[-1]
    if deadline is None or deadline < time.time():
      telemetry.incr("zombie.renew")
      raise StaleLeaseError(
        f"lease for {orig!r} already expired; the task is due for re-issue"
      )
    new_id = f"{time.time() + seconds:.3f}{LEASE_SEP}{orig}"
    try:
      os.rename(
        os.path.join(self.lease_dir, lease_id),
        os.path.join(self.lease_dir, new_id),
      )
    except FileNotFoundError:
      telemetry.incr("zombie.renew")
      raise StaleLeaseError(
        f"lease for {orig!r} was re-issued (or completed) by another worker"
      ) from None
    return new_id

  def delete(self, lease_id) -> bool:
    """Complete a task. Zombie-fenced: the delete (and its completion
    tally) only lands while the lease token is current — a worker that
    stalled past its lease and woke after the task was re-issued gets
    False + a ``zombie.delete`` counter instead of double-completing
    (the acceptance invariant: completions tally == task count). A range
    member acks its sub-range: the parent lease shrinks by one index."""
    from .. import telemetry

    if isinstance(lease_id, RangeSub):
      return self._range_ack(lease_id.parent, lease_id.index)
    deadline = self._lease_deadline(lease_id)
    if deadline is not None and deadline < time.time():
      telemetry.incr("zombie.delete")
      return False
    try:
      os.remove(os.path.join(self.lease_dir, lease_id))
    except FileNotFoundError:
      telemetry.incr("zombie.delete")
      return False
    self._drop_meta(str(lease_id).split(LEASE_SEP, 1)[-1])
    self._tally("completions")
    return True

  def nack(self, lease_id, reason: str = "", requeue: bool = False):
    """Record a failed delivery. The failure reason persists with the
    task's metadata; once ``max_deliveries`` is exhausted the task moves
    to ``dlq/``. Otherwise the lease is left to recycle on its visibility
    timeout (at-least-once semantics unchanged) unless ``requeue=True``
    returns it to rotation immediately. A range member nack SPLITS the
    lease: only the failed index retries (or dead-letters).

    A nack whose lease was already re-issued (or completed) is dropped
    with a ``zombie.nack`` counter — recording it would resurrect meta
    for a task this worker no longer owns."""
    if isinstance(lease_id, RangeSub):
      return self._range_nack(
        lease_id.parent, lease_id.index, reason, requeue=requeue
      )
    orig = lease_id.split(LEASE_SEP, 1)[-1]
    src = os.path.join(self.lease_dir, lease_id)
    if not os.path.exists(src):
      from .. import telemetry

      telemetry.incr("zombie.nack")
      return
    if self._exhausted(orig):
      self._quarantine_to_dlq(src, orig, reason)  # records the reason
    else:
      self._record_failure(orig, reason)
      if requeue:
        self.release(lease_id)

  def release(self, lease_id):
    """Return a lease to rotation immediately (undelivered). A range
    member releases just its index back as a fresh one-task segment."""
    if isinstance(lease_id, RangeSub):
      return self._range_release(lease_id.parent, [lease_id.index])
    orig = lease_id.split(LEASE_SEP, 1)[1]
    try:
      os.rename(
        os.path.join(self.lease_dir, lease_id),
        os.path.join(self.queue_dir, orig),
      )
    except FileNotFoundError:
      return
    if self._pending_cache is not None:
      self._pending_cache.append(orig)

  def release_all(self):
    for name in list(os.listdir(self.lease_dir)):
      if LEASE_SEP in name:
        self.release(name)
    self._pending_cache = None

  # -- batched completion ----------------------------------------------------

  def ack_batch(self, tokens) -> List[bool]:
    """Complete many tasks at once. Range members sharing a parent lease
    collapse into ONE lease-file rewrite; classic tokens delete one by
    one. Results align positionally with ``tokens`` (False = zombie-
    fenced, exactly as the scalar ops report it)."""
    tokens = list(tokens)
    results = [False] * len(tokens)
    by_parent: Dict[int, Tuple[RangeLease, List[Tuple[int, int]]]] = {}
    for pos, tok in enumerate(tokens):
      if isinstance(tok, RangeSub):
        by_parent.setdefault(id(tok.parent), (tok.parent, []))[1].append(
          (pos, tok.index)
        )
      else:
        results[pos] = self.delete(tok)
    for parent, members in by_parent.values():
      acked = self._range_ack_many(parent, [i for _, i in members])
      for pos, i in members:
        results[pos] = bool(acked.get(int(i)))
    return results

  def nack_batch(self, tokens, reason: str = "", requeue: bool = False):
    """Fail many deliveries with one call (per-token semantics identical
    to scalar ``nack``: range members split, exhausted tasks DLQ)."""
    for tok in tokens:
      self.nack(tok, reason, requeue=requeue)

  # -- range-lease mechanics (handles live in .ranges) -----------------------

  def _range_rewrite(self, rl: RangeLease, new_entries: Dict[int, str],
                     new_deadline: Optional[float] = None) -> bool:
    """Swap the range's lease file for one holding ``new_entries``
    (removed outright when empty). Write-new-then-remove-old: a crash in
    between re-delivers, never loses. False = the old lease file was
    gone (expired + re-issued, or completed elsewhere) — the new file is
    withdrawn and the caller is a zombie for this range. Caller holds
    ``rl.lock``."""
    deadline = rl.deadline if new_deadline is None else float(new_deadline)
    old = os.path.join(self.lease_dir, rl.token)
    if not new_entries:
      try:
        os.remove(old)
      except FileNotFoundError:
        return False
      rl.entries = {}
      return True
    new_token = f"{deadline:.3f}{LEASE_SEP}{seg_name(rl.segid, len(new_entries))}"
    if new_token == rl.token:
      rl.entries = dict(new_entries)
      return True
    self._write_file(
      self.lease_dir, new_token, _seg_content(sorted(new_entries.items()))
    )
    try:
      os.remove(old)
    except FileNotFoundError:
      try:
        os.remove(os.path.join(self.lease_dir, new_token))
      except FileNotFoundError:
        pass
      return False
    rl.token = new_token
    rl.entries = dict(new_entries)
    rl.deadline = deadline
    return True

  def _range_ack_many(self, rl: RangeLease, indices) -> Dict[int, bool]:
    """Complete several members of one range with a single rewrite."""
    from .. import telemetry

    todo = [int(i) for i in indices]
    with rl.lock:
      if rl.deadline < time.time():
        telemetry.incr("zombie.delete", len(todo))
        return {i: False for i in todo}
      hit = sorted({i for i in todo if i in rl.entries})
      miss = [i for i in todo if i not in rl.entries]
      if miss:
        telemetry.incr("zombie.delete", len(miss))
      if not hit:
        return {i: False for i in todo}
      remaining = {i: p for i, p in rl.entries.items() if i not in set(hit)}
      if not self._range_rewrite(rl, remaining):
        telemetry.incr("zombie.delete", len(hit))
        return {i: False for i in todo}
      self._tally("completions", len(hit))
      if not remaining:
        self._drop_meta(f"{SEG_PREFIX}{rl.segid}")
      hitset = set(hit)
      return {i: i in hitset for i in todo}

  def _range_ack(self, rl: RangeLease, index: int) -> bool:
    return self._range_ack_many(rl, [index])[int(index)]

  def _range_nack(self, rl: RangeLease, index: int, reason: str = "",
                  requeue: bool = False):
    """Mid-range failure: carve the failed index out as a classic
    single-task lease (``task_<segid>_<idx>.json``) inheriting the
    range's attempt record, shrink the range, then hand the carve to the
    classic nack machinery — so reason recording, DLQ promotion, and
    retry budgets apply to ONLY the failed index while the rest of the
    range proceeds untouched."""
    from .. import telemetry

    index = int(index)
    with rl.lock:
      if rl.deadline < time.time() or index not in rl.entries:
        telemetry.incr("zombie.nack")
        return
      carve = f"task_{rl.segid}_{index}.json"
      seg_meta = self._read_meta(f"{SEG_PREFIX}{rl.segid}")
      meta = self._read_meta(carve)
      meta["deliveries"] = max(
        int(meta.get("deliveries", 0)), int(seg_meta.get("deliveries", 0))
      )
      meta["failures"] = (
        seg_meta.get("failures", []) + meta.get("failures", [])
      )[-MAX_RECORDED_FAILURES:]
      self._write_meta(carve, meta)
      carve_lease = f"{rl.deadline:.3f}{LEASE_SEP}{carve}"
      self._write_file(self.lease_dir, carve_lease, rl.entries[index])
      remaining = {i: p for i, p in rl.entries.items() if i != index}
      if not self._range_rewrite(rl, remaining):
        # the whole range is being redelivered; withdraw the carve so the
        # index isn't duplicated
        try:
          os.remove(os.path.join(self.lease_dir, carve_lease))
        except FileNotFoundError:
          pass
        telemetry.incr("zombie.nack")
        return
    return self.nack(carve_lease, reason, requeue=requeue)

  def _range_release(self, rl: RangeLease, indices=None) -> int:
    """Return members (all surviving ones when ``indices`` is None) to
    the pool immediately as a fresh segment under a new segid (attempt
    meta copied, deliveries kept — matching classic release)."""
    with rl.lock:
      if indices is None:
        chosen = sorted(rl.entries)
      else:
        chosen = sorted({int(i) for i in indices} & set(rl.entries))
      if not chosen or rl.deadline < time.time():
        return 0  # expired: the recycler owns these now
      released = {i: rl.entries[i] for i in chosen}
      new_segid = uuid.uuid4().hex
      self._copy_meta(rl.segid, new_segid)
      new_name = seg_name(new_segid, len(released))
      self._write_file(
        self.queue_dir, new_name, _seg_content(sorted(released.items()))
      )
      remaining = {i: p for i, p in rl.entries.items() if i not in set(chosen)}
      if not self._range_rewrite(rl, remaining):
        try:
          os.remove(os.path.join(self.queue_dir, new_name))
        except FileNotFoundError:
          pass
        return 0
      if self._pending_cache is not None:
        self._pending_cache.append(new_name)
      return len(released)

  def _range_renew(self, rl: RangeLease, seconds: float) -> str:
    """Extend the range's ONE lease. Internally the token rotates (the
    deadline rides in the file name) but RangeSub handles stay valid.
    Freshness guard: when the deadline already covers ~the requested
    extension, this is a no-op — K heartbeat-tracked members cost one
    rename per beat, not K."""
    from .. import telemetry

    with rl.lock:
      now = time.time()
      if not rl.entries:
        # fully completed: a heartbeat racing the final ack — not a zombie
        raise StaleLeaseError(
          f"range {rl.segid!r} fully completed; nothing left to renew"
        )
      if rl.deadline < now:
        telemetry.incr("zombie.renew")
        raise StaleLeaseError(
          f"range lease {rl.segid!r} already expired; due for re-issue"
        )
      if rl.deadline >= now + float(seconds) * 0.9:
        return rl.token
      new_deadline = now + float(seconds)
      new_token = (
        f"{new_deadline:.3f}{LEASE_SEP}{seg_name(rl.segid, len(rl.entries))}"
      )
      try:
        os.rename(
          os.path.join(self.lease_dir, rl.token),
          os.path.join(self.lease_dir, new_token),
        )
      except FileNotFoundError:
        telemetry.incr("zombie.renew")
        raise StaleLeaseError(
          f"range lease {rl.segid!r} was re-issued by another worker"
        ) from None
      rl.token = new_token
      rl.deadline = new_deadline
      return rl.token

  def purge(self):
    for d in (self.queue_dir, self.lease_dir, self.dlq_dir, self.meta_dir):
      for name in list(os.listdir(d)):
        try:
          os.remove(os.path.join(d, name))
        except FileNotFoundError:
          pass
    self._pending_cache = None
    self.rezero()

  # -- worker loop ----------------------------------------------------------

  def poll(
    self,
    lease_seconds: float = 600,
    verbose: bool = False,
    tally: bool = True,
    stop_fn=None,
    max_backoff_window: float = 30.0,
    before_fn=None,
    after_fn=None,
    task_deadline_seconds: Optional[float] = None,
    heartbeat_seconds: Optional[float] = None,
    drain_flag=None,
  ):
    """Lease→execute→delete until stop_fn says stop or the queue drains
    (stop_fn=None polls forever, sleeping with bounded backoff when empty)."""
    del tally  # completions are always tallied; kept for API familiarity
    return poll_loop(
      self, lease_seconds, verbose, stop_fn, max_backoff_window,
      before_fn, after_fn, task_deadline_seconds,
      heartbeat_seconds, drain_flag,
    )

  def __len__(self):
    return self.enqueued
