"""Lease-based filesystem task queue (``fq://``).

Behavioral parity with the reference's FileQueue (python-task-queue,
described at /root/reference/README.md:69-81): at-least-once delivery with a
visibility timeout — a leased task that is not deleted within its lease
returns to the pool; workers pick a random task among the first 100 to
avoid lease contention; completions are tallied 1 byte per task.

All state is plain files, so any shared POSIX filesystem (NFS, /mnt
volumes) works as the control plane across machines.
"""

from __future__ import annotations

import os
import random
import time
import uuid
from typing import Iterable, List, Optional, Tuple

from .registry import RegisteredTask, deserialize, serialize

LEASE_SEP = "--"
CONTENTION_WINDOW = 100


def iter_tasks(tasks):
  """Normalize an insert() argument to an iterator of single tasks.
  Strings/bytes/dicts are single payloads, not collections — shared by
  every queue backend so a payload-dict never gets iterated as keys."""
  if hasattr(tasks, "__iter__") and not isinstance(tasks, (str, bytes, dict)):
    return iter(tasks)
  return iter([tasks])


def poll_loop(
  queue,
  lease_seconds: float = 600,
  verbose: bool = False,
  stop_fn=None,
  max_backoff_window: float = 30.0,
  before_fn=None,
  after_fn=None,
):
  """Shared worker loop: lease→execute→delete until stop_fn says stop or
  the queue drains (stop_fn=None polls forever, sleeping with bounded
  backoff when empty). Used by every queue backend (fq://, sqs://) so
  execution semantics — at-least-once, recycle-on-failure — are uniform."""
  backoff = 1.0
  executed = 0
  while True:
    if stop_fn is not None and stop_fn(executed=executed, empty=False):
      return executed
    leased = queue.lease(lease_seconds)
    if leased is None:
      if stop_fn is not None and stop_fn(executed=executed, empty=True):
        return executed
      time.sleep(backoff + random.random())
      backoff = min(backoff * 2, max_backoff_window)
      continue
    backoff = 1.0
    task, lease_id = leased
    if verbose:
      print(f"Executing {task!r}")
    try:
      if before_fn:
        before_fn(task)
      task.execute()
      if after_fn:
        after_fn(task)
    except Exception:
      # leave the lease in place: the task recycles after the timeout
      # (at-least-once semantics; matches reference behavior on failure)
      if verbose:
        import traceback

        traceback.print_exc()
      continue
    queue.delete(lease_id)
    executed += 1


class FileQueue:
  def __init__(self, path: str):
    if path.startswith("fq://"):
      path = path[len("fq://"):]
    self.path = os.path.abspath(os.path.expanduser(path))
    self.queue_dir = os.path.join(self.path, "queue")
    self.lease_dir = os.path.join(self.path, "leased")
    os.makedirs(self.queue_dir, exist_ok=True)
    os.makedirs(self.lease_dir, exist_ok=True)

  # -- counters -------------------------------------------------------------

  def _tally(self, counter: str, n: int = 1):
    with open(os.path.join(self.path, counter), "ab") as f:
      f.write(b"\x01" * n)

  def _count(self, counter: str) -> int:
    try:
      return os.path.getsize(os.path.join(self.path, counter))
    except FileNotFoundError:
      return 0

  @property
  def inserted(self) -> int:
    return self._count("insertions")

  @property
  def completed(self) -> int:
    return self._count("completions")

  @property
  def enqueued(self) -> int:
    return len(os.listdir(self.queue_dir)) + len(os.listdir(self.lease_dir))

  @property
  def leased(self) -> int:
    return len(os.listdir(self.lease_dir))

  def lease_ages(self) -> List[float]:
    """Seconds until each outstanding lease expires (negative = overdue,
    will recycle on the next poll)."""
    now = time.time()
    out = []
    for name in os.listdir(self.lease_dir):
      try:
        out.append(float(name.split(LEASE_SEP, 1)[0]) - now)
      except ValueError:
        continue
    return sorted(out)

  def fsck(self, repair: bool = False) -> dict:
    """Consistency audit: undeserializable task files (the same check
    lease() applies), unparseable lease names, counter drift. With
    repair=True, malformed files move to ``<queue>/quarantine/`` and
    bad-name leases with VALID payloads recycle into the queue (corrupt
    ones are quarantined too)."""
    problems = {"malformed_tasks": [], "bad_lease_names": [],
                "counter_drift": self.inserted - self.completed - self.enqueued}
    quarantine_dir = os.path.join(self.path, "quarantine")

    def payload_ok(path: str):
      """None if a worker raced us; else (valid, contents)."""
      try:
        with open(path) as f:
          contents = f.read()
      except FileNotFoundError:
        return None  # leased/recycled mid-scan: healthy, skip
      try:
        deserialize(contents)  # exactly what lease() will do
        return (True, contents)
      except Exception:
        return (False, contents)

    def quarantine(path: str, name: str):
      os.makedirs(quarantine_dir, exist_ok=True)
      try:
        os.rename(path, os.path.join(quarantine_dir, name))
      except FileNotFoundError:
        pass

    for name in list(os.listdir(self.queue_dir)):
      path = os.path.join(self.queue_dir, name)
      result = payload_ok(path)
      if result is None or result[0]:
        continue
      problems["malformed_tasks"].append(name)
      if repair:
        quarantine(path, name)

    for name in list(os.listdir(self.lease_dir)):
      try:
        float(name.split(LEASE_SEP, 1)[0])
        continue  # well-formed lease
      except ValueError:
        pass
      problems["bad_lease_names"].append(name)
      if repair:
        path = os.path.join(self.lease_dir, name)
        result = payload_ok(path)
        if result is not None and result[0]:
          try:
            os.rename(path, os.path.join(self.queue_dir, name))
          except FileNotFoundError:
            pass
        elif result is not None:
          quarantine(path, name)
    return problems

  def is_empty(self) -> bool:
    return self.enqueued == 0

  def rezero(self):
    for counter in ("insertions", "completions"):
      try:
        os.remove(os.path.join(self.path, counter))
      except FileNotFoundError:
        pass

  # -- producer -------------------------------------------------------------

  def insert(self, tasks: Iterable, total: Optional[int] = None):
    del total
    n = 0
    for task in self._iter(tasks):
      payload = serialize(task)
      name = f"{uuid.uuid4().hex}.json"
      tmp = os.path.join(self.path, f".tmp-{name}")
      with open(tmp, "w") as f:
        f.write(payload)
      os.replace(tmp, os.path.join(self.queue_dir, name))
      n += 1
    self._tally("insertions", n)
    return n

  insert_all = insert

  _iter = staticmethod(lambda tasks: iter_tasks(tasks))

  # -- consumer -------------------------------------------------------------

  def _recycle_expired(self):
    now = time.time()
    for name in os.listdir(self.lease_dir):
      try:
        deadline = float(name.split(LEASE_SEP, 1)[0])
      except ValueError:
        continue
      if deadline < now:
        orig = name.split(LEASE_SEP, 1)[1]
        try:
          os.rename(
            os.path.join(self.lease_dir, name),
            os.path.join(self.queue_dir, orig),
          )
        except FileNotFoundError:
          pass  # another worker recycled it first

  def lease(self, seconds: float = 600) -> Optional[Tuple[RegisteredTask, str]]:
    """Returns (task, lease_id) or None if the queue is drained."""
    self._recycle_expired()
    for _ in range(10):  # bounded retries under contention
      names = sorted(os.listdir(self.queue_dir))
      if not names:
        return None
      name = random.choice(names[:CONTENTION_WINDOW])
      deadline = time.time() + seconds
      lease_name = f"{deadline:.3f}{LEASE_SEP}{name}"
      src = os.path.join(self.queue_dir, name)
      dst = os.path.join(self.lease_dir, lease_name)
      try:
        os.rename(src, dst)
      except FileNotFoundError:
        continue  # lost the race; try another
      with open(dst) as f:
        return deserialize(f.read()), lease_name
    return None

  def delete(self, lease_id: str):
    try:
      os.remove(os.path.join(self.lease_dir, lease_id))
    except FileNotFoundError:
      pass
    self._tally("completions")

  def release(self, lease_id: str):
    orig = lease_id.split(LEASE_SEP, 1)[1]
    try:
      os.rename(
        os.path.join(self.lease_dir, lease_id),
        os.path.join(self.queue_dir, orig),
      )
    except FileNotFoundError:
      pass

  def release_all(self):
    for name in list(os.listdir(self.lease_dir)):
      if LEASE_SEP in name:
        self.release(name)

  def purge(self):
    for d in (self.queue_dir, self.lease_dir):
      for name in list(os.listdir(d)):
        try:
          os.remove(os.path.join(d, name))
        except FileNotFoundError:
          pass
    self.rezero()

  # -- worker loop ----------------------------------------------------------

  def poll(
    self,
    lease_seconds: float = 600,
    verbose: bool = False,
    tally: bool = True,
    stop_fn=None,
    max_backoff_window: float = 30.0,
    before_fn=None,
    after_fn=None,
  ):
    """Lease→execute→delete until stop_fn says stop or the queue drains
    (stop_fn=None polls forever, sleeping with bounded backoff when empty)."""
    del tally  # completions are always tallied; kept for API familiarity
    return poll_loop(
      self, lease_seconds, verbose, stop_fn, max_backoff_window,
      before_fn, after_fn,
    )

  def __len__(self):
    return self.enqueued
