"""Lease-based filesystem task queue (``fq://``).

Behavioral parity with the reference's FileQueue (python-task-queue,
described at /root/reference/README.md:69-81): at-least-once delivery with a
visibility timeout — a leased task that is not deleted within its lease
returns to the pool; workers pick a random task among the first 100 to
avoid lease contention; completions are tallied 1 byte per task.

All state is plain files, so any shared POSIX filesystem (NFS, /mnt
volumes) works as the control plane across machines.

Failure containment (ISSUE 1): each task carries persisted attempt
metadata (``meta/<name>``: delivery count + recent failure reasons).
With ``max_deliveries`` configured, a task that keeps failing — by
raising, overrunning its deadline, or losing its worker — moves to the
``dlq/`` sidecar instead of re-entering rotation, where ``igneous queue
dlq ls|retry|purge`` can inspect, requeue, or drop it. The default
(``max_deliveries=None``) preserves the historical infinite-retry
at-least-once semantics.

Queue scale-out (ISSUE 15): the classic layout is one file + meta per
task, which goes quadratic-ish on listings at the tens-of-millions-of-
tasks campaigns the paper's grid sizes imply. ``insert_batch`` instead
writes **sharded metadata segments** — ``seg_<segid>_<count>.jsonl``
files holding up to ``IGNEOUS_QUEUE_SEG_TASKS`` tasks each (one line
``<index>\\t<payload>`` per task), sized so a batch lands in about
``IGNEOUS_QUEUE_SHARDS`` appends — and ``lease_batch`` leases a whole
segment as ONE :class:`~.ranges.RangeLease`. Depth reads stay
O(segments): task counts ride in the file names, completion tallies stay
1-byte-per-task counter files, and delivery counts key on the segment id
(stable across ack rewrites and splits). Per-task semantics survive
through sub-task accounting — see :mod:`.ranges`. Classic per-task files
and segments coexist freely in one queue directory, so pre-ISSUE-15
layouts keep reading.

Campaign survival (ISSUE 17): two sidecar protocols keep a hostile
fleet's tail from holding a campaign hostage, both dormant (zero reads,
zero writes) until first use — queues that never speculate or steal
read byte-for-byte unchanged.

* **Straggler speculation** (``spec/`` sidecar): :meth:`speculate_lease`
  double-issues the unfinished tail of a held range lease as a twin
  segment. First RESOLUTION wins: completing an index creates a
  per-index ``O_EXCL`` marker, and only the marker creator tallies the
  completion — the loser's late ack shrinks its lease *without*
  tallying, so completions never double-count. Exactly one of
  ``speculation.won`` (twin resolved first) / ``speculation.fenced``
  (original resolved first) increments per issued index, making
  ``won + fenced == issued`` an end-of-campaign invariant.
* **Work stealing** (``steal/`` sidecar): an idle worker claims a
  long-held range with :meth:`steal_claim` (``O_EXCL`` claim file =
  deterministic winner among racing thieves); the holder's next
  heartbeat renewal services the claim by releasing the unstarted tail
  of its range back to the pool through the expiry-fenced range-release
  seam, then removes the claim.

While a speculation pair is live, ``enqueued``/``backlog`` transiently
count both copies and ``fsck`` counter drift dips negative by the
twinned index count; both read exact again once the pair resolves.
"""

from __future__ import annotations

import json
import os
import random
import re
import time
import uuid
from typing import Dict, Iterable, List, Optional, Tuple

from .ranges import RangeLease, RangeSub
from .registry import RegisteredTask, deserialize, serialize

LEASE_SEP = "--"
CONTENTION_WINDOW = 100
MAX_RECORDED_FAILURES = 5  # per-task failure-reason ring (meta file bound)

SEG_PREFIX = "seg_"
SEG_SUFFIX = ".jsonl"
# defaults mirrored by the knobs registry (analysis/knobs.py)
DEFAULT_QUEUE_SHARDS = 16
DEFAULT_SEG_TASKS = 1024
DEFAULT_RECYCLE_SEC = 5.0
DEFAULT_SPECULATE_MIN_TASKS = 1
DEFAULT_SPECULATE_MAX_TWINS = 4
DEFAULT_SPECULATE_MIN_HELD_SEC = 0.0
DEFAULT_STEAL_MIN_TASKS = 2
DEFAULT_STEAL_MIN_HELD_SEC = 2.0
DEFAULT_STEAL_FRACTION = 0.5
DEFAULT_STEAL_CLAIM_TTL_SEC = 300.0

# mid-range failures / DLQ expansions carve per-index classic files
_CARVE_RE = re.compile(r"^task_([0-9a-f]+)_(\d+)\.json$")


def seg_parse(name: str) -> Optional[Tuple[str, int]]:
  """``seg_<segid>_<count>.jsonl`` → (segid, count); None for classic
  per-task file names. The count in the NAME is the task count in the
  file (maintained across ack rewrites), so depth reads never open
  segment files."""
  if not name.startswith(SEG_PREFIX) or not name.endswith(SEG_SUFFIX):
    return None
  parts = name[len(SEG_PREFIX):-len(SEG_SUFFIX)].rsplit("_", 1)
  if len(parts) != 2:
    return None
  try:
    return parts[0], int(parts[1])
  except ValueError:
    return None


def seg_name(segid: str, count: int) -> str:
  return f"{SEG_PREFIX}{segid}_{int(count)}{SEG_SUFFIX}"


def _name_tasks(name: str) -> int:
  """Tasks a queue/lease file name represents (lease prefixes allowed)."""
  parsed = seg_parse(name.split(LEASE_SEP, 1)[-1])
  return parsed[1] if parsed else 1


def _seg_content(entries) -> str:
  return "".join(f"{int(i)}\t{p}\n" for i, p in entries)


class TaskDeadlineError(Exception):
  """A task overran its per-delivery wall-clock deadline (poll_loop)."""


class StaleLeaseError(Exception):
  """The lease behind a renew/delete no longer belongs to this worker —
  it expired, or the queue re-issued the task to someone else. A worker
  seeing this is a *zombie* for that task: it must stop acting on it
  (the work itself is safe to discard — tasks are idempotent and the
  current owner will complete it)."""


def iter_tasks(tasks):
  """Normalize an insert() argument to an iterator of single tasks.
  Strings/bytes/dicts are single payloads, not collections — shared by
  every queue backend so a payload-dict never gets iterated as keys."""
  if hasattr(tasks, "__iter__") and not isinstance(tasks, (str, bytes, dict)):
    return iter(tasks)
  return iter([tasks])


def failure_reason(exc: BaseException) -> str:
  """One-line failure record shared by every containment path (poll_loop,
  the lease batcher, LocalTaskQueue) so DLQ entries read uniformly."""
  msg = str(exc)
  return f"{type(exc).__name__}: {msg}" if msg else type(exc).__name__


def run_with_deadline(fn, deadline_seconds: Optional[float]):
  """Run ``fn()`` with a wall-clock deadline. On overrun, raises
  TaskDeadlineError so the caller's failure bookkeeping (nack → DLQ)
  takes over. The overrunning call keeps executing on an abandoned
  daemon thread — it cannot be killed safely — which is sound here
  because tasks are idempotent and the lease it held stays failed."""
  if not deadline_seconds or deadline_seconds <= 0:
    return fn()
  import threading

  result = {}

  def body():
    try:
      result["value"] = fn()
    except BaseException as e:  # noqa: BLE001 - relayed to the caller
      result["error"] = e

  t = threading.Thread(target=body, daemon=True)
  t.start()
  t.join(deadline_seconds)
  if t.is_alive():
    raise TaskDeadlineError(
      f"task exceeded its {deadline_seconds:.1f}s deadline"
    )
  if "error" in result:
    raise result["error"]
  return result.get("value")


def poll_loop(
  queue,
  lease_seconds: float = 600,
  verbose: bool = False,
  stop_fn=None,
  max_backoff_window: float = 30.0,
  before_fn=None,
  after_fn=None,
  task_deadline_seconds: Optional[float] = None,
  heartbeat_seconds: Optional[float] = None,
  drain_flag=None,
):
  """Shared worker loop: lease→execute→delete until stop_fn says stop or
  the queue drains (stop_fn=None polls forever, sleeping with bounded
  backoff when empty). Used by every queue backend (fq://, sqs://) so
  execution semantics — at-least-once, recycle-on-failure — are uniform.

  Failure containment: an exception (or ``task_deadline_seconds``
  overrun) records its reason with the task via ``queue.nack`` when the
  backend supports it — feeding the same bookkeeping that promotes
  repeat offenders to the DLQ — and otherwise leaves the lease to
  recycle on its visibility timeout, exactly as before.

  Lifecycle (ISSUE 2): a heartbeat thread renews the held lease every
  ``heartbeat_seconds`` (default lease/3, env IGNEOUS_HEARTBEAT_SEC;
  <= 0 disables) so long tasks outlive a short ``--lease-sec`` without
  being double-executed. ``drain_flag`` (anything with ``is_set()``,
  e.g. lifecycle.StopFlag) requests graceful shutdown: the in-flight
  task finishes, no new lease is taken."""
  from .. import telemetry
  from ..observability import journal as journal_mod
  from ..observability import trace
  from .heartbeat import LeaseHeartbeat

  def draining() -> bool:
    return drain_flag is not None and drain_flag.is_set()

  def attempt_of(lease_id) -> Optional[int]:
    # fq:// persists delivery counts; SQS reports ApproximateReceiveCount
    try:
      if hasattr(queue, "delivery_count"):
        return int(queue.delivery_count(lease_id))
      if getattr(queue, "last_receive_count", 0):
        return int(queue.last_receive_count)
    except Exception:
      pass
    return None

  def idle(seconds: float):
    # wake early when a drain request lands mid-backoff
    if drain_flag is not None and hasattr(drain_flag, "wait"):
      drain_flag.wait(seconds)
    else:
      time.sleep(seconds)

  backoff = 1.0
  executed = 0
  hb = LeaseHeartbeat(queue, lease_seconds, interval=heartbeat_seconds)
  try:
   with hb:
    while True:
      # interval/drain-requested journal flush between tasks: the poll
      # loop IS the worker's main thread, so batches land without a
      # dedicated flusher thread
      journal_mod.maybe_flush_active()
      if draining():
        return executed
      if stop_fn is not None and stop_fn(executed=executed, empty=False):
        return executed
      leased = queue.lease(lease_seconds)
      if leased is None:
        if stop_fn is not None and stop_fn(executed=executed, empty=True):
          return executed
        if draining():
          return executed
        idle(backoff + random.random())
        backoff = min(backoff * 2, max_backoff_window)
        continue
      backoff = 1.0
      task, lease_id = leased
      key = hb.track(lease_id)
      if isinstance(lease_id, RangeSub):
        # stealing only carves UNSTARTED members; this one is in flight
        lease_id.mark_started()
      if verbose:
        print(f"Executing {task!r}")
      try:
        if before_fn:
          before_fn(task)
        # IGNEOUS_PIPELINE=1 opts the solo worker loop into tier-A
        # pipelining: the task's chunk encodes+puts thread on the shared
        # pool, joined before the lease delete below — completion
        # semantics are unchanged (execute_with_sink falls back to plain
        # execute() when the task has no stage plan or pipelining is off)
        from ..pipeline import execute_with_sink

        # the task span wraps this delivery: stage/storage spans on this
        # thread (and pool threads the upload ticket propagates to)
        # parent under it, attributed to the payload's trace
        with trace.task_span(
          task, attempt=attempt_of(lease_id), queue=type(queue).__name__
        ):
          run_with_deadline(
            lambda: execute_with_sink(task), task_deadline_seconds
          )
        if after_fn:
          after_fn(task)
      except Exception as e:
        # leave the lease in place: the task recycles after the timeout
        # (at-least-once semantics; matches reference behavior on failure).
        # nack records the reason and quarantines exhausted tasks.
        if verbose:
          import traceback

          traceback.print_exc()
        telemetry.incr("tasks.failed")
        current = hb.untrack(key)
        if hasattr(queue, "nack"):
          queue.nack(current, failure_reason(e))
        continue
      # untrack returns the CURRENT lease token (heartbeat renewals
      # re-timestamp fq:// lease names); delete is fenced against stale
      # tokens, so a zombie's late ack can never complete a re-issued task
      queue.delete(hb.untrack(key))
      executed += 1
  finally:
    # whatever ends the loop — drain, stop_fn, an unhandled error — the
    # pending span batch must not die with the worker
    journal_mod.flush_active(
      event="drain" if draining() else "poll_exit"
    )


class FileQueue:
  def __init__(self, path: str, max_deliveries: Optional[int] = None,
               worker_id: Optional[str] = None):
    """``max_deliveries``: after this many deliveries (leases), a task
    that fails again is quarantined in ``dlq/`` instead of recycling.
    None (default) keeps the historical infinite-retry behavior.

    ``worker_id`` names this consumer in segment lease metadata (the
    ``holder`` field speculation/steal planners target). Defaults to the
    journal's worker id so HealthEngine flags — which name journal
    workers — map straight onto lease holders; pass the same id given to
    :class:`~..observability.journal.Journal` when overriding one."""
    if path.startswith("fq://"):
      path = path[len("fq://"):]
    self.path = os.path.abspath(os.path.expanduser(path))
    self.queue_dir = os.path.join(self.path, "queue")
    self.lease_dir = os.path.join(self.path, "leased")
    self.dlq_dir = os.path.join(self.path, "dlq")
    self.meta_dir = os.path.join(self.path, "meta")
    # survival sidecars (ISSUE 17): created lazily on first use, so a
    # queue that never speculates/steals keeps its pre-ISSUE-17 layout
    self.spec_dir = os.path.join(self.path, "spec")
    self.steal_dir = os.path.join(self.path, "steal")
    self._worker_id = worker_id
    self.max_deliveries = (
      None if not max_deliveries or int(max_deliveries) <= 0
      else int(max_deliveries)
    )
    os.makedirs(self.queue_dir, exist_ok=True)
    os.makedirs(self.lease_dir, exist_ok=True)
    os.makedirs(self.dlq_dir, exist_ok=True)
    os.makedirs(self.meta_dir, exist_ok=True)
    # cached per-shard pending index (lease picks from here instead of a
    # full listdir+sort per acquisition) and the recycle-scan throttle
    self._pending_cache: Optional[List[str]] = None
    self._last_recycle = 0.0

  @property
  def worker_id(self) -> str:
    if self._worker_id is None:
      from ..observability.journal import default_worker_id

      self._worker_id = default_worker_id()
    return self._worker_id

  # -- per-task attempt metadata --------------------------------------------

  def _meta_path(self, name: str) -> str:
    return os.path.join(self.meta_dir, name)

  @staticmethod
  def _meta_key(name_or_lease: str) -> str:
    """Meta file key for a queue/lease/dlq name. Segments key on the
    SEGID (``seg_<segid>``) so ack rewrites — which change the count in
    the file name — never orphan the delivery-count record."""
    name = str(name_or_lease).split(LEASE_SEP, 1)[-1]
    parsed = seg_parse(name)
    return f"{SEG_PREFIX}{parsed[0]}" if parsed else name

  def _read_meta(self, name: str) -> dict:
    try:
      with open(self._meta_path(name)) as f:
        return json.load(f)
    except (FileNotFoundError, ValueError):
      return {"deliveries": 0, "failures": []}

  def _write_meta(self, name: str, meta: dict):
    tmp = os.path.join(self.path, f".tmp-meta-{uuid.uuid4().hex}")
    try:
      with open(tmp, "w") as f:
        json.dump(meta, f)
      os.replace(tmp, self._meta_path(name))
    except BaseException:
      # same turd-free contract as storage put(): a failed write must not
      # leave .tmp-* files accumulating next to the counters
      try:
        os.remove(tmp)
      except FileNotFoundError:
        pass
      raise

  def _drop_meta(self, name: str):
    try:
      os.remove(self._meta_path(name))
    except FileNotFoundError:
      pass

  def _record_failure(self, name: str, reason: str) -> dict:
    meta = self._read_meta(name)
    meta.setdefault("failures", []).append({
      "time": time.time(), "error": str(reason)[:2000],
    })
    meta["failures"] = meta["failures"][-MAX_RECORDED_FAILURES:]
    self._write_meta(name, meta)
    return meta

  def delivery_count(self, name_or_lease) -> int:
    """Deliveries so far for a task (by queue filename, lease id, or
    range-member handle) — the fq:// analogue of SQS's
    ApproximateReceiveCount. Range members report the shared segment's
    delivery count until a failure splits them out solo."""
    if isinstance(name_or_lease, RangeSub):
      key = f"{SEG_PREFIX}{name_or_lease.parent.segid}"
    else:
      key = self._meta_key(name_or_lease)
    return int(self._read_meta(key).get("deliveries", 0))

  def _exhausted(self, name: str) -> bool:
    return (
      self.max_deliveries is not None
      and self.delivery_count(name) >= self.max_deliveries
    )

  # -- dead-letter queue ----------------------------------------------------

  def _quarantine_to_dlq(self, src_path: str, name: str, reason: str):
    """Move a task file into dlq/ (terminal until an operator intervenes).
    The meta file stays: it holds the delivery count + failure reasons
    that `dlq ls` reports."""
    self._record_failure(name, reason)
    try:
      os.rename(src_path, os.path.join(self.dlq_dir, name))
    except FileNotFoundError:
      return  # another worker moved it first
    from .. import telemetry

    telemetry.incr("dlq.promoted")

  @property
  def dlq_count(self) -> int:
    return len(os.listdir(self.dlq_dir))

  def dlq_ls(self) -> List[dict]:
    """One record per quarantined task: name, payload (JSON string),
    delivery count, and the recorded failure reasons (newest last)."""
    out = []
    for name in sorted(os.listdir(self.dlq_dir)):
      try:
        with open(os.path.join(self.dlq_dir, name)) as f:
          payload = f.read()
      except FileNotFoundError:
        continue
      meta = self._read_meta(name)
      out.append({
        "name": name,
        "payload": payload,
        "deliveries": int(meta.get("deliveries", 0)),
        "failures": meta.get("failures", []),
      })
    return out

  def dlq_retry(self, names: Optional[Iterable[str]] = None) -> int:
    """Return quarantined tasks to rotation (all, or just ``names``),
    resetting their delivery counts so they get a fresh budget."""
    if names is None:
      names = sorted(os.listdir(self.dlq_dir))
    n = 0
    for name in names:
      src = os.path.join(self.dlq_dir, name)
      try:
        os.rename(src, os.path.join(self.queue_dir, name))
      except FileNotFoundError:
        continue
      meta = self._read_meta(name)
      meta["deliveries"] = 0
      self._write_meta(name, meta)
      n += 1
    self._pending_cache = None
    return n

  def dlq_purge(self) -> int:
    """Drop all quarantined tasks (and their metadata). Irreversible."""
    n = 0
    for name in list(os.listdir(self.dlq_dir)):
      try:
        os.remove(os.path.join(self.dlq_dir, name))
        n += 1
      except FileNotFoundError:
        continue
      finally:
        self._drop_meta(name)
    return n

  # -- counters -------------------------------------------------------------

  def _tally(self, counter: str, n: int = 1):
    with open(os.path.join(self.path, counter), "ab") as f:
      f.write(b"\x01" * n)

  def _count(self, counter: str) -> int:
    try:
      return os.path.getsize(os.path.join(self.path, counter))
    except FileNotFoundError:
      return 0

  @property
  def inserted(self) -> int:
    return self._count("insertions")

  @property
  def completed(self) -> int:
    return self._count("completions")

  @property
  def speculation_won(self) -> int:
    """Crash-safe count of pair indices the TWIN resolved first."""
    return self._count("speculation_won")

  @property
  def speculation_fenced(self) -> int:
    """Crash-safe count of pair indices the ORIGINAL resolved first."""
    return self._count("speculation_fenced")

  @property
  def enqueued(self) -> int:
    """Tasks in rotation (queued + leased). O(segments) — segment task
    counts ride in the file names, so no segment file is ever opened."""
    return (
      sum(_name_tasks(n) for n in os.listdir(self.queue_dir))
      + sum(_name_tasks(n) for n in os.listdir(self.lease_dir))
    )

  @property
  def leased(self) -> int:
    return sum(_name_tasks(n) for n in os.listdir(self.lease_dir))

  @property
  def queue_files(self) -> int:
    """Control-plane objects backing the pending pool — O(shards) per
    batch-inserted campaign, vs O(tasks) for the classic layout (the
    `queue status`/smoke-gate scalability signal)."""
    return len(os.listdir(self.queue_dir))

  def lease_ages(self) -> List[float]:
    """Seconds until each outstanding lease expires (negative = overdue,
    will recycle on the next poll)."""
    now = time.time()
    out = []
    for name in os.listdir(self.lease_dir):
      try:
        out.append(float(name.split(LEASE_SEP, 1)[0]) - now)
      except ValueError:
        continue
    return sorted(out)

  @property
  def stale_leases(self) -> int:
    """Leases past expiry that no poll has recycled yet — the queue's
    zombie pressure: each one is a worker that died, hung, or stopped
    heartbeating (`igneous queue status` surfaces this)."""
    return sum(1 for age in self.lease_ages() if age < 0)

  @property
  def backlog(self) -> int:
    """Work remaining (queued + leased, DLQ excluded) — the autoscaler's
    demand signal (ISSUE 6)."""
    return self.enqueued

  def depth_snapshot(self) -> dict:
    """One consistent-ish read of every depth the health plane consumes
    (listing races are possible; each field is individually truthful)."""
    leased = self.leased
    return {
      "inserted": self.inserted,
      "enqueued": self.enqueued,
      "leased": leased,
      "completed": self.completed,
      "backlog": self.backlog,
      "dlq": self.dlq_count,
      "stale_leases": self.stale_leases,
    }

  def reset_deliveries(self) -> int:
    """Zero the delivery count of every task still in rotation (queued or
    leased) so a ``max_deliveries`` budget starts fresh — the operator
    re-arm after a bad deploy burned deliveries on healthy tasks. DLQ'd
    tasks keep their counts (``dlq retry`` already grants fresh budgets)."""
    n = 0
    quarantined = set(os.listdir(self.dlq_dir))
    for name in list(os.listdir(self.meta_dir)):
      if name in quarantined:
        continue
      meta = self._read_meta(name)
      if not meta.get("deliveries"):
        continue
      meta["deliveries"] = 0
      self._write_meta(name, meta)
      n += 1
    return n

  def fsck(self, repair: bool = False) -> dict:
    """Consistency audit: undeserializable task files (the same check
    lease() applies), unparseable lease names, counter drift. With
    repair=True, malformed files move to ``<queue>/quarantine/`` and
    bad-name leases with VALID payloads recycle into the queue (corrupt
    ones are quarantined too)."""
    problems = {"malformed_tasks": [], "bad_lease_names": [],
                "counter_drift": (self.inserted - self.completed
                                  - self.enqueued - self.dlq_count)}
    quarantine_dir = os.path.join(self.path, "quarantine")

    def payload_ok(path: str):
      """None if a worker raced us; else (valid, contents)."""
      try:
        with open(path) as f:
          contents = f.read()
      except FileNotFoundError:
        return None  # leased/recycled mid-scan: healthy, skip
      try:
        deserialize(contents)  # exactly what lease() will do
        return (True, contents)
      except Exception:
        return (False, contents)

    def quarantine(path: str, name: str):
      os.makedirs(quarantine_dir, exist_ok=True)
      try:
        os.rename(path, os.path.join(quarantine_dir, name))
      except FileNotFoundError:
        pass

    def segment_ok(path: str, count: int):
      """None if raced; else whether every line deserializes AND the
      task count in the name matches the file (depth reads trust it)."""
      try:
        entries = self._read_segment(path)
      except FileNotFoundError:
        return None
      except Exception:
        return False
      if len(entries) != count:
        return False
      try:
        for _i, p in entries:
          deserialize(p)
      except Exception:
        return False
      return True

    for name in list(os.listdir(self.queue_dir)):
      path = os.path.join(self.queue_dir, name)
      parsed = seg_parse(name)
      if parsed is not None:
        ok = segment_ok(path, parsed[1])
        if ok is None or ok:
          continue
      else:
        result = payload_ok(path)
        if result is None or result[0]:
          continue
      problems["malformed_tasks"].append(name)
      if repair:
        quarantine(path, name)

    for name in list(os.listdir(self.lease_dir)):
      try:
        float(name.split(LEASE_SEP, 1)[0])
        continue  # well-formed lease
      except ValueError:
        pass
      problems["bad_lease_names"].append(name)
      if repair:
        path = os.path.join(self.lease_dir, name)
        result = payload_ok(path)
        if result is not None and result[0]:
          try:
            os.rename(path, os.path.join(self.queue_dir, name))
          except FileNotFoundError:
            pass
        elif result is not None:
          quarantine(path, name)
    return problems

  def is_empty(self) -> bool:
    return self.enqueued == 0

  def rezero(self):
    for counter in ("insertions", "completions"):
      try:
        os.remove(os.path.join(self.path, counter))
      except FileNotFoundError:
        pass

  # -- segment I/O ----------------------------------------------------------

  def _write_file(self, dirpath: str, name: str, content: str):
    """tmp-write + atomic rename with the same turd-free contract as
    insert()/_write_meta."""
    tmp = os.path.join(self.path, f".tmp-{uuid.uuid4().hex}")
    try:
      with open(tmp, "w") as f:
        f.write(content)
      os.replace(tmp, os.path.join(dirpath, name))
    except BaseException:
      try:
        os.remove(tmp)
      except FileNotFoundError:
        pass
      raise

  @staticmethod
  def _read_segment(path: str) -> List[Tuple[int, str]]:
    """Segment file → [(task_index, payload)] (payloads are single-line
    JSON, so one line per task). Raises FileNotFoundError on lease races
    like every other read here; malformed lines raise ValueError for
    fsck to catch."""
    entries = []
    with open(path) as f:
      for line in f:
        line = line.rstrip("\n")
        if not line:
          continue
        idx, payload = line.split("\t", 1)
        entries.append((int(idx), payload))
    return entries

  def _copy_meta(self, src_segid: str, dst_segid: str):
    """Splits inherit the parent segment's attempt record, so per-task
    DLQ attribution survives any number of lease splits. Speculation
    pair membership (ISSUE 17) rides along too — a twin tail split off
    at the batch cap (or a stolen/released remainder) must keep routing
    its acks through first-resolution marker arbitration, else the two
    copies of an index would BOTH tally. Holder identity does not copy:
    the split lands pending, owned by whoever leases it next."""
    meta = self._read_meta(f"{SEG_PREFIX}{src_segid}")
    meta.pop("holder", None)
    meta.pop("leased_at", None)
    if not meta.get("spec") and self._spec_active():
      spec = self._spec_of(src_segid)   # heals a clobbered orig meta
      if spec is not None:
        meta["spec"] = spec
    if meta.get("deliveries") or meta.get("failures") or meta.get("spec"):
      self._write_meta(f"{SEG_PREFIX}{dst_segid}", meta)
    spec = meta.get("spec")
    if isinstance(spec, dict) and spec.get("pair"):
      # lineage marker: until this descendant drains, the pair's done
      # markers must survive — a GC that only tracked the two original
      # segids would collect them and let a lingering copy re-tally
      try:
        fd = os.open(
          self._spec_path(f"side_{spec['pair']}_{dst_segid}"),
          os.O_CREAT | os.O_WRONLY,
        )
        os.close(fd)
      except OSError:
        pass

  # -- producer -------------------------------------------------------------

  def insert(self, tasks: Iterable, total: Optional[int] = None):
    """Classic one-file-per-task insert (kept verbatim for layout
    compatibility; batched producers should call :meth:`insert_batch`)."""
    del total
    n = 0
    for task in self._iter(tasks):
      payload = serialize(task)
      name = f"{uuid.uuid4().hex}.json"
      tmp = os.path.join(self.path, f".tmp-{name}")
      try:
        with open(tmp, "w") as f:
          f.write(payload)
        os.replace(tmp, os.path.join(self.queue_dir, name))
      except BaseException:
        try:
          os.remove(tmp)
        except FileNotFoundError:
          pass
        raise
      n += 1
    self._tally("insertions", n)
    self._pending_cache = None
    return n

  def insert_batch(self, tasks: Iterable, total: Optional[int] = None):
    """Batched wire protocol (ISSUE 15): tasks land in segment files of
    up to ``IGNEOUS_QUEUE_SEG_TASKS`` tasks each — one append per
    segment instead of one file + meta per task. ``total`` (when the
    producer knows it, e.g. a regular grid's task count) sizes segments
    so the batch spreads across ~``IGNEOUS_QUEUE_SHARDS`` files for
    lease-contention spread; unknown totals stream at the per-segment
    cap. ``IGNEOUS_QUEUE_SEG_TASKS=0`` falls back to the classic
    per-task layout."""
    from ..analysis import knobs

    seg_cap = knobs.get_int("IGNEOUS_QUEUE_SEG_TASKS")
    seg_cap = DEFAULT_SEG_TASKS if seg_cap is None else int(seg_cap)
    if seg_cap <= 0:
      return self.insert(tasks, total=total)
    shards = knobs.get_int("IGNEOUS_QUEUE_SHARDS")
    shards = max(int(shards or DEFAULT_QUEUE_SHARDS), 1)
    if total:
      seg_size = min(max(-(-int(total) // shards), 1), seg_cap)
    else:
      seg_size = seg_cap
    base = self.inserted   # global task indices continue across batches
    n = 0
    chunk: List[Tuple[int, str]] = []

    def flush():
      nonlocal chunk
      if chunk:
        self._write_file(
          self.queue_dir, seg_name(uuid.uuid4().hex, len(chunk)),
          _seg_content(chunk),
        )
        chunk = []

    for task in self._iter(tasks):
      payload = task if isinstance(task, str) else serialize(task)
      chunk.append((base + n, payload))
      n += 1
      if len(chunk) >= seg_size:
        flush()
    flush()
    self._tally("insertions", n)
    self._pending_cache = None
    return n

  insert_all = insert

  _iter = staticmethod(lambda tasks: iter_tasks(tasks))

  # -- consumer -------------------------------------------------------------

  def _recycle_expired(self, force: bool = False) -> int:
    """Return expired leases to rotation. Throttled to one lease-dir scan
    per ``IGNEOUS_QUEUE_RECYCLE_SEC`` (0 = scan on every call) — the full
    scan dominated small-task lease latency. ``force=True`` bypasses the
    throttle (used when the pending pool looks drained, so an
    emptied-but-expired queue never reads as done). Returns the number of
    files returned to the pool."""
    from ..analysis import knobs

    now = time.time()
    if not force:
      interval = knobs.get_float("IGNEOUS_QUEUE_RECYCLE_SEC")
      interval = DEFAULT_RECYCLE_SEC if interval is None else float(interval)
      if interval > 0 and now - self._last_recycle < interval:
        return 0
    self._last_recycle = now
    n = 0
    for name in os.listdir(self.lease_dir):
      try:
        deadline = float(name.split(LEASE_SEP, 1)[0])
      except ValueError:
        continue
      if deadline >= now:
        continue
      orig = name.split(LEASE_SEP, 1)[1]
      src = os.path.join(self.lease_dir, name)
      if self._exhausted(orig):
        # the worker that held this lease died (or never acked): the
        # lease expiring IS the failure signal for its final delivery
        reason = (
          f"lease expired after delivery {self.delivery_count(orig)} "
          f"(worker lost or task hung)"
        )
        parsed = seg_parse(orig)
        if parsed:
          self._expire_segment_to_dlq(src, parsed[0], reason)
        else:
          self._quarantine_to_dlq(src, orig, reason)
        continue
      try:
        os.rename(src, os.path.join(self.queue_dir, orig))
      except FileNotFoundError:
        continue  # another worker recycled it first
      n += 1
      if self._pending_cache is not None:
        self._pending_cache.append(orig)
    if os.path.isdir(self.spec_dir) or os.path.isdir(self.steal_dir):
      self._survival_gc(now)
    return n

  def _expire_segment_to_dlq(self, src: str, segid: str, reason: str):
    """A segment that exhausted its delivery budget quarantines
    per-index: every surviving member becomes its own ``dlq/`` entry
    (``task_<segid>_<idx>.json``) carrying the shared attempt record, so
    `dlq ls|retry` keep their per-task granularity. Deterministic names
    make a racing double-expansion idempotent; dlq files land before the
    lease file is removed, so a crash mid-expansion re-runs cleanly."""
    from .. import telemetry

    try:
      entries = self._read_segment(src)
    except FileNotFoundError:
      return  # another worker expanded it first
    seg_meta = self._read_meta(f"{SEG_PREFIX}{segid}")
    spec = seg_meta.get("spec") if self._spec_active() else None
    for idx, payload in entries:
      if spec and self._spec_resolved(spec["pair"], idx):
        # the pair's other copy already completed (and tallied) this
        # index — dropping it is the resolution, not a quarantine
        self._spec_collapse(None, None, 1)
        continue
      name = f"task_{segid}_{idx}.json"
      meta = self._read_meta(name)
      meta["deliveries"] = max(
        int(meta.get("deliveries", 0)), int(seg_meta.get("deliveries", 0))
      )
      meta["failures"] = (
        seg_meta.get("failures", []) + meta.get("failures", [])
      )[-MAX_RECORDED_FAILURES:]
      if spec:
        meta["spec"] = spec
      self._write_meta(name, meta)
      self._record_failure(name, reason)
      self._write_file(self.dlq_dir, name, payload)
      telemetry.incr("dlq.promoted")
    try:
      os.remove(src)
    except FileNotFoundError:
      pass
    self._drop_meta(f"{SEG_PREFIX}{segid}")

  def _pop_pending(self) -> Optional[str]:
    """Pick a pending name from the cached per-shard index — the random-
    within-window contention dodge of the classic lease(), without the
    listdir+sort per acquisition. The cache is reverse-sorted so the
    window sits at the tail for O(1) pops."""
    cache = self._pending_cache
    if not cache:
      return None
    window = min(len(cache), CONTENTION_WINDOW)
    return cache.pop(len(cache) - 1 - random.randrange(window))

  def _lease_one(self, name: str, seconds: float, cap: int):
    """Acquire one pending file (rename = the mutex). A classic per-task
    file leases whole; a segment leases as a :class:`RangeLease`, split
    at ``cap`` members — the remainder returns to the pool under a new
    segid (attempt meta copied) BEFORE the lease shrinks, so a crash
    between duplicates deliveries but never loses tasks. Returns a list
    of (task, token) pairs, None when the rename race was lost, or []
    when the file held only speculation-resolved indices (already
    completed by the pair's other copy) and collapsed to nothing."""
    deadline = time.time() + seconds
    lease_name = f"{deadline:.3f}{LEASE_SEP}{name}"
    src = os.path.join(self.queue_dir, name)
    dst = os.path.join(self.lease_dir, lease_name)
    try:
      os.rename(src, dst)
    except FileNotFoundError:
      return None  # lost the race; caller tries another
    parsed = seg_parse(name)
    if parsed is None:
      spec = self._spec_of_name(name) if self._spec_active() else None
      if spec is not None:
        carve = _CARVE_RE.match(name)
        if carve and self._spec_resolved(spec["pair"], int(carve.group(2))):
          # the pair's other copy already completed (and tallied) this
          # index — drop the duplicate instead of delivering it
          self._spec_collapse(dst, name, 1)
          return []
      meta = self._read_meta(name)
      meta["deliveries"] = int(meta.get("deliveries", 0)) + 1
      self._write_meta(name, meta)
      with open(dst) as f:
        return [(deserialize(f.read()), lease_name)]
    segid = parsed[0]
    entries = self._read_segment(dst)
    if self._spec_active():
      spec = self._spec_of(segid)
      if spec is not None:
        live = [
          (i, p) for i, p in entries
          if not self._spec_resolved(spec["pair"], i)
        ]
        if len(live) != len(entries):
          self._spec_collapse(None, None, len(entries) - len(live))
          if not live:
            try:
              os.remove(dst)
            except FileNotFoundError:
              pass
            self._drop_meta(f"{SEG_PREFIX}{segid}")
            return []
          new_lease = f"{deadline:.3f}{LEASE_SEP}{seg_name(segid, len(live))}"
          self._write_file(self.lease_dir, new_lease, _seg_content(live))
          try:
            os.remove(dst)
          except FileNotFoundError:
            pass
          lease_name, entries = new_lease, live
          dst = os.path.join(self.lease_dir, lease_name)
    cap = max(int(cap), 1)
    if len(entries) > cap:
      keep, rest = entries[:cap], entries[cap:]
      rest_segid = uuid.uuid4().hex
      self._copy_meta(segid, rest_segid)
      rest_name = seg_name(rest_segid, len(rest))
      self._write_file(self.queue_dir, rest_name, _seg_content(rest))
      if self._pending_cache is not None:
        # next pop likely continues the contiguous run on this worker
        self._pending_cache.append(rest_name)
      lease_name_new = f"{deadline:.3f}{LEASE_SEP}{seg_name(segid, len(keep))}"
      self._write_file(self.lease_dir, lease_name_new, _seg_content(keep))
      try:
        os.remove(dst)
      except FileNotFoundError:
        pass
      lease_name, entries = lease_name_new, keep
    meta = self._read_meta(f"{SEG_PREFIX}{segid}")
    meta["deliveries"] = int(meta.get("deliveries", 0)) + 1
    # holder identity: what speculation targets (flagged worker -> its
    # leases) and stealing filters (a thief never claims its own range)
    meta["holder"] = self.worker_id
    meta["leased_at"] = round(time.time(), 3)
    self._write_meta(f"{SEG_PREFIX}{segid}", meta)
    rl = RangeLease(self, lease_name, segid, dict(entries), deadline)
    return [(deserialize(p), RangeSub(rl, i)) for i, p in entries]

  def lease_batch(self, seconds: float = 600, max_tasks: int = 1):
    """Lease up to ``max_tasks`` tasks in one call. Segments come back as
    range members — (task, :class:`RangeSub`) pairs sharing one
    underlying lease — classic files as (task, lease_id) pairs; the two
    mix freely in one result. Returns [] when the queue is drained."""
    self._recycle_expired()
    out: List[Tuple[RegisteredTask, object]] = []
    refreshed = False
    races = 0
    while len(out) < max_tasks and races < 10:
      name = self._pop_pending()
      if name is None:
        if refreshed:
          break
        # cache drained: force a recycle pass (the throttle must not make
        # an emptied-but-expired queue look drained), then re-list once
        self._recycle_expired(force=True)
        self._pending_cache = sorted(os.listdir(self.queue_dir), reverse=True)
        refreshed = True
        continue
      got = self._lease_one(name, seconds, max_tasks - len(out))
      if got is None:
        races += 1
        continue
      out.extend(got)
    return out

  def lease(self, seconds: float = 600) -> Optional[Tuple[RegisteredTask, str]]:
    """Returns (task, lease_id) or None if the queue is drained. On a
    segmented queue the single task splits off its segment, so solo
    pollers interoperate with batch producers."""
    got = self.lease_batch(seconds, max_tasks=1)
    return got[0] if got else None

  def _lease_deadline(self, lease_id: str) -> Optional[float]:
    try:
      return float(str(lease_id).split(LEASE_SEP, 1)[0])
    except ValueError:
      return None

  def renew(self, lease_id, seconds: float = 600):
    """Extend a held lease's visibility timeout (the fq:// analogue of
    SQS ChangeMessageVisibility) by re-timestamping the lease name.
    Returns the NEW lease token — the old one is dead; callers (normally
    a LeaseHeartbeat) must use the returned token from here on. A range
    member renews its parent's ONE lease and returns the same handle:
    RangeSub tokens are stable across renewals (rotation is internal).

    Zombie fencing: renewal is refused (StaleLeaseError + ``zombie.renew``
    counter) once the lease has expired or the task was re-issued — a
    stalled worker that wakes up cannot re-acquire what it lost."""
    from .. import telemetry

    if isinstance(lease_id, RangeSub):
      self._range_renew(lease_id.parent, seconds)
      return lease_id
    deadline = self._lease_deadline(lease_id)
    orig = str(lease_id).split(LEASE_SEP, 1)[-1]
    if deadline is None or deadline < time.time():
      telemetry.incr("zombie.renew")
      raise StaleLeaseError(
        f"lease for {orig!r} already expired; the task is due for re-issue"
      )
    new_id = f"{time.time() + seconds:.3f}{LEASE_SEP}{orig}"
    try:
      os.rename(
        os.path.join(self.lease_dir, lease_id),
        os.path.join(self.lease_dir, new_id),
      )
    except FileNotFoundError:
      telemetry.incr("zombie.renew")
      raise StaleLeaseError(
        f"lease for {orig!r} was re-issued (or completed) by another worker"
      ) from None
    return new_id

  def delete(self, lease_id) -> bool:
    """Complete a task. Zombie-fenced: the delete (and its completion
    tally) only lands while the lease token is current — a worker that
    stalled past its lease and woke after the task was re-issued gets
    False + a ``zombie.delete`` counter instead of double-completing
    (the acceptance invariant: completions tally == task count). A range
    member acks its sub-range: the parent lease shrinks by one index."""
    from .. import telemetry

    if isinstance(lease_id, RangeSub):
      return self._range_ack(lease_id.parent, lease_id.index)
    deadline = self._lease_deadline(lease_id)
    if deadline is not None and deadline < time.time():
      telemetry.incr("zombie.delete")
      return False
    orig = str(lease_id).split(LEASE_SEP, 1)[-1]
    try:
      os.remove(os.path.join(self.lease_dir, lease_id))
    except FileNotFoundError:
      telemetry.incr("zombie.delete")
      return False
    spec = self._spec_of_name(orig) if self._spec_active() else None
    self._drop_meta(orig)
    if spec is not None:
      # a speculated index carved out as a classic task: the O_EXCL
      # marker arbitrates the tally exactly as in _range_ack_many
      carve = _CARVE_RE.match(orig)
      idx = int(carve.group(2)) if carve else None
      if idx is not None:
        if not self._spec_mark_first(spec["pair"], idx):
          self._spec_wasted(spec, 1)
          return False  # pair's other copy completed (and tallied) it
        self._spec_account_first(spec, 1)
    self._tally("completions")
    return True

  def nack(self, lease_id, reason: str = "", requeue: bool = False):
    """Record a failed delivery. The failure reason persists with the
    task's metadata; once ``max_deliveries`` is exhausted the task moves
    to ``dlq/``. Otherwise the lease is left to recycle on its visibility
    timeout (at-least-once semantics unchanged) unless ``requeue=True``
    returns it to rotation immediately. A range member nack SPLITS the
    lease: only the failed index retries (or dead-letters).

    A nack whose lease was already re-issued (or completed) is dropped
    with a ``zombie.nack`` counter — recording it would resurrect meta
    for a task this worker no longer owns."""
    if isinstance(lease_id, RangeSub):
      return self._range_nack(
        lease_id.parent, lease_id.index, reason, requeue=requeue
      )
    orig = lease_id.split(LEASE_SEP, 1)[-1]
    src = os.path.join(self.lease_dir, lease_id)
    if not os.path.exists(src):
      from .. import telemetry

      telemetry.incr("zombie.nack")
      return
    if self._exhausted(orig):
      self._quarantine_to_dlq(src, orig, reason)  # records the reason
    else:
      self._record_failure(orig, reason)
      if requeue:
        self.release(lease_id)

  def release(self, lease_id):
    """Return a lease to rotation immediately (undelivered). A range
    member releases just its index back as a fresh one-task segment."""
    if isinstance(lease_id, RangeSub):
      return self._range_release(lease_id.parent, [lease_id.index])
    orig = lease_id.split(LEASE_SEP, 1)[1]
    try:
      os.rename(
        os.path.join(self.lease_dir, lease_id),
        os.path.join(self.queue_dir, orig),
      )
    except FileNotFoundError:
      return
    if self._pending_cache is not None:
      self._pending_cache.append(orig)

  def release_all(self):
    for name in list(os.listdir(self.lease_dir)):
      if LEASE_SEP in name:
        self.release(name)
    self._pending_cache = None

  # -- batched completion ----------------------------------------------------

  def ack_batch(self, tokens) -> List[bool]:
    """Complete many tasks at once. Range members sharing a parent lease
    collapse into ONE lease-file rewrite; classic tokens delete one by
    one. Results align positionally with ``tokens`` (False = zombie-
    fenced, exactly as the scalar ops report it)."""
    tokens = list(tokens)
    results = [False] * len(tokens)
    by_parent: Dict[int, Tuple[RangeLease, List[Tuple[int, int]]]] = {}
    for pos, tok in enumerate(tokens):
      if isinstance(tok, RangeSub):
        by_parent.setdefault(id(tok.parent), (tok.parent, []))[1].append(
          (pos, tok.index)
        )
      else:
        results[pos] = self.delete(tok)
    for parent, members in by_parent.values():
      acked = self._range_ack_many(parent, [i for _, i in members])
      for pos, i in members:
        results[pos] = bool(acked.get(int(i)))
    return results

  def nack_batch(self, tokens, reason: str = "", requeue: bool = False):
    """Fail many deliveries with one call (per-token semantics identical
    to scalar ``nack``: range members split, exhausted tasks DLQ)."""
    for tok in tokens:
      self.nack(tok, reason, requeue=requeue)

  # -- range-lease mechanics (handles live in .ranges) -----------------------

  def _range_rewrite(self, rl: RangeLease, new_entries: Dict[int, str],
                     new_deadline: Optional[float] = None) -> bool:
    """Swap the range's lease file for one holding ``new_entries``
    (removed outright when empty). Write-new-then-remove-old: a crash in
    between re-delivers, never loses. False = the old lease file was
    gone (expired + re-issued, or completed elsewhere) — the new file is
    withdrawn and the caller is a zombie for this range. Caller holds
    ``rl.lock``."""
    deadline = rl.deadline if new_deadline is None else float(new_deadline)
    old = os.path.join(self.lease_dir, rl.token)
    if not new_entries:
      try:
        os.remove(old)
      except FileNotFoundError:
        return False
      rl.entries = {}
      return True
    new_token = f"{deadline:.3f}{LEASE_SEP}{seg_name(rl.segid, len(new_entries))}"
    if new_token == rl.token:
      rl.entries = dict(new_entries)
      return True
    self._write_file(
      self.lease_dir, new_token, _seg_content(sorted(new_entries.items()))
    )
    try:
      os.remove(old)
    except FileNotFoundError:
      try:
        os.remove(os.path.join(self.lease_dir, new_token))
      except FileNotFoundError:
        pass
      return False
    rl.token = new_token
    rl.entries = dict(new_entries)
    rl.deadline = deadline
    return True

  def _range_ack_many(self, rl: RangeLease, indices) -> Dict[int, bool]:
    """Complete several members of one range with a single rewrite."""
    from .. import telemetry

    todo = [int(i) for i in indices]
    with rl.lock:
      if rl.deadline < time.time():
        telemetry.incr("zombie.delete", len(todo))
        return {i: False for i in todo}
      hit = sorted({i for i in todo if i in rl.entries})
      miss = [i for i in todo if i not in rl.entries]
      if miss:
        telemetry.incr("zombie.delete", len(miss))
      if not hit:
        return {i: False for i in todo}
      remaining = {i: p for i, p in rl.entries.items() if i not in set(hit)}
      if not self._range_rewrite(rl, remaining):
        telemetry.incr("zombie.delete", len(hit))
        return {i: False for i in todo}
      # first-RESOLUTION-wins (ISSUE 17): with a live speculation pair,
      # the per-index O_EXCL marker — attempted only AFTER the rewrite
      # proved this worker still owns its copy — arbitrates the tally.
      # Exactly one side creates each marker (and tallies); the loser's
      # ack shrank its lease above but tallies nothing.
      spec = self._spec_of(rl.segid) if self._spec_active() else None
      if spec is None:
        first = hit
      else:
        first = [i for i in hit if self._spec_mark_first(spec["pair"], i)]
        if first:
          self._spec_account_first(spec, len(first))
        if len(first) != len(hit):
          self._spec_wasted(spec, len(hit) - len(first))
      if first:
        self._tally("completions", len(first))
      if not remaining:
        self._drop_meta(f"{SEG_PREFIX}{rl.segid}")
      hitset = set(hit)
      return {i: i in hitset for i in todo}

  def _range_ack(self, rl: RangeLease, index: int) -> bool:
    return self._range_ack_many(rl, [index])[int(index)]

  def _range_nack(self, rl: RangeLease, index: int, reason: str = "",
                  requeue: bool = False):
    """Mid-range failure: carve the failed index out as a classic
    single-task lease (``task_<segid>_<idx>.json``) inheriting the
    range's attempt record, shrink the range, then hand the carve to the
    classic nack machinery — so reason recording, DLQ promotion, and
    retry budgets apply to ONLY the failed index while the rest of the
    range proceeds untouched."""
    from .. import telemetry

    index = int(index)
    with rl.lock:
      if rl.deadline < time.time() or index not in rl.entries:
        telemetry.incr("zombie.nack")
        return
      carve = f"task_{rl.segid}_{index}.json"
      seg_meta = self._read_meta(f"{SEG_PREFIX}{rl.segid}")
      meta = self._read_meta(carve)
      meta["deliveries"] = max(
        int(meta.get("deliveries", 0)), int(seg_meta.get("deliveries", 0))
      )
      meta["failures"] = (
        seg_meta.get("failures", []) + meta.get("failures", [])
      )[-MAX_RECORDED_FAILURES:]
      if seg_meta.get("spec"):
        # pair membership rides along: the carve's eventual ack must
        # still go through first-resolution marker arbitration
        meta["spec"] = seg_meta["spec"]
      self._write_meta(carve, meta)
      carve_lease = f"{rl.deadline:.3f}{LEASE_SEP}{carve}"
      self._write_file(self.lease_dir, carve_lease, rl.entries[index])
      remaining = {i: p for i, p in rl.entries.items() if i != index}
      if not self._range_rewrite(rl, remaining):
        # the whole range is being redelivered; withdraw the carve so the
        # index isn't duplicated
        try:
          os.remove(os.path.join(self.lease_dir, carve_lease))
        except FileNotFoundError:
          pass
        telemetry.incr("zombie.nack")
        return
    return self.nack(carve_lease, reason, requeue=requeue)

  def _range_release(self, rl: RangeLease, indices=None) -> int:
    """Return members (all surviving ones when ``indices`` is None) to
    the pool immediately as a fresh segment under a new segid (attempt
    meta copied, deliveries kept — matching classic release)."""
    with rl.lock:
      if indices is None:
        chosen = sorted(rl.entries)
      else:
        chosen = sorted({int(i) for i in indices} & set(rl.entries))
      if not chosen or rl.deadline < time.time():
        return 0  # expired: the recycler owns these now
      released = {i: rl.entries[i] for i in chosen}
      new_segid = uuid.uuid4().hex
      self._copy_meta(rl.segid, new_segid)
      new_name = seg_name(new_segid, len(released))
      self._write_file(
        self.queue_dir, new_name, _seg_content(sorted(released.items()))
      )
      remaining = {i: p for i, p in rl.entries.items() if i not in set(chosen)}
      if not self._range_rewrite(rl, remaining):
        try:
          os.remove(os.path.join(self.queue_dir, new_name))
        except FileNotFoundError:
          pass
        return 0
      if self._pending_cache is not None:
        self._pending_cache.append(new_name)
      return len(released)

  def _range_renew(self, rl: RangeLease, seconds: float) -> str:
    """Extend the range's ONE lease. Internally the token rotates (the
    deadline rides in the file name) but RangeSub handles stay valid.
    Freshness guard: when the deadline already covers ~the requested
    extension, this is a no-op — K heartbeat-tracked members cost one
    rename per beat, not K."""
    from .. import telemetry

    with rl.lock:
      now = time.time()
      if not rl.entries:
        # fully completed: a heartbeat racing the final ack — not a zombie
        raise StaleLeaseError(
          f"range {rl.segid!r} fully completed; nothing left to renew"
        )
      if rl.deadline < now:
        telemetry.incr("zombie.renew")
        raise StaleLeaseError(
          f"range lease {rl.segid!r} already expired; due for re-issue"
        )
      # work stealing (ISSUE 17): the heartbeat IS the holder's claim
      # inbox — service a pending claim before the freshness guard can
      # short-circuit, so a thief never waits past one renewal interval
      self._steal_service(rl)
      if rl.deadline >= now + float(seconds) * 0.9:
        return rl.token
      new_deadline = now + float(seconds)
      new_token = (
        f"{new_deadline:.3f}{LEASE_SEP}{seg_name(rl.segid, len(rl.entries))}"
      )
      try:
        os.rename(
          os.path.join(self.lease_dir, rl.token),
          os.path.join(self.lease_dir, new_token),
        )
      except FileNotFoundError:
        telemetry.incr("zombie.renew")
        raise StaleLeaseError(
          f"range lease {rl.segid!r} was re-issued by another worker"
        ) from None
      rl.token = new_token
      rl.deadline = new_deadline
      return rl.token

  # -- campaign survival: straggler speculation + work stealing (ISSUE 17) ---

  def _spec_active(self) -> bool:
    """One stat call gates every speculation hook: the ``spec/`` sidecar
    only exists once something speculated, so queues that never do read
    byte-for-byte as before ISSUE 17."""
    return os.path.isdir(self.spec_dir)

  def _spec_path(self, name: str) -> str:
    return os.path.join(self.spec_dir, name)

  def _spec_of(self, segid: str) -> Optional[dict]:
    """Pair membership of a segment: ``{"pair": …, "side": "orig"|"twin"}``
    from its attempt meta. The ORIG side gets a pair-file fallback:
    ``speculate_lease`` (driver process) stamping ``meta["spec"]`` can
    race the holder's own meta read-modify-write (a lease split's
    delivery bump, a failure record) and lose — but the pair file is
    NAMED after the orig segid, so its existence alone proves
    membership no matter which write landed last."""
    spec = self._read_meta(f"{SEG_PREFIX}{segid}").get("spec")
    if isinstance(spec, dict) and "pair" in spec:
      return spec
    if os.path.exists(self._spec_path(f"pair_{segid}.json")):
      return {"pair": segid, "side": "orig"}
    return None

  def _spec_of_name(self, name: str) -> Optional[dict]:
    """Pair membership of a classic queue/lease/dlq file name (carves
    inherit it into their own meta; plain per-task files never have
    any)."""
    spec = self._read_meta(self._meta_key(name)).get("spec")
    return spec if isinstance(spec, dict) and "pair" in spec else None

  def _spec_resolved(self, pairid: str, index: int) -> bool:
    return os.path.exists(self._spec_path(f"done_{pairid}_{int(index)}"))

  def _spec_mark_first(self, pairid: str, index: int) -> bool:
    """Atomically claim first resolution of (pair, index). The O_EXCL
    create is the ONE commitment point for the completion tally: the
    creator tallies, everyone else is fenced."""
    try:
      fd = os.open(
        self._spec_path(f"done_{pairid}_{int(index)}"),
        os.O_CREAT | os.O_EXCL | os.O_WRONLY,
      )
    except FileExistsError:
      return False
    os.close(fd)
    return True

  def _spec_account_first(self, spec: dict, n: int):
    """Exactly one of won/fenced per issued index, settled at first
    resolution — the twin resolving first means speculation paid off,
    the original resolving first means the twin's copy is now waste.
    Besides the in-process telemetry counter (journal-flushed, LOSSY
    when the acking worker is SIGKILLed before its next flush) the
    resolution appends to a crash-safe queue tally, committed in the
    same breath as the done marker — the campaign driver reconciles
    the journal ledger from these after the pool is down."""
    from .. import telemetry

    if spec.get("side") == "twin":
      telemetry.incr("speculation.won", n)
      self._tally("speculation_won", n)
    else:
      telemetry.incr("speculation.fenced", n)
      self._tally("speculation_fenced", n)

  def _spec_wasted(self, spec: dict, n: int):
    """A duplicate ack: the loser executed work the winner already
    tallied. ``speculation.wasted_ms`` accumulates the pair-open window
    per duplicate — the wall-clock bound on the duplicated effort."""
    from .. import telemetry

    telemetry.incr("speculation.duplicate_ack", n)
    pair = self._read_pair(spec["pair"])
    if pair and pair.get("ts"):
      window_ms = int(max(0.0, time.time() - float(pair["ts"])) * 1000)
      telemetry.incr("speculation.wasted_ms", window_ms * n)

  def _spec_collapse(self, lease_path: Optional[str],
                     meta_name: Optional[str], n: int):
    """Drop an already-resolved duplicate copy at lease/expiry time. No
    tally — the winner tallied at resolution; this is how a fenced
    twin's leftover copies drain out of rotation."""
    from .. import telemetry

    if lease_path is not None:
      try:
        os.remove(lease_path)
      except FileNotFoundError:
        pass
    if meta_name is not None:
      self._drop_meta(meta_name)
    telemetry.incr("speculation.deduped", n)

  def _read_pair(self, pairid: str) -> Optional[dict]:
    try:
      with open(self._spec_path(f"pair_{pairid}.json")) as f:
        return json.load(f)
    except (FileNotFoundError, ValueError):
      return None

  def range_leases(self) -> List[dict]:
    """Live range leases with holder identity — the planner's view for
    speculation targeting and steal candidate selection."""
    now = time.time()
    spec_on = self._spec_active()
    out = []
    for name in os.listdir(self.lease_dir):
      try:
        deadline = float(name.split(LEASE_SEP, 1)[0])
      except ValueError:
        continue
      parsed = seg_parse(name.split(LEASE_SEP, 1)[-1])
      if parsed is None:
        continue
      segid, count = parsed
      meta = self._read_meta(f"{SEG_PREFIX}{segid}")
      paired = bool(meta.get("spec")) or (
        # pair-file fallback: a clobbered orig meta must not make this
        # lease look stealable/re-speculatable (see _spec_of)
        spec_on
        and os.path.exists(self._spec_path(f"pair_{segid}.json"))
      )
      out.append({
        "lease": name, "segid": segid, "count": count,
        "deadline": deadline, "expired": deadline < now,
        "holder": meta.get("holder"),
        "leased_at": meta.get("leased_at"),
        "spec": paired,
      })
    return out

  def speculate_lease(self, lease_name: str) -> int:
    """Double-issue the unfinished tail of one held range lease as a
    speculative TWIN segment: fresh segid, fresh delivery budget, the
    SAME global task indices. The twin enters normal rotation; whichever
    copy resolves an index first tallies it (see ``_range_ack_many``)
    and the loser's copy is fenced. One live pair per segment —
    re-speculation waits until the pair resolves and GCs. Returns the
    number of indices twinned (0 when the target is not a range lease,
    is already paired, is below ``IGNEOUS_SPECULATE_MIN_TASKS``, or
    rotated away since it was listed)."""
    from .. import telemetry
    from ..analysis import knobs

    orig = str(lease_name).split(LEASE_SEP, 1)[-1]
    parsed = seg_parse(orig)
    if parsed is None:
      return 0
    segid = parsed[0]
    key = f"{SEG_PREFIX}{segid}"
    meta = self._read_meta(key)
    if meta.get("spec"):
      return 0
    v = knobs.get_int("IGNEOUS_SPECULATE_MIN_TASKS")
    min_tasks = DEFAULT_SPECULATE_MIN_TASKS if v is None else int(v)
    try:
      entries = self._read_segment(os.path.join(self.lease_dir, lease_name))
    except FileNotFoundError:
      return 0  # rotated or completed since the listing; next sweep
    if len(entries) < max(min_tasks, 1):
      return 0
    os.makedirs(self.spec_dir, exist_ok=True)
    # the pair file is the mutex: an O_EXCL loss means a racing driver
    # just speculated this segment
    try:
      fd = os.open(
        self._spec_path(f"pair_{segid}.json"),
        os.O_CREAT | os.O_EXCL | os.O_WRONLY,
      )
    except FileExistsError:
      return 0
    twin = uuid.uuid4().hex
    with os.fdopen(fd, "w") as f:
      json.dump({
        "pair": segid, "orig": segid, "twin": twin,
        "indices": [int(i) for i, _ in entries],
        "ts": round(time.time(), 3), "holder": meta.get("holder"),
      }, f)
    self._write_meta(
      f"{SEG_PREFIX}{twin}",
      {"deliveries": 0, "failures": [],
       "spec": {"pair": segid, "side": "twin"}},
    )
    meta["spec"] = {"pair": segid, "side": "orig"}
    self._write_meta(key, meta)
    # the twin entering rotation is the commit point
    twin_name = seg_name(twin, len(entries))
    self._write_file(self.queue_dir, twin_name, _seg_content(entries))
    if self._pending_cache is not None:
      self._pending_cache.append(twin_name)
    telemetry.incr("speculation.issued", len(entries))
    return len(entries)

  def speculate_flagged(self, workers, max_twins: Optional[int] = None) -> int:
    """Driver entry point: twin the tails of every unexpired, unpaired
    range lease held by a flagged worker — biggest ranges first, capped
    at ``max_twins`` new pairs per sweep (IGNEOUS_SPECULATE_MAX_TWINS).
    Returns the total number of indices twinned."""
    from ..analysis import knobs

    workers = {str(w) for w in workers}
    if not workers:
      return 0
    if max_twins is None:
      v = knobs.get_int("IGNEOUS_SPECULATE_MAX_TWINS")
      max_twins = DEFAULT_SPECULATE_MAX_TWINS if v is None else int(v)
    held = knobs.get_float("IGNEOUS_SPECULATE_MIN_HELD_SEC")
    min_held = DEFAULT_SPECULATE_MIN_HELD_SEC if held is None else float(held)
    now = time.time()
    cands = [
      r for r in self.range_leases()
      if not r["expired"] and not r["spec"] and r["holder"] in workers
      and now - float(r["leased_at"] or now) >= min_held
    ]
    cands.sort(key=lambda r: (-r["count"], r["lease"]))
    issued = twins = 0
    for r in cands:
      if twins >= max_twins:
        break
      n = self.speculate_lease(r["lease"])
      if n:
        issued += n
        twins += 1
    return issued

  def steal_claim(self, thief: Optional[str] = None) -> Optional[str]:
    """Thief entry point: claim the biggest long-held foreign range so
    its holder's next heartbeat renewal releases the unstarted tail back
    to the pool, where the thief (or any idle worker) leases it. One
    claim file per segment; O_EXCL creation makes racing thieves
    converge on distinct targets deterministically. Returns the claimed
    segid, or None when nothing qualifies."""
    from .. import telemetry
    from ..analysis import knobs

    thief = thief or self.worker_id
    v = knobs.get_int("IGNEOUS_STEAL_MIN_TASKS")
    min_tasks = DEFAULT_STEAL_MIN_TASKS if v is None else int(v)
    held = knobs.get_float("IGNEOUS_STEAL_MIN_HELD_SEC")
    min_held = DEFAULT_STEAL_MIN_HELD_SEC if held is None else float(held)
    now = time.time()
    cands = [
      r for r in self.range_leases()
      if not r["expired"] and r["count"] >= max(min_tasks, 1)
      and r["holder"] not in (None, thief)
      and now - float(r["leased_at"] or now) >= min_held
    ]
    cands.sort(key=lambda r: (-r["count"], r["lease"]))
    for r in cands:
      os.makedirs(self.steal_dir, exist_ok=True)
      try:
        fd = os.open(
          os.path.join(self.steal_dir, f"{r['segid']}.claim"),
          os.O_CREAT | os.O_EXCL | os.O_WRONLY,
        )
      except FileExistsError:
        continue  # another thief got this range; try the next
      with os.fdopen(fd, "w") as f:
        json.dump({"thief": thief, "ts": round(now, 3)}, f)
      telemetry.incr("steal.claims")
      return r["segid"]
    return None

  def _steal_service(self, rl: RangeLease) -> int:
    """Holder side, under ``rl.lock`` from ``_range_renew``: a pending
    claim releases ``IGNEOUS_STEAL_FRACTION`` of the UNSTARTED tail
    through the expiry-fenced range-release seam, always keeping at
    least one member so the holder's in-flight work keeps its lease.
    Too-small grants deny the claim (file removed) rather than starve
    the thief silently."""
    if not os.path.isdir(self.steal_dir):
      return 0
    claim = os.path.join(self.steal_dir, f"{rl.segid}.claim")
    if not os.path.exists(claim):
      return 0
    from .. import telemetry
    from ..analysis import knobs

    frac = knobs.get_float("IGNEOUS_STEAL_FRACTION")
    frac = DEFAULT_STEAL_FRACTION if frac is None else float(frac)
    v = knobs.get_int("IGNEOUS_STEAL_MIN_TASKS")
    min_tasks = DEFAULT_STEAL_MIN_TASKS if v is None else int(v)
    unstarted = sorted(set(rl.entries) - rl.started)
    grant_n = min(
      int(len(unstarted) * max(min(frac, 1.0), 0.0)),
      len(rl.entries) - 1,
    )
    granted = 0
    if grant_n >= 1 and len(unstarted) >= max(min_tasks, 1):
      granted = self._range_release(rl, unstarted[-grant_n:])
    try:
      os.remove(claim)
    except FileNotFoundError:
      pass
    if granted:
      telemetry.incr("steal.granted")
      telemetry.incr("steal.tasks", granted)
    else:
      telemetry.incr("steal.denied")
    return granted

  def _survival_gc(self, now: float):
    """Recycle-pass housekeeping for the survival sidecars: TTL-expired
    steal claims recycle (so a re-leased range can be claimed again),
    DLQ carves whose index the pair's other copy completed are pruned
    as stale duplicates, and fully-resolved pairs drop their markers +
    pair file — but only once NOTHING on disk references either segid,
    because any lingering copy must keep deduping against the markers."""
    from .. import telemetry
    from ..analysis import knobs

    if os.path.isdir(self.steal_dir):
      ttl = knobs.get_float("IGNEOUS_STEAL_CLAIM_TTL_SEC")
      ttl = DEFAULT_STEAL_CLAIM_TTL_SEC if ttl is None else float(ttl)
      for name in os.listdir(self.steal_dir):
        if not name.endswith(".claim"):
          continue
        path = os.path.join(self.steal_dir, name)
        try:
          with open(path) as f:
            ts = float(json.load(f).get("ts") or 0)
        except (FileNotFoundError, ValueError, TypeError):
          ts = 0.0
        if now - ts > max(ttl, 0.0):
          try:
            os.remove(path)
            telemetry.incr("steal.expired_claims")
          except FileNotFoundError:
            pass
    if not self._spec_active():
      return
    names = os.listdir(self.spec_dir)
    pairs = [n for n in names if n.startswith("pair_")]
    if not pairs:
      return
    markers = {n for n in names if n.startswith("done_")}
    qlive = os.listdir(self.queue_dir) + os.listdir(self.lease_dir)

    for pname in pairs:
      try:
        with open(self._spec_path(pname)) as f:
          pair = json.load(f)
      except (FileNotFoundError, ValueError):
        continue
      pid = pair.get("pair")
      # descendants (lease splits, stolen/released tails) carry the
      # pair under fresh segids; their side_ lineage markers make them
      # visible here so the pair outlives every circulating copy
      side_pref = f"side_{pid}_"
      lineage = [n[len(side_pref):] for n in names if n.startswith(side_pref)]
      sides = tuple(
        [pair.get("orig", ""), pair.get("twin", "")] + lineage
      )
      # stale DLQ duplicates: the other copy completed this index AFTER
      # it was quarantined — zero-DLQ-leakage means pruning them
      for n in os.listdir(self.dlq_dir):
        m = _CARVE_RE.match(n)
        if not m or m.group(1) not in sides:
          continue
        if f"done_{pid}_{int(m.group(2))}" in markers:
          try:
            os.remove(os.path.join(self.dlq_dir, n))
          except FileNotFoundError:
            continue
          self._drop_meta(n)
          telemetry.incr("speculation.dlq_pruned")
      idxs = pair.get("indices", [])
      done = [f"done_{pid}_{i}" for i in idxs]
      if not all(d in markers for d in done):
        continue

      def referenced(segid: str, listing) -> bool:
        seg_pref = f"{SEG_PREFIX}{segid}_"
        carve_pref = f"task_{segid}_"
        return any(seg_pref in n or carve_pref in n for n in listing)

      dlq_live = os.listdir(self.dlq_dir)
      if any(
        referenced(s, qlive) or referenced(s, dlq_live) for s in sides
      ):
        continue
      for d in done:
        try:
          os.remove(self._spec_path(d))
        except FileNotFoundError:
          pass
      for n in names:
        if n.startswith(side_pref):
          try:
            os.remove(self._spec_path(n))
          except FileNotFoundError:
            pass
      try:
        os.remove(self._spec_path(pname))
      except FileNotFoundError:
        pass
      for s in sides:
        self._drop_meta(f"{SEG_PREFIX}{s}")

  def purge(self):
    for d in (self.queue_dir, self.lease_dir, self.dlq_dir, self.meta_dir,
              self.spec_dir, self.steal_dir):
      if not os.path.isdir(d):
        continue
      for name in list(os.listdir(d)):
        try:
          os.remove(os.path.join(d, name))
        except FileNotFoundError:
          pass
    self._pending_cache = None
    self.rezero()

  # -- worker loop ----------------------------------------------------------

  def poll(
    self,
    lease_seconds: float = 600,
    verbose: bool = False,
    tally: bool = True,
    stop_fn=None,
    max_backoff_window: float = 30.0,
    before_fn=None,
    after_fn=None,
    task_deadline_seconds: Optional[float] = None,
    heartbeat_seconds: Optional[float] = None,
    drain_flag=None,
  ):
    """Lease→execute→delete until stop_fn says stop or the queue drains
    (stop_fn=None polls forever, sleeping with bounded backoff when empty)."""
    del tally  # completions are always tallied; kept for API familiarity
    return poll_loop(
      self, lease_seconds, verbose, stop_fn, max_backoff_window,
      before_fn, after_fn, task_deadline_seconds,
      heartbeat_seconds, drain_flag,
    )

  def __len__(self):
    return self.enqueued
