"""Lease heartbeats: renew visibility while work executes (ISSUE 2).

The visibility timeout is a dead-worker detector, but a LONG timeout
makes detection slow (a crashed worker strands its task for the whole
lease) while a SHORT one double-executes any task slower than the lease.
The heartbeat resolves the tension: workers run with a short
``--lease-sec`` and a daemon thread renews every tracked lease at
``interval`` (default lease/3, overridable via IGNEOUS_HEARTBEAT_SEC),
so liveness detection stays fast and long mesh/skeleton tasks still run
exactly once.

Renewal is backend-polymorphic through ``queue.renew(lease_id, seconds)``:
fq:// re-timestamps the lease name (the token CHANGES — this class keeps
the original-token → current-token map so callers can keep using the id
they leased with), sqs:// calls ChangeMessageVisibility (token stable),
LocalTaskQueue is a no-op. A queue without ``renew`` disables the
heartbeat entirely.

A renewal refused with StaleLeaseError means this worker became a zombie
for that lease (it expired or was re-issued); the lease is dropped from
tracking and recorded in ``self.lost`` — the later delete is fenced by
the queue anyway.
"""

from __future__ import annotations

import threading
from typing import Optional

from .filequeue import StaleLeaseError
from ..analysis import racecheck


class LeaseHeartbeat:
  """Renews tracked leases on a daemon thread.

  Usage::

    hb = LeaseHeartbeat(queue, lease_seconds)
    with hb:
      key = hb.track(lease_id)      # start renewing
      ... execute ...
      queue.delete(hb.untrack(key))  # current token; renewing stops

  ``interval=None`` resolves IGNEOUS_HEARTBEAT_SEC, then lease/3;
  ``interval <= 0`` disables (track/untrack become identity pass-throughs).
  """

  def __init__(self, queue, lease_seconds: float,
               interval: Optional[float] = None):
    if interval is None:
      from .. import secrets

      interval = secrets.heartbeat_seconds()
    if interval is None:
      interval = max(float(lease_seconds) / 3.0, 0.01)
    self.queue = queue
    self.lease_seconds = float(lease_seconds)
    self.interval = float(interval)
    self.enabled = self.interval > 0 and hasattr(queue, "renew")
    self.renewals = 0
    self._lock = threading.Lock()
    self.lost = racecheck.guard(  # guarded-by: self._lock
      set(), self._lock, "LeaseHeartbeat.lost")
    # token at track() time -> current token
    self._current = racecheck.guard(  # guarded-by: self._lock
      {}, self._lock, "LeaseHeartbeat._current")
    self._stop = threading.Event()
    self._thread: Optional[threading.Thread] = None

  def track(self, lease_id):
    """Begin renewing ``lease_id``; returns the key for current()/untrack().
    Idempotent: re-tracking an already-tracked lease (a pre-leased batch
    member tracked again at round start) keeps the renewed current token
    instead of clobbering it with the stale original."""
    if self.enabled:
      with self._lock:
        self._current.setdefault(lease_id, lease_id)
    return lease_id

  def current(self, key):
    """The lease's current token (== key until a renewal re-timestamps it)."""
    with self._lock:
      return self._current.get(key, key)

  def untrack(self, key):
    """Stop renewing; returns the current token for the final delete/nack."""
    with self._lock:
      return self._current.pop(key, key)

  def beat(self):
    """One renewal pass over every tracked lease (called by the thread;
    public so tests can step it deterministically)."""
    with self._lock:
      keys = list(self._current)
    for key in keys:
      # hold the lock across the renew so an untrack cannot interleave
      # with the token swap and hand the caller a dead token
      with self._lock:
        cur = self._current.get(key)
        if cur is None:
          continue
        try:
          new_id = self.queue.renew(cur, self.lease_seconds)
        except StaleLeaseError:
          # zombie for this lease: stop renewing; the fenced delete path
          # (and the task's new owner) take it from here
          self._current.pop(key, None)
          self.lost.add(key)
          continue
        except Exception:
          # transient renew failure (e.g. SQS 503): the lease has
          # interval << lease_seconds of slack, so the next beat retries
          continue
        self.renewals += 1
        self._current[key] = new_id

  def _run(self):
    while not self._stop.wait(self.interval):
      self.beat()

  def start(self):
    if not self.enabled or self._thread is not None:
      return self
    self._stop.clear()
    self._thread = threading.Thread(
      target=self._run, daemon=True, name="lease-heartbeat"
    )
    self._thread.start()
    return self

  def stop(self):
    self._stop.set()
    if self._thread is not None:
      self._thread.join(timeout=5.0)
      self._thread = None

  __enter__ = start

  def __exit__(self, *exc):
    self.stop()
    return False
