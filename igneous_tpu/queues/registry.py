"""Task registry + JSON wire format.

A task crosses process/machine boundaries as JSON. Two kinds exist, matching
the reference's RegisteredTask-subclass and @queueable-function styles
(/root/reference/igneous/tasks/__init__.py:1-25 registers both kinds):

  {"class": "DownsampleTask", "params": {...}}     RegisteredTask subclass
  {"fn": "delete_mesh_files", "args": [...], "kwargs": {...}}  @queueable

RegisteredTask subclasses get automatic serialization: the constructor's
bound arguments are recorded at instantiation time, so ``__init__``
signatures ARE the wire schema.

Trace identity (ISSUE 5): every task minted by a factory carries a
``"trace"`` payload field ({trace_id, ts[, parent_span_id, sampled]})
assigned at instantiation and restored verbatim on deserialize, so
enqueue → lease → retry → DLQ is one trace across workers. The field is
observability metadata, NOT wire schema: equality and hashing ignore it,
and payloads without it (older queues) deserialize fine — the worker
mints locally and lineage simply starts at the lease.
"""

from __future__ import annotations

import functools
import inspect
import json
from typing import Callable, Dict, Optional, Union

from ..lib import jsonify

TASK_REGISTRY: Dict[str, type] = {}
FN_REGISTRY: Dict[str, Callable] = {}


class RegisteredTask:
  """Base for serializable work units. Subclass and implement execute()."""

  def __init_subclass__(cls, **kw):
    super().__init_subclass__(**kw)
    TASK_REGISTRY[cls.__name__] = cls
    orig_init = cls.__init__

    @functools.wraps(orig_init)
    def wrapped_init(self, *args, **kwargs):
      # only the outermost constructor (the instantiated class) records
      # params; super().__init__ chains must not overwrite them
      if not hasattr(self, "_params"):
        sig = inspect.signature(orig_init)
        bound = sig.bind(self, *args, **kwargs)
        bound.apply_defaults()
        params = dict(bound.arguments)
        params.pop("self", None)
        for pname, p in sig.parameters.items():
          if p.kind is inspect.Parameter.VAR_KEYWORD:
            params.update(params.pop(pname, {}))
        self._params = jsonify(params)
        from ..observability import trace

        self._trace = trace.mint()
      orig_init(self, *args, **kwargs)

    cls.__init__ = wrapped_init

  def __init__(self):
    if not hasattr(self, "_params"):
      self._params = {}
      from ..observability import trace

      self._trace = trace.mint()

  def execute(self):
    raise NotImplementedError

  def payload(self) -> dict:
    out = {
      "class": type(self).__name__,
      "module": type(self).__module__,
      "params": self._params,
    }
    tinfo = getattr(self, "_trace", None)
    if tinfo:
      # exec_span_id is per-delivery state, never part of the wire trace
      out["trace"] = {k: v for k, v in tinfo.items() if k != "exec_span_id"}
    return out

  def to_json(self) -> str:
    return json.dumps(self.payload())

  def __repr__(self):
    args = ", ".join(f"{k}={v!r}" for k, v in self._params.items())
    return f"{type(self).__name__}({args})"

  def __eq__(self, other):
    return (
      type(self) is type(other)
      and self._params == getattr(other, "_params", None)
    )

  def __hash__(self):
    # class + params only: the trace field is identity metadata, and two
    # equal tasks (__eq__ compares _params) must share a hash
    return hash((type(self).__name__, json.dumps(self._params, sort_keys=True)))


def queueable(fn: Callable) -> Callable:
  """Register a function as a queueable task target.

  Insert ``functools.partial(fn, *args, **kwargs)`` into a queue; it
  serializes by function name + arguments.
  """
  FN_REGISTRY[fn.__name__] = fn
  fn._queueable = True
  return fn


class FunctionTask(RegisteredTask):
  """Adapter that executes a @queueable function payload."""

  def __init__(self, fn_name: str, args: list, kwargs: dict):
    self.fn_name = fn_name
    self.args = args or []
    self.kwargs = kwargs or {}

  def payload(self) -> dict:
    out = {
      "fn": self.fn_name,
      "args": jsonify(list(self.args)),
      "kwargs": jsonify(dict(self.kwargs)),
    }
    tinfo = getattr(self, "_trace", None)
    if tinfo:
      out["trace"] = {k: v for k, v in tinfo.items() if k != "exec_span_id"}
    return out

  def execute(self):
    if self.fn_name not in FN_REGISTRY:
      raise KeyError(
        f"Function {self.fn_name!r} is not @queueable-registered. "
        f"Known: {sorted(FN_REGISTRY)}"
      )
    return FN_REGISTRY[self.fn_name](*self.args, **self.kwargs)


class PrintTask(RegisteredTask):
  """Debug/smoke-test task."""

  def __init__(self, txt: str = ""):
    self.txt = txt

  def execute(self):
    print(self.txt or "PrintTask")
    return self.txt


def serialize(task) -> str:
  """Task object | partial | payload-dict → JSON string."""
  if isinstance(task, RegisteredTask):
    return task.to_json()
  if isinstance(task, functools.partial):
    fn = task.func
    if not getattr(fn, "_queueable", False):
      raise ValueError(f"{fn} is not @queueable")
    return FunctionTask(fn.__name__, list(task.args), dict(task.keywords)).to_json()
  if isinstance(task, dict):
    return json.dumps(jsonify(task))
  if isinstance(task, str):
    return task
  raise TypeError(f"Cannot serialize task: {task!r}")


def _reenter_trace(task, payload: dict):
  """Restore the payload's trace identity onto a deserialized task (the
  constructor minted a fresh one; the wire's wins so redeliveries and
  cross-worker hops stay one trace)."""
  tinfo = payload.get("trace")
  if tinfo and isinstance(tinfo, dict) and tinfo.get("trace_id"):
    task._trace = dict(tinfo)
  return task


def deserialize(payload: Union[str, bytes, dict]) -> RegisteredTask:
  if isinstance(payload, (str, bytes)):
    payload = json.loads(payload)
  if "fn" in payload:
    return _reenter_trace(
      FunctionTask(payload["fn"], payload.get("args"), payload.get("kwargs")),
      payload,
    )
  name = payload["class"]
  if name not in TASK_REGISTRY and payload.get("module"):
    # cross-process case: the defining module wasn't imported yet
    import importlib

    importlib.import_module(payload["module"])
  if name not in TASK_REGISTRY:
    raise KeyError(
      f"Task class {name!r} is not registered. Import the module defining it."
    )
  return _reenter_trace(
    TASK_REGISTRY[name](**payload.get("params", {})), payload
  )


totask = deserialize
