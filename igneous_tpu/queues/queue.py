"""TaskQueue facade: protocol-addressed queues.

Mirrors the reference's queue URL convention
(/root/reference/igneous_cli/cli.py:935-964): ``fq://<dir>`` filesystem
queue, ``sqs://`` cloud queue (attachable via register_queue_protocol —
no egress in this environment, same policy as storage backends).
"""

from __future__ import annotations

from .filequeue import FileQueue

_QUEUE_PROTOCOLS = {}


def register_queue_protocol(name: str, factory):
  _QUEUE_PROTOCOLS[name] = factory


def _require_filequeue(q, spec):
  from .filequeue import FileQueue

  if not isinstance(q, FileQueue):
    raise ValueError(
      f"queue cp/mv supports fq:// queues only (got {spec!r}); protocol "
      "backends expose their own bulk-transfer tooling"
    )
  return q


def _snapshot_payloads(src, delete: bool):
  """Yield (name, [payloads]) per pending FILE — one payload for a
  classic per-task file, every member payload for a segment — tolerating
  workers leasing files mid-walk (the same FileNotFoundError races
  lease()/release() absorb). With ``delete=True`` the file is removed
  only after its payloads were yielded back to the consumer."""
  import os

  from .filequeue import seg_parse

  for name in sorted(os.listdir(src.queue_dir)):
    path = os.path.join(src.queue_dir, name)
    if seg_parse(name) is not None:
      try:
        payloads = [p for _i, p in src._read_segment(path)]
      except FileNotFoundError:
        continue  # a worker leased it between listing and reading
    else:
      try:
        with open(path) as f:
          payloads = [f.read()]
      except FileNotFoundError:
        continue
    yield name, payloads
    if delete:
      try:
        os.remove(path)
      except FileNotFoundError:
        pass


def _batched_insert(dest, payloads) -> int:
  ins = getattr(dest, "insert_batch", None)
  if ins is None:
    for p in payloads:
      dest.insert(p)
  else:
    # no total= hint: a source segment moves as ONE dest segment instead
    # of re-sharding per file
    ins(payloads)
  return len(payloads)


def copy_queue(src_spec: str, dest_spec: str) -> int:
  """Copy all pending tasks from one fq:// queue to another
  (`igneous queue cp`). Leased tasks are not copied."""
  src = _require_filequeue(TaskQueue(src_spec), src_spec)
  dest = TaskQueue(dest_spec)
  n = 0
  for _name, payloads in _snapshot_payloads(src, delete=False):
    n += _batched_insert(dest, payloads)
  return n


def move_queue(src_spec: str, dest_spec: str) -> int:
  """Move all pending tasks (`igneous queue mv`). Each file is deleted
  only AFTER its copies land, so tasks inserted concurrently are never
  dropped (they simply stay in the source)."""
  src = _require_filequeue(TaskQueue(src_spec), src_spec)
  dest = TaskQueue(dest_spec)
  n = 0
  for _name, payloads in _snapshot_payloads(src, delete=True):
    n += _batched_insert(dest, payloads)
  return n


def TaskQueue(spec, **kw):
  """Create a queue from a URL spec (or pass through a queue object)."""
  if not isinstance(spec, str):
    return spec
  if spec.startswith("fq://") or "://" not in spec:
    return FileQueue(spec, **kw)
  protocol = spec.split("://", 1)[0]
  if protocol in _QUEUE_PROTOCOLS:
    return _QUEUE_PROTOCOLS[protocol](spec, **kw)
  raise ValueError(
    f"Queue protocol {protocol}:// not available. "
    f"Use fq:// or register_queue_protocol()."
  )
