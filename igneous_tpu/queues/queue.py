"""TaskQueue facade: protocol-addressed queues.

Mirrors the reference's queue URL convention
(/root/reference/igneous_cli/cli.py:935-964): ``fq://<dir>`` filesystem
queue, ``sqs://`` cloud queue (attachable via register_queue_protocol —
no egress in this environment, same policy as storage backends).
"""

from __future__ import annotations

from .filequeue import FileQueue

_QUEUE_PROTOCOLS = {}


def register_queue_protocol(name: str, factory):
  _QUEUE_PROTOCOLS[name] = factory


def TaskQueue(spec, **kw):
  """Create a queue from a URL spec (or pass through a queue object)."""
  if not isinstance(spec, str):
    return spec
  if spec.startswith("fq://") or "://" not in spec:
    return FileQueue(spec, **kw)
  protocol = spec.split("://", 1)[0]
  if protocol in _QUEUE_PROTOCOLS:
    return _QUEUE_PROTOCOLS[protocol](spec, **kw)
  raise ValueError(
    f"Queue protocol {protocol}:// not available. "
    f"Use fq:// or register_queue_protocol()."
  )
