"""Range leases: a contiguous run of grid tasks held as ONE queue message.

A regular-grid campaign (ISSUE 15) is index-addressable: task i is fully
determined by its grid coordinate, and neighbors in index order are
neighbors in the volume. Leasing K such tasks one message at a time costs
K queue round-trips; a *range lease* moves the whole run in one — the
FileQueue segment file (``seg_<segid>_<count>.jsonl``) IS the lease unit,
and SQS-style backends can pack a range descriptor into one message.

Per-task semantics survive through sub-task accounting:

* :class:`RangeSub` is the worker-side handle for ONE member. Every queue
  op (``delete``/``nack``/``release``/``renew``/``delivery_count``)
  accepts it wherever a classic lease token is accepted, so the shared
  poll loop and the lease batcher run unmodified over ranges.
* partial completion **acks a sub-range**: each ack rewrites the lease
  file minus the completed index, so an expiry recycles only what is
  still unfinished;
* a mid-range failure **splits the lease**: the failed index is carved
  out as a classic single-task lease with inherited attempt metadata, so
  only that index retries (and only it can dead-letter);
* heartbeat renewal re-timestamps the ONE underlying lease, with a
  freshness guard so K tracked members don't trigger K renames per beat.

The mutable state (current token, surviving entries, deadline) lives
here; the filesystem/wire mechanics live on the owning queue
(``FileQueue._range_*``), keeping this module import-light so the
simulator and the lease batcher can type-check handles without pulling
in a backend.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional


class RangeSub:
  """Handle for one member of a :class:`RangeLease`. Accepted anywhere a
  classic lease token is (queue delete/nack/release/renew), hashable so
  heartbeats and round bookkeeping can track it like a token string."""

  __slots__ = ("parent", "index")

  def __init__(self, parent: "RangeLease", index: int):
    self.parent = parent
    self.index = int(index)

  def mark_started(self):
    """Record that work on this member has begun (see
    :meth:`RangeLease.mark_started`)."""
    self.parent.mark_started(self.index)

  def __repr__(self):
    return f"RangeSub({self.parent.segid[:8]}:{self.index})"


class RangeLease:
  """A leased contiguous (or split-survivor) set of task indices backed
  by one queue message. ``entries`` holds only the *surviving* members —
  acked/nacked/released indices leave it, and lease expiry recycles
  exactly what remains."""

  def __init__(self, queue, token: str, segid: str,
               entries: Dict[int, str], deadline: float):
    self.queue = queue
    self.token = token          # current lease token (renewals rotate it)
    self.segid = segid          # stable across rewrites; keys attempt meta
    self.entries = dict(entries)  # index -> serialized payload, pending only
    self.deadline = float(deadline)
    self.started = set()        # members whose execution has begun
    self.lock = threading.RLock()

  # -- shape ----------------------------------------------------------------

  @property
  def start(self) -> Optional[int]:
    with self.lock:
      return min(self.entries) if self.entries else None

  @property
  def end(self) -> Optional[int]:
    """Exclusive end of the surviving index set."""
    with self.lock:
      return max(self.entries) + 1 if self.entries else None

  def __len__(self) -> int:
    with self.lock:
      return len(self.entries)

  def subs(self) -> List[RangeSub]:
    with self.lock:
      return [RangeSub(self, i) for i in sorted(self.entries)]

  def mark_started(self, index: int):
    """Record that work on a member has begun. Work stealing (ISSUE 17)
    only carves UNSTARTED members off a claimed range — marking is what
    protects in-flight work from being handed to a thief mid-execution.
    Workers that never mark still converge (an in-flight member granted
    away just zombie-fences its late ack), only less efficiently."""
    with self.lock:
      self.started.add(int(index))

  def unstarted(self) -> List[int]:
    """Surviving members no one has begun — the stealable tail."""
    with self.lock:
      return sorted(set(self.entries) - self.started)

  def __repr__(self):
    with self.lock:
      return (
        f"RangeLease({self.segid[:8]}, n={len(self.entries)}, "
        f"[{self.start}:{self.end}])"
      )

  # -- per-member ops (delegate to the owning queue) ------------------------

  def ack(self, index: int) -> bool:
    """Complete one member: the sub-range shrinks, the completion
    tallies, and expiry can no longer recycle this index. Zombie-fenced
    like a classic delete (False + ``zombie.delete`` when stale)."""
    return self.queue._range_ack(self, index)

  def ack_many(self, indices) -> Dict[int, bool]:
    """Complete several members with ONE lease-file rewrite."""
    return self.queue._range_ack_many(self, indices)

  def nack(self, index: int, reason: str = "", requeue: bool = False):
    """Fail one member: it splits out of the range as a classic
    single-task lease carrying the range's delivery count, so only this
    index retries (or dead-letters when exhausted)."""
    return self.queue._range_nack(self, index, reason, requeue=requeue)

  def release(self, indices=None):
    """Return members (all surviving ones when ``indices`` is None) to
    the queue immediately as a fresh segment."""
    return self.queue._range_release(self, indices)

  def heartbeat_renew(self, seconds: float):
    """Extend the shared lease. The underlying token rotates internally;
    callers keep using their RangeSub handles unchanged. Raises
    StaleLeaseError once the range expired or fully completed."""
    return self.queue._range_renew(self, seconds)
