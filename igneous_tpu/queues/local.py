"""In-process / multi-process task execution.

LocalTaskQueue semantics mirror the reference's
``LocalTaskQueue(parallel=N)`` (/root/reference/README.md:69-81): inserting
tasks executes them immediately, optionally across N spawned worker
processes. Spawn (not fork) is used for the same reason the reference CLI
does (/root/reference/igneous_cli/cli.py:920-922): forking a process with
live thread pools / device handles deadlocks; with JAX in the picture fork
is outright unsafe.
"""

from __future__ import annotations

import multiprocessing as mp
import os
from typing import Iterable, Optional

from tqdm import tqdm

from .registry import deserialize, serialize


def _execute_payload(payload: str):
  # runs in a spawned worker: re-import the task universe first
  import igneous_tpu.tasks  # noqa: F401  (registers all task classes)

  from ..observability import trace

  task = deserialize(payload)
  with trace.task_span(task, queue="LocalTaskQueue"):
    task.execute()
  return True


def _execute_payload_contained(payload: str, max_deliveries: int):
  """Spawned-worker body with the containment contract: retry up to
  ``max_deliveries`` attempts, then report the failure instead of
  killing the whole pool. Returns (payload, error_or_None)."""
  from .filequeue import failure_reason

  last = None
  for _ in range(max(int(max_deliveries), 1)):
    try:
      _execute_payload(payload)
      return payload, None
    except Exception as e:  # noqa: BLE001 - recorded as a dead letter
      last = failure_reason(e)
  return payload, last


def _worker_init(pool_threads: int):
  """Spawned-worker setup: N process-parallel workers each get 1/N of the
  cores for their native kernel threading (same oversubscription hygiene as
  the reference's cv2.setNumThreads(0),
  /root/reference/igneous/tasks/image/image.py:177-180)."""
  os.environ.setdefault("IGNEOUS_POOL_THREADS", str(pool_threads))


class LocalTaskQueue:
  """Executes tasks on insert; parallel > 1 uses a spawn process pool.

  ``max_deliveries`` opts into the same failure containment the lease
  queues have: each task gets that many attempts, and tasks that still
  fail are collected in ``self.dead_letters`` (payload + failure reason)
  instead of aborting the whole insert. The default (None) keeps the
  historical fail-fast behavior — the first exception propagates."""

  def __init__(self, parallel: int = 1, progress: bool = True,
               max_deliveries: Optional[int] = None, drain_flag=None):
    """``drain_flag`` (anything with ``is_set()``): graceful preemption —
    the in-flight task finishes, remaining tasks are left unexecuted
    (mirrors the lease queues' drain contract for local runs)."""
    self.parallel = max(int(parallel), 1)
    self.progress = progress
    self.inserted = 0
    self.completed = 0
    self.max_deliveries = (
      None if not max_deliveries or int(max_deliveries) <= 0
      else int(max_deliveries)
    )
    self.dead_letters: list = []
    self.drain_flag = drain_flag
    self.drained = False

  def _draining(self) -> bool:
    if self.drain_flag is not None and self.drain_flag.is_set():
      self.drained = True
    return self.drained

  @property
  def backlog(self) -> int:
    """Tasks inserted but not completed or dead-lettered (insert()
    executes inline, so this is nonzero only mid-insert — kept for
    backend-uniform health plumbing, ISSUE 6)."""
    return max(self.inserted - self.completed - len(self.dead_letters), 0)

  def depth_snapshot(self) -> dict:
    return {
      "inserted": self.inserted,
      "enqueued": self.backlog,
      "leased": 0,
      "completed": self.completed,
      "backlog": self.backlog,
      "dlq": len(self.dead_letters),
    }

  def renew(self, lease_id, seconds: float = 600):
    """No-op: local tasks execute in-process with no visibility timeout;
    exists so the shared heartbeat/lifecycle plumbing is backend-uniform."""
    return lease_id

  def _record_dead_letter(self, payload: str, error: str):
    from .. import telemetry

    self.dead_letters.append({"payload": payload, "error": error})
    telemetry.incr("dlq.promoted")

  def insert(self, tasks: Iterable, total: Optional[int] = None):
    if self.parallel == 1:
      from ..pipeline import config as pipeline_config

      # a task STREAM on one process is exactly what the staged pipeline
      # exists for: download(i+1) overlaps compute(i) overlaps
      # encode/upload(i-1), byte-identical to this serial loop
      # (IGNEOUS_PIPELINE=off restores strict serial execution)
      if pipeline_config.enabled(default=True):
        return self._insert_pipelined(tasks, total)
    payloads = (serialize(t) for t in self._iter(tasks))
    bar = tqdm(
      total=total, desc="Tasks", disable=(not self.progress), unit="task"
    )
    if self.parallel == 1:
      for payload in payloads:
        if self._draining():
          break
        self.inserted += 1
        if self.max_deliveries is None:
          _execute_payload(payload)
        else:
          _p, err = _execute_payload_contained(payload, self.max_deliveries)
          if err is not None:
            self._record_dead_letter(payload, err)
            bar.update(1)
            continue
        self.completed += 1
        bar.update(1)
    else:
      ctx = mp.get_context("spawn")
      threads = max(1, (os.cpu_count() or 1) // self.parallel)
      with ctx.Pool(
        self.parallel, initializer=_worker_init, initargs=(threads,)
      ) as pool:
        if self.max_deliveries is None:
          for _ in pool.imap_unordered(
            _execute_payload, payloads, chunksize=1
          ):
            self.inserted += 1
            self.completed += 1
            bar.update(1)
            if self._draining():
              break  # pool __exit__ terminates; unconsumed payloads stay
        else:
          import functools

          runner = functools.partial(
            _execute_payload_contained, max_deliveries=self.max_deliveries
          )
          for payload, err in pool.imap_unordered(
            runner, payloads, chunksize=1
          ):
            self.inserted += 1
            if err is not None:
              self._record_dead_letter(payload, err)
            else:
              self.completed += 1
            bar.update(1)
            if self._draining():
              break
    bar.close()

  def _insert_pipelined(self, tasks: Iterable, total: Optional[int] = None):
    """parallel=1 insert through the staged pipeline (ISSUE 3).

    Semantics preserved from the serial loop: tasks round-trip through
    serialize/deserialize, ``inserted``/``completed`` tally the same
    way, drain stops admission and finishes in-flight work, fail-fast
    raises the first failure (after in-flight uploads join — a task is
    never abandoned mid-write), and ``max_deliveries`` retries failures
    solo before dead-lettering them."""
    from ..pipeline import run_tasks_pipelined
    from .filequeue import failure_reason

    bar = tqdm(
      total=total, desc="Tasks", disable=(not self.progress), unit="task"
    )

    def stream():
      for t in self._iter(tasks):
        payload = serialize(t)
        self.inserted += 1
        yield deserialize(payload)

    def on_complete(task):
      self.completed += 1
      bar.update(1)

    on_error = None
    if self.max_deliveries is not None:
      def on_error(task, exc):
        payload = serialize(task)
        if self.max_deliveries <= 1:
          self._record_dead_letter(payload, failure_reason(exc))
          bar.update(1)
          return
        # the pipelined attempt spent one delivery; the rest run solo
        _p, err = _execute_payload_contained(payload, self.max_deliveries - 1)
        if err is not None:
          self._record_dead_letter(payload, err)
        else:
          self.completed += 1
        bar.update(1)

    try:
      stats = run_tasks_pipelined(
        stream(),
        drain_flag=self.drain_flag,
        on_error=on_error,
        on_complete=on_complete,
      )
      if stats["drained"]:
        self.drained = True
    finally:
      bar.close()

  insert_all = insert
  # batched wire protocol (ISSUE 15): local execution has no wire, so the
  # batch entry point IS the streaming insert
  insert_batch = insert

  @staticmethod
  def _iter(tasks):
    if hasattr(tasks, "__iter__") and not isinstance(tasks, (str, bytes, dict)):
      return iter(tasks)
    return iter([tasks])

  def wait(self, *args, **kw):
    return self

  def __enter__(self):
    return self

  def __exit__(self, *exc):
    return False


class MockTaskQueue:
  """Serial immediate execution without serialization (debugging)."""

  def __init__(self, *args, **kw):
    pass

  def insert(self, tasks, *args, **kw):
    for task in LocalTaskQueue._iter(tasks):
      task = deserialize(serialize(task))
      task.execute()

  insert_all = insert
  insert_batch = insert

  def wait(self, *args, **kw):
    return self
