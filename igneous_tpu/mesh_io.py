"""Mesh container, Precomputed mesh codec, simplification, .frags container.

Reference equivalents: zmesh's Mesh type + cloud-volume's mesh IO
(/root/reference/igneous/tasks/mesh/mesh.py:385-450) and the mapbuffer
``.frags`` container (SURVEY.md §2.3 mapbuffer). Draco encoding defaults
to the built-in pure-numpy bitstream codec (igneous_tpu.draco) and can be
overridden via register_draco_codec; the legacy interchange format is
Precomputed (raw little-endian), which Neuroglancer also reads natively.
"""

from __future__ import annotations

import struct
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np


def drop_degenerate_faces(faces: np.ndarray) -> np.ndarray:
  """Remove faces that reference the same vertex index twice."""
  ok = (
    (faces[:, 0] != faces[:, 1])
    & (faces[:, 1] != faces[:, 2])
    & (faces[:, 0] != faces[:, 2])
  )
  return faces[ok]


class Mesh:
  """Triangle mesh: vertices (V,3) float32 physical units, faces (F,3) uint32."""

  def __init__(self, vertices: np.ndarray, faces: np.ndarray):
    self.vertices = np.asarray(vertices, dtype=np.float32).reshape(-1, 3)
    self.faces = np.asarray(faces, dtype=np.uint32).reshape(-1, 3)

  def __len__(self) -> int:
    return len(self.vertices)

  def __eq__(self, other) -> bool:
    return (
      isinstance(other, Mesh)
      and np.array_equal(self.vertices, other.vertices)
      and np.array_equal(self.faces, other.faces)
    )

  def clone(self) -> "Mesh":
    return Mesh(self.vertices.copy(), self.faces.copy())

  @classmethod
  def concatenate(cls, *meshes: "Mesh") -> "Mesh":
    if not meshes:
      return cls(np.zeros((0, 3), np.float32), np.zeros((0, 3), np.uint32))
    verts = []
    faces = []
    voff = 0
    for m in meshes:
      verts.append(m.vertices)
      faces.append(m.faces + np.uint32(voff))
      voff += len(m.vertices)
    return cls(np.concatenate(verts), np.concatenate(faces))

  def consolidate(self) -> "Mesh":
    """Weld duplicate vertices and drop degenerate faces."""
    if len(self.vertices) == 0:
      return self.clone()
    uniq, inverse = np.unique(self.vertices, axis=0, return_inverse=True)
    faces = inverse[self.faces.astype(np.int64)].astype(np.uint32)
    return Mesh(uniq, drop_degenerate_faces(faces))

  # -- codecs ---------------------------------------------------------------

  def to_precomputed(self) -> bytes:
    """Neuroglancer legacy mesh: uint32le V, float32le xyz*V, uint32le faces."""
    return (
      struct.pack("<I", len(self.vertices))
      + self.vertices.astype("<f4").tobytes()
      + self.faces.astype("<u4").tobytes()
    )

  @classmethod
  def from_precomputed(cls, data: bytes) -> "Mesh":
    (nverts,) = struct.unpack("<I", data[:4])
    vend = 4 + nverts * 12
    vertices = np.frombuffer(data[4:vend], dtype="<f4").reshape(-1, 3)
    faces = np.frombuffer(data[vend:], dtype="<u4").reshape(-1, 3)
    return cls(vertices.copy(), faces.copy())


# draco hook: defaults to the built-in pure-numpy bitstream codec
# (igneous_tpu.draco); a deployment with a native draco library can
# override it by registering its own (encode, decode) pair.
_DRACO_CODEC = None


def register_draco_codec(encode_fn, decode_fn):
  global _DRACO_CODEC
  _DRACO_CODEC = (encode_fn, decode_fn)


def _draco_codec():
  global _DRACO_CODEC
  if _DRACO_CODEC is None:
    from . import draco

    _DRACO_CODEC = (draco.encode_to_bytes, draco.decode_to_mesh)
  return _DRACO_CODEC


def encode_mesh(mesh: Mesh, encoding: str = "precomputed", **kw) -> bytes:
  if encoding == "precomputed":
    return mesh.to_precomputed()
  if encoding == "draco":
    return _draco_codec()[0](mesh, **kw)
  raise ValueError(f"Unknown mesh encoding: {encoding}")


def decode_mesh(data: bytes, encoding: str = "precomputed") -> Mesh:
  if encoding == "precomputed":
    return Mesh.from_precomputed(data)
  if encoding == "draco":
    return _draco_codec()[1](data)
  raise ValueError(f"Unknown mesh encoding: {encoding}")


# ---------------------------------------------------------------------------
# simplification


def simplify(
  mesh: Mesh,
  reduction_factor: float = 100.0,
  max_error: float = 40.0,
  max_iters: int = 8,
  placement: str = "qem",
) -> Mesh:
  """Mesh simplification toward ``faces/reduction_factor`` faces without
  exceeding ``max_error`` physical-units geometric deviation.

  Capability equivalent of zmesh's quadratic edge collapse (reference
  mesh.py:371-383) and the pyfqmr LOD reducer (reference
  multires.py:308-359). Two engines:

  * ``placement="qem"`` (default): native C++ priority-queue QEM edge
    collapse (``native/csrc/simplify.cpp``) — mean-normalized
    area-weighted Garland-Heckbert quadrics, optimal vertex placement,
    border constraints, link-condition and flip rejection. Collapsing
    stops once the cheapest collapse's summed quadric cost exceeds
    ``max_error**2`` (zmesh-style: a conservative length²-unit bound on
    accumulated squared point-plane deviation, NOT a per-point distance
    — regions whose quadrics have absorbed many planes stop collapsing
    earlier than a pointwise bound would).
  * ``placement="centroid"`` (and the fallback when the native library
    is unavailable): vectorized vertex-clustering with cell size capped
    at ``max_error`` — sort, segment sums, one batched 3x3 solve.
  """
  if placement not in ("qem", "centroid"):
    raise ValueError(f"placement must be 'qem' or 'centroid': {placement!r}")
  if len(mesh.faces) == 0 or reduction_factor <= 1:
    return mesh.clone()

  target_faces = max(int(len(mesh.faces) / reduction_factor), 4)

  if placement == "qem":
    out = _native_collapse(mesh, target_faces, max_error)
    if out is not None:
      return out
  extent = mesh.vertices.max(axis=0) - mesh.vertices.min(axis=0)
  hi_cell = float(max(extent.max(), 1.0))
  if max_error is not None and max_error > 0:
    hi_cell = min(hi_cell, float(max_error))

  # quadrics depend only on the input mesh: build once for every
  # cell-bisection iteration
  Qv = _vertex_quadrics(mesh) if placement == "qem" else None
  best = mesh
  cell = hi_cell
  for _ in range(max_iters):
    m = _cluster_collapse(mesh, cell, placement=placement, Qv=Qv)
    if len(m.faces) >= target_faces or cell >= hi_cell:
      best = m
    if len(m.faces) < target_faces:
      cell *= 0.5
    else:
      break
  return best if len(best.faces) > 0 else mesh.clone()


def _native_collapse(
  mesh: Mesh, target_faces: int, max_error, preserve_border: bool = True
) -> "Mesh | None":
  """Priority-queue QEM edge collapse via native/csrc/simplify.cpp;
  None when the native library is unavailable (caller falls back to
  clustering)."""
  import ctypes

  from .native import simplify_lib

  lib = simplify_lib()
  if lib is None:
    return None
  v = np.ascontiguousarray(mesh.vertices, dtype=np.float32)
  f = np.ascontiguousarray(mesh.faces, dtype=np.uint32)
  vout = np.empty_like(v)
  fout = np.empty_like(f)
  out_nv = ctypes.c_int64(0)
  out_nf = ctypes.c_int64(0)
  rc = lib.igsimp_simplify(
    v.ctypes.data_as(ctypes.c_void_p), len(v),
    f.ctypes.data_as(ctypes.c_void_p), len(f),
    int(target_faces),
    float(max_error) if max_error is not None and max_error > 0 else -1.0,
    1 if preserve_border else 0,
    vout.ctypes.data_as(ctypes.c_void_p),
    fout.ctypes.data_as(ctypes.c_void_p),
    ctypes.byref(out_nv), ctypes.byref(out_nf),
  )
  if rc != 0 or out_nf.value <= 0:
    return None
  return Mesh(vout[: out_nv.value].copy(), fout[: out_nf.value].copy())


def _vertex_quadrics(mesh: Mesh) -> np.ndarray:
  """Per-vertex 4x4 error quadrics: the sum of the squared-distance
  quadrics of every incident face plane (Garland-Heckbert)."""
  v = mesh.vertices.astype(np.float64)
  f = mesh.faces.astype(np.int64)
  p0, p1, p2 = v[f[:, 0]], v[f[:, 1]], v[f[:, 2]]
  n = np.cross(p1 - p0, p2 - p0)
  norm = np.linalg.norm(n, axis=1, keepdims=True)
  n = np.divide(n, norm, out=np.zeros_like(n), where=norm > 1e-12)
  d = -np.einsum("ij,ij->i", n, p0)
  plane = np.concatenate([n, d[:, None]], axis=1)  # (F, 4)
  K = plane[:, :, None] * plane[:, None, :]  # (F, 4, 4)
  Q = np.zeros((len(v), 4, 4), dtype=np.float64)
  for corner in range(3):
    np.add.at(Q, f[:, corner], K)
  return Q


def _cluster_collapse(
  mesh: Mesh, cell: float, placement: str = "qem", Qv=None
) -> Mesh:
  keys = np.floor(mesh.vertices / max(cell, 1e-6)).astype(np.int64)
  uniq, inverse = np.unique(keys, axis=0, return_inverse=True)
  nclusters = len(uniq)
  sums = np.zeros((nclusters, 3), dtype=np.float64)
  np.add.at(sums, inverse, mesh.vertices)
  counts = np.bincount(inverse, minlength=nclusters).astype(np.float64)
  centroids = sums / counts[:, None]

  if placement == "qem" and len(mesh.faces):
    # place each cluster's vertex at the point minimizing the summed
    # quadric error of its members' face planes — preserves sharp
    # features that plain centroids smear (Garland-Heckbert placement
    # over Rossignac-Borrel clustering)
    if Qv is None:
      Qv = _vertex_quadrics(mesh)
    Qc = np.zeros((nclusters, 4, 4), dtype=np.float64)
    np.add.at(Qc, inverse, Qv)
    A = Qc[:, :3, :3]
    b = -Qc[:, :3, 3]
    placed = centroids.copy()
    # batch-solve the well-conditioned systems; singular ones (flat or
    # degenerate neighborhoods) keep the centroid
    dets = np.abs(np.linalg.det(A))
    scale = np.maximum(np.abs(A).sum(axis=(1, 2)), 1e-12) ** 3
    good = dets > 1e-10 * scale
    if good.any():
      sol = np.linalg.solve(A[good], b[good][..., None])[..., 0]
      # reject wild extrapolations outside the cluster cell
      near = np.all(np.abs(sol - centroids[good]) <= 2.0 * cell, axis=1)
      idx = np.flatnonzero(good)[near]
      placed[idx] = sol[near]
    centroids = placed

  faces = inverse[mesh.faces.astype(np.int64)].astype(np.uint32)
  return Mesh(centroids.astype(np.float32), drop_degenerate_faces(faces))


# ---------------------------------------------------------------------------
# .frags container (mapbuffer equivalent)


class FragMap:
  """Zero-parse random-access uint64 → bytes container.

  Capability parity with mapbuffer's MapBuffer (the ``.frags`` files of
  sharded mesh/skeleton stage 1, reference tasks/mesh/mesh.py:385-397).
  Layout (little endian):
    magic b'IGFM' | uint32 version | uint64 N
    uint64 keys[N] (sorted) | uint64 offsets[N+1] (into blob section)
    blobs
  Lookups binary-search the key table; nothing else is parsed.
  """

  MAGIC = b"IGFM"

  def __init__(self, data: bytes):
    if data[:4] != self.MAGIC:
      raise ValueError("not a FragMap")
    self._data = data
    (self._n,) = struct.unpack_from("<Q", data, 8)
    ko = 16
    self._keys = np.frombuffer(data, dtype="<u8", count=self._n, offset=ko)
    self._offsets = np.frombuffer(
      data, dtype="<u8", count=self._n + 1, offset=ko + 8 * self._n
    )
    self._blob0 = ko + 8 * self._n + 8 * (self._n + 1)

  @classmethod
  def frombytes(cls, data: bytes) -> "FragMap":
    return cls(data)

  @classmethod
  def tobytes(cls, mapping: Dict[int, bytes]) -> bytes:
    keys = sorted(mapping.keys())
    blobs = [mapping[k] for k in keys]
    offsets = np.zeros(len(keys) + 1, dtype="<u8")
    np.cumsum([len(b) for b in blobs], out=offsets[1:])
    return b"".join([
      cls.MAGIC,
      struct.pack("<I", 1),
      struct.pack("<Q", len(keys)),
      np.asarray(keys, dtype="<u8").tobytes(),
      offsets.tobytes(),
      *blobs,
    ])

  def __len__(self) -> int:
    return int(self._n)

  def __contains__(self, key: int) -> bool:
    return self.get(key) is not None

  def keys(self) -> np.ndarray:
    return self._keys

  def get(self, key: int) -> Optional[bytes]:
    i = int(np.searchsorted(self._keys, np.uint64(key)))
    if i >= self._n or self._keys[i] != np.uint64(key):
      return None
    a = self._blob0 + int(self._offsets[i])
    b = self._blob0 + int(self._offsets[i + 1])
    return self._data[a:b]

  def __getitem__(self, key: int) -> bytes:
    out = self.get(key)
    if out is None:
      raise KeyError(key)
    return out

  def items(self) -> Iterator[Tuple[int, bytes]]:
    for i in range(self._n):
      a = self._blob0 + int(self._offsets[i])
      b = self._blob0 + int(self._offsets[i + 1])
      yield int(self._keys[i]), self._data[a:b]
