"""PyChunkGraph HTTP client: the real graphene:// wire protocol
(VERDICT r3 item 8).

``graphene://https://server/segmentation/api/v1/table/<id>`` volumes talk
to a PCG server. This client speaks the REST surface the reference stack
exercises through CloudVolume (reference
igneous/tasks/mesh/mesh.py:466-622 GrapheneMeshTask downloads at
stop_layer 1/2 with timestamps; tasks/skeleton.py:337-400 builds the
autapse voxel-connectivity graph from L2 + root label fields):

  * ``GET  {base}/info`` — graphene metadata: the ``graph`` section
    (chunk_size, n_layers) and ``data_dir`` (the watershed layer the
    Precomputed chunks actually live in).
  * ``POST {base}/node/roots_binary?timestamp=T[&stop_layer=N]`` —
    supervoxel ids in (little-endian uint64 array), mapped node ids out.
    stop_layer=2 yields L2 ids; omitted yields roots. Ids are deduplicated
    client-side before the POST (cutouts repeat each supervoxel
    thousands of times).
  * ``GET  {base}/root/{root_id}/tabular_change_log`` — the merge/split
    operation log for a root (proofreading provenance).

The voxel-connectivity graph is computed exactly the way the reference
does it (skeleton.py:337-400): direction bitfields over the L2 label
field, with graph-chunk boundary planes shaded from the root-level field
— an approximation PCG deployments accept (the reference's own comment:
"the error rate should be over 100x less" than naive root connectivity).

Tested against the in-process fake PCG server in
tests/fake_pcg_server.py; the real endpoint is unreachable from this
zero-egress image.
"""

from __future__ import annotations

import json
import os
import urllib.parse
from typing import Optional

import numpy as np

from .retry import default_policy
from .storage_http import HttpError, request


_AUTH_CACHE: dict = {}


def _auth_header() -> dict:
  """CAVE/PCG deployments use a bearer token from
  ``~/.cloudvolume/secrets/cave-secret.json`` (or chunkedgraph-secret) —
  honor the same convention. Successful loads cache per secrets dir
  (this sits on the hot download path); a MISSING token is never cached,
  so a long-running worker picks up a token provisioned after startup,
  and a 401/403 invalidates the cache (_invalidate_auth) so a rotated
  secret file is re-read. A secret file without a usable ``token`` key
  falls through to the next candidate instead of ending the search."""
  from . import secrets

  tok = os.environ.get("CAVE_TOKEN")
  if not tok:
    sdir = secrets.secrets_dir()
    tok = _AUTH_CACHE.get(sdir)
    if not tok:
      for name in ("cave-secret.json", "chunkedgraph-secret.json"):
        path = os.path.join(sdir, name)
        if not os.path.exists(path):
          continue
        with open(path) as f:
          blob = json.load(f)
        tok = blob.get("token")
        if tok:
          _AUTH_CACHE[sdir] = tok
          break
  return {"Authorization": f"Bearer {tok}"} if tok else {}


def _invalidate_auth() -> None:
  _AUTH_CACHE.clear()


def _auth_request(method: str, url: str, data=None, extra_headers=None):
  """request() with the bearer header, retried ONCE with a re-read token
  on 401/403 — so a worker whose secret was rotated (or provisioned late)
  recovers without a restart."""
  headers = dict(extra_headers or {})
  # unified retry schedule (retry.RetryPolicy): transient 5xx/connection
  # faults back off the same way the storage backends do
  policy = default_policy()
  try:
    return request(method, url, data=data,
                   headers={**headers, **_auth_header()}, policy=policy)
  except HttpError as e:
    # an env-var token can't be refreshed by re-reading secret files —
    # retrying would resend the identical request
    if e.status not in (401, 403) or os.environ.get("CAVE_TOKEN"):
      raise
    _invalidate_auth()
    return request(method, url, data=data,
                   headers={**headers, **_auth_header()}, policy=policy)


class PCGClient:
  """GrapheneClient protocol over the PyChunkGraph REST API."""

  def __init__(self, base_url: str):
    self.base = base_url.rstrip("/")
    self._info: Optional[dict] = None

  # -- metadata -------------------------------------------------------------

  @property
  def info(self) -> dict:
    if self._info is None:
      status, _h, body = _auth_request("GET", f"{self.base}/info")
      if status != 200:
        raise HttpError(status, f"{self.base}/info", body)
      self._info = json.loads(body)
    return self._info

  @property
  def chunk_size(self):
    return tuple(int(v) for v in self.info["graph"]["chunk_size"])

  @property
  def data_dir(self) -> Optional[str]:
    """Watershed layer path the Precomputed chunks live in."""
    return self.info.get("data_dir")

  # -- node mapping ---------------------------------------------------------

  def _map_nodes(
    self,
    supervoxels: np.ndarray,
    timestamp: Optional[float],
    stop_layer: Optional[int],
  ) -> np.ndarray:
    sv = np.asarray(supervoxels, dtype=np.uint64)
    uniq, inv = np.unique(sv, return_inverse=True)
    send = uniq[uniq != 0]
    out_uniq = np.zeros_like(uniq)
    if len(send):
      params = []
      if timestamp is not None:
        params.append(f"timestamp={urllib.parse.quote(str(timestamp))}")
      if stop_layer is not None:
        params.append(f"stop_layer={int(stop_layer)}")
      url = f"{self.base}/node/roots_binary"
      if params:
        url += "?" + "&".join(params)
      status, _h, body = _auth_request(
        "POST", url, data=send.astype("<u8").tobytes(),
        extra_headers={"Content-Type": "application/octet-stream"},
      )
      if status != 200:
        raise HttpError(status, url, body)
      mapped = np.frombuffer(body, dtype="<u8")
      if len(mapped) != len(send):
        raise ValueError(
          f"roots_binary returned {len(mapped)} ids for {len(send)} nodes"
        )
      out_uniq[uniq != 0] = mapped
    return out_uniq[inv].reshape(sv.shape)

  def get_roots(self, supervoxels, timestamp=None) -> np.ndarray:
    return self._map_nodes(supervoxels, timestamp, None)

  def get_l2_ids(self, supervoxels, voxel_chunks, timestamp=None) -> np.ndarray:
    """L2 node per voxel. PCG supervoxel ids encode their chunk, so the
    mapping is per-supervoxel and ``voxel_chunks`` (needed by the
    in-process LocalChunkGraph whose test ids carry no chunk info) is
    not sent over the wire."""
    del voxel_chunks
    return self._map_nodes(supervoxels, timestamp, 2)

  # -- merge log ------------------------------------------------------------

  def change_log(self, root_id: int) -> dict:
    """Merge/split operation log for one root
    (``tabular_change_log``): {"operations": [{"is_merge": bool,
    "timestamp": float, "sink": [...], "source": [...]}, ...]}."""
    url = f"{self.base}/root/{int(root_id)}/tabular_change_log"
    status, _h, body = _auth_request("GET", url)
    if status != 200:
      raise HttpError(status, url, body)
    return json.loads(body)

  # -- voxel connectivity graph --------------------------------------------

  def voxel_connectivity_graph(
    self, supervoxels, connectivity: int = 26, timestamp=None,
    offset=(0, 0, 0), downsample_ratio=(1, 1, 1),
  ) -> np.ndarray:
    """Reference-style (skeleton.py:337-400): bitfields of the L2 label
    field, graph-chunk boundary planes shaded from the root field.

    ``offset`` is the cutout's global minpt at its mip and
    ``downsample_ratio`` the mip→base scale: boundary planes are located
    on the GLOBAL graph-chunk grid (the reference shades relative to the
    cutout origin, which is only correct for chunk-aligned tasks)."""
    from .ops.ccl import voxel_connectivity_graph as _vcg

    sv = np.asarray(supervoxels)
    l2 = self._map_nodes(sv, timestamp, 2)
    vcg = _vcg(l2, connectivity)

    roots = self._map_nodes(sv, timestamp, None)
    root_vcg = _vcg(roots, connectivity)

    gcs = np.maximum(
      np.asarray(self.chunk_size) // np.asarray(downsample_ratio), 1
    ).astype(np.int64)
    off = np.asarray(offset, dtype=np.int64)
    shape = np.asarray(sv.shape[:3], dtype=np.int64)
    g_lo = (off // gcs).astype(np.int64)
    g_hi = -(-(off + shape) // gcs)  # ceil of global max in chunk units
    for gx in range(int(g_lo[0]), int(g_hi[0])):
      for gy in range(int(g_lo[1]), int(g_hi[1])):
        for gz in range(int(g_lo[2]), int(g_hi[2])):
          lo = np.maximum(np.array([gx, gy, gz]) * gcs - off, 0)
          hi = np.minimum((np.array([gx, gy, gz]) + 1) * gcs - off, shape)
          if (lo >= hi).any():
            continue
          for axis in range(3):
            for plane in (lo[axis], hi[axis] - 1):
              sl = [slice(int(a), int(b)) for a, b in zip(lo, hi)]
              sl[axis] = slice(int(plane), int(plane) + 1)
              sl = tuple(sl)
              vcg[sl] = root_vcg[sl]
    return vcg


def parse_graphene_server(inner_path: str) -> Optional[str]:
  """graphene:// inner paths addressing a PCG server start with http(s)."""
  if inner_path.startswith(("http://", "https://")):
    return inner_path
  return None
