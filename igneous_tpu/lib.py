"""Geometry primitives: integer vectors, bounding boxes, and grid math.

This is the substrate every layer of the framework cites. It provides the
same capabilities as the reference's data-plane geometry (cloudvolume.lib
``Vec``/``Bbox``, used throughout e.g. /root/reference/igneous/tasks/image/image.py)
but is a fresh, minimal implementation designed around numpy int64 arrays.

Conventions:
  - All voxel coordinates are (x, y, z) triples.
  - ``Bbox`` is half-open: [minpt, maxpt).
  - Chunk/grid alignment helpers take an ``offset`` (the volume's voxel_offset)
    because Precomputed chunk grids are anchored at the voxel offset, not 0.
"""

from __future__ import annotations

import re
from typing import Iterable, Iterator, Sequence, Tuple, Union

import numpy as np

VecLike = Union[Sequence[int], Sequence[float], np.ndarray, "Vec"]


class Vec(np.ndarray):
  """A small numpy vector with .x/.y/.z accessors (always a 1-D array)."""

  def __new__(cls, *args, dtype=None):
    if len(args) == 1 and isinstance(args[0], (list, tuple, np.ndarray)):
      args = tuple(args[0])
    if dtype is None:
      dtype = np.float64 if any(isinstance(a, float) for a in args) else np.int64
    return np.asarray(args, dtype=dtype).view(cls)

  @classmethod
  def clamp(cls, val: VecLike, low: VecLike, high: VecLike) -> "Vec":
    return Vec(*np.clip(np.asarray(val), np.asarray(low), np.asarray(high)))

  @property
  def x(self):
    return self[0]

  @property
  def y(self):
    return self[1]

  @property
  def z(self):
    return self[2]

  def clone(self) -> "Vec":
    return Vec(*self)

  def astype_int(self) -> "Vec":
    return Vec(*[int(v) for v in self])

  def rectVolume(self):
    return int(np.prod(np.asarray(self, dtype=np.int64)))

  # Vec is a coordinate type: == / != compare whole coordinates (bool), so
  # Vecs work as dict/set keys. Use np.asarray(v) first for elementwise math.
  def __eq__(self, other):  # type: ignore[override]
    return bool(np.array_equal(np.asarray(self), np.asarray(other)))

  def __ne__(self, other):  # type: ignore[override]
    return not self.__eq__(other)

  def __hash__(self):  # type: ignore[override]
    return hash(tuple(self))


def floor_div(a: np.ndarray, b: np.ndarray) -> np.ndarray:
  return np.floor_divide(a, b)


def ceil_div(a, b) -> np.ndarray:
  a = np.asarray(a, dtype=np.int64)
  b = np.asarray(b, dtype=np.int64)
  return -(-a // b)


class Bbox:
  """Half-open integer bounding box [minpt, maxpt) in voxel coordinates."""

  __slots__ = ("minpt", "maxpt", "dtype")

  def __init__(self, minpt: VecLike, maxpt: VecLike, dtype=np.int64):
    self.minpt = Vec(*minpt, dtype=dtype)
    self.maxpt = Vec(*maxpt, dtype=dtype)
    self.dtype = dtype

  # -- constructors ---------------------------------------------------------

  @classmethod
  def from_shape(cls, shape: VecLike) -> "Bbox":
    return cls((0,) * len(tuple(shape)), shape)

  @classmethod
  def from_delta(cls, minpt: VecLike, plus: VecLike) -> "Bbox":
    minpt = Vec(*minpt)
    return cls(minpt, minpt + Vec(*plus))

  @classmethod
  def from_slices(cls, slices: Sequence[slice]) -> "Bbox":
    return cls([s.start for s in slices], [s.stop for s in slices])

  @classmethod
  def from_list(cls, lst: Sequence[int]) -> "Bbox":
    n = len(lst) // 2
    return cls(lst[:n], lst[n:])

  _FILENAME_RE = re.compile(r"(-?\d+)-(-?\d+)_(-?\d+)-(-?\d+)_(-?\d+)-(-?\d+)")

  @classmethod
  def from_filename(cls, filename: str) -> "Bbox":
    """Parse the Precomputed chunk-name convention ``x0-x1_y0-y1_z0-z1``."""
    m = cls._FILENAME_RE.search(filename)
    if m is None:
      raise ValueError(f"Not a chunk filename: {filename}")
    g = [int(v) for v in m.groups()]
    return cls((g[0], g[2], g[4]), (g[1], g[3], g[5]))

  # -- geometry -------------------------------------------------------------

  def size3(self) -> Vec:
    return Vec(*(self.maxpt - self.minpt))

  size = size3

  def volume(self) -> int:
    return int(np.prod(np.maximum(self.maxpt - self.minpt, 0)))

  def center(self) -> Vec:
    return Vec(*((self.minpt + self.maxpt) / 2.0))

  def empty(self) -> bool:
    return bool(np.any(self.maxpt <= self.minpt))

  def valid(self) -> bool:
    return bool(np.all(self.maxpt >= self.minpt))

  def clone(self) -> "Bbox":
    return Bbox(self.minpt, self.maxpt, dtype=self.dtype)

  def contains(self, pt: VecLike) -> bool:
    pt = np.asarray(pt)
    return bool(np.all(pt >= self.minpt) and np.all(pt < self.maxpt))

  def contains_bbox(self, other: "Bbox") -> bool:
    return bool(
      np.all(other.minpt >= self.minpt) and np.all(other.maxpt <= self.maxpt)
    )

  @classmethod
  def intersection(cls, a: "Bbox", b: "Bbox") -> "Bbox":
    mn = np.maximum(a.minpt, b.minpt)
    mx = np.minimum(a.maxpt, b.maxpt)
    mx = np.maximum(mn, mx)
    return cls(mn, mx)

  @classmethod
  def intersects(cls, a: "Bbox", b: "Bbox") -> bool:
    return not cls.intersection(a, b).empty()

  @classmethod
  def expand(cls, *boxes: "Bbox") -> "Bbox":
    mn = np.min([b.minpt for b in boxes], axis=0)
    mx = np.max([b.maxpt for b in boxes], axis=0)
    return cls(mn, mx)

  def clamp(self, other: "Bbox") -> "Bbox":
    return Bbox.intersection(self, other)

  def translate(self, delta: VecLike) -> "Bbox":
    d = Vec(*delta)
    return Bbox(self.minpt + d, self.maxpt + d)

  def grow(self, amt: Union[int, VecLike]) -> "Bbox":
    amt = np.asarray(amt, dtype=np.int64)
    return Bbox(self.minpt - amt, self.maxpt + amt)

  def shrink(self, amt: Union[int, VecLike]) -> "Bbox":
    return self.grow(-np.asarray(amt, dtype=np.int64))

  # scaling between mips
  def __truediv__(self, factor) -> "Bbox":
    f = np.asarray(factor)
    return Bbox(self.minpt // f, ceil_div(self.maxpt, f))

  def __mul__(self, factor) -> "Bbox":
    f = np.asarray(factor)
    return Bbox(self.minpt * f, self.maxpt * f)

  def scale(self, factor) -> "Bbox":
    """Exact scale for downsample factor math: floor min, ceil max."""
    return self / factor

  # -- chunk alignment ------------------------------------------------------

  def expand_to_chunk_size(self, chunk_size: VecLike, offset: VecLike = (0, 0, 0)) -> "Bbox":
    cs = np.asarray(chunk_size, dtype=np.int64)
    off = np.asarray(offset, dtype=np.int64)
    mn = (self.minpt - off) // cs * cs + off
    mx = ceil_div(self.maxpt - off, cs) * cs + off
    return Bbox(mn, mx)

  def shrink_to_chunk_size(self, chunk_size: VecLike, offset: VecLike = (0, 0, 0)) -> "Bbox":
    cs = np.asarray(chunk_size, dtype=np.int64)
    off = np.asarray(offset, dtype=np.int64)
    mn = ceil_div(self.minpt - off, cs) * cs + off
    mx = (self.maxpt - off) // cs * cs + off
    mx = np.maximum(mn, mx)
    return Bbox(mn, mx)

  def round_to_chunk_size(self, chunk_size: VecLike, offset: VecLike = (0, 0, 0)) -> "Bbox":
    cs = np.asarray(chunk_size, dtype=np.int64)
    off = np.asarray(offset, dtype=np.int64)
    mn = np.round((self.minpt - off) / cs).astype(np.int64) * cs + off
    mx = np.round((self.maxpt - off) / cs).astype(np.int64) * cs + off
    return Bbox(mn, mx)

  # -- conversions ----------------------------------------------------------

  def to_slices(self) -> Tuple[slice, ...]:
    return tuple(slice(int(a), int(b)) for a, b in zip(self.minpt, self.maxpt))

  def to_filename(self) -> str:
    return "_".join(
      f"{int(a)}-{int(b)}" for a, b in zip(self.minpt, self.maxpt)
    )

  def to_list(self):
    return [int(v) for v in self.minpt] + [int(v) for v in self.maxpt]

  # -- dunder ---------------------------------------------------------------

  def __eq__(self, other) -> bool:
    if not isinstance(other, Bbox):
      return NotImplemented
    return bool(
      np.array_equal(self.minpt, other.minpt)
      and np.array_equal(self.maxpt, other.maxpt)
    )

  def __hash__(self):
    return hash(tuple(self.to_list()))

  def __repr__(self):
    return f"Bbox({list(map(int, self.minpt))}, {list(map(int, self.maxpt))})"


def xyzrange(start, stop=None, step=None) -> Iterator[Vec]:
  """Iterate integer grid coordinates in Fortran order (x fastest)."""
  if stop is None:
    start, stop = np.zeros(len(tuple(start)), dtype=np.int64), start
  start = np.asarray(start, dtype=np.int64)
  stop = np.asarray(stop, dtype=np.int64)
  if step is None:
    step = np.ones_like(start)
  step = np.asarray(step, dtype=np.int64)

  rngs = [range(int(a), int(b), int(s)) for a, b, s in zip(start, stop, step)]
  # x varies fastest to mirror chunk-file enumeration order
  for z in rngs[2]:
    for y in rngs[1]:
      for x in rngs[0]:
        yield Vec(x, y, z)


def chunk_bboxes(
  bounds: Bbox,
  chunk_size: VecLike,
  offset: VecLike = (0, 0, 0),
  clamp: bool = True,
) -> Iterator[Bbox]:
  """Enumerate grid-aligned chunk bboxes covering ``bounds``."""
  cs = Vec(*chunk_size)
  aligned = bounds.expand_to_chunk_size(cs, offset)
  for pt in xyzrange(aligned.minpt, aligned.maxpt, cs):
    bbx = Bbox(pt, pt + cs)
    if clamp:
      bbx = Bbox.intersection(bbx, bounds)
    if not bbx.empty():
      yield bbx


def jsonify(obj) -> object:
  """Recursively convert numpy scalars/arrays to JSON-safe python types."""
  if isinstance(obj, dict):
    return {k: jsonify(v) for k, v in obj.items()}
  if isinstance(obj, (list, tuple)):
    return [jsonify(v) for v in obj]
  if isinstance(obj, np.ndarray):
    return [jsonify(v) for v in obj.tolist()]
  if isinstance(obj, np.integer):
    return int(obj)
  if isinstance(obj, np.floating):
    return float(obj)
  if isinstance(obj, bytes):
    return obj.decode("utf8")
  return obj


def sip(iterable: Iterable, block_size: int) -> Iterator[list]:
  """Yield lists of up to ``block_size`` items from ``iterable``."""
  block = []
  for item in iterable:
    block.append(item)
    if len(block) == block_size:
      yield block
      block = []
  if block:
    yield block


def toabs(path: str) -> str:
  import os

  return os.path.abspath(os.path.expanduser(path))
