"""Chunked Precomputed volume IO — the data plane of the framework.

Capability-parity target: the subset of CloudVolume the reference pipeline
uses for image IO (download/upload of bbox cutouts at a mip, fill_missing,
bounded clamping, renumbered downloads, chunk-aligned writes, deletion) —
see /root/reference/igneous/tasks/image/image.py:434-517 for the canonical
consumer. Mesh/skeleton sub-clients live in their own modules
(``igneous_tpu.mesh_io``, ``igneous_tpu.skeleton_io``).

Design: pure host IO. Device compute happens in ``igneous_tpu.ops`` on
arrays produced here; this layer stays numpy so the TPU never blocks on
object-store latency (tasks batch many cutouts per device step instead).
"""

from __future__ import annotations

import concurrent.futures as cf
from typing import List, Optional, Sequence, Tuple, Union

import numpy as np

from . import chunk_cache, codecs, integrity, telemetry
from .lib import Bbox, Vec, chunk_bboxes, jsonify
from .meta import PrecomputedMetadata
from .storage import CloudFiles, decompress_bytes

IO_THREADS = 8


class VolumeException(Exception):
  pass


class OutOfBoundsError(VolumeException):
  pass


class AlignmentError(VolumeException):
  pass


class EmptyVolumeError(VolumeException):
  pass


def _renumber(img: np.ndarray, preserve_zero: bool = True):
  """fastremap.renumber parity; see ops.remap (single implementation)."""
  from .ops.remap import renumber

  return renumber(img, start=1, preserve_zero=preserve_zero)


class Volume:
  """A Precomputed volume rooted at ``cloudpath`` (file:// or mem://)."""

  def __init__(
    self,
    cloudpath: str,
    mip: int = 0,
    fill_missing: bool = False,
    bounded: bool = True,
    non_aligned_writes: bool = False,
    delete_black_uploads: bool = False,
    background_color: int = 0,
    info: Optional[dict] = None,
    progress: bool = False,
    parallel: int = 1,
  ):
    from .graphene import (
      graphene_client,
      is_graphene,
      watershed_path,
    )

    self.graphene = None
    if is_graphene(cloudpath):
      # proofreading volume: metadata/chunks come from the watershed
      # (supervoxel) layer; the chunk-graph client supplies the
      # supervoxel->root and ->L2 mappings on download
      self.graphene = graphene_client(cloudpath)
      # server-addressed graphene volumes publish the watershed layer
      # location in their /info (data_dir); local doubles embed it in
      # the cloudpath itself
      cloudpath = (
        getattr(self.graphene, "data_dir", None)
        or watershed_path(cloudpath)
      )
    self.meta = PrecomputedMetadata(cloudpath, info=info)
    self.cloudpath = self.meta.cloudpath
    self.cf = self.meta.cf
    self.mip = mip
    self.fill_missing = fill_missing
    self.bounded = bounded
    self.non_aligned_writes = non_aligned_writes
    self.delete_black_uploads = delete_black_uploads
    self.background_color = background_color
    self.progress = progress
    self.parallel = parallel

  # -- constructors ---------------------------------------------------------

  @classmethod
  def create_new_info(cls, *args, **kw) -> dict:
    return PrecomputedMetadata.create_info(*args, **kw)

  @classmethod
  def create(cls, cloudpath: str, info: dict, **kw) -> "Volume":
    meta = PrecomputedMetadata(cloudpath, info=info)
    meta.commit_info()
    meta.refresh_provenance()
    meta.commit_provenance()
    return cls(cloudpath, **kw)

  @classmethod
  def from_numpy(
    cls,
    arr: np.ndarray,
    cloudpath: str,
    resolution: Sequence[int] = (1, 1, 1),
    voxel_offset: Sequence[int] = (0, 0, 0),
    chunk_size: Sequence[int] = (64, 64, 64),
    layer_type: Optional[str] = None,
    encoding: str = "raw",
    encoding_level: Optional[int] = None,
    max_mip: int = 0,
    compress="gzip",
  ) -> "Volume":
    if arr.ndim == 3:
      arr = arr[..., np.newaxis]
    if layer_type is None:
      layer_type = (
        "segmentation" if np.issubdtype(arr.dtype, np.unsignedinteger)
        and arr.dtype.itemsize >= 4 else "image"
      )
    info = cls.create_new_info(
      num_channels=arr.shape[3],
      layer_type=layer_type,
      data_type=np.dtype(arr.dtype).name,
      encoding=encoding,
      resolution=resolution,
      voxel_offset=voxel_offset,
      volume_size=arr.shape[:3],
      chunk_size=chunk_size,
    )
    if max_mip != 0:
      raise NotImplementedError(
        "max_mip: build mips with create_downsampling_tasks after ingest"
      )
    vol = cls.create(cloudpath, info)
    if encoding_level is not None:
      # must precede the upload: the quality knob lives in the scale
      vol.meta.set_encoding(0, None, encoding_level)
      vol.commit_info()
    vol.upload(vol.meta.bounds(0), arr, mip=0, compress=compress)
    return vol

  # -- properties -----------------------------------------------------------

  @property
  def info(self) -> dict:
    return self.meta.info

  @property
  def layer_type(self) -> str:
    return self.meta.layer_type

  @property
  def dtype(self) -> np.dtype:
    return self.meta.dtype

  @property
  def num_channels(self) -> int:
    return self.meta.num_channels

  @property
  def bounds(self) -> Bbox:
    return self.meta.bounds(self.mip)

  @property
  def chunk_size(self) -> Vec:
    return self.meta.chunk_size(self.mip)

  @property
  def resolution(self) -> Vec:
    return self.meta.resolution(self.mip)

  @property
  def voxel_offset(self) -> Vec:
    return self.meta.voxel_offset(self.mip)

  @property
  def volume_size(self) -> Vec:
    return self.meta.volume_size(self.mip)

  @property
  def shape(self) -> Tuple[int, int, int, int]:
    s = self.volume_size
    return (int(s.x), int(s.y), int(s.z), self.num_channels)

  @property
  def encoding(self) -> str:
    return self.meta.encoding(self.mip)

  def mip_bounds(self, mip: int) -> Bbox:
    return self.meta.bounds(mip)

  def mip_chunk_size(self, mip: int) -> Vec:
    return self.meta.chunk_size(mip)

  def mip_resolution(self, mip: int) -> Vec:
    return self.meta.resolution(mip)

  def mip_volume_size(self, mip: int) -> Vec:
    return self.meta.volume_size(mip)

  def mip_voxel_offset(self, mip: int) -> Vec:
    return self.meta.voxel_offset(mip)

  def commit_info(self):
    self.meta.commit_info()

  def refresh_info(self):
    self.meta.refresh_info()

  def commit_provenance(self):
    self.meta.commit_provenance()

  @property
  def provenance(self):
    if self.meta.provenance is None:
      self.meta.refresh_provenance()
    return self.meta.provenance

  # -- download -------------------------------------------------------------

  def _decode_chunk(
    self, data: Optional[bytes], chunk_bbx: Bbox, mip: int,
    writable: bool = True,
  ) -> np.ndarray:
    shape = tuple(int(v) for v in chunk_bbx.size3()) + (self.num_channels,)
    if data is None:
      if not self.fill_missing:
        raise EmptyVolumeError(
          f"Missing chunk {self.meta.chunk_name(mip, chunk_bbx)} in {self.cloudpath}"
        )
      return np.full(shape, self.background_color, dtype=self.dtype)
    return codecs.decode(
      data,
      self.meta.encoding(mip),
      shape,
      self.dtype,
      block_size=self.meta.cseg_block_size(mip),
      writable=writable,
    )

  def _decode_stored(
    self, stored, chunk_bbx: Bbox, mip: int
  ) -> np.ndarray:
    """Decode a (stored bytes, wire method) pair through the shared chunk
    decode cache: a digest hit skips BOTH the inflate and the chunk codec.
    Returns a read-only chunk — every caller copies voxels into its own
    cutout assembly (the ``writable=False`` contract)."""
    data, method = stored
    if data is None:
      return self._decode_chunk(None, chunk_bbx, mip, writable=False)
    encoding = self.meta.encoding(mip)
    # uncompressed raw chunks decode as a zero-copy view; caching those
    # would spend budget to save nothing
    cacheable = chunk_cache.enabled() and (
      method is not None or encoding != "raw"
    )
    if not cacheable:
      return self._guarded_decode(data, method, chunk_bbx, mip)
    bbox_key = (
      tuple(int(v) for v in chunk_bbx.minpt),
      tuple(int(v) for v in chunk_bbx.maxpt),
    )
    key, arr = chunk_cache.lookup(self.cloudpath, mip, bbox_key, data)
    if arr is not None:
      return arr
    # a corrupt chunk raises out of the guarded decode BEFORE
    # chunk_cache.store — no cache tier ever holds bytes that failed
    # to decode, and the digest-keyed lookup above cannot alias a
    # corrupt wire body onto a previously-cached clean decode
    arr = self._guarded_decode(data, method, chunk_bbx, mip)
    return chunk_cache.store(key, arr)

  def _guarded_decode(
    self, data: bytes, method: Optional[str], chunk_bbx: Bbox, mip: int
  ) -> np.ndarray:
    """Inflate + codec-decode with the read-path corruption guard: a
    torn or bit-flipped object at rest surfaces as a typed
    :class:`~igneous_tpu.integrity.CorruptChunkError` (never an opaque
    zlib/codec traceback), ticks ``integrity.corrupt_reads``, and files
    the object reference in the layer's quarantine ledger."""
    import zlib

    try:
      return self._decode_chunk(
        decompress_bytes(data, method), chunk_bbx, mip, writable=False
      )
    except (OSError, EOFError, ValueError, zlib.error) as e:
      key = self.meta.chunk_name(mip, chunk_bbx)
      telemetry.incr("integrity.corrupt_reads")
      reason = f"{type(e).__name__}: {e}"
      integrity.quarantine(self.cloudpath, key, reason)
      raise integrity.CorruptChunkError(self.cloudpath, key, reason) from e

  def download(
    self,
    bbox: Bbox,
    mip: Optional[int] = None,
    renumber: bool = False,
    label: Optional[int] = None,
    parallel: Optional[int] = None,
    agglomerate: bool = False,
    timestamp: Optional[float] = None,
    stop_layer: Optional[int] = None,
  ):
    """Download cutout; returns (x, y, z, c) array (plus mapping if renumber).

    Graphene volumes additionally accept ``agglomerate`` (map supervoxels
    to proofread root ids as of ``timestamp``) and ``stop_layer=2`` (map
    to L2 chunk-graph ids) — the reference's
    ``download(agglomerate, timestamp, stop_layer)`` surface
    (/root/reference/igneous/tasks/skeleton.py:159-164,:337-398).
    """
    if (agglomerate or stop_layer is not None) and self.graphene is None:
      raise ValueError(
        "agglomerate/stop_layer require a graphene:// volume"
      )
    if stop_layer not in (None, 1, 2):
      # pure argument validation: reject before any chunk is fetched
      raise ValueError(
        f"stop_layer={stop_layer!r} unsupported: 1 (supervoxels) and "
        "2 (L2 chunk ids) are the graphene stop layers"
      )
    mip = self.mip if mip is None else mip
    bbox = Bbox(bbox.minpt, bbox.maxpt)
    bounds = self.meta.bounds(mip)
    if self.bounded:
      if not bounds.contains_bbox(bbox):
        raise OutOfBoundsError(f"{bbox} is not contained in {bounds}")
      inner = bbox
    else:
      inner = Bbox.intersection(bbox, bounds)

    if self.meta.is_sharded(mip):
      from .sharded_image import download_sharded

      renders = download_sharded(self, inner, mip)
    else:
      # stored chunks are grid-aligned and clamped to the volume bounds
      chunks = [
        c
        for c in (
          Bbox.intersection(gc, bounds)
          for gc in chunk_bboxes(
            inner,
            self.meta.chunk_size(mip),
            offset=self.meta.voxel_offset(mip),
            clamp=False,
          )
        )
        if not c.empty()
      ]
      keys = [self.meta.chunk_name(mip, c) for c in chunks]
      stored = self._parallel_get_stored(keys, parallel)
      # read-only decode (possibly straight from the shared decode
      # cache): the voxels are copied into the assembly buffer below, so
      # a writable defensive copy here would be pure overhead
      renders = [
        (c, self._decode_stored(s, c, mip))
        for c, s in zip(chunks, stored)
      ]

    # Fortran order end to end: decoded chunks are F-order views, the
    # device layout (c,z,y,x) is a zero-copy transpose of an F-order
    # cutout, and raw encode is tobytes("F") — C-order assembly here would
    # force a full-volume transpose copy on BOTH sides of the compute.
    out_shape = tuple(int(v) for v in bbox.size3()) + (self.num_channels,)
    if inner == bbox:
      # the chunk grid covers every output voxel (missing chunks arrive
      # background-filled): skip the background memset
      out = np.empty(out_shape, dtype=self.dtype, order="F")
    else:
      out = np.full(
        out_shape, self.background_color, dtype=self.dtype, order="F"
      )
    for chunk_bbx, chunk_img in renders:
      isect = Bbox.intersection(chunk_bbx, bbox)
      if isect.empty():
        continue
      dst = tuple(
        slice(int(a), int(b))
        for a, b in zip(isect.minpt - bbox.minpt, isect.maxpt - bbox.minpt)
      )
      src = tuple(
        slice(int(a), int(b))
        for a, b in zip(isect.minpt - chunk_bbx.minpt, isect.maxpt - chunk_bbx.minpt)
      )
      out[dst] = chunk_img[src]

    if self.graphene is not None and (agglomerate or stop_layer is not None):
      from .graphene import voxel_chunk_index

      if stop_layer == 2:
        # graph chunks are defined at the watershed BASE resolution:
        # scale mip coordinates by the downsample ratio so L2 identity
        # is mip-invariant
        chunks = voxel_chunk_index(
          bbox.minpt, out.shape[:3], self.graphene.chunk_size,
          scale=self.meta.downsample_ratio(mip),
        )
        mapped = self.graphene.get_l2_ids(
          out[..., 0], chunks, timestamp
        )
      elif stop_layer == 1:
        mapped = out[..., 0].astype(np.uint64, copy=False)  # raw supervoxels
      else:
        mapped = self.graphene.get_roots(out[..., 0], timestamp)
      # root/L2 ids live above 2^40 — NEVER narrow them to the watershed
      # layer's dtype (a uint32 layer would silently wrap ids to garbage)
      out = mapped[..., np.newaxis].astype(np.uint64, copy=False)

    if label is not None:
      out = (out == label).astype(np.uint8)
    if renumber:
      out, mapping = _renumber(out)
      return out, mapping
    return out

  def _parallel_get_stored(self, keys: List[str], parallel: Optional[int]):
    # stored-domain reads: (wire bytes, method) pairs, decompressed by
    # the caller AFTER the cache digest gets a chance to skip the work.
    # parallel=1 keeps strict serial semantics; anything wider rides the
    # fixed-width shared pool — spawning a fresh executor per cutout (to
    # honor an exact thread count) showed up as pure thread-start
    # overhead in the e2e profile (ISSUE 3)
    if (parallel or IO_THREADS) <= 1 or len(keys) <= 1:
      return [self.cf.get_stored(k) for k in keys]
    from .pipeline.encoder import shared_io_pool

    return list(shared_io_pool().map(self.cf.get_stored, keys))

  def __getitem__(self, slices) -> np.ndarray:
    bbox = self._interpret_slices(slices)
    return self.download(bbox)

  def _interpret_slices(self, slices) -> Bbox:
    if isinstance(slices, Bbox):
      return slices
    if isinstance(slices, (list, tuple)) and all(isinstance(s, slice) for s in slices):
      bounds = self.bounds
      fixed = []
      for i, s in enumerate(slices[:3]):
        start = s.start if s.start is not None else int(bounds.minpt[i])
        stop = s.stop if s.stop is not None else int(bounds.maxpt[i])
        fixed.append(slice(start, stop))
      return Bbox.from_slices(fixed)
    raise TypeError(f"Unsupported index: {slices}")

  def exists(self, bbox: Bbox, mip: Optional[int] = None):
    """Map of chunk key → bool for chunks covering bbox (TouchTask support)."""
    mip = self.mip if mip is None else mip
    bounds = self.meta.bounds(mip)
    chunks = [
      Bbox.intersection(c, bounds)
      for c in chunk_bboxes(
        bbox,
        self.meta.chunk_size(mip),
        offset=self.meta.voxel_offset(mip),
        clamp=False,
      )
    ]
    return {
      self.meta.chunk_name(mip, c): self.cf.exists(self.meta.chunk_name(mip, c))
      for c in chunks
      if not c.empty()
    }

  # -- upload ---------------------------------------------------------------

  def upload(
    self,
    bbox: Bbox,
    img: np.ndarray,
    mip: Optional[int] = None,
    compress: Optional[str] = "gzip",
    parallel: Optional[int] = None,
    sink=None,
  ):
    """``sink`` (pipeline.UploadTicket / SerialSink): when given, chunk
    encode+compress+put runs as submitted closures instead of inline —
    the staged pipeline's parallel encode/upload stage. The caller owns
    joining the sink before treating the upload as durable, and must not
    mutate ``img`` until then. Bytes are identical either way (each
    chunk encodes independently, gzip is mtime=0 deterministic)."""
    mip = self.mip if mip is None else mip
    if img.ndim == 3:
      img = img[..., np.newaxis]
    if tuple(img.shape[:3]) != tuple(int(v) for v in bbox.size3()):
      raise VolumeException(
        f"Image shape {img.shape} does not match bbox {bbox}"
      )
    if img.shape[3] != self.num_channels:
      raise VolumeException(
        f"Image has {img.shape[3]} channels, volume has {self.num_channels}"
      )
    if img.dtype != self.dtype:
      if not np.can_cast(img.dtype, self.dtype, casting="same_kind"):
        raise VolumeException(
          f"Image dtype {img.dtype} is not compatible with volume dtype "
          f"{self.meta.data_type}; cast explicitly."
        )
      img = img.astype(self.dtype)
    bounds = self.meta.bounds(mip)
    if self.bounded and not bounds.contains_bbox(bbox):
      raise OutOfBoundsError(f"{bbox} exceeds bounds {bounds}")

    cs = self.meta.chunk_size(mip)
    offset = self.meta.voxel_offset(mip)
    expanded = bbox.expand_to_chunk_size(cs, offset)
    clamped_expanded = Bbox.intersection(expanded, bounds)
    if clamped_expanded != bbox and not self.non_aligned_writes:
      raise AlignmentError(
        f"{bbox} is not chunk-aligned (chunk {list(map(int, cs))}, "
        f"offset {list(map(int, offset))}) nor clipped to bounds {bounds}"
      )

    if self.meta.is_sharded(mip):
      raise VolumeException(
        "Direct writes to sharded scales are not supported; "
        "use ImageShardTransferTask / make_shard."
      )

    encoding = self.meta.encoding(mip)
    block_size = self.meta.cseg_block_size(mip)
    # per-scale quality knobs (meta.set_encoding; reference
    # task_creation/common.py:215-236 records them in the scale)
    enc_kw = {}
    scale = self.meta.scale(mip)
    if encoding == "jpeg" and "jpeg_quality" in scale:
      enc_kw["jpeg_quality"] = int(scale["jpeg_quality"])
    elif encoding == "png" and "png_level" in scale:
      enc_kw["png_level"] = int(scale["png_level"])
    jobs = []  # (key, cutout): encode deferred so a sink can thread it
    deletes = []
    for gchunk in chunk_bboxes(bbox, cs, offset=offset, clamp=False):
      chunk_bbx = Bbox.intersection(gchunk, bounds)  # stored chunk extent
      if chunk_bbx.empty():
        continue
      isect = Bbox.intersection(chunk_bbx, bbox)
      src = tuple(
        slice(int(a), int(b))
        for a, b in zip(isect.minpt - bbox.minpt, isect.maxpt - bbox.minpt)
      )
      key = self.meta.chunk_name(mip, chunk_bbx)
      if isect == chunk_bbx:
        cutout = img[src]
      else:
        # non-aligned write: read-modify-write the grid-aligned chunk so the
        # stored file keeps its canonical key and untouched voxels survive
        shape = tuple(int(v) for v in chunk_bbx.size3()) + (self.num_channels,)
        data = self.cf.get(key)
        if data is None:
          base = np.full(shape, self.background_color, dtype=self.dtype)
        else:
          base = codecs.decode(
            data, encoding, shape, self.dtype, block_size=block_size
          )
        dst = tuple(
          slice(int(a), int(b))
          for a, b in zip(isect.minpt - chunk_bbx.minpt, isect.maxpt - chunk_bbx.minpt)
        )
        base[dst] = img[src]
        cutout = base
      if self.delete_black_uploads and np.all(cutout == self.background_color):
        deletes.append(key)
        continue
      jobs.append((key, cutout))

    if sink is not None:
      for key, cutout in jobs:
        def encode_and_put(key=key, cutout=cutout):
          self.cf.put(
            key,
            codecs.encode(cutout, encoding, block_size=block_size, **enc_kw),
            compress=compress,
          )
        sink.submit(encode_and_put)
    else:
      puts = [
        (key, codecs.encode(cutout, encoding, block_size=block_size, **enc_kw))
        for key, cutout in jobs
      ]
      self._parallel_put(puts, compress, parallel)
    if deletes:
      self.cf.delete(deletes)
    # decode-cache hygiene: entries under this (path, mip) are doomed
    # (digest keying already keeps late readers correct — a rewritten
    # chunk hashes differently — this frees the memory now). Sink-routed
    # puts may still be in flight; the pipeline runner re-invalidates
    # when the ticket joins.
    chunk_cache.invalidate(self.cloudpath, mip)

  def _parallel_put(self, puts, compress, parallel: Optional[int]):
    # same policy as _parallel_get: parallel=1 is serial, wider requests
    # share the fixed-width pool
    if (parallel or IO_THREADS) <= 1 or len(puts) <= 1:
      for key, data in puts:
        self.cf.put(key, data, compress=compress)
      return
    from .pipeline.encoder import shared_io_pool

    list(shared_io_pool().map(
      lambda kv: self.cf.put(kv[0], kv[1], compress=compress), puts
    ))

  def __setitem__(self, slices, img):
    bbox = self._interpret_slices(slices)
    if np.isscalar(img):
      img = np.full(
        tuple(int(v) for v in bbox.size3()) + (self.num_channels,),
        img,
        dtype=self.dtype,
      )
    self.upload(bbox, np.asarray(img, dtype=self.dtype))

  # -- deletion -------------------------------------------------------------

  def delete(self, bbox: Bbox, mip: Optional[int] = None):
    """Delete all chunk files covering bbox (must be chunk aligned)."""
    mip = self.mip if mip is None else mip
    cs = self.meta.chunk_size(mip)
    offset = self.meta.voxel_offset(mip)
    if bbox != bbox.expand_to_chunk_size(cs, offset).clamp(self.meta.bounds(mip)):
      raise AlignmentError(f"delete bbox {bbox} must be chunk aligned")
    keys = [
      self.meta.chunk_name(mip, c)
      for c in chunk_bboxes(bbox, cs, offset=offset)
    ]
    self.cf.delete(keys)
    chunk_cache.invalidate(self.cloudpath, mip)

  def __repr__(self):
    return (
      f"Volume({self.cloudpath!r}, mip={self.mip}, "
      f"bounds={self.bounds}, dtype={self.meta.data_type})"
    )


CloudVolume = Volume  # familiar alias for users migrating from the reference
