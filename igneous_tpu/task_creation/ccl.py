"""CCL task factories + single-machine orchestration.

Reference parity: /root/reference/igneous/task_creation/image.py:1763-1926
(create_ccl_face_tasks, equivalence, relabel factories) and the
`igneous image ccl auto` orchestration (igneous_cli/cli.py:799-852).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..lib import Bbox, Vec
from ..volume import Volume
from ..storage import CloudFiles
from ..tasks.ccl import (
  CCLEquivalancesTask,
  CCLFacesTask,
  RelabelCCLTask,
  ccl_scratch_path,
  create_relabeling,
)
from .common import GridTaskIterator, get_bounds, operator_contact

DEFAULT_CCL_SHAPE = (448, 448, 448)


def _grid(vol: Volume, mip: int, shape: Sequence[int], bounds: Optional[Bbox]):
  from ..lib import ceil_div

  # pass 4 writes core bboxes directly: the task shape and bounds must be
  # aligned to the chunk grid (every factory normalizes identically so all
  # four passes agree on the task grid)
  cs = np.asarray(vol.meta.chunk_size(mip))
  task_bounds = get_bounds(vol, bounds, mip, mip, chunk_size=cs)
  shape = Vec(*(ceil_div(np.asarray(shape), cs) * cs))
  grid_size = Vec(*ceil_div(np.asarray(task_bounds.size3()), np.asarray(shape)))
  return task_bounds, shape, grid_size


def _ccl_iterator(task_cls, src_path, mip, shape, bounds, grid_size, extra):
  def make_task(shape_: Vec, offset: Vec):
    # task_num must be derived from the grid coord, not closure order,
    # because iterators can be sliced for resumption
    coord = (np.asarray(offset) - np.asarray(bounds.minpt)) // np.asarray(shape_)
    task_num = int(
      coord[0] + int(grid_size.x) * (coord[1] + int(grid_size.y) * coord[2])
    )
    kw = dict(
      src_path=src_path,
      mip=mip,
      shape=shape_.tolist(),
      offset=offset.tolist(),
      task_num=task_num,
      **extra,
    )
    return task_cls(**kw)

  return GridTaskIterator(bounds, shape, make_task)


def create_ccl_face_tasks(
  src_path: str,
  mip: int = 0,
  shape: Sequence[int] = DEFAULT_CCL_SHAPE,
  fill_missing: bool = False,
  threshold_gte: Optional[float] = None,
  threshold_lte: Optional[float] = None,
  bounds: Optional[Bbox] = None,
  dust_threshold: int = 0,
):
  vol = Volume(src_path, mip=mip)
  task_bounds, shape, grid_size = _grid(vol, mip, shape, bounds)
  return _ccl_iterator(
    CCLFacesTask, src_path, mip, shape, task_bounds, grid_size,
    dict(
      fill_missing=fill_missing,
      threshold_gte=threshold_gte,
      threshold_lte=threshold_lte,
      dust_threshold=dust_threshold,
    ),
  )


def create_ccl_equivalence_tasks(
  src_path: str,
  mip: int = 0,
  shape: Sequence[int] = DEFAULT_CCL_SHAPE,
  fill_missing: bool = False,
  threshold_gte: Optional[float] = None,
  threshold_lte: Optional[float] = None,
  bounds: Optional[Bbox] = None,
  dust_threshold: int = 0,
):
  vol = Volume(src_path, mip=mip)
  task_bounds, shape, grid_size = _grid(vol, mip, shape, bounds)
  return _ccl_iterator(
    CCLEquivalancesTask, src_path, mip, shape, task_bounds, grid_size,
    dict(
      grid_size=[int(v) for v in grid_size],
      fill_missing=fill_missing,
      threshold_gte=threshold_gte,
      threshold_lte=threshold_lte,
      dust_threshold=dust_threshold,
    ),
  )


def create_ccl_relabel_tasks(
  src_path: str,
  dest_path: str,
  mip: int = 0,
  shape: Sequence[int] = DEFAULT_CCL_SHAPE,
  fill_missing: bool = False,
  threshold_gte: Optional[float] = None,
  threshold_lte: Optional[float] = None,
  bounds: Optional[Bbox] = None,
  encoding: str = "compressed_segmentation",
  chunk_size: Optional[Sequence[int]] = None,
  dust_threshold: int = 0,
):
  """Creates the destination segmentation layer and the pass-4 grid.
  Requires create_relabeling to have produced max_label.json."""
  vol = Volume(src_path, mip=mip)
  cf = CloudFiles(src_path)
  scratch = ccl_scratch_path(src_path, mip)
  max_doc = cf.get_json(f"{scratch}/max_label.json")
  if max_doc is None:
    raise FileNotFoundError(
      "max_label.json missing: run create_relabeling (ccl calc-labels) first"
    )
  max_label = int(max_doc["max_label"])
  dtype = "uint16" if max_label < 2**16 else (
    "uint32" if max_label < 2**32 else "uint64"
  )

  scale = vol.meta.scale(mip)
  info = Volume.create_new_info(
    num_channels=1,
    layer_type="segmentation",
    data_type=dtype,
    encoding=encoding,
    resolution=scale["resolution"],
    voxel_offset=scale.get("voxel_offset", [0, 0, 0]),
    volume_size=scale["size"],
    chunk_size=chunk_size or scale["chunk_sizes"][0],
  )
  try:
    dest = Volume(dest_path)
  except FileNotFoundError:
    dest = Volume.create(dest_path, info)
  dest.meta.refresh_provenance()
  dest.meta.add_provenance_entry(
    {"task": "RelabelCCLTask", "src": src_path, "mip": mip,
     "max_label": max_label},
    operator_contact(),
  )
  dest.commit_provenance()

  task_bounds, shape, grid_size = _grid(vol, mip, shape, bounds)
  if chunk_size is not None and np.any(
    np.asarray(shape) % np.asarray(chunk_size) != 0
  ):
    raise ValueError(
      f"dest chunk_size {list(chunk_size)} must divide the task shape "
      f"{shape.tolist()} or pass-4 writes will be misaligned"
    )
  return _ccl_iterator(
    RelabelCCLTask, src_path, mip, shape, task_bounds, grid_size,
    dict(
      dest_path=dest_path,
      fill_missing=fill_missing,
      threshold_gte=threshold_gte,
      threshold_lte=threshold_lte,
      dust_threshold=dust_threshold,
    ),
  )


def clean_ccl_files(src_path: str, mip: int = 0):
  """Delete the intermediate faces/equivalences/relabel scratch files."""
  cf = CloudFiles(src_path)
  cf.delete(list(cf.list(ccl_scratch_path(src_path, mip) + "/")))


def ccl_auto(
  src_path: str,
  dest_path: str,
  mip: int = 0,
  shape: Sequence[int] = DEFAULT_CCL_SHAPE,
  queue=None,
  clean: bool = True,
  encoding: str = "compressed_segmentation",
  chunk_size: Optional[Sequence[int]] = None,
  **kw,
):
  """Run all four passes with a barrier between each — the
  `igneous image ccl auto` capability (reference cli.py:799-852 runs
  `execute` between passes for the same reason).

  With the default LocalTaskQueue, insert executes inline. With a
  lease-based queue (fq://), each pass is DRAINED here by polling before
  the next begins — passes are sequential by construction.
  """
  from ..queues import LocalTaskQueue

  tq = queue if queue is not None else LocalTaskQueue(progress=False)

  def run_pass(tasks):
    tq.insert(tasks)
    if hasattr(tq, "poll"):  # lease-based queue: drain before moving on
      tq.poll(lease_seconds=600, stop_fn=lambda executed, empty: empty)

  run_pass(create_ccl_face_tasks(src_path, mip, shape, **kw))
  run_pass(create_ccl_equivalence_tasks(src_path, mip, shape, **kw))
  max_label = create_relabeling(src_path, mip)
  run_pass(create_ccl_relabel_tasks(
    src_path, dest_path, mip, shape,
    encoding=encoding, chunk_size=chunk_size, **kw,
  ))
  if clean:
    clean_ccl_files(src_path, mip)
  return max_label
