"""create_inference_tasks: grid factory for the InferenceTask family
(ISSUE 10) — destination info creation, halo-aware bounds clamping,
provenance, and the chunk-aligned task grid.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..lib import Bbox, Vec
from ..volume import Volume
from ..tasks.inference import InferenceTask, POSTPROCESS_MODES
from .common import GridTaskIterator, get_bounds
from .image import _provenance


def _default_task_shape(chunk: Sequence[int]) -> Vec:
  """Smallest chunk multiple at or above (256, 256, 64) per axis — a few
  dozen patches per task, large enough to amortize the halo re-download
  along task faces without blowing the pipeline's byte budget."""
  target = (256, 256, 64)
  return Vec(*[
    int(c) * max(1, -(-t // int(c))) for c, t in zip(chunk, target)
  ])


def create_inference_tasks(
  src_path: str,
  dest_path: str,
  model_path: str,
  mip: int = 0,
  shape: Optional[Sequence[int]] = None,
  halo: Optional[Sequence[int]] = None,
  bounds: Optional[Bbox] = None,
  bounds_mip: int = 0,
  fill_missing: bool = False,
  batch_size: int = 4,
  postprocess: str = "none",
  compress="gzip",
  chunk_size: Optional[Sequence[int]] = None,
):
  """Grid of InferenceTasks over ``src_path`` at ``mip``, writing model
  output to ``dest_path`` (created here if absent, mirroring the source
  scale structure through ``mip`` so mip indices line up).

  ``halo`` defaults to the model's blend overlap — enough context that
  every core voxel is produced by at least one interior patch position.
  Task shapes snap UP to destination chunk multiples and the grid walks
  the chunk-expanded bounds, so every core write is chunk-aligned or
  clipped at dataset bounds: the staged pipeline's proven-aligned
  overlap rule holds for the whole campaign.

  Output dtype/channels follow ``postprocess``: ``none`` → float32 with
  the model's out_channels; ``quantize`` → uint8 out_channels;
  ``argmax`` → uint8 single channel (segmentation-style).
  """
  from ..infer.registry import load_model

  if postprocess not in POSTPROCESS_MODES:
    raise ValueError(
      f"postprocess must be one of {POSTPROCESS_MODES}: {postprocess!r}"
    )
  model = load_model(model_path)
  spec = model.spec
  src = Volume(src_path, mip=mip)
  if src.num_channels != spec.in_channels:
    raise ValueError(
      f"model {model_path} wants {spec.in_channels} channel(s); "
      f"{src_path} has {src.num_channels}"
    )
  if halo is None:
    halo = spec.overlap
  halo = Vec(*[int(v) for v in halo])

  if postprocess == "none":
    dtype, out_channels = "float32", spec.out_channels
  elif postprocess == "quantize":
    dtype, out_channels = "uint8", spec.out_channels
  else:  # argmax
    dtype, out_channels = "uint8", 1

  src_scale = src.meta.scale(mip)
  base_scale = src.meta.scale(0)
  dest_chunk = (
    list(chunk_size) if chunk_size else list(src_scale["chunk_sizes"][0])
  )
  dest_info = Volume.create_new_info(
    num_channels=out_channels,
    layer_type="segmentation" if postprocess == "argmax" else "image",
    data_type=dtype,
    encoding="raw",
    resolution=base_scale["resolution"],
    voxel_offset=base_scale.get("voxel_offset", [0, 0, 0]),
    volume_size=base_scale["size"],
    chunk_size=dest_chunk,
  )
  try:
    dest = Volume(dest_path)  # existing destination info wins
  except FileNotFoundError:
    dest = Volume.create(dest_path, dest_info)
    for m in range(1, mip + 1):
      dest.meta.add_scale(
        np.asarray(src.meta.downsample_ratio(m)),
        chunk_size=dest_chunk,
        encoding="raw",
      )
    dest.commit_info()

  dchunk = dest.meta.chunk_size(mip)
  if shape is None:
    shape = _default_task_shape(dchunk)
  else:
    # snap UP to a chunk multiple: unaligned task shapes would shear the
    # grid off the chunk lattice and forfeit the aligned-writes proof
    shape = Vec(*[
      int(c) * max(1, -(-int(s) // int(c))) for s, c in zip(shape, dchunk)
    ])

  task_bounds = get_bounds(
    dest, bounds, mip, bounds_mip, chunk_size=dchunk
  )

  def make_task(shape_: Vec, offset: Vec):
    return InferenceTask(
      src_path=src_path,
      dest_path=dest_path,
      model_path=model_path,
      mip=mip,
      shape=shape_.tolist(),
      offset=offset.tolist(),
      halo=halo.tolist(),
      fill_missing=fill_missing,
      batch_size=batch_size,
      postprocess=postprocess,
      compress=compress,
    )

  def finish():
    _provenance(dest, {
      "task": "InferenceTask",
      "src": src_path,
      "dest": dest_path,
      "model": model_path,
      "architecture": spec.architecture,
      "mip": mip,
      "shape": shape.tolist(),
      "halo": halo.tolist(),
      "patch_shape": list(spec.patch_shape),
      "overlap": list(spec.overlap),
      "postprocess": postprocess,
      "bounds": task_bounds.to_list(),
    })

  return GridTaskIterator(task_bounds, shape, make_task, finish)
