"""Skeleton task factories.

Reference parity: /root/reference/igneous/task_creation/skeleton.py
(create_skeletonizing_tasks :68-388 incl. vertex_attributes management
:244-268; unsharded merge :535-591; create_sharded_skeleton_merge_tasks
:442-532; deletion :593-657; xfer :756-793).
"""

from __future__ import annotations

from functools import partial
from typing import Iterator, Optional, Sequence

import numpy as np

from ..lib import Bbox, Vec
from ..volume import Volume
from ..skeleton_io import DEFAULT_ATTRIBUTES
from ..tasks.skeleton import (
  DeleteSkeletonFilesTask,
  ShardedSkeletonMergeTask,
  SkeletonTask,
  TransferSkeletonFilesTask,
  UnshardedSkeletonMergeTask,
  skel_dir_for,
)
from .common import GridTaskIterator, get_bounds, operator_contact


def create_skeletonizing_tasks(
  cloudpath: str,
  mip: int = 0,
  shape: Sequence[int] = (512, 512, 512),
  teasar_params: Optional[dict] = None,
  object_ids: Optional[Sequence[int]] = None,
  mask_ids: Optional[Sequence[int]] = None,
  dust_threshold: int = 1000,
  dust_global: bool = False,
  fill_missing: bool = False,
  sharded: bool = False,
  skel_dir: Optional[str] = None,
  spatial_index: bool = True,
  fix_borders: bool = True,
  fill_holes: int = 0,
  fix_branching: bool = True,
  fix_avocados: bool = False,
  fix_autapses: bool = False,
  cross_sectional_area: bool = False,
  csa_smoothing_window: int = 1,
  csa_repair_sec_per_label: int = -1,
  low_memory_csa: bool = False,
  synapses: Optional[dict] = None,
  parallel: int = 1,
  bounds: Optional[Bbox] = None,
  timestamp: Optional[float] = None,
  frag_path: Optional[str] = None,
  root_ids_cloudpath: Optional[str] = None,
  cross_sectional_area_smoothing_window: Optional[int] = None,
  cross_sectional_area_repair_sec_per_label: Optional[int] = None,
):
  """Stage-1 skeleton forge grid; creates the skeleton info with its
  vertex_attributes (reference :68-388). The two long reference kwarg
  spellings alias csa_smoothing_window / csa_repair_sec_per_label."""
  if cross_sectional_area_smoothing_window is not None:
    csa_smoothing_window = cross_sectional_area_smoothing_window
  if cross_sectional_area_repair_sec_per_label is not None:
    csa_repair_sec_per_label = cross_sectional_area_repair_sec_per_label
  vol = Volume(cloudpath, mip=mip)
  if vol.layer_type != "segmentation":
    raise ValueError("Skeletonization requires a segmentation layer")
  if fix_autapses and vol.graphene is None:
    raise ValueError("fix_autapses requires a graphene:// volume")

  if skel_dir is None:
    skel_dir = vol.info.get("skeletons") or f"skeletons_mip_{mip}"
  vol.info["skeletons"] = skel_dir

  vertex_attributes = list(DEFAULT_ATTRIBUTES)
  if cross_sectional_area:
    # extra attributes serialize sorted by id after the defaults
    # (skeleton_io.Skeleton.to_precomputed); the info must list the same
    # order (reference vertex_attributes management, :244-268)
    vertex_attributes.append({
      "id": "cross_sectional_area",
      "data_type": "float32",
      "num_components": 1,
    })
  skel_info = {
    "@type": "neuroglancer_skeletons",
    # vertices are stored in physical nm already: identity transform
    "transform": [1, 0, 0, 0, 0, 1, 0, 0, 0, 0, 1, 0],
    "vertex_attributes": vertex_attributes,
    "mip": int(mip),
  }
  if spatial_index:
    res = [int(v) for v in vol.resolution]
    skel_info["spatial_index"] = {
      "resolution": res,
      "chunk_size": [int(s * r) for s, r in zip(shape, res)],
    }
  vol.cf.put_json(f"{skel_dir}/info", skel_info)
  vol.commit_info()

  shape = Vec(*shape)
  task_bounds = get_bounds(
    vol, bounds, mip, mip, chunk_size=vol.meta.chunk_size(mip)
  )

  # synapses → per-task voxel targets. Accepted forms:
  #   {label: [[x,y,z] PHYSICAL points]}                      (dict)
  #   [((x,y,z), label, swc_label), ...]                      (reference
  #     task_creation/skeleton.py:390-411 tuple list)
  # Points are bucketed by grid cell once, so per-task lookup is O(1)
  # (the reference's kD-tree serves the same purpose).
  cell_targets = {}  # (cx,cy,cz) -> {label: [[x,y,z,swc_label], ...]}
  if synapses:
    res = np.asarray(vol.resolution, dtype=np.float64)
    grid_lo = np.asarray(task_bounds.minpt, dtype=np.int64)
    shape_arr = np.asarray(shape, dtype=np.int64)

    def normalized():
      if isinstance(synapses, dict):
        for label, pts in synapses.items():
          for p in pts:
            yield (p, int(label), 0)
      else:
        for p, label, swc_label in synapses:
          yield (p, int(label), int(swc_label))

    for p, label, swc_label in normalized():
      vox = (np.asarray(p, dtype=np.float64) / res).astype(np.int64)
      rel = vox - grid_lo
      cells = {tuple((rel // shape_arr).tolist())}
      # a point on a cell's first plane also sits in the previous cell's
      # +1 overlap cutout
      for axis in range(3):
        if rel[axis] % shape_arr[axis] == 0 and rel[axis] > 0:
          for c in list(cells):
            lower = list(c)
            lower[axis] -= 1
            cells.add(tuple(lower))
      entry = [int(vox[0]), int(vox[1]), int(vox[2]), swc_label]
      for c in cells:
        cell_targets.setdefault(c, {}).setdefault(label, []).append(entry)

  def task_targets(offset: Vec, shape_: Vec):
    if not cell_targets:
      return None
    cell = tuple((
      (np.asarray(offset, dtype=np.int64)
       - np.asarray(task_bounds.minpt, dtype=np.int64))
      // np.asarray(shape_, dtype=np.int64)
    ).tolist())
    return cell_targets.get(cell)

  def make_task(shape_: Vec, offset: Vec):
    return SkeletonTask(
      cloudpath=cloudpath,
      shape=shape_.tolist(),
      offset=offset.tolist(),
      mip=mip,
      teasar_params=teasar_params,
      object_ids=list(object_ids) if object_ids else None,
      mask_ids=list(mask_ids) if mask_ids else None,
      dust_threshold=dust_threshold,
      dust_global=dust_global,
      fill_missing=fill_missing,
      sharded=sharded,
      skel_dir=skel_dir,
      spatial_index=spatial_index,
      fix_borders=fix_borders,
      fill_holes=fill_holes,
      fix_branching=fix_branching,
      fix_avocados=fix_avocados,
      fix_autapses=fix_autapses,
      cross_sectional_area=cross_sectional_area,
      csa_smoothing_window=csa_smoothing_window,
      csa_repair_sec_per_label=csa_repair_sec_per_label,
      low_memory_csa=low_memory_csa,
      extra_targets=task_targets(offset, shape_),
      parallel=parallel,
      timestamp=timestamp,
      frag_path=frag_path,
      root_ids_cloudpath=root_ids_cloudpath,
    )

  def finish():
    vol.meta.refresh_provenance()
    vol.meta.add_provenance_entry({
      "task": "SkeletonTask", "mip": mip, "shape": shape.tolist(),
      "skel_dir": skel_dir, "sharded": sharded,
      "teasar_params": teasar_params or {},
      "dust_threshold": dust_threshold,
      "dust_global": dust_global,
      "bounds": task_bounds.to_list(),
    }, operator_contact())
    vol.commit_provenance()

  return GridTaskIterator(task_bounds, shape, make_task, finish)


def create_unsharded_skeleton_merge_tasks(
  cloudpath: str,
  magnitude: int = 1,
  skel_dir: Optional[str] = None,
  dust_threshold: float = 4000.0,
  tick_threshold: float = 6000.0,
  delete_fragments: bool = False,
  max_cable_length: Optional[float] = None,
  crop: int = 0,
) -> Iterator:
  """Stage-2 merge split by decimal label prefix (reference :535-591;
  common.label_prefixes gives exactly-once coverage)."""
  from .common import label_prefixes

  for prefix in label_prefixes(magnitude):
    yield UnshardedSkeletonMergeTask(
      cloudpath=cloudpath,
      prefix=prefix,
      skel_dir=skel_dir,
      dust_threshold=dust_threshold,
      tick_threshold=tick_threshold,
      delete_fragments=delete_fragments,
      max_cable_length=max_cable_length,
      crop=crop,
    )


def create_sharded_skeleton_merge_tasks(
  cloudpath: str,
  skel_dir: Optional[str] = None,
  dust_threshold: float = 4000.0,
  tick_threshold: float = 6000.0,
  shard_index_bytes: int = 8192,
  minishard_index_bytes: int = 40000,
  min_shards: int = 1,
  max_cable_length: Optional[float] = None,
  max_labels_per_shard: Optional[int] = None,
  minishard_index_encoding: str = "gzip",
  data_encoding: str = "gzip",
  spatial_index_db: Optional[str] = None,
) -> Iterator:
  """Stage-2 sharded merge: census labels via the spatial index, solve
  shard parameters, attach the sharding spec to the skeleton info, and
  emit one task per shard file (reference :442-532)."""
  from ..sharding import ShardingSpecification, compute_shard_params_for_hashed
  from ..spatial_index import SpatialIndex

  vol = Volume(cloudpath)
  sdir = skel_dir_for(vol, skel_dir)
  if spatial_index_db:
    labels = SpatialIndex.query_sqlite(spatial_index_db)
  else:
    labels = SpatialIndex(vol.cf, sdir).query()
  if max_labels_per_shard and len(labels) > 0:
    # bound the average shard population (reference
    # task_creation/skeleton.py:472-476)
    min_shards = max(
      min_shards, int(np.ceil(len(labels) / max_labels_per_shard))
    )
  shard_bits, minishard_bits, preshift_bits = compute_shard_params_for_hashed(
    num_labels=len(labels),
    shard_index_bytes=shard_index_bytes,
    minishard_index_bytes=minishard_index_bytes,
    min_shards=min_shards,
  )
  spec = ShardingSpecification(
    preshift_bits=preshift_bits,
    hash="murmurhash3_x86_128",
    minishard_bits=minishard_bits,
    shard_bits=shard_bits,
    minishard_index_encoding=minishard_index_encoding,
    data_encoding=data_encoding,
  )
  skel_info = vol.cf.get_json(f"{sdir}/info") or {}
  skel_info["sharding"] = spec.to_dict()
  vol.cf.put_json(f"{sdir}/info", skel_info)

  for shard_no in range(2**shard_bits):
    yield ShardedSkeletonMergeTask(
      cloudpath=cloudpath,
      shard_no=shard_no,
      skel_dir=sdir,
      dust_threshold=dust_threshold,
      tick_threshold=tick_threshold,
      max_cable_length=max_cable_length,
    )


def create_sharded_from_unsharded_skeleton_merge_tasks(
  cloudpath: str,
  dest_cloudpath: Optional[str] = None,
  src_skel_dir: Optional[str] = None,
  skel_dir: Optional[str] = None,
) -> Iterator:
  """Re-pack finished unsharded skeletons into shard files
  (reference :659-754). ``dest_cloudpath`` writes them into a different
  volume (`skeleton xfer --sharded`)."""
  from ..sharding import ShardingSpecification, compute_shard_params_for_hashed
  from ..skeleton_io import DEFAULT_ATTRIBUTES as _ATTRS
  from ..tasks.skeleton import ShardedFromUnshardedSkeletonMergeTask

  vol = Volume(cloudpath)
  src = src_skel_dir or skel_dir_for(vol, None)
  out = skel_dir or f"{src}_sharded"

  labels = [
    int(k.split("/")[-1]) for k in vol.cf.list(f"{src}/")
    if k.split("/")[-1].isdigit()
  ]
  shard_bits, minishard_bits, preshift_bits = compute_shard_params_for_hashed(
    len(labels)
  )
  spec = ShardingSpecification(
    preshift_bits=preshift_bits,
    hash="murmurhash3_x86_128",
    minishard_bits=minishard_bits,
    shard_bits=shard_bits,
  )
  src_info = vol.cf.get_json(f"{src}/info") or {
    "@type": "neuroglancer_skeletons",
    "transform": [1, 0, 0, 0, 0, 1, 0, 0, 0, 0, 1, 0],
    "vertex_attributes": _ATTRS,
  }
  src_info["sharding"] = spec.to_dict()
  if dest_cloudpath:
    from ..storage import CloudFiles as _CF

    _CF(dest_cloudpath).put_json(f"{out}/info", src_info)
    try:
      dest = Volume(dest_cloudpath)
      dest.info["skeletons"] = out
      dest.commit_info()
    except FileNotFoundError:
      pass  # skeleton-only bucket
  else:
    vol.cf.put_json(f"{out}/info", src_info)
    vol.info["skeletons"] = out
    vol.commit_info()

  for shard_no in range(2**shard_bits):
    yield ShardedFromUnshardedSkeletonMergeTask(
      cloudpath=cloudpath,
      shard_no=shard_no,
      src_skel_dir=src,
      skel_dir=out,
      dest_cloudpath=dest_cloudpath,
    )


def create_skeleton_deletion_tasks(
  cloudpath: str, magnitude: int = 1, skel_dir: Optional[str] = None
):
  from .common import label_prefixes

  sdir = skel_dir_for(Volume(cloudpath), skel_dir)
  for prefix in label_prefixes(magnitude):
    yield partial(DeleteSkeletonFilesTask, cloudpath, sdir, prefix)


def create_skeleton_transfer_tasks(
  src_layer: str, dest_layer: str, skel_dir: Optional[str] = None,
  magnitude: int = 1,
):
  from .common import label_prefixes

  sdir = skel_dir_for(Volume(src_layer), skel_dir)
  try:
    dest = Volume(dest_layer)
    dest.info["skeletons"] = sdir
    dest.commit_info()
  except FileNotFoundError:
    pass
  for prefix in label_prefixes(magnitude):
    yield partial(TransferSkeletonFilesTask, src_layer, dest_layer, sdir, prefix)
