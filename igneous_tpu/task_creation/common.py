"""Factory commons: grid decomposition, bounds resolution, provenance ops.

Reference parity: /root/reference/igneous/task_creation/common.py
(FinelyDividedTaskIterator :60-104, get_bounds :29-55, num_tasks :57,
operator_contact :11-24).
"""

from __future__ import annotations

import subprocess
from typing import Callable, Iterator, Optional, Sequence

import numpy as np

from ..lib import Bbox, Vec, ceil_div
from ..volume import Volume


def operator_contact() -> str:
  """git email for provenance records (best effort)."""
  try:
    return (
      subprocess.check_output(
        ["git", "config", "user.email"], stderr=subprocess.DEVNULL
      )
      .decode("utf8")
      .strip()
    )
  except Exception:
    return ""


def get_bounds(
  vol: Volume,
  bounds: Optional[Bbox],
  mip: int,
  bounds_mip: int = 0,
  chunk_size: Optional[Sequence[int]] = None,
) -> Bbox:
  """Resolve a user bbox (given at bounds_mip) to task bounds at mip,
  expanded to the chunk grid and clamped to the volume."""
  if bounds is None:
    return vol.meta.bounds(mip)
  bounds = vol.meta.bbox_to_mip(bounds, bounds_mip, mip)
  if chunk_size is not None:
    bounds = bounds.expand_to_chunk_size(chunk_size, vol.meta.voxel_offset(mip))
  return Bbox.intersection(bounds, vol.meta.bounds(mip))


def num_tasks(bounds: Bbox, shape: Sequence[int]) -> int:
  return int(np.prod(ceil_div(np.asarray(bounds.size3()), np.asarray(shape))))


def label_prefixes(magnitude: int) -> Iterator[str]:
  """Decimal prefixes covering every positive integer label exactly once:
  full-length prefixes (no leading zeros) plus terminated ``N:`` prefixes
  for labels shorter than ``magnitude`` digits. Shared by mesh-manifest
  and skeleton-merge fan-out (reference prefix strategy,
  task_creation/mesh.py:54-89)."""
  for prefix in range(10 ** (magnitude - 1), 10**magnitude):
    yield str(prefix)
  for ndigits in range(1, magnitude):
    lo = 10 ** (ndigits - 1) if ndigits > 1 else 1
    for prefix in range(lo, 10**ndigits):
      yield f"{prefix}:"


class FinelyDividedTaskIterator:
  """Splits ``bounds`` into a shape-sized grid; index → task.

  Sliceable (``it[a:b]``) so interrupted insertions can resume mid-range,
  like the reference iterator (common.py:77-81). Subclass and override
  ``task(shape, offset)``; ``on_finish()`` runs after full iteration.
  """

  def __init__(self, bounds: Bbox, shape: Sequence[int]):
    self.bounds = bounds
    self.shape = Vec(*shape)
    self.grid = Vec(*ceil_div(np.asarray(bounds.size3()), np.asarray(self.shape)))
    self.start = 0
    self.end = len(self)

  def __len__(self) -> int:
    # the FULL grid size, slice-unaware: __getitem__ relies on
    # sl.indices(len(self)) resolving against the whole grid
    return int(np.prod(np.asarray(self.grid)))

  def num_pending(self) -> int:
    """Tasks this (possibly sliced) iterator will actually yield — the
    ``total=`` hint batched enqueue uses to size fq:// segment shards
    (ISSUE 15). Index-addressable: task i is fully determined by its
    grid coordinate, which is what makes range leases sound."""
    return max(int(self.end) - int(self.start), 0)

  def to_coord(self, index: int) -> Vec:
    gx, gy, _gz = (int(v) for v in self.grid)
    return Vec(index % gx, (index // gx) % gy, index // (gx * gy))

  def task(self, shape: Vec, offset: Vec):
    raise NotImplementedError

  def on_finish(self):
    pass

  def __getitem__(self, sl: slice) -> "FinelyDividedTaskIterator":
    import copy

    if not isinstance(sl, slice):
      raise TypeError("index must be a slice")
    clone = copy.copy(self)
    clone.start, clone.end, _ = sl.indices(len(self))
    return clone

  def __iter__(self) -> Iterator:
    for index in range(self.start, self.end):
      coord = self.to_coord(index)
      offset = self.bounds.minpt + coord * self.shape
      yield self.task(self.shape.clone(), Vec(*offset))
    self.on_finish()


class GridTaskIterator(FinelyDividedTaskIterator):
  """FinelyDividedTaskIterator driven by a callback instead of subclassing."""

  def __init__(
    self,
    bounds: Bbox,
    shape: Sequence[int],
    task_fn: Callable[[Vec, Vec], object],
    finish_fn: Optional[Callable[[], None]] = None,
  ):
    super().__init__(bounds, shape)
    self._task_fn = task_fn
    self._finish_fn = finish_fn

  def task(self, shape: Vec, offset: Vec):
    return self._task_fn(shape, offset)

  def on_finish(self):
    if self._finish_fn is not None:
      self._finish_fn()
