"""Mesh task factories.

Reference parity: /root/reference/igneous/task_creation/mesh.py
(create_meshing_tasks :158-267, create_mesh_manifest_tasks :54-89,
mesh xfer :548-588). The multires/sharded merge factories land with the
multires module (draco codec is a pluggable hook in this environment).
"""

from __future__ import annotations

from functools import partial
from typing import Iterator, Optional, Sequence

import numpy as np

from ..lib import Bbox, Vec
from ..volume import Volume
from ..tasks.mesh import (
  DeleteMeshFilesTask,
  MeshManifestFilesystemTask,
  MeshManifestPrefixTask,
  MeshTask,
  TransferMeshFilesTask,
)
from .common import GridTaskIterator, get_bounds, operator_contact


def create_meshing_tasks(
  layer_path: str,
  mip: int = 0,
  shape: Sequence[int] = (448, 448, 448),
  simplification: bool = True,
  simplification_factor: int = 100,
  max_simplification_error: int = 40,
  mesh_dir: Optional[str] = None,
  dust_threshold: Optional[int] = None,
  dust_global: bool = False,
  object_ids: Optional[Sequence[int]] = None,
  exclude_object_ids: Optional[Sequence[int]] = None,
  remap_table: Optional[dict] = None,
  fill_missing: bool = False,
  encoding: str = "precomputed",
  spatial_index: bool = True,
  sharded: bool = False,
  bounds: Optional[Bbox] = None,
  closed_dataset_edges: bool = True,
  fill_holes: int = 0,
  mesher: str = "cubes",
  parallel: int = 1,
  compress: str = "gzip",
):
  """Stage-1 mesh forge grid; creates the mesh info
  (reference task_creation/mesh.py:158-267)."""
  vol = Volume(layer_path, mip=mip)
  if vol.layer_type != "segmentation":
    raise ValueError("Meshing requires a segmentation layer")

  if mesh_dir is None:
    mesh_dir = vol.info.get("mesh") or f"mesh_mip_{mip}_err_{max_simplification_error}"
  vol.info["mesh"] = mesh_dir
  mesh_info = {"@type": "neuroglancer_legacy_mesh", "mip": int(mip)}
  if spatial_index:
    res = [int(v) for v in vol.resolution]
    mesh_info["spatial_index"] = {
      "resolution": res,
      "chunk_size": [int(s * r) for s, r in zip(shape, res)],
    }
  vol.cf.put_json(f"{mesh_dir}/info", mesh_info)
  vol.commit_info()

  shape = Vec(*shape)
  task_bounds = get_bounds(
    vol, bounds, mip, mip, chunk_size=vol.meta.chunk_size(mip)
  )

  if not simplification:
    simplification_factor = 1

  def make_task(shape_: Vec, offset: Vec):
    return MeshTask(
      shape=shape_.tolist(),
      offset=offset.tolist(),
      layer_path=layer_path,
      mip=mip,
      simplification_factor=simplification_factor,
      max_simplification_error=max_simplification_error,
      mesh_dir=mesh_dir,
      dust_threshold=dust_threshold,
      dust_global=dust_global,
      object_ids=list(object_ids) if object_ids else None,
      exclude_object_ids=(
        list(exclude_object_ids) if exclude_object_ids else None
      ),
      remap_table=remap_table,
      fill_missing=fill_missing,
      encoding=encoding,
      spatial_index=spatial_index,
      sharded=sharded,
      closed_dataset_edges=closed_dataset_edges,
      fill_holes=fill_holes,
      mesher=mesher,
      parallel=parallel,
      compress=compress,
    )

  def finish():
    vol.meta.refresh_provenance()
    vol.meta.add_provenance_entry({
      "task": "MeshTask", "mip": mip, "shape": shape.tolist(),
      "mesh_dir": mesh_dir, "sharded": sharded,
      "simplification_factor": simplification_factor,
      "bounds": task_bounds.to_list(),
    }, operator_contact())
    vol.commit_provenance()

  return GridTaskIterator(task_bounds, shape, make_task, finish)


def create_mesh_manifest_tasks(
  layer_path: str,
  magnitude: int = 2,
  mesh_dir: Optional[str] = None,
) -> Iterator:
  """Stage-2 manifest tasks split by decimal label prefix
  (common.label_prefixes: exactly-once coverage, no dead tasks)."""
  from .common import label_prefixes

  for prefix in label_prefixes(magnitude):
    yield MeshManifestPrefixTask(
      layer_path=layer_path, prefix=prefix, mesh_dir=mesh_dir
    )


def configure_multires_info(
  cloudpath: str,
  mesh_dir: str,
  vertex_quantization_bits: int = 16,
  sharding: Optional[dict] = None,
  mip: int = 0,
) -> dict:
  """Write the multires mesh dir's info and point the layer at it
  (reference task_creation/mesh.py:437-479)."""
  from ..mesh_multires import multires_info

  from ..storage import CloudFiles

  info = multires_info(
    vertex_quantization_bits=vertex_quantization_bits,
    sharding=sharding,
    mip=mip,
  )
  CloudFiles(cloudpath).put_json(f"{mesh_dir}/info", info)
  try:
    vol = Volume(cloudpath)
    vol.info["mesh"] = mesh_dir
    vol.commit_info()
  except FileNotFoundError:
    pass  # mesh-only bucket: no volume info to update
  return info


def create_unsharded_multires_mesh_tasks(
  cloudpath: str,
  magnitude: int = 2,
  src_mesh_dir: Optional[str] = None,
  mesh_dir: Optional[str] = None,
  num_lods: int = 2,
  encoding: str = "draco",
  parallel: int = 1,
  vertex_quantization_bits: int = 16,
  min_chunk_size: Optional[Sequence[int]] = None,
  draco_compression_level: int = 7,
) -> Iterator:
  """Legacy fragments → unsharded multires (reference :481-546)."""
  from ..tasks.mesh import mesh_dir_for
  from ..tasks.mesh_multires import MultiResUnshardedMeshMergeTask
  from .common import label_prefixes

  vol = Volume(cloudpath)
  src = mesh_dir_for(vol, src_mesh_dir)  # raises if nothing is configured
  out = mesh_dir or f"{src}_multires"
  configure_multires_info(
    cloudpath, out, vertex_quantization_bits=vertex_quantization_bits,
  )
  for prefix in label_prefixes(magnitude):
    yield MultiResUnshardedMeshMergeTask(
      cloudpath=cloudpath,
      prefix=prefix,
      src_mesh_dir=src,
      mesh_dir=out,
      num_lods=num_lods,
      encoding=encoding,
      parallel=parallel,
      min_chunk_size=min_chunk_size,
      draco_compression_level=draco_compression_level,
    )


def _multires_shard_spec(
  num_labels: int,
  shard_index_bytes: int = 2**13,
  minishard_index_bytes: int = 2**15,
  min_shards: int = 1,
  max_labels_per_shard: Optional[int] = None,
  minishard_index_encoding: str = "gzip",
):
  from ..sharding import ShardingSpecification, compute_shard_params_for_hashed

  if max_labels_per_shard and num_labels > 0:
    # bound the average shard population (reference
    # task_creation/mesh.py:737-741)
    min_shards = max(
      min_shards, int(np.ceil(num_labels / max_labels_per_shard))
    )
  shard_bits, minishard_bits, preshift_bits = compute_shard_params_for_hashed(
    num_labels,
    shard_index_bytes=shard_index_bytes,
    minishard_index_bytes=minishard_index_bytes,
    min_shards=min_shards,
  )
  return ShardingSpecification(
    preshift_bits=preshift_bits,
    hash="murmurhash3_x86_128",
    minishard_bits=minishard_bits,
    shard_bits=shard_bits,
    # raw data: fragment ranges inside the shard are read by offset; the
    # multires fragment-before-manifest layout requires it
    minishard_index_encoding=minishard_index_encoding,
    data_encoding="raw",
  )


def create_sharded_multires_mesh_tasks(
  cloudpath: str,
  mesh_dir: Optional[str] = None,
  num_lods: int = 2,
  encoding: str = "draco",
  parallel: int = 1,
  vertex_quantization_bits: int = 16,
  min_chunk_size: Optional[Sequence[int]] = None,
  draco_compression_level: int = 7,
  shard_index_bytes: int = 2**13,
  minishard_index_bytes: int = 2**15,
  minishard_index_encoding: str = "gzip",
  min_shards: int = 1,
  max_labels_per_shard: Optional[int] = None,
  spatial_index_db: Optional[str] = None,
) -> Iterator:
  """Sharded stage-1 .frags → sharded multires: census labels via the
  spatial index (or a pre-materialized sqlite db), solve shard bits,
  write the info, one task per shard (reference :706-813)."""
  from ..spatial_index import SpatialIndex
  from ..tasks.mesh import mesh_dir_for
  from ..tasks.mesh_multires import MultiResShardedMeshMergeTask

  vol = Volume(cloudpath)
  mdir = mesh_dir_for(vol, mesh_dir)
  if spatial_index_db:
    labels = SpatialIndex.query_sqlite(spatial_index_db)
  else:
    labels = SpatialIndex(vol.cf, mdir).query()
  spec = _multires_shard_spec(
    len(labels),
    shard_index_bytes=shard_index_bytes,
    minishard_index_bytes=minishard_index_bytes,
    min_shards=min_shards,
    max_labels_per_shard=max_labels_per_shard,
    minishard_index_encoding=minishard_index_encoding,
  )
  configure_multires_info(
    cloudpath, mdir, sharding=spec.to_dict(),
    vertex_quantization_bits=vertex_quantization_bits,
  )

  for shard_no in range(2**spec.shard_bits):
    yield MultiResShardedMeshMergeTask(
      cloudpath=cloudpath,
      shard_no=shard_no,
      mesh_dir=mdir,
      num_lods=num_lods,
      encoding=encoding,
      parallel=parallel,
      min_chunk_size=min_chunk_size,
      draco_compression_level=draco_compression_level,
    )


def create_sharded_multires_mesh_from_unsharded_tasks(
  cloudpath: str,
  dest_cloudpath: Optional[str] = None,
  src_mesh_dir: Optional[str] = None,
  mesh_dir: Optional[str] = None,
  num_lods: int = 2,
  encoding: str = "draco",
  parallel: int = 1,
  vertex_quantization_bits: int = 16,
  min_chunk_size: Optional[Sequence[int]] = None,
  draco_compression_level: int = 7,
  shard_index_bytes: int = 2**13,
  minishard_index_bytes: int = 2**15,
  minishard_index_encoding: str = "gzip",
  min_shards: int = 1,
  max_labels_per_shard: Optional[int] = None,
) -> Iterator:
  """Legacy unsharded meshes → sharded multires (reference :590-704).
  ``dest_cloudpath`` writes the converted meshes into a different volume
  (the `mesh xfer --sharded` path, reference cli.py:1001-1007)."""
  from ..tasks.mesh import mesh_dir_for
  from ..tasks.mesh_multires import (
    MultiResShardedFromUnshardedMeshMergeTask,
    legacy_manifest_labels,
  )

  vol = Volume(cloudpath)
  src = mesh_dir_for(vol, src_mesh_dir)  # raises if nothing is configured
  out = mesh_dir or f"{src}_multires"
  labels = legacy_manifest_labels(vol.cf, src)
  spec = _multires_shard_spec(
    len(labels),
    shard_index_bytes=shard_index_bytes,
    minishard_index_bytes=minishard_index_bytes,
    min_shards=min_shards,
    max_labels_per_shard=max_labels_per_shard,
    minishard_index_encoding=minishard_index_encoding,
  )
  configure_multires_info(
    dest_cloudpath or cloudpath, out, sharding=spec.to_dict(),
    vertex_quantization_bits=vertex_quantization_bits,
  )

  for shard_no in range(2**spec.shard_bits):
    yield MultiResShardedFromUnshardedMeshMergeTask(
      cloudpath=cloudpath,
      shard_no=shard_no,
      src_mesh_dir=src,
      mesh_dir=out,
      num_lods=num_lods,
      encoding=encoding,
      parallel=parallel,
      min_chunk_size=min_chunk_size,
      draco_compression_level=draco_compression_level,
      dest_cloudpath=dest_cloudpath,
    )


def create_mesh_deletion_tasks(
  layer_path: str, magnitude: int = 1, mesh_dir: Optional[str] = None
):
  from ..tasks.mesh import mesh_dir_for

  mdir = mesh_dir_for(Volume(layer_path), mesh_dir)
  for prefix in range(10**magnitude):
    yield partial(DeleteMeshFilesTask, layer_path, mdir, str(prefix))


def create_mesh_transfer_tasks(
  src_layer: str, dest_layer: str, mesh_dir: Optional[str] = None,
  magnitude: int = 1,
):
  from ..tasks.mesh import mesh_dir_for

  mdir = mesh_dir_for(Volume(src_layer), mesh_dir)
  try:
    dest = Volume(dest_layer)
    dest.info["mesh"] = mdir
    dest.commit_info()
  except FileNotFoundError:
    pass  # mesh-only bucket: no info to update
  for prefix in range(10**magnitude):
    yield partial(TransferMeshFilesTask, src_layer, dest_layer, mdir, str(prefix))


def create_graphene_meshing_tasks(
  cloudpath: str,
  mip: int = 0,
  shape: Optional[Sequence[int]] = None,
  timestamp: Optional[float] = None,
  mesh_dir: Optional[str] = None,
  simplification: bool = True,
  simplification_factor: int = 100,
  max_simplification_error: int = 40,
  fill_missing: bool = False,
  bounds: Optional[Bbox] = None,
  object_ids: Optional[Sequence[int]] = None,
  draco_compression_level: int = 1,
):
  """Stage-1 graphene mesh forge (reference task_creation/mesh.py:269-361):
  L2-granularity draco meshes in sharded .frags containers. The task grid
  defaults to the chunk-graph's chunk size so every task covers whole L2
  chunks (their ids are per-(root, chunk)).

  ``draco_compression_level`` is recorded for interface parity (this
  build's draco encoder is fixed sequential-method); ``simplification``
  False disables the simplifier like create_meshing_tasks."""
  del draco_compression_level
  if not simplification:
    simplification_factor = 1
  from ..tasks.mesh import GrapheneMeshTask

  vol = Volume(cloudpath, mip=mip)
  if vol.graphene is None:
    raise ValueError("create_graphene_meshing_tasks needs a graphene:// path")
  gcs = np.asarray(vol.graphene.chunk_size, dtype=np.int64)
  if shape is None:
    shape = tuple(int(c) * 2 for c in vol.graphene.chunk_size)
  if np.any(np.asarray(shape, dtype=np.int64) % gcs):
    raise ValueError(
      f"graphene mesh task shape {list(shape)} must be a multiple of the "
      f"chunk-graph chunk size {gcs.tolist()} so no L2 chunk straddles "
      "two tasks"
    )
  if mesh_dir is None:
    mesh_dir = vol.info.get("mesh") or "mesh_graphene"
  vol.info["mesh"] = mesh_dir
  res = [int(v) for v in vol.resolution]
  vol.cf.put_json(f"{mesh_dir}/info", {
    "@type": "neuroglancer_legacy_mesh", "mip": int(mip),
    "spatial_index": {
      "resolution": res,
      "chunk_size": [int(s * r) for s, r in zip(shape, res)],
    },
  })
  vol.commit_info()

  shape = Vec(*shape)
  task_bounds = get_bounds(
    vol, bounds, mip, mip, chunk_size=vol.meta.chunk_size(mip)
  )
  # align the task grid to the CHUNK-GRAPH chunk grid (absolute origin):
  # L2 ids are per graph chunk, so a task boundary inside a graph chunk
  # would split one L2 id's mesh across two tasks. Expanded bounds are
  # safe — tasks clamp their cores to the volume themselves.
  mn = (np.asarray(task_bounds.minpt) // gcs) * gcs
  mx = -(-np.asarray(task_bounds.maxpt) // gcs) * gcs
  task_bounds = Bbox(mn, mx)

  def make_task(shape_: Vec, offset: Vec):
    return GrapheneMeshTask(
      object_ids=list(object_ids) if object_ids else None,
      shape=shape_.tolist(),
      offset=offset.tolist(),
      layer_path=cloudpath,
      mip=mip,
      simplification_factor=simplification_factor,
      max_simplification_error=max_simplification_error,
      mesh_dir=mesh_dir,
      fill_missing=fill_missing,
      timestamp=timestamp,
    )

  return GridTaskIterator(task_bounds, shape, make_task)
