"""Mesh task factories.

Reference parity: /root/reference/igneous/task_creation/mesh.py
(create_meshing_tasks :158-267, create_mesh_manifest_tasks :54-89,
mesh xfer :548-588). The multires/sharded merge factories land with the
multires module (draco codec is a pluggable hook in this environment).
"""

from __future__ import annotations

from functools import partial
from typing import Iterator, Optional, Sequence

import numpy as np

from ..lib import Bbox, Vec
from ..volume import Volume
from ..tasks.mesh import (
  DeleteMeshFilesTask,
  MeshManifestFilesystemTask,
  MeshManifestPrefixTask,
  MeshTask,
  TransferMeshFilesTask,
)
from .common import GridTaskIterator, get_bounds, operator_contact


def create_meshing_tasks(
  layer_path: str,
  mip: int = 0,
  shape: Sequence[int] = (448, 448, 448),
  simplification: bool = True,
  simplification_factor: int = 100,
  max_simplification_error: int = 40,
  mesh_dir: Optional[str] = None,
  dust_threshold: Optional[int] = None,
  object_ids: Optional[Sequence[int]] = None,
  fill_missing: bool = False,
  encoding: str = "precomputed",
  spatial_index: bool = True,
  sharded: bool = False,
  bounds: Optional[Bbox] = None,
  closed_dataset_edges: bool = True,
):
  """Stage-1 mesh forge grid; creates the mesh info
  (reference task_creation/mesh.py:158-267)."""
  vol = Volume(layer_path, mip=mip)
  if vol.layer_type != "segmentation":
    raise ValueError("Meshing requires a segmentation layer")

  if mesh_dir is None:
    mesh_dir = vol.info.get("mesh") or f"mesh_mip_{mip}_err_{max_simplification_error}"
  vol.info["mesh"] = mesh_dir
  mesh_info = {"@type": "neuroglancer_legacy_mesh", "mip": int(mip)}
  if spatial_index:
    res = [int(v) for v in vol.resolution]
    mesh_info["spatial_index"] = {
      "resolution": res,
      "chunk_size": [int(s * r) for s, r in zip(shape, res)],
    }
  vol.cf.put_json(f"{mesh_dir}/info", mesh_info)
  vol.commit_info()

  shape = Vec(*shape)
  task_bounds = get_bounds(
    vol, bounds, mip, mip, chunk_size=vol.meta.chunk_size(mip)
  )

  if not simplification:
    simplification_factor = 1

  def make_task(shape_: Vec, offset: Vec):
    return MeshTask(
      shape=shape_.tolist(),
      offset=offset.tolist(),
      layer_path=layer_path,
      mip=mip,
      simplification_factor=simplification_factor,
      max_simplification_error=max_simplification_error,
      mesh_dir=mesh_dir,
      dust_threshold=dust_threshold,
      object_ids=list(object_ids) if object_ids else None,
      fill_missing=fill_missing,
      encoding=encoding,
      spatial_index=spatial_index,
      sharded=sharded,
      closed_dataset_edges=closed_dataset_edges,
    )

  def finish():
    vol.meta.refresh_provenance()
    vol.meta.add_provenance_entry({
      "task": "MeshTask", "mip": mip, "shape": shape.tolist(),
      "mesh_dir": mesh_dir, "sharded": sharded,
      "simplification_factor": simplification_factor,
      "bounds": task_bounds.to_list(),
    }, operator_contact())
    vol.commit_provenance()

  return GridTaskIterator(task_bounds, shape, make_task, finish)


def create_mesh_manifest_tasks(
  layer_path: str,
  magnitude: int = 2,
  mesh_dir: Optional[str] = None,
) -> Iterator:
  """Stage-2 manifest tasks split by decimal label prefix
  (common.label_prefixes: exactly-once coverage, no dead tasks)."""
  from .common import label_prefixes

  for prefix in label_prefixes(magnitude):
    yield MeshManifestPrefixTask(
      layer_path=layer_path, prefix=prefix, mesh_dir=mesh_dir
    )


def create_mesh_deletion_tasks(
  layer_path: str, magnitude: int = 1, mesh_dir: Optional[str] = None
):
  from ..tasks.mesh import mesh_dir_for

  mdir = mesh_dir_for(Volume(layer_path), mesh_dir)
  for prefix in range(10**magnitude):
    yield partial(DeleteMeshFilesTask, layer_path, mdir, str(prefix))


def create_mesh_transfer_tasks(
  src_layer: str, dest_layer: str, mesh_dir: Optional[str] = None,
  magnitude: int = 1,
):
  from ..tasks.mesh import mesh_dir_for

  mdir = mesh_dir_for(Volume(src_layer), mesh_dir)
  try:
    dest = Volume(dest_layer)
    dest.info["mesh"] = mdir
    dest.commit_info()
  except FileNotFoundError:
    pass  # mesh-only bucket: no info to update
  for prefix in range(10**magnitude):
    yield partial(TransferMeshFilesTask, src_layer, dest_layer, mdir, str(prefix))
