"""Image task factories.

Reference parity: /root/reference/igneous/task_creation/image.py
(create_downsampling_tasks :195-345, create_transfer_tasks :921-1170,
create_deletion_tasks :809-850, quantize :1599; MEMORY_TARGET :74).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..lib import Bbox, Vec, jsonify
from ..volume import Volume
from ..downsample_scales import (
  DEFAULT_FACTOR,
  axis_to_factor,
  chunk_writable_factors,
  create_downsample_scales,
  downsample_shape_from_memory_target,
)
from ..tasks.image import (
  BlackoutTask,
  DeleteTask,
  DownsampleTask,
  QuantizeTask,
  TouchTask,
  TransferTask,
)
from .common import GridTaskIterator, get_bounds, operator_contact

MEMORY_TARGET = int(3.5e9)  # bytes per task, reference default (image.py:74)


def _resolve_auto_compress(compress, encoding, vol, mip):
  """compress="auto": gzip for encodings that benefit (raw, cseg,
  compresso, crackle); no second-stage compression for self-compressed
  codecs (reference _select_compression_by_encoding, image.py:913-919)."""
  if compress != "auto":
    return compress
  enc = (encoding or vol.meta.encoding(mip)).lower()
  if enc in ("raw", "compressed_segmentation", "compresso",
             "compresso-cpsx", "crackle"):
    return "gzip"
  return False


def _warn_truncated_mips(factors, num_mips: int, shape, chunk_size):
  """chunk_writable_factors quietly truncates the pyramid at the first
  mip a task couldn't legally upload — which is correct, but operators
  asking for num_mips deserve to learn their memory target (or explicit
  shape) clamped the plan, not discover missing scales later."""
  if len(factors) >= num_mips:
    return
  import warnings

  warnings.warn(
    f"requested num_mips={num_mips} but task shape "
    f"{[int(v) for v in shape]} only supports {len(factors)} "
    f"chunk-writable mip(s) (chunk {[int(v) for v in chunk_size]}); "
    f"raise memory_target or pass a larger shape to plan the full "
    f"pyramid, or re-run downsampling from the deepest produced mip",
    stacklevel=3,
  )


def _provenance(vol: Volume, method: dict):
  vol.meta.refresh_provenance()
  vol.meta.add_provenance_entry(jsonify(method), operator_contact())
  vol.commit_provenance()


def _pick_task_shape(
  vol: Volume,
  mip: int,
  factor,
  memory_target: int,
  num_mips: int,
  chunk_size: Optional[Sequence[int]] = None,
) -> Vec:
  cs = Vec(*(chunk_size if chunk_size is not None else vol.meta.chunk_size(mip)))
  arr = np.asarray(factor, dtype=np.int64)
  if arr.ndim == 2:
    # per-mip factor sequence: the largest chunk-aligned shape whose
    # pyramid fits the byte budget
    width = vol.dtype.itemsize * vol.num_channels
    seq = [np.asarray(f, dtype=np.int64) for f in arr[:num_mips]]
    shape = np.asarray(cs) * seq[0]
    for m in range(1, len(seq) + 1):
      cum = np.prod(np.stack(seq[:m]), axis=0)
      cand = np.asarray(cs) * cum
      vox = float(np.prod(cand))
      series = 1.0 + sum(
        1.0 / float(np.prod(np.prod(np.stack(seq[:i]), axis=0)))
        for i in range(1, m + 1)
      )
      if vox * series * width > memory_target and m > 1:
        break
      shape = cand
  else:
    shape = downsample_shape_from_memory_target(
      vol.dtype.itemsize,
      int(cs.x), int(cs.y), int(cs.z),
      factor,
      memory_target,
      max_mips=num_mips,
      num_channels=vol.num_channels,
    )
  return Vec(*np.minimum(
    np.asarray(shape),
    np.asarray(vol.meta.bounds(mip).expand_to_chunk_size(
      cs, vol.meta.voxel_offset(mip)
    ).size3()),
  ))


def create_downsampling_tasks(
  layer_path: str,
  mip: int = 0,
  fill_missing: bool = False,
  num_mips: int = 5,
  sparse: bool = False,
  chunk_size: Optional[Sequence[int]] = None,
  encoding: Optional[str] = None,
  encoding_level: Optional[int] = None,
  encoding_effort: Optional[int] = None,
  delete_black_uploads: bool = False,
  background_color: int = 0,
  compress="gzip",
  factor: Optional[Sequence[int]] = None,
  axis: str = "z",
  bounds: Optional[Bbox] = None,
  bounds_mip: int = 0,
  memory_target: int = MEMORY_TARGET,
  downsample_method: str = "auto",
  preserve_chunk_size: bool = True,
):
  """Grid of DownsampleTasks; creates the destination scales first
  (reference: task_creation/image.py:195-345).

  ``factor`` may be one triple, a per-mip sequence of triples, or the
  string "isotropic" (per-mip factors from the reference's near-isotropic
  planners, driving resolution toward isotropy)."""
  vol = Volume(layer_path, mip=mip)
  compress = _resolve_auto_compress(compress, encoding, vol, mip)
  if (not preserve_chunk_size and chunk_size is None
      and vol.meta.num_mips > mip + 1):
    # reference add_scales(preserve_chunk_size=False): reuse the NEXT
    # mip's existing chunking for the new scales (downsample_scales.py:233)
    chunk_size = [int(v) for v in vol.meta.chunk_size(mip + 1)]
  if isinstance(factor, str):
    if factor != "isotropic":
      raise ValueError(f"unknown factor spec {factor!r}")
    from ..downsample_scales import near_isotropic_factor_sequence

    factor = near_isotropic_factor_sequence(
      [int(v) for v in vol.resolution], num_mips
    )
  if factor is None:
    factor = axis_to_factor(axis) if axis != "z" else DEFAULT_FACTOR

  shape = _pick_task_shape(vol, mip, factor, memory_target, num_mips, chunk_size)
  factors = chunk_writable_factors(
    shape, factor, num_mips,
    chunk_size if chunk_size is not None else vol.meta.chunk_size(mip),
    vol.meta.bounds(mip).size3(),
  )
  if num_mips > 0 and not factors:
    # a silent no-op plan (0 scales, 0-mip tasks) reads as success while
    # downsampling nothing; batched_downsample raises here too
    raise ValueError(
      f"task shape {shape.tolist()} admits no chunk-writable downsample "
      f"by {list(factor)} (chunk "
      f"{list(chunk_size) if chunk_size is not None else vol.meta.chunk_size(mip).tolist()}); "
      f"raise memory_target or pass a larger/even shape"
    )
  _warn_truncated_mips(
    factors, num_mips, shape,
    chunk_size if chunk_size is not None else vol.meta.chunk_size(mip),
  )
  create_downsample_scales(
    vol.meta, mip, shape, factor,
    num_mips=len(factors),
    chunk_size=chunk_size,
    encoding=encoding,
  )
  if encoding_level is not None or encoding_effort is not None:
    for m in range(mip + 1, mip + 1 + len(factors)):
      vol.meta.set_encoding(m, None, encoding_level, encoding_effort)
  vol.commit_info()

  task_bounds = get_bounds(vol, bounds, mip, bounds_mip)

  def make_task(shape_: Vec, offset: Vec):
    return DownsampleTask(
      layer_path=layer_path,
      mip=mip,
      shape=shape_.tolist(),
      offset=offset.tolist(),
      fill_missing=fill_missing,
      sparse=sparse,
      delete_black_uploads=delete_black_uploads,
      background_color=background_color,
      compress=compress,
      downsample_method=downsample_method,
      num_mips=len(factors),
      factor=tuple(factor),
    )

  def finish():
    # the full task-constructor parameter set rides along so `igneous
    # audit --heal` can re-mint the producing task for a damaged cell
    # from provenance alone (task_creation/audit.py)
    _provenance(vol, {
      "task": "DownsampleTask",
      "mip": mip,
      "num_mips": len(factors),
      "shape": shape.tolist(),
      "factor": list(factor),
      "sparse": sparse,
      "bounds": task_bounds.to_list(),
      "method": downsample_method,
      "fill_missing": fill_missing,
      "compress": compress,
      "delete_black_uploads": delete_black_uploads,
      "background_color": background_color,
    })

  return GridTaskIterator(task_bounds, shape, make_task, finish)


def create_transfer_tasks(
  src_layer_path: str,
  dest_layer_path: str,
  chunk_size: Optional[Sequence[int]] = None,
  shape: Optional[Sequence[int]] = None,
  mip: int = 0,
  dest_voxel_offset: Optional[Sequence[int]] = None,
  translate: Sequence[int] = (0, 0, 0),
  bounds: Optional[Bbox] = None,
  bounds_mip: int = 0,
  fill_missing: bool = False,
  skip_first: bool = False,
  skip_downsamples: bool = False,
  delete_black_uploads: bool = False,
  background_color: int = 0,
  sparse: bool = False,
  compress="gzip",
  encoding: Optional[str] = None,
  encoding_level: Optional[int] = None,
  encoding_effort: Optional[int] = None,
  num_mips: int = 0,
  factor: Optional[Sequence[int]] = None,
  memory_target: int = MEMORY_TARGET,
  downsample_method: str = "auto",
  agglomerate: bool = False,
  timestamp: Optional[float] = None,
  stop_layer: Optional[int] = None,
  clean_info: bool = False,
  no_src_update: bool = False,
  truncate_scales: bool = True,
  cutout: bool = False,
  use_https_for_source: bool = False,
  max_mips: Optional[int] = None,
  preserve_chunk_size: bool = True,
):
  """Grid of TransferTasks; creates/extends the destination info
  (reference: task_creation/image.py:921-1170). ``agglomerate``/
  ``timestamp``/``stop_layer`` materialize a graphene volume's proofread
  root (or L2) ids while copying.

  ``cutout`` restricts a NEWLY created destination's bounds to ``bounds``;
  ``truncate_scales`` drops scales above ``mip`` from a new destination;
  ``clean_info`` scrubs mesh/skeleton fields from a new destination;
  ``no_src_update`` skips the source provenance note (all per reference
  :943-1033). ``use_https_for_source`` is accepted for interface parity;
  this build has no https storage backend, so it only implies
  ``no_src_update`` like the reference (:1033)."""
  src = Volume(src_layer_path, mip=mip)
  compress = _resolve_auto_compress(compress, encoding, src, mip)
  if max_mips is not None:
    num_mips = max_mips  # reference kwarg name for the same cap
  if (not preserve_chunk_size and chunk_size is None
      and src.meta.num_mips > mip + 1):
    chunk_size = [int(v) for v in src.meta.chunk_size(mip + 1)]
  if factor is None:
    factor = DEFAULT_FACTOR

  # validate the graphene options BEFORE any destination state is written
  # (a half-created layer + thousands of doomed queued tasks otherwise)
  materialize_ids = agglomerate or stop_layer is not None
  if materialize_ids and src.graphene is None:
    raise ValueError(
      "agglomerate/stop_layer transfers require a graphene:// source"
    )
  if stop_layer not in (None, 1, 2):
    raise ValueError(f"stop_layer must be 1 or 2: {stop_layer!r}")
  if timestamp is not None and not materialize_ids:
    raise ValueError(
      "timestamp only applies with agglomerate=True or stop_layer"
    )

  # destination metadata mirrors the source scale structure through `mip`
  # (so dest mip indices line up with the task's mip), fresh chunking
  src_scale = src.meta.scale(mip)
  dest_chunk = list(chunk_size) if chunk_size else src_scale["chunk_sizes"][0]
  base_scale = src.meta.scale(0)
  dest_offset0 = (
    None
    if dest_voxel_offset is None
    else list(dest_voxel_offset)
  )
  dest_info = Volume.create_new_info(
    num_channels=src.num_channels,
    layer_type=src.layer_type,
    # agglomerated/L2 downloads return uint64 ids above 2^40 regardless
    # of the watershed layer's dtype; a narrower dest would silently
    # wrap every root id on upload
    data_type="uint64" if materialize_ids else src.meta.data_type,
    encoding=encoding or src_scale["encoding"],
    resolution=base_scale["resolution"],
    voxel_offset=(
      dest_offset0
      if dest_offset0 is not None
      else (np.asarray(base_scale.get("voxel_offset", [0, 0, 0]))
            + np.asarray(translate)).tolist()
    ),
    volume_size=base_scale["size"],
    chunk_size=dest_chunk,
  )
  if use_https_for_source:
    # no https storage backend in this build; match the reference's one
    # hard semantic (a read-only source gets no provenance note, :1033)
    no_src_update = True
  try:
    dest = Volume(dest_layer_path)  # existing destination info wins
    if materialize_ids and dest.meta.data_type != "uint64":
      raise ValueError(
        f"agglomerate/stop_layer transfers write uint64 root ids, but the "
        f"existing destination is {dest.meta.data_type}; they would "
        f"silently wrap on upload — delete or widen the destination first"
      )
  except FileNotFoundError:
    dest = Volume.create(dest_layer_path, dest_info)
    for m in range(1, mip + 1):
      dest.meta.add_scale(
        np.asarray(src.meta.downsample_ratio(m)),
        chunk_size=dest_chunk,
        encoding=encoding or src.meta.encoding(m),
      )
    if not truncate_scales:
      # keep the source's scale structure above `mip` too (reference
      # truncate_scales=False, :904-905 inverted)
      for m in range(mip + 1, src.meta.num_mips):
        dest.meta.add_scale(
          np.asarray(src.meta.downsample_ratio(m)),
          chunk_size=dest_chunk,
          encoding=encoding or src.meta.encoding(m),
        )
    if cutout and bounds is not None:
      # restrict the new volume to the requested bounds (reference :879-886)
      bounds_res = np.asarray(src.meta.resolution(bounds_mip), dtype=float)
      for i in range(len(dest.info["scales"])):
        ratio = bounds_res / np.asarray(dest.meta.resolution(i), dtype=float)
        sc = dest.info["scales"][i]
        sc["voxel_offset"] = [
          int(v) for v in np.asarray(bounds.minpt, dtype=float) * ratio
        ]
        sc["size"] = [
          int(np.ceil(v)) for v in np.asarray(bounds.size3(), float) * ratio
        ]
    if clean_info:
      for key in ("mesh", "meshing", "skeletons"):
        dest.info.pop(key, None)

  if shape is None:
    shape = downsample_shape_from_memory_target(
      8 if materialize_ids else src.dtype.itemsize,
      dest_chunk[0], dest_chunk[1], dest_chunk[2],
      factor, memory_target,
      max_mips=max(num_mips, 1),
      num_channels=src.num_channels,
    )
  shape = Vec(*shape)

  if num_mips > 0:
    factors = chunk_writable_factors(
      shape, factor, num_mips, dest_chunk, dest.meta.bounds(mip).size3()
    )
    if not factors:
      raise ValueError(
        f"task shape {shape.tolist()} admits no chunk-writable downsample "
        f"by {list(factor)} (chunk {list(dest_chunk)}); raise "
        f"memory_target, pass a larger/even shape, or num_mips=0"
      )
    _warn_truncated_mips(factors, num_mips, shape, dest_chunk)
    create_downsample_scales(
      dest.meta, mip, shape, factor, num_mips=len(factors),
      chunk_size=dest_chunk, encoding=encoding,
    )
    # the tasks must carry the truncated plan too: deeper scales may
    # already exist in the destination (truncate_scales=False), and
    # execution would otherwise write unaligned deep mips
    num_mips = len(factors)
  if encoding_level is not None or encoding_effort is not None:
    for m in range(mip, len(dest.info["scales"])):
      dest.meta.set_encoding(m, None, encoding_level, encoding_effort)
  dest.commit_info()

  task_bounds = get_bounds(src, bounds, mip, bounds_mip)

  def make_task(shape_: Vec, offset: Vec):
    return TransferTask(
      src_path=src_layer_path,
      dest_path=dest_layer_path,
      mip=mip,
      shape=shape_.tolist(),
      offset=offset.tolist(),
      fill_missing=fill_missing,
      translate=tuple(translate),
      skip_first=skip_first,
      skip_downsamples=skip_downsamples,
      delete_black_uploads=delete_black_uploads,
      background_color=background_color,
      sparse=sparse,
      compress=compress,
      downsample_method=downsample_method,
      num_mips=num_mips,
      factor=tuple(factor),
      agglomerate=agglomerate,
      timestamp=timestamp,
      stop_layer=stop_layer,
    )

  def finish():
    _provenance(dest, {
      "task": "TransferTask",
      "src": src_layer_path,
      "dest": dest_layer_path,
      "mip": mip,
      "shape": shape.tolist(),
      "translate": list(translate),
      "bounds": task_bounds.to_list(),
    })
    if not no_src_update:
      # note the outbound copy on the source too (reference :1166)
      _provenance(src, {
        "task": "TransferTask",
        "transferred_to": dest_layer_path,
        "mip": mip,
        "bounds": task_bounds.to_list(),
      })

  return GridTaskIterator(task_bounds, shape, make_task, finish)


def create_image_shard_transfer_tasks(
  src_layer_path: str,
  dest_layer_path: str,
  mip: int = 0,
  chunk_size: Optional[Sequence[int]] = None,
  encoding: Optional[str] = None,
  encoding_level: Optional[int] = None,
  encoding_effort: Optional[int] = None,
  translate: Sequence[int] = (0, 0, 0),
  dest_voxel_offset: Optional[Sequence[int]] = None,
  fill_missing: bool = False,
  bounds: Optional[Bbox] = None,
  bounds_mip: int = 0,
  uncompressed_shard_bytesize: int = MEMORY_TARGET,
  memory_target: Optional[int] = None,
  cutout: bool = False,
  clean_info: bool = False,
  truncate_scales: bool = True,
  agglomerate: bool = False,
  timestamp: Optional[float] = None,
  stop_layer: Optional[int] = None,
  compress="auto",
  minishard_index_encoding: str = "gzip",
  use_https_for_source: bool = False,
):
  """Transfer into a SHARDED destination scale
  (reference: task_creation/image.py:507-637). ``memory_target`` is the
  reference's name for ``uncompressed_shard_bytesize``; ``compress``
  False forces raw shard data encoding. ``use_https_for_source`` is a
  parity no-op here (no https backend; sharded transfers never write
  source provenance)."""
  del use_https_for_source
  from ..sharding import create_sharded_image_info, image_shard_shape_from_spec
  from ..tasks.image_sharded import ImageShardTransferTask

  src = Volume(src_layer_path, mip=mip)
  if memory_target is not None:
    uncompressed_shard_bytesize = memory_target
  materialize_ids = agglomerate or stop_layer is not None
  if materialize_ids and src.graphene is None:
    raise ValueError(
      "agglomerate/stop_layer transfers require a graphene:// source"
    )
  if stop_layer not in (None, 1, 2):
    raise ValueError(f"stop_layer must be 1 or 2: {stop_layer!r}")
  if timestamp is not None and not materialize_ids:
    raise ValueError(
      "timestamp only applies with agglomerate=True or stop_layer"
    )
  # shard data encoding from the compress knob (reference image.py:552-572
  # maps gzip-if-compress-else-raw; "auto" defers to the by-encoding rule)
  if compress == "auto":
    data_encoding = None
  elif compress in (None, False, 0) or str(compress).lower() in ("none", "false"):
    data_encoding = "raw"
  elif compress is True or str(compress).lower() == "gzip":
    data_encoding = "gzip"
  else:
    raise ValueError(f"unsupported shard compress: {compress!r}")
  src_scale = src.meta.scale(mip)
  dest_chunk = list(chunk_size) if chunk_size else src_scale["chunk_sizes"][0]
  dest_offset = (
    list(dest_voxel_offset)
    if dest_voxel_offset is not None
    else (np.asarray(src_scale.get("voxel_offset", [0, 0, 0]))
          + np.asarray(translate)).tolist()
  )
  spec = create_sharded_image_info(
    dataset_size=src_scale["size"],
    chunk_size=dest_chunk,
    encoding=encoding or src_scale["encoding"],
    dtype="uint64" if materialize_ids else src.meta.data_type,
    uncompressed_shard_bytesize=uncompressed_shard_bytesize,
    minishard_index_encoding=minishard_index_encoding,
    data_encoding=data_encoding,
  )
  # dest scale structure mirrors the source through `mip` so mip indices
  # line up; dest_voxel_offset applies at mip 0 geometry
  base_scale = src.meta.scale(0)
  dest_info = Volume.create_new_info(
    num_channels=src.num_channels,
    layer_type=src.layer_type,
    data_type=src.meta.data_type,
    encoding=encoding or base_scale["encoding"],
    resolution=base_scale["resolution"],
    voxel_offset=(
      dest_offset if mip == 0
      else base_scale.get("voxel_offset", [0, 0, 0])
    ),
    volume_size=base_scale["size"],
    chunk_size=dest_chunk,
  )
  try:
    dest = Volume(dest_layer_path)
  except FileNotFoundError:
    dest = Volume.create(dest_layer_path, dest_info)
    for m in range(1, mip + 1):
      dest.meta.add_scale(
        np.asarray(src.meta.downsample_ratio(m)),
        chunk_size=dest_chunk,
        encoding=encoding or src.meta.encoding(m),
      )
    if not truncate_scales:
      for m in range(mip + 1, src.meta.num_mips):
        dest.meta.add_scale(
          np.asarray(src.meta.downsample_ratio(m)),
          chunk_size=dest_chunk,
          encoding=encoding or src.meta.encoding(m),
        )
    if mip > 0 and dest_voxel_offset is not None:
      dest.meta.scale(mip)["voxel_offset"] = list(dest_voxel_offset)
    if cutout and bounds is not None:
      # restrict the new volume to the requested bounds (same semantics
      # as the unsharded transfer above; reference :879-886)
      bounds_res = np.asarray(src.meta.resolution(bounds_mip), dtype=float)
      for i in range(len(dest.info["scales"])):
        ratio = bounds_res / np.asarray(dest.meta.resolution(i), dtype=float)
        sc = dest.info["scales"][i]
        sc["voxel_offset"] = [
          int(v) for v in np.asarray(bounds.minpt, dtype=float) * ratio
        ]
        sc["size"] = [
          int(np.ceil(v)) for v in np.asarray(bounds.size3(), float) * ratio
        ]
    if clean_info:
      for key in ("mesh", "meshing", "skeletons"):
        dest.info.pop(key, None)
  # the computed sharding spec always lands on the scale tasks write to —
  # including when the destination layer already existed
  dest.meta.scale(mip)["sharding"] = spec
  if encoding_level is not None or encoding_effort is not None:
    dest.meta.set_encoding(mip, None, encoding_level, encoding_effort)
  dest.commit_info()

  shape = Vec(*image_shard_shape_from_spec(
    spec, src_scale["size"], dest_chunk
  ))
  # shard files are immutable: the task grid must be shard-aligned so no
  # two tasks emit the same shard file
  task_bounds = get_bounds(src, bounds, mip, bounds_mip)
  task_bounds = task_bounds.expand_to_chunk_size(
    shape, src.meta.voxel_offset(mip)
  ).clamp(src.meta.bounds(mip))

  def make_task(shape_: Vec, offset: Vec):
    return ImageShardTransferTask(
      src_path=src_layer_path,
      dest_path=dest_layer_path,
      shape=shape_.tolist(),
      offset=offset.tolist(),
      mip=mip,
      fill_missing=fill_missing,
      translate=tuple(translate),
      agglomerate=agglomerate,
      timestamp=timestamp,
      stop_layer=stop_layer,
    )

  def finish():
    _provenance(dest, {
      "task": "ImageShardTransferTask",
      "src": src_layer_path, "dest": dest_layer_path,
      "mip": mip, "shape": shape.tolist(),
      "sharding": spec,
      "bounds": task_bounds.to_list(),
    })

  return GridTaskIterator(task_bounds, shape, make_task, finish)


def create_image_shard_downsample_tasks(
  layer_path: str,
  mip: int = 0,
  fill_missing: bool = False,
  sparse: bool = False,
  chunk_size: Optional[Sequence[int]] = None,
  encoding: Optional[str] = None,
  encoding_level: Optional[int] = None,
  encoding_effort: Optional[int] = None,
  factor: Sequence[int] = (2, 2, 1),
  bounds: Optional[Bbox] = None,
  bounds_mip: int = 0,
  memory_target: int = MEMORY_TARGET,
  downsample_method: str = "auto",
  num_mips: int = 1,
  agglomerate: bool = False,
  timestamp: Optional[float] = None,
  truncate_scales: bool = False,
):
  """Downsampled SHARDED mips, several per pass (reference:
  task_creation/image.py:639-807). Each of the ``num_mips`` new scales
  gets its own sharding spec; the task stride is the largest per-mip
  shard extent (shard extents are powers of two per axis, so the max
  evenly contains them all — reference :732-740), and ``num_mips`` is
  clamped so every produced mip stays chunk-aligned within the stride
  (reference :742-757)."""
  from ..sharding import create_sharded_image_info, image_shard_shape_from_spec
  from ..tasks.image_sharded import ImageShardDownsampleTask

  vol = Volume(layer_path, mip=mip)
  if agglomerate and vol.graphene is None:
    raise ValueError("agglomerate downsamples require a graphene:// source")
  if agglomerate and vol.meta.data_type != "uint64":
    # Precomputed data_type is volume-global: agglomerated root ids are
    # uint64 and cannot be stored into a narrower watershed layer's own
    # scales — materialize roots into a uint64 destination first
    # (create_image_shard_transfer_tasks(agglomerate=True)), then
    # downsample that
    raise ValueError(
      f"agglomerate downsamples write uint64 root ids, but this layer's "
      f"data_type is {vol.meta.data_type}; transfer the roots to a "
      f"uint64 destination first"
    )
  if truncate_scales:
    # drop scales above mip before regenerating them (reference :685-687)
    vol.info["scales"] = vol.info["scales"][: mip + 1]
  factor = tuple(int(v) for v in factor)
  num_mips = max(int(num_mips), 1)
  cs = list(chunk_size) if chunk_size else [int(v) for v in vol.meta.chunk_size(mip)]

  base_ratio = np.asarray(vol.meta.downsample_ratio(mip), dtype=np.int64)
  specs = []
  dest_mips = []
  stride = np.zeros(3, dtype=np.int64)
  cum = np.ones(3, dtype=np.int64)
  for i in range(1, num_mips + 1):
    cum = cum * np.asarray(factor, dtype=np.int64)
    dest_size = [
      int(v) for v in -(-np.asarray(vol.meta.volume_size(mip)) // cum)
    ]
    spec = create_sharded_image_info(
      dataset_size=dest_size,
      chunk_size=cs,
      encoding=encoding or vol.meta.encoding(mip),
      dtype=vol.meta.data_type,  # uint64 when agglomerate (validated above)
      # the task must hold the SOURCE region for this shard: one dest
      # voxel at mip+i costs prod(cum) source voxels plus the pyramid
      uncompressed_shard_bytesize=max(
        int(memory_target // (int(np.prod(cum)) + 1)), int(1e6)
      ),
    )
    vol.meta.add_scale(
      base_ratio * cum, chunk_size=cs, encoding=encoding, sharding=spec,
    )
    dmip = vol.meta.mip_from_key("_".join(
      str(int(r)) for r in np.asarray(vol.meta.scale(0)["resolution"])
      * base_ratio * cum
    ))
    if encoding_level is not None or encoding_effort is not None:
      vol.meta.set_encoding(dmip, None, encoding_level, encoding_effort)
    specs.append(spec)
    dest_mips.append(dmip)
    shard_shape = np.asarray(
      image_shard_shape_from_spec(spec, dest_size, cs), dtype=np.int64
    )
    stride = np.maximum(stride, shard_shape * cum)

  # clamp num_mips so every produced mip's dest region inside the stride
  # is chunk-aligned (reference :742-757)
  max_mips = num_mips
  for axis in range(3):
    if factor[axis] == 1:
      continue
    chunks_per_dim = stride[axis] // cs[axis]
    max_mip_a = int(np.floor(np.log2(max(chunks_per_dim, 1))
                             / np.log2(factor[axis])))
    max_mips = min(max_mips, max_mip_a)
  max_mips = max(max_mips, 1)
  if max_mips < num_mips:
    # drop the unreachable scales again
    for dmip in sorted(dest_mips[max_mips:], reverse=True):
      del vol.info["scales"][dmip]
    dest_mips = dest_mips[:max_mips]
    specs = specs[:max_mips]
  if max_mips > 1 and (encoding or vol.meta.encoding(mip)) == "jpeg":
    # lossy pyramids keep their TOP mip lossless so further downsample
    # passes can build on it reliably (reference :714-718)
    vol.meta.set_encoding(dest_mips[-1], "png", 9)
  vol.commit_info()

  shape = Vec(*stride)
  # shard-align the task grid: shard files are write-once
  task_bounds = get_bounds(vol, bounds, mip, bounds_mip)
  task_bounds = task_bounds.expand_to_chunk_size(
    shape, vol.meta.voxel_offset(mip)
  ).clamp(vol.meta.bounds(mip))

  def make_task(shape_: Vec, offset: Vec):
    return ImageShardDownsampleTask(
      src_path=layer_path,
      shape=shape_.tolist(),
      offset=offset.tolist(),
      mip=mip,
      fill_missing=fill_missing,
      sparse=sparse,
      factor=list(factor),
      downsample_method=downsample_method,
      num_mips=max_mips,
      agglomerate=agglomerate,
      timestamp=timestamp,
    )

  def finish():
    _provenance(vol, {
      "task": "ImageShardDownsampleTask",
      "mip": mip, "dest_mips": [int(m) for m in dest_mips],
      "num_mips": max_mips,
      "factor": list(factor), "sharding": specs[0],
      "bounds": task_bounds.to_list(),
    })

  return GridTaskIterator(task_bounds, shape, make_task, finish)


def create_deletion_tasks(
  layer_path: str,
  mip: int = 0,
  num_mips: int = 0,
  shape: Optional[Sequence[int]] = None,
  bounds: Optional[Bbox] = None,
  bounds_mip: int = 0,
):
  vol = Volume(layer_path, mip=mip)
  if shape is None:
    shape = vol.meta.chunk_size(mip) * 4
  shape = Vec(*shape)
  task_bounds = get_bounds(vol, bounds, mip, bounds_mip)

  def make_task(shape_: Vec, offset: Vec):
    return DeleteTask(
      layer_path=layer_path,
      shape=shape_.tolist(),
      offset=offset.tolist(),
      mip=mip,
      num_mips=num_mips,
    )

  def finish():
    _provenance(vol, {
      "task": "DeleteTask", "mip": mip, "num_mips": num_mips,
      "bounds": task_bounds.to_list(),
    })

  return GridTaskIterator(task_bounds, shape, make_task, finish)


def create_blackout_tasks(
  cloudpath: str,
  bounds: Bbox,
  mip: int = 0,
  shape: Sequence[int] = (2048, 2048, 64),
  value: int = 0,
  non_aligned_writes: bool = False,
):
  vol = Volume(cloudpath, mip=mip)
  shape = Vec(*shape)
  if not non_aligned_writes:
    bounds = bounds.expand_to_chunk_size(
      vol.meta.chunk_size(mip), vol.meta.voxel_offset(mip)
    )
  bounds = Bbox.intersection(bounds, vol.meta.bounds(mip))

  def make_task(shape_: Vec, offset: Vec):
    return BlackoutTask(
      cloudpath=cloudpath,
      mip=mip,
      shape=np.minimum(
        np.asarray(shape_), np.asarray(bounds.maxpt) - np.asarray(offset)
      ).tolist(),
      offset=offset.tolist(),
      value=value,
      non_aligned_writes=non_aligned_writes,
    )

  return GridTaskIterator(bounds, shape, make_task)


def create_touch_tasks(
  cloudpath: str,
  mip: int = 0,
  shape: Sequence[int] = (2048, 2048, 64),
  bounds: Optional[Bbox] = None,
):
  vol = Volume(cloudpath, mip=mip)
  shape = Vec(*shape)
  task_bounds = get_bounds(vol, bounds, mip, mip)

  def make_task(shape_: Vec, offset: Vec):
    return TouchTask(
      cloudpath=cloudpath, mip=mip,
      shape=shape_.tolist(), offset=offset.tolist(),
    )

  def finish():
    _provenance(vol, {
      "task": "TouchTask", "mip": mip, "bounds": task_bounds.to_list(),
    })

  return GridTaskIterator(task_bounds, shape, make_task, finish)


def create_luminance_levels_tasks(
  src_path: str,
  levels_path: Optional[str] = None,
  mip: int = 0,
  coverage_factor: float = 0.01,
  shape: Optional[Sequence[int]] = None,
  offset: Optional[Sequence[int]] = None,
  bounds: Optional[Bbox] = None,
  bounds_mip: Optional[int] = None,
  fill_missing: bool = False,
):
  """Phase 1 of contrast correction: per-z histograms
  (reference task_creation/image.py:1284-1545)."""
  from ..tasks.contrast import LuminanceLevelsTask

  vol = Volume(src_path, mip=mip)
  if offset is not None and bounds is None and shape is not None:
    # reference shape/offset pair: one explicit task window
    bounds = Bbox(Vec(*offset), Vec(*offset) + Vec(*shape))
  task_bounds = get_bounds(
    vol, bounds, mip, mip if bounds_mip is None else bounds_mip,
    chunk_size=vol.meta.chunk_size(mip),
  )
  if shape is None:
    # one task per CHUNK-Z-ALIGNED z slab (not per z slice): the task
    # downloads sampled patches as whole z columns and histograms every
    # slice from memory, so each stored chunk decodes exactly once
    sz3 = task_bounds.size3()
    shape = (int(sz3.x), int(sz3.y), int(vol.meta.chunk_size(mip).z))
  shape = Vec(*shape)

  def make_task(shape_: Vec, offset: Vec):
    return LuminanceLevelsTask(
      src_path=src_path,
      levels_path_=levels_path,
      shape=shape_.tolist(),
      offset=offset.tolist(),
      mip=mip,
      coverage_factor=coverage_factor,
      fill_missing=fill_missing,
    )

  return GridTaskIterator(task_bounds, shape, make_task)


def create_contrast_normalization_tasks(
  src_path: str,
  dest_path: str,
  levels_path: Optional[str] = None,
  mip: int = 0,
  clip_fraction: float = 0.01,
  shape: Optional[Sequence[int]] = None,
  translate: Sequence[int] = (0, 0, 0),
  bounds: Optional[Bbox] = None,
  bounds_mip: Optional[int] = None,
  fill_missing: bool = False,
  minval: int = 0,
  maxval: int = 255,
  chunk_size: Optional[Sequence[int]] = None,
):
  """Phase 2: histogram stretch into a new layer."""
  from ..tasks.contrast import ContrastNormalizationTask

  src = Volume(src_path, mip=mip)
  scale = src.meta.scale(mip)
  info = Volume.create_new_info(
    num_channels=src.num_channels,
    layer_type=src.layer_type,
    data_type=src.meta.data_type,
    encoding=scale["encoding"],
    resolution=scale["resolution"],
    voxel_offset=(np.asarray(scale.get("voxel_offset", [0, 0, 0]))
                  + np.asarray(translate)).tolist(),
    volume_size=scale["size"],
    chunk_size=chunk_size or scale["chunk_sizes"][0],
  )
  try:
    dest = Volume(dest_path)
  except FileNotFoundError:
    dest = Volume.create(dest_path, info)

  task_bounds = get_bounds(
    src, bounds, mip, mip if bounds_mip is None else bounds_mip,
    chunk_size=src.meta.chunk_size(mip),
  )
  if shape is None:
    cs = dest.meta.chunk_size(0)
    shape = (int(cs.x) * 8, int(cs.y) * 8, int(cs.z))
  shape = Vec(*shape)

  def make_task(shape_: Vec, offset: Vec):
    return ContrastNormalizationTask(
      levels_path_=levels_path,
      src_path=src_path,
      dest_path=dest_path,
      shape=shape_.tolist(),
      offset=offset.tolist(),
      mip=mip,
      clip_fraction=clip_fraction,
      fill_missing=fill_missing,
      translate=tuple(translate),
      minval=minval,
      maxval=maxval,
    )

  def finish():
    _provenance(dest, {
      "task": "ContrastNormalizationTask", "src": src_path,
      "mip": mip, "clip_fraction": clip_fraction,
      "bounds": task_bounds.to_list(),
    })

  return GridTaskIterator(task_bounds, shape, make_task, finish)


def create_clahe_tasks(
  src_path: str,
  dest_path: str,
  mip: int = 0,
  clip_limit: float = 40.0,
  tile_grid_size=8,
  shape: Sequence[int] = (2048, 2048, 64),
  bounds: Optional[Bbox] = None,
  bounds_mip: Optional[int] = None,
  fill_missing: bool = False,
  chunk_size: Optional[Sequence[int]] = None,
):
  from ..tasks.contrast import CLAHETask

  src = Volume(src_path, mip=mip)
  scale = src.meta.scale(mip)
  info = Volume.create_new_info(
    num_channels=src.num_channels,
    layer_type="image",
    data_type=src.meta.data_type,
    encoding=scale["encoding"],
    resolution=scale["resolution"],
    voxel_offset=scale.get("voxel_offset", [0, 0, 0]),
    volume_size=scale["size"],
    chunk_size=chunk_size or scale["chunk_sizes"][0],
  )
  try:
    dest = Volume(dest_path)
  except FileNotFoundError:
    dest = Volume.create(dest_path, info)

  task_bounds = get_bounds(
    src, bounds, mip, mip if bounds_mip is None else bounds_mip,
    chunk_size=src.meta.chunk_size(mip),
  )
  shape = Vec(*shape)

  def make_task(shape_: Vec, offset: Vec):
    return CLAHETask(
      src_path=src_path,
      dest_path=dest_path,
      shape=shape_.tolist(),
      offset=offset.tolist(),
      mip=mip,
      clip_limit=clip_limit,
      tile_grid_size=tile_grid_size,
      fill_missing=fill_missing,
    )

  def finish():
    _provenance(dest, {
      "task": "CLAHETask", "src": src_path, "mip": mip,
      "clip_limit": clip_limit, "bounds": task_bounds.to_list(),
    })

  return GridTaskIterator(task_bounds, shape, make_task, finish)


def create_voxel_counting_tasks(
  cloudpath: str,
  mip: int = 0,
  shape: Sequence[int] = (512, 512, 512),
  bounds: Optional[Bbox] = None,
  fill_missing: bool = False,
  agglomerate: bool = False,
  timestamp: Optional[float] = None,
):
  """Census phase of voxel statistics (reference :1928-2030); reduce with
  tasks.stats.accumulate_voxel_counts."""
  from ..tasks.stats import CountVoxelsTask

  vol = Volume(cloudpath, mip=mip)
  if agglomerate and vol.graphene is None:
    # fail at creation, not in thousands of queued tasks
    raise ValueError("agglomerate voxel counting requires a graphene:// path")
  task_bounds = get_bounds(vol, bounds, mip, mip)
  shape = Vec(*shape)

  def make_task(shape_: Vec, offset: Vec):
    return CountVoxelsTask(
      cloudpath=cloudpath,
      shape=shape_.tolist(),
      offset=offset.tolist(),
      mip=mip,
      fill_missing=fill_missing,
      agglomerate=agglomerate,
      timestamp=timestamp,
    )

  return GridTaskIterator(task_bounds, shape, make_task)


def create_spatial_index_tasks(
  cloudpath: str,
  prefix: str,
  mip: int = 0,
  shape: Sequence[int] = (448, 448, 448),
  bounds: Optional[Bbox] = None,
  fill_missing: bool = False,
):
  """Rebuild a layer's .spatial files (reference tasks/spatial_index.py)."""
  from ..tasks.stats import SpatialIndexTask

  vol = Volume(cloudpath, mip=mip)
  task_bounds = get_bounds(vol, bounds, mip, mip)
  shape = Vec(*shape)

  def make_task(shape_: Vec, offset: Vec):
    return SpatialIndexTask(
      cloudpath=cloudpath,
      prefix=prefix,
      shape=shape_.tolist(),
      offset=offset.tolist(),
      mip=mip,
      fill_missing=fill_missing,
    )

  return GridTaskIterator(task_bounds, shape, make_task)


def create_reordering_tasks(
  src_path: str,
  dest_path: str,
  mapping: dict,
  mip: int = 0,
  z_per_task: int = 16,
  fill_missing: bool = False,
  encoding: Optional[str] = None,
  encoding_level: Optional[int] = None,
  encoding_effort: Optional[int] = None,
  compress="gzip",
  delete_black_uploads: bool = False,
  background_color: int = 0,
):
  """Z-slice shuffle into a fresh layer (reference :1193)."""
  from ..tasks.stats import ReorderTask

  src = Volume(src_path, mip=mip)
  scale = src.meta.scale(mip)
  info = Volume.create_new_info(
    num_channels=src.num_channels,
    layer_type=src.layer_type,
    data_type=src.meta.data_type,
    encoding=encoding or scale["encoding"],
    resolution=scale["resolution"],
    voxel_offset=scale.get("voxel_offset", [0, 0, 0]),
    volume_size=scale["size"],
    chunk_size=scale["chunk_sizes"][0],
  )
  try:
    Volume(dest_path)
  except FileNotFoundError:
    dest = Volume.create(dest_path, info)
    if encoding_level is not None or encoding_effort is not None:
      dest.meta.set_encoding(0, None, encoding_level, encoding_effort)
      dest.commit_info()

  z0 = int(src.bounds.minpt.z)
  z1 = int(src.bounds.maxpt.z)
  for zs in range(z0, z1, z_per_task):
    yield ReorderTask(
      src_path=src_path,
      dest_path=dest_path,
      mip=mip,
      z_start=zs,
      z_end=min(zs + z_per_task, z1),
      mapping=mapping,
      fill_missing=fill_missing,
      compress=compress,
      delete_black_uploads=delete_black_uploads,
      background_color=background_color,
    )


def create_fixup_downsample_tasks(
  layer_path: str,
  bad_bboxes: Optional[Sequence[Bbox]] = None,
  mip: int = 0,
  shape: Sequence[int] = (2048, 2048, 64),
  fill_missing: bool = True,
  num_mips: int = 1,
  sparse: bool = False,
  points: Optional[Sequence[Sequence[int]]] = None,
  axis: str = "z",
):
  """Re-run downsamples covering damaged regions (black spots)
  (reference :1558-1581 repair tool). Give either bounding boxes or the
  reference's form — one ``points`` coordinate inside each black spot."""
  vol = Volume(layer_path, mip=mip)
  if bad_bboxes is None:
    bad_bboxes = []
  if points:
    # reference semantics: points are MIP-0 (high-res) coordinates
    # (compute_fixup_offsets, reference image.py:1547-1556)
    ratio = np.asarray(vol.meta.downsample_ratio(mip), dtype=np.int64)
    bad_bboxes = list(bad_bboxes) + [
      Bbox(Vec(*(np.asarray(p, np.int64) // ratio)),
           Vec(*(np.asarray(p, np.int64) // ratio)) + 1)
      for p in points
    ]
  shape = Vec(*shape)
  seen = set()
  for bbx in bad_bboxes:
    aligned = bbx.expand_to_chunk_size(shape, vol.meta.voxel_offset(mip))
    aligned = Bbox.intersection(aligned, vol.meta.bounds(mip))
    from ..lib import chunk_bboxes

    for task_box in chunk_bboxes(aligned, shape, vol.meta.voxel_offset(mip),
                                 clamp=False):
      key = task_box.to_filename()
      if key in seen:
        continue
      seen.add(key)
      yield DownsampleTask(
        layer_path=layer_path,
        mip=mip,
        shape=shape.tolist(),
        offset=[int(v) for v in task_box.minpt],
        fill_missing=fill_missing,
        sparse=sparse,
        num_mips=num_mips,
        factor=tuple(int(v) for v in axis_to_factor(axis)),
      )


def compute_rois(
  cloudpath: str,
  mip: Optional[int] = None,
  threshold: float = 0.0,
  dust_threshold: int = 100,
  suppress_faint_voxels: int = 0,
  max_axial_length: int = 512,
  z_step: Optional[int] = None,
  progress: bool = False,
  save: bool = True,
) -> list:
  """Detect tissue regions-of-interest: CCL over the coarsest mip's
  foreground, returning physical-space bounding boxes
  (reference :2032-2095). ``save`` also records them in the layer's
  info file as mip-0 voxel bboxes (the reference CLI prints
  "info file updated", cli.py:441).

  ``suppress_faint_voxels`` zeroes values ≤ that level first;
  ``max_axial_length`` downsamples in memory until XY fits that square
  (reference :2050-2065); ``z_step`` evaluates ROIs per z-slab so thin
  tissue at different depths yields separate boxes."""
  from scipy import ndimage as ndi

  vol = Volume(cloudpath)
  mip = vol.meta.num_mips - 1 if mip is None else mip
  img = vol.download(vol.meta.bounds(mip), mip=mip)[..., 0]
  res = np.asarray(vol.meta.resolution(mip), dtype=np.int64)
  offset = np.asarray(vol.meta.voxel_offset(mip), dtype=np.int64)

  # in-memory 2x2x1 average downsample until the XY plane fits the budget
  # (reference :2050-2065); ROI coords scale back up through `scale_xy`
  scale_xy = 1
  while img.shape[0] * img.shape[1] > max_axial_length ** 2:
    from ..ops import pooling

    ds = pooling.host_downsample(
      np.ascontiguousarray(img), (2, 2, 1), 1, method="average"
    )
    img = (
      ds[0] if ds is not None
      else pooling.downsample(img, (2, 2, 1), 1, method="average")[0]
    )
    scale_xy *= 2

  if suppress_faint_voxels:
    img = np.where(img <= suppress_faint_voxels, 0, img)
  fg = img > threshold

  nz = img.shape[2]
  z_step = nz if not z_step else int(z_step)
  rois = []
  z_starts = range(0, nz, z_step)
  if progress:
    from tqdm import tqdm

    z_starts = tqdm(z_starts, desc="ROI z-slabs")
  vx_scale = np.asarray([scale_xy, scale_xy, 1], dtype=np.int64)
  for z0 in z_starts:
    slab = fg[:, :, z0:z0 + z_step]
    labeled, _ = ndi.label(slab)
    for sl in ndi.find_objects(labeled):
      if sl is None:
        continue
      size = np.prod([s.stop - s.start for s in sl])
      if size < dust_threshold:
        continue
      mn = np.asarray([s.start for s in sl]) + [0, 0, z0]
      mx = np.asarray([s.stop for s in sl]) + [0, 0, z0]
      mn = (mn * vx_scale + offset) * res
      mx = (mx * vx_scale + offset) * res
      rois.append(Bbox(mn, mx))
  if save:
    # reference format (image.py:2085-2092): flat [x0,y0,z0,x1,y1,z1]
    # lists with INCLUSIVE max corners, stored on the mip-0 scale
    res0 = np.asarray(vol.meta.resolution(0), dtype=np.int64)
    vol.info["scales"][0]["rois"] = [
      [int(v) for v in np.asarray(r.minpt) // res0]
      + [int(v) - 1 for v in np.asarray(r.maxpt) // res0]
      for r in rois
    ]
    vol.commit_info()
  return rois


def create_quantized_affinity_info(
  src_layer: str,
  dest_layer: str,
  shape: Sequence[int],
  mip: int,
  chunk_size: Sequence[int],
  encoding: str = "raw",
) -> dict:
  src = Volume(src_layer, mip=mip)
  scale = src.meta.scale(mip)
  return Volume.create_new_info(
    num_channels=1,
    layer_type="image",
    data_type="uint8",
    encoding=encoding,
    resolution=scale["resolution"],
    voxel_offset=scale.get("voxel_offset", [0, 0, 0]),
    volume_size=scale["size"],
    chunk_size=chunk_size,
  )


def create_quantize_tasks(
  src_layer: str,
  dest_layer: str,
  shape: Sequence[int],
  mip: int = 0,
  fill_missing: bool = False,
  chunk_size: Sequence[int] = (128, 128, 64),
  encoding: str = "raw",
  bounds: Optional[Bbox] = None,
  bounds_mip: int = 0,
):
  shape = Vec(*shape)
  info = create_quantized_affinity_info(
    src_layer, dest_layer, shape, mip, chunk_size, encoding=encoding,
  )
  dest = Volume.create(dest_layer, info)
  src = Volume(src_layer, mip=mip)
  task_bounds = get_bounds(src, bounds, mip, bounds_mip)

  def make_task(shape_: Vec, offset: Vec):
    return QuantizeTask(
      source_layer_path=src_layer,
      dest_layer_path=dest_layer,
      shape=shape_.tolist(),
      offset=offset.tolist(),
      mip=mip,
      fill_missing=fill_missing,
    )

  def finish():
    _provenance(dest, {
      "task": "QuantizeTask", "mip": mip, "bounds": task_bounds.to_list(),
    })

  return GridTaskIterator(task_bounds, shape, make_task, finish)
