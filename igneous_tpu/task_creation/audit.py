"""Audit + repair factories (ISSUE 16).

``create_integrity_audit_tasks`` fans an :class:`IntegrityAuditTask`
grid over one mip of a layer — grid cells are a whole multiple of the
chunk size, resolved through the same :func:`get_bounds` math the
creation factories use, so the audited universe IS the produced one.

The heal half turns findings back into producing tasks:
``downsample_repair_tasks`` reads the campaign parameters the
downsample factory recorded in provenance, maps each damaged chunk
(at whatever mip it was found) back to the source-mip task-grid cell
that produced it, dedups cells, and re-mints the original
``DownsampleTask`` for exactly those cells. Repairs ride the normal
queue/DLQ/trace machinery — a repair that keeps failing quarantines
like any other task.
"""

from __future__ import annotations

import json
from typing import Iterable, List, Optional, Sequence, Tuple

from ..lib import Bbox, Vec
from ..storage import CloudFiles
from ..tasks.audit import IntegrityAuditTask
from ..tasks.image import DownsampleTask
from ..volume import Volume
from .common import GridTaskIterator, get_bounds

# audit grid cells span this many chunks per axis by default: big enough
# to amortize the per-task manifest load, small enough to range-lease
DEFAULT_CELL_CHUNKS = (8, 8, 4)


def create_integrity_audit_tasks(
  layer_path: str,
  mip: int,
  report_dir: str,
  bounds: Optional[Bbox] = None,
  bounds_mip: int = 0,
  shape: Optional[Sequence[int]] = None,
  check_digest: bool = True,
  require_present: bool = True,
):
  """Task iterator auditing ``mip`` of ``layer_path``; findings land
  under ``report_dir`` (one deterministic JSONL file per grid cell)."""
  vol = Volume(layer_path, mip=mip)
  if shape is None:
    shape = vol.meta.chunk_size(mip) * Vec(*DEFAULT_CELL_CHUNKS)
  shape = Vec(*shape)
  task_bounds = get_bounds(vol, bounds, mip, bounds_mip)

  def make_task(shape_: Vec, offset: Vec):
    return IntegrityAuditTask(
      layer_path=layer_path,
      mip=mip,
      shape=shape_.tolist(),
      offset=offset.tolist(),
      report_dir=report_dir,
      check_digest=check_digest,
      require_present=require_present,
    )

  return GridTaskIterator(task_bounds, shape, make_task)


def load_findings(report_dir: str) -> Tuple[List[dict], dict]:
  """Merge every per-cell report under ``report_dir`` into
  (findings, totals). Reports are deterministic-named and overwritten
  per audit round, so this always reflects the latest round."""
  cf = CloudFiles(report_dir)
  findings: List[dict] = []
  totals = {"chunks": 0, "findings": 0, "unmanifested": 0, "cells": 0}
  for name in sorted(cf.list("")):
    base = name.rsplit("/", 1)[-1]
    if not (base.startswith("findings_") and base.endswith(".jsonl")):
      continue
    raw = cf.get(name)
    if raw is None:
      continue
    for line in raw.splitlines():
      if not line.strip():
        continue
      rec = json.loads(line)
      if rec.get("kind") == "summary":
        totals["cells"] += 1
        for field in ("chunks", "findings", "unmanifested"):
          totals[field] += int(rec.get(field, 0))
      else:
        findings.append(rec)
  # dedup by (mip, key): at-least-once delivery can double-report a cell
  seen = set()
  unique = []
  for f in sorted(findings, key=lambda f: (f["mip"], f["key"], f["kind"])):
    k = (f["mip"], f["key"])
    if k not in seen:
      seen.add(k)
      unique.append(f)
  return unique, totals


def downsample_provenance(vol: Volume) -> Optional[dict]:
  """Latest DownsampleTask campaign record from the layer's provenance
  (the parameter set ``create_downsampling_tasks`` wrote on finish)."""
  prov = vol.meta.refresh_provenance()
  for entry in reversed(prov.get("processing", [])):
    method = entry.get("method", {})
    if isinstance(method, dict) and method.get("task") == "DownsampleTask":
      return method
  return None


def downsample_repair_tasks(
  layer_path: str,
  findings: Iterable[dict],
  provenance: Optional[dict] = None,
) -> Tuple[List[DownsampleTask], List[dict]]:
  """(repair tasks, unhealable findings).

  Each finding's chunk bbox is converted to source-mip coordinates and
  floored onto the producing campaign's task grid; one repair task per
  damaged cell re-runs the original downsample over that cell, which
  rewrites every output mip of the cell — byte-identically, since the
  downsample device pass and gzip (mtime=0) encode are deterministic.
  Findings at or below the source mip have no recorded producer here
  and come back as unhealable."""
  vol = Volume(layer_path, mip=0, bounded=False)
  prov = provenance if provenance is not None else downsample_provenance(vol)
  if prov is None:
    return [], list(findings)

  src_mip = int(prov["mip"])
  shape = Vec(*prov["shape"])
  task_bounds = Bbox.from_list(prov["bounds"])
  cells = set()
  unhealable = []
  for f in findings:
    fmip = int(f["mip"])
    if fmip <= src_mip or fmip > src_mip + int(prov["num_mips"]):
      unhealable.append(f)
      continue
    fbox = Bbox.from_list(f["bbox"])
    at_src = vol.meta.bbox_to_mip(fbox, fmip, src_mip)
    lo = (at_src.minpt - task_bounds.minpt) // shape
    hi = (at_src.maxpt - Vec(1, 1, 1) - task_bounds.minpt) // shape
    for x in range(int(lo.x), int(hi.x) + 1):
      for y in range(int(lo.y), int(hi.y) + 1):
        for z in range(int(lo.z), int(hi.z) + 1):
          cells.add((x, y, z))

  tasks = []
  for cell in sorted(cells):
    offset = task_bounds.minpt + Vec(*cell) * shape
    tasks.append(DownsampleTask(
      layer_path=layer_path,
      mip=src_mip,
      shape=shape.tolist(),
      offset=offset.tolist(),
      fill_missing=bool(prov.get("fill_missing", False)),
      sparse=bool(prov.get("sparse", False)),
      delete_black_uploads=bool(prov.get("delete_black_uploads", False)),
      background_color=int(prov.get("background_color", 0)),
      compress=prov.get("compress", "gzip"),
      downsample_method=prov.get("method", "auto"),
      num_mips=int(prov["num_mips"]),
      factor=tuple(prov["factor"]),
    ))
  return tasks, unhealable
