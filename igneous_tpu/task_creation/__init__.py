"""Task factories: grid decomposition + destination metadata management.

Mirrors /root/reference/igneous/task_creation/__init__.py's role: the
public ``create_*_tasks`` generators the CLI and library users call.
"""

from .common import (
  FinelyDividedTaskIterator,
  GridTaskIterator,
  get_bounds,
  num_tasks,
  operator_contact,
)
from .ccl import (
  ccl_auto,
  clean_ccl_files,
  create_ccl_equivalence_tasks,
  create_ccl_face_tasks,
  create_ccl_relabel_tasks,
  create_relabeling,
)
from .skeleton import (
  create_sharded_from_unsharded_skeleton_merge_tasks,
  create_sharded_skeleton_merge_tasks,
  create_skeleton_deletion_tasks,
  create_skeleton_transfer_tasks,
  create_skeletonizing_tasks,
  create_unsharded_skeleton_merge_tasks,
)
from .mesh import (
  configure_multires_info,
  create_mesh_deletion_tasks,
  create_mesh_manifest_tasks,
  create_mesh_transfer_tasks,
  create_graphene_meshing_tasks,
  create_meshing_tasks,
  create_sharded_multires_mesh_from_unsharded_tasks,
  create_sharded_multires_mesh_tasks,
  create_unsharded_multires_mesh_tasks,
)
from .image import (
  MEMORY_TARGET,
  compute_rois,
  create_blackout_tasks,
  create_clahe_tasks,
  create_contrast_normalization_tasks,
  create_deletion_tasks,
  create_downsampling_tasks,
  create_fixup_downsample_tasks,
  create_image_shard_downsample_tasks,
  create_image_shard_transfer_tasks,
  create_luminance_levels_tasks,
  create_quantized_affinity_info,
  create_quantize_tasks,
  create_reordering_tasks,
  create_spatial_index_tasks,
  create_touch_tasks,
  create_transfer_tasks,
  create_voxel_counting_tasks,
)
from .inference import create_inference_tasks
from ..tasks.stats import accumulate_voxel_counts, load_voxel_counts
