"""Task factories: grid decomposition + destination metadata management.

Mirrors /root/reference/igneous/task_creation/__init__.py's role: the
public ``create_*_tasks`` generators the CLI and library users call.
"""

from .common import (
  FinelyDividedTaskIterator,
  GridTaskIterator,
  get_bounds,
  num_tasks,
  operator_contact,
)
from .ccl import (
  ccl_auto,
  clean_ccl_files,
  create_ccl_equivalence_tasks,
  create_ccl_face_tasks,
  create_ccl_relabel_tasks,
  create_relabeling,
)
from .mesh import (
  create_mesh_deletion_tasks,
  create_mesh_manifest_tasks,
  create_mesh_transfer_tasks,
  create_meshing_tasks,
)
from .image import (
  MEMORY_TARGET,
  create_blackout_tasks,
  create_deletion_tasks,
  create_downsampling_tasks,
  create_image_shard_downsample_tasks,
  create_image_shard_transfer_tasks,
  create_quantized_affinity_info,
  create_quantize_tasks,
  create_touch_tasks,
  create_transfer_tasks,
)
