"""Object-storage abstraction: the data plane every task reads/writes through.

Equivalent in capability to the reference's CloudFiles layer
(/root/reference uses cloud-files for gs/s3/file/mem IO, e.g.
igneous/tasks/image/image.py:17): get/put/list/delete/exists with transparent
gzip/zstd compression, addressed by protocol URL.

Protocols implemented here:
  - ``file://`` — local filesystem (the test + single-host path).
  - ``mem://``  — process-local in-memory store (unit tests, scratch).
  - ``gs://``   — real GCS JSON-API client (storage_gcs.py): resumable
    uploads, paginated listing, Range reads, service-account/static-token
    auth from CloudVolume-style secret files.
  - ``s3://``   — real S3 REST client (storage_s3.py): SigV4 signing,
    multipart upload, ListObjectsV2 pagination.

`register_protocol` remains the override hook (it takes precedence over
the built-in clients): deployments can attach google-cloud-storage/boto
backends, and `attach_memory_protocol` swaps any protocol for the
in-memory double. Zero-egress note: the in-tree cloud clients are
exercised against in-process fake servers (tests/fake_cloud_servers.py);
the real endpoints are unreachable from this build image.

Compression follows the CloudFiles file-layout convention: a file compressed
with gzip is stored under ``<key>.gz`` and listed/read under ``<key>``.
"""

from __future__ import annotations

import gzip as gzip_mod
import json
import os
import shutil
import threading
from typing import Dict, Iterable, Iterator, List, Optional, Tuple, Union

try:
  import zstandard
except ImportError:  # zstd stays readable/writable only where the codec ships
  zstandard = None

from . import integrity
from .lib import jsonify
from .observability import trace as _trace

from .analysis import knobs

# brotli is deliberately absent: no brotli codec ships in this environment,
# so .br files are left visible under their literal names rather than
# advertised as readable and then crashing on get().
COMPRESSION_EXTS = {
  "gzip": ".gz",
  "zstd": ".zstd",
  None: "",
  False: "",
  "": "",
}
# explicit-level gzip variants ("gzip-1" … "gzip-9") share the .gz wire
# format — readers cannot tell levels apart, only writers choose
for _lvl in range(1, 10):
  COMPRESSION_EXTS[f"gzip-{_lvl}"] = ".gz"
_EXT_TO_COMPRESSION = {".gz": "gzip", ".zstd": "zstd"}


def compress_bytes(data: bytes, method) -> bytes:
  if method in (None, False, ""):
    return data
  if method == "gzip" or (
    isinstance(method, str) and method.startswith("gzip-")
  ):
    level = 6 if method == "gzip" else int(method.split("-", 1)[1])
    # mtime=0 keeps output deterministic: re-running a task writes
    # byte-identical objects (idempotent at-least-once execution), and
    # the lease batcher's byte-identity contract with solo execution
    # stays literally true for compressed chunks
    return gzip_mod.compress(data, compresslevel=level, mtime=0)
  if method == "zstd":
    if zstandard is None:
      raise ImportError(
        "zstd compression needs the 'zstandard' package, which this "
        "environment does not ship; use gzip or no compression"
      )
    return zstandard.ZstdCompressor().compress(data)
  raise ValueError(f"Unsupported compression: {method}")


def wire_ext(compress) -> Optional[str]:
  """The on-wire filename extension a ``compress=`` selection produces
  ("" for uncompressed), or None when the method is unknown — callers
  treat None as "not eligible for a compressed-domain move" and take the
  decode path, where the unknown method raises with full context."""
  try:
    return COMPRESSION_EXTS[compress]
  except (KeyError, TypeError):
    return None


def method_for_ext(ext: str) -> Optional[str]:
  """Inverse of :func:`wire_ext`: the compression method a stored
  filename extension implies (None for "" — uncompressed). The serve
  tier's SSD spill mirrors the CloudFiles file layout, so reading a
  spilled ``<key>.gz`` back recovers the wire method from the name."""
  if not ext:
    return None
  return _EXT_TO_COMPRESSION.get(ext)


def stored_exts() -> Tuple[str, ...]:
  """Every extension a stored object may carry ("" first — probe order
  matches :meth:`CloudFiles._resolve`)."""
  return ("",) + tuple(_EXT_TO_COMPRESSION)


def scratch_compression(default="gzip"):
  """Compression for INTERMEDIATE artifacts (.frags containers, CCL face
  planes, transfer scratch) — objects a later merge/fixup task consumes
  and deletes, never part of the published format contract.

  ``IGNEOUS_SCRATCH_COMPRESS`` selects the method fleet-wide:
    gzip-6 (alias gzip)  — the historical default; bytes unchanged.
    gzip-1               — ~3-5x faster deflate for a few % more bytes;
                           the right trade for short-lived scratch.
    zstd                 — when the codec ships in the image.
    none                 — raw (fastest; storage pays the difference).

  Unset (or set to the default) keeps every byte identical to previous
  releases, which is what lets the chaos soak and containment tests keep
  pinning output bytes while operators tune scratch IO independently.
  """
  val = knobs.get_str("IGNEOUS_SCRATCH_COMPRESS").strip().lower()
  if not val:
    return default
  if val in ("none", "raw", "0", "off"):
    return None
  if val == "gzip":
    return "gzip"
  if val == "zstd":
    if zstandard is None:
      return default  # the knob must never take a worker down
    return "zstd"
  if val.startswith("gzip-") and val in COMPRESSION_EXTS:
    return val
  raise ValueError(
    f"IGNEOUS_SCRATCH_COMPRESS={val!r} unsupported: use "
    "gzip-1..gzip-9, gzip, zstd, or none"
  )


def scratch_gzip_level(default: int) -> int:
  """Level override for scratch writers that call gzip directly (the CCL
  face planes pre-date the CloudFiles compress path). Honors the same
  env knob; non-gzip selections keep the caller's default level."""
  method = scratch_compression(f"gzip-{default}")
  if isinstance(method, str) and method.startswith("gzip-"):
    return int(method.split("-", 1)[1])
  if method == "gzip":
    return 6
  return default


def decompress_bytes(data: bytes, method) -> bytes:
  if method in (None, False, ""):
    return data
  if method == "gzip":
    return gzip_mod.decompress(data)
  if method == "zstd":
    if zstandard is None:
      raise ImportError(
        "reading a .zstd object needs the 'zstandard' package, which this "
        "environment does not ship"
      )
    return zstandard.ZstdDecompressor().decompress(data)
  raise ValueError(f"Unsupported compression: {method}")


class ExtractedPath:
  __slots__ = ("protocol", "path")

  def __init__(self, protocol: str, path: str):
    self.protocol = protocol
    self.path = path

  def __repr__(self):
    return f"{self.protocol}://{self.path}"


def extract_path(cloudpath: str) -> ExtractedPath:
  if "://" in cloudpath:
    protocol, path = cloudpath.split("://", 1)
  else:
    protocol, path = "file", cloudpath
  if protocol == "precomputed":  # allow "precomputed://file://..." prefixes
    return extract_path(path)
  if protocol == "file":
    path = os.path.abspath(os.path.expanduser(path))
  return ExtractedPath(protocol, path.rstrip("/"))


def to_https_path(cloudpath: str) -> str:
  p = extract_path(cloudpath)
  return f"{p.protocol}://{p.path}"


normalize_path = to_https_path


# ---------------------------------------------------------------------------
# in-memory store


class _MemBucket:
  def __init__(self):
    self.files: Dict[str, bytes] = {}
    self.lock = threading.RLock()


_MEM_BUCKETS: Dict[str, _MemBucket] = {}
_MEM_LOCK = threading.Lock()


def _mem_bucket(root: str) -> _MemBucket:
  with _MEM_LOCK:
    if root not in _MEM_BUCKETS:
      _MEM_BUCKETS[root] = _MemBucket()
    return _MEM_BUCKETS[root]


def clear_memory_storage():
  with _MEM_LOCK:
    _MEM_BUCKETS.clear()


# ---------------------------------------------------------------------------

_PROTOCOL_HOOKS = {}

# every constructed backend flows through this (chaos fault injection,
# instrumentation): wrapper(backend, extracted_path) -> backend-like
_BACKEND_WRAPPER = None


def set_backend_wrapper(wrapper):
  """Install (or clear, with None) a global backend wrapper. Applied to
  every backend ANY protocol constructs — the seam igneous_tpu.chaos uses
  to inject storage faults without monkey-patching per-protocol clients."""
  global _BACKEND_WRAPPER
  _BACKEND_WRAPPER = wrapper


def register_protocol(name: str, factory):
  """Attach a storage backend factory: factory(path) -> backend object

  The backend must implement the _FileBackend interface below. This is the
  extension point for gs:// and s3:// in real deployments.
  """
  _PROTOCOL_HOOKS[name] = factory


class _FileBackend:
  """file:// backend."""

  def __init__(self, root: str):
    self.root = root

  def _fullpath(self, key: str) -> str:
    return os.path.join(self.root, key)

  def put(self, key: str, data: bytes):
    path = self._fullpath(key)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + f".tmp.{os.getpid()}.{threading.get_ident()}"
    try:
      with open(tmp, "wb") as f:
        f.write(data)
      os.replace(tmp, path)  # atomic within a filesystem
    except BaseException:
      # a failed write (ENOSPC, crash-injected fault, interrupt) must not
      # strand .tmp.* turds next to real chunks — readers never see them,
      # but they accumulate across retries and pollute byte-level audits
      try:
        os.remove(tmp)
      except FileNotFoundError:
        pass
      raise

  def get(self, key: str) -> Optional[bytes]:
    try:
      with open(self._fullpath(key), "rb") as f:
        return f.read()
    except FileNotFoundError:
      return None

  def get_range(self, key: str, start: int, length: int) -> Optional[bytes]:
    try:
      with open(self._fullpath(key), "rb") as f:
        f.seek(start)
        return f.read(length)
    except FileNotFoundError:
      return None

  def exists(self, key: str) -> bool:
    return os.path.exists(self._fullpath(key))

  def delete(self, key: str):
    try:
      os.remove(self._fullpath(key))
    except FileNotFoundError:
      pass

  def list(self, prefix: str = "") -> Iterator[str]:
    # prefix is a path prefix, not necessarily a directory
    directory = os.path.dirname(prefix)
    scandir = os.path.join(self.root, directory) if directory else self.root
    if not os.path.isdir(scandir):
      return
    for dirpath, _dirnames, filenames in os.walk(scandir):
      rel = os.path.relpath(dirpath, self.root)
      rel = "" if rel == "." else rel + "/"
      for fname in sorted(filenames):
        key = rel + fname
        if key.startswith(prefix):
          yield key

  def size(self, key: str) -> Optional[int]:
    try:
      return os.path.getsize(self._fullpath(key))
    except FileNotFoundError:
      return None


class _MemBackend:
  """mem:// backend."""

  def __init__(self, root: str):
    self.bucket = _mem_bucket(root)

  def put(self, key: str, data: bytes):
    with self.bucket.lock:
      self.bucket.files[key] = bytes(data)

  def get(self, key: str) -> Optional[bytes]:
    with self.bucket.lock:
      return self.bucket.files.get(key)

  def get_range(self, key: str, start: int, length: int) -> Optional[bytes]:
    with self.bucket.lock:
      data = self.bucket.files.get(key)
    return None if data is None else data[start : start + length]

  def exists(self, key: str) -> bool:
    with self.bucket.lock:
      return key in self.bucket.files

  def delete(self, key: str):
    with self.bucket.lock:
      self.bucket.files.pop(key, None)

  def list(self, prefix: str = "") -> Iterator[str]:
    with self.bucket.lock:
      keys = sorted(self.bucket.files.keys())
    for k in keys:
      if k.startswith(prefix):
        yield k

  def size(self, key: str) -> Optional[int]:
    with self.bucket.lock:
      data = self.bucket.files.get(key)
    return None if data is None else len(data)


def attach_memory_protocol(protocol: str):
  """Serve ``<protocol>://`` from in-process memory buckets — the test/dev
  double for cloud object stores (gs://, s3://): every caller-facing seam
  (URL parsing, prefix listing, range reads, compression) runs the exact
  code a real backend would, with only the byte transport faked.
  Production deployments instead register a real backend via
  register_protocol (the reference gets these from cloud-files)."""
  register_protocol(
    protocol, lambda path: _MemBackend(f"{protocol}://{path}")
  )


def _make_backend(pth: ExtractedPath):
  if pth.protocol == "file":
    backend = _FileBackend(pth.path)
  elif pth.protocol == "mem":
    backend = _MemBackend(pth.path)
  elif pth.protocol in _PROTOCOL_HOOKS:
    backend = _PROTOCOL_HOOKS[pth.protocol](pth.path)
  elif pth.protocol == "gs":
    from .storage_gcs import GCSBackend

    backend = GCSBackend(pth.path)
  elif pth.protocol == "s3":
    from .storage_s3 import S3Backend

    backend = S3Backend(pth.path)
  else:
    raise ValueError(
      f"Protocol {pth.protocol}:// not available in this environment. "
      f"Use register_protocol() to attach a backend."
    )
  if _BACKEND_WRAPPER is not None:
    backend = _BACKEND_WRAPPER(backend, pth)
  return backend


class CloudFiles:
  """get/put/list/delete against a storage root, with compression handling."""

  def __init__(self, cloudpath: str):
    self.cloudpath = cloudpath.rstrip("/")
    self.pth = extract_path(cloudpath)
    self.backend = _make_backend(self.pth)

  # -- write ---------------------------------------------------------------

  def put(
    self,
    key: str,
    content: bytes,
    compress=None,
    cache_control: Optional[str] = None,
    content_type: Optional[str] = None,
  ):
    del cache_control, content_type  # metadata: meaningful only on cloud backends
    if isinstance(content, str):
      content = content.encode("utf8")
    ext = COMPRESSION_EXTS[compress]
    payload = compress_bytes(bytes(content), compress)
    # storage spans only materialize under a sampled task trace
    # (observability.trace.maybe_span is a thread-local check otherwise)
    with _trace.maybe_span("storage.put", protocol=self.pth.protocol):
      self.backend.put(key + ext, payload)
    integrity.record_put(self.cloudpath, key + ext, payload, backend=self.backend)

  def puts(self, files: Iterable, compress=None, **kw):
    total = 0
    for f in files:
      if isinstance(f, dict):
        self.put(
          f["path"],
          f["content"],
          compress=f.get("compress", compress),
        )
      else:
        key, content = f
        self.put(key, content, compress=compress)
      total += 1
    return total

  def put_json(self, key: str, obj, compress=None):
    self.put(
      key,
      json.dumps(jsonify(obj)).encode("utf8"),
      compress=compress,
    )

  # -- read ----------------------------------------------------------------

  def _resolve(self, key: str) -> Tuple[Optional[bytes], Optional[str]]:
    with _trace.maybe_span("storage.get", protocol=self.pth.protocol):
      data = self.backend.get(key)
      if data is not None:
        return data, None
      for ext, method in _EXT_TO_COMPRESSION.items():
        data = self.backend.get(key + ext)
        if data is not None:
          return data, method
      return None, None

  def get(self, key: Union[str, Iterable[str]], raw: bool = False):
    if not isinstance(key, str):
      return [
        {"path": k, "content": self.get(k, raw=raw), "error": None}
        for k in key
      ]
    data, method = self._resolve(key)
    if data is None:
      return None
    return data if raw else decompress_bytes(data, method)

  def get_stored(self, key: str) -> Tuple[Optional[bytes], Optional[str]]:
    """(stored bytes, wire compression method) — the compressed-domain
    read: callers that only need to MOVE or digest an object skip the
    inflate entirely (zero-decode transfers, decode-cache keys)."""
    return self._resolve(key)

  def put_stored(self, key: str, data: bytes, method) -> None:
    """Store already-wire-compressed bytes verbatim under the extension
    ``method`` implies — the zero-decode transfer's write half. ``method``
    must name the compression the bytes actually carry."""
    stored_key = key + COMPRESSION_EXTS[method]
    payload = bytes(data)
    with _trace.maybe_span("storage.put", protocol=self.pth.protocol):
      self.backend.put(stored_key, payload)
    integrity.record_put(self.cloudpath, stored_key, payload, backend=self.backend)

  def get_range(self, key: str, start: int, length: int) -> Optional[bytes]:
    """Ranged read of an UNCOMPRESSED object (sharded-format reads).

    Only the exact key is consulted: ranged reads into a gzip-compressed
    object are meaningless, so no compression-extension fallback applies.
    """
    return self.backend.get_range(key, start, length)

  def get_json(self, key: str):
    data = self.get(key)
    if data is None:
      return None
    return json.loads(data.decode("utf8"))

  def exists(self, key: Union[str, Iterable[str]]):
    if not isinstance(key, str):
      return {k: self.exists(k) for k in key}
    if self.backend.exists(key):
      return True
    return any(self.backend.exists(key + ext) for ext in _EXT_TO_COMPRESSION)

  def size(self, key: str) -> Optional[int]:
    sz = self.backend.size(key)
    if sz is not None:
      return sz
    for ext in _EXT_TO_COMPRESSION:
      sz = self.backend.size(key + ext)
      if sz is not None:
        return sz
    return None

  # -- listing / deletion --------------------------------------------------

  def list(self, prefix: str = "", flat: bool = False) -> Iterator[str]:
    seen = set()
    for key in self.backend.list(prefix):
      ext = os.path.splitext(key)[1]
      if ext in _EXT_TO_COMPRESSION:
        key = key[: -len(ext)]
      if flat and "/" in key[len(prefix):]:
        continue
      if key not in seen:
        seen.add(key)
        yield key

  def delete(self, key: Union[str, Iterable[str]]):
    keys = [key] if isinstance(key, str) else list(key)
    for k in keys:
      self.backend.delete(k)
      for ext in _EXT_TO_COMPRESSION:
        self.backend.delete(k + ext)

  def delete_prefix(self, prefix: str = ""):
    for key in list(self.backend.list(prefix)):
      self.backend.delete(key)

  def transfer_to(self, dest_cloudpath: str, paths: Optional[Iterable[str]] = None):
    dest = CloudFiles(dest_cloudpath)
    if paths is None:
      paths = self.list()
    for key in paths:
      data, method = self._resolve(key)
      if data is None:
        continue
      dest.put(key + COMPRESSION_EXTS[method], data)

  def join(self, *parts: str) -> str:
    return "/".join(p.strip("/") for p in parts)

  def isdir(self) -> bool:
    if self.pth.protocol == "file":
      return os.path.isdir(self.pth.path)
    return any(True for _ in self.list())
