"""Compresso segmentation codec (EXPERIMENTAL container).

Implements the Compresso scheme — Matejek, Haehn, Lekschas, Mitzenmacher,
Pfister, "Compresso: Efficient Compression of Segmentation Data for
Connectomics" (MICCAI 2017) — which the reference pipeline accepts as an
``--encoding`` choice via cloud-volume (reference igneous_cli/cli.py:50-64
routes it; the reference itself outsources the bitstream to the external
``compresso`` package, which is not vendored in this image).

The scheme, faithfully:

  1. Per z-slice BOUNDARY MAP: voxel (x,y) is a boundary when its label
     differs from its +x or +y neighbor. Non-boundary labels therefore
     propagate right/down: if (x-1,y) is non-boundary, its label equals
     (x,y)'s.
  2. The boundary bitmap is split into 8x8x1 blocks; each block packs to
     a 64-bit WINDOW value (x fastest, LSB first). Distinct values form a
     codebook; blocks store codebook indices (segmentation boundary
     windows repeat heavily — most are all-zero).
  3. Per-slice connected components (4-connectivity) of the non-boundary
     voxels; each component's label is recorded once, in component-id
     order (IDS stream). Decode re-runs CC on the reconstructed boundary
     map — identical input, identical components.
  4. Boundary voxels recover their labels from the propagation rule:
     left neighbor non-boundary -> copy left; else up neighbor
     non-boundary -> copy up; else the voxel is INDETERMINATE and its
     label ships explicitly (LOCATIONS stream, x-fastest order).

All four streams index one sorted unique-label table, so wide labels are
stored once. Steps 1-4 are pure array transforms (numpy here); the CC
pass rides scipy.ndimage per slice.

CONTAINER CAVEAT: no offline oracle for the published compresso v3 byte
layout exists in this zero-egress image, and a silently-wrong bitstream
corrupts datasets, so this codec writes its own container (magic
``cpsx``) rather than risk masquerading as one it cannot verify. It
round-trips exactly under this package and is property-tested against
adversarial volumes; swap-in byte parity with seung-lab/compresso is
gated until a reference-encoded artifact is available to validate
against (same policy that keeps fpzip/zfpc/jpegxl gated — ROADMAP.md).
For the same reason, Precomputed info files advertise this container as
``compresso-cpsx`` (meta.advertised_encoding) so external readers fail
loudly on the unknown encoding instead of mis-decoding it as v3.
"""

from __future__ import annotations

import struct
from typing import Tuple

import numpy as np

MAGIC = b"cpsx"
VERSION = 1
STEPS = (8, 8, 1)  # 8x8 windows pack to one u64 per block

_HEADER = struct.Struct("<4sBBIIIBBBQQIQB")  # 50 bytes


def _boundary_map(labels: np.ndarray) -> np.ndarray:
  """(x,y,z) bool: label differs from +x or +y neighbor (within slice)."""
  B = np.zeros(labels.shape, dtype=bool)
  B[:-1, :, :] |= labels[:-1, :, :] != labels[1:, :, :]
  B[:, :-1, :] |= labels[:, :-1, :] != labels[:, 1:, :]
  return B


def _pack_windows(B: np.ndarray) -> np.ndarray:
  """Boundary bitmap -> u64 window value per 8x8x1 block, block raster
  order (x-blocks fastest, then y, then z)."""
  sx, sy, sz = B.shape
  gx, gy = -(-sx // 8), -(-sy // 8)
  padded = np.zeros((gx * 8, gy * 8, sz), dtype=np.uint8)
  padded[:sx, :sy, :] = B
  # (gx,8,gy,8,z) -> (z,gy,gx, 8y,8x); each 8-bit x-run packs LSB-first
  blocks = (
    padded.reshape(gx, 8, gy, 8, sz).transpose(4, 2, 0, 3, 1)
  )
  rows = np.packbits(blocks, axis=-1, bitorder="little")  # (z,gy,gx,8,1)
  words = rows.reshape(sz, gy, gx, 8).copy().view("<u8")[..., 0]
  return words.ravel()


def _unpack_windows(words: np.ndarray, shape) -> np.ndarray:
  sx, sy, sz = shape
  gx, gy = -(-sx // 8), -(-sy // 8)
  rows = words.reshape(sz, gy, gx, 1).view("<u1").reshape(sz, gy, gx, 8)
  bits = np.unpackbits(rows, axis=-1, bitorder="little")
  bits = bits.reshape(sz, gy, gx, 8, 8).transpose(2, 4, 1, 3, 0)
  return bits.reshape(gx * 8, gy * 8, sz)[:sx, :sy, :].astype(bool)


def _cc_slices(nonboundary: np.ndarray):
  """Per-slice 4-connected components of the non-boundary mask.
  Yields (z, cc_array, n_components); numbering is scipy's scan order,
  identical between encode and decode because the input mask is."""
  from scipy import ndimage

  structure = np.array([[0, 1, 0], [1, 1, 1], [0, 1, 0]], dtype=bool)
  for z in range(nonboundary.shape[2]):
    cc, n = ndimage.label(nonboundary[:, :, z], structure=structure)
    yield z, cc, n


def _resolution_masks(B: np.ndarray):
  """Masks for the decode-time boundary-resolution rule (vectorizable:
  the rule only ever reads NON-boundary neighbors, whose labels come
  straight from the CC pass). Returns (from_left, from_up, indet)."""
  from_left = np.zeros_like(B)
  from_left[1:, :, :] = B[1:, :, :] & ~B[:-1, :, :]
  from_up = np.zeros_like(B)
  from_up[:, 1:, :] = B[:, 1:, :] & ~B[:, :-1, :]
  from_up &= ~from_left
  indet = B & ~from_left & ~from_up
  return from_left, from_up, indet


def _min_uint(n: int) -> np.dtype:
  for dt in ("<u1", "<u2", "<u4", "<u8"):
    if n <= np.iinfo(dt).max:
      return np.dtype(dt)
  raise ValueError(n)


def compress(img: np.ndarray, steps: Tuple[int, int, int] = STEPS) -> bytes:
  """img: (x,y,z) or (x,y,z,1) integer labels -> compresso bytes."""
  if img.ndim == 4:
    if img.shape[3] != 1:
      raise ValueError(f"compresso supports 1 channel, got {img.shape[3]}")
    img = img[..., 0]
  if tuple(steps) != STEPS:
    raise ValueError(f"only {STEPS} windows are supported, got {steps}")
  labels = np.ascontiguousarray(img)
  sx, sy, sz = labels.shape

  uniq = np.unique(labels)  # sorted
  B = _boundary_map(labels)

  ids = []
  for z, cc, n in _cc_slices(~B):
    if n == 0:
      continue
    # first-occurrence voxel of each component, in component-id order
    flat = cc.ravel()
    comp_vals, first = np.unique(flat, return_index=True)
    sel = comp_vals != 0
    ids.append(labels[:, :, z].ravel()[first[sel]])
  ids = np.concatenate(ids) if ids else np.zeros(0, labels.dtype)

  _fl, _fu, indet = _resolution_masks(B)
  # x-fastest enumeration so decode refills in the same order
  locations = labels.reshape(-1, sz, order="F").T[
    indet.reshape(-1, sz, order="F").T
  ]

  words = _pack_windows(B)
  values, win_idx = np.unique(words, return_inverse=True)

  label_w = _min_uint(max(len(uniq) - 1, 0))
  index_w = _min_uint(max(len(values) - 1, 0))
  ids_ix = np.searchsorted(uniq, ids).astype(label_w)
  loc_ix = np.searchsorted(uniq, locations).astype(label_w)

  header = _HEADER.pack(
    MAGIC, VERSION, labels.dtype.itemsize, sx, sy, sz,
    steps[0], steps[1], steps[2],
    len(uniq), len(ids_ix), len(values), len(loc_ix),
    index_w.itemsize,
  )
  return b"".join([
    header,
    uniq.astype(f"<u{labels.dtype.itemsize}").tobytes(),
    ids_ix.tobytes(),
    values.astype("<u8").tobytes(),
    win_idx.astype(index_w).tobytes(),
    loc_ix.tobytes(),
  ])


def decompress(data: bytes, shape=None, dtype=None) -> np.ndarray:
  """compresso bytes -> (x,y,z,1) labels. ``shape``/``dtype``, when
  given (the Precomputed read path knows them), are validated against
  the stream header."""
  (magic, version, width, sx, sy, sz, xs, ys, zs,
   n_labels, n_ids, n_values, n_locs, index_w) = _HEADER.unpack_from(data)
  if magic != MAGIC or version != VERSION:
    raise ValueError(
      f"not an igneous-tpu compresso stream (magic {magic!r} v{version})"
    )
  if (xs, ys, zs) != STEPS:
    raise ValueError(f"unsupported window {xs}x{ys}x{zs}")
  if shape is not None and tuple(shape[:3]) != (sx, sy, sz):
    raise ValueError(f"stream is {(sx, sy, sz)}, expected {tuple(shape)}")
  out_dtype = np.dtype(dtype) if dtype is not None else np.dtype(f"<u{width}")
  if out_dtype.itemsize != width:
    raise ValueError(f"stream stores {width}-byte labels, asked {out_dtype}")

  gx, gy = -(-sx // 8), -(-sy // 8)
  n_windows = gx * gy * sz
  label_w = _min_uint(max(n_labels - 1, 0))

  off = _HEADER.size
  uniq = np.frombuffer(data, f"<u{width}", n_labels, off)
  off += n_labels * width
  ids_ix = np.frombuffer(data, label_w, n_ids, off)
  off += n_ids * label_w.itemsize
  values = np.frombuffer(data, "<u8", n_values, off)
  off += n_values * 8
  win_idx = np.frombuffer(data, f"<u{index_w}", n_windows, off)
  off += n_windows * index_w
  loc_ix = np.frombuffer(data, label_w, n_locs, off)

  B = _unpack_windows(values[win_idx], (sx, sy, sz))

  out = np.zeros((sx, sy, sz), dtype=out_dtype)
  pos = 0
  for z, cc, n in _cc_slices(~B):
    if n == 0:
      continue
    # no np.concatenate([[0], ...]): int64+uint64 promotes to float64
    # and silently rounds 64-bit labels
    comp_labels = np.empty(n + 1, dtype=out_dtype)
    comp_labels[0] = 0
    comp_labels[1:] = uniq[ids_ix[pos : pos + n]]
    out[:, :, z] = comp_labels[cc]
    pos += n

  from_left, from_up, indet = _resolution_masks(B)
  out[1:, :, :][from_left[1:, :, :]] = (
    out[:-1, :, :][from_left[1:, :, :]]
  )
  out[:, 1:, :][from_up[:, 1:, :]] = out[:, :-1, :][from_up[:, 1:, :]]
  if n_locs:
    outT = out.reshape(-1, sz, order="F").T.copy()
    outT[indet.reshape(-1, sz, order="F").T] = uniq[loc_ix].astype(out_dtype)
    out = outT.T.reshape(sx, sy, sz, order="F")
  return np.asfortranarray(out[..., np.newaxis])
